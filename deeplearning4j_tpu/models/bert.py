"""BERT-class transformer encoder / causal LM — the flagship model.

Reference parity: the SameDiff BERT-base fine-tune workload (BASELINE configs
#4/#5; ref: dl4j-examples BERT via `nd4j/samediff-import-tensorflow`, executed
by `org.nd4j.autodiff.samediff.internal.TrainingSession` op-by-op). The
TPU-native redesign compiles the ENTIRE training step — forward, masked/causal
LM loss, backward, AdamW update — into one XLA executable over a
``(data, model, context)`` mesh:

- **data**    — batch sharding; gradient psum inserted by GSPMD.
- **model**   — tensor parallelism: attention heads + MLP hidden sharded
  (Megatron layout: column-parallel in-projections, row-parallel
  out-projections → one all-reduce per block half).
- **context** — sequence parallelism: ring attention (K/V blocks rotating
  over ICI via ppermute with online-softmax accumulation) from
  ``deeplearning4j_tpu.parallel.sequence_parallel`` — Pallas-backed
  (``ring_flash_attention``: per-pair streamed kernels, second-ring-pass
  backward) whenever the local shard fits the kernel envelope.

Params are fp32; matmul compute is bf16 (MXU-native); layernorm/softmax in
fp32. Everything is a plain pytree of jnp arrays — no framework object graph.
"""
from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deeplearning4j_tpu.parallel.mesh import (
    CONTEXT_AXIS, DATA_AXIS, MODEL_AXIS, tree_shardings)
from deeplearning4j_tpu.parallel.sequence_parallel import (
    ring_attention, ring_flash_attention, ulysses_attention)

_log = logging.getLogger(__name__)
_flash_fallback_warned: set = set()


def _warn_flash_fallback(reason: str) -> None:
    """One-time notice when attention_impl='flash' routes to the XLA einsum
    path anyway — a silent perf cliff otherwise (round-4 advisor finding)."""
    if reason not in _flash_fallback_warned:
        _flash_fallback_warned.add(reason)
        _log.warning(
            "attention_impl='flash' falling back to the XLA einsum path: %s",
            reason)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    mlp_dim: int = 3072
    max_seq: int = 512
    dropout: float = 0.0
    causal: bool = False            # False = BERT (bidirectional MLM); True = GPT-style LM
    dtype: Any = jnp.bfloat16       # compute dtype (params stay fp32)
    attention_impl: str = "full"    # 'full' | 'ring' | 'ulysses' (ring/ulysses need context axis)
    remat: bool = True              # jax.checkpoint each block (HBM <-> FLOPs trade)
    # Softmax probability dtype, consumed by BOTH attention paths: the XLA
    # einsum path accumulates its softmax in this dtype, and the packed VMEM
    # Pallas kernel uses it as the probability dtype (p_dtype). fp32 is the
    # safe default (what gradcheck/parity suites assume); bf16 halves the
    # VPU softmax work in the kernel (5.8 -> 4.8 ms/layer fwd+bwd) and cut
    # ~18 GB/step on the old XLA path, with a loss trajectory
    # indistinguishable over 150 steps (max-subtraction keeps exp() in
    # range; see bench.py).
    softmax_dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


BERT_BASE = TransformerConfig()


def init_params(key, cfg: TransformerConfig) -> Dict[str, Any]:
    """Initialize the parameter pytree (truncated-normal 0.02, BERT-style)."""
    def dense(k, fan_in, shape):
        return jax.random.normal(k, shape, jnp.float32) * 0.02

    keys = jax.random.split(key, 4 + cfg.layers)
    params: Dict[str, Any] = {
        "tok_emb": dense(keys[0], cfg.vocab_size, (cfg.vocab_size, cfg.hidden)),
        "pos_emb": dense(keys[1], cfg.max_seq, (cfg.max_seq, cfg.hidden)),
        "ln_f": {"scale": jnp.ones((cfg.hidden,), jnp.float32),
                 "bias": jnp.zeros((cfg.hidden,), jnp.float32)},
        "lm_head": dense(keys[2], cfg.hidden, (cfg.hidden, cfg.vocab_size)),
        "blocks": [],
    }
    for i in range(cfg.layers):
        bk = jax.random.split(keys[4 + i], 4)
        params["blocks"].append({
            "ln1": {"scale": jnp.ones((cfg.hidden,), jnp.float32),
                    "bias": jnp.zeros((cfg.hidden,), jnp.float32)},
            "qkv": {"kernel": dense(bk[0], cfg.hidden, (cfg.hidden, 3 * cfg.hidden)),
                    "bias": jnp.zeros((3 * cfg.hidden,), jnp.float32)},
            "attn_out": {"kernel": dense(bk[1], cfg.hidden, (cfg.hidden, cfg.hidden)),
                         "bias": jnp.zeros((cfg.hidden,), jnp.float32)},
            "ln2": {"scale": jnp.ones((cfg.hidden,), jnp.float32),
                    "bias": jnp.zeros((cfg.hidden,), jnp.float32)},
            "mlp_in": {"kernel": dense(bk[2], cfg.hidden, (cfg.hidden, cfg.mlp_dim)),
                       "bias": jnp.zeros((cfg.mlp_dim,), jnp.float32)},
            "mlp_out": {"kernel": dense(bk[3], cfg.mlp_dim, (cfg.mlp_dim, cfg.hidden)),
                        "bias": jnp.zeros((cfg.hidden,), jnp.float32)},
        })
    return params


def param_pspecs(cfg: TransformerConfig) -> Dict[str, Any]:
    """Megatron-style tensor-parallel PartitionSpecs over the 'model' axis.

    Column-parallel (shard output features): qkv, mlp_in. Row-parallel (shard
    input features): attn_out, mlp_out — GSPMD inserts the block all-reduce.
    Embeddings shard the vocab dim; layernorms replicate.
    """
    ln = {"scale": P(), "bias": P()}
    block = {
        "ln1": ln, "ln2": ln,
        "qkv": {"kernel": P(None, MODEL_AXIS), "bias": P(MODEL_AXIS)},
        "attn_out": {"kernel": P(MODEL_AXIS, None), "bias": P()},
        "mlp_in": {"kernel": P(None, MODEL_AXIS), "bias": P(MODEL_AXIS)},
        "mlp_out": {"kernel": P(MODEL_AXIS, None), "bias": P()},
    }
    return {
        "tok_emb": P(MODEL_AXIS, None),
        "pos_emb": P(),
        "ln_f": ln,
        "lm_head": P(None, MODEL_AXIS),
        "blocks": [block for _ in range(cfg.layers)],
    }


def _layernorm(x, p, eps=1e-12):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _full_attention(q, k, v, causal: bool, softmax_dtype=jnp.float32):
    # q,k,v: (B, H, T, D)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s.astype(softmax_dtype), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _attention(q, k, v, cfg: TransformerConfig, mesh: Optional[Mesh]):
    """Dispatch: full attention, the Pallas flash kernel, or sequence-parallel
    ring/Ulysses via shard_map over the 'context' axis when the mesh has one."""
    impl = cfg.attention_impl
    if impl == "flash":
        # Streamed long-context kernel (T > 1024 — shorter sequences never
        # reach here; _block routes them to the packed whole-head VMEM
        # kernel via _use_packed_kernel before the head transpose). Under a
        # dp/tp mesh the kernel runs per-device via shard_map — batch over
        # 'data', heads over 'model' (embarrassingly parallel, zero extra
        # collectives); a sequence-sharded ('context') mesh falls through to
        # ring/Ulysses below, which own that regime.
        B, nh, T, _ = q.shape
        mesh_spec = None
        if mesh is not None:
            ok = not (CONTEXT_AXIS in mesh.axis_names
                      and mesh.shape[CONTEXT_AXIS] > 1) \
                and B % mesh.shape.get(DATA_AXIS, 1) == 0 \
                and nh % mesh.shape.get(MODEL_AXIS, 1) == 0
            if ok:
                mesh_spec = P(
                    DATA_AXIS if DATA_AXIS in mesh.axis_names else None,
                    MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None,
                    None, None)
        interpret = jax.default_backend() != "tpu"
        from deeplearning4j_tpu.ops.pallas_kernels import (
            flash_attention, flash_envelope_ok)
        # flash_envelope_ok: the auto block must be 8-sublane aligned and
        # VMEM-safe — unaligned whole-T blocks do compile (Mosaic masks
        # partial tiles, verified on v5e), but that envelope is unswept
        # for perf, so odd-T sequences stay on the known-good einsum path
        if flash_envelope_ok(T) \
                and (mesh is None or mesh_spec is not None):

            def _local(ql, kl, vl):
                return flash_attention(ql, kl, vl, cfg.causal, None, None,
                                       None, interpret)

            if mesh is None:
                return _local(q, k, v)
            return shard_map(_local, mesh=mesh,
                             in_specs=(mesh_spec,) * 3, out_specs=mesh_spec,
                             check_rep=False)(q, k, v)
        # T has no usable power-of-2 block divisor, or the mesh shards the
        # sequence/doesn't divide batch+heads — fall through (ring/Ulysses
        # when a context axis exists, XLA einsum otherwise)
        if mesh is None or CONTEXT_AXIS not in mesh.axis_names \
                or mesh.shape[CONTEXT_AXIS] == 1:
            _warn_flash_fallback(
                f"streamed kernel unavailable for T={T} under mesh "
                f"{dict(mesh.shape) if mesh is not None else None}")
            return _full_attention(q, k, v, cfg.causal, cfg.softmax_dtype)
    if impl == "full" or mesh is None \
            or CONTEXT_AXIS not in mesh.axis_names \
            or mesh.shape[CONTEXT_AXIS] == 1:
        return _full_attention(q, k, v, cfg.causal, cfg.softmax_dtype)
    # 'ring' and sequence-sharded 'flash' both take the ppermute ring —
    # ring attention IS flash attention's online-softmax recurrence with
    # k/v blocks arriving over ICI instead of from HBM. When the local
    # shard fits the streamed kernel's envelope (same gate as the
    # single-device streamed route), the per-pair block attention runs in
    # Pallas with a second-ring-pass custom backward (O(T_local) memory
    # both directions); otherwise the einsum ring serves as fallback.
    if impl == "ulysses":
        fn = ulysses_attention
    else:
        T_local = q.shape[2] // mesh.shape[CONTEXT_AXIS]
        from deeplearning4j_tpu.ops.pallas_kernels import flash_envelope_ok
        fn = ring_flash_attention if flash_envelope_ok(T_local) \
            else ring_attention
    # heads sharded over 'model', sequence over 'context'
    spec = P(DATA_AXIS if DATA_AXIS in mesh.axis_names else None,
             MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None,
             CONTEXT_AXIS, None)
    mapped = shard_map(
        functools.partial(fn, axis_name=CONTEXT_AXIS, causal=cfg.causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_rep=False)
    return mapped(q, k, v)


def _packed_mesh_spec(cfg: TransformerConfig, mesh: Mesh, B: int):
    """PartitionSpec + local head count for running the packed VMEM kernel
    under ``mesh`` via shard_map — batch rides the 'data' axis and heads ride
    the 'model' axis (both embarrassingly parallel: per-device pallas_call,
    zero extra collectives). Returns None when the kernel cannot partition
    over this mesh (sequence sharded over 'context', heads or batch not
    divisible) and the einsum/ring paths must serve instead."""
    if CONTEXT_AXIS in mesh.axis_names and mesh.shape[CONTEXT_AXIS] > 1:
        return None  # sequence is sharded — ring/Ulysses own that regime
    dp = mesh.shape.get(DATA_AXIS, 1)
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if B % dp or cfg.heads % tp:
        return None
    spec = P(DATA_AXIS if DATA_AXIS in mesh.axis_names else None, None,
             MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None)
    return spec, cfg.heads // tp


def _use_packed_kernel(cfg: TransformerConfig, mesh: Optional[Mesh],
                       B: int, T: int) -> bool:
    """True when attention routes to the packed-layout Pallas kernel: the
    (B, T, H*D) projections feed the kernel directly, so the (B, H, T, D)
    head transposes (6 physical copies per layer, ~5 GB/step at bench
    shapes) never materialize. Under a mesh the kernel runs per-device via
    shard_map over the (data, model) axes (round-5; a monolithic pallas_call
    over sharded operands would have forced GSPMD all-gathers, which is why
    round 4 disabled it under any mesh)."""
    if cfg.attention_impl != "flash":
        return False
    from deeplearning4j_tpu.ops.pallas_kernels import packed_kernel_shape_ok
    if not packed_kernel_shape_ok(T):
        return False
    if mesh is not None and _packed_mesh_spec(cfg, mesh, B) is None:
        # no warning here: _attention still serves this — ring/Ulysses for
        # sequence-sharded meshes, and ITS einsum fallback warns accurately
        return False
    return True


def _block(params, x, cfg: TransformerConfig, mesh: Optional[Mesh],
           return_kv: bool = False):
    B, T, H = x.shape
    h = _layernorm(x, params["ln1"])
    qkv = h @ params["qkv"]["kernel"].astype(h.dtype) + params["qkv"]["bias"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    if return_kv:
        # (B, T, heads, head_dim) — the KV-cache layout. The packed (B, T,
        # H*D) projection is head-contiguous, so this reshape is free and
        # identical whichever attention impl serves below (prefill captures
        # these for the generation cache without forking the forward).
        kv_out = (k.reshape(B, T, cfg.heads, cfg.head_dim),
                  v.reshape(B, T, cfg.heads, cfg.head_dim))
    if _use_packed_kernel(cfg, mesh, B, T):
        from deeplearning4j_tpu.ops.pallas_kernels import mha_attention_packed
        # cfg.softmax_dtype doubles as the kernel's probability dtype —
        # bf16 halves the VPU softmax work (bench config), fp32 is exact
        interp = jax.default_backend() != "tpu"
        if mesh is None:
            o = mha_attention_packed(q, k, v, cfg.heads, cfg.causal, None,
                                     interp, cfg.softmax_dtype)
        else:
            # Per-device kernel under shard_map: batch over 'data', heads
            # over 'model' (the qkv projection is column-parallel, so the
            # packed H*D dim is already laid out head-contiguous per shard).
            # Attention never mixes batch elements or heads, so in==out
            # specs and no collectives; scale is per-head (1/sqrt(D)) and D
            # is shard-invariant.
            spec, local_heads = _packed_mesh_spec(cfg, mesh, B)

            def _local(ql, kl, vl):
                return mha_attention_packed(ql, kl, vl, local_heads,
                                            cfg.causal, None, interp,
                                            cfg.softmax_dtype)

            o = shard_map(_local, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec, check_rep=False)(q, k, v)
    else:
        def heads(t):  # (B,T,H) -> (B,heads,T,D)
            return t.reshape(B, T, cfg.heads, cfg.head_dim).transpose(0, 2, 1, 3)
        o = _attention(heads(q), heads(k), heads(v), cfg, mesh)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, H)
    x = x + o @ params["attn_out"]["kernel"].astype(o.dtype) \
        + params["attn_out"]["bias"].astype(o.dtype)
    h = _layernorm(x, params["ln2"])
    h = h @ params["mlp_in"]["kernel"].astype(h.dtype) + params["mlp_in"]["bias"].astype(h.dtype)
    h = jax.nn.gelu(h, approximate=True)
    x = x + h @ params["mlp_out"]["kernel"].astype(h.dtype) \
        + params["mlp_out"]["bias"].astype(h.dtype)
    if return_kv:
        return x, kv_out[0], kv_out[1]
    return x


def encode(params, token_ids, cfg: TransformerConfig,
           mesh: Optional[Mesh] = None, block_fn=None):
    """Embeddings + transformer stack + final layernorm (no lm_head).
    ``block_fn`` overrides the per-block function — used by
    tools/profile_flagship.py's ablations so they stay in sync with the
    real forward by construction."""
    B, T = token_ids.shape
    # The package pins jax_default_matmul_precision="highest" so fp32 models
    # get exact fp32 GEMMs (reference semantics). This model casts operands
    # to bf16 explicitly — precision emulation has nothing to add, but
    # "highest" still steers XLA:TPU to a slower dot algorithm (measured
    # ~5% tokens/sec on the bench). Scope the fast default back in here.
    with jax.default_matmul_precision("default"):
        x = params["tok_emb"][token_ids].astype(cfg.dtype) \
            + params["pos_emb"][:T][None].astype(cfg.dtype)
        blk = block_fn or functools.partial(_block, cfg=cfg, mesh=mesh)
        if cfg.remat:
            blk = jax.checkpoint(
                blk, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)
        for bp in params["blocks"]:
            x = blk(bp, x)
        return _layernorm(x, params["ln_f"])


def _forward_raw(params, token_ids, cfg: TransformerConfig,
                 mesh: Optional[Mesh] = None):
    """Logits in the COMPUTE dtype (bf16) — the loss path consumes these
    directly so the (B, T, vocab) tensor is never materialized in fp32
    (~3 GB at BERT-base bench shapes B=48/T=512; halving it + fusing the
    loss reduction was worth several points of MFU)."""
    x = encode(params, token_ids, cfg, mesh)
    with jax.default_matmul_precision("default"):
        return x @ params["lm_head"].astype(x.dtype)


def forward(params, token_ids, cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """token_ids (B, T) int32 -> logits (B, T, vocab) fp32."""
    return _forward_raw(params, token_ids, cfg, mesh).astype(jnp.float32)


def loss_from_logits(logits, batch):
    """Weighted LM cross-entropy from compute-dtype logits, as
    logsumexp(logits) - logits[target] with fp32 accumulation: XLA fuses the
    reduction, so no (B, T, vocab) log-prob tensor is ever written to HBM
    (the log_softmax formulation materialized one in fp32)."""
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(
        logits, batch["targets"][..., None], axis=-1)[..., 0].astype(jnp.float32)
    w = batch["weights"]
    return ((lse - tgt) * w).sum() / jnp.maximum(w.sum(), 1.0)


def lm_loss(params, batch, cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Masked/causal LM cross-entropy. batch = {'tokens': (B,T) int32,
    'targets': (B,T) int32, 'weights': (B,T) float} — weights zero out
    unmasked positions (MLM) or padding."""
    return loss_from_logits(
        _forward_raw(params, batch["tokens"], cfg, mesh), batch)


def batch_pspec(mesh: Mesh) -> P:
    """Tokens (B, T): batch over 'data', sequence over 'context'."""
    d = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
    c = CONTEXT_AXIS if CONTEXT_AXIS in mesh.axis_names else None
    return P(d, c)


def make_infer_last_logits(cfg: TransformerConfig,
                           mesh: Optional[Mesh] = None):
    """Build the batching-engine inference executable: token ids (B, T)
    -> last-position logits (B, vocab). ``CausalLMAdapter.infer``
    (serving/registry.py) dispatches this for InferenceEngine traffic;
    it is minted here — not in the serving layer — so every serving
    executable comes from a models/ factory and inherits forward()'s
    flash/packed-attention routing (the recompile-risk lint enforces
    the boundary). One signature per (B, T) bucket the engine's padded
    ladder produces."""

    def last_logits(params, tokens):
        return forward(params, tokens, cfg, mesh)[:, -1, :]

    return jax.jit(last_logits)


def make_train_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                    learning_rate: float = 1e-4, weight_decay: float = 0.01):
    """Build (init_state, step). step(params, opt_state, batch) -> (params,
    opt_state, loss) — ONE donated pjit executable (the anti-3.2: no per-op
    interpreter, no per-op JNI)."""
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)

    def init_state(params):
        return tx.init(params)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(lm_loss)(params, batch, cfg, mesh)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if mesh is None:
        return init_state, jax.jit(step, donate_argnums=(0, 1))

    param_sh = _shardings(cfg, mesh)
    bspec = NamedSharding(mesh, batch_pspec(mesh))
    batch_sh = {"tokens": bspec, "targets": bspec, "weights": bspec}
    repl = NamedSharding(mesh, P())

    def init_state_sharded(params):
        st = tx.init(params)
        placed = []
        for s in st:
            if hasattr(s, "mu"):  # ScaleByAdamState: mu/nu mirror the param tree
                placed.append(s._replace(
                    count=jax.device_put(s.count, repl),
                    mu=jax.device_put(s.mu, param_sh),
                    nu=jax.device_put(s.nu, param_sh)))
            else:
                placed.append(jax.tree.map(lambda l: jax.device_put(l, repl), s))
        return tuple(placed)

    # optimizer-state sharding tree, structurally derived via eval_shape so
    # the jit contract pins OUTPUT shardings too — leaving out_shardings
    # unconstrained lets GSPMD re-shard returned params (e.g. pos_emb onto
    # 'context'), which then fails the next call's in_shardings check
    abstract_params = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg))
    opt_sh = []
    for s in jax.eval_shape(tx.init, abstract_params):
        if hasattr(s, "mu"):
            opt_sh.append(s._replace(count=repl,
                                     mu=jax.tree.map(lambda _, p: p, s.mu, param_sh),
                                     nu=jax.tree.map(lambda _, p: p, s.nu, param_sh)))
        else:
            opt_sh.append(jax.tree.map(lambda _: repl, s))
    opt_sh = tuple(opt_sh)

    jstep = jax.jit(step, donate_argnums=(0, 1),
                    in_shardings=(param_sh, opt_sh, batch_sh),
                    out_shardings=(param_sh, opt_sh, None))
    return init_state_sharded, jstep


def _shardings(cfg: TransformerConfig, mesh: Mesh):
    """param_pspecs as a matching pytree of NamedShardings; axes absent from
    the mesh (e.g. a pure-DP mesh with no 'model') degrade to replication."""
    return tree_shardings(mesh, param_pspecs(cfg))


def place_params(params, cfg: TransformerConfig, mesh: Mesh):
    """Shard a parameter pytree onto the mesh per param_pspecs."""
    return jax.device_put(params, _shardings(cfg, mesh))


# --------------------------------------------------------------------------
# Autoregressive generation: slot-based KV cache + prefill + decode_step
# --------------------------------------------------------------------------
#
# The generative path is built for continuous batching (ORCA OSDI'22 /
# vLLM SOSP'23): the cache is a FIXED-SHAPE (slots, max_len) tensor per
# layer, per-slot lengths drive the causal mask, and dead slots simply
# compute masked garbage — so the whole serving lifetime compiles exactly
# ONE decode executable (shape (slots,) regardless of how many slots are
# live) plus one prefill executable per prompt-length bucket. Without a
# cache every generated token would re-run full prefill: O(T²) work and a
# fresh jit signature per novel length.
#
# Cache pytree:  {"layers": [{"k","v"}: (slots, max_len, heads, head_dim)
#                 per layer], "lengths": (slots,) int32}
# ``lengths[s]`` counts tokens whose K/V live in slot s. Sharded over the
# mesh like the params: heads ride the 'model' axis (the qkv projection is
# column-parallel, so per-shard heads are already contiguous), slots and
# positions replicate — see kv_cache_pspecs.


def validate_block_size(block_size, max_len: int) -> int:
    """Validate a paged-cache block size and return it as a plain int:
    positive power of two (the in-kernel block index math is a
    shift/mask) no larger than ``max_len``. THE single predicate —
    shared by :func:`init_kv_cache` and the serving engine's constructor
    so the check and its named-value error messages cannot drift."""
    if not isinstance(block_size, (int, np.integer)) or block_size <= 0 \
            or (int(block_size) & (int(block_size) - 1)) != 0:
        raise ValueError(
            f"block_size must be a positive power of two (the in-kernel "
            f"block index math is a shift/mask), got {block_size!r}")
    if block_size > max_len:
        raise ValueError(
            f"block_size {block_size} exceeds max_len {max_len}: a block "
            "larger than a slot's whole capacity can never be filled and "
            "defeats paging")
    return int(block_size)


KV_DTYPES = ("float32", "int8")


def validate_kv_dtype(kv_dtype: str, block_size) -> str:
    """Validate the KV storage mode. ``"float32"`` keeps full-precision
    storage in the cache ``dtype`` (the pre-int8 behavior, bitwise);
    ``"int8"`` stores quantized values + per-token-per-head fp32 scales
    and requires the paged (block-pool) layout — the scales are
    block-shaped tensors and the dequant lives in the block read. THE
    single predicate, shared by :func:`init_kv_cache` and the serving
    engine's constructor."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"kv_dtype must be one of {KV_DTYPES}, got {kv_dtype!r}")
    if kv_dtype == "int8" and block_size is None:
        raise ValueError(
            "kv_dtype='int8' requires the paged KV cache (pass "
            "block_size): the per-block scale tensors and on-read "
            "dequant are block-pool concepts")
    return kv_dtype


def quantize_kv(x):
    """Symmetric per-token-per-head int8 quantization of a K/V tensor
    whose trailing axis is head_dim: returns (int8 values, fp32 scales)
    with ``x ~= values * scales[..., None]``. Per-token scales mean a
    decode-step write touches only its own scale entry — no block
    requantization ever happens."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def init_kv_cache(cfg: TransformerConfig, slots: int, max_len: int,
                  dtype: Any = None, block_size: Optional[int] = None,
                  num_blocks: Optional[int] = None,
                  kv_dtype: str = "float32") -> Dict[str, Any]:
    """Allocate the generation cache. ``dtype`` defaults to the compute
    dtype (bf16 on TPU) — the cache is read every decode step, so halving
    it halves decode's dominant HBM stream.

    Two layouts share this constructor:

    - ``block_size=None`` (legacy): the contiguous per-slot layout,
      ``{"layers": [{"k","v"}: (slots, max_len, heads, head_dim)],
      "lengths": (slots,) int32}`` — every slot reserves worst-case
      ``max_len`` positions whether it uses them or not.
    - ``block_size=B`` (paged, vLLM SOSP'23): a shared block pool
      ``{"layers": [{"k","v"}: (num_blocks, B, heads, head_dim)]}``.
      Block 0 is the reserved scratch block (dead-slot writes and CoW
      no-ops land there; it is never allocated to a stream). Slot →
      position mapping lives OUTSIDE the cache, in a host-side block
      table the paged prefill/decode executables take as a gather index,
      so sequence lengths only consume the blocks they touch and a
      common prefix's blocks can be referenced by many streams.
      ``num_blocks`` defaults to the contiguous layout's capacity
      (``slots * ceil(max_len / B)``) plus the scratch block; pass a
      smaller pool to trade worst-case headroom for resident streams.

    ``kv_dtype="int8"`` (paged only) stores the pool quantized —
    ``{"k","v"}`` int8 plus ``{"k_scale","v_scale"}: (num_blocks, B,
    heads)`` fp32 per-token-per-head scales — roughly quartering the
    dominant HBM stream vs fp32 storage (head_dim bytes + 4 scale bytes
    per head-token instead of 4*head_dim) and so multiplying resident
    streams at a fixed budget. Quantization happens on write (prefill
    scatter + decode writeback, :func:`quantize_kv`), dequantization on
    read (the block gather, or fused into the paged-attention kernel).
    The default ``"float32"`` keeps full-precision storage in ``dtype``
    — the bitwise pre-int8 layout.
    """
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds the model's positional table "
            f"max_seq={cfg.max_seq}")
    if slots <= 0:
        raise ValueError(f"slots must be positive, got {slots}")
    if max_len <= 0:
        raise ValueError(f"max_len must be positive, got {max_len}")
    validate_kv_dtype(kv_dtype, block_size)
    dt = cfg.dtype if dtype is None else dtype
    if block_size is None:
        if num_blocks is not None:
            raise ValueError(
                f"num_blocks={num_blocks} requires block_size: the block "
                "pool is a paged-layout concept")
        shape = (slots, max_len, cfg.heads, cfg.head_dim)
        return {
            "layers": [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
                       for _ in range(cfg.layers)],
            "lengths": jnp.zeros((slots,), jnp.int32),
        }
    block_size = validate_block_size(block_size, max_len)
    blocks_per_slot = -(-max_len // block_size)
    if num_blocks is None:
        num_blocks = slots * blocks_per_slot + 1
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (block 0 is the reserved scratch "
            f"block), got {num_blocks}")
    shape = (num_blocks, block_size, cfg.heads, cfg.head_dim)
    if kv_dtype == "int8":
        sshape = (num_blocks, block_size, cfg.heads)
        return {
            "layers": [{"k": jnp.zeros(shape, jnp.int8),
                        "v": jnp.zeros(shape, jnp.int8),
                        "k_scale": jnp.zeros(sshape, jnp.float32),
                        "v_scale": jnp.zeros(sshape, jnp.float32)}
                       for _ in range(cfg.layers)],
        }
    return {
        "layers": [{"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
                   for _ in range(cfg.layers)],
    }


def kv_cache_pspecs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs for the cache: heads over 'model' (matching the
    column-parallel qkv layout), slots/positions replicated. Slots stay off
    the 'data' axis on purpose: prefill writes ONE slot at a time via
    dynamic_update_slice, which a slot-sharded cache would turn into an
    all-gather per admission."""
    kv = P(None, None, MODEL_AXIS, None)
    return {
        "layers": [{"k": kv, "v": kv} for _ in range(cfg.layers)],
        "lengths": P(),
    }


def paged_kv_cache_pspecs(cfg: TransformerConfig,
                          kv_dtype: str = "float32") -> Dict[str, Any]:
    """PartitionSpecs for the paged block pool: heads over 'model' (the
    same column-parallel qkv alignment as the contiguous cache), blocks
    and in-block positions replicated — the block table is a host-side
    gather index over the (replicated) block axis, so paging adds zero
    collectives under a dp/tp mesh. int8 pools carry per-token-per-head
    scale tensors whose heads axis shards identically."""
    kv = P(None, None, MODEL_AXIS, None)
    layer = {"k": kv, "v": kv}
    if kv_dtype == "int8":
        layer = dict(layer, k_scale=P(None, None, MODEL_AXIS),
                     v_scale=P(None, None, MODEL_AXIS))
    return {"layers": [dict(layer) for _ in range(cfg.layers)]}


def grow_block_table(tables: np.ndarray, slot: int, n_entries: int,
                     block: int) -> int:
    """Append one physical block to a slot's row of the HOST-side block
    table — the on-demand allocator's whole device story. The table is
    FIXED-WIDTH (``(slots, ceil(max_len/block_size))``, zero-padded to
    the scratch block), so growing a stream's footprint is writing the
    next entry of its row: the donated paged decode executable's
    signature never changes, only the gather index it is handed each
    step. Returns the new entry count; raises when the row is already
    full (the stream's ``max_len`` worth of blocks are all mapped —
    admission bounds total length, so hitting this is a bookkeeping
    bug, not load)."""
    if not 0 <= n_entries < tables.shape[1]:
        raise ValueError(
            f"slot {slot} block-table row is full ({n_entries} of "
            f"{tables.shape[1]} entries) — cannot map block {block}")
    tables[slot, n_entries] = block
    return n_entries + 1


def place_kv_cache(cache, cfg: TransformerConfig, mesh: Mesh):
    """Shard a generation cache (any layout — the contiguous one carries
    'lengths', the paged pool does not, the int8 pool adds scales) onto
    the mesh."""
    if "lengths" in cache:
        spec = kv_cache_pspecs(cfg)
    else:
        kv_dtype = "int8" if "k_scale" in cache["layers"][0] else "float32"
        spec = paged_kv_cache_pspecs(cfg, kv_dtype)
    return jax.device_put(cache, tree_shardings(mesh, spec))


def sample_token(logits, key, temperature, top_k):
    """On-device sampling for ONE stream: greedy (``temperature <= 0``),
    temperature, and top-k — all shape-static so per-request knobs never
    mint a new executable (``top_k == 0`` disables the filter; greedy is a
    select, not a python branch). Sampling itself is the gumbel-max trick,
    so only ``key`` (not co-scheduled neighbors) touches the draw —
    bitwise-identical streams whether a slot decodes alone or co-batched.

    The gumbel draw runs under ``threefry_partitionable``: inside the
    sharded prefill/decode executables the logits are vocab-sharded
    (column-parallel lm_head), and legacy threefry generates DIFFERENT
    bits when GSPMD partitions the random op — the partitionable
    implementation is sharding-invariant, so a stream is also bitwise
    independent of the mesh shape serving it."""
    logits = logits.astype(jnp.float32)
    v = logits.shape[-1]
    desc = jnp.sort(logits)[::-1]
    kth = desc[jnp.clip(top_k - 1, 0, v - 1)]
    filtered = jnp.where(
        logits >= jnp.where(top_k > 0, kth, -jnp.inf), logits, -jnp.inf)
    greedy = temperature <= 0.0
    with jax.threefry_partitionable(True):
        gumbel = jax.random.gumbel(key, (v,), jnp.float32)
    z = jnp.where(greedy, filtered,
                  filtered / jnp.where(greedy, 1.0, temperature) + gumbel)
    return jnp.argmax(z).astype(jnp.int32)


def _sample_at(logits, key, step, temperature, top_k):
    """Per-stream sample of token index ``step``: the request's base PRNG
    key folded with the step index, so a stream's draws depend only on
    (key, step) — never on which slot or iteration served it."""
    with jax.threefry_partitionable(True):
        folded = jax.random.fold_in(key, step)
    return sample_token(logits, folded, temperature, top_k)


def make_prefill(cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Build the jitted prefill: run one PADDED prompt through the standard
    forward (the same ``_block`` — flash/packed attention routing included),
    write its per-layer K/V into cache slot ``slot``, and sample token 0.

    ``prefill(params, cache, tokens, slot, length, key, temperature, top_k)
    -> (cache, token0)`` with tokens (1, T_bucket) int32 and ``length`` the
    real prompt length. One executable per T bucket; the cache is donated so
    prefill updates in place. Prompts prefill one at a time (batch dim 1):
    batching prompts too would square the signature ladder (T × B buckets)
    and break per-request bitwise determinism."""
    if not cfg.causal:
        raise ValueError("generation needs a causal LM: set "
                         "TransformerConfig(causal=True)")

    def prefill(params, cache, tokens, slot, length, key, temperature, top_k):
        _, T = tokens.shape
        slot = jnp.asarray(slot, jnp.int32)
        z = jnp.zeros((), jnp.int32)   # literal 0s would be int64 under x64
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][:T][None].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, k, v = _block(bp, x, cfg, mesh, return_kv=True)
                layers.append({
                    "k": lax.dynamic_update_slice(
                        lc["k"], k.astype(lc["k"].dtype), (slot, z, z, z)),
                    "v": lax.dynamic_update_slice(
                        lc["v"], v.astype(lc["v"].dtype), (slot, z, z, z)),
                })
            x = _layernorm(x, params["ln_f"])
            last = lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                            keepdims=False)
            logits = (last @ params["lm_head"].astype(last.dtype)
                      ).astype(jnp.float32)
        token0 = _sample_at(logits, key, 0, temperature, top_k)
        new_cache = {"layers": layers,
                     "lengths": cache["lengths"].at[slot].set(length)}
        return new_cache, token0

    if mesh is None:
        return jax.jit(prefill, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, kv_cache_pspecs(cfg))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        prefill, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh, repl, repl, repl, repl, repl, repl),
        out_shardings=(cache_sh, repl))


def make_decode_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Build THE decode executable: one token for every slot, live or dead.

    ``decode_step(params, cache, tokens, live, keys, steps, temperatures,
    top_ks) -> (cache, next_tokens)`` where every argument after ``cache``
    is a (slots,)-leading array — tokens int32 (last sampled token per
    slot), live bool (dead slots compute masked garbage and keep their
    lengths), keys (slots, 2) uint32 per-request base PRNG keys, steps
    int32 (index of the token being sampled). Shape is (slots,) no matter
    how many slots are occupied, so this compiles EXACTLY ONCE per engine
    lifetime; the cache is donated, so decode is a true in-place update.

    Per-slot math is row-wise (layernorm, GEMMs, masked attention over the
    slot's own cache rows, gumbel-max under the slot's own folded key), so
    a stream's tokens are bitwise-independent of its co-tenants — the
    property continuous batching needs to be transparent to callers."""
    if not cfg.causal:
        raise ValueError("generation needs a causal LM: set "
                         "TransformerConfig(causal=True)")

    def decode_block(bp, x, lc, pos):
        # x: (S, hidden); lc["k"]/["v"]: (S, L, heads, D); pos: (S,) write
        # position (== current length, clamped). New K/V land at pos, the
        # query attends positions 0..pos inclusive — per-slot causal mask.
        S, H = x.shape
        L = lc["k"].shape[1]
        h = _layernorm(x, bp["ln1"])
        qkv = h @ bp["qkv"]["kernel"].astype(h.dtype) \
            + bp["qkv"]["bias"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, cfg.heads, cfg.head_dim)
        rows = jnp.arange(S)
        ck = lc["k"].at[rows, pos].set(
            k.reshape(S, cfg.heads, cfg.head_dim).astype(lc["k"].dtype))
        cv = lc["v"].at[rows, pos].set(
            v.reshape(S, cfg.heads, cfg.head_dim).astype(lc["v"].dtype))
        scale = 1.0 / np.sqrt(cfg.head_dim)
        s = jnp.einsum("shd,slhd->shl", q, ck.astype(q.dtype)) * scale
        mask = jnp.arange(L)[None, :] <= pos[:, None]          # (S, L)
        s = jnp.where(mask[:, None, :], s, jnp.finfo(s.dtype).min)
        p = jax.nn.softmax(s.astype(cfg.softmax_dtype), axis=-1).astype(q.dtype)
        o = jnp.einsum("shl,slhd->shd", p, cv.astype(p.dtype)).reshape(S, H)
        x = x + o @ bp["attn_out"]["kernel"].astype(o.dtype) \
            + bp["attn_out"]["bias"].astype(o.dtype)
        h = _layernorm(x, bp["ln2"])
        h = h @ bp["mlp_in"]["kernel"].astype(h.dtype) \
            + bp["mlp_in"]["bias"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=True)
        x = x + h @ bp["mlp_out"]["kernel"].astype(h.dtype) \
            + bp["mlp_out"]["bias"].astype(h.dtype)
        return x, {"k": ck, "v": cv}

    def decode_step(params, cache, tokens, live, keys, steps,
                    temperatures, top_ks):
        lengths = cache["lengths"]
        max_len = cache["layers"][0]["k"].shape[1]
        pos = jnp.clip(lengths, 0, max_len - 1)
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][pos].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, lc = decode_block(bp, x, lc, pos)
                layers.append(lc)
            x = _layernorm(x, params["ln_f"])
            logits = (x @ params["lm_head"].astype(x.dtype)
                      ).astype(jnp.float32)
        next_tokens = jax.vmap(_sample_at)(logits, keys, steps,
                                           temperatures, top_ks)
        new_cache = {"layers": layers,
                     "lengths": jnp.where(live, lengths + 1, lengths)}
        return new_cache, next_tokens

    if mesh is None:
        return jax.jit(decode_step, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, kv_cache_pspecs(cfg))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        decode_step, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh) + (repl,) * 6,
        out_shardings=(cache_sh, repl))


# --------------------------------------------------------------------------
# Paged generation: block-pool KV cache + block-table gather decode
# --------------------------------------------------------------------------
#
# The contiguous cache above reserves worst-case (slots, max_len) rows, so
# HBM — not compute — caps resident streams. The paged variants (vLLM,
# Kwon et al. SOSP '23) store K/V in a shared pool of fixed-size blocks and
# address it through a per-slot FIXED-SHAPE block table passed from the
# host: decode gathers ``pool[block_table]`` back into the exact (S, L,
# heads, head_dim) layout the contiguous attention consumed, so the math —
# and crucially the compiled-signature story — is unchanged: ONE donated
# decode executable for the engine's lifetime, one prefill per prompt
# bucket. Sequence lengths host-side; copy-on-write for shared prefixes is
# a (src, dst) block-copy argument folded INTO the decode executable (a
# no-op self-copy of the scratch block on steps with nothing to CoW), so
# prefix sharing mints no third executable.


def make_paged_prefill(cfg: TransformerConfig, block_size: int,
                       mesh: Optional[Mesh] = None,
                       kv_dtype: str = "float32"):
    """Build the jitted paged prefill: one PADDED prompt through the
    standard forward (the same ``_block``), its per-layer K/V scattered
    into the physical blocks named by ``block_row``, and token 0 sampled.

    ``prefill(params, cache, tokens, block_row, length, key, temperature,
    top_k, step) -> (cache, token0)`` with tokens (1, T_bucket) int32 and
    ``block_row`` (ceil(T_bucket/block_size),) int32 physical block ids —
    entries past the prompt's real blocks point at the reserved scratch
    block 0, so padding K/V lands in scratch, never in a live block. One
    executable per T bucket; the cache (block pool) is donated. Unlike
    the contiguous prefill there is no ``slot`` argument: lengths live on
    the host, and the block row alone names where this prompt's K/V go.

    ``step`` is the SAMPLE index the trailing token draw folds into the
    request key (``_sample_at``): 0 for a fresh prompt (the pre-existing
    behavior, bitwise-unchanged), and the victim's next token index when
    a preempted stream recomputes through prefill with its
    generated-so-far tokens appended to the prompt — per-request keys
    fold the token index, so the resumed draw is position-stable and the
    resumed stream bitwise-matches its unpreempted run.

    ``kv_dtype="int8"``: quantization is FOLDED into the scatter — each
    block's values land int8 with their per-token scales written beside
    them, so the fp-sized prompt K/V never touches the pool."""
    if not cfg.causal:
        raise ValueError("generation needs a causal LM: set "
                         "TransformerConfig(causal=True)")
    validate_kv_dtype(kv_dtype, block_size)

    def prefill(params, cache, tokens, block_row, length, key,
                temperature, top_k, step):
        _, T = tokens.shape
        nb = block_row.shape[0]
        pad = nb * block_size - T
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][:T][None].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, k, v = _block(bp, x, cfg, mesh, return_kv=True)
                kb = jnp.pad(k[0], ((0, pad), (0, 0), (0, 0))).reshape(
                    nb, block_size, cfg.heads, cfg.head_dim)
                vb = jnp.pad(v[0], ((0, pad), (0, 0), (0, 0))).reshape(
                    nb, block_size, cfg.heads, cfg.head_dim)
                if kv_dtype == "int8":
                    kq, ks = quantize_kv(kb)
                    vq, vs = quantize_kv(vb)
                    layers.append({
                        "k": lc["k"].at[block_row].set(kq),
                        "v": lc["v"].at[block_row].set(vq),
                        "k_scale": lc["k_scale"].at[block_row].set(ks),
                        "v_scale": lc["v_scale"].at[block_row].set(vs),
                    })
                    continue
                layers.append({
                    "k": lc["k"].at[block_row].set(kb.astype(lc["k"].dtype)),
                    "v": lc["v"].at[block_row].set(vb.astype(lc["v"].dtype)),
                })
            x = _layernorm(x, params["ln_f"])
            last = lax.dynamic_index_in_dim(x[0], length - 1, axis=0,
                                            keepdims=False)
            logits = (last @ params["lm_head"].astype(last.dtype)
                      ).astype(jnp.float32)
        token0 = _sample_at(logits, key, step, temperature, top_k)
        return {"layers": layers}, token0

    if mesh is None:
        return jax.jit(prefill, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, paged_kv_cache_pspecs(cfg, kv_dtype))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        prefill, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh) + (repl,) * 7,
        out_shardings=(cache_sh, repl))


def _paged_attention_mesh_spec(cfg: TransformerConfig, mesh: Mesh):
    """PartitionSpecs for running the fused paged-attention kernel under
    ``mesh`` via shard_map — heads ride the 'model' axis (matching the
    column-parallel qkv layout), block/table/position axes replicate, so
    the per-device kernel is embarrassingly parallel over heads: zero
    extra collectives, exactly the packed-kernel pattern. Returns None
    when the kernel cannot partition (heads not divisible)."""
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if cfg.heads % tp:
        return None
    m = MODEL_AXIS if MODEL_AXIS in mesh.axis_names else None
    return {"q": P(None, m, None), "pool": P(None, None, m, None),
            "scale": P(None, None, m), "repl": P()}


def make_paged_decode_step(cfg: TransformerConfig, block_size: int,
                           mesh: Optional[Mesh] = None,
                           kv_dtype: str = "float32",
                           paged_attention: str = "gather"):
    """Build THE paged decode executable: one token for every slot.

    ``decode_step(params, cache, tables, lengths, tokens, keys, steps,
    temperatures, top_ks, cow_src, cow_dst) -> (cache, next_tokens)``
    where ``tables`` is the (slots, max_blocks_per_slot) int32 block table
    (a dead slot's row is all scratch-block 0 — its write lands in
    scratch, its gather reads masked garbage), ``lengths`` (slots,) int32
    the host-tracked token counts, and ``cow_src``/``cow_dst`` (slots,)
    int32 drive the copy-on-write: each slot's dst block is overwritten
    with its src block BEFORE this step's K/V write and gather (slots with
    nothing to CoW pass src == dst == 0, a scratch self-copy). Every
    argument is fixed-shape, so this compiles EXACTLY ONCE per engine
    lifetime — the block-table gather preserves the contiguous path's
    one-donated-executable invariant while the pool replaces the
    per-slot worst-case reservation.

    ``paged_attention`` selects how the attention read happens:

    - ``"gather"`` (default): XLA materializes ``pool[tables]`` back into
      the (S, L, heads, D) layout the contiguous attention consumed —
      same einsums, same mask, bitwise-stable vs PR 6 at
      ``kv_dtype="float32"``, but the single-token read pays a full
      HBM round-trip of the gathered view every step.
    - ``"fused"``: the Pallas :func:`~deeplearning4j_tpu.ops.
      pallas_kernels.paged_decode_attention` kernel streams each slot's
      blocks through VMEM behind a scalar-prefetched block table — the
      (S, L) view never exists in HBM, and int8 dequant fuses into the
      same pass. Numerically equivalent within fp tolerance (online
      softmax reassociates the reduction); still the SAME single donated
      executable and signature.

    ``kv_dtype="int8"`` stores the pool quantized (see
    :func:`init_kv_cache`): the decode writeback quantizes the new token
    (per-token scales — no block requantization), the CoW copy moves
    scales alongside values, and both attention routes dequantize on
    read."""
    if not cfg.causal:
        raise ValueError("generation needs a causal LM: set "
                         "TransformerConfig(causal=True)")
    validate_kv_dtype(kv_dtype, block_size)
    if paged_attention not in ("gather", "fused"):
        raise ValueError(
            f"paged_attention must be 'gather' or 'fused', "
            f"got {paged_attention!r}")
    quantized = kv_dtype == "int8"
    mesh_spec = None
    if paged_attention == "fused" and mesh is not None:
        mesh_spec = _paged_attention_mesh_spec(cfg, mesh)
        if mesh_spec is None:
            raise ValueError(
                f"paged_attention='fused' cannot shard {cfg.heads} heads "
                f"over the mesh's {mesh.shape.get(MODEL_AXIS, 1)}-way "
                f"'{MODEL_AXIS}' axis; use paged_attention='gather' or a "
                "dividing mesh")

    def _fused_attention(q, ck, cv, cks, cvs, tables, pos, scale):
        from deeplearning4j_tpu.ops.pallas_kernels import (
            paged_decode_attention)
        interp = jax.default_backend() != "tpu"

        def _local(ql, kl, vl, tb, ps, *scales):
            ksl, vsl = scales if quantized else (None, None)
            return paged_decode_attention(
                ql, kl, vl, tb, ps, block_size=block_size, scale=scale,
                k_scale=ksl, v_scale=vsl, interpret=interp)

        if mesh is None:
            return _local(q, ck, cv, tables, pos,
                          *((cks, cvs) if quantized else ()))
        ms = mesh_spec
        in_specs = (ms["q"], ms["pool"], ms["pool"], ms["repl"],
                    ms["repl"]) + ((ms["scale"],) * 2 if quantized else ())
        return shard_map(_local, mesh=mesh, in_specs=in_specs,
                         out_specs=ms["q"], check_rep=False)(
            q, ck, cv, tables, pos,
            *((cks, cvs) if quantized else ()))

    def decode_block(bp, x, lc, tables, pos, cow_src, cow_dst):
        # x: (S, hidden); lc["k"]/["v"]: (NB, B, heads, D); tables:
        # (S, max_blocks); pos: (S,) logical write position. CoW first,
        # then the new K/V write, then the attention read — data
        # dependence orders them, so the read sees both.
        S, H = x.shape
        nb = tables.shape[1]
        L = nb * block_size
        h = _layernorm(x, bp["ln1"])
        qkv = h @ bp["qkv"]["kernel"].astype(h.dtype) \
            + bp["qkv"]["bias"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, cfg.heads, cfg.head_dim)
        rows = jnp.arange(S)
        ck = lc["k"].at[cow_dst].set(lc["k"][cow_src])
        cv = lc["v"].at[cow_dst].set(lc["v"][cow_src])
        blk = pos // block_size
        off = pos % block_size
        pb = tables[rows, blk]                                 # (S,)
        cks = cvs = None
        if quantized:
            cks = lc["k_scale"].at[cow_dst].set(lc["k_scale"][cow_src])
            cvs = lc["v_scale"].at[cow_dst].set(lc["v_scale"][cow_src])
            kq, ks = quantize_kv(k.reshape(S, cfg.heads, cfg.head_dim))
            vq, vs = quantize_kv(v.reshape(S, cfg.heads, cfg.head_dim))
            ck = ck.at[pb, off].set(kq)
            cv = cv.at[pb, off].set(vq)
            cks = cks.at[pb, off].set(ks)
            cvs = cvs.at[pb, off].set(vs)
        else:
            ck = ck.at[pb, off].set(
                k.reshape(S, cfg.heads, cfg.head_dim).astype(ck.dtype))
            cv = cv.at[pb, off].set(
                v.reshape(S, cfg.heads, cfg.head_dim).astype(cv.dtype))
        scale = 1.0 / np.sqrt(cfg.head_dim)
        if paged_attention == "fused":
            o = _fused_attention(q, ck, cv, cks, cvs, tables, pos,
                                 scale).reshape(S, H).astype(x.dtype)
        else:
            # block-table gather: back to the exact (S, L, heads, D)
            # layout the contiguous attention consumed — same einsums,
            # same mask (int8 dequantizes into the compute dtype first)
            gk = ck[tables].reshape(S, L, cfg.heads, cfg.head_dim)
            gv = cv[tables].reshape(S, L, cfg.heads, cfg.head_dim)
            if quantized:
                gks = cks[tables].reshape(S, L, cfg.heads)
                gvs = cvs[tables].reshape(S, L, cfg.heads)
                gk = (gk.astype(jnp.float32)
                      * gks[..., None]).astype(q.dtype)
                gv = (gv.astype(jnp.float32)
                      * gvs[..., None]).astype(q.dtype)
            s = jnp.einsum("shd,slhd->shl", q, gk.astype(q.dtype)) * scale
            mask = jnp.arange(L)[None, :] <= pos[:, None]      # (S, L)
            s = jnp.where(mask[:, None, :], s, jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s.astype(cfg.softmax_dtype),
                               axis=-1).astype(q.dtype)
            o = jnp.einsum("shl,slhd->shd", p,
                           gv.astype(p.dtype)).reshape(S, H)
        x = x + o @ bp["attn_out"]["kernel"].astype(o.dtype) \
            + bp["attn_out"]["bias"].astype(o.dtype)
        h = _layernorm(x, bp["ln2"])
        h = h @ bp["mlp_in"]["kernel"].astype(h.dtype) \
            + bp["mlp_in"]["bias"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=True)
        x = x + h @ bp["mlp_out"]["kernel"].astype(h.dtype) \
            + bp["mlp_out"]["bias"].astype(h.dtype)
        out = {"k": ck, "v": cv}
        if quantized:
            out.update(k_scale=cks, v_scale=cvs)
        return x, out

    def decode_step(params, cache, tables, lengths, tokens, keys, steps,
                    temperatures, top_ks, cow_src, cow_dst):
        L = tables.shape[1] * block_size
        pos = jnp.clip(lengths, 0, min(L, cfg.max_seq) - 1)
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][pos].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, lc = decode_block(bp, x, lc, tables, pos, cow_src,
                                     cow_dst)
                layers.append(lc)
            x = _layernorm(x, params["ln_f"])
            logits = (x @ params["lm_head"].astype(x.dtype)
                      ).astype(jnp.float32)
        next_tokens = jax.vmap(_sample_at)(logits, keys, steps,
                                           temperatures, top_ks)
        return {"layers": layers}, next_tokens

    if mesh is None:
        return jax.jit(decode_step, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, paged_kv_cache_pspecs(cfg, kv_dtype))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        decode_step, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh) + (repl,) * 9,
        out_shardings=(cache_sh, repl))


# --------------------------------------------------------------------------
# Speculative decoding: draft-model executables + k-token verify step
# --------------------------------------------------------------------------
#
# Speculative decoding (Leviathan et al., ICML'23) amortizes decode's
# memory-bandwidth cost: a small DRAFT model proposes k tokens one at a
# time (cheap — its whole KV stream is tiny), then the target model scores
# all k+1 positions in ONE fixed-shape verify step and commits the longest
# proposal prefix its own sampling agrees with. The adaptation here is
# exact-match verification against the target's OWN deterministic samples:
# every token of a stream is already a pure function of (request key, token
# index) via ``_sample_at``, so the verify step computes the target's
# samples g_0..g_k at the k+1 positions and acceptance only decides HOW
# MANY of them commit this turn — the emitted values are ALWAYS the
# target's, so a speculative stream is bitwise the non-speculative one at
# ANY temperature, not just greedy. Speedup comes from acceptance, never
# from changed sampling.
#
# The draft model keeps a CONTIGUOUS (slots, max_len) cache with NO
# device-side lengths — the scheduler passes lengths per call, so
# rewinding a rejected tail after verify is host arithmetic, not a device
# op. Both factories preserve the one-donated-executable discipline: one
# draft step, one verify step, for the engine's lifetime.


def init_draft_kv_cache(cfg: TransformerConfig, slots: int, max_len: int,
                        dtype: Any = None) -> Dict[str, Any]:
    """Allocate the draft model's contiguous KV cache: the legacy
    (slots, max_len, heads, head_dim) layout WITHOUT the device-side
    ``lengths`` leaf — draft positions are host-tracked so the serving
    scheduler can rewind a rejected speculation tail for free (the next
    turn simply passes a smaller length and overwrites)."""
    if max_len > cfg.max_seq:
        raise ValueError(
            f"max_len {max_len} exceeds the draft model's positional "
            f"table max_seq={cfg.max_seq}")
    dt = cfg.dtype if dtype is None else dtype
    shape = (slots, max_len, cfg.heads, cfg.head_dim)
    return {"layers": [{"k": jnp.zeros(shape, dt),
                        "v": jnp.zeros(shape, dt)}
                       for _ in range(cfg.layers)]}


def draft_kv_cache_pspecs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs for the draft cache: identical head-over-'model'
    layout as :func:`kv_cache_pspecs`, minus the lengths leaf."""
    kv = P(None, None, MODEL_AXIS, None)
    return {"layers": [{"k": kv, "v": kv} for _ in range(cfg.layers)]}


def place_draft_kv_cache(cache, cfg: TransformerConfig, mesh: Mesh):
    """Shard a draft KV cache onto ``mesh`` per
    :func:`draft_kv_cache_pspecs` (heads over the 'model' axis)."""
    return jax.device_put(cache,
                          tree_shardings(mesh, draft_kv_cache_pspecs(cfg)))


def make_draft_prefill(cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Build the jitted draft prefill: one PADDED prompt through the draft
    model's standard forward, its per-layer K/V written into cache slot
    ``slot``. ``draft_prefill(params, cache, tokens, slot) -> cache`` with
    tokens (1, T_bucket) int32. No sampling — the draft's first proposal
    is drawn by :func:`make_draft_step` feeding the target's last sampled
    token. One executable per T bucket (the engine reuses its prompt
    ladder); the cache is donated. Padding K/V past the real prompt lands
    in the slot row but is masked by every later draft step's causal mask,
    exactly the contiguous target layout's convention."""
    if not cfg.causal:
        raise ValueError("speculative drafting needs a causal LM: set "
                         "TransformerConfig(causal=True)")

    def draft_prefill(params, cache, tokens, slot):
        _, T = tokens.shape
        slot = jnp.asarray(slot, jnp.int32)
        z = jnp.zeros((), jnp.int32)
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][:T][None].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, k, v = _block(bp, x, cfg, mesh, return_kv=True)
                layers.append({
                    "k": lax.dynamic_update_slice(
                        lc["k"], k.astype(lc["k"].dtype), (slot, z, z, z)),
                    "v": lax.dynamic_update_slice(
                        lc["v"], v.astype(lc["v"].dtype), (slot, z, z, z)),
                })
        return {"layers": layers}

    if mesh is None:
        return jax.jit(draft_prefill, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, draft_kv_cache_pspecs(cfg))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        draft_prefill, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh, repl, repl),
        out_shardings=cache_sh)


def make_draft_step(cfg: TransformerConfig, mesh: Optional[Mesh] = None):
    """Build THE draft decode executable: one proposed token per slot.

    ``draft_step(params, cache, tokens, lengths, keys, steps,
    temperatures, top_ks) -> (cache, proposals)`` — the contiguous
    :func:`make_decode_step` math with ``lengths`` passed from the HOST
    (the draft cache has no device lengths and no ``live`` mask: dead or
    draft-cold slots compute masked garbage the scheduler ignores). The
    scheduler invokes this executable k times per speculative turn, each
    call feeding the previous proposal at the next position; ``steps``
    carries the TARGET token index each proposal predicts, so the gumbel
    draw folds the exact key/step the verify step will fold — a draft
    whose logits track the target's proposes the target's own sample with
    high probability even at temperature > 0. Shape is (slots,) always,
    so this compiles EXACTLY ONCE; the cache is donated."""
    if not cfg.causal:
        raise ValueError("speculative drafting needs a causal LM: set "
                         "TransformerConfig(causal=True)")

    def draft_block(bp, x, lc, pos):
        S, H = x.shape
        L = lc["k"].shape[1]
        h = _layernorm(x, bp["ln1"])
        qkv = h @ bp["qkv"]["kernel"].astype(h.dtype) \
            + bp["qkv"]["bias"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, cfg.heads, cfg.head_dim)
        rows = jnp.arange(S)
        ck = lc["k"].at[rows, pos].set(
            k.reshape(S, cfg.heads, cfg.head_dim).astype(lc["k"].dtype))
        cv = lc["v"].at[rows, pos].set(
            v.reshape(S, cfg.heads, cfg.head_dim).astype(lc["v"].dtype))
        scale = 1.0 / np.sqrt(cfg.head_dim)
        s = jnp.einsum("shd,slhd->shl", q, ck.astype(q.dtype)) * scale
        mask = jnp.arange(L)[None, :] <= pos[:, None]
        s = jnp.where(mask[:, None, :], s, jnp.finfo(s.dtype).min)
        p = jax.nn.softmax(s.astype(cfg.softmax_dtype),
                           axis=-1).astype(q.dtype)
        o = jnp.einsum("shl,slhd->shd", p, cv.astype(p.dtype)).reshape(S, H)
        x = x + o @ bp["attn_out"]["kernel"].astype(o.dtype) \
            + bp["attn_out"]["bias"].astype(o.dtype)
        h = _layernorm(x, bp["ln2"])
        h = h @ bp["mlp_in"]["kernel"].astype(h.dtype) \
            + bp["mlp_in"]["bias"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=True)
        x = x + h @ bp["mlp_out"]["kernel"].astype(h.dtype) \
            + bp["mlp_out"]["bias"].astype(h.dtype)
        return x, {"k": ck, "v": cv}

    def draft_step(params, cache, tokens, lengths, keys, steps,
                   temperatures, top_ks):
        max_len = cache["layers"][0]["k"].shape[1]
        pos = jnp.clip(lengths, 0, min(max_len, cfg.max_seq) - 1)
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][pos].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, lc = draft_block(bp, x, lc, pos)
                layers.append(lc)
            x = _layernorm(x, params["ln_f"])
            logits = (x @ params["lm_head"].astype(x.dtype)
                      ).astype(jnp.float32)
        proposals = jax.vmap(_sample_at)(logits, keys, steps,
                                         temperatures, top_ks)
        return {"layers": layers}, proposals

    if mesh is None:
        return jax.jit(draft_step, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, draft_kv_cache_pspecs(cfg))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        draft_step, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh) + (repl,) * 6,
        out_shardings=(cache_sh, repl))


def make_verify_step(cfg: TransformerConfig, block_size: int, k: int,
                     mesh: Optional[Mesh] = None,
                     kv_dtype: str = "float32",
                     paged_attention: str = "gather"):
    """Build THE speculative verify executable: score k+1 positions per
    slot in one step and count the accepted proposal prefix on device.

    ``verify_step(params, cache, tables, lengths, tokens, keys, steps,
    temperatures, top_ks, cow_src, cow_dst) -> (cache, samples,
    accepted)`` — :func:`make_paged_decode_step` extended from one query
    per slot to ``k + 1``: ``tokens`` is (slots, k+1) int32 with column 0
    the slot's last committed token and columns 1..k the draft proposals
    d_1..d_k; K/V for ALL k+1 tokens are written at positions length..
    length+k, each query position length+j attends its own causal prefix
    (positions <= length+j), and ``samples[:, j]`` is the TARGET's own
    deterministic sample for token index ``steps + j`` — per-position
    attention reuses the single-query decode math exactly, so
    ``samples[:, j]`` is bitwise what ``decode_step`` would have sampled
    at that point given the same history. ``accepted[:, ]`` counts the
    longest prefix with ``tokens[:, j+1] == samples[:, j]`` — the
    rejection-sampling acceptance under deterministic gumbel-max
    (exact-match, temperature-independent). The scheduler commits
    ``min(accepted+1, k)`` of the samples; position length+accepted+1's
    K/V (a rejected proposal's) is overwritten by the next turn's write
    at the new length, the same convention a dead slot's garbage follows.

    Writes that would land past the pool capacity or ``cfg.max_seq`` are
    routed to the reserved scratch block 0 instead of clamping — a
    clamped scatter near the boundary would collide multiple positions
    onto a LIVE block entry and corrupt committed K/V; scratch-routing
    keeps dead/overflow garbage where dead-slot garbage already lives.
    Dead slots compute masked garbage across all k+1 positions exactly as
    they do in decode_step. Both attention routes (``"gather"`` and the
    fused Pallas kernel — invoked once per query position inside the SAME
    executable) and both ``kv_dtype`` modes are supported; every argument
    is fixed-shape, so this compiles EXACTLY ONCE per engine lifetime and
    the engine's executable bound grows to buckets + 2 (prefill ladder +
    decode + verify)."""
    if not cfg.causal:
        raise ValueError("generation needs a causal LM: set "
                         "TransformerConfig(causal=True)")
    if k < 1:
        raise ValueError(
            f"verify needs k >= 1 proposed tokens per turn, got {k} — "
            "k == 0 IS the plain decode_step; the engine falls back to "
            "it rather than minting a degenerate verify executable")
    validate_kv_dtype(kv_dtype, block_size)
    if paged_attention not in ("gather", "fused"):
        raise ValueError(
            f"paged_attention must be 'gather' or 'fused', "
            f"got {paged_attention!r}")
    T = k + 1
    quantized = kv_dtype == "int8"
    mesh_spec = None
    if paged_attention == "fused" and mesh is not None:
        mesh_spec = _paged_attention_mesh_spec(cfg, mesh)
        if mesh_spec is None:
            raise ValueError(
                f"paged_attention='fused' cannot shard {cfg.heads} heads "
                f"over the mesh's {mesh.shape.get(MODEL_AXIS, 1)}-way "
                f"'{MODEL_AXIS}' axis; use paged_attention='gather' or a "
                "dividing mesh")

    def _fused_attention(q, ck, cv, cks, cvs, tables, pos, scale):
        from deeplearning4j_tpu.ops.pallas_kernels import (
            paged_decode_attention)
        interp = jax.default_backend() != "tpu"

        def _local(ql, kl, vl, tb, ps, *scales):
            ksl, vsl = scales if quantized else (None, None)
            return paged_decode_attention(
                ql, kl, vl, tb, ps, block_size=block_size, scale=scale,
                k_scale=ksl, v_scale=vsl, interpret=interp)

        if mesh is None:
            return _local(q, ck, cv, tables, pos,
                          *((cks, cvs) if quantized else ()))
        ms = mesh_spec
        in_specs = (ms["q"], ms["pool"], ms["pool"], ms["repl"],
                    ms["repl"]) + ((ms["scale"],) * 2 if quantized else ())
        return shard_map(_local, mesh=mesh, in_specs=in_specs,
                         out_specs=ms["q"], check_rep=False)(
            q, ck, cv, tables, pos,
            *((cks, cvs) if quantized else ()))

    def verify_block(bp, x, lc, tables, pos, cow_src, cow_dst):
        # x: (S, T, hidden); lc pool tensors: (NB, B, heads, D); pos:
        # (S,) the FIRST write position (== current length, clamped).
        # CoW first, then all T K/V writes, then the per-position
        # attention reads — data dependence orders them.
        S, _T, H = x.shape
        nb = tables.shape[1]
        L = nb * block_size
        Lcap = min(L, cfg.max_seq)
        h = _layernorm(x, bp["ln1"])
        qkv = h @ bp["qkv"]["kernel"].astype(h.dtype) \
            + bp["qkv"]["bias"].astype(h.dtype)
        q, kx, vx = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(S, T, cfg.heads, cfg.head_dim)
        rows = jnp.arange(S)
        ck = lc["k"].at[cow_dst].set(lc["k"][cow_src])
        cv = lc["v"].at[cow_dst].set(lc["v"][cow_src])
        # (S, T) write positions; overflow routes to the scratch block —
        # NOT a clamp: a clamped position would scatter-collide onto a
        # live block entry and corrupt committed K/V near the boundary
        posm = pos[:, None] + jnp.arange(T, dtype=pos.dtype)[None, :]
        valid = posm < Lcap
        blk = jnp.minimum(posm, L - 1) // block_size
        off = posm % block_size
        pb = jnp.where(valid, tables[rows[:, None], blk], 0)
        cks = cvs = None
        if quantized:
            cks = lc["k_scale"].at[cow_dst].set(lc["k_scale"][cow_src])
            cvs = lc["v_scale"].at[cow_dst].set(lc["v_scale"][cow_src])
            kq, ks = quantize_kv(
                kx.reshape(S, T, cfg.heads, cfg.head_dim))
            vq, vs = quantize_kv(
                vx.reshape(S, T, cfg.heads, cfg.head_dim))
            ck = ck.at[pb, off].set(kq)
            cv = cv.at[pb, off].set(vq)
            cks = cks.at[pb, off].set(ks)
            cvs = cvs.at[pb, off].set(vs)
        else:
            ck = ck.at[pb, off].set(
                kx.reshape(S, T, cfg.heads, cfg.head_dim).astype(ck.dtype))
            cv = cv.at[pb, off].set(
                vx.reshape(S, T, cfg.heads, cfg.head_dim).astype(cv.dtype))
        scale = 1.0 / np.sqrt(cfg.head_dim)
        if paged_attention == "fused":
            outs = [
                _fused_attention(
                    q[:, j], ck, cv, cks, cvs, tables,
                    jnp.minimum(pos + j, Lcap - 1), scale)
                for j in range(T)]
            o = jnp.stack(outs, axis=1).reshape(S, T, H).astype(x.dtype)
        else:
            gk = ck[tables].reshape(S, L, cfg.heads, cfg.head_dim)
            gv = cv[tables].reshape(S, L, cfg.heads, cfg.head_dim)
            if quantized:
                gks = cks[tables].reshape(S, L, cfg.heads)
                gvs = cvs[tables].reshape(S, L, cfg.heads)
                gk = (gk.astype(jnp.float32)
                      * gks[..., None]).astype(q.dtype)
                gv = (gv.astype(jnp.float32)
                      * gvs[..., None]).astype(q.dtype)
            # one single-query attention per position — the EXACT einsum
            # shapes decode_step compiles, so each position's output (and
            # therefore its sample) is bitwise the sequential decode's
            outs = []
            for j in range(T):
                pj = jnp.minimum(pos + j, Lcap - 1)
                s = jnp.einsum("shd,slhd->shl", q[:, j],
                               gk.astype(q.dtype)) * scale
                mask = jnp.arange(L)[None, :] <= pj[:, None]
                s = jnp.where(mask[:, None, :], s, jnp.finfo(s.dtype).min)
                p = jax.nn.softmax(s.astype(cfg.softmax_dtype),
                                   axis=-1).astype(q.dtype)
                outs.append(jnp.einsum("shl,slhd->shd", p,
                                       gv.astype(p.dtype)))
            o = jnp.stack(outs, axis=1).reshape(S, T, H)
        x = x + o @ bp["attn_out"]["kernel"].astype(o.dtype) \
            + bp["attn_out"]["bias"].astype(o.dtype)
        h = _layernorm(x, bp["ln2"])
        h = h @ bp["mlp_in"]["kernel"].astype(h.dtype) \
            + bp["mlp_in"]["bias"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=True)
        x = x + h @ bp["mlp_out"]["kernel"].astype(h.dtype) \
            + bp["mlp_out"]["bias"].astype(h.dtype)
        out = {"k": ck, "v": cv}
        if quantized:
            out.update(k_scale=cks, v_scale=cvs)
        return x, out

    def verify_step(params, cache, tables, lengths, tokens, keys, steps,
                    temperatures, top_ks, cow_src, cow_dst):
        L = tables.shape[1] * block_size
        Lcap = min(L, cfg.max_seq)
        pos = jnp.clip(lengths, 0, Lcap - 1)
        posm = jnp.minimum(
            pos[:, None] + jnp.arange(T, dtype=pos.dtype)[None, :],
            Lcap - 1)
        with jax.default_matmul_precision("default"):
            x = params["tok_emb"][tokens].astype(cfg.dtype) \
                + params["pos_emb"][posm].astype(cfg.dtype)
            layers = []
            for bp, lc in zip(params["blocks"], cache["layers"]):
                x, lc = verify_block(bp, x, lc, tables, pos, cow_src,
                                     cow_dst)
                layers.append(lc)
            x = _layernorm(x, params["ln_f"])
            logits = (x @ params["lm_head"].astype(x.dtype)
                      ).astype(jnp.float32)

        def _sample_row(lg, key, step0, temperature, top_k):
            st = step0 + jnp.arange(T, dtype=jnp.int32)
            return jax.vmap(
                lambda l, s: _sample_at(l, key, s, temperature, top_k)
            )(lg, st)

        samples = jax.vmap(_sample_row)(logits, keys, steps,
                                        temperatures, top_ks)
        matches = (tokens[:, 1:] == samples[:, :k]).astype(jnp.int32)
        accepted = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
        return {"layers": layers}, samples, accepted.astype(jnp.int32)

    if mesh is None:
        return jax.jit(verify_step, donate_argnums=(1,))
    param_sh = _shardings(cfg, mesh)
    cache_sh = tree_shardings(mesh, paged_kv_cache_pspecs(cfg, kv_dtype))
    repl = NamedSharding(mesh, P())
    return jax.jit(
        verify_step, donate_argnums=(1,),
        in_shardings=(param_sh, cache_sh) + (repl,) * 9,
        out_shardings=(cache_sh, repl, repl))
