"""Evaluation suite (ref: org.nd4j.evaluation)."""
from deeplearning4j_tpu.eval.evaluation import (  # noqa: F401
    ROC, Evaluation, EvaluationBinary, EvaluationCalibration,
    RegressionEvaluation, ROCBinary, ROCMultiClass,
)
