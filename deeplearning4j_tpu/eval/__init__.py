"""Evaluation suite (ref: org.nd4j.evaluation)."""
from deeplearning4j_tpu.eval.evaluation import ROC, Evaluation, RegressionEvaluation, ROCMultiClass  # noqa: F401
