"""Classification / regression / ROC evaluation (ref: org.nd4j.evaluation —
Evaluation, RegressionEvaluation, ROC, ROCMultiClass, EvaluationCalibration).

Streaming accumulators: ``eval(labels, predictions)`` per batch, metrics on
demand — same usage contract as the reference. Accumulation happens on host
in numpy (tiny data); the heavy forward pass stays jitted on device.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def _np(x):
    from deeplearning4j_tpu.ndarray.array import NDArray
    if isinstance(x, NDArray):
        return x.toNumpy()
    return np.asarray(x)


class Evaluation:
    """Multi-class classification metrics (ref: org.nd4j.evaluation.classification.Evaluation):
    accuracy, precision/recall/F1 (macro + per-class), confusion matrix."""

    def __init__(self, num_classes: Optional[int] = None, labels: Optional[list] = None):
        self.num_classes = num_classes
        self.label_names = labels
        self.confusion: Optional[np.ndarray] = None

    def eval(self, labels, predictions, mask=None):
        y = _np(labels)
        p = _np(predictions)
        if y.ndim == 3:  # (B,T,C) time series: flatten time
            y = y.reshape(-1, y.shape[-1])
            p = p.reshape(-1, p.shape[-1])
            if mask is not None:
                m = _np(mask).reshape(-1).astype(bool)
                y, p = y[m], p[m]
        true = y.argmax(-1) if y.ndim > 1 else y.astype(int)
        pred = p.argmax(-1) if p.ndim > 1 else p.astype(int)
        n = self.num_classes or int(max(true.max(initial=0), pred.max(initial=0))) + 1
        if self.confusion is None:
            self.num_classes = n
            self.confusion = np.zeros((n, n), dtype=np.int64)
        elif n > self.confusion.shape[0]:
            grown = np.zeros((n, n), dtype=np.int64)
            grown[:self.confusion.shape[0], :self.confusion.shape[1]] = self.confusion
            self.confusion = grown
            self.num_classes = n
        np.add.at(self.confusion, (true, pred), 1)

    # ---- metrics
    def accuracy(self) -> float:
        c = self.confusion
        return float(np.trace(c) / max(c.sum(), 1))

    def _tp_fp_fn(self, cls):
        c = self.confusion
        tp = c[cls, cls]
        fp = c[:, cls].sum() - tp
        fn = c[cls, :].sum() - tp
        return tp, fp, fn

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            tp, fp, _ = self._tp_fp_fn(cls)
            return float(tp / max(tp + fp, 1))
        return float(np.mean([self.precision(i) for i in range(self.num_classes)]))

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            tp, _, fn = self._tp_fp_fn(cls)
            return float(tp / max(tp + fn, 1))
        return float(np.mean([self.recall(i) for i in range(self.num_classes)]))

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / max(p + r, 1e-12)
        return float(np.mean([self.f1(i) for i in range(self.num_classes)]))

    def falsePositiveRate(self, cls: int) -> float:
        c = self.confusion
        tp, fp, fn = self._tp_fp_fn(cls)
        tn = c.sum() - tp - fp - fn
        return float(fp / max(fp + tn, 1))

    def matthewsCorrelation(self, cls: int) -> float:
        c = self.confusion
        tp, fp, fn = self._tp_fp_fn(cls)
        tn = c.sum() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / max(denom, 1e-12))

    def confusionMatrix(self) -> np.ndarray:
        return self.confusion

    def stats(self) -> str:
        lines = [
            f"# of classes: {self.num_classes}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall:    {self.recall():.4f}",
            f"F1 Score:  {self.f1():.4f}",
            "Confusion matrix:",
            str(self.confusion),
        ]
        return "\n".join(lines)


class RegressionEvaluation:
    """(ref: org.nd4j.evaluation.regression.RegressionEvaluation): MSE, MAE,
    RMSE, R^2, pearson correlation — per-column streaming."""

    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.sum_err2 = None
        self.sum_abs = None
        self.sum_y = None
        self.sum_y2 = None
        self.sum_p = None
        self.sum_p2 = None
        self.sum_yp = None

    def eval(self, labels, predictions, mask=None):
        y = _np(labels).astype(np.float64)
        p = _np(predictions).astype(np.float64)
        y = y.reshape(-1, y.shape[-1])
        p = p.reshape(-1, p.shape[-1])
        if mask is None:
            m = np.ones((y.shape[0], 1))
        else:
            m = _np(mask).astype(np.float64)
            m = m.reshape(-1, y.shape[-1]) if m.size == y.size \
                else m.reshape(-1, 1)  # per-timestep mask broadcast over cols
        if self.sum_err2 is None:
            cols = y.shape[-1]
            self.sum_err2 = np.zeros(cols)
            self.sum_abs = np.zeros(cols)
            self.sum_y = np.zeros(cols)
            self.sum_y2 = np.zeros(cols)
            self.sum_p = np.zeros(cols)
            self.sum_p2 = np.zeros(cols)
            self.sum_yp = np.zeros(cols)
        self.n = self.n + m.sum(0)  # per-col counts ((1,) broadcasts)
        self.sum_err2 += ((p - y) ** 2 * m).sum(0)
        self.sum_abs += (np.abs(p - y) * m).sum(0)
        self.sum_y += (y * m).sum(0)
        self.sum_y2 += (y ** 2 * m).sum(0)
        self.sum_p += (p * m).sum(0)
        self.sum_p2 += (p ** 2 * m).sum(0)
        self.sum_yp += (y * p * m).sum(0)

    def meanSquaredError(self, col=None):
        mse = self.sum_err2 / self.n
        return float(mse.mean() if col is None else mse[col])

    def meanAbsoluteError(self, col=None):
        mae = self.sum_abs / self.n
        return float(mae.mean() if col is None else mae[col])

    def rootMeanSquaredError(self, col=None):
        return float(np.sqrt(self.meanSquaredError(col)))

    def rSquared(self, col=None):
        ss_res = self.sum_err2
        ss_tot = self.sum_y2 - self.sum_y ** 2 / self.n
        r2 = 1.0 - ss_res / np.maximum(ss_tot, 1e-12)
        return float(r2.mean() if col is None else r2[col])

    def pearsonCorrelation(self, col=None):
        cov = self.sum_yp - self.sum_y * self.sum_p / self.n
        vy = self.sum_y2 - self.sum_y ** 2 / self.n
        vp = self.sum_p2 - self.sum_p ** 2 / self.n
        r = cov / np.maximum(np.sqrt(vy * vp), 1e-12)
        return float(r.mean() if col is None else r[col])

    def stats(self) -> str:
        return (f"MSE: {self.meanSquaredError():.6f}  MAE: {self.meanAbsoluteError():.6f}  "
                f"RMSE: {self.rootMeanSquaredError():.6f}  R^2: {self.rSquared():.4f}")


class ROC:
    """Binary ROC/AUC with exact computation (ref: org.nd4j.evaluation.classification.ROC
    with thresholdSteps=0 'exact' mode)."""

    def __init__(self):
        self.scores = []
        self.labels = []

    def eval(self, labels, predictions, mask=None):
        y = _np(labels)
        p = _np(predictions)
        if y.ndim > 1 and y.shape[-1] == 2:  # one-hot binary: positive = col 1
            y = y[..., 1]
            p = p[..., 1]
        self.labels.append(y.reshape(-1))
        self.scores.append(p.reshape(-1))

    def calculateAUC(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tp = np.cumsum(y)
        fp = np.cumsum(1 - y)
        n_pos, n_neg = max(tp[-1], 1e-12), max(fp[-1], 1e-12)
        tpr = np.concatenate([[0.0], tp / n_pos])
        fpr = np.concatenate([[0.0], fp / n_neg])
        return float(np.trapezoid(tpr, fpr))

    def calculateAUCPR(self) -> float:
        y = np.concatenate(self.labels)
        s = np.concatenate(self.scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        tp = np.cumsum(y)
        precision = tp / np.arange(1, len(y) + 1)
        recall = tp / max(tp[-1], 1e-12)
        return float(np.trapezoid(precision, recall))


class ROCMultiClass:
    """One-vs-all ROC per class (ref: org.nd4j.evaluation.classification.ROCMultiClass)."""

    def __init__(self):
        self.per_class: dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        y = _np(labels)
        p = _np(predictions)
        for c in range(y.shape[-1]):
            self.per_class.setdefault(c, ROC()).eval(y[..., c], p[..., c])

    def calculateAUC(self, cls: int) -> float:
        return self.per_class[cls].calculateAUC()

    def calculateAverageAUC(self) -> float:
        return float(np.mean([r.calculateAUC() for r in self.per_class.values()]))


class EvaluationBinary:
    """Per-output independent binary metrics for multi-label sigmoid outputs
    (ref: org.nd4j.evaluation.classification.EvaluationBinary — counts
    TP/FP/TN/FN per output column at a 0.5 decision threshold, mask-aware)."""

    def __init__(self, n_columns: Optional[int] = None, decision_threshold: float = 0.5):
        self.n = n_columns
        self.threshold = decision_threshold
        self._tp = self._fp = self._tn = self._fn = None

    def _ensure(self, n):
        if self._tp is None:
            self.n = n
            self._tp = np.zeros(n); self._fp = np.zeros(n)
            self._tn = np.zeros(n); self._fn = np.zeros(n)

    def eval(self, labels, predictions, mask=None):
        y = _np(labels)
        p = _np(predictions)
        y2 = y.reshape(-1, y.shape[-1])
        p2 = p.reshape(-1, p.shape[-1])
        self._ensure(y2.shape[-1])
        m = np.ones(y2.shape) if mask is None else _np(mask).reshape(-1, y2.shape[-1])
        pred = (p2 >= self.threshold).astype(np.float64)
        self._tp += ((pred == 1) & (y2 == 1) & (m > 0)).sum(0)
        self._fp += ((pred == 1) & (y2 == 0) & (m > 0)).sum(0)
        self._tn += ((pred == 0) & (y2 == 0) & (m > 0)).sum(0)
        self._fn += ((pred == 0) & (y2 == 1) & (m > 0)).sum(0)

    def truePositives(self, col):  return int(self._tp[col])
    def falsePositives(self, col): return int(self._fp[col])
    def trueNegatives(self, col):  return int(self._tn[col])
    def falseNegatives(self, col): return int(self._fn[col])

    def accuracy(self, col) -> float:
        tot = self._tp[col] + self._fp[col] + self._tn[col] + self._fn[col]
        return float((self._tp[col] + self._tn[col]) / max(tot, 1e-12))

    def precision(self, col) -> float:
        return float(self._tp[col] / max(self._tp[col] + self._fp[col], 1e-12))

    def recall(self, col) -> float:
        return float(self._tp[col] / max(self._tp[col] + self._fn[col], 1e-12))

    def f1(self, col) -> float:
        pr, rc = self.precision(col), self.recall(col)
        return 2 * pr * rc / max(pr + rc, 1e-12)

    def averageAccuracy(self) -> float:
        return float(np.mean([self.accuracy(i) for i in range(self.n)]))

    def averageF1(self) -> float:
        return float(np.mean([self.f1(i) for i in range(self.n)]))

    def stats(self) -> str:
        lines = ["EvaluationBinary (threshold %.2f)" % self.threshold]
        for i in range(self.n or 0):
            lines.append(
                f"  out {i}: acc {self.accuracy(i):.4f} precision "
                f"{self.precision(i):.4f} recall {self.recall(i):.4f} "
                f"f1 {self.f1(i):.4f}")
        return "\n".join(lines)


class ROCBinary:
    """Per-output-column ROC for multi-label binary outputs
    (ref: org.nd4j.evaluation.classification.ROCBinary)."""

    def __init__(self):
        self.per_output: dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        y = _np(labels)
        p = _np(predictions)
        y2 = y.reshape(-1, y.shape[-1])
        p2 = p.reshape(-1, p.shape[-1])
        m = None if mask is None else _np(mask).reshape(-1, y2.shape[-1])
        for c in range(y2.shape[-1]):
            yc, pc = y2[:, c], p2[:, c]
            if m is not None:
                keep = m[:, c] > 0
                yc, pc = yc[keep], pc[keep]
            self.per_output.setdefault(c, ROC()).eval(yc, pc)

    def calculateAUC(self, col: int) -> float:
        return self.per_output[col].calculateAUC()

    def calculateAUCPR(self, col: int) -> float:
        return self.per_output[col].calculateAUCPR()

    def calculateAverageAUC(self) -> float:
        return float(np.mean([r.calculateAUC() for r in self.per_output.values()]))


class EvaluationCalibration:
    """Probability-calibration diagnostics (ref: org.nd4j.evaluation.
    classification.EvaluationCalibration): reliability diagram (accuracy vs
    confidence per bin), expected calibration error, residual-probability and
    predicted-probability histograms."""

    def __init__(self, reliability_bins: int = 10, histogram_bins: int = 50):
        self.rbins = reliability_bins
        self.hbins = histogram_bins
        self._conf = []   # predicted prob of the true class's argmax decision
        self._hit = []    # argmax correct?
        self._probs = []  # every predicted probability (flattened)
        self._residuals = []  # |label - p| per class entry

    def eval(self, labels, predictions, mask=None):
        y = _np(labels).reshape(-1, _np(labels).shape[-1])
        p = _np(predictions).reshape(-1, y.shape[-1])
        if mask is not None:
            keep = _np(mask).reshape(-1) > 0
            y, p = y[keep], p[keep]
        pred_cls = p.argmax(-1)
        true_cls = y.argmax(-1)
        self._conf.append(p[np.arange(len(p)), pred_cls])
        self._hit.append((pred_cls == true_cls).astype(np.float64))
        self._probs.append(p.reshape(-1))
        self._residuals.append(np.abs(y - p).reshape(-1))

    def reliabilityDiagram(self):
        """(bin_centers, mean_confidence, accuracy, counts) per bin."""
        conf = np.concatenate(self._conf)
        hit = np.concatenate(self._hit)
        edges = np.linspace(0.0, 1.0, self.rbins + 1)
        idx = np.clip(np.digitize(conf, edges) - 1, 0, self.rbins - 1)
        centers = (edges[:-1] + edges[1:]) / 2
        mean_conf = np.zeros(self.rbins)
        acc = np.zeros(self.rbins)
        counts = np.zeros(self.rbins)
        for b in range(self.rbins):
            sel = idx == b
            counts[b] = sel.sum()
            if counts[b]:
                mean_conf[b] = conf[sel].mean()
                acc[b] = hit[sel].mean()
        return centers, mean_conf, acc, counts

    def expectedCalibrationError(self) -> float:
        _, mean_conf, acc, counts = self.reliabilityDiagram()
        total = max(counts.sum(), 1e-12)
        return float(np.sum(counts / total * np.abs(acc - mean_conf)))

    def probabilityHistogram(self):
        probs = np.concatenate(self._probs)
        counts, edges = np.histogram(probs, bins=self.hbins, range=(0.0, 1.0))
        return edges, counts

    def residualPlot(self):
        res = np.concatenate(self._residuals)
        counts, edges = np.histogram(res, bins=self.hbins, range=(0.0, 1.0))
        return edges, counts

    def stats(self) -> str:
        return (f"EvaluationCalibration: ECE "
                f"{self.expectedCalibrationError():.4f} over {self.rbins} bins")
