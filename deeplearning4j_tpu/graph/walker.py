"""Random-walk generation (ref: deeplearning4j-graph
org.deeplearning4j.graph.iterator.RandomWalkIterator).

The reference walks one vertex at a time through Java iterators; here ALL
walks advance together as one vectorized numpy step per depth level
(gather neighbor rows, sample a column) — the batch shape a TPU-backed
skip-gram trainer wants anyway.
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


def generate_walks(graph: Graph, walk_length: int, walks_per_vertex: int = 1,
                   seed: int = 0, starts: Optional[np.ndarray] = None) -> np.ndarray:
    """(num_walks, walk_length) int32 vertex-id matrix; every vertex starts
    ``walks_per_vertex`` walks (ref: DeepWalk.fit iterates a
    RandomWalkIterator per vertex)."""
    rng = np.random.default_rng(seed)
    nbr, deg = graph.neighbors_arrays()
    if starts is None:
        starts = np.repeat(np.arange(graph.n, dtype=np.int32), walks_per_vertex)
        rng.shuffle(starts)
    walks = np.empty((len(starts), walk_length), np.int32)
    walks[:, 0] = starts
    cur = starts
    for t in range(1, walk_length):
        # uniform neighbor choice: col ~ U[0, deg(v))
        col = (rng.random(len(cur)) * deg[cur]).astype(np.int64)
        cur = nbr[cur, col]
        walks[:, t] = cur
    return walks


class RandomWalkIterator:
    """Iterator facade over generate_walks (ref: RandomWalkIterator —
    kept for API parity; prefer generate_walks for bulk use)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 0):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self._walks = None
        self._i = 0

    def __iter__(self) -> Iterator[np.ndarray]:
        self._walks = generate_walks(self.graph, self.walk_length, 1, self.seed)
        self._i = 0
        return self

    def __next__(self) -> np.ndarray:
        if self._walks is None or self._i >= len(self._walks):
            raise StopIteration
        w = self._walks[self._i]
        self._i += 1
        return w
