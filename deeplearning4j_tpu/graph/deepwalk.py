"""DeepWalk vertex embeddings (ref: deeplearning4j-graph
org.deeplearning4j.graph.models.deepwalk.DeepWalk + GraphVectorsImpl).

The reference trains hierarchical-softmax skip-gram over walks via its own
GraphHuffman tree, one pair at a time. Here walks are a (num_walks, L) int32
matrix and training reuses the word2vec module's batched
negative-sampling skip-gram step (text/word2vec.py _sg_step) — one jitted
scatter-update per batch; vertex ids are the vocabulary directly (no
tokenizer round-trip).
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walker import generate_walks
from deeplearning4j_tpu.text.word2vec import _sg_step_jit


class GraphVectors:
    """Learned vertex embeddings (ref: org.deeplearning4j.graph.models.
    GraphVectors: getVertexVector / verticesNearest / similarity)."""

    def __init__(self, vectors: np.ndarray, graph: Graph):
        self.vectors = vectors
        self.graph = graph

    def numVertices(self) -> int:
        return len(self.vectors)

    def getVertexVector(self, v: int) -> np.ndarray:
        return self.vectors[v]

    def similarity(self, a: int, b: int) -> float:
        va, vb = self.vectors[a], self.vectors[b]
        den = np.linalg.norm(va) * np.linalg.norm(vb)
        return float(va @ vb / max(den, 1e-12))

    def verticesNearest(self, v: int, top: int = 5) -> List[int]:
        sims = np.array([self.similarity(v, u) for u in range(len(self.vectors))])
        sims[v] = -np.inf
        return list(np.argsort(-sims)[:top])


class DeepWalk:
    """(ref: DeepWalk.Builder: windowSize/vectorSize/walkLength/learningRate)."""

    def __init__(self, vectorSize: int = 64, windowSize: int = 5,
                 walkLength: int = 40, walksPerVertex: int = 10,
                 learningRate: float = 0.025, minLearningRate: float = 1e-4,
                 negativeSample: int = 5, epochs: int = 1,
                 batchSize: int = 512, seed: int = 42):
        self.vectorSize = vectorSize
        self.windowSize = windowSize
        self.walkLength = walkLength
        self.walksPerVertex = walksPerVertex
        self.learningRate = learningRate
        self.minLearningRate = minLearningRate
        self.negative = max(int(negativeSample), 1)
        self.epochs = epochs
        self.batchSize = batchSize
        self.seed = seed
        self.vectors: Optional[np.ndarray] = None

    def fit(self, graph: Graph) -> GraphVectors:
        rng = np.random.default_rng(self.seed)
        V, D = graph.numVertices(), self.vectorSize
        syn0 = jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), jnp.float32)

        # unigram table from vertex degree^0.75 (the degree distribution is
        # the walk-visit distribution's stationary proxy)
        deg = np.array([max(graph.getDegree(v), 1) for v in range(V)], np.float64)
        p = deg ** 0.75
        p /= p.sum()

        b_eff = min(self.batchSize, max(64, 4 * V))
        for ep in range(self.epochs):
            walks = generate_walks(graph, self.walkLength, self.walksPerVertex,
                                   seed=self.seed + ep)
            pairs = []
            for walk in walks:
                for i, c in enumerate(walk):
                    b = rng.integers(1, self.windowSize + 1)
                    lo, hi = max(0, i - b), min(len(walk), i + b + 1)
                    for j in range(lo, hi):
                        if j != i:
                            pairs.append((c, walk[j]))
            pairs = np.asarray(pairs, dtype=np.int32)
            rng.shuffle(pairs)
            nb = max(1, -(-len(pairs) // b_eff))
            for bi, k in enumerate(range(0, len(pairs), b_eff)):
                frac = (ep + bi / nb) / max(self.epochs, 1)
                lr = max(self.minLearningRate, self.learningRate * (1 - frac))
                batch = pairs[k:k + b_eff]
                neg = rng.choice(V, size=(len(batch), self.negative),
                                 p=p).astype(np.int32)
                syn0, syn1 = _sg_step_jit(syn0, syn1,
                                          jnp.asarray(batch[:, 0]),
                                          jnp.asarray(batch[:, 1]),
                                          jnp.asarray(neg), lr)
        self.vectors = np.asarray(syn0)
        return GraphVectors(self.vectors, graph)
