from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walker import RandomWalkIterator, generate_walks
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, GraphVectors

__all__ = ["Graph", "RandomWalkIterator", "generate_walks", "DeepWalk",
           "GraphVectors"]
