"""Graph structure (ref: deeplearning4j-graph org.deeplearning4j.graph.graph.
Graph + api.Vertex/Edge — a simple indexed adjacency structure feeding random
walks; vertices are integer ids with optional labels)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class Graph:
    def __init__(self, num_vertices: int, directed: bool = False,
                 labels: Optional[Sequence[str]] = None):
        self.n = num_vertices
        self.directed = directed
        self.labels = list(labels) if labels else [str(i) for i in range(num_vertices)]
        self._adj: List[List[int]] = [[] for _ in range(num_vertices)]
        self._w: List[List[float]] = [[] for _ in range(num_vertices)]

    # ------------------------------------------------------------- building
    def addEdge(self, a: int, b: int, weight: float = 1.0):
        self._adj[a].append(b)
        self._w[a].append(weight)
        if not self.directed:
            self._adj[b].append(a)
            self._w[b].append(weight)

    @staticmethod
    def fromEdgeList(edges: Sequence[Tuple[int, int]], num_vertices=None,
                     directed=False) -> "Graph":
        n = num_vertices or (max(max(a, b) for a, b in edges) + 1)
        g = Graph(n, directed=directed)
        for a, b in edges:
            g.addEdge(a, b)
        return g

    # -------------------------------------------------------------- queries
    def numVertices(self) -> int:
        return self.n

    def getDegree(self, v: int) -> int:
        return len(self._adj[v])

    def getConnectedVertices(self, v: int) -> List[int]:
        return list(self._adj[v])

    def neighbors_arrays(self):
        """Padded neighbor matrix + degree vector for vectorized walking:
        (N, max_deg) int32 with self-loops padding isolated rows."""
        max_deg = max(1, max(len(a) for a in self._adj))
        nbr = np.zeros((self.n, max_deg), np.int32)
        deg = np.zeros(self.n, np.int32)
        for v, a in enumerate(self._adj):
            deg[v] = len(a)
            if a:
                nbr[v, :len(a)] = a
            else:
                nbr[v, :] = v  # isolated: walk stays in place
        return nbr, np.maximum(deg, 1)
