"""Build the native library: ``python -m deeplearning4j_tpu.native.build``.

Single g++ invocation — no cmake ceremony for one translation unit. The
.so lands next to the source and is loaded by ctypes (see __init__.py);
__init__ also auto-builds on first import when g++ is present.
"""
import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "dl4j_native.cpp")
OUT = os.path.join(HERE, "libdl4j_native.so")


def build(verbose: bool = True) -> str:
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        raise RuntimeError("no C++ compiler found (g++/clang++)")
    cmd = [gxx, "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           SRC, "-o", OUT]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    sys.exit(0 if os.path.exists(build()) else 1)
