// Native data-pipeline hot path (ref: the reference's C++ ETL layer —
// datavec native IO and libnd4j's cnpy/file loaders; SURVEY.md §2.3 notes
// the JVM reference drops to native for exactly this: tokenize-and-parse
// throughput on large record files).
//
// Exposed via ctypes (no pybind11 in this toolchain). All functions use a
// plain C ABI; buffers are caller-allocated numpy arrays.
//
// Build: python -m deeplearning4j_tpu.native.build  (g++ -O3 -shared -fPIC)
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- CSV
// Count rows (non-empty lines) and columns (fields in first non-empty line).
// Returns 0 on success.
int csv_dims(const char* buf, int64_t len, char delim, int64_t* rows,
             int64_t* cols) {
  *rows = 0;
  *cols = 0;
  int64_t i = 0;
  // first non-empty line -> cols
  while (i < len) {
    int64_t start = i;
    while (i < len && buf[i] != '\n') i++;
    int64_t line_len = i - start;
    i++;  // skip newline
    if (line_len == 0 || (line_len == 1 && buf[start] == '\r')) continue;
    if (*cols == 0) {
      int64_t c = 1;
      for (int64_t j = start; j < start + line_len; j++)
        if (buf[j] == delim) c++;
      *cols = c;
    }
    (*rows)++;
  }
  return 0;
}

// Parse one chunk of lines [line_lo, line_hi) given precomputed line offsets.
static void parse_chunk(const char* buf, const int64_t* line_off,
                        const int64_t* line_len, int64_t line_lo,
                        int64_t line_hi, int64_t cols, char delim, double* out,
                        std::atomic<int>* err) {
  for (int64_t r = line_lo; r < line_hi; r++) {
    const char* p = buf + line_off[r];
    const char* end = p + line_len[r];
    for (int64_t c = 0; c < cols; c++) {
      // field span [p, fend): bound the parse so an empty trailing field
      // cannot let strtod skip the newline and eat the NEXT row's value
      const char* fend = p;
      while (fend < end && *fend != delim) fend++;
      double v = NAN;
      if (fend > p) {
        char* next = nullptr;
        v = strtod(p, &next);
        if (next == p || next > fend) v = NAN;
      }
      out[r * cols + c] = v;
      p = fend < end ? fend + 1 : end;
    }
  }
}

// Parse a full numeric CSV buffer into out[rows*cols] using `threads`
// worker threads. Rows/cols must come from csv_dims. Returns 0 on success.
int csv_parse(const char* buf, int64_t len, char delim, int64_t rows,
              int64_t cols, double* out, int threads) {
  // index line offsets (single pass)
  std::vector<int64_t> off, llen;
  off.reserve(rows);
  llen.reserve(rows);
  int64_t i = 0;
  while (i < len && (int64_t)off.size() < rows) {
    int64_t start = i;
    while (i < len && buf[i] != '\n') i++;
    int64_t L = i - start;
    i++;
    if (L == 0 || (L == 1 && buf[start] == '\r')) continue;
    off.push_back(start);
    llen.push_back(L);
  }
  if ((int64_t)off.size() != rows) return -1;

  std::atomic<int> err{0};
  if (threads <= 1 || rows < 1024) {
    parse_chunk(buf, off.data(), llen.data(), 0, rows, cols, delim, out, &err);
  } else {
    std::vector<std::thread> pool;
    int64_t per = (rows + threads - 1) / threads;
    for (int t = 0; t < threads; t++) {
      int64_t lo = t * per;
      int64_t hi = lo + per < rows ? lo + per : rows;
      if (lo >= hi) break;
      pool.emplace_back(parse_chunk, buf, off.data(), llen.data(), lo, hi,
                        cols, delim, out, &err);
    }
    for (auto& th : pool) th.join();
  }
  return err.load();
}

// ---------------------------------------------------------------- IDX
// IDX (MNIST container) header: magic [0, 0, dtype, ndim], then ndim
// big-endian uint32 dims, then data. Returns ndim, fills dims (max 8) and
// dtype code; -1 on malformed magic.
int idx_header(const char* buf, int64_t len, int64_t* dims, int* dtype) {
  if (len < 4 || buf[0] != 0 || buf[1] != 0) return -1;
  int dt = (unsigned char)buf[2];
  int nd = (unsigned char)buf[3];
  if (nd > 8 || len < 4 + 4 * nd) return -1;
  for (int d = 0; d < nd; d++) {
    const unsigned char* p = (const unsigned char*)buf + 4 + 4 * d;
    dims[d] = ((int64_t)p[0] << 24) | ((int64_t)p[1] << 16) |
              ((int64_t)p[2] << 8) | (int64_t)p[3];
  }
  *dtype = dt;
  return nd;
}

// Decode IDX payload to float64, scaling uint8 by 1/255 when scale != 0.
// Supports dtype 0x08 (uint8), 0x09 (int8), 0x0B (int16), 0x0C (int32),
// 0x0D (float32), 0x0E (float64). Returns 0 on success.
int idx_decode(const char* buf, int64_t len, int64_t offset, int64_t count,
               int dtype, int scale, double* out) {
  const unsigned char* p = (const unsigned char*)buf + offset;
  switch (dtype) {
    case 0x08: {
      if (offset + count > len) return -1;
      double k = scale ? (1.0 / 255.0) : 1.0;
      for (int64_t i = 0; i < count; i++) out[i] = p[i] * k;
      return 0;
    }
    case 0x09: {
      if (offset + count > len) return -1;
      for (int64_t i = 0; i < count; i++) out[i] = (signed char)p[i];
      return 0;
    }
    case 0x0B: {
      if (offset + 2 * count > len) return -1;
      for (int64_t i = 0; i < count; i++) {
        int16_t v = (int16_t)((p[2 * i] << 8) | p[2 * i + 1]);
        out[i] = v;
      }
      return 0;
    }
    case 0x0C: {
      if (offset + 4 * count > len) return -1;
      for (int64_t i = 0; i < count; i++) {
        int32_t v = (int32_t)(((uint32_t)p[4 * i] << 24) |
                              ((uint32_t)p[4 * i + 1] << 16) |
                              ((uint32_t)p[4 * i + 2] << 8) |
                              (uint32_t)p[4 * i + 3]);
        out[i] = v;
      }
      return 0;
    }
    case 0x0D: {
      if (offset + 4 * count > len) return -1;
      for (int64_t i = 0; i < count; i++) {
        uint32_t u = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
                     ((uint32_t)p[4 * i + 2] << 8) | (uint32_t)p[4 * i + 3];
        float f;
        memcpy(&f, &u, 4);
        out[i] = f;
      }
      return 0;
    }
    case 0x0E: {
      if (offset + 8 * count > len) return -1;
      for (int64_t i = 0; i < count; i++) {
        uint64_t u = 0;
        for (int b = 0; b < 8; b++) u = (u << 8) | p[8 * i + b];
        double d;
        memcpy(&d, &u, 8);
        out[i] = d;
      }
      return 0;
    }
    default:
      return -2;
  }
}

int dl4j_native_abi_version() { return 1; }

}  // extern "C"
