"""Native data-pipeline bindings (ref: the reference's C++ ETL/IO layer —
SURVEY.md §2.3: the JVM drops to native for record-parsing throughput; this
package is the same split: Python orchestrates, C++ parses).

ctypes over a single .so (pybind11 is not in this toolchain). The library
auto-builds on first import when a compiler is available; every entry point
has a pure-numpy fallback so the package works without a toolchain —
``native_available()`` reports which path is active.

Public surface:
- ``parse_csv(text | path)`` -> (rows, cols) float64 ndarray — multithreaded
  numeric CSV parsing.
- ``load_idx(path, scale=...)`` -> ndarray — IDX (MNIST container) decode.
- ``PrefetchIterator(iter, depth)`` — background-thread batch prefetcher
  (ref: AsyncDataSetIterator): overlaps host ETL with device compute.
"""
from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Iterator, Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libdl4j_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO):
        try:
            from deeplearning4j_tpu.native.build import build
            build(verbose=False)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.csv_dims.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
                             ctypes.POINTER(ctypes.c_int64),
                             ctypes.POINTER(ctypes.c_int64)]
    lib.csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
                              ctypes.c_int64, ctypes.c_int64,
                              ctypes.POINTER(ctypes.c_double), ctypes.c_int]
    lib.idx_header.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_int64),
                               ctypes.POINTER(ctypes.c_int)]
    lib.idx_decode.argtypes = [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                               ctypes.c_int64, ctypes.c_int, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_double)]
    if lib.dl4j_native_abi_version() != 1:
        return None
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------- CSV

def parse_csv(source: str, delimiter: str = ",", threads: int = 4,
              force_python: bool = False) -> np.ndarray:
    """Numeric CSV -> (rows, cols) float64. ``source`` is a path or raw text.
    Non-numeric fields become NaN (the caller's schema decides what that
    means — same contract as the reference's CSVRecordReader + Schema)."""
    if os.path.exists(source):
        with open(source, "rb") as f:
            data = f.read()
    else:
        data = source.encode()
    lib = None if force_python else _load()
    if lib is None:
        return _parse_csv_python(data.decode(), delimiter)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.csv_dims(data, len(data), delimiter.encode(), ctypes.byref(rows),
                      ctypes.byref(cols))
    if rc != 0 or rows.value == 0:
        return np.zeros((0, 0))
    out = np.empty((rows.value, cols.value), np.float64)
    rc = lib.csv_parse(data, len(data), delimiter.encode(), rows.value,
                       cols.value,
                       out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                       max(threads, 1))
    if rc != 0:
        raise ValueError(f"native csv parse failed rc={rc}")
    return out


def _parse_csv_python(text: str, delimiter: str) -> np.ndarray:
    rows = []
    for line in text.splitlines():
        if not line.strip():
            continue
        vals = []
        for f in line.split(delimiter):
            try:
                vals.append(float(f))
            except ValueError:
                vals.append(float("nan"))
        rows.append(vals)
    return np.asarray(rows, np.float64) if rows else np.zeros((0, 0))


# ------------------------------------------------------------------- IDX

def load_idx(path: str, scale: bool = False,
             force_python: bool = False) -> np.ndarray:
    """IDX container (MNIST images/labels) -> float64 ndarray; ``scale``
    divides uint8 payloads by 255 (image normalization in the decoder, one
    pass — ref: the reference's MnistManager does this in Java per pixel)."""
    with open(path, "rb") as f:
        data = f.read()
    lib = None if force_python else _load()
    if lib is None:
        return _load_idx_python(data, scale)
    dims = (ctypes.c_int64 * 8)()
    dtype = ctypes.c_int()
    nd = lib.idx_header(data, len(data), dims, ctypes.byref(dtype))
    if nd < 0:
        raise ValueError(f"malformed IDX file: {path}")
    shape = tuple(dims[i] for i in range(nd))
    count = int(np.prod(shape)) if shape else 1
    out = np.empty(count, np.float64)
    offset = 4 + 4 * nd
    rc = lib.idx_decode(data, len(data), offset, count, dtype.value,
                        1 if scale else 0,
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        raise ValueError(f"IDX decode failed rc={rc} dtype={dtype.value}")
    return out.reshape(shape)


_IDX_NP = {0x08: np.uint8, 0x09: np.int8, 0x0B: ">i2", 0x0C: ">i4",
           0x0D: ">f4", 0x0E: ">f8"}


def _load_idx_python(data: bytes, scale: bool) -> np.ndarray:
    if len(data) < 4 or data[0] != 0 or data[1] != 0:
        raise ValueError("malformed IDX header")
    dtype, nd = data[2], data[3]
    shape = tuple(int.from_bytes(data[4 + 4 * i:8 + 4 * i], "big")
                  for i in range(nd))
    arr = np.frombuffer(data, _IDX_NP[dtype], count=int(np.prod(shape)),
                        offset=4 + 4 * nd).reshape(shape).astype(np.float64)
    if scale and dtype == 0x08:
        arr = arr / 255.0
    return arr


# -------------------------------------------------------------- prefetch

class PrefetchIterator:
    """Background-thread prefetcher (ref: AsyncDataSetIterator — the
    reference's dedicated ETL thread + bounded queue). Wraps any iterator;
    ``depth`` bounds queued items so ETL cannot run unboundedly ahead."""

    _END = object()

    def __init__(self, iterable, depth: int = 2):
        self._iterable = iterable
        self.depth = depth
        self._q: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None

    def _worker(self, it):
        try:
            for item in it:
                self._q.put(item)
        except BaseException as e:  # surfaced on the consumer side
            self._err = e
        finally:
            self._q.put(self._END)

    def __iter__(self) -> Iterator:
        self._q = queue.Queue(maxsize=self.depth)
        self._err = None
        self._thread = threading.Thread(target=self._worker,
                                        args=(iter(self._iterable),),
                                        daemon=True)
        self._thread.start()
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._END:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item
