"""Training listeners + optimization callbacks
(ref: org.deeplearning4j.optimize.api.TrainingListener and
org.deeplearning4j.optimize.listeners.*)."""
from deeplearning4j_tpu.optimize.listeners import (
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CollectScoresListener, TimeIterationListener, EvaluativeListener,
    CheckpointListener)

__all__ = [
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "CollectScoresListener", "TimeIterationListener", "EvaluativeListener",
    "CheckpointListener",
]
