"""Training listeners (ref: org.deeplearning4j.optimize.listeners.* —
ScoreIterationListener, PerformanceListener, CollectScoresListener,
TimeIterationListener, EvaluativeListener; CheckpointListener lives in
o.d.optimize.listeners.CheckpointListener).

The listener SPI matches the reference's: iterationDone(model, iteration,
epoch) fired per optimizer step, onEpochEnd(model) per epoch. Models fire
these from their (single-XLA-executable) fit loops."""
from __future__ import annotations

import logging
import os
import time
from typing import List, Optional

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    """SPI (ref: org.deeplearning4j.optimize.api.TrainingListener)."""

    def iterationDone(self, model, iteration: int, epoch: int):
        pass

    def onEpochStart(self, model):
        pass

    def onEpochEnd(self, model):
        pass

    def requiresModelAtIteration(self, iteration: int) -> bool:
        """Whether iterationDone at ``iteration`` reads anything beyond
        ``model.score()`` / wall-clock (parameters, activations, saving the
        model, ...). The fused fit path packs fuseSteps optimizer steps into
        one lax.scan executable and replays the buffered per-step losses to
        listeners afterwards; it flushes the scan so the model is CURRENT
        exactly at iterations where this returns True. The conservative
        default (True for every iteration) keeps unknown listeners on the
        exact per-step path; score-only built-ins override to False."""
        return True


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (ref: ScoreIterationListener)."""

    def __init__(self, printIterations: int = 10):
        self.n = max(printIterations, 1)

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.n == 0:
            log.info("Score at iteration %d is %s", iteration, model.score())
            print(f"Score at iteration {iteration} is {model.score()}")

    def requiresModelAtIteration(self, iteration: int) -> bool:
        return False  # reads only score() — fuse freely


class PerformanceListener(TrainingListener):
    """Throughput reporting (ref: PerformanceListener — samples/sec, iter ms)."""

    def __init__(self, frequency: int = 10, reportScore: bool = False):
        self.frequency = max(frequency, 1)
        self.reportScore = reportScore
        self._last_t: Optional[float] = None
        self._last_iter = 0

    def iterationDone(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._last_t is not None and iteration % self.frequency == 0:
            dt = now - self._last_t
            iters = iteration - self._last_iter
            ms = 1000.0 * dt / max(iters, 1)
            msg = f"iteration {iteration}: {ms:.2f} ms/iter"
            if self.reportScore:
                msg += f", score {model.score()}"
            print(msg)
            self._last_t, self._last_iter = now, iteration
        elif self._last_t is None:
            self._last_t, self._last_iter = now, iteration

    def requiresModelAtIteration(self, iteration: int) -> bool:
        # flush the fused scan exactly at measurement iterations so the
        # wall-clock intervals it reports are real step time, not the
        # ~0-us replay artifacts of callbacks fired back-to-back mid-chunk
        return iteration % self.frequency == 0


class CollectScoresListener(TrainingListener):
    """Accumulate (iteration, score) pairs (ref: CollectScoresListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(frequency, 1)
        self.iterations: List[int] = []
        self.scores: List[float] = []

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.iterations.append(iteration)
            self.scores.append(model.score())

    def requiresModelAtIteration(self, iteration: int) -> bool:
        return False  # reads only score() — fuse freely


class TimeIterationListener(TrainingListener):
    """ETA logging (ref: TimeIterationListener)."""

    def __init__(self, iterationCount: int):
        self.total = iterationCount
        self._start = time.perf_counter()

    def iterationDone(self, model, iteration, epoch):
        elapsed = time.perf_counter() - self._start
        if iteration > 0:
            remaining = elapsed / iteration * (self.total - iteration)
            log.info("Remaining time estimate: %.1fs (%d/%d)", remaining,
                     iteration, self.total)

    def requiresModelAtIteration(self, iteration: int) -> bool:
        # cumulative ETA only (elapsed/iteration extrapolation): replaying
        # callbacks after a chunk shifts each estimate by at most one chunk
        # of wall-clock, it does not corrupt the cumulative math — fuse
        return False


class EvaluativeListener(TrainingListener):
    """Periodic holdout evaluation (ref: EvaluativeListener)."""

    def __init__(self, iterator, frequency: int = 1, unit: str = "epoch"):
        self.iterator = iterator
        self.frequency = max(frequency, 1)
        self.unit = unit
        self.evaluations: List = []

    def _evaluate(self, model):
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        ev = model.evaluate(self.iterator)
        self.evaluations.append(ev)
        print(ev.stats())

    def iterationDone(self, model, iteration, epoch):
        if self.unit == "iteration" and iteration % self.frequency == 0:
            self._evaluate(model)

    def requiresModelAtIteration(self, iteration: int) -> bool:
        # needs live params exactly at its evaluation iterations
        return self.unit == "iteration" and iteration % self.frequency == 0

    def onEpochEnd(self, model, *_):
        if self.unit == "epoch" and model.getEpochCount() % self.frequency == 0:
            self._evaluate(model)


class CheckpointListener(TrainingListener):
    """Periodic checkpoints with retention (ref: o.d.optimize.listeners.
    CheckpointListener: every N iters/epochs, keepLast(k), checkpoint_<n>_
    <Model>.zip + index file; static load helpers)."""

    def __init__(self, dirPath: str, keepLast: int = 0, saveEveryNEpochs: int = 0,
                 saveEveryNIterations: int = 0, logSaving: bool = False):
        self.dir = dirPath
        os.makedirs(dirPath, exist_ok=True)
        self.keepLast = keepLast
        self.everyNEpochs = saveEveryNEpochs
        self.everyNIterations = saveEveryNIterations
        self.logSaving = logSaving
        self._count = 0

    def _save(self, model):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        name = f"checkpoint_{self._count}_{type(model).__name__}.zip"
        path = os.path.join(self.dir, name)
        ModelSerializer.writeModel(model, path, saveUpdater=True)
        with open(os.path.join(self.dir, "checkpointInfo.txt"), "a") as f:
            f.write(f"{self._count},{name},{time.time()}\n")
        if self.logSaving:
            print(f"Saved checkpoint {path}")
        self._count += 1
        if self.keepLast > 0:
            self._prune()

    def _prune(self):
        cps = self.availableCheckpoints(self.dir)
        for n, name in cps[:-self.keepLast]:
            p = os.path.join(self.dir, name)
            if os.path.exists(p):
                os.remove(p)

    @staticmethod
    def availableCheckpoints(dirPath: str):
        out = []
        for f in os.listdir(dirPath):
            if f.startswith("checkpoint_") and f.endswith(".zip"):
                out.append((int(f.split("_")[1]), f))
        return sorted(out)

    @staticmethod
    def lastCheckpoint(dirPath: str) -> Optional[str]:
        cps = CheckpointListener.availableCheckpoints(dirPath)
        return os.path.join(dirPath, cps[-1][1]) if cps else None

    @staticmethod
    def loadCheckpointMLN(dirPath: str, number: Optional[int] = None):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        cps = dict(CheckpointListener.availableCheckpoints(dirPath))
        name = cps[number] if number is not None else cps[max(cps)]
        return ModelSerializer.restoreMultiLayerNetwork(os.path.join(dirPath, name))

    def iterationDone(self, model, iteration, epoch):
        if self.everyNIterations and iteration % self.everyNIterations == 0:
            self._save(model)

    def requiresModelAtIteration(self, iteration: int) -> bool:
        # needs live params exactly at its save iterations
        return bool(self.everyNIterations) \
            and iteration % self.everyNIterations == 0

    def onEpochEnd(self, model, *_):
        if self.everyNEpochs and model.getEpochCount() % self.everyNEpochs == 0:
            self._save(model)
