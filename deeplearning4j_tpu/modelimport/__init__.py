"""Framework import (ref: deeplearning4j-modelimport + nd4j/samediff-import)."""
