"""Keras h5 import (ref: deeplearning4j-modelimport —
org.deeplearning4j.nn.modelimport.keras.KerasModelImport)."""
from deeplearning4j_tpu.modelimport.keras.importer import KerasModelImport

__all__ = ["KerasModelImport"]
