"""Keras h5 -> network import (ref: deeplearning4j-modelimport —
KerasModelImport.importKerasSequentialModelAndWeights /
importKerasModelAndWeights; per-layer mappers under
o.d.nn.modelimport.keras.layers.*; weights via Hdf5Archive).

Layout conversion is the core job, exactly as in the reference's KerasLayer
mappers: Keras is channels_last (NHWC, HWIO kernels); this framework is NCHW /
OIHW. Conv kernels are transposed; a Dense that directly follows a Flatten of
a conv feature map gets its input rows permuted from Keras' (H,W,C) flatten
order to our (C,H,W) order (ref: KerasModelUtils weight reshaping).

Supports the Keras-3 legacy ``.h5`` container (``model_config`` JSON attr +
``model_weights`` groups) for both Sequential and Functional models."""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn import NeuralNetConfiguration
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.updaters import Adam

_ACT = {
    "relu": "RELU", "softmax": "SOFTMAX", "sigmoid": "SIGMOID", "tanh": "TANH",
    "linear": "IDENTITY", "elu": "ELU", "selu": "SELU", "softplus": "SOFTPLUS",
    "softsign": "SOFTSIGN", "hard_sigmoid": "HARDSIGMOID", "swish": "SWISH",
    "gelu": "GELU", "leaky_relu": "LEAKYRELU", "exponential": "IDENTITY",
}


def _act(name: Optional[str]) -> str:
    return _ACT.get((name or "linear").lower(), "IDENTITY")


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _one(v):
    """Keras scalar-or-singleton-list -> scalar (1D layer configs)."""
    return v[0] if isinstance(v, (list, tuple)) else v


def _flat3(p):
    """Keras 3D padding/cropping spec -> flat (d0,d1,h0,h1,w0,w1)."""
    if isinstance(p, int):
        return (p,) * 6
    out = []
    for d in p:
        a, b = (d, d) if isinstance(d, int) else (d[0], d[1])
        out += [a, b]
    return tuple(out)


class _WeightStore:
    """Reads Keras-3 legacy h5 weight groups: model_weights/<layer>/**/<name>."""

    def __init__(self, h5file):
        self.f = h5file

    def layer_weights(self, layer_name: str) -> Dict[str, np.ndarray]:
        """Flat {basename: array} for the layer (unique within one layer)."""
        return {k.rsplit("/", 1)[-1]: v
                for k, v in self.layer_weight_paths(layer_name).items()}

    def layer_weight_paths(self, layer_name: str) -> Dict[str, np.ndarray]:
        """Full-path {path: array} — needed for wrappers (Bidirectional) whose
        sub-layers repeat dataset names."""
        mw = self.f["model_weights"]
        if layer_name not in mw:
            return {}
        out = {}

        def walk(group, prefix=""):
            import h5py
            for k in group:
                item = group[k]
                key = f"{prefix}{k}"
                if isinstance(item, h5py.Group):
                    walk(item, key + "/")
                else:
                    out[key.split(":")[0]] = np.asarray(item)

        walk(mw[layer_name])
        return out


class KerasModelImport:
    """(ref: org.deeplearning4j.nn.modelimport.keras.KerasModelImport)."""

    @staticmethod
    def importKerasSequentialModelAndWeights(path: str,
                                             enforceTrainingConfig: bool = False
                                             ) -> MultiLayerNetwork:
        import h5py
        with h5py.File(path, "r") as f:
            cfg = json.loads(f.attrs["model_config"])
            if cfg["class_name"] != "Sequential":
                raise ValueError(
                    f"{path} holds a {cfg['class_name']} — use importKerasModelAndWeights")
            store = _WeightStore(f)
            return _import_sequential(cfg["config"], store)

    @staticmethod
    def importKerasModelAndWeights(path: str,
                                   enforceTrainingConfig: bool = False
                                   ) -> ComputationGraph:
        import h5py
        with h5py.File(path, "r") as f:
            cfg = json.loads(f.attrs["model_config"])
            if cfg["class_name"] == "Sequential":
                raise ValueError(
                    f"{path} holds a Sequential — use importKerasSequentialModelAndWeights")
            store = _WeightStore(f)
            return _import_functional(cfg["config"], store)


# ---------------------------------------------------------------- mapping

def _input_type_from_shape(shape, consumer_cls: Optional[str] = None
                           ) -> Optional[InputType]:
    """Keras batch_shape (None, H, W, C) / (None, T, F) / (None, F) ->
    InputType. A 4-post-batch-dim input is ambiguous (NDHWC 3D conv vs a
    (T, H, W, C) image sequence); ``consumer_cls`` — the first layer that
    consumes this input — disambiguates."""
    dims = [d for d in shape[1:]]
    if len(dims) == 4 and consumer_cls == "ConvLSTM2D":
        t, h, w, c = dims
        return InputType.convolutionalSequence(h, w, c, t or -1)
    if len(dims) == 4:  # NDHWC -> 3D conv, channels-first internally
        d, h, w, c = dims
        return InputType.convolutional3D(d, h, w, c)
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0] or -1)
    if len(dims) == 1:
        return InputType.feedForward(dims[0])
    return None


def _map_layer(cls: str, c: dict) -> Tuple[Optional[L.Layer], bool]:
    """Keras layer config -> (Layer | None, consumes_weights). None = structural
    no-op at our level (Flatten/InputLayer)."""
    act = _act(c.get("activation"))
    same = (c.get("padding", "valid") == "same")
    mode = "Same" if same else "Truncate"
    if cls == "TimeDistributed":
        # ref: KerasTimeDistributed — unwrap; a Dense applied per timestep is
        # exactly our DenseLayer on (B, T, F) (the matmul broadcasts over T)
        inner = c["layer"]
        inner_cls = inner["class_name"]
        if inner_cls not in ("Dense", "Activation", "Dropout"):
            raise ValueError(
                f"TimeDistributed({inner_cls}) not supported by the importer")
        return _map_layer(inner_cls, inner["config"])
    if cls == "RepeatVector":
        return L.RepeatVector(repetitionFactor=c["n"]), False
    if cls == "ConvLSTM2D":
        if c.get("data_format", "channels_last") != "channels_last":
            raise ValueError("ConvLSTM2D: only channels_last exports supported")
        if c.get("padding", "valid") != "same":
            raise ValueError("ConvLSTM2D: only padding='same' supported "
                             "(the layer keeps H, W)")
        if c.get("activation", "tanh") != "tanh":
            raise ValueError("ConvLSTM2D: only activation='tanh' supported")
        if c.get("recurrent_activation", "sigmoid") != "sigmoid":
            raise ValueError("ConvLSTM2D: only recurrent_activation='sigmoid' supported")
        if _pair(c.get("strides", 1)) != (1, 1) or _pair(c.get("dilation_rate", 1)) != (1, 1):
            raise ValueError("ConvLSTM2D: strides/dilation_rate must be 1")
        return L.ConvLSTM2D(nOut=c["filters"], kernelSize=_pair(c["kernel_size"]),
                            returnSequences=c.get("return_sequences", False)), True
    if cls == "Dense":
        return L.DenseLayer(nOut=c["units"], activation=act,
                            hasBias=c.get("use_bias", True)), True
    if cls == "Conv2D":
        return L.ConvolutionLayer(nOut=c["filters"], kernelSize=_pair(c["kernel_size"]),
                                  stride=_pair(c.get("strides", 1)),
                                  dilation=_pair(c.get("dilation_rate", 1)),
                                  convolutionMode=mode, activation=act,
                                  hasBias=c.get("use_bias", True)), True
    if cls == "DepthwiseConv2D":
        return L.DepthwiseConvolution2D(depthMultiplier=c.get("depth_multiplier", 1),
                                        kernelSize=_pair(c["kernel_size"]),
                                        stride=_pair(c.get("strides", 1)),
                                        convolutionMode=mode, activation=act,
                                        hasBias=c.get("use_bias", True)), True
    if cls == "SeparableConv2D":
        return L.SeparableConvolution2D(nOut=c["filters"], kernelSize=_pair(c["kernel_size"]),
                                        stride=_pair(c.get("strides", 1)),
                                        convolutionMode=mode, activation=act,
                                        hasBias=c.get("use_bias", True)), True
    if cls == "Conv2DTranspose":
        return L.Deconvolution2D(nOut=c["filters"], kernelSize=_pair(c["kernel_size"]),
                                 stride=_pair(c.get("strides", 1)),
                                 convolutionMode=mode, activation=act,
                                 hasBias=c.get("use_bias", True)), True
    if cls in ("MaxPooling2D", "AveragePooling2D"):
        return L.SubsamplingLayer(
            poolingType="MAX" if cls.startswith("Max") else "AVG",
            kernelSize=_pair(c.get("pool_size", 2)),
            stride=_pair(c.get("strides") or c.get("pool_size", 2)),
            convolutionMode=mode), False
    if cls in ("GlobalAveragePooling2D", "GlobalMaxPooling2D",
               "GlobalAveragePooling1D", "GlobalMaxPooling1D"):
        return L.GlobalPoolingLayer(
            poolingType="AVG" if "Average" in cls else "MAX"), False
    if cls == "BatchNormalization":
        return L.BatchNormalization(eps=c.get("epsilon", 1e-3),
                                    decay=c.get("momentum", 0.99)), True
    if cls == "Dropout":
        return L.DropoutLayer(dropOut=1.0 - c["rate"]), False
    if cls == "GaussianDropout":
        from deeplearning4j_tpu.nn.conf.dropout import GaussianDropout
        return L.DropoutLayer(dropOut=GaussianDropout(rate=c["rate"])), False
    if cls == "GaussianNoise":
        from deeplearning4j_tpu.nn.conf.dropout import GaussianNoise
        return L.DropoutLayer(dropOut=GaussianNoise(stddev=c["stddev"])), False
    if cls == "AlphaDropout":
        from deeplearning4j_tpu.nn.conf.dropout import AlphaDropout
        return L.DropoutLayer(dropOut=AlphaDropout(p=1.0 - c["rate"])), False
    if cls in ("SpatialDropout1D", "SpatialDropout2D", "SpatialDropout3D"):
        from deeplearning4j_tpu.nn.conf.dropout import SpatialDropout
        return L.DropoutLayer(dropOut=SpatialDropout(p=1.0 - c["rate"])), False
    if cls == "ThresholdedReLU":
        return L.ActivationLayer(activation="THRESHOLDEDRELU",
                                 alpha=c.get("theta", 1.0)), False
    if cls == "Activation":
        return L.ActivationLayer(activation=act), False
    if cls == "ReLU":
        # Keras 3 folded ThresholdedReLU into ReLU(threshold=...); honor the
        # parameterization instead of silently dropping it
        thr = c.get("threshold", 0.0) or 0.0
        ns = c.get("negative_slope", 0.0) or 0.0
        mv = c.get("max_value")
        if thr and not ns and mv is None:
            return L.ActivationLayer(activation="THRESHOLDEDRELU", alpha=thr), False
        if ns and not thr and mv is None:
            return L.ActivationLayer(activation="LEAKYRELU", alpha=ns), False
        if mv == 6.0 and not thr and not ns:
            return L.ActivationLayer(activation="RELU6"), False
        if thr or ns or mv is not None:
            raise ValueError(
                f"ReLU(threshold={thr}, negative_slope={ns}, max_value={mv}) "
                "combination not supported by the importer")
        return L.ActivationLayer(activation="RELU"), False
    if cls == "LeakyReLU":
        # Keras default negative_slope is 0.3 (keras-2 key: "alpha")
        return L.ActivationLayer(activation="LEAKYRELU",
                                 alpha=c.get("negative_slope",
                                             c.get("alpha", 0.3))), False
    if cls == "Softmax":
        return L.ActivationLayer(activation="SOFTMAX"), False
    if cls == "ZeroPadding2D":
        p = c.get("padding", 1)
        if isinstance(p, (list, tuple)) and isinstance(p[0], (list, tuple)):
            pad = (p[0][0], p[0][1], p[1][0], p[1][1])
        else:
            p = _pair(p)
            pad = (p[0], p[0], p[1], p[1])
        return L.ZeroPaddingLayer(padding=pad), False
    if cls == "Cropping2D":
        p = c.get("cropping", 1)
        if isinstance(p, (list, tuple)) and isinstance(p[0], (list, tuple)):
            crop = (p[0][0], p[0][1], p[1][0], p[1][1])
        else:
            p = _pair(p)
            crop = (p[0], p[0], p[1], p[1])
        return L.Cropping2D(cropping=crop), False
    if cls == "UpSampling2D":
        return L.Upsampling2D(size=_pair(c.get("size", 2))), False
    if cls == "Conv1D":
        if c.get("padding") == "causal":
            raise ValueError("Conv1D(padding='causal') import is not supported")
        return L.Convolution1DLayer(
            nOut=c["filters"], kernelSize=_one(c["kernel_size"]),
            stride=_one(c.get("strides", 1)),
            dilation=_one(c.get("dilation_rate", 1)),
            convolutionMode=mode, activation=act,
            hasBias=c.get("use_bias", True)), True
    if cls == "Conv3D":
        dil = c.get("dilation_rate", (1, 1, 1))
        return L.Convolution3D(nOut=c["filters"],
                               kernelSize=tuple(c["kernel_size"]),
                               stride=tuple(c.get("strides", (1, 1, 1))),
                               dilation=tuple(dil) if isinstance(dil, (list, tuple))
                               else (dil,) * 3,
                               convolutionMode=mode, activation=act,
                               hasBias=c.get("use_bias", True)), True
    if cls in ("MaxPooling1D", "AveragePooling1D"):
        if same:
            raise ValueError(f"{cls}(padding='same') import is not supported")
        ps = _one(c.get("pool_size", 2))
        return L.Subsampling1DLayer(
            poolingType="MAX" if cls.startswith("Max") else "AVG",
            kernelSize=ps, stride=_one(c.get("strides") or ps)), False
    if cls in ("MaxPooling3D", "AveragePooling3D"):
        ps = c.get("pool_size", (2, 2, 2))
        return L.Subsampling3DLayer(
            poolingType="MAX" if cls.startswith("Max") else "AVG",
            kernelSize=tuple(ps), stride=tuple(c.get("strides") or ps),
            convolutionMode=mode), False
    if cls == "UpSampling1D":
        return L.Upsampling1D(size=c.get("size", 2)), False
    if cls == "UpSampling3D":
        return L.Upsampling3D(size=tuple(c.get("size", (2, 2, 2)))), False
    if cls == "ZeroPadding1D":
        p = _pair(c.get("padding", 1))
        return L.ZeroPadding1DLayer(padding=(p[0], p[1])), False
    if cls == "Cropping1D":
        p = _pair(c.get("cropping", 1))
        return L.Cropping1D(cropping=(p[0], p[1])), False
    if cls == "ZeroPadding3D":
        p = c.get("padding", 1)
        flat = _flat3(p)
        return L.ZeroPadding3DLayer(padding=flat), False
    if cls == "Cropping3D":
        flat = _flat3(c.get("cropping", 1))
        return L.Cropping3D(cropping=flat), False
    if cls == "ELU":
        return L.ActivationLayer(activation="ELU",
                                 alpha=c.get("alpha", 1.0)), False
    if cls == "PReLU":
        # shared_axes are keras channels-LAST per-example axes; ours are
        # channels-first. 2D conv: (H,W,C) 1,2,3 -> (C,H,W) 2,3,1.
        # 3D conv: (D,H,W,C) 1,2,3,4 -> (C,D,H,W) 2,3,4,1. The maps agree
        # on axes {1,2}; axis 3 is ambiguous without the input rank, so it
        # is only accepted when axis 4 disambiguates to the 3D case.
        axes = tuple(c.get("shared_axes") or ())
        if 4 in axes:
            amap = {1: 2, 2: 3, 3: 4, 4: 1}
        elif 3 in axes:
            raise ValueError(
                "PReLU(shared_axes containing 3) is ambiguous between 2D "
                "(channel axis) and 3D (width axis) inputs; re-export with "
                "explicit per-element alpha or include axis 4")
        else:
            amap = {1: 2, 2: 3}
        return L.PReLULayer(sharedAxes=tuple(amap[a] for a in axes)), True
    if cls == "Masking":
        import warnings
        warnings.warn(
            "Keras Masking imports as value-zeroing only: a downstream RNN "
            "still steps through masked positions (state at T-1, not at the "
            "last unmasked step). Pass explicit masks / use padded-value "
            "zeroing semantics, or slice sequences before import.")
        return L.MaskZeroLayer(maskValue=c.get("mask_value", 0.0)), False
    if cls == "Embedding":
        return L.EmbeddingSequenceLayer(nIn=c["input_dim"], nOut=c["output_dim"]), True
    if cls in ("LSTM", "GRU", "SimpleRNN"):
        if cls == "LSTM":
            cell = L.LSTM(nOut=c["units"], activation=_act(c.get("activation", "tanh")))
        elif cls == "GRU":
            if not c.get("reset_after", True):
                raise ValueError("GRU(reset_after=False) import is not supported")
            cell = L.GRU(nOut=c["units"])
        else:
            cell = L.SimpleRnn(nOut=c["units"],
                               activation=_act(c.get("activation", "tanh")))
        if not c.get("return_sequences", False):
            # Keras LSTM(units) returns the LAST step only (ref: KerasLSTM ->
            # LastTimeStep wrapper)
            return L.LastTimeStep(underlying=cell), True
        return cell, True
    if cls == "Bidirectional":
        inner_cls = c["layer"]["class_name"]
        inner, _ = _map_layer(inner_cls, c["layer"]["config"])
        if isinstance(inner, L.LastTimeStep):
            # Keras Bidirectional(return_sequences=False) concatenates the fwd
            # state at T-1 with the bwd state at 0 — no single-wrapper parity
            raise ValueError("Bidirectional(return_sequences=False) import is "
                             "not supported; re-export with return_sequences=True")
        return L.Bidirectional(fwd=inner, mode=c.get("merge_mode", "concat").upper()), True
    if cls == "Reshape":
        return L.ReshapeLayer(targetShape=tuple(c["target_shape"])), False
    if cls == "Permute":
        return L.PermuteLayer(permuteDims=tuple(c["dims"])), False
    if cls in ("Flatten", "InputLayer"):
        return None, False
    raise ValueError(f"Keras layer type {cls} is not supported by the importer "
                     f"(ref: KerasLayer registry)")


def _convert_weights(layer: L.Layer, kw: Dict[str, np.ndarray],
                     flatten_src: Optional[InputType],
                     paths: Optional[Dict[str, np.ndarray]] = None) -> dict:
    """Keras weight dict -> our param dict, with layout conversion."""
    def t_conv(k):  # HWIO -> OIHW
        return np.transpose(k, (3, 2, 0, 1))

    if isinstance(layer, L.LastTimeStep):  # params are the wrapped cell's
        return _convert_weights(layer.underlying, kw, flatten_src, paths)
    if isinstance(layer, L.Bidirectional):
        fwd = {k.rsplit("/", 1)[-1]: v for k, v in (paths or {}).items()
               if "backward" not in k}
        bwd = {k.rsplit("/", 1)[-1]: v for k, v in (paths or {}).items()
               if "backward" in k}
        return {"fwd": _convert_weights(layer.fwd, fwd, None),
                "bwd": _convert_weights(layer.fwd, bwd, None)}

    if isinstance(layer, L.ConvLSTM2D):
        p = {"W": np.transpose(kw["kernel"], (3, 2, 0, 1)),
             "RW": np.transpose(kw["recurrent_kernel"], (3, 2, 0, 1))}
        # apply() reads params['b'] unconditionally, so use_bias=False h5
        # files get an explicit zero bias (gate order i,f,c,o; 4*filters).
        p["b"] = kw.get("bias", np.zeros(kw["kernel"].shape[-1],
                                         dtype=kw["kernel"].dtype))
        return p
    if isinstance(layer, L.SeparableConvolution2D):
        p = {"dW": np.transpose(kw["depthwise_kernel"], (2, 3, 0, 1)),
             "pW": np.transpose(kw["pointwise_kernel"], (3, 2, 0, 1))}
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    if isinstance(layer, L.DepthwiseConvolution2D):
        k = kw["kernel"]  # (kh, kw, C, mult) -> (C*mult, 1, kh, kw)
        kh, kwid, C, mult = k.shape
        p = {"W": k.transpose(2, 3, 0, 1).reshape(C * mult, 1, kh, kwid)}
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    if isinstance(layer, L.Deconvolution2D):
        # keras Conv2DTranspose kernel: (kh, kw, out, in)
        p = {"W": np.transpose(kw["kernel"], (2, 3, 0, 1))}
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    if isinstance(layer, L.Convolution1DLayer):
        p = {"W": np.transpose(kw["kernel"], (2, 1, 0))}  # (K,I,O) -> (O,I,K)
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    if isinstance(layer, L.Convolution3D):
        # (kd,kh,kw,I,O) -> (O,I,kd,kh,kw)
        p = {"W": np.transpose(kw["kernel"], (4, 3, 0, 1, 2))}
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    if isinstance(layer, L.ConvolutionLayer):
        p = {"W": t_conv(kw["kernel"])}
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    if isinstance(layer, L.PReLULayer):
        a = kw["alpha"]
        if a.ndim == 3:    # keras (H,W,C) -> ours (C,H,W)
            a = np.transpose(a, (2, 0, 1))
        elif a.ndim == 4:  # keras (D,H,W,C) -> ours (C,D,H,W)
            a = np.transpose(a, (3, 0, 1, 2))
        return {"alpha": a}
    if isinstance(layer, L.BatchNormalization):
        return {"gamma": kw.get("gamma", np.ones_like(kw["moving_mean"])),
                "beta": kw.get("beta", np.zeros_like(kw["moving_mean"])),
                "_mean": kw["moving_mean"], "_var": kw["moving_variance"]}
    if isinstance(layer, L.GRU):
        W, U = kw["kernel"], kw["recurrent_kernel"]
        b = kw.get("bias")
        H = layer.nOut
        perm = _gru_perm(H)  # keras [z,r,h] -> ours [r,z,n]
        p = {"W": W[:, perm], "RW": U[:, perm]}
        if b is not None:
            b = np.asarray(b)
            if b.ndim == 2:  # reset_after: (2, 3H) = [input bias, recurrent bias]
                p["bi"], p["bh"] = b[0][perm], b[1][perm]
            else:
                p["bi"], p["bh"] = b[perm], np.zeros_like(b[perm])
        else:
            p["bi"] = np.zeros((3 * H,), W.dtype)
            p["bh"] = np.zeros((3 * H,), W.dtype)
        return p
    if isinstance(layer, L.LSTM):  # keras gate order [i,f,c,o] == ours [i,f,g,o]
        p = {"W": kw["kernel"], "RW": kw["recurrent_kernel"]}
        p["b"] = kw.get("bias", np.zeros((4 * layer.nOut,), kw["kernel"].dtype))
        return p
    if isinstance(layer, L.SimpleRnn):
        return {"W": kw["kernel"], "RW": kw["recurrent_kernel"],
                "b": kw.get("bias", np.zeros((layer.nOut,), kw["kernel"].dtype))}
    if isinstance(layer, (L.EmbeddingSequenceLayer, L.EmbeddingLayer)):
        return {"W": kw["embeddings"]}
    if isinstance(layer, (L.DenseLayer, L.BaseOutputLayer)):
        W = kw["kernel"]
        if flatten_src is not None and flatten_src.kind == "cnn":
            # permute rows: keras flatten order (H,W,C) -> ours (C,H,W)
            H, Wd, C = flatten_src.height, flatten_src.width, flatten_src.channels
            idx = np.arange(H * Wd * C).reshape(H, Wd, C).transpose(2, 0, 1).ravel()
            W = W[idx]
        elif flatten_src is not None and flatten_src.kind == "cnn3d":
            # keras flatten order (D,H,W,C) -> ours (C,D,H,W)
            D, H, Wd, C = (flatten_src.depth, flatten_src.height,
                           flatten_src.width, flatten_src.channels)
            idx = np.arange(D * H * Wd * C).reshape(D, H, Wd, C) \
                .transpose(3, 0, 1, 2).ravel()
            W = W[idx]
        p = {"W": W}
        if "bias" in kw:
            p["b"] = kw["bias"]
        return p
    raise ValueError(f"no weight mapper for {type(layer).__name__}")


def _gru_perm(H: int) -> np.ndarray:
    # columns [z | r | h] -> [r | z | n]
    return np.concatenate([np.arange(H, 2 * H), np.arange(0, H),
                           np.arange(2 * H, 3 * H)])


def _set_weights(net_params: dict, layer: L.Layer, state: dict, converted: dict):
    import jax.numpy as jnp
    mean = converted.pop("_mean", None)
    var = converted.pop("_var", None)
    for k, v in converted.items():
        net_params[k] = ({kk: jnp.asarray(vv) for kk, vv in v.items()}
                         if isinstance(v, dict) else jnp.asarray(v))
    if mean is not None:
        state["mean"] = jnp.asarray(mean)
        state["var"] = jnp.asarray(var)


def _import_sequential(cfg: dict, store: _WeightStore) -> MultiLayerNetwork:
    layers_cfg = cfg["layers"]
    built: List[Tuple[str, L.Layer, bool, Optional[InputType]]] = []
    input_type: Optional[InputType] = None
    cur_type: Optional[InputType] = None
    flatten_pending: Optional[InputType] = None

    b = NeuralNetConfiguration.Builder().updater(Adam(1e-3)).list()
    # a 4-post-batch-dim input is NDHWC (3D conv) UNLESS the first real layer
    # is ConvLSTM2D, where it is a (T, H, W, C) image sequence
    first_real = next((lc["class_name"] for lc in layers_cfg
                       if lc["class_name"] != "InputLayer"), None)
    for lc in layers_cfg:
        cls, c = lc["class_name"], lc["config"]
        if cls == "InputLayer":
            input_type = _input_type_from_shape(
                c.get("batch_shape") or c["batch_input_shape"],
                consumer_cls=first_real)
            cur_type = input_type
            continue
        layer, has_w = _map_layer(cls, c)
        if layer is None:  # Flatten: remember the conv shape for Dense row perm
            if cur_type is not None and cur_type.kind in ("cnn", "cnn3d"):
                flatten_pending = cur_type
                cur_type = InputType.feedForward(cur_type.flat_size())
            elif cur_type is not None and cur_type.kind in ("rnn", "cnnseq"):
                raise ValueError(
                    "Flatten over a sequence feature map is not "
                    "supported by the importer — use GlobalAveragePooling1D/"
                    "GlobalMaxPooling1D (imported as GlobalPoolingLayer) or "
                    "an RNN with return_sequences=False instead")
            continue
        layer.name = c.get("name", cls.lower())
        b = b.layer(layer)
        # the flatten row-permutation applies to the first WEIGHTED consumer;
        # weightless layers between Flatten and Dense (Dropout/Activation) are
        # elementwise and preserve feature order, so the marker passes through
        fl_for_layer = flatten_pending if has_w else None
        built.append((layer.name, layer, has_w, fl_for_layer))
        if has_w:
            flatten_pending = None
        if cur_type is not None:
            layer.set_n_in(cur_type)
            cur_type = layer.output_type(cur_type)
    if input_type is not None:
        b = b.setInputType(input_type)
    net = MultiLayerNetwork(b.build()).init()
    for i, (name, layer, has_w, fl_src) in enumerate(built):
        if not has_w:
            continue
        kw = store.layer_weights(name)
        if not kw:
            continue
        converted = _convert_weights(layer, kw, fl_src,
                                     paths=store.layer_weight_paths(name))
        net._params[i] = dict(net._params[i])
        _set_weights(net._params[i], layer, net._state[i], converted)
    net._opt_state = net._tx.init(net._params)
    return net


def _import_functional(cfg: dict, store: _WeightStore) -> ComputationGraph:
    layers_cfg = cfg["layers"]
    g = NeuralNetConfiguration.Builder().updater(Adam(1e-3)).graphBuilder()
    input_types: List[InputType] = []
    name_alias: Dict[str, str] = {}   # keras node name -> our graph node name
    weighted: List[Tuple[str, L.Layer, Optional[InputType]]] = []
    type_at: Dict[str, Optional[InputType]] = {}
    flatten_src: Dict[str, Optional[InputType]] = {}

    def inbound(lc) -> List[str]:
        names = []
        for node in lc.get("inbound_nodes", []):
            if isinstance(node, dict):  # keras 3 format
                for arg in node.get("args", []):
                    names.extend(_hist_names(arg))
            else:  # keras 2: [[name, idx, tensor_idx, {}], ...]
                for item in node:
                    names.append(item[0])
        return [name_alias.get(n, n) for n in names]

    def _hist_names(arg):
        out = []
        if isinstance(arg, dict) and arg.get("class_name") == "__keras_tensor__":
            out.append(arg["config"]["keras_history"][0])
        elif isinstance(arg, (list, tuple)):
            for a in arg:
                out.extend(_hist_names(a))
        return out

    for lc in layers_cfg:
        cls, c = lc["class_name"], lc["config"]
        name = c.get("name", cls.lower())
        ins = inbound(lc)
        if cls == "InputLayer":
            g.addInputs(name)
            consumer = next(
                (lc2["class_name"] for lc2 in layers_cfg
                 if lc2["class_name"] != "InputLayer"
                 and name in inbound(lc2)), None)
            t = _input_type_from_shape(
                c.get("batch_shape") or c["batch_input_shape"],
                consumer_cls=consumer)
            input_types.append(t)
            type_at[name] = t
            continue
        if cls == "Add":
            g.addVertex(name, ElementWiseVertex(op="Add"), *ins)
            type_at[name] = type_at.get(ins[0])
            continue
        if cls in ("Concatenate", "Merge"):
            g.addVertex(name, MergeVertex(), *ins)
            ts = [type_at.get(i) for i in ins]
            type_at[name] = MergeVertex().output_type(ts) if all(ts) else None
            continue
        if cls in ("Multiply", "Average", "Maximum", "Subtract"):
            op = {"Multiply": "Product", "Average": "Average",
                  "Maximum": "Max", "Subtract": "Subtract"}[cls]
            g.addVertex(name, ElementWiseVertex(op=op), *ins)
            type_at[name] = type_at.get(ins[0])
            continue
        layer, has_w = _map_layer(cls, c)
        if layer is None:  # Flatten
            src = ins[0]
            t = type_at.get(src)
            name_alias[name] = src
            if t is not None and t.kind in ("cnn", "cnn3d"):
                flatten_src[src] = t
                type_at[src] = t  # unchanged; Dense consumer handles perm
            elif t is not None and t.kind in ("rnn", "cnnseq"):
                raise ValueError(
                    "Flatten over a sequence feature map is not "
                    "supported by the importer — use GlobalAveragePooling1D/"
                    "GlobalMaxPooling1D (imported as GlobalPoolingLayer) or "
                    "a recurrent layer with return_sequences=False instead")
            continue
        layer.name = name
        src = ins[0] if ins else None
        g.addLayer(name, layer, *(ins if ins else []))
        t = type_at.get(src) if src else None
        fl = None
        if src in flatten_src:
            if has_w:  # first weighted consumer takes the row permutation
                fl = flatten_src[src]
                layer.set_n_in(InputType.feedForward(fl.flat_size()))
                type_at[name] = layer.output_type(
                    InputType.feedForward(fl.flat_size()))
            else:      # weightless elementwise layer: marker flows through
                flatten_src[name] = flatten_src[src]
                type_at[name] = InputType.feedForward(flatten_src[src].flat_size())
        elif t is not None:
            layer.set_n_in(t)
            type_at[name] = layer.output_type(t)
        else:
            type_at[name] = None
        if has_w:
            weighted.append((name, layer, fl))

    ol = cfg.get("output_layers", [])
    if ol and isinstance(ol[0], str):  # single output: ["name", idx, tensor_idx]
        ol = [ol]
    outputs = [name_alias.get(n[0], n[0]) for n in ol]
    g.setOutputs(*outputs)
    g.setInputTypes(*[t for t in input_types if t is not None])
    net = ComputationGraph(g.build()).init()
    for name, layer, fl in weighted:
        kw = store.layer_weights(name)
        if not kw:
            continue
        converted = _convert_weights(layer, kw, fl,
                                     paths=store.layer_weight_paths(name))
        net._params[name] = dict(net._params[name])
        _set_weights(net._params[name], layer, net._state[name], converted)
    net._opt_state = net._tx.init(net._params)
    return net
