"""ONNX ModelProto -> SameDiff import (ref: nd4j/samediff-import-onnx —
OnnxFrameworkImporter.runImport + per-op OnnxMappingProcess rules).

Same declarative architecture as the TF importer (one rule per op_type,
emitting shared-registry ops onto a SameDiff graph), with two ONNX-specific
simplifications:

- ONNX is **NCHW-native** for conv/pool, matching this framework's cnn ops —
  no layout transposes are needed (the TF path wraps every spatial op in
  NHWC<->NCHW permutes).
- Attribute-carrying inputs (Reshape shapes, Slice starts/ends, Clip bounds)
  are initializers or Constant nodes in practice; the importer resolves them
  eagerly to python values, as the reference's mapping rules read initializer
  protos.

The wire format is parsed with protoc-generated bindings from a hand-written
subset of the public ONNX schema (onnx_minimal.proto) — the pip ``onnx``
package is not required.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable
from deeplearning4j_tpu.modelimport.onnx import onnx_minimal_pb2 as onnx_pb

# TensorProto.DataType -> numpy
_NP_DT = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}


def tensor_to_numpy(t) -> np.ndarray:
    """Decode a TensorProto (raw_data or typed repeated fields)."""
    dt = _NP_DT.get(t.data_type)
    if dt is None:
        raise ValueError(f"unsupported ONNX tensor dtype {t.data_type}")
    dims = tuple(t.dims)
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        arr = np.asarray(list(t.float_data), dtype=dt)
    elif t.int64_data:
        arr = np.asarray(list(t.int64_data), dtype=dt)
    elif t.int32_data:
        arr = np.asarray(list(t.int32_data), dtype=dt)
    elif t.double_data:
        arr = np.asarray(list(t.double_data), dtype=dt)
    else:
        arr = np.zeros(int(np.prod(dims)) if dims else 1, dtype=dt)
    return arr.reshape(dims)


def numpy_to_tensor(name: str, arr: np.ndarray):
    """Encode (used by tests / model writers)."""
    rev = {np.dtype(v): k for k, v in _NP_DT.items()}
    t = onnx_pb.TensorProto()
    t.name = name
    t.data_type = rev[arr.dtype]
    t.dims.extend(arr.shape)
    t.raw_data = arr.tobytes()
    return t


class OnnxFrameworkImporter:
    """(ref: org.nd4j.samediff.frameworkimport.onnx.importer.
    OnnxFrameworkImporter)."""

    @staticmethod
    def runImport(model_or_path) -> SameDiff:
        model = _load_model(model_or_path)
        return _OnnxGraphImporter(model).run()


def _load_model(src):
    if isinstance(src, onnx_pb.ModelProto):
        return src
    m = onnx_pb.ModelProto()
    if isinstance(src, bytes):
        m.ParseFromString(src)
        return m
    with open(src, "rb") as f:
        m.ParseFromString(f.read())
    return m


def _attrs(node) -> Dict[str, Any]:
    out = {}
    for a in node.attribute:
        T = onnx_pb.AttributeProto
        if a.type == T.FLOAT:
            out[a.name] = a.f
        elif a.type == T.INT:
            out[a.name] = int(a.i)
        elif a.type == T.STRING:
            out[a.name] = a.s.decode("utf-8")
        elif a.type == T.TENSOR:
            out[a.name] = tensor_to_numpy(a.t)
        elif a.type == T.FLOATS:
            out[a.name] = list(a.floats)
        elif a.type == T.INTS:
            out[a.name] = [int(i) for i in a.ints]
        elif a.type == T.STRINGS:
            out[a.name] = [s.decode("utf-8") for s in a.strings]
        else:
            out[a.name] = a
    return out


def _onnx_pads(pads: List[int], spatial: int):
    """ONNX pads = [b1..bn, e1..en] -> ((b1,e1), ...)."""
    if not pads:
        return [(0, 0)] * spatial
    return list(zip(pads[:spatial], pads[spatial:]))


class _OnnxGraphImporter:
    def __init__(self, model):
        self.model = model
        self.g = model.graph
        self.sd = SameDiff.create()
        self.vars: Dict[str, SDVariable] = {}
        self.consts: Dict[str, np.ndarray] = {}

    # ------------------------------------------------------------- helpers
    def _in(self, node, i) -> SDVariable:
        return self.vars[node.input[i]]

    def _opt(self, node, i):
        if i < len(node.input) and node.input[i]:
            return self.vars[node.input[i]]
        return None

    def _const(self, node, i) -> np.ndarray:
        name = node.input[i]
        if name not in self.consts:
            raise ValueError(
                f"input {i} of {node.name or node.op_type} must be an "
                f"initializer/Constant (dynamic attribute inputs unsupported)")
        return self.consts[name]

    def _emit(self, ns, opname, inputs, name, **kw) -> SDVariable:
        return self.sd._op(ns, opname, inputs, name=name, **kw)

    def _register(self, node, outs):
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        for ref, o in zip(node.output, outs):
            if ref:
                self.vars[ref] = o

    # ----------------------------------------------------------------- run
    def run(self) -> SameDiff:
        import jax.numpy as jnp
        init_names = set()
        for t in self.g.initializer:
            arr = tensor_to_numpy(t)
            self.consts[t.name] = arr
            self.vars[t.name] = self.sd.constant(t.name, arr)
            init_names.add(t.name)
        for vi in self.g.input:
            if vi.name in init_names:
                continue  # pre-IR4 models list initializers as inputs too
            shape = None
            tt = vi.type.tensor_type
            if tt.shape.dim:
                shape = tuple(d.dim_value if d.dim_value > 0 else None
                              for d in tt.shape.dim)
            dt = jnp.dtype(_NP_DT.get(tt.elem_type, np.float32))
            self.vars[vi.name] = self.sd.placeHolder(vi.name, shape=shape, dtype=dt)
        for node in self.g.node:
            self._map_node(node)
        # expose graph outputs under their ONNX names via identity when a
        # node output name differs from the var name (they coincide here,
        # since vars are registered by tensor name)
        return self.sd

    def outputs(self) -> List[str]:
        return [o.name for o in self.g.output]

    def _map_node(self, node):
        op = node.op_type
        rule = _RULES.get(op)
        if rule is None:
            raise ValueError(f"ONNX op '{op}' (node {node.name}) has no "
                             f"mapping rule (ref: OpMappingRegistry lookup)")
        out = rule(self, node)
        if out is not None:
            self._register(node, out)
            # eager const folding for attribute-carrying chains
            # (Shape->Gather->Unsqueeze->Concat feeding a Reshape)
            if all((not i) or i in self.consts for i in node.input) and node.input:
                try:
                    outs = out if isinstance(out, (tuple, list)) else [out]
                    for ref, o in zip(node.output, outs):
                        self.consts[ref] = np.asarray(o.eval({}).toNumpy())
                except Exception:
                    pass


_RULES: Dict[str, Any] = {}


def rule(*op_types):
    def deco(fn):
        for t in op_types:
            _RULES[t] = fn
        return fn
    return deco


# ------------------------------------------------------------- elementwise

for _t, _ns, _o in [
    ("Add", "math", "add"), ("Sub", "math", "sub"), ("Mul", "math", "mul"),
    ("Div", "math", "div"), ("Pow", "math", "pow"),
    ("Equal", "math", "eq"), ("Greater", "math", "gt"), ("Less", "math", "lt"),
    ("GreaterOrEqual", "math", "gte"), ("LessOrEqual", "math", "lte"),
    ("And", "math", "logicalAnd"), ("Or", "math", "logicalOr"),
    ("Xor", "math", "logicalXor"), ("Min", "math", "min"), ("Max", "math", "max"),
]:
    _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
        ns, o, [g._in(n, 0), g._in(n, 1)], n.output[0]))(_ns, _o)

for _t, _ns, _o in [
    ("Abs", "math", "abs"), ("Neg", "math", "neg"), ("Exp", "math", "exp"),
    ("Log", "math", "log"), ("Sqrt", "math", "sqrt"),
    ("Reciprocal", "math", "reciprocal"), ("Floor", "math", "floor"),
    ("Ceil", "math", "ceil"), ("Round", "math", "round"), ("Sign", "math", "sign"),
    ("Sin", "math", "sin"), ("Cos", "math", "cos"), ("Tan", "math", "tan"),
    ("Asin", "math", "asin"), ("Acos", "math", "acos"), ("Atan", "math", "atan"),
    ("Sinh", "math", "sinh"), ("Cosh", "math", "cosh"), ("Tanh", "math", "tanh"),
    ("Erf", "math", "erf"), ("Not", "math", "logicalNot"),
    ("Relu", "nn", "relu"), ("Sigmoid", "nn", "sigmoid"),
    ("Softplus", "nn", "softplus"), ("Softsign", "nn", "softsign"),
    ("Identity", "math", "identity"),
]:
    _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
        ns, o, [g._in(n, 0)], n.output[0]))(_ns, _o)


@rule("Constant")
def _constant(g, n):
    a = _attrs(n)
    if "value" in a:
        val = a["value"]
    elif "value_float" in a:
        val = np.float32(a["value_float"])
    elif "value_int" in a:
        val = np.int64(a["value_int"])
    elif "value_floats" in a:
        val = np.asarray(a["value_floats"], np.float32)
    elif "value_ints" in a:
        val = np.asarray(a["value_ints"], np.int64)
    else:
        raise ValueError("Constant node without value attribute")
    g.consts[n.output[0]] = np.asarray(val)
    return g.sd.constant(n.output[0], np.asarray(val))


@rule("LeakyRelu")
def _leaky(g, n):
    alpha = _attrs(n).get("alpha", 0.01)
    return g._emit("nn", "leakyRelu", [g._in(n, 0)], n.output[0], alpha=alpha)


@rule("Elu")
def _elu(g, n):
    alpha = _attrs(n).get("alpha", 1.0)
    return g._emit("nn", "elu", [g._in(n, 0)], n.output[0], alpha=alpha)


@rule("Selu")
def _selu(g, n):
    return g._emit("nn", "selu", [g._in(n, 0)], n.output[0])


@rule("HardSigmoid")
def _hard_sigmoid(g, n):
    a = _attrs(n)
    alpha, beta = a.get("alpha", 0.2), a.get("beta", 0.5)
    x = g._in(n, 0)
    ax = g._emit("math", "mul", [x, alpha], f"{n.output[0]}/ax")
    axb = g._emit("math", "add", [ax, beta], f"{n.output[0]}/axb")
    return g._emit("math", "clipByValue", [axb], n.output[0], lo=0.0, hi=1.0)


@rule("PRelu")
def _prelu(g, n):
    return g._emit("nn", "prelu", [g._in(n, 0), g._in(n, 1)], n.output[0])


@rule("Softmax")
def _softmax(g, n):
    axis = _attrs(n).get("axis", -1)
    return g._emit("nn", "softmax", [g._in(n, 0)], n.output[0], axis=axis)


@rule("LogSoftmax")
def _log_softmax(g, n):
    axis = _attrs(n).get("axis", -1)
    return g._emit("nn", "logSoftmax", [g._in(n, 0)], n.output[0], axis=axis)


@rule("Clip")
def _clip(g, n):
    a = _attrs(n)
    if "min" in a or "max" in a:  # opset < 11
        lo, hi = a.get("min", -np.inf), a.get("max", np.inf)
    else:
        lo = float(g._const(n, 1)) if len(n.input) > 1 and n.input[1] else -np.inf
        hi = float(g._const(n, 2)) if len(n.input) > 2 and n.input[2] else np.inf
    return g._emit("math", "clipByValue", [g._in(n, 0)], n.output[0], lo=lo, hi=hi)


@rule("Where")
def _where(g, n):
    return g._emit("shape", "where", [g._in(n, 0), g._in(n, 1), g._in(n, 2)],
                   n.output[0])


@rule("Cast")
def _cast(g, n):
    to = _NP_DT[_attrs(n)["to"]]
    return g._emit("shape", "castTo", [g._in(n, 0)], n.output[0],
                   dtype=np.dtype(to).name)


@rule("Dropout")
def _dropout(g, n):
    # inference import: dropout is identity (ref: the reference imports
    # Dropout as noop outside training)
    return g._emit("math", "identity", [g._in(n, 0)], n.output[0])


# ------------------------------------------------------------------ matmul


@rule("MatMul")
def _matmul(g, n):
    return g._emit("linalg", "matmul", [g._in(n, 0), g._in(n, 1)], n.output[0])


@rule("Gemm")
def _gemm(g, n):
    a = _attrs(n)
    alpha, beta = a.get("alpha", 1.0), a.get("beta", 1.0)
    transA, transB = a.get("transA", 0), a.get("transB", 0)
    A, B = g._in(n, 0), g._in(n, 1)
    if transA:
        A = g._emit("shape", "transpose", [A], f"{n.output[0]}/At")
    if transB:
        B = g._emit("shape", "transpose", [B], f"{n.output[0]}/Bt")
    out = g._emit("linalg", "matmul", [A, B], f"{n.output[0]}/mm")
    if alpha != 1.0:
        out = g._emit("math", "mul", [out, alpha], f"{n.output[0]}/alpha")
    if len(n.input) > 2 and n.input[2]:
        C = g._in(n, 2)
        if beta != 1.0:
            C = g._emit("math", "mul", [C, beta], f"{n.output[0]}/beta")
        out = g._emit("math", "add", [out, C], n.output[0])
    else:
        out = g._emit("math", "identity", [out], n.output[0])
    return out


# ------------------------------------------------------------ conv / pool


@rule("Conv")
def _conv(g, n):
    a = _attrs(n)
    w = g._in(n, 1)
    b = g._opt(n, 2)
    kshape = a.get("kernel_shape") or list(g._const(n, 1).shape[2:])
    spatial = len(kshape)
    strides = tuple(a.get("strides", [1] * spatial))
    dilations = tuple(a.get("dilations", [1] * spatial))
    groups = a.get("group", 1)
    auto_pad = a.get("auto_pad", "NOTSET")
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    elif auto_pad == "VALID" or not a.get("pads"):
        padding = "VALID" if not a.get("pads") else _onnx_pads(a["pads"], spatial)
    else:
        padding = _onnx_pads(a["pads"], spatial)
    inputs = [g._in(n, 0), w] + ([b] if b is not None else [])
    if spatial == 2:
        return g._emit("cnn", "conv2d", inputs, n.output[0], strides=strides,
                       padding=padding, dilation=dilations, groups=groups)
    if spatial == 1:
        if groups != 1:
            raise ValueError("grouped Conv1d import unsupported")
        return g._emit("cnn", "conv1d", inputs, n.output[0],
                       strides=strides[0], padding=padding)
    if groups != 1:
        raise ValueError("grouped Conv3d import unsupported")
    return g._emit("cnn", "conv3d", inputs, n.output[0], strides=strides,
                   padding=padding)


def _pool_rule(kind):
    def fn(g, n):
        a = _attrs(n)
        kshape = a["kernel_shape"]
        spatial = len(kshape)
        strides = tuple(a.get("strides", [1] * spatial))
        auto_pad = a.get("auto_pad", "NOTSET")
        if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
            padding = "SAME"
        elif a.get("pads"):
            padding = _onnx_pads(a["pads"], spatial)
        else:
            padding = "VALID"
        opname = {1: f"{kind}Pool1d", 2: f"{kind}Pool2d", 3: f"{kind}Pool3d"}[spatial]
        kernel = kshape[0] if spatial == 1 else tuple(kshape)
        stride = strides[0] if spatial == 1 else strides
        return g._emit("cnn", opname, [g._in(n, 0)], n.output[0],
                       kernel=kernel, strides=stride, padding=padding)
    return fn


_RULES["MaxPool"] = _pool_rule("max")
_RULES["AveragePool"] = _pool_rule("avg")


@rule("GlobalAveragePool")
def _gap(g, n):
    return g._emit("cnn", "globalAvgPool", [g._in(n, 0)], n.output[0],
                   keepdims=True)


@rule("GlobalMaxPool")
def _gmp(g, n):
    return g._emit("cnn", "globalMaxPool", [g._in(n, 0)], n.output[0],
                   keepdims=True)


@rule("BatchNormalization")
def _batchnorm(g, n):
    eps = _attrs(n).get("epsilon", 1e-5)
    x, scale, bias, mean, var = (g._in(n, i) for i in range(5))
    return g._emit("nn", "batchNorm", [x, mean, var, scale, bias], n.output[0],
                   eps=eps, axis=1)


@rule("InstanceNormalization")
def _instancenorm(g, n):
    eps = _attrs(n).get("epsilon", 1e-5)
    x, scale, bias = g._in(n, 0), g._in(n, 1), g._in(n, 2)
    # normalize over spatial dims per-sample per-channel
    return g._emit("nn", "instanceNorm", [x, scale, bias], n.output[0], eps=eps)


@rule("LRN")
def _lrn(g, n):
    a = _attrs(n)
    size = a.get("size", 5)
    return g._emit("nn", "lrn", [g._in(n, 0)], n.output[0],
                   depth_radius=(size - 1) // 2, bias=a.get("bias", 1.0),
                   alpha=a.get("alpha", 1e-4) / size, beta=a.get("beta", 0.75))


@rule("Flatten")
def _flatten(g, n):
    """ONNX Flatten: 2D output (prod(dims[:axis]), prod(dims[axis:]))."""
    axis = _attrs(n).get("axis", 1)
    x = g._in(n, 0)
    dims = list(x.shape or ())
    lead, tail = dims[:axis], dims[axis:]
    if all(d is not None for d in tail):
        shape = (-1, int(np.prod(tail)) if tail else 1)
    elif all(d is not None for d in lead):
        shape = (int(np.prod(lead)) if lead else 1, -1)
    else:
        raise ValueError(f"Flatten {n.name}: unresolvable shape {dims}")
    return g._emit("shape", "reshape", [x], n.output[0], shape=shape)


# ------------------------------------------------------------ shape ops


@rule("Reshape")
def _reshape(g, n):
    shape = [int(s) for s in g._const(n, 1)]
    return g._emit("shape", "reshape", [g._in(n, 0)], n.output[0], shape=shape)


@rule("Transpose")
def _transpose(g, n):
    perm = _attrs(n).get("perm")
    if perm is None:
        return g._emit("shape", "transpose", [g._in(n, 0)], n.output[0])
    return g._emit("shape", "permute", [g._in(n, 0)], n.output[0],
                   axes=tuple(perm))


@rule("Concat")
def _concat(g, n):
    axis = _attrs(n)["axis"]
    ins = [g.vars[i] for i in n.input]
    return g._emit("shape", "concatN", ins, n.output[0], axis=axis)


@rule("Split")
def _split(g, n):
    a = _attrs(n)
    axis = a.get("axis", 0)
    x = g._in(n, 0)
    if "split" in a:
        sizes = a["split"]
    elif len(n.input) > 1 and n.input[1]:
        sizes = [int(s) for s in g._const(n, 1)]
    else:
        sizes = None
    if axis < 0:
        axis += len(x.shape or ())
    if sizes is None:
        num = len(n.output)
        outs = g._emit("shape", "splitN", [x], n.output[0], num=num, axis=axis)
        return list(outs) if isinstance(outs, (tuple, list)) else [outs]
    outs = []
    start = 0
    for i, s in enumerate(sizes):
        sl = [slice(None)] * axis + [slice(start, start + s)]
        outs.append(g._emit("shape", "stridedSlice", [x], n.output[i],
                            slices=tuple(sl)))
        start += s
    return outs


@rule("Squeeze")
def _squeeze(g, n):
    a = _attrs(n)
    axes = a.get("axes")
    if axes is None and len(n.input) > 1 and n.input[1]:
        axes = [int(i) for i in g._const(n, 1)]
    return g._emit("shape", "squeeze", [g._in(n, 0)], n.output[0],
                   axis=tuple(axes) if axes else None)


@rule("Unsqueeze")
def _unsqueeze(g, n):
    a = _attrs(n)
    axes = a.get("axes")
    if axes is None:
        axes = [int(i) for i in g._const(n, 1)]
    out = g._in(n, 0)
    for i, ax in enumerate(sorted(axes)):
        nm = n.output[0] if i == len(axes) - 1 else f"{n.output[0]}/u{i}"
        out = g._emit("shape", "expandDims", [out], nm, axis=ax)
    return out


@rule("Gather")
def _gather(g, n):
    axis = _attrs(n).get("axis", 0)
    return g._emit("shape", "gather", [g._in(n, 0), g._in(n, 1)], n.output[0],
                   axis=axis)


@rule("Slice")
def _slice(g, n):
    a = _attrs(n)
    if "starts" in a:  # opset < 10
        starts, ends = a["starts"], a["ends"]
        axes = a.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    else:
        starts = [int(i) for i in g._const(n, 1)]
        ends = [int(i) for i in g._const(n, 2)]
        axes = ([int(i) for i in g._const(n, 3)]
                if len(n.input) > 3 and n.input[3] else list(range(len(starts))))
        steps = ([int(i) for i in g._const(n, 4)]
                 if len(n.input) > 4 and n.input[4] else [1] * len(starts))
    x = g._in(n, 0)
    rank = len(x.shape or ())
    INT_MAX = 2 ** 31 - 1
    slices = [slice(None)] * rank
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        if ax < 0:
            ax += rank
        en = None if en >= INT_MAX else en  # INT64_MAX/INT32_MAX = "to end"
        slices[ax] = slice(st, en, sp)
    return g._emit("shape", "stridedSlice", [x], n.output[0],
                   slices=tuple(slices))


@rule("Pad")
def _pad_rule(g, n):
    a = _attrs(n)
    mode = a.get("mode", "constant")
    if mode != "constant":
        raise ValueError(f"Pad mode {mode} unsupported")
    if "pads" in a:
        pads = a["pads"]
        value = a.get("value", 0.0)
    else:
        pads = [int(i) for i in g._const(n, 1)]
        value = float(g._const(n, 2)) if len(n.input) > 2 and n.input[2] else 0.0
    rank = len(pads) // 2
    pairs = [(pads[i], pads[i + rank]) for i in range(rank)]
    return g._emit("shape", "pad", [g._in(n, 0)], n.output[0],
                   paddings=pairs, value=value)


@rule("Expand")
def _expand(g, n):
    shape = [int(s) for s in g._const(n, 1)]
    return g._emit("shape", "broadcastTo", [g._in(n, 0)], n.output[0],
                   shape=shape)


@rule("Shape")
def _shape(g, n):
    return g._emit("shape", "shapeOf", [g._in(n, 0)], n.output[0])


@rule("ConstantOfShape")
def _const_of_shape(g, n):
    shape = [int(s) for s in g._const(n, 0)]
    val = _attrs(n).get("value")
    fill = float(val.ravel()[0]) if val is not None else 0.0
    dtype = val.dtype if val is not None else np.float32
    arr = np.full(shape, fill, dtype=dtype)
    g.consts[n.output[0]] = arr
    return g.sd.constant(n.output[0], arr)


@rule("Tile")
def _tile(g, n):
    reps = [int(i) for i in g._const(n, 1)]
    return g._emit("shape", "tile", [g._in(n, 0)], n.output[0], reps=reps)


@rule("Range")
def _range(g, n):
    start, limit, delta = (g._const(n, i) for i in range(3))
    # ONNX: output dtype == input dtype (int Range must stay integer —
    # float-folding would break Gather indices downstream)
    dtype = np.result_type(start.dtype, limit.dtype, delta.dtype)
    arr = np.arange(start.item(), limit.item(), delta.item(), dtype=dtype)
    g.consts[n.output[0]] = arr
    return g.sd.constant(n.output[0], arr)


# ------------------------------------------------------------- reductions


def _reduce_rule(opname):
    def fn(g, n):
        a = _attrs(n)
        axes = a.get("axes")
        if axes is None and len(n.input) > 1 and n.input[1]:
            axes = [int(i) for i in g._const(n, 1)]
        keepdims = bool(a.get("keepdims", 1))
        return g._emit("reduce", opname, [g._in(n, 0)], n.output[0],
                       dims=tuple(axes) if axes else None, keepdims=keepdims)
    return fn


for _t, _o in [("ReduceSum", "sum"), ("ReduceMean", "mean"), ("ReduceMax", "max"),
               ("ReduceMin", "min"), ("ReduceProd", "prod")]:
    _RULES[_t] = _reduce_rule(_o)


@rule("ArgMax")
def _argmax(g, n):
    a = _attrs(n)
    return g._emit("reduce", "argmax", [g._in(n, 0)], n.output[0],
                   dims=a.get("axis", 0), keepdims=bool(a.get("keepdims", 1)))


@rule("ArgMin")
def _argmin(g, n):
    a = _attrs(n)
    return g._emit("reduce", "argmin", [g._in(n, 0)], n.output[0],
                   dims=a.get("axis", 0), keepdims=bool(a.get("keepdims", 1)))


# ---------------------------------------------------------------------------
# Round-2 widening: recurrent ops, ConvTranspose, Resize, einsum, indexing,
# reductions, and activation stragglers (ref: samediff-import-onnx rule set).

_UNARY2 = [
    ("HardSwish", "nn", "hardSwish"), ("Mish", "nn", "mish"),
    ("IsNaN", "math", "isnan"), ("IsInf", "math", "isinf"),
    ("Acosh", "math", "acosh"), ("Asinh", "math", "asinh"),
    ("Atanh", "math", "atanh"), ("Cosh", "math", "cosh"),
    ("Tanh", "math", "tanh"), ("Erf", "math", "erf"),
]
for _t, _ns, _o in _UNARY2:
    if _t not in _RULES:
        _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
            ns, o, [g._in(n, 0)], n.output[0]))(_ns, _o)


@rule("Celu")
def _celu(g, n):
    return g._emit("nn", "celu", [g._in(n, 0)], n.output[0],
                   alpha=_attrs(n).get("alpha", 1.0))


@rule("ThresholdedRelu")
def _thresholded_relu(g, n):
    return g._emit("nn", "thresholdRelu", [g._in(n, 0)], n.output[0],
                   theta=_attrs(n).get("alpha", 1.0))


@rule("Shrink")
def _shrink(g, n):
    a = _attrs(n)
    return g._emit("nn", "shrink", [g._in(n, 0)], n.output[0],
                   bias=a.get("bias", 0.0), lambd=a.get("lambd", 0.5))


@rule("Mod")
def _mod(g, n):
    fmod = _attrs(n).get("fmod", 0)
    opname = "fmod" if fmod else "mod"
    return g._emit("math", opname, [g._in(n, 0), g._in(n, 1)], n.output[0])


@rule("Sum")
def _sum_variadic(g, n):
    if len(n.input) == 1:  # legal ONNX identity form
        return g._emit("math", "identity", [g._in(n, 0)], n.output[0])
    out = g._in(n, 0)
    for i in range(1, len(n.input)):
        out = g._emit("math", "add", [out, g._in(n, i)],
                      f"{n.output[0]}/acc{i}" if i < len(n.input) - 1
                      else n.output[0])
    return out


@rule("Mean")
def _mean_variadic(g, n):
    k = len(n.input)
    out = g._in(n, 0)
    for i in range(1, k):
        out = g._emit("math", "add", [out, g._in(n, i)], f"{n.output[0]}/acc{i}")
    inv = g.sd.constant(f"{n.output[0]}/invk", np.float32(1.0 / k))
    return g._emit("math", "mul", [out, inv], n.output[0])


def _reduce_composed(inner, post=None, pre=None):
    """ReduceL1/L2/LogSum/LogSumExp/SumSquare as compositions."""
    def fn(g, n):
        a = _attrs(n)
        axes = a.get("axes")
        if axes is None and len(n.input) > 1 and n.input[1]:
            axes = [int(i) for i in g._const(n, 1)]
        keepdims = bool(a.get("keepdims", 1))
        x = g._in(n, 0)
        if pre:
            x = g._emit("math", pre, [x], n.output[0] + "/pre")
        red = g._emit("reduce", inner, [x],
                      n.output[0] + "/red" if post else n.output[0],
                      dims=tuple(axes) if axes else None, keepdims=keepdims)
        if post:
            return g._emit("math", post, [red], n.output[0])
        return red
    return fn


_RULES["ReduceL1"] = _reduce_rule("norm1")
_RULES["ReduceSumSquare"] = _reduce_rule("squaredNorm")
_RULES["ReduceL2"] = _reduce_rule("norm2")
_RULES["ReduceLogSum"] = _reduce_composed("sum", post="log")


@rule("ReduceLogSumExp")
def _reduce_lse(g, n):
    a = _attrs(n)
    axes = a.get("axes")
    if axes is None and len(n.input) > 1 and n.input[1]:
        axes = [int(i) for i in g._const(n, 1)]
    keepdims = bool(a.get("keepdims", 1))
    return g._emit("reduce", "logSumExp", [g._in(n, 0)], n.output[0],
                   dims=tuple(axes) if axes else None, keepdims=keepdims)


@rule("Einsum")
def _einsum_onnx(g, n):
    eq = _attrs(n)["equation"]
    if isinstance(eq, bytes):
        eq = eq.decode()
    return g._emit("linalg", "einsum", [g._in(n, i) for i in range(len(n.input))],
                   n.output[0], equation=eq)


@rule("TopK")
def _topk_onnx(g, n):
    a = _attrs(n)
    k = int(np.atleast_1d(g._const(n, 1))[0])
    axis = a.get("axis", -1)
    largest = a.get("largest", 1)
    x = g._in(n, 0)
    if axis not in (-1, len(x.shape or []) - 1):
        raise ValueError("TopK: only last-axis supported")
    if not largest:  # smallest-k via negation (indices unaffected)
        x = g._emit("math", "neg", [x], n.output[0] + "/neg")
    vals, idx = g._emit("math", "topK", [x], n.output[0] + "/tk", k=k)
    if not largest:
        vals = g._emit("math", "neg", [vals], n.output[0] + "/vneg")
    outs = [g._emit("math", "identity", [o], ref)
            for ref, o in zip(n.output, (vals, idx)) if ref]
    g._register(n, outs)
    return None


@rule("CumSum")
def _cumsum_onnx(g, n):
    a = _attrs(n)
    axis = int(np.atleast_1d(g._const(n, 1))[0])
    x = g._in(n, 0)
    if a.get("reverse"):
        x = g._emit("shape", "reverse", [x], n.output[0] + "/rin", dims=(axis,))
    out = g._emit("shape", "cumsum", [x], n.output[0] + "/cs", axis=axis)
    if a.get("exclusive"):
        out = g._emit("math", "sub", [out, x], n.output[0] + "/excl")
    if a.get("reverse"):
        out = g._emit("shape", "reverse", [out], n.output[0] + "/rout",
                      dims=(axis,))
    return g._emit("math", "identity", [out], n.output[0])


@rule("OneHot")
def _onehot_onnx(g, n):
    depth = int(np.atleast_1d(g._const(n, 1))[0])
    values = g._const(n, 2)  # [off, on]
    axis = _attrs(n).get("axis", -1)
    return g._emit("shape", "oneHot", [g._in(n, 0)], n.output[0],
                   depth=depth, axis=axis, on=float(values[1]),
                   off=float(values[0]))


@rule("GatherND")
def _gather_nd_onnx(g, n):
    if _attrs(n).get("batch_dims", 0):
        raise ValueError("GatherND: batch_dims unsupported")
    return g._emit("shape", "gatherNd", [g._in(n, 0), g._in(n, 1)], n.output[0])


@rule("ScatterND")
def _scatter_nd_onnx(g, n):
    red = _attrs(n).get("reduction", "none")
    if isinstance(red, bytes):
        red = red.decode()
    opname = {"none": "scatterNdUpdate", "add": "scatterNdAdd"}.get(red)
    if opname is None:
        raise ValueError(f"ScatterND: reduction '{red}' unsupported")
    return g._emit("shape", opname,
                   [g._in(n, 0), g._in(n, 1), g._in(n, 2)], n.output[0])


@rule("GatherElements")
def _gather_elements(g, n):
    return g._emit("shape", "gatherElements", [g._in(n, 0), g._in(n, 1)],
                   n.output[0], axis=_attrs(n).get("axis", 0))


@rule("ScatterElements")
def _scatter_elements(g, n):
    a = _attrs(n)
    red = a.get("reduction", "none")
    if isinstance(red, bytes):
        red = red.decode()
    return g._emit("shape", "scatterElements",
                   [g._in(n, 0), g._in(n, 1), g._in(n, 2)], n.output[0],
                   axis=a.get("axis", 0), reduction=red)


@rule("EyeLike")
def _eyelike(g, n):
    return g._emit("shape", "eyeLike", [g._in(n, 0)], n.output[0],
                   k=_attrs(n).get("k", 0))


@rule("Trilu")
def _trilu(g, n):
    upper = _attrs(n).get("upper", 1)
    k = 0
    if len(n.input) > 1 and n.input[1]:
        k = int(np.atleast_1d(g._const(n, 1))[0])
    return g._emit("shape", "triu" if upper else "tril", [g._in(n, 0)],
                   n.output[0], k=k)


@rule("MeanVarianceNormalization")
def _mvn(g, n):
    axes = tuple(_attrs(n).get("axes", (0, 2, 3)))
    return g._emit("nn", "meanVarianceNormalization", [g._in(n, 0)],
                   n.output[0], axes=axes)


@rule("DepthToSpace")
def _d2s_onnx(g, n):
    a = _attrs(n)
    bs = int(a["blocksize"])
    mode = a.get("mode", "DCR")
    if isinstance(mode, bytes):
        mode = mode.decode()
    x = g._in(n, 0)
    if mode == "DCR":
        return g._emit("cnn", "depthToSpace", [x], n.output[0],
                       block_size=bs, data_format="NCHW")
    # CRD: reshape (N, C', b, b, H, W) -> permute -> (N, C', H*b, W*b)
    N, C, H, W = x.shape
    r1 = g._emit("shape", "reshape", [x], n.output[0] + "/r1",
                 shape=(N, C // (bs * bs), bs, bs, H, W))
    p = g._emit("shape", "permute", [r1], n.output[0] + "/p",
                axes=(0, 1, 4, 2, 5, 3))
    return g._emit("shape", "reshape", [p], n.output[0],
                   shape=(N, C // (bs * bs), H * bs, W * bs))


@rule("SpaceToDepth")
def _s2d_onnx(g, n):
    bs = int(_attrs(n)["blocksize"])
    return g._emit("cnn", "spaceToDepth", [g._in(n, 0)], n.output[0],
                   block_size=bs, data_format="NCHW")


@rule("ConvTranspose")
def _conv_transpose(g, n):
    a = _attrs(n)
    w = g._in(n, 1)  # ONNX: (C_in, C_out/groups, kH, kW)
    b = g._opt(n, 2)
    spatial = len(a.get("kernel_shape") or g._const(n, 1).shape[2:])
    if spatial != 2:
        raise ValueError("ConvTranspose: only 2D supported")
    if a.get("group", 1) != 1:
        raise ValueError("ConvTranspose: groups unsupported")
    strides = tuple(a.get("strides", [1, 1]))
    pads = a.get("pads")
    if a.get("output_padding") or a.get("output_shape"):
        raise ValueError("ConvTranspose: output_padding/output_shape unsupported")
    if pads and any(pads):
        padding = _onnx_pads(pads, 2)
    else:
        padding = "VALID"
    inputs = [g._in(n, 0), w] + ([b] if b is not None else [])
    return g._emit("cnn", "deconv2d", inputs, n.output[0], strides=strides,
                   padding=padding)


@rule("Resize", "Upsample")
def _resize_onnx(g, n):
    a = _attrs(n)
    mode = a.get("mode", "nearest")
    if isinstance(mode, bytes):
        mode = mode.decode()
    coord = a.get("coordinate_transformation_mode", "half_pixel")
    if isinstance(coord, bytes):
        coord = coord.decode()
    if n.op_type == "Upsample":
        coord = "asymmetric"  # the deprecated op's fixed semantics
        a = dict(a, nearest_mode="floor")
    x = g._in(n, 0)
    N, C, H, W = x.shape
    align = coord == "align_corners"
    half_pixel = coord in ("half_pixel", "pytorch_half_pixel")
    tf_car = coord == "tf_crop_and_resize"
    if not (align or half_pixel or coord == "asymmetric" or tf_car):
        raise ValueError(f"Resize: coordinate mode '{coord}' unsupported")
    extra = {}
    roi_hw = ((0.0, 1.0), (0.0, 1.0))
    if tf_car:
        # roi (input 1): 2*rank normalized starts then ends; only the
        # spatial axes may crop — N/C roi must be the identity [0, 1]
        roi_vals = [float(v) for v in g._const(n, 1)]
        starts, ends = roi_vals[:4], roi_vals[4:]
        if (starts[0], starts[1], ends[0], ends[1]) != (0.0, 0.0, 1.0, 1.0):
            raise ValueError("Resize(tf_crop_and_resize): N/C roi must be "
                             "[0, 1] — only spatial cropping is supported")
        roi_hw = ((starts[2], ends[2]), (starts[3], ends[3]))
        extra["roi"] = roi_hw
        extra["extrapolation_value"] = float(a.get("extrapolation_value", 0.0))
    if coord == "pytorch_half_pixel":
        extra["pytorch_half_pixel"] = True
    # sizes (input 3) take precedence over scales (input 2; Upsample: input 1)
    if len(n.input) > 3 and n.input[3]:
        sizes = [int(s) for s in g._const(n, 3)]
        out_hw = (sizes[2], sizes[3])
    else:
        scale_idx = 1 if n.op_type == "Upsample" else 2
        scales = [float(s) for s in g._const(n, scale_idx)]
        # tf_crop_and_resize scales apply to the ROI extent, not the full
        # image: output_dim = floor(input_dim * (roi_end - roi_start) * scale)
        eh = roi_hw[0][1] - roi_hw[0][0]
        ew = roi_hw[1][1] - roi_hw[1][0]
        out_hw = (int(H * eh * scales[2]), int(W * ew * scales[3]))
    if mode == "nearest":
        nearest_mode = a.get("nearest_mode", "round_prefer_floor")
        if isinstance(nearest_mode, bytes):
            nearest_mode = nearest_mode.decode()
        if nearest_mode not in ("floor", "round_prefer_floor"):
            raise ValueError(f"Resize: nearest_mode '{nearest_mode}' unsupported")
        opname = "resizeNearest"
        extra["nearest_mode"] = nearest_mode
    elif mode in ("linear", "bilinear"):
        opname = "resizeBilinear"
    elif mode == "cubic":
        opname = "resizeBicubic"
        extra["cubic_coeff_a"] = float(a.get("cubic_coeff_a", -0.75))
        extra["exclude_outside"] = bool(a.get("exclude_outside", 0))
    else:
        raise ValueError(f"Resize: mode '{mode}' unsupported")
    return g._emit("image", opname, [x], n.output[0], size=out_hw,
                   data_format="NCHW", align_corners=align,
                   half_pixel_centers=half_pixel, **extra)


def _rnn_common(g, n, opname, extra_kw):
    """ONNX LSTM/GRU/RNN share the optional-input layout (B, sequence_lens,
    initial_h[, initial_c]); missing optionals are materialized as their
    defaulting constants so the op call stays uniformly positional."""
    a = _attrs(n)
    direction = a.get("direction", "forward")
    if isinstance(direction, bytes):
        direction = direction.decode()
    x, w, r = g._in(n, 0), g._in(n, 1), g._in(n, 2)
    T, B = x.shape[0], x.shape[1]
    if T is None or B is None:
        raise ValueError(
            f"{n.op_type} node '{n.name or n.output[0]}': dynamic time/batch "
            "dims need explicit sequence_lens/initial state inputs (defaults "
            "cannot be materialized from an unknown shape)")
    D, gates_h = w.shape[0], w.shape[1]
    H = {"lstmOnnx": gates_h // 4, "gruOnnx": gates_h // 3,
         "rnnOnnx": gates_h}[opname]
    n_b = {"lstmOnnx": 8, "gruOnnx": 6, "rnnOnnx": 2}[opname] * H

    def opt_or(i, name, default):
        v = g._opt(n, i)
        if v is None:
            v = g.sd.constant(f"{n.output[0]}/{name}", default)
        return v

    b = opt_or(3, "b0", np.zeros((D, n_b), np.float32))
    seq = opt_or(4, "seqlens", np.full((B,), T, np.int32))
    h0 = opt_or(5, "h0", np.zeros((D, B, H), np.float32))
    inputs = [x, w, r, b, seq, h0]
    if opname == "lstmOnnx":
        inputs.append(opt_or(6, "c0", np.zeros((D, B, H), np.float32)))
    outs = g._emit("rnn", opname, inputs, n.output[0] + "/rnn",
                   direction=direction, **extra_kw)
    # multi-output vars are named base#i — re-emit identities so each ONNX
    # output ref is a real SameDiff variable name
    outs = [g._emit("math", "identity", [o], ref)
            for ref, o in zip(n.output, outs) if ref]
    g._register(n, outs)
    return None


@rule("LSTM")
def _lstm_onnx_rule(g, n):
    if _attrs(n).get("layout", 0) != 0:
        raise ValueError("LSTM: layout=1 unsupported (use default T,B,I)")
    return _rnn_common(g, n, "lstmOnnx", {})


@rule("GRU")
def _gru_onnx_rule(g, n):
    a = _attrs(n)
    if a.get("layout", 0) != 0:
        raise ValueError("GRU: layout=1 unsupported")
    return _rnn_common(g, n, "gruOnnx",
                       {"linear_before_reset": a.get("linear_before_reset", 0)})


@rule("RNN")
def _rnn_onnx_rule(g, n):
    a = _attrs(n)
    if a.get("layout", 0) != 0:
        raise ValueError("RNN: layout=1 unsupported")
    acts = a.get("activations")
    act = "Tanh"
    if acts:
        act = acts[0].decode() if isinstance(acts[0], bytes) else acts[0]
    return _rnn_common(g, n, "rnnOnnx", {"activation": act})
