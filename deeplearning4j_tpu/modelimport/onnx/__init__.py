"""ONNX import (ref: nd4j/samediff-import-onnx — OnnxFrameworkImporter).

The pip ``onnx`` package is absent in this environment; the wire format is
parsed with protoc-generated bindings from onnx_minimal.proto (a hand-written
subset of the public ONNX IR schema with matching field numbers, so real
.onnx files parse byte-compatibly).
"""
from deeplearning4j_tpu.modelimport.onnx.importer import (
    OnnxFrameworkImporter,
    numpy_to_tensor,
    tensor_to_numpy,
)
from deeplearning4j_tpu.modelimport.onnx import onnx_minimal_pb2 as onnx_pb

__all__ = ["OnnxFrameworkImporter", "onnx_pb", "numpy_to_tensor",
           "tensor_to_numpy"]
