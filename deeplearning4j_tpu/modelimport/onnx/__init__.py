"""ONNX import entry point (ref: nd4j/samediff-import-onnx —
OnnxFrameworkImporter). The ``onnx`` package is not available in this build
environment, so the importer is gated: it raises at call time with guidance
rather than at import time (environment policy: stub or gate optional deps)."""
from __future__ import annotations


class OnnxFrameworkImporter:
    """(ref: org.nd4j.samediff.frameworkimport.onnx.importer.OnnxFrameworkImporter)."""

    @staticmethod
    def runImport(path: str):
        try:
            import onnx  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "ONNX import requires the 'onnx' package, which is not "
                "installed in this environment. Convert the model to a TF "
                "frozen graph or Keras h5 and use "
                "modelimport.tensorflow.TensorflowFrameworkImporter / "
                "modelimport.keras.KerasModelImport instead.") from e
        raise NotImplementedError(
            "onnx runtime mapping not yet implemented; TF and Keras import "
            "cover the reference corpus (SURVEY.md §2.2 samediff-import)")


__all__ = ["OnnxFrameworkImporter"]
