"""TF frozen-graph import (ref: nd4j/samediff-import-tensorflow —
TensorflowFrameworkImporter / TFGraphMapper)."""
from deeplearning4j_tpu.modelimport.tensorflow.importer import TensorflowFrameworkImporter

__all__ = ["TensorflowFrameworkImporter"]
