"""TF GraphDef -> SameDiff import (ref: nd4j/samediff-import-tensorflow —
TensorflowFrameworkImporter.runImport + per-op MappingProcess rules;
legacy path TFGraphMapper).

Design mirrors the reference's declarative registry: one mapping rule per TF
op type, translating a NodeDef (attrs + const-resolved inputs) into ops from
the shared registry on a SameDiff graph. Layout: TF conv/pool nodes are NHWC;
this framework's cnn ops are NCHW, so rules wrap them in transposes (XLA
fuses/cancels adjacent transposes at compile time — free on TPU, unlike the
reference which carries format flags through every kernel).

The importer resolves Const nodes eagerly so attribute-carrying inputs
(axes, shapes, paddings, perms) become python values, exactly as the
reference's `MappingRule`s pull from initializers.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable

_JNP_DT = {
    1: "float32", 2: "float64", 3: "int32", 4: "uint8", 6: "int8",
    9: "int64", 10: "bool", 14: "bfloat16", 19: "float16",
}


def _clean(name: str) -> str:
    if name.startswith("^"):
        return ""
    return name.split(":")[0]


class TensorflowFrameworkImporter:
    """(ref: org.nd4j.samediff.frameworkimport.tensorflow.importer.
    TensorflowFrameworkImporter)."""

    @staticmethod
    def runImport(graph_def_or_path) -> SameDiff:
        """Import a frozen GraphDef (proto object, serialized bytes, or .pb
        path) into a SameDiff graph (ref: runImport / importFrozenTF)."""
        gd = _load_graphdef(graph_def_or_path)
        return _GraphImporter(gd).run()

    # reference-parity alias (SameDiff.importFrozenTF)
    importFrozenTF = runImport


def _load_graphdef(src):
    from tensorflow.core.framework import graph_pb2
    if isinstance(src, graph_pb2.GraphDef):
        return src
    gd = graph_pb2.GraphDef()
    if isinstance(src, bytes):
        gd.ParseFromString(src)
        return gd
    with open(src, "rb") as f:
        gd.ParseFromString(f.read())
    return gd


class _GraphImporter:
    def __init__(self, gd):
        self.gd = gd
        self.sd = SameDiff.create()
        self.vars: Dict[str, SDVariable] = {}     # tf node name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}   # eagerly-resolved Const values
        # function library (control-flow bodies; GraphDef.library.function)
        self.library: Dict[str, Any] = {
            f.signature.name: f for f in gd.library.function} if gd is not None else {}
        # NodeDef by name — rules peek at producers (e.g. Pack feeding Reshape)
        self.nodes: Dict[str, Any] = {
            n.name: n for n in gd.node} if gd is not None else {}

    # ------------------------------------------------------------- helpers
    def _resolve(self, ref: str) -> SDVariable:
        """Resolve a tensor reference. GraphDef refs are ``name[:idx]``;
        FunctionDef refs are ``name:out_arg:idx`` — multi-output nodes (While,
        If) register their extra outputs under ``name:idx``."""
        parts = ref.split(":")
        name = parts[0]
        idx = int(parts[-1]) if len(parts) > 1 and parts[-1].isdigit() else 0
        if idx:
            if f"{name}:{idx}" not in self.vars:
                raise KeyError(
                    f"tensor ref {ref}: node {name} registered no output {idx}")
            return self.vars[f"{name}:{idx}"]
        return self.vars[name]

    def _in(self, node, i) -> SDVariable:
        return self._resolve(node.input[i])

    def _const(self, node, i) -> np.ndarray:
        name = _clean(node.input[i])
        if name not in self.consts:
            raise ValueError(
                f"input {i} of {node.name} ({node.op}) must be a Const "
                f"(dynamic attribute inputs are not supported)")
        return self.consts[name]

    def _ins(self, node) -> List[SDVariable]:
        return [self._resolve(n) for n in node.input if _clean(n)]

    def _register_outputs(self, node, outs):
        """Register a multi-output node's results as name / name:i."""
        outs = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        self.vars[node.name] = outs[0]
        for i, o in enumerate(outs[1:], start=1):
            self.vars[f"{node.name}:{i}"] = o

    def _import_function(self, fname: str):
        """FunctionDef -> (sub-SameDiff, in_names, out_names) for control ops
        (ref: samediff-import maps tf.function bodies to SameDiff subgraphs)."""
        import jax.numpy as jnp
        import tensorflow as tf
        fdef = self.library[fname]
        sub = _GraphImporter.__new__(_GraphImporter)
        sub.gd = None
        sub.sd = SameDiff.create()
        sub.vars = {}
        sub.consts = {}
        sub.library = self.library
        sub.nodes = {n.name: n for n in fdef.node_def}
        in_names = []
        for arg in fdef.signature.input_arg:
            dt = getattr(jnp, _JNP_DT.get(arg.type, "float32"))
            ph = sub.sd.placeHolder(arg.name, shape=None, dtype=dt)
            sub.vars[arg.name] = ph
            in_names.append(arg.name)
        for node in fdef.node_def:
            sub._map_node(node, tf)
        out_names = []
        for out_arg in fdef.signature.output_arg:
            ref = fdef.ret[out_arg.name]
            out_names.append(sub._resolve(ref).name)
        return sub.sd, in_names, out_names

    def _emit(self, ns: str, opname: str, inputs, name: str, **kwargs) -> SDVariable:
        out = self.sd._op(ns, opname, inputs, name=name, **kwargs)
        return out

    def _nhwc_to_nchw(self, v, name):
        return self._emit("shape", "permute", [v], f"{name}/nchw", axes=(0, 3, 1, 2))

    def _nchw_to_nhwc(self, v, name):
        return self._emit("shape", "permute", [v], f"{name}/nhwc", axes=(0, 2, 3, 1))

    # ----------------------------------------------------------------- run
    def run(self) -> SameDiff:
        import tensorflow as tf
        for node in self.gd.node:
            self._map_node(node, tf)
        return self.sd

    def _map_node(self, node, tf):
        op = node.op
        name = node.name
        sd = self.sd

        if op == "Const":
            val = tf.make_ndarray(node.attr["value"].tensor)
            self.consts[name] = val
            self.vars[name] = sd.constant(name, val)
            return
        if op == "Placeholder":
            shape = None
            if node.attr["shape"].shape.dim:
                shape = tuple(d.size if d.size > 0 else None
                              for d in node.attr["shape"].shape.dim)
            import jax.numpy as jnp
            dt = getattr(jnp, _JNP_DT.get(node.attr["dtype"].type, "float32"))
            self.vars[name] = sd.placeHolder(name, shape=shape, dtype=dt)
            return
        if op in ("Identity", "StopGradient", "PreventGradient", "Snapshot",
                  "CheckNumerics"):
            src = _clean(node.input[0])
            # emit a real node so the TF node name is addressable as a graph
            # output (frozen-fn outputs are typically named "Identity");
            # _resolve keeps multi-output refs like "while:1" intact
            self.vars[name] = self._emit(
                "math", "identity", [self._resolve(node.input[0])], name)
            if src in self.consts:
                self.consts[name] = self.consts[src]
            return
        if op == "NoOp":
            return

        fn = _RULES.get(op)
        if fn is None:
            raise ValueError(f"TF op '{op}' (node {name}) has no mapping rule "
                             f"(ref: OpMappingRegistry lookup failure)")
        out = fn(self, node)
        if out is not None:
            self.vars[name] = out
            # eager const folding: a node whose inputs are all consts is
            # itself a const (ref: the importer resolves constant subgraphs so
            # downstream rules can read attribute-carrying inputs — e.g.
            # StridedSlice over a constant-folded Shape feeding a Reshape)
            ins = [_clean(x) for x in node.input if _clean(x)]
            if ins and all(i in self.consts for i in ins):
                try:
                    val = _eval_const_node(self, node, out)
                    if val is not None:
                        self.consts[name] = val
                except Exception:
                    pass


def _eval_const_node(g, node, out: SDVariable):
    """Evaluate a const-input node's value at import time (small results only
    — shape math; folding megabyte weights would duplicate them)."""
    if out.shape is None or int(np.prod(out.shape or (1,))) > 4096:
        return None
    return np.asarray(out.eval({}).toNumpy())


# --------------------------------------------------------------- mapping rules

def _rule(*tf_ops):
    def deco(fn):
        for t in tf_ops:
            _RULES[t] = fn
        return fn
    return deco


_RULES: Dict[str, Any] = {}

_BINARY = {
    "Add": ("math", "add"), "AddV2": ("math", "add"), "Sub": ("math", "sub"),
    "Mul": ("math", "mul"), "RealDiv": ("math", "div"), "Div": ("math", "div"),
    "Maximum": ("math", "max"), "Minimum": ("math", "min"),
    "Pow": ("math", "pow"), "FloorDiv": ("math", "floorDiv"),
    "FloorMod": ("math", "floorMod"), "Atan2": ("math", "atan2"),
    "LogicalAnd": ("math", "logicalAnd"), "LogicalOr": ("math", "logicalOr"),
    "SquaredDifference": ("math", "squaredDifference"),
    "Equal": ("math", "eq"), "NotEqual": ("math", "neq"),
    "Less": ("math", "lt"), "LessEqual": ("math", "lte"),
    "Greater": ("math", "gt"), "GreaterEqual": ("math", "gte"),
}
_UNARY = {
    "Relu": ("nn", "relu"), "Relu6": ("nn", "relu6"), "Elu": ("nn", "elu"),
    "Selu": ("nn", "selu"), "Sigmoid": ("nn", "sigmoid"),
    "Softplus": ("nn", "softplus"), "Softsign": ("nn", "softsign"),
    "Tanh": ("math", "tanh"), "Exp": ("math", "exp"), "Log": ("math", "log"),
    "Log1p": ("math", "log1p"), "Neg": ("math", "neg"), "Abs": ("math", "abs"),
    "Square": ("math", "square"), "Sqrt": ("math", "sqrt"),
    "Rsqrt": ("math", "rsqrt"), "Erf": ("math", "erf"), "Floor": ("math", "floor"),
    "Ceil": ("math", "ceil"), "Round": ("math", "round"), "Sign": ("math", "sign"),
    "Sin": ("math", "sin"), "Cos": ("math", "cos"), "Tan": ("math", "tan"),
    "Reciprocal": ("math", "reciprocal"), "LogicalNot": ("math", "logicalNot"),
    "IsNan": ("math", "isnan"), "IsInf": ("math", "isinf"),
    "IsFinite": ("math", "isfinite"),
    "Sinh": ("math", "sinh"), "Cosh": ("math", "cosh"),
    "Asin": ("math", "asin"), "Acos": ("math", "acos"),
    "Atan": ("math", "atan"), "Asinh": ("math", "asinh"),
    "Acosh": ("math", "acosh"), "Atanh": ("math", "atanh"),
    "Expm1": ("math", "expm1"), "Erfc": ("math", "erfc"),
    "Digamma": ("math", "digamma"), "Lgamma": ("math", "lgamma"),
}
_REDUCE = {
    "Mean": "mean", "Sum": "sum", "Max": "max", "Min": "min", "Prod": "prod",
    "All": "all", "Any": "any",
}

for _t, (_ns, _o) in list(_BINARY.items()):
    _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
        ns, o, [g._in(n, 0), g._in(n, 1)], n.name))(_ns, _o)
for _t, (_ns, _o) in list(_UNARY.items()):
    _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
        ns, o, [g._in(n, 0)], n.name))(_ns, _o)
for _t, _o in list(_REDUCE.items()):
    def _red(g, n, _o=_o):
        axes = g._const(n, 1)
        dims = tuple(int(a) for a in np.atleast_1d(axes))
        keep = bool(n.attr["keep_dims"].b)
        return g._emit("reduce", _o, [g._in(n, 0)], n.name, dims=dims, keepdims=keep)
    _RULES[_t] = _red


@_rule("MatMul")
def _matmul(g, n):
    a, b = g._in(n, 0), g._in(n, 1)
    if n.attr["transpose_a"].b:
        a = g._emit("shape", "permute", [a], n.name + "/ta", axes=(1, 0))
    if n.attr["transpose_b"].b:
        b = g._emit("shape", "permute", [b], n.name + "/tb", axes=(1, 0))
    return g._emit("linalg", "matmul", [a, b], n.name)


@_rule("BatchMatMul", "BatchMatMulV2")
def _bmm(g, n):
    a, b = g._in(n, 0), g._in(n, 1)
    if n.attr["adj_x"].b:
        nd = len(a.shape or (0, 0, 0))
        g_axes = tuple(range(nd - 2)) + (nd - 1, nd - 2)
        a = g._emit("shape", "permute", [a], n.name + "/ta", axes=g_axes)
    if n.attr["adj_y"].b:
        nd = len(b.shape or (0, 0, 0))
        g_axes = tuple(range(nd - 2)) + (nd - 1, nd - 2)
        b = g._emit("shape", "permute", [b], n.name + "/tb", axes=g_axes)
    return g._emit("linalg", "matmul", [a, b], n.name)


@_rule("BiasAdd")
def _bias_add(g, n):
    # NHWC (default): bias broadcasts over the trailing channel dim
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    x, b = g._in(n, 0), g._in(n, 1)
    if fmt == "NCHW":
        raise ValueError("BiasAdd NCHW import unsupported (TF frozen graphs are NHWC)")
    return g._emit("math", "add", [x, b], n.name)


@_rule("Softmax")
def _softmax(g, n):
    return g._emit("nn", "softmax", [g._in(n, 0)], n.name)


@_rule("LeakyRelu")
def _leaky(g, n):
    return g._emit("nn", "leakyRelu", [g._in(n, 0)], n.name,
                   alpha=float(n.attr["alpha"].f or 0.2))


@_rule("Reshape")
def _reshape(g, n):
    ref = _clean(n.input[1])
    if ref in g.consts:
        shape = tuple(int(s) for s in g.consts[ref])
        return g._emit("shape", "reshape", [g._in(n, 0)], n.name, shape=shape)
    # dynamic shape: typically Pack([batch_from_Shape, const, const, ...]).
    # XLA needs static shapes, so resolve each dynamic component back to the
    # tensor whose tf.shape() it came from ("dim:i" of a reference input,
    # static at trace time); a single unresolvable one degrades to -1.
    producer = g.nodes.get(ref)
    if producer is not None and producer.op == "Pack":
        dims: List[Any] = []
        ref_node = None
        for inp in producer.input:
            nm = _clean(inp)
            if nm in g.consts:
                dims.append(int(np.atleast_1d(g.consts[nm])[0]))
                continue
            src = _dim_of_shape(g, nm)
            if src is not None:
                target, idx = src
                if ref_node is None or ref_node == target:
                    ref_node = target
                    dims.append(f"dim:{idx}")
                    continue
            dims.append(-1)
        n_unres = sum(1 for d in dims if d == -1)
        if ref_node is not None and n_unres == 0:
            return g._emit("shape", "reshapeRef",
                           [g._in(n, 0), g._resolve(ref_node)], n.name,
                           dims=list(dims))
        if ref_node is None and n_unres <= 1:
            return g._emit("shape", "reshape", [g._in(n, 0)], n.name,
                           shape=tuple(dims))
        if n_unres <= 1:
            # mixed: keep ref dims, let the one unresolved dim be inferred
            return g._emit("shape", "reshapeRef",
                           [g._in(n, 0), g._resolve(ref_node)], n.name,
                           dims=list(dims))
    raise ValueError(
        f"Reshape {n.name}: dynamic shape input {ref} unresolvable "
        "(need Const or Pack of consts / tf.shape() components)")


def _dim_of_shape(g, name):
    """If node ``name`` is StridedSlice(Shape(y), [i]) return (y, i)."""
    node = g.nodes.get(name)
    if node is None or node.op != "StridedSlice":
        return None
    shp = g.nodes.get(_clean(node.input[0]))
    if shp is None or shp.op != "Shape":
        return None
    try:
        i = int(np.atleast_1d(g.consts[_clean(node.input[1])])[0])
    except KeyError:
        return None
    return _clean(shp.input[0]), i


@_rule("Transpose")
def _transpose(g, n):
    perm = tuple(int(p) for p in g._const(n, 1))
    return g._emit("shape", "permute", [g._in(n, 0)], n.name, axes=perm)


@_rule("ExpandDims")
def _expand(g, n):
    axis = int(np.atleast_1d(g._const(n, 1))[0])
    return g._emit("shape", "expandDims", [g._in(n, 0)], n.name, axis=axis)


@_rule("Squeeze")
def _squeeze(g, n):
    dims = tuple(int(d) for d in n.attr["squeeze_dims"].list.i) or None
    return g._emit("shape", "squeeze", [g._in(n, 0)], n.name, axis=dims)


@_rule("ConcatV2")
def _concat(g, n):
    axis = int(np.atleast_1d(g._const(n, len(n.input) - 1))[0])
    xs = [g._in(n, i) for i in range(len(n.input) - 1)]
    return g._emit("shape", "concatN", xs, n.name, axis=axis)


@_rule("Pack")
def _pack(g, n):
    axis = int(n.attr["axis"].i)
    return g._emit("shape", "stackN", g._ins(n), n.name, axis=axis)


@_rule("Pad", "PadV2")
def _pad(g, n):
    pads = tuple(tuple(int(v) for v in row) for row in g._const(n, 1))
    return g._emit("shape", "pad", [g._in(n, 0)], n.name, paddings=pads)


@_rule("GatherV2", "Gather")
def _gather(g, n):
    axis = 0
    if len(n.input) > 2:
        axis = int(np.atleast_1d(g._const(n, 2))[0])
    return g._emit("shape", "gather", [g._in(n, 0), g._in(n, 1)], n.name, axis=axis)


@_rule("Cast")
def _cast(g, n):
    import jax.numpy as jnp
    dt = getattr(jnp, _JNP_DT.get(n.attr["DstT"].type, "float32"))
    return g._emit("shape", "castTo", [g._in(n, 0)], n.name, dtype=dt)


@_rule("ArgMax")
def _argmax(g, n):
    axis = int(np.atleast_1d(g._const(n, 1))[0])
    return g._emit("reduce", "argmax", [g._in(n, 0)], n.name, dims=axis)


@_rule("OneHot")
def _onehot(g, n):
    depth = int(np.atleast_1d(g._const(n, 1))[0])
    on = float(np.atleast_1d(g._const(n, 2))[0])
    off = float(np.atleast_1d(g._const(n, 3))[0])
    return g._emit("shape", "oneHot", [g._in(n, 0)], n.name, depth=depth,
                   on=on, off=off)


@_rule("Shape")
def _shape(g, n):
    return g._emit("shape", "shapeOf", [g._in(n, 0)], n.name)


@_rule("StridedSlice")
def _strided_slice(g, n):
    begin = [int(v) for v in g._const(n, 1)]
    end = [int(v) for v in g._const(n, 2)]
    strides = [int(v) for v in g._const(n, 3)]
    bm = int(n.attr["begin_mask"].i)
    em = int(n.attr["end_mask"].i)
    sm = int(n.attr["shrink_axis_mask"].i)
    nm = int(n.attr["new_axis_mask"].i)
    el = int(n.attr["ellipsis_mask"].i)
    if el:
        raise ValueError("StridedSlice with ellipsis mask unsupported")
    slices = []
    for i in range(len(begin)):
        if nm & (1 << i):
            slices.append(None)  # np.newaxis (e.g. pos_emb[tf.newaxis])
            continue
        if sm & (1 << i):
            slices.append(begin[i])
            continue
        b = None if bm & (1 << i) else begin[i]
        e = None if em & (1 << i) else end[i]
        slices.append(slice(b, e, strides[i]))
    return g._emit("shape", "stridedSlice", [g._in(n, 0)], n.name,
                   slices=tuple(slices))


@_rule("Conv2D")
def _conv2d(g, n):
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    if fmt != "NHWC":
        raise ValueError(f"Conv2D data_format {fmt} unsupported (frozen TF graphs are NHWC)")
    strides = list(n.attr["strides"].list.i)  # NHWC order
    dil = list(n.attr["dilations"].list.i) or [1, 1, 1, 1]
    padding = n.attr["padding"].s.decode()
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    # kernel HWIO -> OIHW
    w = g._emit("shape", "permute", [g._in(n, 1)], n.name + "/w", axes=(3, 2, 0, 1))
    out = g._emit("cnn", "conv2d", [x, w], n.name + "/conv",
                  strides=(strides[1], strides[2]), padding=padding,
                  dilation=(dil[1], dil[2]))
    return g._nchw_to_nhwc(out, n.name)


@_rule("DepthwiseConv2dNative")
def _depthwise(g, n):
    strides = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    # kernel (kh,kw,C,mult) -> (C*mult, 1, kh, kw); frozen graphs have it const
    kv = g._const(n, 1)
    kh, kw_, C, mult = kv.shape
    w = g.sd.constant(n.name + "/w",
                      kv.transpose(2, 3, 0, 1).reshape(C * mult, 1, kh, kw_))
    out = g._emit("cnn", "depthwiseConv2d", [x, w], n.name + "/conv",
                  strides=(strides[1], strides[2]), padding=padding)
    return g._nchw_to_nhwc(out, n.name)


@_rule("MaxPool", "AvgPool")
def _pool(g, n):
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    if fmt != "NHWC":
        raise ValueError(f"{n.op} data_format {fmt} unsupported")
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    opname = "maxPool2d" if n.op == "MaxPool" else "avgPool2d"
    out = g._emit("cnn", opname, [x], n.name + "/pool",
                  kernel=(k[1], k[2]), strides=(s[1], s[2]), padding=padding)
    return g._nchw_to_nhwc(out, n.name)


@_rule("Split")
def _split(g, n):
    axis = int(np.atleast_1d(g._const(n, 0))[0])
    num = int(n.attr["num_split"].i)
    outs = g._emit("shape", "splitN", [g._in(n, 1)], n.name, num=num, axis=axis)
    g._register_outputs(n, outs)
    return None


@_rule("SplitV")
def _splitv(g, n):
    sizes = [int(s) for s in g._const(n, 1)]
    axis = int(np.atleast_1d(g._const(n, 2))[0])
    x = g._in(n, 0)
    rank = len(x.shape) if x.shape is not None else None
    if axis < 0:
        if rank is None:
            raise ValueError(f"SplitV {n.name}: negative axis on unknown rank")
        axis += rank
    outs, off = [], 0
    for j, sz in enumerate(sizes):
        sl = [slice(None)] * axis + [slice(off, off + sz)]
        outs.append(g._emit("shape", "stridedSlice", [x], f"{n.name}/s{j}",
                            slices=tuple(sl)))
        off += sz
    g._register_outputs(n, outs)
    return None


@_rule("Fill")
def _fill(g, n):
    dims = tuple(int(d) for d in g._const(n, 0))
    val = g._const(n, 1)
    return g.sd.constant(n.name, np.full(dims, val))


@_rule("Select", "SelectV2")
def _select(g, n):
    return g._emit("shape", "where", [g._in(n, 0), g._in(n, 1), g._in(n, 2)],
                   n.name)


@_rule("AddN")
def _addn(g, n):
    xs = g._ins(n)
    acc = xs[0]
    for j, x in enumerate(xs[1:]):
        acc = g._emit("math", "add", [acc, x],
                      n.name if j == len(xs) - 2 else f"{n.name}/p{j}")
    return acc


@_rule("Rank")
def _rank(g, n):
    return g._emit("shape", "rank", [g._in(n, 0)], n.name)


@_rule("ZerosLike", "OnesLike")
def _fill_like(g, n):
    opname = "zerosLike" if n.op == "ZerosLike" else "onesLike"
    return g._emit("math", opname, [g._in(n, 0)], n.name)


@_rule("While", "StatelessWhile")
def _while_rule(g, n):
    """TF2 functional while: cond/body live in the function library (ref:
    SameDiff InferenceSession Enter/Exit/... — structured lax loop here)."""
    cg = g._import_function(n.attr["cond"].func.name)
    bg = g._import_function(n.attr["body"].func.name)
    loop_vars = g._ins(n)
    outs = g.sd._control_op("while", loop_vars,
                            {"cond_graph": cg, "body_graph": bg}, n.name)
    g._register_outputs(n, outs)
    return None


@_rule("If", "StatelessIf")
def _if_rule(g, n):
    tg = g._import_function(n.attr["then_branch"].func.name)
    fg = g._import_function(n.attr["else_branch"].func.name)
    ins = g._ins(n)
    outs = g.sd._control_op("if", ins,  # ins[0] is the predicate
                            {"true_graph": tg, "false_graph": fg}, n.name)
    g._register_outputs(n, outs)
    return None


@_rule("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(g, n):
    eps = float(n.attr["epsilon"].f or 1e-3)
    x, gamma, beta, mean, var = (g._in(n, i) for i in range(5))
    # NHWC: channel is the last axis
    return g._emit("nn", "batchNorm", [x, mean, var, gamma, beta],
                   n.name, eps=eps, axis=-1)


# ---------------------------------------------------------------------------
# Round-2 widening: high-frequency frozen-graph ops beyond the original set
# (ref: samediff-import-tensorflow per-op mapping rules for the same TF ops).

@_rule("Tile")
def _tile(g, n):
    reps = tuple(int(r) for r in np.atleast_1d(g._const(n, 1)))
    return g._emit("shape", "tile", [g._in(n, 0)], n.name, reps=reps)


@_rule("Range")
def _range(g, n):
    start = float(np.atleast_1d(g._const(n, 0))[0])
    limit = float(np.atleast_1d(g._const(n, 1))[0])
    delta = float(np.atleast_1d(g._const(n, 2))[0])
    out_dtype = n.attr["Tidx"].type if "Tidx" in n.attr else None
    v = g._emit("shape", "arange", [], n.name, start=start, stop=limit,
                step=delta)
    if out_dtype in (3, 9):  # DT_INT32 / DT_INT64
        v = g._emit("shape", "castTo", [v], n.name + "/cast",
                    dtype="int32" if out_dtype == 3 else "int64")
    return v


@_rule("Slice")
def _slice(g, n):
    x = g._in(n, 0)
    begin = [int(b) for b in np.atleast_1d(g._const(n, 1))]
    size = [int(s) for s in np.atleast_1d(g._const(n, 2))]
    # TF size=-1 means "to the end of the dim" — needs a static dim to resolve
    for i, s in enumerate(size):
        if s == -1 and (x.shape is None or x.shape[i] is None):
            raise ValueError(
                f"Slice '{n.name}': size=-1 over dynamic dim {i} cannot be "
                "resolved at import time (shape unknown)")
    size = [x.shape[i] - begin[i] if s == -1 else s
            for i, s in enumerate(size)]
    return g._emit("shape", "slice", [x], n.name, begin=tuple(begin),
                   size=tuple(size))


@_rule("Unpack")
def _unpack(g, n):
    axis = int(n.attr["axis"].i)
    outs = g._emit("shape", "unstack", [g._in(n, 0)], n.name, axis=axis)
    g._register_outputs(n, outs)
    return None


@_rule("ReverseV2")
def _reverse_v2(g, n):
    dims = tuple(int(a) for a in np.atleast_1d(g._const(n, 1)))
    return g._emit("shape", "reverse", [g._in(n, 0)], n.name, dims=dims)


@_rule("Cumsum")
def _cumsum(g, n):
    axis = int(np.atleast_1d(g._const(n, 1))[0])
    exclusive = bool(n.attr["exclusive"].b)
    reverse = bool(n.attr["reverse"].b)
    x = g._in(n, 0)
    if reverse:
        x = g._emit("shape", "reverse", [x], n.name + "/rev_in", dims=(axis,))
    out = g._emit("shape", "cumsum", [x], n.name + "/cs", axis=axis)
    if exclusive:  # shift right by one along axis: out - x
        out = g._emit("math", "sub", [out, x], n.name + "/excl")
    if reverse:
        out = g._emit("shape", "reverse", [out], n.name + "/rev_out",
                      dims=(axis,))
    return out


@_rule("TopKV2")
def _topk(g, n):
    k = int(np.atleast_1d(g._const(n, 1))[0])
    outs = g._emit("math", "topK", [g._in(n, 0)], n.name, k=k)
    g._register_outputs(n, outs)
    return None


@_rule("GatherNd")
def _gather_nd(g, n):
    return g._emit("shape", "gatherNd", [g._in(n, 0), g._in(n, 1)], n.name)


@_rule("ScatterNd")
def _scatter_nd(g, n):
    shape = tuple(int(s) for s in np.atleast_1d(g._const(n, 2)))
    return g._emit("shape", "scatterNd", [g._in(n, 0), g._in(n, 1)], n.name,
                   shape=shape)


@_rule("MirrorPad")
def _mirror_pad(g, n):
    pads = tuple(tuple(int(v) for v in row) for row in g._const(n, 1))
    mode = n.attr["mode"].s.decode() or "REFLECT"
    return g._emit("shape", "mirrorPad", [g._in(n, 0)], n.name,
                   paddings=pads, mode=mode)


@_rule("ClipByValue")
def _clip_by_value(g, n):
    return g._emit("math", "clipByValue",
                   [g._in(n, 0), g._in(n, 1), g._in(n, 2)], n.name)


@_rule("L2Loss")
def _l2_loss(g, n):
    return g._emit("loss", "l2Loss", [g._in(n, 0)], n.name)


@_rule("LRN")
def _lrn(g, n):
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    out = g._emit("nn", "lrn", [x], n.name + "/lrn",
                  depth_radius=int(n.attr["depth_radius"].i or 5),
                  bias=float(n.attr["bias"].f or 1.0),
                  alpha=float(n.attr["alpha"].f or 1.0),
                  beta=float(n.attr["beta"].f or 0.5))
    return g._nchw_to_nhwc(out, n.name)


@_rule("SpaceToBatchND")
def _space_to_batch_nd(g, n):
    block = [int(b) for b in np.atleast_1d(g._const(n, 1))]
    pads = [tuple(int(v) for v in row) for row in np.atleast_2d(g._const(n, 2))]
    # TF layout (N, spatial..., rest) matches the op's contract directly
    return g._emit("cnn", "spaceToBatchNd", [g._in(n, 0)], n.name,
                   block_shape=block, paddings=pads)


@_rule("BatchToSpaceND")
def _batch_to_space_nd(g, n):
    block = [int(b) for b in np.atleast_1d(g._const(n, 1))]
    crops = [tuple(int(v) for v in row) for row in np.atleast_2d(g._const(n, 2))]
    return g._emit("cnn", "batchToSpaceNd", [g._in(n, 0)], n.name,
                   block_shape=block, crops=crops)


@_rule("DepthToSpace")
def _depth_to_space(g, n):
    bs = int(n.attr["block_size"].i)
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    return g._emit("cnn", "depthToSpace", [g._in(n, 0)], n.name,
                   block_size=bs, data_format=fmt)


@_rule("SpaceToDepth")
def _space_to_depth_rule(g, n):
    bs = int(n.attr["block_size"].i)
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    return g._emit("cnn", "spaceToDepth", [g._in(n, 0)], n.name,
                   block_size=bs, data_format=fmt)


@_rule("ResizeBilinear", "ResizeNearestNeighbor")
def _resize(g, n):
    size = tuple(int(s) for s in np.atleast_1d(g._const(n, 1)))
    opname = ("resizeBilinear" if n.op == "ResizeBilinear"
              else "resizeNearest")
    # TF1 graphs carry align_corners / legacy coordinates; TF2 emits
    # half_pixel_centers=true — the op implements all three samplings
    return g._emit("image", opname, [g._in(n, 0)], n.name, size=size,
                   data_format="NHWC",
                   align_corners=bool(n.attr["align_corners"].b),
                   half_pixel_centers=bool(n.attr["half_pixel_centers"].b))


@_rule("Einsum")
def _einsum(g, n):
    eq = n.attr["equation"].s.decode()
    return g._emit("linalg", "einsum", g._ins(n), n.name, equation=eq)
