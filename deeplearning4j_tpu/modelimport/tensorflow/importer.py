"""TF GraphDef -> SameDiff import (ref: nd4j/samediff-import-tensorflow —
TensorflowFrameworkImporter.runImport + per-op MappingProcess rules;
legacy path TFGraphMapper).

Design mirrors the reference's declarative registry: one mapping rule per TF
op type, translating a NodeDef (attrs + const-resolved inputs) into ops from
the shared registry on a SameDiff graph. Layout: TF conv/pool nodes are NHWC;
this framework's cnn ops are NCHW, so rules wrap them in transposes (XLA
fuses/cancels adjacent transposes at compile time — free on TPU, unlike the
reference which carries format flags through every kernel).

The importer resolves Const nodes eagerly so attribute-carrying inputs
(axes, shapes, paddings, perms) become python values, exactly as the
reference's `MappingRule`s pull from initializers.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable

_JNP_DT = {
    1: "float32", 2: "float64", 3: "int32", 4: "uint8", 6: "int8",
    9: "int64", 10: "bool", 14: "bfloat16", 19: "float16",
}


def _clean(name: str) -> str:
    if name.startswith("^"):
        return ""
    return name.split(":")[0]


class TensorflowFrameworkImporter:
    """(ref: org.nd4j.samediff.frameworkimport.tensorflow.importer.
    TensorflowFrameworkImporter)."""

    @staticmethod
    def runImport(graph_def_or_path) -> SameDiff:
        """Import a frozen GraphDef (proto object, serialized bytes, or .pb
        path) into a SameDiff graph (ref: runImport / importFrozenTF)."""
        gd = _load_graphdef(graph_def_or_path)
        return _GraphImporter(gd).run()

    # reference-parity alias (SameDiff.importFrozenTF)
    importFrozenTF = runImport


def _load_graphdef(src):
    from tensorflow.core.framework import graph_pb2
    if isinstance(src, graph_pb2.GraphDef):
        return src
    gd = graph_pb2.GraphDef()
    if isinstance(src, bytes):
        gd.ParseFromString(src)
        return gd
    with open(src, "rb") as f:
        gd.ParseFromString(f.read())
    return gd


class _GraphImporter:
    def __init__(self, gd):
        self.gd = gd
        self.sd = SameDiff.create()
        self.vars: Dict[str, SDVariable] = {}     # tf node name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}   # eagerly-resolved Const values

    # ------------------------------------------------------------- helpers
    def _in(self, node, i) -> SDVariable:
        return self.vars[_clean(node.input[i])]

    def _const(self, node, i) -> np.ndarray:
        name = _clean(node.input[i])
        if name not in self.consts:
            raise ValueError(
                f"input {i} of {node.name} ({node.op}) must be a Const "
                f"(dynamic attribute inputs are not supported)")
        return self.consts[name]

    def _ins(self, node) -> List[SDVariable]:
        return [self.vars[_clean(n)] for n in node.input if _clean(n)]

    def _emit(self, ns: str, opname: str, inputs, name: str, **kwargs) -> SDVariable:
        out = self.sd._op(ns, opname, inputs, name=name, **kwargs)
        return out

    def _nhwc_to_nchw(self, v, name):
        return self._emit("shape", "permute", [v], f"{name}/nchw", axes=(0, 3, 1, 2))

    def _nchw_to_nhwc(self, v, name):
        return self._emit("shape", "permute", [v], f"{name}/nhwc", axes=(0, 2, 3, 1))

    # ----------------------------------------------------------------- run
    def run(self) -> SameDiff:
        import tensorflow as tf
        for node in self.gd.node:
            self._map_node(node, tf)
        return self.sd

    def _map_node(self, node, tf):
        op = node.op
        name = node.name
        sd = self.sd

        if op == "Const":
            val = tf.make_ndarray(node.attr["value"].tensor)
            self.consts[name] = val
            self.vars[name] = sd.constant(name, val)
            return
        if op == "Placeholder":
            shape = None
            if node.attr["shape"].shape.dim:
                shape = tuple(d.size if d.size > 0 else None
                              for d in node.attr["shape"].shape.dim)
            import jax.numpy as jnp
            dt = getattr(jnp, _JNP_DT.get(node.attr["dtype"].type, "float32"))
            self.vars[name] = sd.placeHolder(name, shape=shape, dtype=dt)
            return
        if op in ("Identity", "StopGradient", "PreventGradient", "Snapshot",
                  "CheckNumerics"):
            src = _clean(node.input[0])
            # emit a real node so the TF node name is addressable as a graph
            # output (frozen-fn outputs are typically named "Identity")
            self.vars[name] = self._emit("math", "identity", [self.vars[src]], name)
            if src in self.consts:
                self.consts[name] = self.consts[src]
            return
        if op == "NoOp":
            return

        fn = _RULES.get(op)
        if fn is None:
            raise ValueError(f"TF op '{op}' (node {name}) has no mapping rule "
                             f"(ref: OpMappingRegistry lookup failure)")
        out = fn(self, node)
        if out is not None:
            self.vars[name] = out


# --------------------------------------------------------------- mapping rules

def _rule(*tf_ops):
    def deco(fn):
        for t in tf_ops:
            _RULES[t] = fn
        return fn
    return deco


_RULES: Dict[str, Any] = {}

_BINARY = {
    "Add": ("math", "add"), "AddV2": ("math", "add"), "Sub": ("math", "sub"),
    "Mul": ("math", "mul"), "RealDiv": ("math", "div"), "Div": ("math", "div"),
    "Maximum": ("math", "max"), "Minimum": ("math", "min"),
    "Pow": ("math", "pow"), "FloorDiv": ("math", "floorDiv"),
    "FloorMod": ("math", "floorMod"), "Atan2": ("math", "atan2"),
    "LogicalAnd": ("math", "logicalAnd"), "LogicalOr": ("math", "logicalOr"),
}
_UNARY = {
    "Relu": ("nn", "relu"), "Relu6": ("nn", "relu6"), "Elu": ("nn", "elu"),
    "Selu": ("nn", "selu"), "Sigmoid": ("nn", "sigmoid"),
    "Softplus": ("nn", "softplus"), "Softsign": ("nn", "softsign"),
    "Tanh": ("math", "tanh"), "Exp": ("math", "exp"), "Log": ("math", "log"),
    "Log1p": ("math", "log1p"), "Neg": ("math", "neg"), "Abs": ("math", "abs"),
    "Square": ("math", "square"), "Sqrt": ("math", "sqrt"),
    "Rsqrt": ("math", "rsqrt"), "Erf": ("math", "erf"), "Floor": ("math", "floor"),
    "Ceil": ("math", "ceil"), "Round": ("math", "round"), "Sign": ("math", "sign"),
    "Sin": ("math", "sin"), "Cos": ("math", "cos"), "Tan": ("math", "tan"),
    "Reciprocal": ("math", "reciprocal"), "LogicalNot": ("math", "logicalNot"),
    "IsNan": ("math", "isnan"), "IsInf": ("math", "isinf"),
    "IsFinite": ("math", "isfinite"),
}
_REDUCE = {
    "Mean": "mean", "Sum": "sum", "Max": "max", "Min": "min", "Prod": "prod",
    "All": "all", "Any": "any",
}

for _t, (_ns, _o) in list(_BINARY.items()):
    _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
        ns, o, [g._in(n, 0), g._in(n, 1)], n.name))(_ns, _o)
for _t, (_ns, _o) in list(_UNARY.items()):
    _RULES[_t] = (lambda ns, o: lambda g, n: g._emit(
        ns, o, [g._in(n, 0)], n.name))(_ns, _o)
for _t, _o in list(_REDUCE.items()):
    def _red(g, n, _o=_o):
        axes = g._const(n, 1)
        dims = tuple(int(a) for a in np.atleast_1d(axes))
        keep = bool(n.attr["keep_dims"].b)
        return g._emit("reduce", _o, [g._in(n, 0)], n.name, dims=dims, keepdims=keep)
    _RULES[_t] = _red


@_rule("MatMul")
def _matmul(g, n):
    a, b = g._in(n, 0), g._in(n, 1)
    if n.attr["transpose_a"].b:
        a = g._emit("shape", "permute", [a], n.name + "/ta", axes=(1, 0))
    if n.attr["transpose_b"].b:
        b = g._emit("shape", "permute", [b], n.name + "/tb", axes=(1, 0))
    return g._emit("linalg", "matmul", [a, b], n.name)


@_rule("BatchMatMul", "BatchMatMulV2")
def _bmm(g, n):
    a, b = g._in(n, 0), g._in(n, 1)
    if n.attr["adj_x"].b:
        nd = len(a.shape or (0, 0, 0))
        g_axes = tuple(range(nd - 2)) + (nd - 1, nd - 2)
        a = g._emit("shape", "permute", [a], n.name + "/ta", axes=g_axes)
    if n.attr["adj_y"].b:
        nd = len(b.shape or (0, 0, 0))
        g_axes = tuple(range(nd - 2)) + (nd - 1, nd - 2)
        b = g._emit("shape", "permute", [b], n.name + "/tb", axes=g_axes)
    return g._emit("linalg", "matmul", [a, b], n.name)


@_rule("BiasAdd")
def _bias_add(g, n):
    # NHWC (default): bias broadcasts over the trailing channel dim
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    x, b = g._in(n, 0), g._in(n, 1)
    if fmt == "NCHW":
        raise ValueError("BiasAdd NCHW import unsupported (TF frozen graphs are NHWC)")
    return g._emit("math", "add", [x, b], n.name)


@_rule("Softmax")
def _softmax(g, n):
    return g._emit("nn", "softmax", [g._in(n, 0)], n.name)


@_rule("LeakyRelu")
def _leaky(g, n):
    return g._emit("nn", "leakyRelu", [g._in(n, 0)], n.name,
                   alpha=float(n.attr["alpha"].f or 0.2))


@_rule("Reshape")
def _reshape(g, n):
    shape = tuple(int(s) for s in g._const(n, 1))
    return g._emit("shape", "reshape", [g._in(n, 0)], n.name, shape=shape)


@_rule("Transpose")
def _transpose(g, n):
    perm = tuple(int(p) for p in g._const(n, 1))
    return g._emit("shape", "permute", [g._in(n, 0)], n.name, axes=perm)


@_rule("ExpandDims")
def _expand(g, n):
    axis = int(np.atleast_1d(g._const(n, 1))[0])
    return g._emit("shape", "expandDims", [g._in(n, 0)], n.name, axis=axis)


@_rule("Squeeze")
def _squeeze(g, n):
    dims = tuple(int(d) for d in n.attr["squeeze_dims"].list.i) or None
    return g._emit("shape", "squeeze", [g._in(n, 0)], n.name, axis=dims)


@_rule("ConcatV2")
def _concat(g, n):
    axis = int(np.atleast_1d(g._const(n, len(n.input) - 1))[0])
    xs = [g._in(n, i) for i in range(len(n.input) - 1)]
    return g._emit("shape", "concatN", xs, n.name, axis=axis)


@_rule("Pack")
def _pack(g, n):
    axis = int(n.attr["axis"].i)
    return g._emit("shape", "stackN", g._ins(n), n.name, axis=axis)


@_rule("Pad", "PadV2")
def _pad(g, n):
    pads = tuple(tuple(int(v) for v in row) for row in g._const(n, 1))
    return g._emit("shape", "pad", [g._in(n, 0)], n.name, paddings=pads)


@_rule("GatherV2", "Gather")
def _gather(g, n):
    axis = 0
    if len(n.input) > 2:
        axis = int(np.atleast_1d(g._const(n, 2))[0])
    return g._emit("shape", "gather", [g._in(n, 0), g._in(n, 1)], n.name, axis=axis)


@_rule("Cast")
def _cast(g, n):
    import jax.numpy as jnp
    dt = getattr(jnp, _JNP_DT.get(n.attr["DstT"].type, "float32"))
    return g._emit("shape", "castTo", [g._in(n, 0)], n.name, dtype=dt)


@_rule("ArgMax")
def _argmax(g, n):
    axis = int(np.atleast_1d(g._const(n, 1))[0])
    return g._emit("reduce", "argmax", [g._in(n, 0)], n.name, dims=axis)


@_rule("OneHot")
def _onehot(g, n):
    depth = int(np.atleast_1d(g._const(n, 1))[0])
    on = float(np.atleast_1d(g._const(n, 2))[0])
    off = float(np.atleast_1d(g._const(n, 3))[0])
    return g._emit("shape", "oneHot", [g._in(n, 0)], n.name, depth=depth,
                   on=on, off=off)


@_rule("Shape")
def _shape(g, n):
    return g._emit("shape", "shapeOf", [g._in(n, 0)], n.name)


@_rule("StridedSlice")
def _strided_slice(g, n):
    begin = [int(v) for v in g._const(n, 1)]
    end = [int(v) for v in g._const(n, 2)]
    strides = [int(v) for v in g._const(n, 3)]
    bm = int(n.attr["begin_mask"].i)
    em = int(n.attr["end_mask"].i)
    sm = int(n.attr["shrink_axis_mask"].i)
    nm = int(n.attr["new_axis_mask"].i)
    el = int(n.attr["ellipsis_mask"].i)
    if nm or el:
        raise ValueError("StridedSlice with new_axis/ellipsis masks unsupported")
    slices = []
    for i in range(len(begin)):
        if sm & (1 << i):
            slices.append(begin[i])
            continue
        b = None if bm & (1 << i) else begin[i]
        e = None if em & (1 << i) else end[i]
        slices.append(slice(b, e, strides[i]))
    return g._emit("shape", "stridedSlice", [g._in(n, 0)], n.name,
                   slices=tuple(slices))


@_rule("Conv2D")
def _conv2d(g, n):
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    if fmt != "NHWC":
        raise ValueError(f"Conv2D data_format {fmt} unsupported (frozen TF graphs are NHWC)")
    strides = list(n.attr["strides"].list.i)  # NHWC order
    dil = list(n.attr["dilations"].list.i) or [1, 1, 1, 1]
    padding = n.attr["padding"].s.decode()
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    # kernel HWIO -> OIHW
    w = g._emit("shape", "permute", [g._in(n, 1)], n.name + "/w", axes=(3, 2, 0, 1))
    out = g._emit("cnn", "conv2d", [x, w], n.name + "/conv",
                  strides=(strides[1], strides[2]), padding=padding,
                  dilation=(dil[1], dil[2]))
    return g._nchw_to_nhwc(out, n.name)


@_rule("DepthwiseConv2dNative")
def _depthwise(g, n):
    strides = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    # kernel (kh,kw,C,mult) -> (C*mult, 1, kh, kw); frozen graphs have it const
    kv = g._const(n, 1)
    kh, kw_, C, mult = kv.shape
    w = g.sd.constant(n.name + "/w",
                      kv.transpose(2, 3, 0, 1).reshape(C * mult, 1, kh, kw_))
    out = g._emit("cnn", "depthwiseConv2d", [x, w], n.name + "/conv",
                  strides=(strides[1], strides[2]), padding=padding)
    return g._nchw_to_nhwc(out, n.name)


@_rule("MaxPool", "AvgPool")
def _pool(g, n):
    fmt = n.attr["data_format"].s.decode() or "NHWC"
    if fmt != "NHWC":
        raise ValueError(f"{n.op} data_format {fmt} unsupported")
    k = list(n.attr["ksize"].list.i)
    s = list(n.attr["strides"].list.i)
    padding = n.attr["padding"].s.decode()
    x = g._nhwc_to_nchw(g._in(n, 0), n.name)
    opname = "maxPool2d" if n.op == "MaxPool" else "avgPool2d"
    out = g._emit("cnn", opname, [x], n.name + "/pool",
                  kernel=(k[1], k[2]), strides=(s[1], s[2]), padding=padding)
    return g._nchw_to_nhwc(out, n.name)


@_rule("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(g, n):
    eps = float(n.attr["epsilon"].f or 1e-3)
    x, gamma, beta, mean, var = (g._in(n, i) for i in range(5))
    # NHWC: channel is the last axis
    return g._emit("nn", "batchNorm", [x, mean, var, gamma, beta],
                   n.name, eps=eps, axis=-1)
