"""Hyperparameter optimization (ref: arbiter — arbiter-core's
ParameterSpace/CandidateGenerator/OptimizationConfiguration/
LocalOptimizationRunner + arbiter-deeplearning4j's MultiLayerSpace;
SURVEY.md §2.6).

TPU-first simplification: arbiter serializes candidate configs through JSON
and spins worker threads per candidate; here a candidate is a plain dict of
sampled hyperparameters handed to a user model-builder, and the runner
executes sequentially (XLA already saturates the chip per candidate — the
reference's thread pool parallelized CPU training, which doesn't transfer).
"""
from deeplearning4j_tpu.arbiter.space import (
    BooleanSpace,
    ContinuousParameterSpace,
    DiscreteParameterSpace,
    FixedValue,
    IntegerParameterSpace,
    ParameterSpace,
)
from deeplearning4j_tpu.arbiter.generator import (
    GridSearchCandidateGenerator,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.arbiter.runner import (
    Candidate,
    CandidateResult,
    MaxCandidatesCondition,
    MaxTimeCondition,
    OptimizationConfiguration,
    OptimizationRunner,
    ScoreImprovementCondition,
)

__all__ = [
    "ParameterSpace", "ContinuousParameterSpace", "DiscreteParameterSpace",
    "IntegerParameterSpace", "BooleanSpace", "FixedValue",
    "RandomSearchGenerator", "GridSearchCandidateGenerator",
    "Candidate", "CandidateResult", "OptimizationConfiguration",
    "OptimizationRunner", "MaxCandidatesCondition", "MaxTimeCondition",
    "ScoreImprovementCondition",
]
