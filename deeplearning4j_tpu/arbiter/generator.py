"""Candidate generators (ref: org.deeplearning4j.arbiter.optimize.generator —
RandomSearchGenerator, GridSearchCandidateGenerator with Sequential and
RandomOrder modes)."""
from __future__ import annotations

import itertools
from typing import Dict, Iterator, Optional

import numpy as np

from deeplearning4j_tpu.arbiter.space import ParameterSpace


class RandomSearchGenerator:
    """i.i.d. samples from every space (ref: RandomSearchGenerator)."""

    def __init__(self, spaces: Dict[str, ParameterSpace], seed: int = 0):
        self.spaces = spaces
        self.rng = np.random.RandomState(seed)

    def __iter__(self) -> Iterator[dict]:
        while True:
            yield {k: s.sample(self.rng) for k, s in self.spaces.items()}


class GridSearchCandidateGenerator:
    """Cartesian product over discretized spaces (ref:
    GridSearchCandidateGenerator; ``discretization_count`` mirrors the
    reference's discretizationCount for continuous dims)."""

    def __init__(self, spaces: Dict[str, ParameterSpace],
                 discretization_count: int = 3, order: str = "Sequential",
                 seed: int = 0):
        self.spaces = spaces
        self.count = discretization_count
        self.order = order
        self.seed = seed

    def total(self) -> int:
        n = 1
        for s in self.spaces.values():
            n *= len(s.grid_values(self.count))
        return n

    def __iter__(self) -> Iterator[dict]:
        keys = list(self.spaces)
        grids = [self.spaces[k].grid_values(self.count) for k in keys]
        combos = list(itertools.product(*grids))
        if self.order == "RandomOrder":
            np.random.RandomState(self.seed).shuffle(combos)
        for combo in combos:
            yield dict(zip(keys, combo))
