"""Optimization runner (ref: org.deeplearning4j.arbiter.optimize.runner.
LocalOptimizationRunner + OptimizationConfiguration: candidate generator +
score function + termination conditions -> best candidate; results carry
per-candidate scores/exceptions as the reference's OptimizationResult does)."""
from __future__ import annotations

import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class Candidate:
    index: int
    hyperparameters: Dict[str, Any]


@dataclass
class CandidateResult:
    candidate: Candidate
    score: Optional[float]
    duration_sec: float
    exception: Optional[str] = None
    model: Any = None


class MaxCandidatesCondition:
    """(ref: MaxCandidatesCondition)."""

    def __init__(self, n: int):
        self.n = n

    def terminate(self, runner) -> bool:
        return len(runner.results) >= self.n


class MaxTimeCondition:
    """(ref: MaxTimeCondition)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def terminate(self, runner) -> bool:
        return (time.monotonic() - runner._start) >= self.seconds


class ScoreImprovementCondition:
    """Stop after N candidates without best-score improvement."""

    def __init__(self, patience: int):
        self.patience = patience

    def terminate(self, runner) -> bool:
        best = runner.bestResult()
        if best is None:
            return False
        since = len(runner.results) - 1 - best.candidate.index
        return since >= self.patience


@dataclass
class OptimizationConfiguration:
    """(ref: OptimizationConfiguration.Builder). ``model_builder(hp) -> model``
    and ``score_function(model, hp) -> float`` replace the reference's
    TaskCreator/ScoreFunction SPI pair; minimize_score as in the reference's
    ScoreFunction.minimize()."""
    candidate_generator: Any = None
    model_builder: Callable[[dict], Any] = None
    score_function: Callable[[Any, dict], float] = None
    termination_conditions: List[Any] = field(default_factory=list)
    minimize_score: bool = True


class OptimizationRunner:
    """(ref: LocalOptimizationRunner.execute). Sequential candidate loop —
    see package docstring for why the reference's worker pool is dropped."""

    def __init__(self, config: OptimizationConfiguration, listeners=()):
        self.config = config
        self.results: List[CandidateResult] = []
        self.listeners = list(listeners)
        self._start = None

    def execute(self) -> CandidateResult:
        cfg = self.config
        assert cfg.candidate_generator is not None
        assert cfg.termination_conditions, "at least one termination condition"
        self._start = time.monotonic()
        for i, hp in enumerate(cfg.candidate_generator):
            cand = Candidate(i, hp)
            t0 = time.monotonic()
            try:
                model = cfg.model_builder(hp)
                score = float(cfg.score_function(model, hp))
                res = CandidateResult(cand, score, time.monotonic() - t0,
                                      model=model)
            except Exception:
                res = CandidateResult(cand, None, time.monotonic() - t0,
                                      exception=traceback.format_exc())
            self.results.append(res)
            for lst in self.listeners:
                lst(res)
            if any(tc.terminate(self) for tc in cfg.termination_conditions):
                break
        best = self.bestResult()
        if best is None:
            raise RuntimeError("no candidate produced a score; last error:\n"
                               + (self.results[-1].exception or "<none>"))
        return best

    def bestResult(self) -> Optional[CandidateResult]:
        scored = [r for r in self.results if r.score is not None]
        if not scored:
            return None
        key = (min if self.config.minimize_score else max)
        return key(scored, key=lambda r: r.score)

    def numCandidatesCompleted(self) -> int:
        return len(self.results)

    def numCandidatesFailed(self) -> int:
        return sum(1 for r in self.results if r.exception is not None)
