"""Parameter spaces (ref: org.deeplearning4j.arbiter.optimize.parameter —
ContinuousParameterSpace, DiscreteParameterSpace, IntegerParameterSpace,
FixedValue; log-uniform matches the reference's
ContinuousParameterSpace(min, max) + logUniform flag)."""
from __future__ import annotations

from typing import Any, List, Sequence

import numpy as np


class ParameterSpace:
    def sample(self, rng: np.random.RandomState) -> Any:
        raise NotImplementedError

    def grid_values(self, points: int) -> List[Any]:
        """Discretization for grid search (ref: GridSearchCandidateGenerator
        discretizes continuous spaces into ``discretizationCount`` points)."""
        raise NotImplementedError


class ContinuousParameterSpace(ParameterSpace):
    def __init__(self, min_value: float, max_value: float, log_uniform: bool = False):
        assert max_value > min_value
        if log_uniform:
            assert min_value > 0, "log-uniform needs positive bounds"
        self.lo, self.hi, self.log = min_value, max_value, log_uniform

    def sample(self, rng):
        if self.log:
            return float(np.exp(rng.uniform(np.log(self.lo), np.log(self.hi))))
        return float(rng.uniform(self.lo, self.hi))

    def grid_values(self, points):
        if self.log:
            return [float(v) for v in np.exp(np.linspace(np.log(self.lo),
                                                         np.log(self.hi), points))]
        return [float(v) for v in np.linspace(self.lo, self.hi, points)]


class IntegerParameterSpace(ParameterSpace):
    def __init__(self, min_value: int, max_value: int):
        self.lo, self.hi = min_value, max_value

    def sample(self, rng):
        return int(rng.randint(self.lo, self.hi + 1))

    def grid_values(self, points):
        vals = np.unique(np.linspace(self.lo, self.hi, points).round().astype(int))
        return [int(v) for v in vals]


class DiscreteParameterSpace(ParameterSpace):
    def __init__(self, values: Sequence):
        self.values = list(values)

    def sample(self, rng):
        return self.values[rng.randint(len(self.values))]

    def grid_values(self, points):
        return list(self.values)


class BooleanSpace(DiscreteParameterSpace):
    def __init__(self):
        super().__init__([False, True])


class FixedValue(ParameterSpace):
    def __init__(self, value):
        self.value = value

    def sample(self, rng):
        return self.value

    def grid_values(self, points):
        return [self.value]
