"""Deep Q-learning (ref: org.deeplearning4j.rl4j.learning.sync.qlearning.
discrete.QLearningDiscreteDense + QLearning.QLConfiguration).

TPU redesign: rl4j's learner steps fetch/fit through the ND4J graph per
minibatch with a separate target-network copy held as a second network
object. Here the Q-network is the nn framework's layer stack applied purely
(params in, Q out), the target network is just a second param pytree, and
one jitted executable computes TD targets (double-DQN or vanilla), gathers
the taken-action Q, and applies the optax update — env stepping is the only
host-side work.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.rl.env import MDP
from deeplearning4j_tpu.rl.policy import EpsGreedy, GreedyPolicy
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition


@dataclass
class QLearningConfiguration:
    """(ref: QLearning.QLConfiguration builder)."""
    seed: int = 0
    gamma: float = 0.99
    batchSize: int = 32
    expRepMaxSize: int = 10000
    targetDqnUpdateFreq: int = 100
    updateStart: int = 64          # env steps before learning begins
    trainFreq: int = 1             # learn every N env steps
    doubleDQN: bool = True
    minEpsilon: float = 0.05
    epsilonNbStep: int = 1000
    maxStep: int = 5000            # total env steps
    maxEpochStep: int = 500        # per-episode cap
    errorClamp: Optional[float] = 1.0  # huber-style TD clamp (ref: errorClamp)


class QLearningDiscreteDense:
    """(ref: QLearningDiscreteDense — dense-observation discrete-action DQN)."""

    def __init__(self, mdp: MDP, net_conf, config: QLearningConfiguration):
        self.mdp = mdp
        self.config = config
        self.net = (net_conf if isinstance(net_conf, MultiLayerNetwork)
                    else MultiLayerNetwork(net_conf).init())
        self._params = self.net._params
        self._target = jax.tree.map(jnp.array, self._params)
        self._state = self.net._state
        self._tx = self.net.conf.updater.to_optax()
        self._opt_state = self._tx.init(self._params)
        self.replay = ExpReplay(config.expRepMaxSize, mdp.obs_size,
                                seed=config.seed)
        self.policy = EpsGreedy(config.minEpsilon, config.epsilonNbStep,
                                seed=config.seed)
        self._jit_q = jax.jit(self._q_fn)
        self._jit_update = jax.jit(self._update_fn)
        self.episode_rewards: List[float] = []
        self._steps = 0

    # ---------------------------------------------------------------- pure
    def _q_fn(self, params, obs):
        out, _, _ = self.net._forward(params, self._state, obs,
                                      training=False, rng=None)
        return out

    def _update_fn(self, params, target, opt_state, obs, actions, rewards,
                   next_obs, dones):
        cfg = self.config
        q_next_target = self._q_fn(target, next_obs)
        if cfg.doubleDQN:
            # online net picks the argmax, target net evaluates it
            sel = jnp.argmax(self._q_fn(params, next_obs), axis=-1)
            q_next = jnp.take_along_axis(q_next_target, sel[:, None], -1)[:, 0]
        else:
            q_next = q_next_target.max(-1)
        td_target = rewards + cfg.gamma * q_next * (1.0 - dones)
        td_target = jax.lax.stop_gradient(td_target)

        def loss_fn(p):
            q = self._q_fn(p, obs)
            q_sel = jnp.take_along_axis(q, actions[:, None].astype(jnp.int32), -1)[:, 0]
            err = q_sel - td_target
            if cfg.errorClamp is not None:
                # huber: quadratic within the clamp, linear outside
                c = cfg.errorClamp
                ae = jnp.abs(err)
                return jnp.mean(jnp.where(ae <= c, 0.5 * err ** 2,
                                          c * (ae - 0.5 * c)))
            return jnp.mean(err ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self._tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # ------------------------------------------------------------ training
    def q_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._jit_q(self._params, jnp.asarray(obs[None])))[0]

    def train(self) -> List[float]:
        """Run until maxStep env steps; returns per-episode rewards
        (ref: ILearning.train + TrainingListener loop)."""
        cfg = self.config
        while self._steps < cfg.maxStep:
            obs = self.mdp.reset()
            ep_reward, ep_steps = 0.0, 0
            while True:
                action = self.policy.select(self.q_values(obs))
                next_obs, reward, done, _ = self.mdp.step(action)
                self.replay.store(Transition(obs, action, reward, next_obs, done))
                obs = next_obs
                ep_reward += reward
                ep_steps += 1
                self._steps += 1
                if (len(self.replay) >= max(cfg.updateStart, cfg.batchSize)
                        and self._steps % cfg.trainFreq == 0):
                    b = self.replay.sample(cfg.batchSize)
                    self._params, self._opt_state, _ = self._jit_update(
                        self._params, self._target, self._opt_state,
                        *(jnp.asarray(x) for x in b))
                if self._steps % cfg.targetDqnUpdateFreq == 0:
                    self._target = jax.tree.map(jnp.array, self._params)
                if done or ep_steps >= cfg.maxEpochStep or self._steps >= cfg.maxStep:
                    break
            self.episode_rewards.append(ep_reward)
        self.net._params = self._params  # expose learned weights on the net
        return self.episode_rewards

    def getPolicy(self) -> GreedyPolicy:
        return GreedyPolicy()

    def play(self, max_steps: Optional[int] = None) -> float:
        """One greedy episode (ref: Policy.play)."""
        obs = self.mdp.reset()
        total, steps = 0.0, 0
        cap = max_steps or self.config.maxEpochStep
        while steps < cap:
            action = int(np.argmax(self.q_values(obs)))
            obs, reward, done, _ = self.mdp.step(action)
            total += reward
            steps += 1
            if done:
                break
        return total
