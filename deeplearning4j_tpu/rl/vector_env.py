"""Vectorized environment execution (the TPU-native stand-in for rl4j's
async worker threads — ref: org.deeplearning4j.rl4j.learning.async.
AsyncLearning + AsyncThread, where N threads each own an MDP instance and
race gradients into a shared global network).

On TPU the redesign inverts control: N MDP instances step in lockstep on the
host while ONE jitted network evaluates/updates over the whole (N, obs)
batch — same experience parallelism, no gradient staleness, and every network
call is a single fused device program instead of N racing ones (SURVEY.md
§2.9 P12 discusses the same hogwild→batched translation for word2vec).
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.rl.env import MDP


class VectorizedMDP:
    """Steps N independent MDP instances as one batched environment.

    Auto-reset semantics (the standard vector-env contract): when instance i
    finishes an episode, ``step`` returns ``done[i]=True`` with the FRESH
    reset observation in ``obs[i]``, and the finished episode's total reward
    in ``infos[i]["episode_reward"]``.
    """

    def __init__(self, env_fns: Sequence[Callable[[], MDP]]):
        if not env_fns:
            raise ValueError("need at least one env factory")
        self.envs: List[MDP] = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.obs_size = self.envs[0].obs_size
        self.n_actions = self.envs[0].n_actions
        self._ep_reward = np.zeros(self.num_envs, np.float64)
        self._ep_steps = np.zeros(self.num_envs, np.int64)

    def reset(self) -> np.ndarray:
        self._ep_reward[:] = 0.0
        self._ep_steps[:] = 0
        return np.stack([e.reset() for e in self.envs]).astype(np.float32)

    def step(self, actions: Sequence[int], max_episode_steps: int = 0
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        """actions: one int per env. ``max_episode_steps`` > 0 additionally
        truncates episodes (reported via info["truncated"], done stays the
        env's own signal so learners can bootstrap through time limits)."""
        obs = np.empty((self.num_envs, self.obs_size), np.float32)
        rewards = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, bool)
        infos: List[dict] = [{} for _ in range(self.num_envs)]
        for i, (env, a) in enumerate(zip(self.envs, actions)):
            o, r, d, info = env.step(int(a))
            rewards[i] = r
            self._ep_reward[i] += r
            self._ep_steps[i] += 1
            truncated = bool(max_episode_steps
                             and self._ep_steps[i] >= max_episode_steps)
            if d or truncated:
                # final_obs: the episode's true last observation — learners
                # must bootstrap from THIS on truncation, never from the
                # fresh reset obs returned in obs[i]
                infos[i] = dict(info, episode_reward=float(self._ep_reward[i]),
                                episode_steps=int(self._ep_steps[i]),
                                truncated=truncated and not d,
                                final_obs=np.asarray(o, np.float32))
                self._ep_reward[i] = 0.0
                self._ep_steps[i] = 0
                o = env.reset()
            dones[i] = d
            obs[i] = o
        return obs, rewards, dones, infos

    def close(self):
        for e in self.envs:
            e.close()


def collect_rollout(venv: VectorizedMDP, obs: np.ndarray, select_actions,
                    n_steps: int, max_episode_steps: int,
                    episode_rewards: list):
    """Run ``n_steps`` lockstep vector steps (shared by the n-step Q and
    A2C/A3C learners so their terminal/truncation bookkeeping cannot drift).

    ``select_actions(obs) -> (N,) actions``. Completed-episode rewards are
    appended to ``episode_rewards``. Returns
    ``(obs, ro, ra, rr, rd, rtrunc, tobs)`` where ``rtrunc``/``tobs`` mark
    truncated streams and their pre-reset final observations (see
    returns.nstep_returns for why the chain must break there).
    """
    S, N = n_steps, venv.num_envs
    ro = np.empty((S, N, venv.obs_size), np.float32)
    ra = np.empty((S, N), np.int64)
    rr = np.empty((S, N), np.float32)
    rd = np.empty((S, N), bool)
    rtrunc = np.zeros((S, N), bool)
    tobs = np.zeros((S, N, venv.obs_size), np.float32)
    for t in range(S):
        actions = select_actions(obs)
        ro[t], ra[t] = obs, actions
        obs, rr[t], rd[t], infos = venv.step(
            actions, max_episode_steps=max_episode_steps)
        for i, info in enumerate(infos):
            if "episode_reward" in info:
                episode_rewards.append(info["episode_reward"])
            if info.get("truncated"):
                rtrunc[t, i] = True
                tobs[t, i] = info["final_obs"]
    return obs, ro, ra, rr, rd, rtrunc, tobs
