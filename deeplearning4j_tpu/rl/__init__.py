"""Reinforcement learning (ref: rl4j — rl4j-core's QLearningDiscreteDense /
A3CDiscreteDense, ExpReplay, EpsGreedy policies, MDP SPI; SURVEY.md §2.5).

TPU-first redesign: rl4j threads actor/learner Java objects and steps the
network op-by-op; here the environment SPI stays host-side python (gym-shaped)
while every learning update — TD targets, double-DQN argmax/gather, advantage
actor-critic — is ONE jitted XLA executable over the nn framework's layer
forward. Replay sampling is vectorized numpy into device batches.
"""
from deeplearning4j_tpu.rl.env import MDP, CartPole, ChainMDP
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.rl.policy import BoltzmannPolicy, EpsGreedy, GreedyPolicy
from deeplearning4j_tpu.rl.qlearning import QLearningConfiguration, QLearningDiscreteDense
from deeplearning4j_tpu.rl.a2c import A2CConfiguration, A2CDiscreteDense

__all__ = [
    "MDP", "CartPole", "ChainMDP",
    "ExpReplay", "Transition",
    "EpsGreedy", "GreedyPolicy", "BoltzmannPolicy",
    "QLearningConfiguration", "QLearningDiscreteDense",
    "A2CConfiguration", "A2CDiscreteDense",
]
