"""Reinforcement learning (ref: rl4j — rl4j-core's QLearningDiscreteDense /
A3CDiscreteDense, ExpReplay, EpsGreedy policies, MDP SPI; SURVEY.md §2.5).

TPU-first redesign: rl4j threads actor/learner Java objects and steps the
network op-by-op; here the environment SPI stays host-side python (gym-shaped)
while every learning update — TD targets, double-DQN argmax/gather, advantage
actor-critic — is ONE jitted XLA executable over the nn framework's layer
forward. Replay sampling is vectorized numpy into device batches.
"""
from deeplearning4j_tpu.rl.env import MDP, CartPole, ChainMDP, MountainCar, GymEnvAdapter
from deeplearning4j_tpu.rl.replay import ExpReplay, Transition
from deeplearning4j_tpu.rl.policy import BoltzmannPolicy, EpsGreedy, GreedyPolicy
from deeplearning4j_tpu.rl.qlearning import QLearningConfiguration, QLearningDiscreteDense
from deeplearning4j_tpu.rl.a2c import A2CConfiguration, A2CDiscreteDense
from deeplearning4j_tpu.rl.vector_env import VectorizedMDP
from deeplearning4j_tpu.rl.nstep_q import (
    AsyncNStepQLearningDiscreteDense, AsyncQLearningConfiguration)

# A3C parity name (ref: A3CDiscreteDense): the vectorized-sync A2C with
# numEnvs > 1 carries the same N experience streams minus gradient staleness.
A3CDiscreteDense = A2CDiscreteDense
A3CConfiguration = A2CConfiguration

__all__ = [
    "MDP", "CartPole", "ChainMDP", "MountainCar", "GymEnvAdapter",
    "ExpReplay", "Transition",
    "EpsGreedy", "GreedyPolicy", "BoltzmannPolicy",
    "QLearningConfiguration", "QLearningDiscreteDense",
    "A2CConfiguration", "A2CDiscreteDense",
    "A3CConfiguration", "A3CDiscreteDense",
    "VectorizedMDP",
    "AsyncQLearningConfiguration", "AsyncNStepQLearningDiscreteDense",
]
