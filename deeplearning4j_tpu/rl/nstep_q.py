"""n-step Q-learning over vectorized environments (ref: org.deeplearning4j.
rl4j.learning.async.nstep.discrete.AsyncNStepQLearningDiscreteDense +
AsyncNStepQLConfiguration).

The reference's async design: ``numThreads`` workers each roll ``nStep``
transitions on a private MDP, compute gradients against a shared global
network, and apply them asynchronously (hogwild-over-JVM). The TPU redesign
keeps the same data flow — N parallel experience streams, n-step bootstrapped
targets, one shared network — but synchronously: a ``VectorizedMDP`` steps N
envs in lockstep, action selection is ONE batched jitted Q evaluation, and
each rollout produces ONE fused update over the (N*nStep) batch. Equivalent
sample parallelism, zero gradient staleness (the async variant's staleness is
an artifact of JVM threading, not an algorithmic feature worth reproducing).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.rl.env import MDP
from deeplearning4j_tpu.rl.returns import nstep_returns
from deeplearning4j_tpu.rl.vector_env import VectorizedMDP, collect_rollout


@dataclass
class AsyncQLearningConfiguration:
    """(ref: AsyncNStepQLConfiguration builder; numThreads -> numEnvs)."""
    seed: int = 0
    gamma: float = 0.99
    nStep: int = 5                  # rollout length per update
    numEnvs: int = 4                # experience-stream parallelism (ref: numThreads)
    targetDqnUpdateFreq: int = 100  # env steps between target-net syncs
    minEpsilon: float = 0.05
    epsilonNbStep: int = 1000
    maxStep: int = 5000             # total env steps across all envs
    maxEpochStep: int = 500         # per-episode cap (truncation, bootstrapped)
    errorClamp: Optional[float] = 1.0


class AsyncNStepQLearningDiscreteDense:
    """(ref: AsyncNStepQLearningDiscreteDense — class name kept for parity;
    see module docstring for the sync-vectorized redesign)."""

    def __init__(self, mdp_fn: Union[Callable[[], MDP], VectorizedMDP],
                 net_conf, config: AsyncQLearningConfiguration):
        self.config = config
        if isinstance(mdp_fn, VectorizedMDP):
            self.venv = mdp_fn
        elif callable(mdp_fn) and not isinstance(mdp_fn, MDP):
            self.venv = VectorizedMDP([mdp_fn for _ in range(config.numEnvs)])
        else:
            raise ValueError("pass an env factory (lambda: MyMDP()) or a "
                             "VectorizedMDP, not a single MDP instance")
        self.net = (net_conf if isinstance(net_conf, MultiLayerNetwork)
                    else MultiLayerNetwork(net_conf).init())
        self._params = self.net._params
        self._target = jax.tree.map(jnp.array, self._params)
        self._net_state = self.net._state
        self._tx = self.net.conf.updater.to_optax()
        self._opt_state = self._tx.init(self._params)
        self._jit_q = jax.jit(self._q_fn)
        self._jit_update = jax.jit(self._update_fn)
        self.rng = np.random.RandomState(config.seed)
        self.episode_rewards: List[float] = []
        self._steps = 0      # total env steps (all envs)
        self._eps_steps = 0  # epsilon-annealing counter (advances per selection)

    # ---------------------------------------------------------------- pure
    def _q_fn(self, params, obs):
        out, _, _ = self.net._forward(params, self._net_state, obs,
                                      training=False, rng=None)
        return out

    def _update_fn(self, params, opt_state, obs, actions, returns):
        cfg = self.config

        def loss_fn(p):
            q = self._q_fn(p, obs)
            q_sel = jnp.take_along_axis(q, actions[:, None], -1)[:, 0]
            err = q_sel - returns
            if cfg.errorClamp is not None:
                c = cfg.errorClamp
                ae = jnp.abs(err)
                return jnp.mean(jnp.where(ae <= c, 0.5 * err ** 2,
                                          c * (ae - 0.5 * c)))
            return jnp.mean(err ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self._tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # ------------------------------------------------------------ training
    def _epsilon(self) -> float:
        # annealed on its own per-selection counter so the schedule advances
        # every vector step, not once per rollout (self._steps updates only
        # after collect_rollout returns)
        frac = min(self._eps_steps / max(self.config.epsilonNbStep, 1), 1.0)
        return 1.0 + (self.config.minEpsilon - 1.0) * frac

    def _select_actions(self, obs: np.ndarray) -> np.ndarray:
        """Batched eps-greedy: ONE device call scores all envs."""
        q = np.asarray(self._jit_q(self._params, jnp.asarray(obs)))
        greedy = q.argmax(-1)
        explore = self.rng.rand(len(obs)) < self._epsilon()
        self._eps_steps += len(obs)
        randoms = self.rng.randint(self.venv.n_actions, size=len(obs))
        return np.where(explore, randoms, greedy).astype(np.int64)

    def train(self) -> List[float]:
        cfg = self.config
        N, S = self.venv.num_envs, cfg.nStep
        obs = self.venv.reset()
        last_sync = 0
        while self._steps < cfg.maxStep:
            obs, ro, ra, rr, rd, rtrunc, tobs = collect_rollout(
                self.venv, obs, self._select_actions, S, cfg.maxEpochStep,
                self.episode_rewards)
            self._steps += S * N
            # ---- n-step bootstrapped returns per env (one batched target
            # eval for the rollout tail + every truncation point)
            boot = np.asarray(self._jit_q(self._target, jnp.asarray(obs))).max(-1)
            if rtrunc.any():
                qtrunc = np.asarray(self._jit_q(
                    self._target, jnp.asarray(tobs.reshape(S * N, -1)))
                ).max(-1).reshape(S, N)
            else:  # no truncation this rollout — skip the masked-out eval
                qtrunc = np.zeros((S, N), np.float32)
            returns = nstep_returns(rr, rd, rtrunc, boot, qtrunc, cfg.gamma)
            # ---- one fused update over the (S*N) batch
            self._params, self._opt_state, _ = self._jit_update(
                self._params, self._opt_state,
                jnp.asarray(ro.reshape(S * N, -1)),
                jnp.asarray(ra.reshape(S * N).astype(np.int32)),
                jnp.asarray(returns.reshape(S * N)))
            if self._steps - last_sync >= cfg.targetDqnUpdateFreq:
                self._target = jax.tree.map(jnp.array, self._params)
                last_sync = self._steps
        self.net._params = self._params
        return self.episode_rewards

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._jit_q(self._params, jnp.asarray(obs[None])))[0]

    def play(self, max_steps: Optional[int] = None) -> float:
        """One greedy episode on a fresh single env."""
        env = self.venv.envs[0]
        obs = env.reset()
        total, steps = 0.0, 0
        cap = max_steps or self.config.maxEpochStep
        while steps < cap:
            obs, reward, done, _ = env.step(int(np.argmax(self.q_values(obs))))
            total += reward
            steps += 1
            if done:
                break
        return total
