"""Advantage actor-critic (ref: org.deeplearning4j.rl4j.learning.async.a3c.
discrete.A3CDiscreteDense — the synchronous-batch equivalent: rl4j's async
workers exist to parallelize CPU gradient computation, which a single fused
XLA update makes unnecessary; SURVEY.md §2.5 notes A3C's async machinery is
deleted by design on TPU).

One jitted executable per update: n-step returns, advantage, policy-gradient
loss with entropy bonus, value MSE — both heads updated together.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.rl.env import MDP
from deeplearning4j_tpu.rl.returns import nstep_returns
from deeplearning4j_tpu.rl.vector_env import collect_rollout


@dataclass
class A2CConfiguration:
    """(ref: A3CConfiguration builder; numThreads -> numEnvs)."""
    seed: int = 0
    gamma: float = 0.99
    nStep: int = 32                # rollout length per update
    entropyCoef: float = 0.01
    valueCoef: float = 0.5
    maxStep: int = 5000
    maxEpochStep: int = 500
    numEnvs: int = 1               # >1: lockstep vectorized rollouts (the
                                   # sync stand-in for A3C's worker threads)


class A2CDiscreteDense:
    """Policy net (softmax over actions) + value net (scalar), both dense
    layer stacks from the nn config DSL."""

    def __init__(self, mdp, policy_conf, value_conf, config: A2CConfiguration):
        """``mdp``: an MDP instance (numEnvs=1), or an env factory callable /
        VectorizedMDP when config.numEnvs > 1."""
        self.config = config
        self.venv = None
        if config.numEnvs > 1:
            from deeplearning4j_tpu.rl.vector_env import VectorizedMDP
            if isinstance(mdp, VectorizedMDP):
                self.venv = mdp
            elif callable(mdp) and not isinstance(mdp, MDP):
                self.venv = VectorizedMDP([mdp for _ in range(config.numEnvs)])
            else:
                raise ValueError("numEnvs > 1 needs an env factory or "
                                 "VectorizedMDP, not a single MDP instance")
            self.mdp = self.venv.envs[0]
        else:
            self.mdp = mdp() if (callable(mdp) and not isinstance(mdp, MDP)) else mdp
        self.pi_net = (policy_conf if isinstance(policy_conf, MultiLayerNetwork)
                       else MultiLayerNetwork(policy_conf).init())
        self.v_net = (value_conf if isinstance(value_conf, MultiLayerNetwork)
                      else MultiLayerNetwork(value_conf).init())
        self._pi = self.pi_net._params
        self._v = self.v_net._params
        self._tx = self.pi_net.conf.updater.to_optax()
        self._opt = self._tx.init({"pi": self._pi, "v": self._v})
        self._jit_update = jax.jit(self._update_fn)
        self._jit_probs = jax.jit(self._probs_fn)
        self._jit_value = jax.jit(self._value_fn)
        self.rng = np.random.RandomState(config.seed)
        self.episode_rewards: List[float] = []
        self._steps = 0

    def _probs_fn(self, pi_params, obs):
        out, _, _ = self.pi_net._forward(pi_params, self.pi_net._state, obs,
                                         training=False, rng=None)
        return out

    def _value_fn(self, v_params, obs):
        out, _, _ = self.v_net._forward(v_params, self.v_net._state, obs,
                                        training=False, rng=None)
        return out[:, 0]

    def _update_fn(self, params, opt_state, obs, actions, returns):
        cfg = self.config

        def loss_fn(p):
            probs = self._probs_fn(p["pi"], obs)
            logp = jnp.log(jnp.clip(probs, 1e-8))
            values = self._value_fn(p["v"], obs)
            adv = jax.lax.stop_gradient(returns - values)
            sel_logp = jnp.take_along_axis(logp, actions[:, None], -1)[:, 0]
            policy_loss = -jnp.mean(sel_logp * adv)
            entropy = -jnp.mean(jnp.sum(probs * logp, -1))
            value_loss = jnp.mean((returns - values) ** 2)
            return (policy_loss + cfg.valueCoef * value_loss
                    - cfg.entropyCoef * entropy)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = self._tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def action_probs(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._jit_probs(self._pi, jnp.asarray(obs[None])))[0]

    def train(self) -> List[float]:
        if self.venv is not None:
            return self._train_vectorized()
        cfg = self.config
        obs = self.mdp.reset()
        ep_reward, ep_steps = 0.0, 0
        buf_obs, buf_act, buf_rew, buf_done = [], [], [], []
        while self._steps < cfg.maxStep:
            p = self.action_probs(obs)
            action = int(self.rng.choice(len(p), p=p / p.sum()))
            next_obs, reward, done, _ = self.mdp.step(action)
            buf_obs.append(obs); buf_act.append(action)
            buf_rew.append(reward); buf_done.append(done)
            obs = next_obs
            ep_reward += reward
            ep_steps += 1
            self._steps += 1
            episode_over = done or ep_steps >= cfg.maxEpochStep
            if len(buf_obs) >= cfg.nStep or episode_over:
                # n-step discounted returns, bootstrapped from V(s_T).
                # Time-limit truncation is NOT a terminal: bootstrap there
                # too, else the value head trains toward 0 exactly where the
                # agent survives longest
                if done:
                    boot = 0.0
                else:
                    boot = float(np.asarray(self._jit_value(
                        self._v, jnp.asarray(obs[None])))[0])
                R = boot
                returns = np.zeros(len(buf_rew), np.float32)
                for i in reversed(range(len(buf_rew))):
                    R = buf_rew[i] + cfg.gamma * R * (1.0 - float(buf_done[i]))
                    returns[i] = R
                params = {"pi": self._pi, "v": self._v}
                params, self._opt, _ = self._jit_update(
                    params, self._opt, jnp.asarray(np.stack(buf_obs)),
                    jnp.asarray(np.array(buf_act, np.int32)),
                    jnp.asarray(returns))
                self._pi, self._v = params["pi"], params["v"]
                buf_obs, buf_act, buf_rew, buf_done = [], [], [], []
            if episode_over:
                self.episode_rewards.append(ep_reward)
                obs = self.mdp.reset()
                ep_reward, ep_steps = 0.0, 0
        self.pi_net._params = self._pi
        self.v_net._params = self._v
        return self.episode_rewards

    def _train_vectorized(self) -> List[float]:
        """Lockstep N-env rollouts (ref: A3C's numThreads workers — same
        experience parallelism, one batched policy eval + one fused update
        per rollout instead of N async racing gradients)."""
        cfg = self.config
        N, S = self.venv.num_envs, cfg.nStep
        obs = self.venv.reset()

        def select_actions(o):
            probs = np.asarray(self._jit_probs(self._pi, jnp.asarray(o)))
            probs = probs / probs.sum(-1, keepdims=True)
            # per-env categorical sample via inverse-CDF (one rand per env)
            cdf = probs.cumsum(-1)
            u = self.rng.rand(N, 1)
            return (u > cdf[:, :-1]).sum(-1)

        while self._steps < cfg.maxStep:
            obs, ro, ra, rr, rd, rtrunc, tobs = collect_rollout(
                self.venv, obs, select_actions, S, cfg.maxEpochStep,
                self.episode_rewards)
            self._steps += S * N
            # bootstrap: V(s_T) at the rollout tail, 0 at terminals,
            # V(final_obs) at truncation points
            boot = np.asarray(self._jit_value(self._v, jnp.asarray(obs)))
            if rtrunc.any():
                vtrunc = np.asarray(self._jit_value(
                    self._v, jnp.asarray(tobs.reshape(S * N, -1)))).reshape(S, N)
            else:  # no truncation this rollout — skip the masked-out eval
                vtrunc = np.zeros((S, N), np.float32)
            returns = nstep_returns(rr, rd, rtrunc, boot, vtrunc, cfg.gamma)
            params = {"pi": self._pi, "v": self._v}
            params, self._opt, _ = self._jit_update(
                params, self._opt, jnp.asarray(ro.reshape(S * N, -1)),
                jnp.asarray(ra.reshape(S * N).astype(np.int32)),
                jnp.asarray(returns.reshape(S * N)))
            self._pi, self._v = params["pi"], params["v"]
        self.pi_net._params = self._pi
        self.v_net._params = self._v
        return self.episode_rewards

    def play(self, max_steps=None) -> float:
        obs = self.mdp.reset()
        total, steps = 0.0, 0
        cap = max_steps or self.config.maxEpochStep
        while steps < cap:
            action = int(np.argmax(self.action_probs(obs)))
            obs, reward, done, _ = self.mdp.step(action)
            total += reward
            steps += 1
            if done:
                break
        return total
