"""Action-selection policies (ref: org.deeplearning4j.rl4j.policy —
EpsGreedy, Policy/ACPolicy, BoltzmannQ)."""
from __future__ import annotations

import numpy as np


class GreedyPolicy:
    """argmax-Q (ref: DQNPolicy)."""

    def select(self, q_values: np.ndarray, rng=None) -> int:
        return int(np.argmax(q_values))


class EpsGreedy:
    """Annealed epsilon-greedy (ref: rl4j EpsGreedy: epsilon decays linearly
    from 1.0 to minEpsilon over epsilonNbStep steps)."""

    def __init__(self, min_epsilon: float = 0.05, anneal_steps: int = 1000,
                 seed: int = 0):
        self.min_epsilon = min_epsilon
        self.anneal_steps = max(anneal_steps, 1)
        self.rng = np.random.RandomState(seed)
        self._step = 0

    @property
    def epsilon(self) -> float:
        frac = min(self._step / self.anneal_steps, 1.0)
        return 1.0 + (self.min_epsilon - 1.0) * frac

    def select(self, q_values: np.ndarray, rng=None) -> int:
        eps = self.epsilon
        self._step += 1
        if self.rng.rand() < eps:
            return int(self.rng.randint(len(q_values)))
        return int(np.argmax(q_values))


class BoltzmannPolicy:
    """Softmax-over-Q sampling (ref: BoltzmannQ)."""

    def __init__(self, temperature: float = 1.0, seed: int = 0):
        self.temperature = temperature
        self.rng = np.random.RandomState(seed)

    def select(self, q_values: np.ndarray, rng=None) -> int:
        z = q_values / max(self.temperature, 1e-8)
        z = z - z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self.rng.choice(len(q_values), p=p))
