"""Experience replay (ref: org.deeplearning4j.rl4j.learning.sync.ExpReplay —
circular buffer + uniform minibatch sampling). Storage is preallocated numpy
rings (no per-transition objects); sampling returns contiguous arrays ready
to become one device batch."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass
class Transition:
    """(ref: rl4j Transition)."""
    observation: np.ndarray
    action: int
    reward: float
    next_observation: np.ndarray
    done: bool


class ExpReplay:
    def __init__(self, max_size: int, obs_size: int, seed: int = 0):
        self.max_size = max_size
        self.obs = np.zeros((max_size, obs_size), np.float32)
        self.next_obs = np.zeros((max_size, obs_size), np.float32)
        self.actions = np.zeros(max_size, np.int32)
        self.rewards = np.zeros(max_size, np.float32)
        self.dones = np.zeros(max_size, np.float32)
        self._idx = 0
        self._size = 0
        self.rng = np.random.RandomState(seed)

    def store(self, t: Transition):
        i = self._idx
        self.obs[i] = t.observation
        self.next_obs[i] = t.next_observation
        self.actions[i] = t.action
        self.rewards[i] = t.reward
        self.dones[i] = float(t.done)
        self._idx = (i + 1) % self.max_size
        self._size = min(self._size + 1, self.max_size)

    def __len__(self) -> int:
        return self._size

    def sample(self, batch_size: int) -> Tuple[np.ndarray, ...]:
        idx = self.rng.randint(0, self._size, batch_size)
        return (self.obs[idx], self.actions[idx], self.rewards[idx],
                self.next_obs[idx], self.dones[idx])
