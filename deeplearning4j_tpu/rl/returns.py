"""n-step return computation over vectorized rollouts — shared by the
n-step Q and A2C/A3C learners, factored pure so the terminal/truncation
semantics are unit-testable in isolation.

Semantics per stream i, step t (backwards recursion):
- terminal (``dones[t,i]``): value beyond t is 0 — the episode really ended.
- truncated (``truncs[t,i]``): the env hit a time limit and was auto-reset;
  the value beyond t is ``trunc_boot[t,i]`` = V/maxQ of the episode's FINAL
  observation. Chaining the running return here would leak the next
  episode's rewards across the reset boundary.
- otherwise: chain the running return.
The recursion seeds from ``tail_boot`` = V/maxQ of the rollout's last
next-observation per stream.
"""
from __future__ import annotations

import numpy as np


def nstep_returns(rewards: np.ndarray, dones: np.ndarray, truncs: np.ndarray,
                  tail_boot: np.ndarray, trunc_boot: np.ndarray,
                  gamma: float) -> np.ndarray:
    """All args (S, N) except tail_boot (N,); returns (S, N) float32."""
    S, N = rewards.shape
    returns = np.empty((S, N), np.float32)
    R = np.asarray(tail_boot, np.float32)
    for t in reversed(range(S)):
        vnext = np.where(dones[t], 0.0,
                         np.where(truncs[t], trunc_boot[t], R))
        R = rewards[t] + gamma * vnext
        returns[t] = R
    return returns
