"""Environment SPI + builtin test environments (ref: rl4j-api MDP interface
and rl4j-gym's Box/Discrete spaces; gym is unavailable in this environment,
so the classic-control CartPole dynamics are implemented directly from the
public equations of motion — the same ones rl4j's gym-java-client drives)."""
from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np


class MDP:
    """(ref: org.deeplearning4j.rl4j.mdp.MDP). step returns
    (observation, reward, done, info)."""

    obs_size: int
    n_actions: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError

    def close(self):
        pass


class ChainMDP(MDP):
    """Deterministic n-state chain: actions {0: left, 1: right}; reward 1 at
    the right end, 0.01 at the left end (the classic exploration testbed —
    optimal policy always goes right). Episode ends after ``horizon`` steps.
    Observations are one-hot state encodings."""

    def __init__(self, n_states: int = 6, horizon: int = 20):
        self.n = n_states
        self.horizon = horizon
        self.obs_size = n_states
        self.n_actions = 2
        self._s = 0
        self._t = 0

    def _obs(self):
        o = np.zeros(self.n, np.float32)
        o[self._s] = 1.0
        return o

    def reset(self):
        self._s = 1
        self._t = 0
        return self._obs()

    def step(self, action):
        self._t += 1
        if action == 1:
            self._s = min(self._s + 1, self.n - 1)
        else:
            self._s = max(self._s - 1, 0)
        reward = 1.0 if self._s == self.n - 1 else (0.01 if self._s == 0 else 0.0)
        done = self._t >= self.horizon
        return self._obs(), reward, done, {}


class CartPole(MDP):
    """Classic-control cart-pole balance (public dynamics: Barto, Sutton &
    Anderson 1983 as used by gym CartPole-v1). Reward 1 per step until the
    pole falls or 500 steps elapse."""

    def __init__(self, seed: int = 0, max_steps: int = 500):
        self.rng = np.random.RandomState(seed)
        self.obs_size = 4
        self.n_actions = 2
        self.max_steps = max_steps
        self.gravity = 9.8
        self.masscart = 1.0
        self.masspole = 0.1
        self.length = 0.5  # half pole length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_limit = 12 * 2 * np.pi / 360
        self.x_limit = 2.4
        self._state: Optional[np.ndarray] = None
        self._t = 0

    def reset(self):
        self._state = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._t = 0
        return self._state.copy()

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        total_mass = self.masscart + self.masspole
        polemass_length = self.masspole * self.length
        temp = (force + polemass_length * theta_dot ** 2 * sintheta) / total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta ** 2 / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._t += 1
        done = bool(abs(x) > self.x_limit or abs(theta) > self.theta_limit
                    or self._t >= self.max_steps)
        return self._state.copy(), 1.0, done, {}


class MountainCar(MDP):
    """Classic-control mountain car (ref: rl4j-gym MountainCar-v0 binding;
    dynamics from the public equations — Moore 1990): position in
    [-1.2, 0.6], velocity in [-0.07, 0.07], actions {0: left, 1: idle,
    2: right}, reward -1 per step until the goal at x >= 0.5."""

    def __init__(self, horizon: int = 200, seed: int = 0):
        self.obs_size = 2
        self.n_actions = 3
        self.horizon = horizon
        self._rng = np.random.default_rng(seed)
        self._s = np.zeros(2, np.float32)
        self._t = 0

    def reset(self):
        self._s = np.array([self._rng.uniform(-0.6, -0.4), 0.0], np.float32)
        self._t = 0
        return self._s.copy()

    def step(self, action: int):
        pos, vel = float(self._s[0]), float(self._s[1])
        vel += (action - 1) * 0.001 + np.cos(3 * pos) * (-0.0025)
        vel = float(np.clip(vel, -0.07, 0.07))
        pos = float(np.clip(pos + vel, -1.2, 0.6))
        if pos <= -1.2:
            vel = 0.0
        self._s = np.array([pos, vel], np.float32)
        self._t += 1
        done = pos >= 0.5 or self._t >= self.horizon
        return self._s.copy(), -1.0, done, {}


class GymEnvAdapter(MDP):
    """Adapter over a gymnasium/gym environment (ref: rl4j-gym's GymEnv via
    gym-java-client). Gated: neither package ships in this image, so
    construction raises with instructions unless one is importable; the
    adapter itself handles both the 5-tuple (gymnasium) and 4-tuple (legacy
    gym) step signatures."""

    def __init__(self, env_id: str, **make_kwargs):
        gym = None
        for mod in ("gymnasium", "gym"):
            try:
                gym = __import__(mod)
                break
            except ImportError:
                continue
        if gym is None:
            raise ImportError(
                "GymEnvAdapter needs gymnasium or gym (neither is installed "
                "in this environment); use the built-in CartPole/MountainCar/"
                "ChainMDP envs instead")
        self._env = gym.make(env_id, **make_kwargs)
        self.obs_size = int(np.prod(self._env.observation_space.shape))
        self.n_actions = int(self._env.action_space.n)

    def reset(self):
        out = self._env.reset()
        obs = out[0] if isinstance(out, tuple) else out
        return np.asarray(obs, np.float32).ravel()

    def step(self, action: int):
        out = self._env.step(int(action))
        if len(out) == 5:  # gymnasium: obs, reward, terminated, truncated, info
            obs, r, term, trunc, info = out
            done = bool(term or trunc)
        else:              # legacy gym: obs, reward, done, info
            obs, r, done, info = out
        return np.asarray(obs, np.float32).ravel(), float(r), done, info

    def close(self):
        self._env.close()
