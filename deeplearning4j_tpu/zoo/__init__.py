"""Model zoo (ref: deeplearning4j-zoo — org.deeplearning4j.zoo.ZooModel and
org.deeplearning4j.zoo.model.*)."""
from deeplearning4j_tpu.zoo.models import (
    ZooModel, LeNet, SimpleCNN, AlexNet, VGG16, VGG19, ResNet50, SqueezeNet,
    Darknet19, UNet, Xception, TextGenerationLSTM, TinyYOLO, YOLO2,
    InceptionResNetV1, FaceNetNN4Small2, NASNetMobile)

__all__ = [
    "ZooModel", "LeNet", "SimpleCNN", "AlexNet", "VGG16", "VGG19", "ResNet50",
    "SqueezeNet", "Darknet19", "UNet", "Xception", "TextGenerationLSTM",
    "TinyYOLO", "YOLO2", "InceptionResNetV1", "FaceNetNN4Small2",
    "NASNetMobile",
]
