"""Reference architectures (ref: deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/model/ — LeNet, SimpleCNN, AlexNet, VGG16/19, ResNet50,
SqueezeNet, Darknet19, UNet, Xception, TextGenerationLSTM).

Each model is a config builder over the nn DSL, exactly as the reference's
ZooModel.conf() methods build MultiLayerConfiguration/
ComputationGraphConfiguration. Pretrained-weight downloads (ZooModel.
initPretrained) require network access the build environment lacks — the
hook exists and raises with a clear message; the Keras-h5 importer covers
weight loading for users with local files."""
from __future__ import annotations

from typing import Optional, Tuple

from deeplearning4j_tpu.nn import NeuralNetConfiguration
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, Deconvolution2D, GlobalPoolingLayer, LSTM, LocalResponseNormalization,
    OutputLayer, RnnOutputLayer, SeparableConvolution2D, SubsamplingLayer,
    ZeroPaddingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.updaters import AdaDelta, Adam, Nesterovs


class ZooModel:
    """(ref: org.deeplearning4j.zoo.ZooModel)."""
    numClasses: int
    seed: int
    inputShape: Tuple[int, int, int]

    def __init__(self, numClasses: int = 1000, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 224, 224)):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the network (ref: ZooModel.init)."""
        c = self.conf()
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        if isinstance(c, MultiLayerConfiguration):
            return MultiLayerNetwork(c).init()
        return ComputationGraph(c).init()

    def initPretrained(self, pretrained_type: str = "IMAGENET"):
        raise NotImplementedError(
            "pretrained weight download is unavailable in this environment; "
            "use deeplearning4j_tpu.modelimport.keras to load local .h5 weights "
            "(ref: ZooModel.initPretrained)")

    def pretrainedAvailable(self, *_):
        return False


class LeNet(ZooModel):
    """(ref: zoo.model.LeNet — BASELINE config #1)."""

    def __init__(self, numClasses: int = 10, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (1, 28, 28)):
        super().__init__(numClasses, seed, inputShape)

    def conf(self):
        c, h, w = self.inputShape
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Adam(1e-3)).weightInit("XAVIER")
                .list()
                .layer(ConvolutionLayer(nOut=20, kernelSize=(5, 5), stride=(1, 1),
                                        convolutionMode="Same", activation="IDENTITY"))
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(nOut=50, kernelSize=(5, 5), stride=(1, 1),
                                        convolutionMode="Same", activation="IDENTITY"))
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(nOut=500, activation="RELU"))
                .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class SimpleCNN(ZooModel):
    """(ref: zoo.model.SimpleCNN)."""

    def __init__(self, numClasses: int = 10, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 48, 48)):
        super().__init__(numClasses, seed, inputShape)

    def conf(self):
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(AdaDelta()).activation("RELU").weightInit("XAVIER")
             .list())
        for n_out in (96, 96, 192, 192):
            b = b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                         convolutionMode="Same", activation="IDENTITY"))
            b = b.layer(BatchNormalization())
            b = b.layer(ActivationLayer(activation="RELU"))
        b = (b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
             .layer(DropoutLayer(dropOut=0.5))
             .layer(GlobalPoolingLayer(poolingType="AVG"))
             .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                lossFunction="MCXENT")))
        return b.setInputType(InputType.convolutional(h, w, c)).build()


class AlexNet(ZooModel):
    """(ref: zoo.model.AlexNet — one-tower variant)."""

    def conf(self):
        c, h, w = self.inputShape
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9)).weightInit("NORMAL")
                .list()
                .layer(ConvolutionLayer(nOut=96, kernelSize=(11, 11), stride=(4, 4),
                                        activation="RELU"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(nOut=256, kernelSize=(5, 5), convolutionMode="Same",
                                        activation="RELU", biasInit=1.0))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(nOut=384, kernelSize=(3, 3), convolutionMode="Same",
                                        activation="RELU"))
                .layer(ConvolutionLayer(nOut=384, kernelSize=(3, 3), convolutionMode="Same",
                                        activation="RELU", biasInit=1.0))
                .layer(ConvolutionLayer(nOut=256, kernelSize=(3, 3), convolutionMode="Same",
                                        activation="RELU", biasInit=1.0))
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5, biasInit=1.0))
                .layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5, biasInit=1.0))
                .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


def _vgg_blocks(b, spec):
    for n_convs, n_out in spec:
        for _ in range(n_convs):
            b = b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                         convolutionMode="Same", activation="RELU"))
        b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
    return b


class VGG16(ZooModel):
    """(ref: zoo.model.VGG16)."""

    _spec = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]

    def conf(self):
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9)).weightInit("XAVIER").list())
        b = _vgg_blocks(b, self._spec)
        return (b.layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5))
                .layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5))
                .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class VGG19(VGG16):
    """(ref: zoo.model.VGG19)."""
    _spec = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


class ResNet50(ZooModel):
    """(ref: zoo.model.ResNet50 — BASELINE config #2). Bottleneck residual
    blocks over ComputationGraph with ElementWiseVertex(Add) shortcuts."""

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("RELU")  # he-style
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        g.addLayer("stem_conv", ConvolutionLayer(nOut=64, kernelSize=(7, 7), stride=(2, 2),
                                                 convolutionMode="Same",
                                                 activation="IDENTITY"), "input")
        g.addLayer("stem_bn", BatchNormalization(activation="RELU"), "stem_conv")
        g.addLayer("stem_pool", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                                 stride=(2, 2), convolutionMode="Same"),
                   "stem_bn")
        prev = "stem_pool"
        stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]
        for si, (blocks, mid, out, first_stride) in enumerate(stages):
            for bi in range(blocks):
                stride = first_stride if bi == 0 else 1
                name = f"s{si}b{bi}"
                g.addLayer(f"{name}_c1", ConvolutionLayer(nOut=mid, kernelSize=(1, 1),
                                                          stride=(stride, stride),
                                                          activation="IDENTITY"), prev)
                g.addLayer(f"{name}_bn1", BatchNormalization(activation="RELU"), f"{name}_c1")
                g.addLayer(f"{name}_c2", ConvolutionLayer(nOut=mid, kernelSize=(3, 3),
                                                          convolutionMode="Same",
                                                          activation="IDENTITY"), f"{name}_bn1")
                g.addLayer(f"{name}_bn2", BatchNormalization(activation="RELU"), f"{name}_c2")
                g.addLayer(f"{name}_c3", ConvolutionLayer(nOut=out, kernelSize=(1, 1),
                                                          activation="IDENTITY"), f"{name}_bn2")
                g.addLayer(f"{name}_bn3", BatchNormalization(activation="IDENTITY"), f"{name}_c3")
                if bi == 0:
                    g.addLayer(f"{name}_sc", ConvolutionLayer(nOut=out, kernelSize=(1, 1),
                                                              stride=(stride, stride),
                                                              activation="IDENTITY"), prev)
                    g.addLayer(f"{name}_scbn", BatchNormalization(activation="IDENTITY"),
                               f"{name}_sc")
                    shortcut = f"{name}_scbn"
                else:
                    shortcut = prev
                g.addVertex(f"{name}_add", ElementWiseVertex(op="Add"),
                            f"{name}_bn3", shortcut)
                g.addLayer(f"{name}_relu", ActivationLayer(activation="RELU"), f"{name}_add")
                prev = f"{name}_relu"
        g.addLayer("avgpool", GlobalPoolingLayer(poolingType="AVG"), prev)
        g.addLayer("output", OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                         lossFunction="MCXENT"), "avgpool")
        g.setOutputs("output")
        return g.build()


class SqueezeNet(ZooModel):
    """(ref: zoo.model.SqueezeNet — fire modules: squeeze 1x1 -> expand 1x1|3x3)."""

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER")
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        g.addLayer("conv1", ConvolutionLayer(nOut=64, kernelSize=(3, 3), stride=(2, 2),
                                             convolutionMode="Same", activation="RELU"),
                   "input")
        g.addLayer("pool1", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                             stride=(2, 2), convolutionMode="Same"), "conv1")
        prev = "pool1"
        fires = [(16, 64), (16, 64), (32, 128), (32, 128),
                 (48, 192), (48, 192), (64, 256), (64, 256)]
        for i, (sq, ex) in enumerate(fires):
            n = f"fire{i + 2}"
            g.addLayer(f"{n}_sq", ConvolutionLayer(nOut=sq, kernelSize=(1, 1),
                                                   activation="RELU"), prev)
            g.addLayer(f"{n}_e1", ConvolutionLayer(nOut=ex, kernelSize=(1, 1),
                                                   activation="RELU"), f"{n}_sq")
            g.addLayer(f"{n}_e3", ConvolutionLayer(nOut=ex, kernelSize=(3, 3),
                                                   convolutionMode="Same",
                                                   activation="RELU"), f"{n}_sq")
            g.addVertex(f"{n}_cat", MergeVertex(), f"{n}_e1", f"{n}_e3")
            prev = f"{n}_cat"
            if i in (2, 6):
                g.addLayer(f"pool{i}", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                                        stride=(2, 2), convolutionMode="Same"),
                           prev)
                prev = f"pool{i}"
        g.addLayer("drop", DropoutLayer(dropOut=0.5), prev)
        g.addLayer("conv10", ConvolutionLayer(nOut=self.numClasses, kernelSize=(1, 1),
                                              activation="RELU"), "drop")
        g.addLayer("gap", GlobalPoolingLayer(poolingType="AVG"), "conv10")
        g.addLayer("output", OutputLayer(nIn=self.numClasses, nOut=self.numClasses,
                                         activation="SOFTMAX", lossFunction="MCXENT"), "gap")
        g.setOutputs("output")
        return g.build()


class Darknet19(ZooModel):
    """(ref: zoo.model.Darknet19)."""

    def conf(self):
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Nesterovs(1e-3, 0.9)).weightInit("XAVIER").list())

        def conv_bn(b, n_out, k):
            return (b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(k, k),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"))
                    .layer(BatchNormalization(activation="LEAKYRELU")))

        spec = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False),
                (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False), (512, 1, False),
                (1024, 3, False)]
        for n_out, k, pool in spec:
            b = conv_bn(b, n_out, k)
            if pool:
                b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                             stride=(2, 2)))
        return (b.layer(ConvolutionLayer(nOut=self.numClasses, kernelSize=(1, 1),
                                         activation="IDENTITY"))
                .layer(GlobalPoolingLayer(poolingType="AVG"))
                .layer(OutputLayer(nIn=self.numClasses, nOut=self.numClasses,
                                   activation="SOFTMAX", lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class UNet(ZooModel):
    """(ref: zoo.model.UNet — encoder/decoder with skip MergeVertex concat;
    sigmoid pixel output)."""

    def __init__(self, numClasses: int = 1, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 128, 128), depth: int = 4,
                 baseFilters: int = 16):
        super().__init__(numClasses, seed, inputShape)
        self.depth = depth
        self.baseFilters = baseFilters

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER")
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def double_conv(name, n_out, src):
            g.addLayer(f"{name}_c1", ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                                      convolutionMode="Same",
                                                      activation="RELU"), src)
            g.addLayer(f"{name}_c2", ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                                      convolutionMode="Same",
                                                      activation="RELU"), f"{name}_c1")
            return f"{name}_c2"

        skips = []
        prev = "input"
        f = self.baseFilters
        for d in range(self.depth):
            prev = double_conv(f"enc{d}", f * (2 ** d), prev)
            skips.append(prev)
            g.addLayer(f"down{d}", SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                                    stride=(2, 2)), prev)
            prev = f"down{d}"
        prev = double_conv("bottleneck", f * (2 ** self.depth), prev)
        for d in reversed(range(self.depth)):
            g.addLayer(f"up{d}", Deconvolution2D(nOut=f * (2 ** d), kernelSize=(2, 2),
                                                 stride=(2, 2), convolutionMode="Same",
                                                 activation="RELU"), prev)
            g.addVertex(f"skip{d}", MergeVertex(), f"up{d}", skips[d])
            prev = double_conv(f"dec{d}", f * (2 ** d), f"skip{d}")
        g.addLayer("head", ConvolutionLayer(nOut=self.numClasses, kernelSize=(1, 1),
                                            activation="SIGMOID"), prev)
        from deeplearning4j_tpu.nn.conf.layers import LossLayer
        g.addLayer("output", LossLayer(lossFunction="XENT"), "head")
        g.setOutputs("output")
        return g.build()


class Xception(ZooModel):
    """(ref: zoo.model.Xception — depthwise-separable conv towers with
    residual shortcuts; simplified to entry + 4 middle blocks + exit)."""

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER")
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        g.addLayer("stem1", ConvolutionLayer(nOut=32, kernelSize=(3, 3), stride=(2, 2),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"), "input")
        g.addLayer("stem1_bn", BatchNormalization(activation="RELU"), "stem1")
        g.addLayer("stem2", ConvolutionLayer(nOut=64, kernelSize=(3, 3),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"), "stem1_bn")
        g.addLayer("stem2_bn", BatchNormalization(activation="RELU"), "stem2")
        prev = "stem2_bn"
        for i, n_out in enumerate((128, 256, 728)):
            n = f"entry{i}"
            g.addLayer(f"{n}_s1", SeparableConvolution2D(nOut=n_out, kernelSize=(3, 3),
                                                         convolutionMode="Same",
                                                         activation="RELU"), prev)
            g.addLayer(f"{n}_s2", SeparableConvolution2D(nOut=n_out, kernelSize=(3, 3),
                                                         convolutionMode="Same",
                                                         activation="IDENTITY"), f"{n}_s1")
            g.addLayer(f"{n}_pool", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                                     stride=(2, 2), convolutionMode="Same"),
                       f"{n}_s2")
            g.addLayer(f"{n}_sc", ConvolutionLayer(nOut=n_out, kernelSize=(1, 1),
                                                   stride=(2, 2), convolutionMode="Same",
                                                   activation="IDENTITY"), prev)
            g.addVertex(f"{n}_add", ElementWiseVertex(op="Add"), f"{n}_pool", f"{n}_sc")
            prev = f"{n}_add"
        for i in range(4):  # middle flow (reference has 8; 4 keeps tests fast)
            n = f"mid{i}"
            src = prev
            for j in range(3):
                g.addLayer(f"{n}_s{j}", SeparableConvolution2D(
                    nOut=728, kernelSize=(3, 3), convolutionMode="Same",
                    activation="RELU"), prev)
                prev = f"{n}_s{j}"
            g.addVertex(f"{n}_add", ElementWiseVertex(op="Add"), prev, src)
            prev = f"{n}_add"
        g.addLayer("exit_s1", SeparableConvolution2D(nOut=1024, kernelSize=(3, 3),
                                                     convolutionMode="Same",
                                                     activation="RELU"), prev)
        g.addLayer("gap", GlobalPoolingLayer(poolingType="AVG"), "exit_s1")
        g.addLayer("output", OutputLayer(nIn=1024, nOut=self.numClasses,
                                         activation="SOFTMAX", lossFunction="MCXENT"), "gap")
        g.setOutputs("output")
        return g.build()


class TextGenerationLSTM(ZooModel):
    """(ref: zoo.model.TextGenerationLSTM — the GravesLSTM char-RNN,
    BASELINE config #3)."""

    def __init__(self, totalUniqueCharacters: int = 47, seed: int = 123,
                 lstmLayerSize: int = 200):
        super().__init__(totalUniqueCharacters, seed, (0, 0, 0))
        self.lstmLayerSize = lstmLayerSize

    def conf(self):
        n = self.numClasses
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Adam(1e-3)).weightInit("XAVIER")
                .list()
                .layer(LSTM(nIn=n, nOut=self.lstmLayerSize, activation="TANH"))
                .layer(LSTM(nIn=self.lstmLayerSize, nOut=self.lstmLayerSize,
                            activation="TANH"))
                .layer(RnnOutputLayer(nIn=self.lstmLayerSize, nOut=n,
                                      activation="SOFTMAX", lossFunction="MCXENT"))
                .backpropType("TruncatedBPTT").tBPTTForwardLength(50)
                .tBPTTBackwardLength(50)
                .build())


class TinyYOLO(ZooModel):
    """(ref: zoo.model.TinyYOLO — Darknet-tiny backbone + Yolo2OutputLayer;
    default anchors from the VOC-trained reference config, grid units)."""

    DEFAULT_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                       (9.42, 5.11), (16.62, 10.52))

    def __init__(self, numClasses: int = 20, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 416, 416),
                 boundingBoxes=None):
        super().__init__(numClasses, seed, inputShape)
        self.boundingBoxes = tuple(boundingBoxes or self.DEFAULT_ANCHORS)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.layers import Yolo2OutputLayer
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER").list())

        def conv_bn(b, n_out):
            return (b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"))
                    .layer(BatchNormalization(activation="LEAKYRELU")))

        for i, n_out in enumerate([16, 32, 64, 128, 256]):
            b = conv_bn(b, n_out)
            b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                         stride=(2, 2)))
        b = conv_bn(b, 512)
        b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                     stride=(1, 1), convolutionMode="Same"))
        b = conv_bn(b, 1024)
        A = len(self.boundingBoxes)
        return (b.layer(ConvolutionLayer(nOut=A * (5 + self.numClasses),
                                         kernelSize=(1, 1), activation="IDENTITY"))
                .layer(Yolo2OutputLayer(boundingBoxes=self.boundingBoxes))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class YOLO2(ZooModel):
    """(ref: zoo.model.YOLO2 — Darknet19 backbone + Yolo2OutputLayer).

    Deviation from the reference: the passthrough reorg (26x26 features
    SpaceToDepth'd and concatenated into the 13x13 head) needs a skip
    connection, which a sequential conf cannot express — this build is the
    straight-through backbone only. Use ``graph_conf()`` for the faithful
    passthrough variant."""

    def graph_conf(self):
        """ComputationGraph variant WITH the passthrough: conv13's 26x26x512
        features go through 1x1 conv(64) + SpaceToDepth(2) and merge into the
        13x13 head (the reference's reorg route)."""
        from deeplearning4j_tpu.nn.conf.layers import SpaceToDepthLayer, Yolo2OutputLayer
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER").graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, n_out, k, frm):
            g.addLayer(f"{name}c", ConvolutionLayer(nOut=n_out, kernelSize=(k, k),
                                                    convolutionMode="Same", hasBias=False,
                                                    activation="IDENTITY"), frm)
            g.addLayer(name, BatchNormalization(activation="LEAKYRELU"), f"{name}c")
            return name

        spec = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False),
                (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False),
                (512, 1, False), (1024, 3, False),
                (1024, 3, False), (1024, 3, False)]
        prev, passthrough = "input", None
        for i, (n_out, k, pool) in enumerate(spec):
            prev = conv_bn(f"b{i}", n_out, k, prev)
            if i == 12:
                passthrough = prev  # conv13 output, 26x26x512, pre-pool
            if pool:
                g.addLayer(f"b{i}p", SubsamplingLayer(poolingType="MAX",
                                                      kernelSize=(2, 2), stride=(2, 2)),
                           prev)
                prev = f"b{i}p"
        pt = conv_bn("pt", 64, 1, passthrough)
        g.addLayer("pt_s2d", SpaceToDepthLayer(blockSize=2), pt)  # 13x13x256
        g.addVertex("cat", MergeVertex(), "pt_s2d", prev)
        head = conv_bn("head", 1024, 3, "cat")
        A = len(self.boundingBoxes)
        g.addLayer("det", ConvolutionLayer(nOut=A * (5 + self.numClasses),
                                           kernelSize=(1, 1), activation="IDENTITY"),
                   head)
        g.addLayer("output", Yolo2OutputLayer(boundingBoxes=self.boundingBoxes), "det")
        g.setOutputs("output")
        return g.build()

    DEFAULT_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253),
                       (3.33843, 5.47434), (7.88282, 3.52778),
                       (9.77052, 9.16828))

    def __init__(self, numClasses: int = 80, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 608, 608),
                 boundingBoxes=None):
        super().__init__(numClasses, seed, inputShape)
        self.boundingBoxes = tuple(boundingBoxes or self.DEFAULT_ANCHORS)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.layers import Yolo2OutputLayer
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER").list())

        def conv_bn(b, n_out, k):
            return (b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(k, k),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"))
                    .layer(BatchNormalization(activation="LEAKYRELU")))

        spec = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False),
                (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False),
                (512, 1, False), (1024, 3, False),
                (1024, 3, False), (1024, 3, False)]
        for n_out, k, pool in spec:
            b = conv_bn(b, n_out, k)
            if pool:
                b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                             stride=(2, 2)))
        A = len(self.boundingBoxes)
        return (b.layer(ConvolutionLayer(nOut=A * (5 + self.numClasses),
                                         kernelSize=(1, 1), activation="IDENTITY"))
                .layer(Yolo2OutputLayer(boundingBoxes=self.boundingBoxes))
                .setInputType(InputType.convolutional(h, w, c))
                .build())
