"""Reference architectures (ref: deeplearning4j-zoo/src/main/java/org/
deeplearning4j/zoo/model/ — LeNet, SimpleCNN, AlexNet, VGG16/19, ResNet50,
SqueezeNet, Darknet19, UNet, Xception, TextGenerationLSTM).

Each model is a config builder over the nn DSL, exactly as the reference's
ZooModel.conf() methods build MultiLayerConfiguration/
ComputationGraphConfiguration. Pretrained weights load through the
Resources cache resolver (ZooModel.initPretrained): local-first (seed
~/.deeplearning4j_tpu/resources/zoo/) with checksum verification, plus a
pluggable fetch hook for networked environments — this build environment
itself has zero egress."""
from __future__ import annotations

from typing import Optional, Tuple

from deeplearning4j_tpu.nn import NeuralNetConfiguration
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, Deconvolution2D, GlobalPoolingLayer, LSTM, LocalResponseNormalization,
    OutputLayer, RnnOutputLayer, SeparableConvolution2D, SubsamplingLayer,
    ZeroPaddingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train.updaters import AdaDelta, Adam, Nesterovs


class ZooModel:
    """(ref: org.deeplearning4j.zoo.ZooModel)."""
    numClasses: int
    seed: int
    inputShape: Tuple[int, int, int]

    def __init__(self, numClasses: int = 1000, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 224, 224)):
        self.numClasses = numClasses
        self.seed = seed
        self.inputShape = inputShape

    def conf(self):
        raise NotImplementedError

    def init(self):
        """Build + initialize the network (ref: ZooModel.init)."""
        c = self.conf()
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        if isinstance(c, MultiLayerConfiguration):
            return MultiLayerNetwork(c).init()
        return ComputationGraph(c).init()

    def pretrainedResourceName(self, pretrained_type: str = "IMAGENET") -> str:
        """Cache-relative resource name for this model's weights
        (ref: ZooModel.pretrainedUrl — here a Resources cache key)."""
        return f"zoo/{type(self).__name__.lower()}_{pretrained_type.lower()}.zip"

    def pretrainedAvailable(self, pretrained_type: str = "IMAGENET") -> bool:
        """True when weights are loadable: cached (zip or .h5 sibling), or
        fetchable via a registered hook (ref: ZooModel.pretrainedUrl != null)."""
        from deeplearning4j_tpu.util.resources import Resources
        name = self.pretrainedResourceName(pretrained_type)
        return (Resources.exists(name)
                or Resources.exists(name.removesuffix(".zip") + ".h5")
                or Resources._fetch_hook is not None)

    def initPretrained(self, pretrained_type: str = "IMAGENET",
                       sha256: Optional[str] = None):
        """Load pretrained weights through the Resources resolver
        (ref: ZooModel.initPretrained — download + cache + checksum; here
        the cache is local-first and the download is a pluggable fetch hook,
        since this environment has zero egress). The cached artifact is a
        ModelSerializer zip, or a Keras .h5 sibling routed by the h5's own
        model_config class. ``sha256`` applies to whichever artifact is
        picked; a mismatch raises without deleting the seeded file."""
        from deeplearning4j_tpu.util.resources import Resources
        name = self.pretrainedResourceName(pretrained_type)
        h5 = name.removesuffix(".zip") + ".h5"
        picked = name if (Resources.exists(name)
                          or not Resources.exists(h5)) else h5
        try:
            path = Resources.asFile(picked, sha256=sha256,
                                    evictOnMismatch=False)
        except FileNotFoundError:
            raise FileNotFoundError(
                f"no cached weights for {type(self).__name__} "
                f"({pretrained_type}): seed {Resources.cacheDir() / name} "
                "(ModelSerializer zip) or the .h5 sibling (Keras), or "
                "registerFetchHook for networked environments "
                "(ref: ZooModel.initPretrained)") from None
        if str(path).endswith(".h5"):
            import h5py
            import json as _json
            from deeplearning4j_tpu.modelimport.keras import KerasModelImport
            with h5py.File(str(path), "r") as f:
                cls = _json.loads(f.attrs["model_config"])["class_name"]
            if cls == "Sequential":
                return KerasModelImport.importKerasSequentialModelAndWeights(str(path))
            return KerasModelImport.importKerasModelAndWeights(str(path))
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        return ModelSerializer.restoreModel(str(path))


class LeNet(ZooModel):
    """(ref: zoo.model.LeNet — BASELINE config #1)."""

    def __init__(self, numClasses: int = 10, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (1, 28, 28)):
        super().__init__(numClasses, seed, inputShape)

    def conf(self):
        c, h, w = self.inputShape
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Adam(1e-3)).weightInit("XAVIER")
                .list()
                .layer(ConvolutionLayer(nOut=20, kernelSize=(5, 5), stride=(1, 1),
                                        convolutionMode="Same", activation="IDENTITY"))
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(nOut=50, kernelSize=(5, 5), stride=(1, 1),
                                        convolutionMode="Same", activation="IDENTITY"))
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(nOut=500, activation="RELU"))
                .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class SimpleCNN(ZooModel):
    """(ref: zoo.model.SimpleCNN)."""

    def __init__(self, numClasses: int = 10, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 48, 48)):
        super().__init__(numClasses, seed, inputShape)

    def conf(self):
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(AdaDelta()).activation("RELU").weightInit("XAVIER")
             .list())
        for n_out in (96, 96, 192, 192):
            b = b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                         convolutionMode="Same", activation="IDENTITY"))
            b = b.layer(BatchNormalization())
            b = b.layer(ActivationLayer(activation="RELU"))
        b = (b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
             .layer(DropoutLayer(dropOut=0.5))
             .layer(GlobalPoolingLayer(poolingType="AVG"))
             .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                lossFunction="MCXENT")))
        return b.setInputType(InputType.convolutional(h, w, c)).build()


class AlexNet(ZooModel):
    """(ref: zoo.model.AlexNet — one-tower variant)."""

    def conf(self):
        c, h, w = self.inputShape
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9)).weightInit("NORMAL")
                .list()
                .layer(ConvolutionLayer(nOut=96, kernelSize=(11, 11), stride=(4, 4),
                                        activation="RELU"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(nOut=256, kernelSize=(5, 5), convolutionMode="Same",
                                        activation="RELU", biasInit=1.0))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(nOut=384, kernelSize=(3, 3), convolutionMode="Same",
                                        activation="RELU"))
                .layer(ConvolutionLayer(nOut=384, kernelSize=(3, 3), convolutionMode="Same",
                                        activation="RELU", biasInit=1.0))
                .layer(ConvolutionLayer(nOut=256, kernelSize=(3, 3), convolutionMode="Same",
                                        activation="RELU", biasInit=1.0))
                .layer(SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5, biasInit=1.0))
                .layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5, biasInit=1.0))
                .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


def _vgg_blocks(b, spec):
    for n_convs, n_out in spec:
        for _ in range(n_convs):
            b = b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                         convolutionMode="Same", activation="RELU"))
        b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2), stride=(2, 2)))
    return b


class VGG16(ZooModel):
    """(ref: zoo.model.VGG16)."""

    _spec = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]

    def conf(self):
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Nesterovs(1e-2, 0.9)).weightInit("XAVIER").list())
        b = _vgg_blocks(b, self._spec)
        return (b.layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5))
                .layer(DenseLayer(nOut=4096, activation="RELU", dropOut=0.5))
                .layer(OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                   lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class VGG19(VGG16):
    """(ref: zoo.model.VGG19)."""
    _spec = [(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)]


class ResNet50(ZooModel):
    """(ref: zoo.model.ResNet50 — BASELINE config #2). Bottleneck residual
    blocks over ComputationGraph with ElementWiseVertex(Add) shortcuts."""

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("RELU")  # he-style
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        g.addLayer("stem_conv", ConvolutionLayer(nOut=64, kernelSize=(7, 7), stride=(2, 2),
                                                 convolutionMode="Same",
                                                 activation="IDENTITY"), "input")
        g.addLayer("stem_bn", BatchNormalization(activation="RELU"), "stem_conv")
        g.addLayer("stem_pool", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                                 stride=(2, 2), convolutionMode="Same"),
                   "stem_bn")
        prev = "stem_pool"
        stages = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2), (3, 512, 2048, 2)]
        for si, (blocks, mid, out, first_stride) in enumerate(stages):
            for bi in range(blocks):
                stride = first_stride if bi == 0 else 1
                name = f"s{si}b{bi}"
                g.addLayer(f"{name}_c1", ConvolutionLayer(nOut=mid, kernelSize=(1, 1),
                                                          stride=(stride, stride),
                                                          activation="IDENTITY"), prev)
                g.addLayer(f"{name}_bn1", BatchNormalization(activation="RELU"), f"{name}_c1")
                g.addLayer(f"{name}_c2", ConvolutionLayer(nOut=mid, kernelSize=(3, 3),
                                                          convolutionMode="Same",
                                                          activation="IDENTITY"), f"{name}_bn1")
                g.addLayer(f"{name}_bn2", BatchNormalization(activation="RELU"), f"{name}_c2")
                g.addLayer(f"{name}_c3", ConvolutionLayer(nOut=out, kernelSize=(1, 1),
                                                          activation="IDENTITY"), f"{name}_bn2")
                g.addLayer(f"{name}_bn3", BatchNormalization(activation="IDENTITY"), f"{name}_c3")
                if bi == 0:
                    g.addLayer(f"{name}_sc", ConvolutionLayer(nOut=out, kernelSize=(1, 1),
                                                              stride=(stride, stride),
                                                              activation="IDENTITY"), prev)
                    g.addLayer(f"{name}_scbn", BatchNormalization(activation="IDENTITY"),
                               f"{name}_sc")
                    shortcut = f"{name}_scbn"
                else:
                    shortcut = prev
                g.addVertex(f"{name}_add", ElementWiseVertex(op="Add"),
                            f"{name}_bn3", shortcut)
                g.addLayer(f"{name}_relu", ActivationLayer(activation="RELU"), f"{name}_add")
                prev = f"{name}_relu"
        g.addLayer("avgpool", GlobalPoolingLayer(poolingType="AVG"), prev)
        g.addLayer("output", OutputLayer(nOut=self.numClasses, activation="SOFTMAX",
                                         lossFunction="MCXENT"), "avgpool")
        g.setOutputs("output")
        return g.build()


class SqueezeNet(ZooModel):
    """(ref: zoo.model.SqueezeNet — fire modules: squeeze 1x1 -> expand 1x1|3x3)."""

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER")
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        g.addLayer("conv1", ConvolutionLayer(nOut=64, kernelSize=(3, 3), stride=(2, 2),
                                             convolutionMode="Same", activation="RELU"),
                   "input")
        g.addLayer("pool1", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                             stride=(2, 2), convolutionMode="Same"), "conv1")
        prev = "pool1"
        fires = [(16, 64), (16, 64), (32, 128), (32, 128),
                 (48, 192), (48, 192), (64, 256), (64, 256)]
        for i, (sq, ex) in enumerate(fires):
            n = f"fire{i + 2}"
            g.addLayer(f"{n}_sq", ConvolutionLayer(nOut=sq, kernelSize=(1, 1),
                                                   activation="RELU"), prev)
            g.addLayer(f"{n}_e1", ConvolutionLayer(nOut=ex, kernelSize=(1, 1),
                                                   activation="RELU"), f"{n}_sq")
            g.addLayer(f"{n}_e3", ConvolutionLayer(nOut=ex, kernelSize=(3, 3),
                                                   convolutionMode="Same",
                                                   activation="RELU"), f"{n}_sq")
            g.addVertex(f"{n}_cat", MergeVertex(), f"{n}_e1", f"{n}_e3")
            prev = f"{n}_cat"
            if i in (2, 6):
                g.addLayer(f"pool{i}", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                                        stride=(2, 2), convolutionMode="Same"),
                           prev)
                prev = f"pool{i}"
        g.addLayer("drop", DropoutLayer(dropOut=0.5), prev)
        g.addLayer("conv10", ConvolutionLayer(nOut=self.numClasses, kernelSize=(1, 1),
                                              activation="RELU"), "drop")
        g.addLayer("gap", GlobalPoolingLayer(poolingType="AVG"), "conv10")
        g.addLayer("output", OutputLayer(nIn=self.numClasses, nOut=self.numClasses,
                                         activation="SOFTMAX", lossFunction="MCXENT"), "gap")
        g.setOutputs("output")
        return g.build()


class Darknet19(ZooModel):
    """(ref: zoo.model.Darknet19)."""

    def conf(self):
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Nesterovs(1e-3, 0.9)).weightInit("XAVIER").list())

        def conv_bn(b, n_out, k):
            return (b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(k, k),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"))
                    .layer(BatchNormalization(activation="LEAKYRELU")))

        spec = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False),
                (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False), (512, 1, False),
                (1024, 3, False)]
        for n_out, k, pool in spec:
            b = conv_bn(b, n_out, k)
            if pool:
                b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                             stride=(2, 2)))
        return (b.layer(ConvolutionLayer(nOut=self.numClasses, kernelSize=(1, 1),
                                         activation="IDENTITY"))
                .layer(GlobalPoolingLayer(poolingType="AVG"))
                .layer(OutputLayer(nIn=self.numClasses, nOut=self.numClasses,
                                   activation="SOFTMAX", lossFunction="MCXENT"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class UNet(ZooModel):
    """(ref: zoo.model.UNet — encoder/decoder with skip MergeVertex concat;
    sigmoid pixel output)."""

    def __init__(self, numClasses: int = 1, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 128, 128), depth: int = 4,
                 baseFilters: int = 16):
        super().__init__(numClasses, seed, inputShape)
        self.depth = depth
        self.baseFilters = baseFilters

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER")
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def double_conv(name, n_out, src):
            g.addLayer(f"{name}_c1", ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                                      convolutionMode="Same",
                                                      activation="RELU"), src)
            g.addLayer(f"{name}_c2", ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                                      convolutionMode="Same",
                                                      activation="RELU"), f"{name}_c1")
            return f"{name}_c2"

        skips = []
        prev = "input"
        f = self.baseFilters
        for d in range(self.depth):
            prev = double_conv(f"enc{d}", f * (2 ** d), prev)
            skips.append(prev)
            g.addLayer(f"down{d}", SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                                    stride=(2, 2)), prev)
            prev = f"down{d}"
        prev = double_conv("bottleneck", f * (2 ** self.depth), prev)
        for d in reversed(range(self.depth)):
            g.addLayer(f"up{d}", Deconvolution2D(nOut=f * (2 ** d), kernelSize=(2, 2),
                                                 stride=(2, 2), convolutionMode="Same",
                                                 activation="RELU"), prev)
            g.addVertex(f"skip{d}", MergeVertex(), f"up{d}", skips[d])
            prev = double_conv(f"dec{d}", f * (2 ** d), f"skip{d}")
        g.addLayer("head", ConvolutionLayer(nOut=self.numClasses, kernelSize=(1, 1),
                                            activation="SIGMOID"), prev)
        from deeplearning4j_tpu.nn.conf.layers import LossLayer
        g.addLayer("output", LossLayer(lossFunction="XENT"), "head")
        g.setOutputs("output")
        return g.build()


class Xception(ZooModel):
    """(ref: zoo.model.Xception — depthwise-separable conv towers with
    residual shortcuts; simplified to entry + 4 middle blocks + exit)."""

    def conf(self):
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER")
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        g.addLayer("stem1", ConvolutionLayer(nOut=32, kernelSize=(3, 3), stride=(2, 2),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"), "input")
        g.addLayer("stem1_bn", BatchNormalization(activation="RELU"), "stem1")
        g.addLayer("stem2", ConvolutionLayer(nOut=64, kernelSize=(3, 3),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"), "stem1_bn")
        g.addLayer("stem2_bn", BatchNormalization(activation="RELU"), "stem2")
        prev = "stem2_bn"
        for i, n_out in enumerate((128, 256, 728)):
            n = f"entry{i}"
            g.addLayer(f"{n}_s1", SeparableConvolution2D(nOut=n_out, kernelSize=(3, 3),
                                                         convolutionMode="Same",
                                                         activation="RELU"), prev)
            g.addLayer(f"{n}_s2", SeparableConvolution2D(nOut=n_out, kernelSize=(3, 3),
                                                         convolutionMode="Same",
                                                         activation="IDENTITY"), f"{n}_s1")
            g.addLayer(f"{n}_pool", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                                     stride=(2, 2), convolutionMode="Same"),
                       f"{n}_s2")
            g.addLayer(f"{n}_sc", ConvolutionLayer(nOut=n_out, kernelSize=(1, 1),
                                                   stride=(2, 2), convolutionMode="Same",
                                                   activation="IDENTITY"), prev)
            g.addVertex(f"{n}_add", ElementWiseVertex(op="Add"), f"{n}_pool", f"{n}_sc")
            prev = f"{n}_add"
        for i in range(4):  # middle flow (reference has 8; 4 keeps tests fast)
            n = f"mid{i}"
            src = prev
            for j in range(3):
                g.addLayer(f"{n}_s{j}", SeparableConvolution2D(
                    nOut=728, kernelSize=(3, 3), convolutionMode="Same",
                    activation="RELU"), prev)
                prev = f"{n}_s{j}"
            g.addVertex(f"{n}_add", ElementWiseVertex(op="Add"), prev, src)
            prev = f"{n}_add"
        g.addLayer("exit_s1", SeparableConvolution2D(nOut=1024, kernelSize=(3, 3),
                                                     convolutionMode="Same",
                                                     activation="RELU"), prev)
        g.addLayer("gap", GlobalPoolingLayer(poolingType="AVG"), "exit_s1")
        g.addLayer("output", OutputLayer(nIn=1024, nOut=self.numClasses,
                                         activation="SOFTMAX", lossFunction="MCXENT"), "gap")
        g.setOutputs("output")
        return g.build()


class TextGenerationLSTM(ZooModel):
    """(ref: zoo.model.TextGenerationLSTM — the GravesLSTM char-RNN,
    BASELINE config #3)."""

    def __init__(self, totalUniqueCharacters: int = 47, seed: int = 123,
                 lstmLayerSize: int = 200):
        super().__init__(totalUniqueCharacters, seed, (0, 0, 0))
        self.lstmLayerSize = lstmLayerSize

    def conf(self):
        n = self.numClasses
        return (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Adam(1e-3)).weightInit("XAVIER")
                .list()
                .layer(LSTM(nIn=n, nOut=self.lstmLayerSize, activation="TANH"))
                .layer(LSTM(nIn=self.lstmLayerSize, nOut=self.lstmLayerSize,
                            activation="TANH"))
                .layer(RnnOutputLayer(nIn=self.lstmLayerSize, nOut=n,
                                      activation="SOFTMAX", lossFunction="MCXENT"))
                .backpropType("TruncatedBPTT").tBPTTForwardLength(50)
                .tBPTTBackwardLength(50)
                .build())


class TinyYOLO(ZooModel):
    """(ref: zoo.model.TinyYOLO — Darknet-tiny backbone + Yolo2OutputLayer;
    default anchors from the VOC-trained reference config, grid units)."""

    DEFAULT_ANCHORS = ((1.08, 1.19), (3.42, 4.41), (6.63, 11.38),
                       (9.42, 5.11), (16.62, 10.52))

    def __init__(self, numClasses: int = 20, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 416, 416),
                 boundingBoxes=None):
        super().__init__(numClasses, seed, inputShape)
        self.boundingBoxes = tuple(boundingBoxes or self.DEFAULT_ANCHORS)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.layers import Yolo2OutputLayer
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER").list())

        def conv_bn(b, n_out):
            return (b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(3, 3),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"))
                    .layer(BatchNormalization(activation="LEAKYRELU")))

        for i, n_out in enumerate([16, 32, 64, 128, 256]):
            b = conv_bn(b, n_out)
            b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                         stride=(2, 2)))
        b = conv_bn(b, 512)
        b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                     stride=(1, 1), convolutionMode="Same"))
        b = conv_bn(b, 1024)
        A = len(self.boundingBoxes)
        return (b.layer(ConvolutionLayer(nOut=A * (5 + self.numClasses),
                                         kernelSize=(1, 1), activation="IDENTITY"))
                .layer(Yolo2OutputLayer(boundingBoxes=self.boundingBoxes))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class YOLO2(ZooModel):
    """(ref: zoo.model.YOLO2 — Darknet19 backbone + Yolo2OutputLayer).

    Deviation from the reference: the passthrough reorg (26x26 features
    SpaceToDepth'd and concatenated into the 13x13 head) needs a skip
    connection, which a sequential conf cannot express — this build is the
    straight-through backbone only. Use ``graph_conf()`` for the faithful
    passthrough variant."""

    def graph_conf(self):
        """ComputationGraph variant WITH the passthrough: conv13's 26x26x512
        features go through 1x1 conv(64) + SpaceToDepth(2) and merge into the
        13x13 head (the reference's reorg route)."""
        from deeplearning4j_tpu.nn.conf.layers import SpaceToDepthLayer, Yolo2OutputLayer
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER").graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, n_out, k, frm):
            g.addLayer(f"{name}c", ConvolutionLayer(nOut=n_out, kernelSize=(k, k),
                                                    convolutionMode="Same", hasBias=False,
                                                    activation="IDENTITY"), frm)
            g.addLayer(name, BatchNormalization(activation="LEAKYRELU"), f"{name}c")
            return name

        spec = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False),
                (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False),
                (512, 1, False), (1024, 3, False),
                (1024, 3, False), (1024, 3, False)]
        prev, passthrough = "input", None
        for i, (n_out, k, pool) in enumerate(spec):
            prev = conv_bn(f"b{i}", n_out, k, prev)
            if i == 12:
                passthrough = prev  # conv13 output, 26x26x512, pre-pool
            if pool:
                g.addLayer(f"b{i}p", SubsamplingLayer(poolingType="MAX",
                                                      kernelSize=(2, 2), stride=(2, 2)),
                           prev)
                prev = f"b{i}p"
        pt = conv_bn("pt", 64, 1, passthrough)
        g.addLayer("pt_s2d", SpaceToDepthLayer(blockSize=2), pt)  # 13x13x256
        g.addVertex("cat", MergeVertex(), "pt_s2d", prev)
        head = conv_bn("head", 1024, 3, "cat")
        A = len(self.boundingBoxes)
        g.addLayer("det", ConvolutionLayer(nOut=A * (5 + self.numClasses),
                                           kernelSize=(1, 1), activation="IDENTITY"),
                   head)
        g.addLayer("output", Yolo2OutputLayer(boundingBoxes=self.boundingBoxes), "det")
        g.setOutputs("output")
        return g.build()

    DEFAULT_ANCHORS = ((0.57273, 0.677385), (1.87446, 2.06253),
                       (3.33843, 5.47434), (7.88282, 3.52778),
                       (9.77052, 9.16828))

    def __init__(self, numClasses: int = 80, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 608, 608),
                 boundingBoxes=None):
        super().__init__(numClasses, seed, inputShape)
        self.boundingBoxes = tuple(boundingBoxes or self.DEFAULT_ANCHORS)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.layers import Yolo2OutputLayer
        c, h, w = self.inputShape
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("XAVIER").list())

        def conv_bn(b, n_out, k):
            return (b.layer(ConvolutionLayer(nOut=n_out, kernelSize=(k, k),
                                             convolutionMode="Same", hasBias=False,
                                             activation="IDENTITY"))
                    .layer(BatchNormalization(activation="LEAKYRELU")))

        spec = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False), (256, 1, False),
                (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False),
                (512, 1, False), (1024, 3, False),
                (1024, 3, False), (1024, 3, False)]
        for n_out, k, pool in spec:
            b = conv_bn(b, n_out, k)
            if pool:
                b = b.layer(SubsamplingLayer(poolingType="MAX", kernelSize=(2, 2),
                                             stride=(2, 2)))
        A = len(self.boundingBoxes)
        return (b.layer(ConvolutionLayer(nOut=A * (5 + self.numClasses),
                                         kernelSize=(1, 1), activation="IDENTITY"))
                .layer(Yolo2OutputLayer(boundingBoxes=self.boundingBoxes))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class InceptionResNetV1(ZooModel):
    """(ref: zoo.model.InceptionResNetV1 — the FaceNet backbone: stem,
    scaled-residual Inception-ResNet A/B/C blocks with reductions, global
    pool, bottleneck embedding). Block counts are configurable (reference:
    5/10/5) so tests instantiate shallow variants; the 1x1-linear-then-
    ScaleVertex-then-ElementWiseAdd residual wiring is the reference's.
    Ends with an L2-normalized ``embeddings`` output feeding a softmax
    classification head (the reference trains it the same way and reads the
    embedding layer at inference)."""

    def __init__(self, numClasses: int = 1000, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 160, 160),
                 embeddingSize: int = 128, blocks: Tuple[int, int, int] = (5, 10, 5)):
        super().__init__(numClasses, seed, inputShape)
        self.embeddingSize = embeddingSize
        self.blocks = blocks

    def conf(self):
        from deeplearning4j_tpu.nn.conf.graph import ScaleVertex, L2NormalizeVertex
        from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                                       GlobalPoolingLayer)
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("RELU").graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv(name, frm, n_out, k, stride=1, act="RELU", same=True):
            g.addLayer(name, ConvolutionLayer(
                nOut=n_out, kernelSize=k if isinstance(k, tuple) else (k, k),
                stride=(stride, stride),
                convolutionMode="Same" if same else "Truncate",
                activation=act), frm)
            return name

        # stem (ref: InceptionResNetV1.inputBlock)
        prev = conv("stem1", "input", 32, 3, 2, same=False)
        prev = conv("stem2", prev, 32, 3, same=False)
        prev = conv("stem3", prev, 64, 3)
        g.addLayer("stem_pool", SubsamplingLayer(poolingType="MAX",
                                                 kernelSize=(3, 3), stride=(2, 2)),
                   prev)
        prev = conv("stem4", "stem_pool", 80, 1)
        prev = conv("stem5", prev, 192, 3, same=False)
        prev = conv("stem6", prev, 256, 3, 2, same=False)

        def residual_block(name, frm, branches, filters, scale):
            """branches: list of [(n_out, kernel), ...] chains; concat ->
            1x1 linear to `filters` -> scale -> add -> relu."""
            outs = []
            for bi, chain in enumerate(branches):
                p = frm
                for ci, (n_out, k) in enumerate(chain):
                    p = conv(f"{name}_b{bi}c{ci}", p, n_out, k)
                outs.append(p)
            g.addVertex(f"{name}_cat", MergeVertex(), *outs)
            conv(f"{name}_up", f"{name}_cat", filters, 1, act="IDENTITY")
            g.addVertex(f"{name}_scale", ScaleVertex(scaleFactor=scale),
                        f"{name}_up")
            g.addVertex(f"{name}_add", ElementWiseVertex(op="Add"), frm,
                        f"{name}_scale")
            g.addLayer(f"{name}_relu", ActivationLayer(activation="RELU"),
                       f"{name}_add")
            return f"{name}_relu"

        a, b_, c_ = self.blocks
        for i in range(a):  # Inception-ResNet-A (block35)
            prev = residual_block(f"a{i}", prev,
                                  [[(32, 1)], [(32, 1), (32, 3)],
                                   [(32, 1), (32, 3), (32, 3)]], 256, 0.17)
        # reduction-A
        ra = [conv("redA_b0", prev, 384, 3, 2, same=False),
              conv("redA_b1c2",
                   conv("redA_b1c1", conv("redA_b1c0", prev, 192, 1), 192, 3),
                   256, 3, 2, same=False)]
        g.addLayer("redA_pool", SubsamplingLayer(poolingType="MAX",
                                                 kernelSize=(3, 3), stride=(2, 2)),
                   prev)
        g.addVertex("redA", MergeVertex(), *ra, "redA_pool")
        prev = "redA"
        for i in range(b_):  # Inception-ResNet-B (block17), asymmetric 1x7/7x1
            prev = residual_block(f"b{i}", prev,
                                  [[(128, 1)],
                                   [(128, 1), (128, (1, 7)), (128, (7, 1))]],
                                  896, 0.10)
        # reduction-B
        rb = [conv("redB_b0c1", conv("redB_b0c0", prev, 256, 1), 384, 3, 2, same=False),
              conv("redB_b1c1", conv("redB_b1c0", prev, 256, 1), 256, 3, 2, same=False),
              conv("redB_b2c2",
                   conv("redB_b2c1", conv("redB_b2c0", prev, 256, 1), 256, 3),
                   256, 3, 2, same=False)]
        g.addLayer("redB_pool", SubsamplingLayer(poolingType="MAX",
                                                 kernelSize=(3, 3), stride=(2, 2)),
                   prev)
        g.addVertex("redB", MergeVertex(), *rb, "redB_pool")
        prev = "redB"
        for i in range(c_):  # Inception-ResNet-C (block8), asymmetric 1x3/3x1
            prev = residual_block(f"c{i}", prev,
                                  [[(192, 1)],
                                   [(192, 1), (192, (1, 3)), (192, (3, 1))]],
                                  1792, 0.20)

        g.addLayer("avgpool", GlobalPoolingLayer(poolingType="AVG"), prev)
        g.addLayer("bottleneck", DenseLayer(nOut=self.embeddingSize,
                                            activation="IDENTITY"), "avgpool")
        g.addVertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.addLayer("output", OutputLayer(nOut=self.numClasses,
                                         lossFunction="MCXENT"), "embeddings")
        g.setOutputs("output")
        return g.build()


class FaceNetNN4Small2(ZooModel):
    """(ref: zoo.model.FaceNetNN4Small2Deep — OpenFace's nn4.small2
    inception stack trained with CENTER LOSS on identities; embeddings read
    from the L2-normalized 128-d bottleneck).

    Topology follows the public nn4.small2 definition exactly: conv1 7x7/2
    -> maxpool -> LRN -> conv2 1x1 -> conv3 3x3 -> LRN -> maxpool ->
    inception 3a/3b/3c -> 4a/4e -> 5a/5b (mixed 1x1 / reduced-3x3 /
    reduced-5x5 branches with MAX or L2 (p-norm, p=2) pool projections;
    3c/4e are the stride-2 grid reductions with pass-through pools) ->
    global avgpool -> 128-d linear -> L2 normalize. Every conv carries
    batch-norm + ReLU, as in the reference."""

    def __init__(self, numClasses: int = 100, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 96, 96),
                 embeddingSize: int = 128, alpha: float = 0.5,
                 lambda_: float = 3e-3):
        super().__init__(numClasses, seed, inputShape)
        self.embeddingSize = embeddingSize
        self.alpha = alpha
        self.lambda_ = lambda_

    def conf(self):
        from deeplearning4j_tpu.nn.conf.graph import L2NormalizeVertex
        from deeplearning4j_tpu.nn.conf.layers import (
            ActivationLayer, BatchNormalization, CenterLossOutputLayer,
            GlobalPoolingLayer, LocalResponseNormalization)
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("RELU").graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, frm, n_out, k, stride=1):
            """conv -> BN -> ReLU (nn4 uses SpatialBatchNormalization)."""
            g.addLayer(f"{name}_c", ConvolutionLayer(
                nOut=n_out, kernelSize=(k, k), stride=(stride, stride),
                convolutionMode="Same", activation="IDENTITY"), frm)
            g.addLayer(f"{name}_bn", BatchNormalization(), f"{name}_c")
            g.addLayer(name, ActivationLayer(activation="RELU"), f"{name}_bn")
            return name

        def inception(name, frm, n1, r3, n3, r5, n5, pool_kind, pool_proj,
                      stride=1):
            """nn4 inception module. n1=0 drops the 1x1 branch (3c/4e);
            r5=0 drops the 5x5 branch (5a/5b); pool_proj=0 passes the pool
            through unprojected (the stride-2 modules)."""
            branches = []
            if n1:
                branches.append(conv_bn(f"{name}_1x1", frm, n1, 1))
            branches.append(conv_bn(
                f"{name}_3x3", conv_bn(f"{name}_3x3r", frm, r3, 1), n3, 3,
                stride))
            if r5:
                branches.append(conv_bn(
                    f"{name}_5x5", conv_bn(f"{name}_5x5r", frm, r5, 1), n5, 5,
                    stride))
            pool = f"{name}_pool"
            g.addLayer(pool, SubsamplingLayer(
                poolingType="MAX" if pool_kind == "max" else "PNORM",
                pnorm=2, kernelSize=(3, 3), stride=(stride, stride),
                convolutionMode="Same"), frm)
            branches.append(conv_bn(f"{name}_poolproj", pool, pool_proj, 1)
                            if pool_proj else pool)
            g.addVertex(name, MergeVertex(), *branches)
            return name

        prev = conv_bn("conv1", "input", 64, 7, 2)
        g.addLayer("pool1", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                             stride=(2, 2),
                                             convolutionMode="Same"), prev)
        g.addLayer("lrn1", LocalResponseNormalization(), "pool1")
        prev = conv_bn("conv2", "lrn1", 64, 1)
        prev = conv_bn("conv3", prev, 192, 3)
        g.addLayer("lrn2", LocalResponseNormalization(), prev)
        g.addLayer("pool2", SubsamplingLayer(poolingType="MAX", kernelSize=(3, 3),
                                             stride=(2, 2),
                                             convolutionMode="Same"), "lrn2")
        # (n1, r3, n3, r5, n5, pool, proj, stride) per the nn4.small2 table
        prev = inception("inc3a", "pool2", 64, 96, 128, 16, 32, "max", 32)
        prev = inception("inc3b", prev, 64, 96, 128, 32, 64, "l2", 64)
        prev = inception("inc3c", prev, 0, 128, 256, 32, 64, "max", 0, stride=2)
        prev = inception("inc4a", prev, 256, 96, 192, 32, 64, "l2", 128)
        prev = inception("inc4e", prev, 0, 160, 256, 64, 128, "max", 0, stride=2)
        prev = inception("inc5a", prev, 256, 96, 384, 0, 0, "l2", 96)
        prev = inception("inc5b", prev, 256, 96, 384, 0, 0, "max", 96)
        g.addLayer("avgpool", GlobalPoolingLayer(poolingType="AVG"), prev)
        g.addLayer("bottleneck", DenseLayer(nOut=self.embeddingSize,
                                            activation="IDENTITY"), "avgpool")
        g.addVertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.addLayer("output", CenterLossOutputLayer(
            nOut=self.numClasses, alpha=self.alpha, lambda_=self.lambda_,
            lossFunction="MCXENT"), "embeddings")
        g.setOutputs("output")
        return g.build()


class NASNetMobile(ZooModel):
    """(ref: zoo.model.NASNet — NASNet-A cells). Normal cells combine
    separable-conv/pool/identity pairs on (h, h_prev) with 5 block outputs
    concatenated; reduction cells halve the spatial dims. Cell count and
    penultimate-filter width are configurable (reference mobile: 4 cells @
    1056 penultimate). After each reduction, h_prev stays at the old
    resolution and the next cell's adjust block applies the reference's
    FACTORIZED REDUCTION: two 1x1-stride-2 average-pool paths, the second
    offset one pixel, concatenated and batch-normed."""

    def __init__(self, numClasses: int = 1000, seed: int = 123,
                 inputShape: Tuple[int, int, int] = (3, 224, 224),
                 cells_per_stage: int = 2, stem_filters: int = 32,
                 filters: int = 44):
        super().__init__(numClasses, seed, inputShape)
        self.cells_per_stage = cells_per_stage
        self.stem_filters = stem_filters
        self.filters = filters

    def conf(self):
        from deeplearning4j_tpu.nn.conf.layers import (
            BatchNormalization, Cropping2D, GlobalPoolingLayer,
            ZeroPaddingLayer)
        c, h, w = self.inputShape
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weightInit("RELU").graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))
        uid = [0]
        # spatial-resolution level per tensor name (increments at each
        # stride-2 reduction) — drives h_prev factorized reduction
        res: dict = {}

        def sep(frm, n_out, k, stride=1):
            uid[0] += 1
            name = f"sep{uid[0]}"
            g.addLayer(name, SeparableConvolution2D(
                nOut=n_out, kernelSize=(k, k), stride=(stride, stride),
                convolutionMode="Same", activation="RELU"), frm)
            return name

        def factorized_reduction(frm, n_out):
            """Stride-2 downsample without information loss at the grid
            boundary (ref: NASNet's FactorizedReduction / adjust_block):
            two 1x1-stride-2 average-pool paths, the second offset by one
            pixel, each 1x1-conv'd to n_out/2, concatenated, batch-normed."""
            uid[0] += 1
            base = f"fr{uid[0]}"
            g.addLayer(f"{base}_p1", SubsamplingLayer(
                poolingType="AVG", kernelSize=(1, 1), stride=(2, 2)), frm)
            g.addLayer(f"{base}_c1", ConvolutionLayer(
                nOut=n_out // 2, kernelSize=(1, 1), activation="IDENTITY"),
                f"{base}_p1")
            # offset path: shift the grid by (1,1) so the concat covers the
            # pixels the first path's stride skipped
            g.addLayer(f"{base}_pad", ZeroPaddingLayer(padding=(0, 1, 0, 1)), frm)
            g.addLayer(f"{base}_crop", Cropping2D(cropping=(1, 0, 1, 0)),
                       f"{base}_pad")
            g.addLayer(f"{base}_p2", SubsamplingLayer(
                poolingType="AVG", kernelSize=(1, 1), stride=(2, 2)),
                f"{base}_crop")
            g.addLayer(f"{base}_c2", ConvolutionLayer(
                nOut=n_out - n_out // 2, kernelSize=(1, 1),
                activation="IDENTITY"), f"{base}_p2")
            g.addVertex(f"{base}_cat", MergeVertex(), f"{base}_c1", f"{base}_c2")
            g.addLayer(base, BatchNormalization(), f"{base}_cat")
            return base

        def adjust(frm, n_out, target_res=None):
            """Match h_prev to the cell's filter count — and, when it sits
            one resolution level behind (the cell right after a reduction),
            bring it down via factorized reduction (ref: adjust_block)."""
            if target_res is not None and res.get(frm, target_res) < target_res:
                name = factorized_reduction(frm, n_out)
                res[name] = target_res
                return name
            uid[0] += 1
            name = f"adj{uid[0]}"
            g.addLayer(name, ConvolutionLayer(
                nOut=n_out, kernelSize=(1, 1), activation="RELU"), frm)
            res[name] = res.get(frm, 0)
            return name

        def pool(frm, kind, stride=1):
            uid[0] += 1
            name = f"pool{uid[0]}"
            g.addLayer(name, SubsamplingLayer(
                poolingType=kind, kernelSize=(3, 3), stride=(stride, stride),
                convolutionMode="Same"), frm)
            return name

        def add(a, b):
            uid[0] += 1
            name = f"add{uid[0]}"
            g.addVertex(name, ElementWiseVertex(op="Add"), a, b)
            return name

        def normal_cell(h_cur, h_prev, f):
            """NASNet-A normal cell: 5 combinations concat'd."""
            hc = adjust(h_cur, f)
            hp = adjust(h_prev, f, target_res=res.get(h_cur, 0))
            b1 = add(sep(hc, f, 3), hc)
            b2 = add(sep(hp, f, 3), sep(hc, f, 5))
            b3 = add(pool(hp, "AVG"), hp)
            b4 = add(pool(hp, "AVG"), pool(hp, "AVG"))
            b5 = add(sep(hp, f, 5), sep(hp, f, 3))
            uid[0] += 1
            name = f"ncell{uid[0]}"
            g.addVertex(name, MergeVertex(), b1, b2, b3, b4, b5)
            res[name] = res.get(h_cur, 0)
            return name

        def reduction_cell(h_cur, h_prev, f):
            hc = adjust(h_cur, f)
            hp = adjust(h_prev, f, target_res=res.get(h_cur, 0))
            b1 = add(sep(hc, f, 5, 2), sep(hp, f, 7, 2))
            b2 = add(pool(hc, "MAX", 2), sep(hp, f, 7, 2))
            b3 = add(pool(hc, "AVG", 2), sep(hp, f, 5, 2))
            b4 = add(pool(b1, "AVG"), b2)
            b5 = add(sep(b1, f, 3), pool(hc, "MAX", 2))
            uid[0] += 1
            name = f"rcell{uid[0]}"
            g.addVertex(name, MergeVertex(), b2, b3, b4, b5)
            res[name] = res.get(h_cur, 0) + 1
            return name

        g.addLayer("stem", ConvolutionLayer(nOut=self.stem_filters,
                                            kernelSize=(3, 3), stride=(2, 2),
                                            convolutionMode="Same",
                                            activation="RELU"), "input")
        res["stem"] = 0
        h_prev, h_cur = "stem", "stem"
        f = self.filters
        for stage in range(3):
            if stage > 0:
                f *= 2
                nxt = reduction_cell(h_cur, h_prev, f)
                # the reference keeps h_prev at the OLD resolution here; the
                # next cell's adjust() brings it down via factorized
                # reduction (two offset stride-2 avg-pool paths, concat, BN)
                h_prev, h_cur = h_cur, nxt
            for _ in range(self.cells_per_stage):
                nxt = normal_cell(h_cur, h_prev, f)
                h_prev, h_cur = h_cur, nxt
        g.addLayer("gap", GlobalPoolingLayer(poolingType="AVG"), h_cur)
        g.addLayer("output", OutputLayer(nOut=self.numClasses,
                                         lossFunction="MCXENT"), "gap")
        g.setOutputs("output")
        return g.build()
