"""Early stopping trainer (ref: org.deeplearning4j.earlystopping —
EarlyStoppingConfiguration.Builder, EarlyStoppingTrainer, termination
conditions (MaxEpochs, ScoreImprovementEpochs, MaxTime, MaxScore), model
savers (InMemoryModelSaver, LocalFileModelSaver), EarlyStoppingResult)."""
from __future__ import annotations

import copy
import os
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional


# ---------------------------------------------------------------- conditions
class MaxEpochsTerminationCondition:
    """(ref: termination.MaxEpochsTerminationCondition)."""

    def __init__(self, maxEpochs: int):
        self.maxEpochs = maxEpochs

    def terminate_epoch(self, epoch: int, score: float, best: float) -> bool:
        return epoch + 1 >= self.maxEpochs


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs without improvement (ref: same name).

    Stateful: EarlyStoppingTrainer.fit() calls reset() at the start of every
    run so a configuration can be reused. Only invoked on epochs where a score
    was actually computed (requires_score flag)."""

    requires_score = True

    def __init__(self, maxEpochsWithNoImprovement: int, minImprovement: float = 0.0):
        self.patience = maxEpochsWithNoImprovement
        self.minImprovement = minImprovement
        self.reset()

    def reset(self):
        self._best = float("inf")
        self._since = 0

    def terminate_epoch(self, epoch: int, score: float, best: float) -> bool:
        if score < self._best - self.minImprovement:
            self._best = score
            self._since = 0
            return False
        self._since += 1
        return self._since > self.patience


class MaxTimeIterationTerminationCondition:
    """Wall-clock bound (ref: MaxTimeIterationTerminationCondition)."""

    def __init__(self, maxTimeSeconds: float):
        self.maxTime = maxTimeSeconds
        self.reset()

    def reset(self):
        self._start = time.perf_counter()

    def terminate_iteration(self, score: float) -> bool:
        return (time.perf_counter() - self._start) > self.maxTime


class MaxScoreIterationTerminationCondition:
    """Abort on diverging score (ref: MaxScoreIterationTerminationCondition)."""

    def __init__(self, maxScore: float):
        self.maxScore = maxScore

    def terminate_iteration(self, score: float) -> bool:
        return score > self.maxScore or score != score  # NaN counts


# -------------------------------------------------------------------- savers
class InMemoryModelSaver:
    """(ref: saver.InMemoryModelSaver)."""

    def __init__(self):
        self._best = None
        self._latest = None

    def saveBestModel(self, model, score: float):
        self._best = model.clone()

    def saveLatestModel(self, model, score: float):
        self._latest = model.clone()

    def getBestModel(self):
        return self._best

    def getLatestModel(self):
        return self._latest


class LocalFileModelSaver:
    """(ref: saver.LocalFileModelSaver) — bestModel.zip / latestModel.zip."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.dir, name)

    def saveBestModel(self, model, score: float):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        ModelSerializer.writeModel(model, self._path("bestModel.zip"), True)

    def saveLatestModel(self, model, score: float):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        ModelSerializer.writeModel(model, self._path("latestModel.zip"), True)

    def getBestModel(self):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        p = self._path("bestModel.zip")
        return ModelSerializer.restoreModel(p) if os.path.exists(p) else None

    def getLatestModel(self):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        p = self._path("latestModel.zip")
        return ModelSerializer.restoreModel(p) if os.path.exists(p) else None


# ---------------------------------------------------------- score calculator
class DataSetLossCalculator:
    """Holdout loss as the early-stopping score (ref: scorecalc.
    DataSetLossCalculator)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculateScore(self, model) -> float:
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        total, n = 0.0, 0
        for ds in self.iterator:
            total += model.score(ds)
            n += 1
        return total / max(n, 1) if self.average else total


# ---------------------------------------------------------------- config
@dataclass
class EarlyStoppingConfiguration:
    """(ref: EarlyStoppingConfiguration.Builder)."""
    epochTerminationConditions: List[Any] = field(default_factory=list)
    iterationTerminationConditions: List[Any] = field(default_factory=list)
    scoreCalculator: Optional[Any] = None
    modelSaver: Any = field(default_factory=InMemoryModelSaver)
    evaluateEveryNEpochs: int = 1
    saveLastModel: bool = False

    class Builder:
        def __init__(self):
            self._c = EarlyStoppingConfiguration()

        def epochTerminationConditions(self, *conds):
            self._c.epochTerminationConditions = list(conds)
            return self

        def iterationTerminationConditions(self, *conds):
            self._c.iterationTerminationConditions = list(conds)
            return self

        def scoreCalculator(self, sc):
            self._c.scoreCalculator = sc
            return self

        def modelSaver(self, saver):
            self._c.modelSaver = saver
            return self

        def evaluateEveryNEpochs(self, n: int):
            self._c.evaluateEveryNEpochs = n
            return self

        def saveLastModel(self, b: bool):
            self._c.saveLastModel = b
            return self

        def build(self) -> "EarlyStoppingConfiguration":
            return self._c


@dataclass
class EarlyStoppingResult:
    """(ref: EarlyStoppingResult)."""
    terminationReason: str
    terminationDetails: str
    scoreVsEpoch: dict
    bestModelEpoch: int
    bestModelScore: float
    totalEpochs: int
    bestModel: Any


class _IterationGuard:
    """Listener bridging iteration termination conditions into fit."""

    def __init__(self, conds):
        self.conds = conds
        self.tripped: Optional[str] = None

    def iterationDone(self, model, iteration, epoch):
        for c in self.conds:
            if c.terminate_iteration(model.score()):
                self.tripped = type(c).__name__
                raise _StopTraining


class _StopTraining(Exception):
    _control_flow = True  # not a crash: fit()'s dump-and-reraise skips it

    pass


class EarlyStoppingTrainer:
    """(ref: EarlyStoppingTrainer / BaseEarlyStoppingTrainer.fit loop)."""

    def __init__(self, config: EarlyStoppingConfiguration, model, trainData):
        self.config = config
        self.model = model
        self.trainData = trainData

    def _fit_epoch(self):
        """One epoch of training; subclasses swap the executor (the parallel
        trainer routes through ParallelWrapper)."""
        self.model.fit(self.trainData)

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        score_vs_epoch = {}
        best_score, best_epoch = float("inf"), -1
        reason, details = "EpochTerminationCondition", ""
        for c in list(cfg.epochTerminationConditions) + list(
                cfg.iterationTerminationConditions):
            if hasattr(c, "reset"):
                c.reset()
        guard = _IterationGuard(cfg.iterationTerminationConditions)
        saved_listeners = list(self.model.listeners)
        if cfg.iterationTerminationConditions:
            self.model.addListeners(guard)
        epoch = 0
        try:
            while True:
                if hasattr(self.trainData, "reset"):
                    self.trainData.reset()
                try:
                    self._fit_epoch()
                except _StopTraining:
                    reason = "IterationTerminationCondition"
                    details = guard.tripped or ""
                    break
                if epoch % cfg.evaluateEveryNEpochs == 0:
                    score = (cfg.scoreCalculator.calculateScore(self.model)
                             if cfg.scoreCalculator else self.model.score())
                    score_vs_epoch[epoch] = score
                    if score < best_score:
                        best_score, best_epoch = score, epoch
                        cfg.modelSaver.saveBestModel(self.model, score)
                    if cfg.saveLastModel:
                        cfg.modelSaver.saveLatestModel(self.model, score)
                stop = False
                for c in cfg.epochTerminationConditions:
                    if getattr(c, "requires_score", False) and epoch not in score_vs_epoch:
                        continue  # non-evaluation epoch: no score to judge
                    if c.terminate_epoch(epoch, score_vs_epoch.get(epoch, best_score),
                                         best_score):
                        details = type(c).__name__
                        stop = True
                        break
                if stop:
                    break
                epoch += 1
        finally:
            self.model.listeners = saved_listeners
        # only consult the saver if THIS run saved a best model — a reused
        # saver may hold a previous run's (stale) best
        best = (cfg.modelSaver.getBestModel() if best_epoch >= 0 else None) \
            or self.model
        return EarlyStoppingResult(
            terminationReason=reason, terminationDetails=details,
            scoreVsEpoch=score_vs_epoch, bestModelEpoch=best_epoch,
            bestModelScore=best_score, totalEpochs=epoch + 1, bestModel=best)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping over sharded data-parallel training (ref:
    org.deeplearning4j.parallelism.EarlyStoppingParallelTrainer — the
    reference threads replicas; here each epoch runs through
    ParallelWrapper's lockstep-psum jit, and scoring/saving read the single
    authoritative model the wrapper trains in place)."""

    def __init__(self, config, model, trainData, mesh=None, workers=None):
        super().__init__(config, model, trainData)
        from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper
        self.wrapper = ParallelWrapper(model, mesh=mesh, workers=workers)

    def _fit_epoch(self):
        self.wrapper.fit(self.trainData)
