"""Early stopping (ref: org.deeplearning4j.earlystopping.*)."""
from deeplearning4j_tpu.earlystopping.trainer import (
    EarlyStoppingConfiguration, EarlyStoppingResult, EarlyStoppingTrainer,
    InMemoryModelSaver, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition, ScoreImprovementEpochTerminationCondition,
    DataSetLossCalculator)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult", "EarlyStoppingTrainer",
    "InMemoryModelSaver", "LocalFileModelSaver",
    "MaxEpochsTerminationCondition", "MaxScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition", "ScoreImprovementEpochTerminationCondition",
    "DataSetLossCalculator",
]
