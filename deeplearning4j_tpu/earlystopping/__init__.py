"""Early stopping (ref: org.deeplearning4j.earlystopping.*)."""
from deeplearning4j_tpu.earlystopping.trainer import (
    EarlyStoppingConfiguration, EarlyStoppingResult, EarlyStoppingTrainer,
    EarlyStoppingParallelTrainer,
    InMemoryModelSaver, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition, ScoreImprovementEpochTerminationCondition,
    DataSetLossCalculator)

__all__ = [
    "EarlyStoppingConfiguration", "EarlyStoppingResult", "EarlyStoppingTrainer",
    "EarlyStoppingParallelTrainer",
    "InMemoryModelSaver", "LocalFileModelSaver",
    "MaxEpochsTerminationCondition", "MaxScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition", "ScoreImprovementEpochTerminationCondition",
    "DataSetLossCalculator",
]
