"""Geo record transforms (ref: datavec-geo —
org.datavec.api.transform.transform.geo.IPAddressToLocationTransform backed
by MaxMind GeoIP2; SURVEY.md §2.3 misc readers).

The reference resolves IPs through a bundled GeoIP2 binary database. That
database is proprietary and this environment has zero egress, so the
TPU-native analog reads an open CSV network database (the format GeoLite2
CSV exports use: ``network,latitude,longitude,city``) through the stdlib
``ipaddress`` module. Point the transform at any such file — including a
real GeoLite2 CSV export — via the Resources cache or a direct path.
"""
from __future__ import annotations

import csv
import ipaddress
from bisect import bisect_right
from typing import List, Optional, Tuple

from deeplearning4j_tpu.datavec.records import RecordReader


def _is_network(cell: str) -> bool:
    try:
        ipaddress.ip_network(cell.strip())
        return True
    except ValueError:
        return False
from deeplearning4j_tpu.datavec.writables import (
    DoubleWritable,
    NullWritable,
    Text,
    Writable,
)


class IPLocationDatabase:
    """CIDR -> (lat, lon, label) lookup over a CSV network database.

    Accepted layouts (auto-detected from the header):
    - simple: ``network,latitude,longitude[,label]`` (header optional);
    - GeoLite2 Blocks export: header names the columns (``network``,
      ``latitude``, ``longitude``, label from ``geoname_id``); rows with
      blank coordinates are skipped.

    IPv4 and IPv6 networks live in separate keyspaces (an IPv6 address
    whose integer happens to fall inside an IPv4 range must NOT match),
    and nested CIDRs resolve to the most specific containing network.
    """

    def __init__(self, path: str):
        nets4: List[Tuple[int, int, Tuple[float, float, str]]] = []
        nets6: List[Tuple[int, int, Tuple[float, float, str]]] = []
        with open(path, newline="") as f:
            reader = csv.reader(f)
            rows = list(reader)
        cols = {"network": 0, "latitude": 1, "longitude": 2, "label": 3}
        start_row = 0
        if rows and not _is_network(rows[0][0]) \
                and "network" in [c.strip().lower() for c in rows[0]]:
            header = [c.strip().lower() for c in rows[0]]
            cols["network"] = header.index("network")
            cols["latitude"] = header.index("latitude")
            cols["longitude"] = header.index("longitude")
            if "label" in header:
                cols["label"] = header.index("label")
            elif "geoname_id" in header:
                cols["label"] = header.index("geoname_id")
            else:
                cols["label"] = None
            start_row = 1
        for row in rows[start_row:]:
            if not row or not row[cols["network"]].strip():
                continue
            lat_s = row[cols["latitude"]].strip() if cols["latitude"] < len(row) else ""
            lon_s = row[cols["longitude"]].strip() if cols["longitude"] < len(row) else ""
            if not lat_s or not lon_s:
                continue  # GeoLite2 rows without coordinates
            net = ipaddress.ip_network(row[cols["network"]].strip())
            label = ""
            if cols["label"] is not None and cols["label"] < len(row):
                label = row[cols["label"]].strip()
            loc = (float(lat_s), float(lon_s), label)
            target = nets4 if net.version == 4 else nets6
            target.append((int(net.network_address),
                           int(net.broadcast_address), loc))
        self._tables = {}
        for ver, nets in ((4, nets4), (6, nets6)):
            nets.sort()
            # prefix max of interval ends: lets lookup() walk left past
            # more-specific-but-non-containing subnets to find a supernet
            pmax, cur = [], -1
            for s, e, _ in nets:
                cur = max(cur, e)
                pmax.append(cur)
            self._tables[ver] = ([n[0] for n in nets], nets, pmax)

    def lookup(self, ip: str) -> Optional[Tuple[float, float, str]]:
        try:
            parsed = ipaddress.ip_address(ip.strip())
        except ValueError:
            return None
        starts, nets, pmax = self._tables[parsed.version]
        addr = int(parsed)
        i = bisect_right(starts, addr) - 1
        # walk left: the first containing interval is the most specific
        # (largest start); pmax prunes once no remaining interval can reach
        while i >= 0 and pmax[i] >= addr:
            if nets[i][0] <= addr <= nets[i][1]:
                return nets[i][2]
            i -= 1
        return None


class IPAddressToLocationTransform:
    """Column transform: replaces an IP string column with lat/lon(/label)
    columns (ref: IPAddressToLocationTransform). Works standalone on
    record lists; unresolvable IPs become NullWritable coordinates."""

    def __init__(self, db: IPLocationDatabase, column_index: int,
                 include_label: bool = False):
        self.db = db
        self.col = column_index
        self.include_label = include_label

    def map(self, record: List[Writable]) -> List[Writable]:
        ip = record[self.col].toString() if hasattr(record[self.col], "toString") \
            else str(record[self.col].value)
        loc = self.db.lookup(ip)
        if loc is None:
            repl: List[Writable] = [NullWritable(), NullWritable()]
            if self.include_label:
                repl.append(NullWritable())
        else:
            repl = [DoubleWritable(loc[0]), DoubleWritable(loc[1])]
            if self.include_label:
                repl.append(Text(loc[2]))
        return record[:self.col] + repl + record[self.col + 1:]


class GeoRecordReader(RecordReader):
    """Wraps another reader, applying the IP->location transform per record
    (ref: datavec-geo usage pattern: reader + transform in a pipeline).

    Deliberately NOT a TransformProcess step: steps are JSON-serializable
    (kind, spec) pairs, and this transform closes over a loaded database;
    a thin wrapper reader is simpler than threading a DB handle through the
    serde machinery."""

    def __init__(self, base: RecordReader, transform: IPAddressToLocationTransform):
        self.base = base
        self.transform = transform

    def initialize(self, split):
        self.base.initialize(split)
        return self

    def hasNext(self) -> bool:
        return self.base.hasNext()

    def next(self) -> List[Writable]:
        return self.transform.map(self.base.next())

    def reset(self):
        self.base.reset()
