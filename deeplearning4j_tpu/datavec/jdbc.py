"""JDBC-equivalent record reader (ref: datavec-jdbc
org.datavec.jdbc.records.reader.impl.jdbc.JDBCRecordReader — reads rows of a
SQL query as records; the reference takes a javax.sql.DataSource + query).

Python has no JDBC; the natural analog is a DB-API 2.0 connection (sqlite3
in the stdlib, or any driver with the same interface). The reader maps SQL
types to Writables exactly as the reference's JdbcWritableConverter does:
ints -> LongWritable, floats -> DoubleWritable, str -> Text, bytes ->
BytesWritable, NULL -> NullWritable.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.writables import (
    BooleanWritable,
    BytesWritable,
    DoubleWritable,
    LongWritable,
    NullWritable,
    Text,
    Writable,
)


def _to_writable(v: Any) -> Writable:
    if v is None:
        return NullWritable()
    if isinstance(v, bool):
        return BooleanWritable(v)
    if isinstance(v, int):
        return LongWritable(v)
    if isinstance(v, float):
        return DoubleWritable(v)
    if isinstance(v, (bytes, bytearray, memoryview)):
        return BytesWritable(bytes(v))
    return Text(str(v))


class JdbcRecordReader(RecordReader):
    """(ref: JDBCRecordReader). ``conn`` is any DB-API connection; the query
    runs on initialize()/reset() so the reader is re-iterable."""

    def __init__(self, conn, query: str,
                 params: Optional[Sequence[Any]] = None):
        self._conn = conn
        self._query = query
        self._params = tuple(params or ())
        self._rows: Optional[List[tuple]] = None
        self._pos = 0
        self._columns: List[str] = []

    def initialize(self, split=None):
        cur = self._conn.cursor()
        cur.execute(self._query, self._params)
        self._columns = [d[0] for d in cur.description or []]
        self._rows = cur.fetchall()
        cur.close()
        self._pos = 0
        return self

    # metadata parity with the reference's record metadata
    def getLabels(self) -> List[str]:
        return list(self._columns)

    def hasNext(self) -> bool:
        if self._rows is None:
            self.initialize()
        return self._pos < len(self._rows)

    def next(self) -> List[Writable]:
        if not self.hasNext():
            raise StopIteration
        row = self._rows[self._pos]
        self._pos += 1
        return [_to_writable(v) for v in row]

    def reset(self):
        self.initialize()
