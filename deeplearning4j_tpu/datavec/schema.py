"""Schema (ref: datavec-api org.datavec.api.transform.schema.Schema — typed
column metadata flowing through TransformProcess)."""
from __future__ import annotations

import json
from typing import List, Optional, Sequence


class ColumnType:
    Double = "Double"
    Float = "Float"
    Integer = "Integer"
    Long = "Long"
    Categorical = "Categorical"
    String = "String"
    Boolean = "Boolean"
    Time = "Time"
    NDArray = "NDArray"


class ColumnMeta:
    def __init__(self, name: str, ctype: str, stateNames: Optional[Sequence[str]] = None):
        self.name = name
        self.type = ctype
        self.stateNames = list(stateNames) if stateNames else None

    def to_dict(self):
        return {"name": self.name, "type": self.type, "stateNames": self.stateNames}

    @staticmethod
    def from_dict(d):
        return ColumnMeta(d["name"], d["type"], d.get("stateNames"))


class Schema:
    """(ref: Schema + Schema.Builder)."""

    def __init__(self, columns: Optional[List[ColumnMeta]] = None):
        self.columns: List[ColumnMeta] = columns or []

    # ---- query
    def numColumns(self) -> int:
        return len(self.columns)

    def getColumnNames(self) -> List[str]:
        return [c.name for c in self.columns]

    def getIndexOfColumn(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise ValueError(f"no column {name}")

    def getType(self, name_or_idx) -> str:
        if isinstance(name_or_idx, int):
            return self.columns[name_or_idx].type
        return self.columns[self.getIndexOfColumn(name_or_idx)].type

    def getMetaData(self, name: str) -> ColumnMeta:
        return self.columns[self.getIndexOfColumn(name)]

    def to_json(self) -> str:
        return json.dumps({"columns": [c.to_dict() for c in self.columns]}, indent=2)

    @staticmethod
    def from_json(s: str) -> "Schema":
        return Schema([ColumnMeta.from_dict(d) for d in json.loads(s)["columns"]])

    class Builder:
        def __init__(self):
            self._cols: List[ColumnMeta] = []

        def addColumnDouble(self, name: str):
            self._cols.append(ColumnMeta(name, ColumnType.Double))
            return self

        def addColumnFloat(self, name: str):
            self._cols.append(ColumnMeta(name, ColumnType.Float))
            return self

        def addColumnInteger(self, name: str):
            self._cols.append(ColumnMeta(name, ColumnType.Integer))
            return self

        def addColumnLong(self, name: str):
            self._cols.append(ColumnMeta(name, ColumnType.Long))
            return self

        def addColumnCategorical(self, name: str, *stateNames: str):
            states = list(stateNames[0]) if len(stateNames) == 1 and \
                isinstance(stateNames[0], (list, tuple)) else list(stateNames)
            self._cols.append(ColumnMeta(name, ColumnType.Categorical, states))
            return self

        def addColumnString(self, name: str):
            self._cols.append(ColumnMeta(name, ColumnType.String))
            return self

        def addColumnBoolean(self, name: str):
            self._cols.append(ColumnMeta(name, ColumnType.Boolean))
            return self

        def addColumnTime(self, name: str, timezone: str = "UTC"):
            self._cols.append(ColumnMeta(name, ColumnType.Time))
            return self

        def addColumnsDouble(self, *names: str):
            for n in names:
                self.addColumnDouble(n)
            return self

        def addColumnsInteger(self, *names: str):
            for n in names:
                self.addColumnInteger(n)
            return self

        def addColumnsString(self, *names: str):
            for n in names:
                self.addColumnString(n)
            return self

        def build(self) -> "Schema":
            return Schema(list(self._cols))
