"""Input splits (ref: datavec-api org.datavec.api.split.* — enumerate the
locations a RecordReader pulls from)."""
from __future__ import annotations

import glob
import os
import random
from typing import List, Optional, Sequence


class InputSplit:
    def locations(self) -> List[str]:
        raise NotImplementedError

    def length(self) -> int:
        return len(self.locations())


class FileSplit(InputSplit):
    """(ref: org.datavec.api.split.FileSplit) — a file, or a directory
    recursively enumerated with optional extension filter + shuffle."""

    def __init__(self, path: str, allowFormats: Optional[Sequence[str]] = None,
                 recursive: bool = True, rngSeed: Optional[int] = None):
        self.path = str(path)
        self.formats = tuple(f.lstrip(".").lower() for f in (allowFormats or ()))
        self.recursive = recursive
        self.seed = rngSeed

    def locations(self) -> List[str]:
        if os.path.isfile(self.path):
            return [self.path]
        out = []
        walker = os.walk(self.path) if self.recursive else \
            [(self.path, [], os.listdir(self.path))]
        for root, _dirs, files in walker:
            for f in sorted(files):
                p = os.path.join(root, f)
                if not os.path.isfile(p):
                    continue
                if self.formats and f.rsplit(".", 1)[-1].lower() not in self.formats:
                    continue
                out.append(p)
        out.sort()
        if self.seed is not None:
            random.Random(self.seed).shuffle(out)
        return out


class CollectionInputSplit(InputSplit):
    def __init__(self, paths: Sequence[str]):
        self._paths = list(paths)

    def locations(self) -> List[str]:
        return list(self._paths)


class NumberedFileInputSplit(InputSplit):
    """(ref: NumberedFileInputSplit) — pattern like "file_%d.txt", inclusive
    min/max indices."""

    def __init__(self, baseString: str, minIdx: int, maxIdx: int):
        if "%d" not in baseString:
            raise ValueError("baseString must contain %d")
        self.base = baseString
        self.min = minIdx
        self.max = maxIdx

    def locations(self) -> List[str]:
        return [self.base % i for i in range(self.min, self.max + 1)]


class StringSplit(InputSplit):
    """A single in-memory string as the source (ref: StringSplit)."""

    def __init__(self, data: str):
        self.data = data

    def locations(self) -> List[str]:
        return [self.data]
