"""NLP ETL (ref: datavec-data-nlp — org.datavec.nlp.reader.TfidfRecordReader
+ vectorizer.TfidfVectorizer / BagOfWordsVectorizer over the tokenizer SPI).

Vectorization reuses the text package's tokenizer factories; the fitted
vocabulary/IDF table lives on the vectorizer and document vectors come out
as one dense numpy row (the reference emits a sparse INDArray through
NDArrayWritable — dense is the TPU-friendly layout at these vocab sizes).
"""
from __future__ import annotations

import math
import os
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import InputSplit
from deeplearning4j_tpu.datavec.writables import NDArrayWritable, Text, Writable
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory


class BagOfWordsVectorizer:
    """Count vectors (ref: org.datavec.nlp.vectorizer.BagOfWordsVectorizer)."""

    def __init__(self, tokenizerFactory=None, minWordFrequency: int = 1):
        self.tokenizer = tokenizerFactory or DefaultTokenizerFactory()
        self.minWordFrequency = minWordFrequency
        self.vocab: Dict[str, int] = {}

    def _tokens(self, text: str) -> List[str]:
        return self.tokenizer.create(text).getTokens()

    def fit(self, documents: List[str]) -> "BagOfWordsVectorizer":
        counts: Counter = Counter()
        for doc in documents:
            counts.update(self._tokens(doc))
        words = sorted(w for w, c in counts.items() if c >= self.minWordFrequency)
        self.vocab = {w: i for i, w in enumerate(words)}
        return self

    def numWords(self) -> int:
        return len(self.vocab)

    def transform(self, text: str) -> np.ndarray:
        v = np.zeros(len(self.vocab), np.float32)
        for t in self._tokens(text):
            i = self.vocab.get(t)
            if i is not None:
                v[i] += 1.0
        return v


class TfidfVectorizer(BagOfWordsVectorizer):
    """tf-idf with smoothed idf = ln((1+N)/(1+df)) + 1 (ref:
    org.datavec.nlp.vectorizer.TfidfVectorizer)."""

    def __init__(self, tokenizerFactory=None, minWordFrequency: int = 1):
        super().__init__(tokenizerFactory, minWordFrequency)
        self.idf: Optional[np.ndarray] = None

    def fit(self, documents: List[str]) -> "TfidfVectorizer":
        super().fit(documents)
        df = np.zeros(len(self.vocab), np.float64)
        for doc in documents:
            for t in set(self._tokens(doc)):
                i = self.vocab.get(t)
                if i is not None:
                    df[i] += 1
        n = len(documents)
        self.idf = (np.log((1.0 + n) / (1.0 + df)) + 1.0).astype(np.float32)
        return self

    def transform(self, text: str) -> np.ndarray:
        tf = super().transform(text)
        return tf * self.idf


class TfidfRecordReader(RecordReader):
    """Text files -> [tfidf NDArrayWritable, label Text] records; the label
    is the parent directory name, as the reference's file-per-document corpus
    layout (ref: org.datavec.nlp.reader.TfidfRecordReader)."""

    def __init__(self, vectorizer: Optional[TfidfVectorizer] = None,
                 appendLabel: bool = True):
        self.vectorizer = vectorizer or TfidfVectorizer()
        self.appendLabel = appendLabel
        self._paths: List[str] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._paths = list(split.locations())
        self._pos = 0
        docs = []
        for p in self._paths:
            with open(p) as f:
                docs.append(f.read())
        if not self.vectorizer.vocab:
            self.vectorizer.fit(docs)
        self._docs = docs

    def getLabels(self) -> List[str]:
        return sorted({os.path.basename(os.path.dirname(p)) for p in self._paths})

    def hasNext(self) -> bool:
        return self._pos < len(self._paths)

    def next(self) -> List[Writable]:
        vec = self.vectorizer.transform(self._docs[self._pos])
        rec: List[Writable] = [NDArrayWritable(vec)]
        if self.appendLabel:
            rec.append(Text(os.path.basename(
                os.path.dirname(self._paths[self._pos]))))
        self._pos += 1
        return rec

    def reset(self):
        self._pos = 0
