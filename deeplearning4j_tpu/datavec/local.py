"""Local transform executor (ref: datavec-local
org.datavec.local.transforms.LocalTransformExecutor)."""
from __future__ import annotations

from typing import List, Sequence

from deeplearning4j_tpu.datavec.transform import TransformProcess
from deeplearning4j_tpu.datavec.writables import Writable


class LocalTransformExecutor:
    """(ref: LocalTransformExecutor.execute)."""

    @staticmethod
    def execute(records: Sequence[Sequence[Writable]], tp: TransformProcess
                ) -> List[List[Writable]]:
        return tp.execute(records)

    @staticmethod
    def executeToSequence(sequences, tp: TransformProcess):
        return [tp.execute(seq) for seq in sequences]
