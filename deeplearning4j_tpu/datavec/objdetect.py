"""Object-detection ETL (ref: datavec-data-image org.datavec.image.recordreader.
objdetect — ObjectDetectionRecordReader + ImageObject + VocLabelProvider).

The reader emits [image CHW, label grid (4+C, gridH, gridW)] records where
the label grid carries, at each object's center cell, the YOLOv2 target
encoding consumed by Yolo2OutputLayer.compute_loss (nn/conf/layers.py):
tx,ty = center offset within the cell in [0,1); tw,th = box size in grid
units; then the one-hot class vector. Cells without objects stay zero.
"""
from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datavec.image import NativeImageLoader
from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import InputSplit
from deeplearning4j_tpu.datavec.writables import NDArrayWritable, Writable


@dataclass
class ImageObject:
    """One annotated box in PIXEL coordinates (ref: o.d.image.recordreader.
    objdetect.ImageObject)."""
    x1: float
    y1: float
    x2: float
    y2: float
    label: str

    @property
    def cx(self):
        return (self.x1 + self.x2) / 2.0

    @property
    def cy(self):
        return (self.y1 + self.y2) / 2.0


class ImageObjectLabelProvider:
    """SPI (ref: objdetect.ImageObjectLabelProvider)."""

    def getImageObjectsForPath(self, path: str) -> List[ImageObject]:
        raise NotImplementedError


class VocLabelProvider(ImageObjectLabelProvider):
    """Pascal-VOC layout: <base>/Annotations/<stem>.xml beside
    <base>/JPEGImages/<stem>.jpg (ref: objdetect.impl.VocLabelProvider)."""

    def __init__(self, base_dir: str):
        self.annotations = os.path.join(base_dir, "Annotations")

    def getImageObjectsForPath(self, path: str) -> List[ImageObject]:
        stem = os.path.splitext(os.path.basename(path))[0]
        xml_path = os.path.join(self.annotations, stem + ".xml")
        out: List[ImageObject] = []
        root = ET.parse(xml_path).getroot()
        for obj in root.iter("object"):
            name = obj.findtext("name")
            box = obj.find("bndbox")
            out.append(ImageObject(
                float(box.findtext("xmin")), float(box.findtext("ymin")),
                float(box.findtext("xmax")), float(box.findtext("ymax")),
                name))
        return out


class JsonLinesLabelProvider(ImageObjectLabelProvider):
    """<image>.boxes.jsonl sidecar files: one JSON object per line with
    x1/y1/x2/y2/label — a dependency-free fixture format for tests and
    simple datasets."""

    def getImageObjectsForPath(self, path: str) -> List[ImageObject]:
        import json
        side = os.path.splitext(path)[0] + ".boxes.jsonl"
        out = []
        with open(side) as f:
            for line in f:
                if line.strip():
                    d = json.loads(line)
                    out.append(ImageObject(d["x1"], d["y1"], d["x2"], d["y2"],
                                           d["label"]))
        return out


class ObjectDetectionRecordReader(RecordReader):
    """(ref: objdetect.ObjectDetectionRecordReader). next() ->
    [image (C,H,W) NDArrayWritable, label (4+C, gridH, gridW) NDArrayWritable]."""

    def __init__(self, height: int, width: int, channels: int,
                 gridH: int, gridW: int,
                 labelProvider: ImageObjectLabelProvider,
                 labels: Optional[Sequence[str]] = None):
        self.h, self.w, self.c = height, width, channels
        self.gh, self.gw = gridH, gridW
        self.provider = labelProvider
        self._labels = list(labels) if labels else None
        self._paths: List[str] = []
        self._pos = 0
        self._loader = NativeImageLoader(height, width, channels)

    def initialize(self, split: InputSplit):
        self._paths = list(split.locations())
        self._pos = 0
        if self._labels is None:
            names = set()
            for p in self._paths:
                for o in self.provider.getImageObjectsForPath(p):
                    names.add(o.label)
            self._labels = sorted(names)

    def getLabels(self) -> List[str]:
        return list(self._labels or [])

    def label_grid(self, path: str, orig_w: float, orig_h: float) -> np.ndarray:
        """(4+C, gridH, gridW) YOLOv2 target grid for one image."""
        C = len(self._labels)
        grid = np.zeros((4 + C, self.gh, self.gw), np.float32)
        for o in self.provider.getImageObjectsForPath(path):
            # scale pixel coords to grid units
            gx = o.cx / orig_w * self.gw
            gy = o.cy / orig_h * self.gh
            gw_box = (o.x2 - o.x1) / orig_w * self.gw
            gh_box = (o.y2 - o.y1) / orig_h * self.gh
            cx = min(int(gx), self.gw - 1)
            cy = min(int(gy), self.gh - 1)
            cls = self._labels.index(o.label)
            grid[0, cy, cx] = gx - cx            # tx in [0,1)
            grid[1, cy, cx] = gy - cy            # ty
            grid[2, cy, cx] = gw_box             # tw (grid units)
            grid[3, cy, cx] = gh_box             # th
            grid[4 + cls, cy, cx] = 1.0
        return grid

    def hasNext(self) -> bool:
        return self._pos < len(self._paths)

    def next(self) -> List[Writable]:
        path = self._paths[self._pos]
        self._pos += 1
        from PIL import Image
        with Image.open(path) as im:
            orig_w, orig_h = im.size
        img = np.asarray(self._loader.asMatrix(path))
        if img.ndim == 4:  # NativeImageLoader emits batch-leading (1,C,H,W)
            img = img[0]
        label = self.label_grid(path, orig_w, orig_h)
        return [NDArrayWritable(img), NDArrayWritable(label)]

    def reset(self):
        self._pos = 0
