"""Arrow interop (ref: datavec/datavec-arrow org.datavec.arrow.ArrowConverter
+ recordreader.ArrowRecordReader, and nd4j/nd4j-serde/nd4j-arrow — columnar
record batches as the zero-copy interchange format).

The reference converts List<List<Writable>> ⇄ Arrow record batches so
DataVec pipelines can exchange data with Spark/Arrow tooling. Here the same
conversion targets ``pyarrow.Table``; the IPC file format (Feather v2)
round-trips records to disk. On TPU this is also the natural bridge from
columnar stores into the host-side input pipeline (arrow column → numpy →
device batch, no per-row Python loop).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.schema import ColumnType, Schema
from deeplearning4j_tpu.datavec.writables import (
    BooleanWritable, DoubleWritable, FloatWritable, IntWritable, LongWritable,
    NullWritable, Text, Writable,
)


def _pa():
    try:
        import pyarrow
        return pyarrow
    except ImportError as e:  # pragma: no cover - pyarrow present in this env
        raise ImportError("Arrow interop needs pyarrow") from e


_TO_ARROW = {
    ColumnType.Double: "float64",
    ColumnType.Float: "float32",
    ColumnType.Integer: "int32",
    ColumnType.Long: "int64",
    ColumnType.Boolean: "bool_",
    ColumnType.String: "string",
    ColumnType.Categorical: "string",
    ColumnType.Time: "int64",
}

_FROM_ARROW_WRITABLE = {
    "double": DoubleWritable, "float": FloatWritable,
    "int32": IntWritable, "int64": LongWritable, "bool": BooleanWritable,
    "string": Text, "large_string": Text,
}


class ArrowConverter:
    """List-of-Writable-rows ⇄ pyarrow.Table (ref: ArrowConverter)."""

    @staticmethod
    def toArrowTable(records: Sequence[Sequence[Writable]], schema: Schema):
        pa = _pa()
        fields = []
        for meta in schema.columns:
            at = _TO_ARROW.get(meta.type)
            if at is None:
                raise ValueError(
                    f"column '{meta.name}': type {meta.type} has no Arrow mapping")
            fields.append(pa.field(meta.name, getattr(pa, at)()))
        cols = []
        for j, meta in enumerate(schema.columns):
            vals = []
            for r in records:
                w = r[j]
                if isinstance(w, NullWritable) or w.value is None:
                    vals.append(None)
                elif meta.type in (ColumnType.Double, ColumnType.Float):
                    vals.append(w.toDouble())
                elif meta.type in (ColumnType.Integer, ColumnType.Long,
                                   ColumnType.Time):
                    vals.append(w.toLong())
                elif meta.type == ColumnType.Boolean:
                    vals.append(bool(w.value))
                else:
                    vals.append(w.toString())
            cols.append(pa.array(vals, type=fields[j].type))
        return pa.Table.from_arrays(cols, schema=pa.schema(fields))

    @staticmethod
    def fromArrowTable(table) -> List[List[Writable]]:
        rows: List[List[Writable]] = []
        arrow_cols = [(str(f.type), table.column(i).to_pylist())
                      for i, f in enumerate(table.schema)]
        n = table.num_rows
        for i in range(n):
            row: List[Writable] = []
            for tname, vals in arrow_cols:
                v = vals[i]
                if v is None:
                    row.append(NullWritable())
                else:
                    row.append(_FROM_ARROW_WRITABLE.get(tname, Text)(v))
            rows.append(row)
        return rows

    @staticmethod
    def schemaFromArrow(table) -> Schema:
        """Arrow schema → datavec Schema (lossy: categorical becomes String)."""
        b = Schema.Builder()
        for f in table.schema:
            t = str(f.type)
            if t in ("double", "float64"):
                b.addColumnDouble(f.name)
            elif t in ("float", "float32"):
                b.addColumnFloat(f.name)
            elif t in ("int8", "int16", "int32", "uint8", "uint16"):
                b.addColumnInteger(f.name)
            elif t in ("int64", "uint32", "uint64"):
                b.addColumnLong(f.name)
            elif t == "bool":
                b.addColumnBoolean(f.name)
            else:
                b.addColumnString(f.name)
        return b.build()

    # ------------------------------------------------------------- IPC file
    @staticmethod
    def writeRecordsToFile(path: str, records: Sequence[Sequence[Writable]],
                           schema: Schema) -> str:
        pa = _pa()
        table = ArrowConverter.toArrowTable(records, schema)
        with pa.ipc.new_file(path, table.schema) as w:
            w.write_table(table)
        return path

    @staticmethod
    def _read_table(path: str):
        pa = _pa()
        with pa.ipc.open_file(path) as r:
            return r.read_all()

    @staticmethod
    def readRecordsFromFile(path: str) -> List[List[Writable]]:
        return ArrowConverter.fromArrowTable(ArrowConverter._read_table(path))


class ArrowRecordReader(RecordReader):
    """Reads Arrow IPC files as records (ref: org.datavec.arrow.recordreader.
    ArrowRecordReader). ``initialize`` takes an InputSplit over .arrow files."""

    def __init__(self):
        self._rows: List[List[Writable]] = []
        self._i = 0
        self.schema: Optional[Schema] = None

    def initialize(self, split):
        self._rows = []
        self.schema = None  # re-derive from the new split's first file
        for loc in split.locations():
            table = ArrowConverter._read_table(loc)
            if self.schema is None:
                self.schema = ArrowConverter.schemaFromArrow(table)
            self._rows.extend(ArrowConverter.fromArrowTable(table))
        self._i = 0
        return self

    def hasNext(self) -> bool:
        return self._i < len(self._rows)

    def next(self) -> List[Writable]:
        if not self.hasNext():
            raise StopIteration
        r = self._rows[self._i]
        self._i += 1
        return r

    def reset(self):
        self._i = 0
