"""Video/codec frame-sequence reader (ref: datavec/datavec-data-codec
org.datavec.codec.reader.CodecRecordReader — decodes video into one sequence
record per file, each time step a frame; the reference decodes via JCodec/
JavaCV with conf keys START_FRAME / TOTAL_FRAMES / ROWS_PER_FRAME).

No ffmpeg exists in this environment, so the decode backends are:
- **multi-frame images** (.gif / animated .webp / multipage .tif) via PIL's
  frame-seek API — the same decode-to-frames contract;
- **array containers** (.npy / .npz holding a (T, H, W, C) or (T, H, W)
  uint8/float stack) — the interchange format scientific video pipelines
  already produce.

Each sequence step is one ``NDArrayWritable`` holding a (C, H, W) float32
frame (optionally resized / normalized), matching ImageRecordReader's layout
so downstream iterators treat video exactly like image sequences.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datavec.records import SequenceRecordReader
from deeplearning4j_tpu.datavec.split import InputSplit
from deeplearning4j_tpu.datavec.writables import NDArrayWritable, Writable

_IMAGE_EXTS = {".gif", ".webp", ".tif", ".tiff", ".png", ".apng"}
_ARRAY_EXTS = {".npy", ".npz"}


class CodecRecordReader(SequenceRecordReader):
    """One sequence per file; steps are frames (ref: CodecRecordReader).

    ``startFrame`` / ``numFrames`` / ``frameStep`` window the decoded stream
    (ref conf keys START_FRAME / TOTAL_FRAMES; frameStep is the rebuild's
    stride generalization). ``size=(H, W)`` resizes frames; ``normalize``
    scales uint8 content to [0, 1].
    """

    def __init__(self, startFrame: int = 0, numFrames: Optional[int] = None,
                 frameStep: int = 1, size: Optional[Tuple[int, int]] = None,
                 normalize: bool = True):
        if frameStep < 1:
            raise ValueError("frameStep must be >= 1")
        self.startFrame = startFrame
        self.numFrames = numFrames
        self.frameStep = frameStep
        self.size = size
        self.normalize = normalize
        self._locations: List[str] = []
        self._pos = 0

    # ------------------------------------------------------------- decode
    def _decode_image_frames(self, path: str) -> List[np.ndarray]:
        from PIL import Image, ImageSequence
        frames = []
        with Image.open(path) as im:
            for frame in ImageSequence.Iterator(im):
                f = frame.convert("RGB")
                if self.size is not None:
                    f = f.resize((self.size[1], self.size[0]))
                frames.append(np.asarray(f, np.float32))  # (H, W, C)
        return frames

    def _decode_array_frames(self, path: str):
        if path.endswith(".npz"):
            with np.load(path) as z:
                stack = z[list(z.files)[0]]
        else:
            stack = np.load(path)
        was_uint8 = stack.dtype == np.uint8
        if stack.ndim == 3:                       # (T, H, W) → add channel
            stack = stack[..., None]
        if stack.ndim != 4:
            raise ValueError(
                f"{path}: expected (T,H,W[,C]) video stack, got {stack.shape}")
        frames = [np.asarray(f, np.float32) for f in stack]
        if self.size is not None:
            from PIL import Image
            h, w = self.size
            out = []
            for f in frames:
                # per-channel float resize (PIL mode "F") — no uint8
                # roundtrip, so float-valued stacks survive untouched
                chans = [np.asarray(
                    Image.fromarray(f[..., c], mode="F").resize((w, h)),
                    np.float32) for c in range(f.shape[-1])]
                out.append(np.stack(chans, axis=-1))
            frames = out
        return frames, was_uint8

    def _frames_for(self, path: str):
        """Returns (frames, uint8_scaled) — the flag says pixel values live
        in 0..255 and normalize should rescale them."""
        ext = os.path.splitext(path)[1].lower()
        if ext in _ARRAY_EXTS:
            frames, uint8_scaled = self._decode_array_frames(path)
        elif ext in _IMAGE_EXTS:
            frames, uint8_scaled = self._decode_image_frames(path), True
        else:
            raise ValueError(f"unsupported container '{ext}' "
                             f"(multi-frame image or .npy/.npz stack)")
        stop = (self.startFrame + self.numFrames * self.frameStep
                if self.numFrames is not None else None)
        return frames[self.startFrame:stop:self.frameStep], uint8_scaled

    # ---------------------------------------------------------------- SPI
    def initialize(self, split: InputSplit):
        self._locations = list(split.locations())
        self._pos = 0
        return self

    def next(self) -> List[List[Writable]]:
        path = self._locations[self._pos]
        self._pos += 1
        frames, uint8_scaled = self._frames_for(path)
        steps: List[List[Writable]] = []
        for hwc in frames:
            chw = np.transpose(hwc, (2, 0, 1))
            if self.normalize and uint8_scaled:
                chw = chw / 255.0   # float stacks are already in the
                                    # caller's scale — leave them alone
            steps.append([NDArrayWritable(chw.astype(np.float32))])
        return steps

    def hasNext(self) -> bool:
        return self._pos < len(self._locations)

    def reset(self):
        self._pos = 0
