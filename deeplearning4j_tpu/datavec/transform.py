"""Transform engine (ref: datavec-api org.datavec.api.transform.* —
TransformProcess fluent DSL over a Schema: column/row transforms, conditions,
filters, grouped reductions; JSON-serializable)."""
from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.datavec.schema import ColumnMeta, ColumnType, Schema
from deeplearning4j_tpu.datavec.writables import (
    BooleanWritable, DoubleWritable, IntWritable, NullWritable, Text, Writable,
    as_writable)


class MathOp:
    Add = "Add"
    Subtract = "Subtract"
    Multiply = "Multiply"
    Divide = "Divide"
    Modulus = "Modulus"
    ReverseSubtract = "ReverseSubtract"
    ReverseDivide = "ReverseDivide"
    ScalarMin = "ScalarMin"
    ScalarMax = "ScalarMax"


_MATH = {
    MathOp.Add: lambda a, b: a + b,
    MathOp.Subtract: lambda a, b: a - b,
    MathOp.Multiply: lambda a, b: a * b,
    MathOp.Divide: lambda a, b: a / b,
    MathOp.Modulus: lambda a, b: a % b,
    MathOp.ReverseSubtract: lambda a, b: b - a,
    MathOp.ReverseDivide: lambda a, b: b / a,
    MathOp.ScalarMin: min,
    MathOp.ScalarMax: max,
}


class ConditionOp:
    LessThan = "LessThan"
    LessOrEqual = "LessOrEqual"
    GreaterThan = "GreaterThan"
    GreaterOrEqual = "GreaterOrEqual"
    Equal = "Equal"
    NotEqual = "NotEqual"
    InSet = "InSet"
    NotInSet = "NotInSet"


_COND = {
    ConditionOp.LessThan: lambda v, t: v < t,
    ConditionOp.LessOrEqual: lambda v, t: v <= t,
    ConditionOp.GreaterThan: lambda v, t: v > t,
    ConditionOp.GreaterOrEqual: lambda v, t: v >= t,
    ConditionOp.Equal: lambda v, t: v == t,
    ConditionOp.NotEqual: lambda v, t: v != t,
    ConditionOp.InSet: lambda v, t: v in t,
    ConditionOp.NotInSet: lambda v, t: v not in t,
}


class Condition:
    """(ref: o.d.api.transform.condition.column.*Condition)."""

    def __init__(self, column: str, op: str, value, numeric: bool = True):
        self.column = column
        self.op = op
        self.value = set(value) if op in (ConditionOp.InSet, ConditionOp.NotInSet) \
            else value
        self.numeric = numeric
        self._idx_cache = None  # (schema, index) memo — avoids per-row scans

    def matches(self, record: List[Writable], schema: Schema) -> bool:
        if self._idx_cache is None or self._idx_cache[0] is not schema:
            self._idx_cache = (schema, schema.getIndexOfColumn(self.column))
        w = record[self._idx_cache[1]]
        v = w.toDouble() if self.numeric else w.toString()
        return _COND[self.op](v, self.value)

    def to_dict(self):
        return {"column": self.column, "op": self.op,
                "value": list(self.value) if isinstance(self.value, (set, list, tuple))
                else self.value, "numeric": self.numeric}

    @staticmethod
    def from_dict(d):
        return Condition(d["column"], d["op"], d["value"], d.get("numeric", True))


class ConditionFilter:
    """Remove records matching the condition (ref: filter.ConditionFilter)."""

    def __init__(self, condition: Condition):
        self.condition = condition

    def removeExample(self, record, schema) -> bool:
        return self.condition.matches(record, schema)

    def to_dict(self):
        return {"@type": "ConditionFilter", "condition": self.condition.to_dict()}


class FilterInvalidValues:
    """Drop rows whose named columns fail to parse for their type
    (ref: filter.FilterInvalidValues)."""

    def __init__(self, *columns: str):
        self.columns = list(columns)

    def removeExample(self, record, schema) -> bool:
        cols = self.columns or schema.getColumnNames()
        for c in cols:
            idx = schema.getIndexOfColumn(c)
            t = schema.getType(idx)
            w = record[idx]
            try:
                if t in (ColumnType.Double, ColumnType.Float):
                    v = w.toDouble()
                    if math.isnan(v) or math.isinf(v):
                        return True
                elif t in (ColumnType.Integer, ColumnType.Long):
                    w.toInt()
                elif t == ColumnType.Categorical:
                    states = schema.getMetaData(c).stateNames or []
                    if w.toString() not in states:
                        return True
            except (ValueError, TypeError):
                return True
        return False

    def to_dict(self):
        return {"@type": "FilterInvalidValues", "columns": self.columns}


class _Step:
    """One pipeline step: transform | filter | reduce."""

    def __init__(self, kind: str, spec: Dict[str, Any]):
        self.kind = kind
        self.spec = spec


class TransformProcess:
    """(ref: org.datavec.api.transform.TransformProcess + .Builder)."""

    def __init__(self, initialSchema: Schema, steps: List[_Step]):
        self.initialSchema = initialSchema
        self.steps = steps

    # ------------------------------------------------------------- schema
    def getFinalSchema(self) -> Schema:
        schema = self.initialSchema
        for s in self.steps:
            schema = _apply_schema(schema, s)
        return schema

    # ---------------------------------------------------------------- exec
    _SEQ_KINDS = ("convertToSequence", "trimSequence", "offsetSequence",
                  "movingWindowReduce")

    def execute(self, records: Sequence[Sequence[Writable]]) -> List[List[Writable]]:
        if any(s.kind in self._SEQ_KINDS for s in self.steps):
            raise ValueError("process contains sequence steps — call "
                             "executeToSequence (ref: TransformProcess."
                             "execute throws on sequence processes)")
        rows = [list(r) for r in records]
        schema = self.initialSchema
        for s in self.steps:
            rows = _apply_rows(rows, schema, s)
            schema = _apply_schema(schema, s)
        return rows

    def executeToSequence(self, records: Sequence[Sequence[Writable]]):
        """Flat records -> list of sequences through a pipeline containing
        convertToSequence + sequence ops (ref: LocalTransformExecutor.
        executeToSequence). Row steps before the conversion apply to flat
        rows; after it, row steps apply per sequence step-row and sequence
        steps transform whole sequences."""
        from deeplearning4j_tpu.datavec import sequence as _seq
        rows = [list(r) for r in records]
        sequences = None
        schema = self.initialSchema
        for s in self.steps:
            k, spec = s.kind, s.spec
            if k == "convertToSequence":
                sequences = _seq.convertToSequence(
                    rows, schema, spec["key"], spec["sort"],
                    ascending=spec.get("ascending", True))
            elif k == "trimSequence":
                if sequences is None:
                    raise ValueError(f"{k} requires a convertToSequence step first")
                sequences = [_seq.trimSequence(q, spec["numSteps"],
                                               spec["fromFirst"])
                             for q in sequences]
            elif k == "offsetSequence":
                if sequences is None:
                    raise ValueError(f"{k} requires a convertToSequence step first")
                sequences = [_seq.offsetSequence(q, schema, spec["columns"],
                                                 spec["offset"],
                                                 op=spec.get("op", "InPlace"))
                             for q in sequences]
            elif k == "movingWindowReduce":
                if sequences is None:
                    raise ValueError(f"{k} requires a convertToSequence step first")
                sequences = [_seq.sequenceMovingWindowReduce(
                    q, schema, spec["column"], spec["window"],
                    agg=spec.get("agg", "mean")) for q in sequences]
            elif sequences is None:
                rows = _apply_rows(rows, schema, s)
            else:
                sequences = [_apply_rows(q, schema, s) for q in sequences]
            schema = _apply_schema(schema, s)
        if sequences is None:
            raise ValueError("executeToSequence: no convertToSequence step in this process")
        return sequences

    # ---------------------------------------------------------------- serde
    def to_json(self) -> str:
        return json.dumps({
            "initialSchema": json.loads(self.initialSchema.to_json()),
            "steps": [{"kind": s.kind, "spec": _spec_to_json(s.spec)}
                      for s in self.steps],
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "TransformProcess":
        d = json.loads(s)
        schema = Schema.from_json(json.dumps(d["initialSchema"]))
        steps = [_Step(sd["kind"], _spec_from_json(sd["spec"])) for sd in d["steps"]]
        return TransformProcess(schema, steps)

    class Builder:
        def __init__(self, initialSchema: Schema):
            self._schema = initialSchema
            self._steps: List[_Step] = []

        def _add(self, kind, **spec):
            self._steps.append(_Step(kind, spec))
            return self

        # ---- column structure
        def removeColumns(self, *names: str):
            return self._add("removeColumns", names=list(names))

        def removeAllColumnsExceptFor(self, *names: str):
            return self._add("keepColumns", names=list(names))

        def renameColumn(self, old: str, new: str):
            return self._add("renameColumn", old=old, new=new)

        def reorderColumns(self, *names: str):
            return self._add("reorderColumns", names=list(names))

        def duplicateColumn(self, src: str, dst: str):
            return self._add("duplicateColumn", src=src, dst=dst)

        # ---- categorical
        def categoricalToInteger(self, *names: str):
            return self._add("categoricalToInteger", names=list(names))

        def categoricalToOneHot(self, *names: str):
            return self._add("categoricalToOneHot", names=list(names))

        def integerToCategorical(self, name: str, states: Sequence[str]):
            return self._add("integerToCategorical", name=name, states=list(states))

        def stringToCategorical(self, name: str, states: Sequence[str]):
            return self._add("stringToCategorical", name=name, states=list(states))

        # ---- math
        def doubleMathOp(self, name: str, op: str, scalar: float):
            return self._add("doubleMathOp", name=name, op=op, scalar=scalar)

        def integerMathOp(self, name: str, op: str, scalar: int):
            return self._add("integerMathOp", name=name, op=op, scalar=scalar)

        def doubleColumnsMathOp(self, newName: str, op: str, *columns: str):
            return self._add("doubleColumnsMathOp", newName=newName, op=op,
                             columns=list(columns))

        def normalize(self, name: str, mode: str, stats: Dict[str, float]):
            """mode: 'MinMax' | 'Standardize' with stats from AnalyzeLocal."""
            return self._add("normalize", name=name, mode=mode, stats=dict(stats))

        # ---- strings
        def stringMapTransform(self, name: str, mapping: Dict[str, str]):
            return self._add("stringMap", name=name, mapping=dict(mapping))

        def appendStringColumnTransform(self, name: str, toAppend: str):
            return self._add("appendString", name=name, toAppend=toAppend)

        def stringRemoveWhitespaceTransform(self, name: str):
            return self._add("stringStrip", name=name)

        def replaceStringTransform(self, name: str, mapping: Dict[str, str]):
            return self._add("replaceString", name=name, mapping=dict(mapping))

        # ---- conditional
        def conditionalReplaceValueTransform(self, name: str, newValue,
                                             condition: Condition):
            return self._add("conditionalReplace", name=name, newValue=newValue,
                             condition=condition)

        # ---- filters
        def filter(self, f):
            return self._add("filter", filter=f)

        # ---- grouped reduction
        def reduce(self, keyColumn: str, aggregations: Dict[str, str]):
            """aggregations: {column: 'sum'|'mean'|'min'|'max'|'count'|'first'}
            (ref: o.d.api.transform.reduce.Reducer grouped by key)."""
            return self._add("reduce", key=keyColumn, aggs=dict(aggregations))

        # ---- sequence (ref: TransformProcess.Builder.convertToSequence /
        # trimSequence / offsetSequence + SequenceMovingWindowReduceTransform;
        # run via executeToSequence)
        def convertToSequence(self, keyColumn: str, sortColumn: str,
                              ascending: bool = True):
            return self._add("convertToSequence", key=keyColumn,
                             sort=sortColumn, ascending=ascending)

        def trimSequence(self, numSteps: int, fromFirst: bool = True):
            return self._add("trimSequence", numSteps=numSteps,
                             fromFirst=fromFirst)

        def offsetSequence(self, columns: Sequence[str], offset: int,
                           op: str = "InPlace"):
            return self._add("offsetSequence", columns=list(columns),
                             offset=offset, op=op)

        def sequenceMovingWindowReduce(self, column: str, window: int,
                                       agg: str = "mean"):
            return self._add("movingWindowReduce", column=column,
                             window=window, agg=agg)

        def build(self) -> "TransformProcess":
            return TransformProcess(self._schema, list(self._steps))


# ------------------------------------------------------------ serde helpers

def _spec_to_json(spec):
    out = {}
    for k, v in spec.items():
        if isinstance(v, (Condition,)):
            out[k] = {"@cond": v.to_dict()}
        elif isinstance(v, (ConditionFilter, FilterInvalidValues)):
            out[k] = v.to_dict()
        else:
            out[k] = v
    return out


def _spec_from_json(spec):
    out = {}
    for k, v in spec.items():
        if isinstance(v, dict) and "@cond" in v:
            out[k] = Condition.from_dict(v["@cond"])
        elif isinstance(v, dict) and v.get("@type") == "ConditionFilter":
            out[k] = ConditionFilter(Condition.from_dict(v["condition"]))
        elif isinstance(v, dict) and v.get("@type") == "FilterInvalidValues":
            out[k] = FilterInvalidValues(*v["columns"])
        else:
            out[k] = v
    return out


# --------------------------------------------------------- schema evolution

def _apply_schema(schema: Schema, step: _Step) -> Schema:
    cols = [ColumnMeta(c.name, c.type, c.stateNames) for c in schema.columns]
    k, s = step.kind, step.spec
    if k == "removeColumns":
        cols = [c for c in cols if c.name not in set(s["names"])]
    elif k == "keepColumns":
        keep = set(s["names"])
        cols = [c for c in cols if c.name in keep]
    elif k == "renameColumn":
        for c in cols:
            if c.name == s["old"]:
                c.name = s["new"]
    elif k == "reorderColumns":
        by = {c.name: c for c in cols}
        ordered = [by[n] for n in s["names"]]
        ordered += [c for c in cols if c.name not in set(s["names"])]
        cols = ordered
    elif k == "duplicateColumn":
        src = next(c for c in cols if c.name == s["src"])
        cols.insert(cols.index(src) + 1, ColumnMeta(s["dst"], src.type, src.stateNames))
    elif k == "categoricalToInteger":
        for c in cols:
            if c.name in set(s["names"]):
                c.type = ColumnType.Integer
    elif k == "categoricalToOneHot":
        out = []
        names = set(s["names"])
        for c in cols:
            if c.name in names:
                for st in (c.stateNames or []):
                    out.append(ColumnMeta(f"{c.name}[{st}]", ColumnType.Integer))
            else:
                out.append(c)
        cols = out
    elif k in ("integerToCategorical", "stringToCategorical"):
        for c in cols:
            if c.name == s["name"]:
                c.type = ColumnType.Categorical
                c.stateNames = list(s["states"])
    elif k == "doubleColumnsMathOp":
        cols.append(ColumnMeta(s["newName"], ColumnType.Double))
    elif k == "reduce":
        key = s["key"]
        out = [ColumnMeta(key, schema.getType(key))]
        for col, agg in s["aggs"].items():
            ctype = ColumnType.Integer if agg == "count" else ColumnType.Double
            out.append(ColumnMeta(f"{agg}({col})", ctype))
        cols = out
    elif k == "offsetSequence" and s.get("op") == "NewColumn":
        for name in s["columns"]:
            src = next(c for c in cols if c.name == name)
            cols.append(ColumnMeta(f"{name}_offset{s['offset']}", src.type,
                                   src.stateNames))
    elif k == "movingWindowReduce":
        cols.append(ColumnMeta(f"{s.get('agg', 'mean')}({s['column']},{s['window']})",
                               ColumnType.Double))
    return Schema(cols)


# --------------------------------------------------------------- row apply

def _apply_rows(rows: List[List[Writable]], schema: Schema, step: _Step
                ) -> List[List[Writable]]:
    k, s = step.kind, step.spec
    names = schema.getColumnNames()
    idx = {n: i for i, n in enumerate(names)}

    if k == "removeColumns":
        drop = {idx[n] for n in s["names"]}
        return [[w for i, w in enumerate(r) if i not in drop] for r in rows]
    if k == "keepColumns":
        keep = [i for i, n in enumerate(names) if n in set(s["names"])]
        return [[r[i] for i in keep] for r in rows]
    if k == "renameColumn":
        return rows
    if k == "reorderColumns":
        order = [idx[n] for n in s["names"]]
        order += [i for i in range(len(names)) if i not in set(order)]
        return [[r[i] for i in order] for r in rows]
    if k == "duplicateColumn":
        i = idx[s["src"]]
        return [r[:i + 1] + [r[i]] + r[i + 1:] for r in rows]
    if k == "categoricalToInteger":
        out = []
        targets = {idx[n]: (schema.getMetaData(n).stateNames or []) for n in s["names"]}
        for r in rows:
            r = list(r)
            for i, states in targets.items():
                r[i] = IntWritable(states.index(r[i].toString()))
            out.append(r)
        return out
    if k == "categoricalToOneHot":
        targets = {idx[n]: (schema.getMetaData(n).stateNames or []) for n in s["names"]}
        out = []
        for r in rows:
            nr: List[Writable] = []
            for i, w in enumerate(r):
                if i in targets:
                    states = targets[i]
                    hot = states.index(w.toString())
                    nr.extend(IntWritable(1 if j == hot else 0)
                              for j in range(len(states)))
                else:
                    nr.append(w)
            out.append(nr)
        return out
    if k == "integerToCategorical":
        i = idx[s["name"]]
        states = s["states"]
        return [_set(r, i, Text(states[r[i].toInt()])) for r in rows]
    if k == "stringToCategorical":
        return rows
    if k == "doubleMathOp":
        i = idx[s["name"]]
        fn = _MATH[s["op"]]
        return [_set(r, i, DoubleWritable(fn(r[i].toDouble(), s["scalar"])))
                for r in rows]
    if k == "integerMathOp":
        i = idx[s["name"]]
        fn = _MATH[s["op"]]
        return [_set(r, i, IntWritable(int(fn(r[i].toInt(), s["scalar"]))))
                for r in rows]
    if k == "doubleColumnsMathOp":
        cols = [idx[n] for n in s["columns"]]
        fn = _MATH[s["op"]]
        out = []
        for r in rows:
            acc = r[cols[0]].toDouble()
            for c in cols[1:]:
                acc = fn(acc, r[c].toDouble())
            out.append(list(r) + [DoubleWritable(acc)])
        return out
    if k == "normalize":
        i = idx[s["name"]]
        st = s["stats"]
        if s["mode"] == "MinMax":
            lo, hi = st["min"], st["max"]
            return [_set(r, i, DoubleWritable((r[i].toDouble() - lo) / max(hi - lo, 1e-12)))
                    for r in rows]
        mu, sd = st["mean"], st.get("std", 1.0)
        return [_set(r, i, DoubleWritable((r[i].toDouble() - mu) / max(sd, 1e-12)))
                for r in rows]
    if k == "stringMap":
        i = idx[s["name"]]
        m = s["mapping"]
        return [_set(r, i, Text(m.get(r[i].toString(), r[i].toString()))) for r in rows]
    if k == "appendString":
        i = idx[s["name"]]
        return [_set(r, i, Text(r[i].toString() + s["toAppend"])) for r in rows]
    if k == "stringStrip":
        i = idx[s["name"]]
        return [_set(r, i, Text("".join(r[i].toString().split()))) for r in rows]
    if k == "replaceString":
        i = idx[s["name"]]
        out = []
        for r in rows:
            v = r[i].toString()
            for old, new in s["mapping"].items():
                v = v.replace(old, new)
            out.append(_set(r, i, Text(v)))
        return out
    if k == "conditionalReplace":
        i = idx[s["name"]]
        cond = s["condition"]
        return [_set(r, i, as_writable(s["newValue"])) if cond.matches(r, schema)
                else r for r in rows]
    if k == "filter":
        f = s["filter"]
        return [r for r in rows if not f.removeExample(r, schema)]
    if k == "reduce":
        key_i = idx[s["key"]]
        groups: Dict[str, List[List[Writable]]] = {}
        order: List[str] = []
        for r in rows:
            kv = r[key_i].toString()
            if kv not in groups:
                groups[kv] = []
                order.append(kv)
            groups[kv].append(r)
        out = []
        for kv in order:
            grp = groups[kv]
            row: List[Writable] = [grp[0][key_i]]
            for col, agg in s["aggs"].items():
                ci = idx[col]
                vals = [g[ci].toDouble() for g in grp]
                if agg == "sum":
                    row.append(DoubleWritable(sum(vals)))
                elif agg == "mean":
                    row.append(DoubleWritable(sum(vals) / len(vals)))
                elif agg == "min":
                    row.append(DoubleWritable(min(vals)))
                elif agg == "max":
                    row.append(DoubleWritable(max(vals)))
                elif agg == "count":
                    row.append(IntWritable(len(vals)))
                elif agg == "first":
                    row.append(grp[0][ci])
                else:
                    raise ValueError(f"unknown aggregation {agg}")
            out.append(row)
        return out
    raise ValueError(f"unknown transform step {k}")


def _set(r: List[Writable], i: int, w: Writable) -> List[Writable]:
    r = list(r)
    r[i] = w
    return r
