"""Record -> DataSet adapters (ref: deeplearning4j-datavec-iterators —
RecordReaderDataSetIterator, SequenceRecordReaderDataSetIterator)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader, SequenceRecordReader
from deeplearning4j_tpu.datavec.writables import NDArrayWritable


def _row_to_floats(record, skip: Optional[int] = None) -> List[float]:
    out = []
    for i, w in enumerate(record):
        if skip is not None and i == skip:
            continue
        if isinstance(w, NDArrayWritable):
            out.extend(np.asarray(w.value, dtype=np.float64).ravel().tolist())
        else:
            out.append(w.toDouble())
    return out


class RecordReaderDataSetIterator(DataSetIterator):
    """(ref: org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator).
    labelIndex + numClasses -> classification (one-hot); regression=True keeps
    the label column(s) raw."""

    def __init__(self, recordReader: RecordReader, batchSize: int,
                 labelIndex: Optional[int] = None, numClasses: Optional[int] = None,
                 regression: bool = False,
                 labelIndexFrom: Optional[int] = None, labelIndexTo: Optional[int] = None):
        self.reader = recordReader
        self.batchSize = batchSize
        self.labelIndex = labelIndex
        self.numClasses = numClasses
        self.regression = regression
        self.labelFrom = labelIndexFrom
        self.labelTo = labelIndexTo
        self._exhausted = False

    def reset(self):
        self.reader.reset()
        self._exhausted = False

    def hasNext(self) -> bool:
        return not self._exhausted and self.reader.hasNext()

    def batch(self) -> int:
        return self.batchSize

    def next(self) -> DataSet:
        feats, labels = [], []
        n = 0
        while self.reader.hasNext() and n < self.batchSize:
            rec = self.reader.next()
            n += 1
            if self.labelFrom is not None:
                lo, hi = self.labelFrom, self.labelTo
                labels.append([w.toDouble() for w in rec[lo:hi + 1]])
                feats.append(_row_to_floats(rec[:lo] + rec[hi + 1:]))
            elif self.labelIndex is not None:
                label_w = rec[self.labelIndex]
                feats.append(_row_to_floats(rec, skip=self.labelIndex))
                if self.regression:
                    labels.append([label_w.toDouble()])
                else:
                    labels.append(_one_hot(label_w.toInt(), self.numClasses))
            else:
                feats.append(_row_to_floats(rec))
        if not self.reader.hasNext():
            self._exhausted = True
        x = np.asarray(feats, dtype=np.float32)
        y = np.asarray(labels, dtype=np.float32) if labels else None
        return DataSet(x, y if y is not None else x)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """(ref: SequenceRecordReaderDataSetIterator) — either one reader with the
    label as a column, or separate feature/label readers (ALIGN_END-style
    same-length alignment). Output: (B, T, F) NWC."""

    def __init__(self, featureReader: SequenceRecordReader, labelReader=None,
                 miniBatchSize: int = 8, numPossibleLabels: int = -1,
                 labelIndex: Optional[int] = None, regression: bool = False):
        self.fr = featureReader
        self.lr = labelReader
        self.batchSize = miniBatchSize
        self.numClasses = numPossibleLabels
        self.labelIndex = labelIndex
        self.regression = regression
        self._exhausted = False

    def reset(self):
        self.fr.reset()
        if self.lr is not None:
            self.lr.reset()
        self._exhausted = False

    def hasNext(self) -> bool:
        return not self._exhausted and self.fr.hasNext()

    def batch(self) -> int:
        return self.batchSize

    def next(self) -> DataSet:
        xs, ys, lens = [], [], []
        n = 0
        while self.fr.hasNext() and n < self.batchSize:
            seq = self.fr.next()
            n += 1
            if self.lr is not None:
                lab_seq = self.lr.next()
                xs.append([[w.toDouble() for w in step] for step in seq])
                ys.append([self._label(step) for step in lab_seq])
            elif self.labelIndex is not None:
                xs.append([[w.toDouble() for i, w in enumerate(step)
                            if i != self.labelIndex] for step in seq])
                ys.append([self._label([step[self.labelIndex]]) for step in seq])
            else:
                xs.append([[w.toDouble() for w in step] for step in seq])
                ys.append(None)
            lens.append(len(seq))
        if not self.fr.hasNext():
            self._exhausted = True
        T = max(lens)
        F = len(xs[0][0])
        x = np.zeros((len(xs), T, F), np.float32)
        mask = np.zeros((len(xs), T), np.float32)
        for i, s in enumerate(xs):
            x[i, :len(s)] = s
            mask[i, :len(s)] = 1.0
        if ys[0] is None:
            return DataSet(x, x, features_mask=mask, labels_mask=mask)
        L = len(ys[0][0])
        y = np.zeros((len(ys), T, L), np.float32)
        for i, s in enumerate(ys):
            y[i, :len(s)] = s
        return DataSet(x, y, features_mask=mask, labels_mask=mask)

    def _label(self, step) -> List[float]:
        w = step[-1]
        if self.regression:
            return [w.toDouble()]
        return _one_hot(w.toInt(), self.numClasses)


def _one_hot(label: int, num_classes: int) -> List[float]:
    if not 0 <= label < num_classes:
        raise ValueError(f"label {label} outside [0, {num_classes}) — negative "
                         f"sentinels must be filtered before vectorization")
    hot = [0.0] * num_classes
    hot[label] = 1.0
    return hot
