"""Sequence transforms (ref: org.datavec.api.transform.sequence —
ConvertToSequence + SequenceComparator, window.OverlappingTimeWindowFunction
/ TimeWindowFunction, transform.SequenceOffsetTransform, trim/
SequenceTrimTransform, split.SequenceSplitTimeSeparation,
ReduceSequenceTransform).

A sequence is List[List[Writable]] (steps x columns), matching the
SequenceRecordReader contract. Operations are plain list/numpy code — this
is host-side ETL; device work starts after iterators batch the output.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

from deeplearning4j_tpu.datavec.schema import Schema
from deeplearning4j_tpu.datavec.writables import (
    DoubleWritable, IntWritable, NullWritable, Writable, as_writable,
)

Seq = List[List[Writable]]


def convertToSequence(rows: Sequence[Sequence[Writable]], schema: Schema,
                      keyColumn: str, sortColumn: str,
                      ascending: bool = True) -> List[Seq]:
    """Group flat records by key, sort each group on sortColumn (ref:
    ConvertToSequence + NumericalColumnComparator)."""
    ki = schema.getIndexOfColumn(keyColumn)
    si = schema.getIndexOfColumn(sortColumn)
    groups: Dict[str, Seq] = {}
    order: List[str] = []
    for r in rows:
        k = r[ki].toString()
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(list(r))
    out = []
    for k in order:
        seq = sorted(groups[k], key=lambda row: row[si].toDouble(),
                     reverse=not ascending)
        out.append(seq)
    return out


def trimSequence(seq: Seq, numSteps: int, fromStart: bool) -> Seq:
    """Drop numSteps from one end (ref: SequenceTrimTransform)."""
    return seq[numSteps:] if fromStart else seq[:len(seq) - numSteps]


def offsetSequence(seq: Seq, schema: Schema, columns: Sequence[str],
                   offset: int, op: str = "InPlace") -> Seq:
    """Shift ``columns`` by ``offset`` steps (positive = values move to later
    steps — a lag feature; ref: SequenceOffsetTransform with
    OperationType.InPlace/NewColumn). Steps whose shifted value would fall
    outside the sequence are dropped, as the reference's EdgeHandling.
    TrimSequence."""
    idx = [schema.getIndexOfColumn(c) for c in columns]
    n = len(seq)
    out: Seq = []
    for t in range(n):
        src = t - offset
        if src < 0 or src >= n:
            continue
        row = list(seq[t])
        if op == "NewColumn":
            row = row + [seq[src][i] for i in idx]
        else:
            for i in idx:
                row[i] = seq[src][i]
        out.append(row)
    return out


def reduceSequence(seq: Seq, schema: Schema,
                   aggregations: Dict[str, str]) -> List[Writable]:
    """Collapse a sequence to ONE row (ref: ReduceSequenceTransform).
    aggregations: {column: 'sum'|'mean'|'min'|'max'|'count'|'first'|'last'}."""
    out: List[Writable] = []
    for name, agg in aggregations.items():
        i = schema.getIndexOfColumn(name)
        vals = [r[i].toDouble() for r in seq]
        if agg == "sum":
            out.append(DoubleWritable(sum(vals)))
        elif agg == "mean":
            out.append(DoubleWritable(sum(vals) / max(len(vals), 1)))
        elif agg == "min":
            out.append(DoubleWritable(min(vals)))
        elif agg == "max":
            out.append(DoubleWritable(max(vals)))
        elif agg == "count":
            out.append(IntWritable(len(vals)))
        elif agg == "first":
            out.append(seq[0][i])
        elif agg == "last":
            out.append(seq[-1][i])
        else:
            raise ValueError(f"unknown aggregation {agg}")
    return out


def windowSequence(seq: Seq, windowSize: int, step: int = 1,
                   dropPartial: bool = True) -> List[Seq]:
    """Overlapping fixed-size windows (ref: OverlappingTimeWindowFunction on
    an integer time axis; step == windowSize gives the non-overlapping
    TimeWindowFunction)."""
    out = []
    t = 0
    n = len(seq)
    while t < n:
        w = seq[t:t + windowSize]
        if len(w) == windowSize or (w and not dropPartial):
            out.append([list(r) for r in w])
        t += step
        if t + (windowSize if dropPartial else 1) > n and dropPartial and t < n \
                and n - t < windowSize:
            break
    return out


def splitSequenceOnGap(seq: Seq, schema: Schema, timeColumn: str,
                       maxGap: float) -> List[Seq]:
    """Split where consecutive timestamps differ by more than maxGap (ref:
    SequenceSplitTimeSeparation)."""
    i = schema.getIndexOfColumn(timeColumn)
    out: List[Seq] = []
    cur: Seq = []
    prev = None
    for r in seq:
        t = r[i].toDouble()
        if prev is not None and t - prev > maxGap and cur:
            out.append(cur)
            cur = []
        cur.append(list(r))
        prev = t
    if cur:
        out.append(cur)
    return out


def sequenceMovingWindowReduce(seq: Seq, schema: Schema, column: str,
                               window: int, agg: str = "mean",
                               edge: str = "TrimSequence") -> Seq:
    """Append a trailing-window statistic of ``column`` as a new column (ref:
    SequenceMovingWindowReduceTransform; edge 'TrimSequence' drops the warmup
    steps, 'SpecifiedValue'/'NoOp' keeps them with NullWritable)."""
    i = schema.getIndexOfColumn(column)
    fns: Dict[str, Callable[[List[float]], float]] = {
        "mean": lambda v: sum(v) / len(v), "sum": sum,
        "min": min, "max": max,
    }
    fn = fns[agg]
    out: Seq = []
    for t in range(len(seq)):
        row = list(seq[t])
        if t + 1 >= window:
            vals = [seq[j][i].toDouble() for j in range(t + 1 - window, t + 1)]
            row.append(DoubleWritable(fn(vals)))
            out.append(row)
        elif edge != "TrimSequence":
            row.append(NullWritable())
            out.append(row)
    return out
