"""HTML analysis report (ref: datavec-api org.datavec.api.transform.ui.
HtmlAnalysis — renders an AnalyzeLocal DataAnalysis as a standalone page with
per-column stats tables and categorical state-count bars).

Dependency-free HTML+SVG, same artifact style as ui/html_report.py.
"""
from __future__ import annotations

import html
from typing import Optional

from deeplearning4j_tpu.datavec.analysis import DataAnalysis
from deeplearning4j_tpu.ui.palette import PALETTE

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>Data analysis</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 24px; color: #222; }}
 h1 {{ font-size: 18px; }} h2 {{ font-size: 14px; margin: 16px 0 4px; }}
 table {{ border-collapse: collapse; font-size: 13px; }}
 td, th {{ border: 1px solid #ddd; padding: 3px 10px; text-align: right; }}
 th {{ background: #f5f5f5; }} td:first-child {{ text-align: left; }}
 svg text {{ font-size: 10px; fill: #444; }}
</style></head><body>
<h1>Data analysis</h1>
<div>{ncols} columns · {nrows} rows</div>
{sections}
</body></html>"""


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.5g}"
    return str(v)


def _bars(counts: dict, w=420, row_h=18) -> str:
    if not counts:
        return ""
    items = sorted(counts.items(), key=lambda kv: -kv[1])[:20]
    mx = max(c for _, c in items)
    h = row_h * len(items) + 6
    parts = [f'<svg width="{w}" height="{h}">']
    for i, (state, c) in enumerate(items):
        bw = (c / mx) * (w - 180)
        y = i * row_h + 3
        parts.append(
            f'<text x="2" y="{y + 12}">{html.escape(str(state))[:18]}</text>'
            f'<rect x="130" y="{y}" width="{bw:.1f}" height="{row_h - 4}" '
            f'fill="{PALETTE[0]}"/>'
            f'<text x="{134 + bw:.1f}" y="{y + 12}">{c}</text>')
    parts.append("</svg>")
    return "".join(parts)


class HtmlAnalysis:
    """(ref: HtmlAnalysis.createHtmlAnalysisFile)."""

    @staticmethod
    def createHtmlAnalysisFile(analysis: DataAnalysis, path: str) -> str:
        sections = []
        nrows = 0
        for name in analysis.schema.getColumnNames():
            ca = analysis.getColumnAnalysis(name)
            stats = ca.stats
            nrows = max(nrows, int(stats.get("count", 0)))
            rows = "".join(
                f"<tr><td>{html.escape(k)}</td><td>{_fmt(v)}</td></tr>"
                for k, v in stats.items() if k != "stateCounts")
            section = (f"<h2>{html.escape(name)}</h2>"
                       f"<table><tr><th>stat</th><th>value</th></tr>{rows}</table>")
            if "stateCounts" in stats:
                section += _bars(stats["stateCounts"])
            sections.append(section)
        page = _PAGE.format(ncols=analysis.schema.numColumns(), nrows=nrows,
                            sections="".join(sections))
        with open(path, "w") as f:
            f.write(page)
        return path
