"""Audio ETL (ref: datavec-data-audio — WavFileRecordReader over JavaSound,
plus the reference's MFCC pipeline via musicg/jAudio helpers).

WAV decode uses the stdlib ``wave`` module (PCM 8/16/32-bit); feature
extraction (spectrogram, log-mel, MFCC) is jnp code — framing is one
strided-window reshape, the filterbank is one matmul, the DCT one matmul:
all fuse into a handful of XLA ops, where the reference loops frames in
Java.
"""
from __future__ import annotations

import wave
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader, SequenceRecordReader
from deeplearning4j_tpu.datavec.split import InputSplit
from deeplearning4j_tpu.datavec.writables import FloatWritable, NDArrayWritable, Writable


def read_wav(path: str):
    """-> (samples float32 in [-1, 1] shaped (n,) mono / (n, ch), rate)."""
    with wave.open(path, "rb") as w:
        n, ch, width, rate = (w.getnframes(), w.getnchannels(),
                              w.getsampwidth(), w.getframerate())
        raw = w.readframes(n)
    if width == 2:
        x = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
    elif width == 4:
        x = np.frombuffer(raw, "<i4").astype(np.float32) / 2147483648.0
    elif width == 1:  # unsigned 8-bit
        x = (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if ch > 1:
        x = x.reshape(-1, ch)
    return x, rate


def write_wav(path: str, samples: np.ndarray, rate: int):
    """Mono/multi-channel float [-1,1] -> 16-bit PCM (test-fixture helper)."""
    x = np.asarray(samples)
    ch = 1 if x.ndim == 1 else x.shape[1]
    pcm = np.clip(x * 32767.0, -32768, 32767).astype("<i2")
    with wave.open(path, "wb") as w:
        w.setnchannels(ch)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())


# ------------------------------------------------------------- features

def frame_signal(x, frame_length: int, frame_step: int):
    """(n,) -> (num_frames, frame_length) via strided windows."""
    x = jnp.asarray(x)
    n_frames = 1 + max(0, (x.shape[0] - frame_length)) // frame_step
    idx = (jnp.arange(frame_length)[None, :]
           + frame_step * jnp.arange(n_frames)[:, None])
    return x[idx]


def spectrogram(x, frame_length: int = 256, frame_step: int = 128,
                window: str = "hann"):
    """Magnitude STFT (num_frames, frame_length//2 + 1)."""
    frames = frame_signal(x, frame_length, frame_step)
    if window == "hann":
        frames = frames * jnp.hanning(frame_length)
    return jnp.abs(jnp.fft.rfft(frames, axis=-1))


def mel_filterbank(num_mel: int, frame_length: int, rate: int,
                   fmin: float = 0.0, fmax: Optional[float] = None):
    """(num_mel, frame_length//2+1) triangular filters on the mel scale."""
    fmax = fmax or rate / 2

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    n_bins = frame_length // 2 + 1
    mel_pts = np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), num_mel + 2)
    hz_pts = mel_to_hz(mel_pts)
    bins = np.floor((frame_length + 1) * hz_pts / rate).astype(int)
    fb = np.zeros((num_mel, n_bins), np.float32)
    for m in range(1, num_mel + 1):
        lo, ctr, hi = bins[m - 1], bins[m], bins[m + 1]
        for k in range(lo, ctr):
            if ctr > lo:
                fb[m - 1, k] = (k - lo) / (ctr - lo)
        for k in range(ctr, hi):
            if hi > ctr:
                fb[m - 1, k] = (hi - k) / (hi - ctr)
    return jnp.asarray(fb)


def _dct_matrix(n_out: int, n_in: int):
    k = np.arange(n_out)[:, None]
    i = np.arange(n_in)[None, :]
    m = np.sqrt(2.0 / n_in) * np.cos(np.pi * k * (2 * i + 1) / (2 * n_in))
    m[0] /= np.sqrt(2.0)
    return jnp.asarray(m.astype(np.float32))


def mfcc(x, rate: int, num_coeffs: int = 13, num_mel: int = 26,
         frame_length: int = 256, frame_step: int = 128):
    """(num_frames, num_coeffs) mel-frequency cepstral coefficients."""
    spec = spectrogram(x, frame_length, frame_step)
    fb = mel_filterbank(num_mel, frame_length, rate)
    mel_energy = jnp.log(jnp.maximum(spec ** 2 @ fb.T, 1e-10))
    return mel_energy @ _dct_matrix(num_coeffs, num_mel).T


# -------------------------------------------------------------- readers

class WavFileRecordReader(RecordReader):
    """One record per WAV file: every amplitude sample as a FloatWritable
    (ref: org.datavec.audio.recordreader.WavFileRecordReader)."""

    def __init__(self):
        self._paths: List[str] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._paths = list(split.locations())
        self._pos = 0

    def hasNext(self) -> bool:
        return self._pos < len(self._paths)

    def next(self) -> List[Writable]:
        x, _ = read_wav(self._paths[self._pos])
        self._pos += 1
        return [FloatWritable(float(v)) for v in np.ravel(x)]

    def reset(self):
        self._pos = 0


class SpectrogramSequenceRecordReader(SequenceRecordReader):
    """WAV -> feature-frame sequence: each step one NDArrayWritable row of
    the spectrogram (or MFCC with ``features='mfcc'``). The datavec-native
    route from audio files to masked sequence DataSets."""

    def __init__(self, frame_length: int = 256, frame_step: int = 128,
                 features: str = "spectrogram", num_coeffs: int = 13):
        self.frame_length = frame_length
        self.frame_step = frame_step
        self.features = features
        self.num_coeffs = num_coeffs
        self._paths: List[str] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._paths = list(split.locations())
        self._pos = 0

    def hasNext(self) -> bool:
        return self._pos < len(self._paths)

    def next(self):
        x, rate = read_wav(self._paths[self._pos])
        self._pos += 1
        if x.ndim > 1:
            x = x.mean(-1)
        if self.features == "mfcc":
            feats = mfcc(x, rate, num_coeffs=self.num_coeffs,
                         frame_length=self.frame_length,
                         frame_step=self.frame_step)
        else:
            feats = spectrogram(x, self.frame_length, self.frame_step)
        feats = np.asarray(feats)
        return [[NDArrayWritable(row)] for row in feats]

    def reset(self):
        self._pos = 0
