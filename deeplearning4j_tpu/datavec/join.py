"""Record-set joins (ref: org.datavec.api.transform.join.Join — Inner/
LeftOuter/RightOuter/FullOuter on key columns, executed by
LocalTransformExecutor.executeJoin; schemas merge left-then-right with key
columns deduplicated)."""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from deeplearning4j_tpu.datavec.schema import Schema
from deeplearning4j_tpu.datavec.writables import NullWritable, Writable


class Join:
    """Declarative join spec + executor.

    joinType: 'Inner' | 'LeftOuter' | 'RightOuter' | 'FullOuter'
    (ref: Join.Builder: setJoinColumns / setSchemas).
    """

    def __init__(self, joinType: str, leftSchema: Schema, rightSchema: Schema,
                 joinColumns: Sequence[str]):
        assert joinType in ("Inner", "LeftOuter", "RightOuter", "FullOuter"), joinType
        self.joinType = joinType
        self.left = leftSchema
        self.right = rightSchema
        self.keys = list(joinColumns)

    # ------------------------------------------------------------- schema
    def getOutputSchema(self) -> Schema:
        cols = [self.left.getMetaData(n) for n in self.left.getColumnNames()]
        cols += [self.right.getMetaData(n) for n in self.right.getColumnNames()
                 if n not in self.keys]
        return Schema(list(cols))

    # ---------------------------------------------------------------- exec
    def _key_of(self, row: List[Writable], schema: Schema) -> Tuple:
        return tuple(row[schema.getIndexOfColumn(k)].toString() for k in self.keys)

    def execute(self, leftRows: Sequence[Sequence[Writable]],
                rightRows: Sequence[Sequence[Writable]]) -> List[List[Writable]]:
        right_names = [n for n in self.right.getColumnNames() if n not in self.keys]
        right_idx = [self.right.getIndexOfColumn(n) for n in right_names]
        index: Dict[Tuple, List[List[Writable]]] = {}
        for r in rightRows:
            index.setdefault(self._key_of(list(r), self.right), []).append(list(r))

        out: List[List[Writable]] = []
        matched_keys = set()
        for l in leftRows:
            l = list(l)
            key = self._key_of(l, self.left)
            matches = index.get(key, [])
            if matches:
                matched_keys.add(key)
                for r in matches:
                    out.append(l + [r[i] for i in right_idx])
            elif self.joinType in ("LeftOuter", "FullOuter"):
                out.append(l + [NullWritable() for _ in right_idx])

        if self.joinType in ("RightOuter", "FullOuter"):
            left_key_idx = [self.left.getIndexOfColumn(k) for k in self.keys]
            n_left = len(self.left.getColumnNames())
            for r in rightRows:
                r = list(r)
                key = self._key_of(r, self.right)
                if key in matched_keys:
                    continue
                left_row: List[Writable] = [NullWritable()] * n_left
                for k, i in zip(self.keys, left_key_idx):
                    left_row[i] = r[self.right.getIndexOfColumn(k)]
                out.append(left_row + [r[i] for i in right_idx])
        return out
