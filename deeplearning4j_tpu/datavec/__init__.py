"""ETL / data vectorization (ref: datavec/ — records -> tensors pipeline,
SURVEY.md §2.3)."""
from deeplearning4j_tpu.datavec.writables import (
    BytesWritable,
    Writable, DoubleWritable, FloatWritable, IntWritable, LongWritable, Text,
    BooleanWritable, NDArrayWritable, NullWritable)
from deeplearning4j_tpu.datavec.split import (
    InputSplit, FileSplit, CollectionInputSplit, NumberedFileInputSplit, StringSplit)
from deeplearning4j_tpu.datavec.schema import Schema, ColumnType
from deeplearning4j_tpu.datavec.records import (
    RecordReader, SequenceRecordReader, CSVRecordReader, CSVSequenceRecordReader,
    LineRecordReader, CollectionRecordReader, CollectionSequenceRecordReader,
    RegexLineRecordReader, ComposableRecordReader, TransformProcessRecordReader)
from deeplearning4j_tpu.datavec.transform import (
    TransformProcess, Condition, ConditionOp, ConditionFilter, FilterInvalidValues,
    MathOp)
from deeplearning4j_tpu.datavec.local import LocalTransformExecutor
from deeplearning4j_tpu.datavec.analysis import AnalyzeLocal
from deeplearning4j_tpu.datavec.iterator import (
    RecordReaderDataSetIterator, SequenceRecordReaderDataSetIterator)
from deeplearning4j_tpu.datavec.image import ImageRecordReader, NativeImageLoader
from deeplearning4j_tpu.datavec.arrow import ArrowConverter, ArrowRecordReader
from deeplearning4j_tpu.datavec.codec import CodecRecordReader
from deeplearning4j_tpu.datavec.jdbc import JdbcRecordReader
from deeplearning4j_tpu.datavec.excel import ExcelRecordReader
from deeplearning4j_tpu.datavec.geo import (GeoRecordReader, IPAddressToLocationTransform, IPLocationDatabase)

__all__ = [
    "Writable", "DoubleWritable", "FloatWritable", "IntWritable", "LongWritable",
    "Text", "BooleanWritable", "NDArrayWritable", "NullWritable",
    "InputSplit", "FileSplit", "CollectionInputSplit", "NumberedFileInputSplit",
    "StringSplit", "Schema", "ColumnType",
    "RecordReader", "SequenceRecordReader", "CSVRecordReader",
    "CSVSequenceRecordReader", "LineRecordReader", "CollectionRecordReader",
    "CollectionSequenceRecordReader", "RegexLineRecordReader",
    "ComposableRecordReader", "TransformProcessRecordReader",
    "TransformProcess", "Condition", "ConditionOp", "ConditionFilter",
    "FilterInvalidValues", "MathOp", "LocalTransformExecutor", "AnalyzeLocal",
    "RecordReaderDataSetIterator", "SequenceRecordReaderDataSetIterator",
    "ImageRecordReader", "NativeImageLoader",
    "ArrowConverter", "ArrowRecordReader",
    "CodecRecordReader", "JdbcRecordReader", "ExcelRecordReader",
    "GeoRecordReader", "IPAddressToLocationTransform", "IPLocationDatabase",
    "BytesWritable",
]
