"""Record readers (ref: datavec-api org.datavec.api.records.reader.* — pull-
based record sources over InputSplits)."""
from __future__ import annotations

import csv
import io
import re
from typing import Iterator, List, Optional, Sequence

from deeplearning4j_tpu.datavec.split import InputSplit, StringSplit
from deeplearning4j_tpu.datavec.writables import Text, Writable, as_writable


class RecordReader:
    """(ref: org.datavec.api.records.reader.RecordReader)."""

    def initialize(self, split: InputSplit):
        raise NotImplementedError

    def next(self) -> List[Writable]:
        raise NotImplementedError

    def hasNext(self) -> bool:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self) -> Iterator[List[Writable]]:
        self.reset()
        while self.hasNext():
            yield self.next()


class SequenceRecordReader(RecordReader):
    """(ref: SequenceRecordReader) — next() returns a sequence: list of steps,
    each a list of Writables."""

    def sequenceRecord(self) -> List[List[Writable]]:
        return self.next()


class _LineBased(RecordReader):
    """Shared machinery: enumerate lines across the split's locations."""

    def __init__(self, skipNumLines: int = 0):
        self.skip = skipNumLines
        self._lines: List[str] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._lines = []
        for loc in split.locations():
            if isinstance(split, StringSplit):
                text = loc
            else:
                with open(loc, "r") as f:
                    text = f.read()
            lines = [l for l in text.splitlines()[self.skip:] if l.strip()]
            self._lines.extend(lines)
        self._pos = 0
        return self

    def hasNext(self) -> bool:
        return self._pos < len(self._lines)

    def reset(self):
        self._pos = 0

    def _next_line(self) -> str:
        line = self._lines[self._pos]
        self._pos += 1
        return line


class LineRecordReader(_LineBased):
    """One Text writable per line (ref: LineRecordReader)."""

    def next(self) -> List[Writable]:
        return [Text(self._next_line())]


class CSVRecordReader(_LineBased):
    """(ref: org.datavec.api.records.reader.impl.csv.CSVRecordReader)."""

    def __init__(self, skipNumLines: int = 0, delimiter: str = ","):
        super().__init__(skipNumLines)
        self.delimiter = delimiter

    def next(self) -> List[Writable]:
        row = next(csv.reader(io.StringIO(self._next_line()),
                              delimiter=self.delimiter))
        return [Text(v.strip()) for v in row]


class RegexLineRecordReader(_LineBased):
    """Line -> regex groups (ref: RegexLineRecordReader)."""

    def __init__(self, regex: str, skipNumLines: int = 0):
        super().__init__(skipNumLines)
        self.pattern = re.compile(regex)

    def next(self) -> List[Writable]:
        line = self._next_line()
        m = self.pattern.match(line)
        if m is None:
            raise ValueError(f"line does not match regex: {line!r}")
        return [Text(g) for g in m.groups()]


class CSVSequenceRecordReader(SequenceRecordReader):
    """One sequence per FILE (ref: CSVSequenceRecordReader — each location is
    a time series, rows = steps)."""

    def __init__(self, skipNumLines: int = 0, delimiter: str = ","):
        self.skip = skipNumLines
        self.delimiter = delimiter
        self._seqs: List[List[List[Writable]]] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._seqs = []
        for loc in split.locations():
            with open(loc, "r") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter))[self.skip:]
            self._seqs.append([[Text(v.strip()) for v in row] for row in rows if row])
        self._pos = 0
        return self

    def next(self) -> List[List[Writable]]:
        s = self._seqs[self._pos]
        self._pos += 1
        return s

    def hasNext(self) -> bool:
        return self._pos < len(self._seqs)

    def reset(self):
        self._pos = 0


class CollectionRecordReader(RecordReader):
    """In-memory records (ref: CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence]):
        self._records = [[as_writable(v) for v in r] for r in records]
        self._pos = 0

    def initialize(self, split: Optional[InputSplit] = None):
        self._pos = 0
        return self

    def next(self) -> List[Writable]:
        r = self._records[self._pos]
        self._pos += 1
        return list(r)

    def hasNext(self) -> bool:
        return self._pos < len(self._records)

    def reset(self):
        self._pos = 0


class CollectionSequenceRecordReader(SequenceRecordReader):
    """(ref: CollectionSequenceRecordReader)."""

    def __init__(self, sequences: Sequence[Sequence[Sequence]]):
        self._seqs = [[[as_writable(v) for v in step] for step in seq]
                      for seq in sequences]
        self._pos = 0

    def initialize(self, split: Optional[InputSplit] = None):
        self._pos = 0
        return self

    def next(self):
        s = self._seqs[self._pos]
        self._pos += 1
        return s

    def hasNext(self):
        return self._pos < len(self._seqs)

    def reset(self):
        self._pos = 0


class ComposableRecordReader(RecordReader):
    """Concatenate several readers' records per step (ref: ComposableRecordReader)."""

    def __init__(self, *readers: RecordReader):
        self.readers = list(readers)

    def initialize(self, split: Optional[InputSplit] = None):
        return self

    def next(self) -> List[Writable]:
        out: List[Writable] = []
        for r in self.readers:
            out.extend(r.next())
        return out

    def hasNext(self) -> bool:
        return all(r.hasNext() for r in self.readers)

    def reset(self):
        for r in self.readers:
            r.reset()


class TransformProcessRecordReader(RecordReader):
    """Wrap a reader with a TransformProcess applied per record
    (ref: TransformProcessRecordReader). Filtered records are skipped."""

    def __init__(self, recordReader: RecordReader, transformProcess):
        self.reader = recordReader
        self.tp = transformProcess
        self._pending: Optional[List[Writable]] = None

    def initialize(self, split: InputSplit):
        self.reader.initialize(split)
        return self

    def _advance(self):
        while self._pending is None and self.reader.hasNext():
            out = self.tp.execute([self.reader.next()])
            if out:
                self._pending = out[0]

    def hasNext(self) -> bool:
        self._advance()
        return self._pending is not None

    def next(self) -> List[Writable]:
        self._advance()
        if self._pending is None:
            raise StopIteration
        r, self._pending = self._pending, None
        return r

    def reset(self):
        self.reader.reset()
        self._pending = None
