"""Excel record reader (ref: datavec-excel
org.datavec.poi.excel.ExcelRecordReader — reads spreadsheet rows as records
via Apache POI). POI's Python analog would be openpyxl, which is not in this
environment; .xlsx is just a zip of XML (ECMA-376), so this reader parses
the sheet XML directly with the stdlib — shared strings, inline strings,
numeric cells, and sparse rows (missing cells become NullWritable).

Only .xlsx (OOXML) is supported; legacy .xls (BIFF) raises — the reference
supports both via POI, and BIFF is a binary format not worth reimplementing
for parity (documented divergence).
"""
from __future__ import annotations

import re
import zipfile
from typing import List, Optional
from xml.etree import ElementTree

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import FileSplit, InputSplit
from deeplearning4j_tpu.datavec.writables import (
    BooleanWritable,
    DoubleWritable,
    NullWritable,
    Text,
    Writable,
)

_NS = {"m": "http://schemas.openxmlformats.org/spreadsheetml/2006/main"}


def _col_index(cell_ref: str) -> int:
    """'C7' -> 2 (zero-based column)."""
    letters = re.match(r"[A-Z]+", cell_ref).group(0)
    idx = 0
    for ch in letters:
        idx = idx * 26 + (ord(ch) - ord("A") + 1)
    return idx - 1


def _read_sheet(zf: zipfile.ZipFile, sheet_path: str,
                shared: List[str]) -> List[List[Writable]]:
    root = ElementTree.fromstring(zf.read(sheet_path))
    rows: List[List[Writable]] = []
    for row in root.iterfind(".//m:sheetData/m:row", _NS):
        cells: List[Writable] = []
        for c in row.iterfind("m:c", _NS):
            ci = _col_index(c.get("r", "A1"))
            while len(cells) < ci:
                cells.append(NullWritable())
            ctype = c.get("t", "n")
            v = c.find("m:v", _NS)
            if ctype == "s" and v is not None:          # shared string
                cells.append(Text(shared[int(v.text)]))
            elif ctype == "inlineStr":
                t = c.find("m:is/m:t", _NS)
                cells.append(Text(t.text if t is not None else ""))
            elif ctype == "str" and v is not None:       # formula cached str
                cells.append(Text(v.text))
            elif ctype == "b" and v is not None:         # boolean
                cells.append(BooleanWritable(v.text in ("1", "true")))
            elif v is not None:                          # numeric
                cells.append(DoubleWritable(float(v.text)))
            else:
                cells.append(NullWritable())
        rows.append(cells)
    width = max((len(r) for r in rows), default=0)
    for r in rows:
        while len(r) < width:
            r.append(NullWritable())
    return rows


def _read_xlsx(path: str, sheet_index: int = 0) -> List[List[Writable]]:
    if str(path).lower().endswith(".xls"):
        raise ValueError(
            ".xls (BIFF) is not supported — convert to .xlsx "
            "(the reference reads both via Apache POI)")
    with zipfile.ZipFile(path) as zf:
        shared: List[str] = []
        if "xl/sharedStrings.xml" in zf.namelist():
            sroot = ElementTree.fromstring(zf.read("xl/sharedStrings.xml"))
            for si in sroot.iterfind("m:si", _NS):
                shared.append("".join(t.text or ""
                                      for t in si.iterfind(".//m:t", _NS)))
        sheets = sorted(
            (n for n in zf.namelist()
             if re.fullmatch(r"xl/worksheets/sheet\d+\.xml", n)),
            key=lambda n: int(re.search(r"(\d+)\.xml$", n).group(1)))
        if sheet_index >= len(sheets):
            raise IndexError(f"sheet {sheet_index} of {len(sheets)}")
        return _read_sheet(zf, sheets[sheet_index], shared)


class ExcelRecordReader(RecordReader):
    """(ref: ExcelRecordReader). Iterates every row of every file in the
    split; ``skipNumLinesStart`` skips header rows per sheet."""

    def __init__(self, sheet_index: int = 0, skipNumLinesStart: int = 0):
        self._sheet = sheet_index
        self._skip = skipNumLinesStart
        self._rows: List[List[Writable]] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._rows = []
        for loc in split.locations():
            self._rows.extend(_read_xlsx(loc, self._sheet)[self._skip:])
        self._pos = 0
        return self

    def hasNext(self) -> bool:
        return self._pos < len(self._rows)

    def next(self) -> List[Writable]:
        if not self.hasNext():
            raise StopIteration
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def reset(self):
        self._pos = 0
