"""Data analysis (ref: datavec-api org.datavec.api.transform.analysis.
AnalyzeLocal — per-column statistics used to parameterize normalizers)."""
from __future__ import annotations

import math
from typing import Dict, List

from deeplearning4j_tpu.datavec.schema import ColumnType, Schema


class ColumnAnalysis:
    def __init__(self, stats: Dict[str, float]):
        self.stats = stats

    def getMin(self):
        return self.stats.get("min")

    def getMax(self):
        return self.stats.get("max")

    def getMean(self):
        return self.stats.get("mean")

    def getSampleStdev(self):
        return self.stats.get("std")

    def getCountTotal(self):
        return self.stats.get("count")


class DataAnalysis:
    def __init__(self, schema: Schema, columns: Dict[str, ColumnAnalysis]):
        self.schema = schema
        self.columns = columns

    def getColumnAnalysis(self, name: str) -> ColumnAnalysis:
        return self.columns[name]


class AnalyzeLocal:
    """(ref: org.datavec.local.transforms.AnalyzeLocal.analyze)."""

    @staticmethod
    def analyze(schema: Schema, reader_or_rows) -> DataAnalysis:
        rows = list(reader_or_rows)
        out: Dict[str, ColumnAnalysis] = {}
        for i, name in enumerate(schema.getColumnNames()):
            t = schema.getType(i)
            if t in (ColumnType.Double, ColumnType.Float, ColumnType.Integer,
                     ColumnType.Long):
                vals: List[float] = []
                for r in rows:
                    try:
                        v = r[i].toDouble()
                    except (ValueError, TypeError):
                        continue
                    if not (math.isnan(v) or math.isinf(v)):
                        vals.append(v)
                n = len(vals)
                mean = sum(vals) / n if n else float("nan")
                var = sum((v - mean) ** 2 for v in vals) / (n - 1) if n > 1 else 0.0
                out[name] = ColumnAnalysis({
                    "count": n, "min": min(vals) if vals else float("nan"),
                    "max": max(vals) if vals else float("nan"),
                    "mean": mean, "std": math.sqrt(var),
                })
            elif t == ColumnType.Categorical:
                counts: Dict[str, int] = {}
                for r in rows:
                    counts[r[i].toString()] = counts.get(r[i].toString(), 0) + 1
                out[name] = ColumnAnalysis({"count": len(rows), "stateCounts": counts})
            else:
                out[name] = ColumnAnalysis({"count": len(rows)})
        return DataAnalysis(schema, out)
