"""Image loading + augmentation (ref: datavec-data-image —
org.datavec.image.loader.NativeImageLoader (JavaCPP OpenCV) and
org.datavec.image.recordreader.ImageRecordReader).

The reference decodes via native OpenCV; here PIL decodes on the host and
NCHW float tensors feed straight to device. Augmentations (ref:
org.datavec.image.transform.*) are numpy-side functions applied pre-transfer."""
from __future__ import annotations

import os
import random
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import InputSplit
from deeplearning4j_tpu.datavec.writables import IntWritable, NDArrayWritable, Writable


class NativeImageLoader:
    """Decode to NCHW float32 (ref: NativeImageLoader(h, w, c))."""

    def __init__(self, height: int, width: int, channels: int = 3):
        self.height = height
        self.width = width
        self.channels = channels

    def asMatrix(self, path_or_img) -> np.ndarray:
        from PIL import Image
        img = path_or_img if hasattr(path_or_img, "resize") else Image.open(path_or_img)
        img = img.convert("L" if self.channels == 1 else "RGB")
        img = img.resize((self.width, self.height))
        arr = np.asarray(img, dtype=np.float32)
        if arr.ndim == 2:
            arr = arr[None]  # (1, H, W)
        else:
            arr = arr.transpose(2, 0, 1)  # HWC -> CHW
        return arr[None]  # (1, C, H, W)


class ImageTransform:
    """Augmentation SPI (ref: org.datavec.image.transform.ImageTransform)."""

    def transform(self, chw: np.ndarray, rng: random.Random) -> np.ndarray:
        raise NotImplementedError


class FlipImageTransform(ImageTransform):
    """Random horizontal flip (ref: FlipImageTransform)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def transform(self, chw, rng):
        return chw[:, :, ::-1].copy() if rng.random() < self.p else chw


class CropImageTransform(ImageTransform):
    """Random crop by up to ``margin`` px each side, resized back
    (ref: CropImageTransform)."""

    def __init__(self, margin: int):
        self.margin = margin

    def transform(self, chw, rng):
        c, h, w = chw.shape
        t = rng.randint(0, self.margin)
        l = rng.randint(0, self.margin)
        b = rng.randint(0, self.margin)
        r = rng.randint(0, self.margin)
        crop = chw[:, t:h - b or h, l:w - r or w]
        # nearest-neighbor resize back
        ys = (np.arange(h) * crop.shape[1] / h).astype(int)
        xs = (np.arange(w) * crop.shape[2] / w).astype(int)
        return crop[:, ys][:, :, xs]


class PipelineImageTransform(ImageTransform):
    def __init__(self, *transforms: ImageTransform):
        self.transforms = list(transforms)

    def transform(self, chw, rng):
        for t in self.transforms:
            chw = t.transform(chw, rng)
        return chw


class ParentPathLabelGenerator:
    """Label = parent directory name (ref: org.datavec.api.io.labels.
    ParentPathLabelGenerator)."""

    def getLabelForPath(self, path: str) -> str:
        return os.path.basename(os.path.dirname(path))


class ImageRecordReader(RecordReader):
    """(ref: org.datavec.image.recordreader.ImageRecordReader) — record =
    [NDArrayWritable(CHW image), IntWritable(label)]."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 labelGenerator=None, imageTransform: Optional[ImageTransform] = None,
                 seed: int = 0):
        self.loader = NativeImageLoader(height, width, channels)
        self.labelGen = labelGenerator or ParentPathLabelGenerator()
        self.imageTransform = imageTransform
        self._rng = random.Random(seed)
        self._paths: List[str] = []
        self._labels: List[str] = []
        self._pos = 0

    def initialize(self, split: InputSplit):
        self._paths = split.locations()
        labels = sorted({self.labelGen.getLabelForPath(p) for p in self._paths})
        self._labels = labels
        self._pos = 0
        return self

    def getLabels(self) -> List[str]:
        return list(self._labels)

    def next(self) -> List[Writable]:
        p = self._paths[self._pos]
        self._pos += 1
        img = self.loader.asMatrix(p)[0]
        if self.imageTransform is not None:
            img = self.imageTransform.transform(img, self._rng)
        label = self._labels.index(self.labelGen.getLabelForPath(p))
        return [NDArrayWritable(img), IntWritable(label)]

    def hasNext(self) -> bool:
        return self._pos < len(self._paths)

    def reset(self):
        self._pos = 0
