"""Writable value family (ref: datavec-api org.datavec.api.writable.* —
Hadoop-style typed cells)."""
from __future__ import annotations

import numpy as np


class Writable:
    def __init__(self, value=None):
        self.value = value

    def toDouble(self) -> float:
        return float(self.value)

    def toFloat(self) -> float:
        return float(self.value)

    def toInt(self) -> int:
        return int(float(self.value))

    def toLong(self) -> int:
        return int(float(self.value))

    def toString(self) -> str:
        return str(self.value)

    def __repr__(self):
        return f"{type(self).__name__}({self.value!r})"

    def __eq__(self, other):
        return type(self) is type(other) and self.value == other.value

    def __hash__(self):
        return hash((type(self).__name__, self.value))


class DoubleWritable(Writable):
    def __init__(self, value=0.0):
        super().__init__(float(value))


class FloatWritable(Writable):
    def __init__(self, value=0.0):
        super().__init__(float(value))


class IntWritable(Writable):
    def __init__(self, value=0):
        super().__init__(int(value))


class LongWritable(Writable):
    def __init__(self, value=0):
        super().__init__(int(value))


class BooleanWritable(Writable):
    def __init__(self, value=False):
        super().__init__(bool(value))

    def toDouble(self):
        return 1.0 if self.value else 0.0

    def toInt(self):
        return 1 if self.value else 0


class Text(Writable):
    def __init__(self, value=""):
        super().__init__(str(value))

    def toDouble(self):
        return float(self.value)

    def toInt(self):
        return int(float(self.value))


class BytesWritable(Writable):
    """(ref: org.datavec.api.writable.BytesWritable)."""

    def __init__(self, value=b""):
        super().__init__(bytes(value))

    def toDouble(self):
        raise TypeError("BytesWritable cannot convert to double")

    def toFloat(self):
        raise TypeError("BytesWritable cannot convert to float")

    def toInt(self):
        raise TypeError("BytesWritable cannot convert to int")

    def toLong(self):
        raise TypeError("BytesWritable cannot convert to long")

    def toString(self):
        return self.value.hex()


class NullWritable(Writable):
    def __init__(self):
        super().__init__(None)

    def toDouble(self):
        return float("nan")

    def toString(self):
        return ""


class NDArrayWritable(Writable):
    """(ref: org.datavec.api.writable.NDArrayWritable)."""

    def __init__(self, array):
        super().__init__(np.asarray(array))

    def toString(self):
        return str(self.value)


def as_writable(v) -> Writable:
    if isinstance(v, Writable):
        return v
    if isinstance(v, bool):
        return BooleanWritable(v)
    if isinstance(v, (int, np.integer)):
        return IntWritable(int(v))
    if isinstance(v, (float, np.floating)):
        return DoubleWritable(float(v))
    if isinstance(v, np.ndarray):
        return NDArrayWritable(v)
    if v is None:
        return NullWritable()
    return Text(str(v))
