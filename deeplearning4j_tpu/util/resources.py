"""Test-resource / archive utilities (ref: nd4j-common —
org.nd4j.common.resources.Resources + strumpf resolver, ArchiveUtils,
org.nd4j.common.util.ArchiveUtils; SURVEY.md §2.2 nd4j-common row).

The reference resolves named test resources from a remote artifact with
checksum verification and a local cache. This environment has zero egress,
so the resolver works against a local cache directory only (seeded by the
user or CI); download hooks are pluggable for environments that have
network. Checksums use sha256 (the reference's strumpf uses sha256 too).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import tarfile
import zipfile
from pathlib import Path
from typing import Callable, Optional


class ArchiveUtils:
    """(ref: org.nd4j.common.util.ArchiveUtils — unzip/untar helpers with
    path-traversal protection)."""

    @staticmethod
    def _check_member(dest: Path, name: str):
        target = (dest / name).resolve()
        if not str(target).startswith(str(dest.resolve()) + os.sep) \
                and target != dest.resolve():
            raise ValueError(f"archive member escapes destination: {name}")

    @staticmethod
    def unzipFileTo(archive: str, dest: str):
        destp = Path(dest)
        destp.mkdir(parents=True, exist_ok=True)
        with zipfile.ZipFile(archive) as zf:
            for n in zf.namelist():
                ArchiveUtils._check_member(destp, n)
            zf.extractall(destp)

    @staticmethod
    def tarGzExtractSingleFile(archive: str, dest_file: str, member: str):
        with tarfile.open(archive, "r:*") as tf:
            try:
                info = tf.getmember(member)
            except KeyError:
                raise FileNotFoundError(member) from None
            src = tf.extractfile(info)
            if src is None:
                raise FileNotFoundError(member)
            Path(dest_file).parent.mkdir(parents=True, exist_ok=True)
            with open(dest_file, "wb") as out:
                shutil.copyfileobj(src, out)

    @staticmethod
    def untarTo(archive: str, dest: str):
        destp = Path(dest)
        destp.mkdir(parents=True, exist_ok=True)
        with tarfile.open(archive, "r:*") as tf:
            # filter='data' rejects traversal, symlink-through-writes,
            # devices, and absolute names (PEP 706) — a name-only pre-scan
            # is bypassable via archive-created symlinks
            tf.extractall(destp, filter="data")

    @staticmethod
    def zipDirectory(src_dir: str, archive: str):
        srcp = Path(src_dir)
        with zipfile.ZipFile(archive, "w", zipfile.ZIP_DEFLATED) as zf:
            for f in sorted(srcp.rglob("*")):
                if f.is_file():
                    zf.write(f, f.relative_to(srcp))


def sha256_of(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class Resources:
    """(ref: org.nd4j.common.resources.Resources — `asFile("name")` resolves
    a named resource via registered resolvers; the strumpf resolver fetches
    + caches + checksum-verifies).

    Resolution order: (1) explicit cache dir (env
    DL4JTPU_RESOURCES_CACHE_DIR, default ~/.deeplearning4j_tpu/resources),
    (2) a registered fetch hook (none by default — zero-egress environment).
    """

    _fetch_hook: Optional[Callable[[str, Path], None]] = None

    @staticmethod
    def cacheDir() -> Path:
        return Path(os.environ.get(
            "DL4JTPU_RESOURCES_CACHE_DIR",
            str(Path.home() / ".deeplearning4j_tpu" / "resources")))

    @classmethod
    def registerFetchHook(cls, hook: Optional[Callable[[str, Path], None]]):
        """hook(resource_name, dest_path) — downloads into dest_path.
        Pass None to deregister."""
        cls._fetch_hook = hook

    @classmethod
    def _resolve(cls, name: str) -> Path:
        cache = cls.cacheDir()
        p = (cache / name)
        if not str(p.resolve()).startswith(str(cache.resolve()) + os.sep):
            raise ValueError(f"resource name escapes the cache dir: {name}")
        return p

    @classmethod
    def exists(cls, name: str) -> bool:
        return cls._resolve(name).exists()

    @classmethod
    def asFile(cls, name: str, sha256: Optional[str] = None,
               evictOnMismatch: bool = True) -> Path:
        p = cls._resolve(name)
        fetched = False
        if not p.exists():
            if cls._fetch_hook is None:
                raise FileNotFoundError(
                    f"resource '{name}' not in cache {cls.cacheDir()} and no "
                    "fetch hook is registered (zero-egress environment; seed "
                    "the cache manually or registerFetchHook)")
            p.parent.mkdir(parents=True, exist_ok=True)
            # fetch to a unique temp sibling and rename on success: an
            # aborted download never poses as cached, and concurrent
            # fetchers of the same name cannot clobber each other's temp
            import tempfile
            fd, tmp_name = tempfile.mkstemp(prefix=p.name + ".", suffix=".part",
                                            dir=p.parent)
            os.close(fd)
            tmp = Path(tmp_name)
            try:
                cls._fetch_hook(name, tmp)
                os.replace(tmp, p)
                fetched = True
            finally:
                tmp.unlink(missing_ok=True)
        if sha256 is not None:
            got = sha256_of(str(p))
            if got != sha256:
                # a freshly fetched artifact is certainly bad — evict it; a
                # pre-seeded file is only evicted when the caller opts in
                # (evictOnMismatch=False protects user-seeded weights)
                note = ""
                if fetched or evictOnMismatch:
                    p.unlink(missing_ok=True)
                    note = " (cached copy removed)"
                raise IOError(f"checksum mismatch for {name}: expected "
                              f"{sha256}, got {got}{note}")
        return p
