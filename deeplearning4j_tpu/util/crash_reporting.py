"""Crash forensics (ref: org.deeplearning4j.util.CrashReportingUtil — on an
OOM during fit, dl4j writes a crash dump with JVM/system memory state, the
network configuration, and workspace info so users can diagnose without a
debugger).

The TPU analog dumps: the exception + traceback, backend + per-device memory
stats (live/peak bytes from PJRT when the backend exposes them), host RSS,
and the model's class/param-count/configuration JSON. Enabled by default,
like the reference (``crashDumpsEnabled(False)`` to opt out); dumps land in
the current directory or ``crashDumpOutputDirectory(path)``.
"""
from __future__ import annotations

import datetime
import os
import sys
import traceback
from typing import Optional

_enabled = True
_out_dir: Optional[str] = None


def crashDumpsEnabled(enabled: bool):
    """(ref: CrashReportingUtil.crashDumpsEnabled)."""
    global _enabled
    _enabled = bool(enabled)


def crashDumpOutputDirectory(path: Optional[str]):
    """(ref: CrashReportingUtil.crashDumpOutputDirectory)."""
    global _out_dir
    _out_dir = path


def writeMemoryCrashDump(model, exception: BaseException,
                         context: Optional[dict] = None) -> Optional[str]:
    """Write the dump; returns the path (None when disabled or the dump
    itself fails — crash reporting must never mask the original error).
    ``context`` adds caller-provided key/value lines (the serving engines
    record which component/engine/bucket was dispatching when it died)."""
    if not _enabled:
        return None
    try:
        import jax
        lines = []
        lines.append("deeplearning4j_tpu crash dump")
        lines.append(f"time: {datetime.datetime.now().isoformat()}")
        lines.append(f"pid: {os.getpid()}")
        lines.append("")
        lines.append("---- exception " + "-" * 50)
        lines.append("".join(traceback.format_exception(
            type(exception), exception, exception.__traceback__)))
        lines.append("---- devices " + "-" * 52)
        try:
            lines.append(f"backend: {jax.default_backend()}")
            for d in jax.devices():
                stats = {}
                try:
                    stats = d.memory_stats() or {}
                except Exception:
                    pass
                keep = {k: v for k, v in stats.items()
                        if k in ("bytes_in_use", "peak_bytes_in_use",
                                 "bytes_limit", "largest_alloc_size")}
                lines.append(f"  {d}: {keep or 'no memory stats exposed'}")
        except Exception as e:  # backend itself may be the thing that died
            lines.append(f"  <device query failed: {e}>")
        try:
            import resource  # Unix-only; dumps degrade gracefully elsewhere
            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is KiB on Linux, BYTES on macOS
            rss_mb = rss / (1048576.0 if sys.platform == "darwin" else 1024.0)
            lines.append(f"host max RSS: {rss_mb:.1f} MB")
        except ImportError:
            pass
        if context:
            lines.append("")
            lines.append("---- context " + "-" * 52)
            for k in sorted(context):
                lines.append(f"{k}: {context[k]}")
        try:
            # the serving flight recorder (serving/tracing.py): a bounded
            # always-on ring of recent structured events — breaker
            # transitions, retries, watchdog restarts, dispatch failures —
            # so the dump carries what the serving stack did just before
            # it died. Lazy + guarded: a dump must work even when the
            # serving package was never imported or is itself broken.
            import json as _json

            from deeplearning4j_tpu.serving.tracing import flight_recorder
            events = flight_recorder().snapshot()
            if events:
                lines.append("")
                lines.append(f"---- flight recorder (last {len(events)} "
                             "events) " + "-" * 20)
                for e in events:
                    lines.append(_json.dumps(e, default=str))
        except Exception:
            pass
        lines.append("")
        lines.append("---- model " + "-" * 54)
        lines.append(f"class: {type(model).__name__}")
        try:
            lines.append(f"numParams: {model.numParams()}")
        except Exception:
            pass
        try:
            conf = getattr(model, "conf", None)
            if conf is not None and hasattr(conf, "to_json"):
                lines.append("configuration:")
                lines.append(conf.to_json())
        except Exception:
            pass
        name = (f"dl4jtpu-crash-{datetime.datetime.now():%Y%m%d-%H%M%S}"
                f"-{os.getpid()}.txt")
        path = os.path.join(_out_dir or os.getcwd(), name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write("\n".join(lines))
        return path
    except Exception:
        return None  # never shadow the original failure
