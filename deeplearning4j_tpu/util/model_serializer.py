"""Model persistence (ref: org.deeplearning4j.util.ModelSerializer — zip
containing configuration.json + coefficients.bin + updaterState.bin).

Same container design: a zip with
- ``configuration.json`` — the network config (JSON round-trip DSL) plus a
  ``networkType`` discriminator and iteration/epoch counters,
- ``coefficients.npy``  — the flat parameter vector (the reference's
  paramsFlattened invariant, preserved at this boundary),
- ``updaterState.npz``  — optimizer-state leaves in tree order (structure is
  reconstructed from a fresh ``tx.init`` on load, so only leaves are stored —
  exact-resume parity with saveUpdater=true),
- ``state.npz``         — layer-state leaves in tree order (BatchNorm running
  mean/var etc.; the reference stores BN global stats inside the params
  vector, so its checkpoint preserves them — ours must too).
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import jax
import numpy as np


def _save_leaves(z: zipfile.ZipFile, name: str, tree) -> None:
    """Serialize a pytree's leaves (tree order) into an npz archive member."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return
    buf = io.BytesIO()
    np.savez(buf, **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)})
    z.writestr(name, buf.getvalue())


def _load_leaves(z: zipfile.ZipFile, name: str, like):
    """Restore a pytree saved by _save_leaves, taking structure/dtypes/shapes
    from a freshly initialized ``like`` tree."""
    data = np.load(io.BytesIO(z.read(name)))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(data.files) != len(leaves):
        raise ValueError(
            f"{name}: checkpoint has {len(data.files)} leaves but the model "
            f"expects {len(leaves)} — incompatible framework version?")
    restored = [jax.numpy.asarray(
        np.asarray(data[f"leaf_{i}"], dtype=np.asarray(l).dtype)
        .reshape(np.shape(l))) for i, l in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, restored)


class ModelSerializer:

    @staticmethod
    def writeModel(model, path: str, saveUpdater: bool = True):
        """(ref: ModelSerializer.writeModel)."""
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(model, MultiLayerNetwork):
            net_type = "MultiLayerNetwork"
        elif isinstance(model, ComputationGraph):
            net_type = "ComputationGraph"
        else:
            raise TypeError(f"cannot serialize {type(model).__name__}")
        meta = {
            "networkType": net_type,
            "configuration": json.loads(model.conf.to_json()),
            "iterationCount": model.getIterationCount(),
            "epochCount": model.getEpochCount(),
            "saveUpdater": bool(saveUpdater),
        }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("configuration.json", json.dumps(meta, indent=2))
            buf = io.BytesIO()
            np.save(buf, np.asarray(model.params().jax, dtype=np.float64))
            z.writestr("coefficients.npy", buf.getvalue())
            _save_leaves(z, "state.npz", model._state)
            if saveUpdater and model._opt_state is not None:
                _save_leaves(z, "updaterState.npz", model._opt_state)

    @staticmethod
    def _restore(path: str, expect_type: Optional[str], loadUpdater: bool):
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path, "r") as z:
            meta = json.loads(z.read("configuration.json"))
            net_type = meta["networkType"]
            if expect_type and net_type != expect_type:
                raise ValueError(f"{path} contains a {net_type}, expected {expect_type}")
            conf_json = json.dumps(meta["configuration"])
            if net_type == "MultiLayerNetwork":
                model = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json)).init()
            else:
                model = ComputationGraph(ComputationGraphConfiguration.from_json(conf_json)).init()
            flat = np.load(io.BytesIO(z.read("coefficients.npy")))
            model.setParams(flat)
            if "state.npz" in z.namelist():
                model._state = _load_leaves(z, "state.npz", model._state)
            model._iteration = meta.get("iterationCount", 0)
            model._epoch = meta.get("epochCount", 0)
            if loadUpdater and meta.get("saveUpdater") and "updaterState.npz" in z.namelist():
                model._opt_state = _load_leaves(
                    z, "updaterState.npz", model._tx.init(model._params))
        return model

    @staticmethod
    def restoreMultiLayerNetwork(path: str, loadUpdater: bool = True):
        """(ref: ModelSerializer.restoreMultiLayerNetwork)."""
        return ModelSerializer._restore(path, "MultiLayerNetwork", loadUpdater)

    @staticmethod
    def restoreComputationGraph(path: str, loadUpdater: bool = True):
        """(ref: ModelSerializer.restoreComputationGraph)."""
        return ModelSerializer._restore(path, "ComputationGraph", loadUpdater)

    @staticmethod
    def restoreModel(path: str, loadUpdater: bool = True):
        """Type-sniffing restore (ref: ModelGuesser.loadModelGuess)."""
        return ModelSerializer._restore(path, None, loadUpdater)
