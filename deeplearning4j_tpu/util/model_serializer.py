"""Model persistence (ref: org.deeplearning4j.util.ModelSerializer — zip
containing configuration.json + coefficients.bin + updaterState.bin).

Same container design: a zip with
- ``configuration.json`` — the network config (JSON round-trip DSL) plus a
  ``networkType`` discriminator and iteration/epoch counters,
- ``coefficients.npy``  — the flat parameter vector (the reference's
  paramsFlattened invariant, preserved at this boundary),
- ``updaterState.npz``  — optimizer-state leaves in tree order (structure is
  reconstructed from a fresh ``tx.init`` on load, so only leaves are stored —
  exact-resume parity with saveUpdater=true).
"""
from __future__ import annotations

import io
import json
import zipfile
from typing import Optional

import jax
import numpy as np


class ModelSerializer:

    @staticmethod
    def writeModel(model, path: str, saveUpdater: bool = True):
        """(ref: ModelSerializer.writeModel)."""
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if isinstance(model, MultiLayerNetwork):
            net_type = "MultiLayerNetwork"
        elif isinstance(model, ComputationGraph):
            net_type = "ComputationGraph"
        else:
            raise TypeError(f"cannot serialize {type(model).__name__}")
        meta = {
            "networkType": net_type,
            "configuration": json.loads(model.conf.to_json()),
            "iterationCount": model.getIterationCount(),
            "epochCount": model.getEpochCount(),
            "saveUpdater": bool(saveUpdater),
        }
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("configuration.json", json.dumps(meta, indent=2))
            buf = io.BytesIO()
            np.save(buf, np.asarray(model.params().jax, dtype=np.float64))
            z.writestr("coefficients.npy", buf.getvalue())
            if saveUpdater and model._opt_state is not None:
                leaves = jax.tree_util.tree_leaves(model._opt_state)
                buf = io.BytesIO()
                np.savez(buf, **{f"leaf_{i}": np.asarray(l)
                                 for i, l in enumerate(leaves)})
                z.writestr("updaterState.npz", buf.getvalue())

    @staticmethod
    def _restore(path: str, expect_type: Optional[str], loadUpdater: bool):
        from deeplearning4j_tpu.nn.computation_graph import ComputationGraph
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(path, "r") as z:
            meta = json.loads(z.read("configuration.json"))
            net_type = meta["networkType"]
            if expect_type and net_type != expect_type:
                raise ValueError(f"{path} contains a {net_type}, expected {expect_type}")
            conf_json = json.dumps(meta["configuration"])
            if net_type == "MultiLayerNetwork":
                model = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json)).init()
            else:
                model = ComputationGraph(ComputationGraphConfiguration.from_json(conf_json)).init()
            flat = np.load(io.BytesIO(z.read("coefficients.npy")))
            model.setParams(flat)
            model._iteration = meta.get("iterationCount", 0)
            model._epoch = meta.get("epochCount", 0)
            if loadUpdater and meta.get("saveUpdater") and "updaterState.npz" in z.namelist():
                data = np.load(io.BytesIO(z.read("updaterState.npz")))
                fresh = model._tx.init(model._params)
                leaves, treedef = jax.tree_util.tree_flatten(fresh)
                restored = [np.asarray(data[f"leaf_{i}"], dtype=np.asarray(l).dtype)
                            .reshape(np.shape(l)) for i, l in enumerate(leaves)]
                model._opt_state = jax.tree_util.tree_unflatten(
                    treedef, [jax.numpy.asarray(r) for r in restored])
        return model

    @staticmethod
    def restoreMultiLayerNetwork(path: str, loadUpdater: bool = True):
        """(ref: ModelSerializer.restoreMultiLayerNetwork)."""
        return ModelSerializer._restore(path, "MultiLayerNetwork", loadUpdater)

    @staticmethod
    def restoreComputationGraph(path: str, loadUpdater: bool = True):
        """(ref: ModelSerializer.restoreComputationGraph)."""
        return ModelSerializer._restore(path, "ComputationGraph", loadUpdater)

    @staticmethod
    def restoreModel(path: str, loadUpdater: bool = True):
        """Type-sniffing restore (ref: ModelGuesser.loadModelGuess)."""
        return ModelSerializer._restore(path, None, loadUpdater)
