"""Utilities (ref: org.deeplearning4j.util)."""
from deeplearning4j_tpu.util.model_serializer import ModelSerializer

__all__ = ["ModelSerializer"]
