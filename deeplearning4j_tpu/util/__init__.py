"""Utilities (ref: org.deeplearning4j.util)."""
from deeplearning4j_tpu.util.model_serializer import ModelSerializer
from deeplearning4j_tpu.util import crash_reporting as CrashReportingUtil

__all__ = ["ModelSerializer", "CrashReportingUtil"]
