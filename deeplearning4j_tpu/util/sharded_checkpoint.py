"""Sharded checkpointing + preemption grace for the flagship-scale models
(ref: SURVEY.md §5.4 rebuild mapping — the reference's ModelSerializer zip
handles host-memory models; sharded device state needs per-shard persistence,
which orbax provides: each host writes its addressable shards, restore
re-places them per a target sharding tree).

Components:
- ``ShardedCheckpointManager`` — orbax CheckpointManager wrapper with the
  CheckpointListener-style retention contract (keep-last-k, save-every-N);
  saves {params, opt_state, step} + a JSON metadata sidecar, restores into
  a sharding-annotated abstract tree so arrays land directly on the mesh.
- ``GracefulShutdown`` — SIGTERM/SIGINT grace (ref §5.3 failure-detection
  mapping: preemption -> final checkpoint -> clean exit; TPU pods deliver
  SIGTERM on eviction).
- ``train_with_checkpointing`` — the reference's fit-with-CheckpointListener
  loop for pjit train steps: resume-exact (params AND optimizer state) from
  the latest checkpoint.
"""
from __future__ import annotations

import json
import os
import signal
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np


class GracefulShutdown:
    """SIGTERM/SIGINT -> flag; training loops poll should_stop() and write a
    final checkpoint before exiting. Restores prior handlers on __exit__."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = signals
        self._stop = False
        self._prev: Dict[int, Any] = {}

    def __enter__(self):
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._handler)
        return self

    def _handler(self, signum, frame):
        self._stop = True
        # chain-call the handler we displaced so wrapping an outer
        # GracefulShutdown (or any app-level handler) doesn't silently
        # disable it. SIG_DFL/SIG_IGN aren't callable; the stock
        # default_int_handler is excluded because chaining it would turn a
        # graceful SIGINT into a KeyboardInterrupt mid-checkpoint —
        # exactly what this class exists to prevent.
        prev = self._prev.get(signum)
        if callable(prev) and prev is not signal.default_int_handler:
            prev(signum, frame)

    def should_stop(self) -> bool:
        return self._stop

    def __exit__(self, *exc):
        for s, h in self._prev.items():
            signal.signal(s, h)
        return False


class ShardedCheckpointManager:
    """keep-last-k / save-every-N sharded checkpoints (ref: CheckpointListener
    retention + ModelSerializer, rebuilt over orbax for sharded trees)."""

    def __init__(self, directory: str, keep_last: int = 3,
                 save_interval_steps: int = 1):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep_last or None,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=False))

    def save(self, step: int, params, opt_state, metadata: Optional[dict] = None,
             force: bool = False) -> bool:
        ocp = self._ocp
        state = {"params": params, "opt_state": opt_state}
        if step in self.manager.all_steps():
            return True  # already durable (e.g. preemption save of a step
            # the periodic save just wrote) — idempotent by contract
        saved = self.manager.save(
            step, args=ocp.args.Composite(state=ocp.args.StandardSave(state)),
            force=force)
        if saved and metadata:
            with open(os.path.join(self.directory, str(step), "meta.json"), "w") as f:
                json.dump(metadata, f)
        return saved

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def all_steps(self):
        return sorted(self.manager.all_steps())

    def restore(self, params_like, opt_state_like, step: Optional[int] = None):
        """Restore (params, opt_state, step, metadata). ``*_like`` may be live
        trees OR jax.ShapeDtypeStruct trees with .sharding set — arrays are
        materialized directly onto those shardings (no host round-trip)."""
        ocp = self._ocp
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")

        def abstract(leaf):
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return leaf
            arr = jax.numpy.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
            sharding = getattr(arr, "sharding", None)
            return jax.ShapeDtypeStruct(np.shape(arr), arr.dtype, sharding=sharding)

        target = {"params": jax.tree.map(abstract, params_like),
                  "opt_state": jax.tree.map(abstract, opt_state_like)}
        restored = self.manager.restore(
            step, args=self._ocp.args.Composite(
                state=ocp.args.StandardRestore(target)))["state"]
        meta_path = os.path.join(self.directory, str(step), "meta.json")
        metadata = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                metadata = json.load(f)
        return restored["params"], restored["opt_state"], step, metadata

    def wait(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()


def train_with_checkpointing(
        step_fn: Callable, params, opt_state, batch_fn: Callable[[int], Any],
        num_steps: int, manager: ShardedCheckpointManager,
        start_step: int = 0, shutdown: Optional[GracefulShutdown] = None,
        listeners=()) -> tuple:
    """Run ``step_fn(params, opt_state, batch) -> (params, opt_state, loss)``
    from ``start_step`` to ``num_steps`` with periodic checkpoints (manager's
    save_interval_steps) and preemption grace: on SIGTERM a final checkpoint
    is forced before returning. ``batch_fn(step)`` supplies the batch — keyed
    by step so a resumed run replays the identical schedule (resume-exact).
    Returns (params, opt_state, last_step_completed, losses)."""
    losses = []
    step = start_step

    class _LoopModel:
        """Minimal model facade for TrainingListener consumers (score() +
        _params are what StatsListener/ProfilingListener read)."""
        def score(self):
            return losses[-1] if losses else float("nan")

        @property
        def _params(self):
            return params

        def numParams(self):
            import numpy as _np
            return int(sum(_np.size(l) for l in jax.tree_util.tree_leaves(params)))

    proxy = _LoopModel()
    for step in range(start_step, num_steps):
        batch = batch_fn(step)
        params, opt_state, loss = step_fn(params, opt_state, batch)
        losses.append(float(loss))
        for lst in listeners:
            lst.iterationDone(proxy, step, 0)
        completed = step + 1
        manager.save(completed, params, opt_state,
                     metadata={"step": completed, "loss": float(loss)})
        if shutdown is not None and shutdown.should_stop():
            manager.save(completed, params, opt_state, force=True,
                         metadata={"step": completed, "loss": float(loss),
                                   "preempted": True})
            manager.wait()
            return params, opt_state, completed, losses
    manager.wait()
    return params, opt_state, num_steps, losses
