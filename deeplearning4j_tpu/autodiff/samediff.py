"""SameDiff — the declarative autodiff graph engine (ref:
org.nd4j.autodiff.samediff.SameDiff + SDVariable + internal sessions,
SURVEY.md §1 L3 / §3.2).

Architectural shift vs the reference: dl4j's SameDiff is a **JVM-side op-by-op
interpreter** over an explicit DAG (InferenceSession/TrainingSession dispatch
one JNI call per op per step). Here the same declarative graph API *traces to
a single jaxpr*: ``output()`` and ``fit()`` build a python function that
interprets the DAG symbolically exactly once under ``jax.jit``, so XLA
compiles the WHOLE graph (forward + backward + updater for fit) into one
executable — realizing the native whole-graph execution path the reference
left dormant (libnd4j GraphExecutioner).

Gradients: the reference walks the DAG in reverse topological order calling
each op's hand-written ``doDiff``. Here ``jax.grad`` differentiates the traced
interpretation — no per-op gradient code exists anywhere in this framework.

Op surface: the graph namespaces (sd.math, sd.nn, sd.cnn, sd.rnn, sd.loss,
sd.image, sd.random, sd.bitwise, sd.linalg — ref: generated SDMath/SDNN/...)
read the SAME op-spec registry as the eager namespaces.
"""
from __future__ import annotations

import json
import os
import zipfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.array import NDArray, _unwrap
from deeplearning4j_tpu.ops import registry as _registry
from deeplearning4j_tpu.train import updaters as _upd
from deeplearning4j_tpu.train import regularization as _rega


class VariableType:
    VARIABLE = "VARIABLE"      # trainable
    CONSTANT = "CONSTANT"
    PLACEHOLDER = "PLACEHOLDER"
    ARRAY = "ARRAY"            # op output


@dataclass
class SDVariable:
    """Symbolic variable (ref: org.nd4j.autodiff.samediff.SDVariable)."""
    sd: "SameDiff"
    name: str
    varType: str
    shape: Optional[Tuple] = None
    dtype: Any = None

    # -- fluent math (a subset of SDVariable's surface; all route via registry)
    def _bin(self, other, opname):
        return self.sd._op("math", opname, [self, other])

    def add(self, other):
        return self._bin(other, "add")

    def sub(self, other):
        return self._bin(other, "sub")

    def mul(self, other):
        return self._bin(other, "mul")

    def div(self, other):
        return self._bin(other, "div")

    def rsub(self, other):
        return self.sd._op("math", "sub", [other, self])

    def rdiv(self, other):
        return self.sd._op("math", "div", [other, self])

    def pow(self, other):
        return self._bin(other, "pow")

    def neg(self):
        return self.sd._op("math", "neg", [self])

    __add__ = add
    __radd__ = add
    __sub__ = sub
    __rsub__ = rsub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __rtruediv__ = rdiv
    __pow__ = pow
    __neg__ = neg

    def mmul(self, other):
        return self.sd._op("linalg", "matmul", [self, other])

    __matmul__ = mmul

    def sum(self, *dims, keepdims=False):
        return self.sd._op("reduce", "sum", [self], dims=list(dims) or None, keepdims=keepdims)

    def mean(self, *dims, keepdims=False):
        return self.sd._op("reduce", "mean", [self], dims=list(dims) or None, keepdims=keepdims)

    def max(self, *dims, keepdims=False):
        return self.sd._op("reduce", "max", [self], dims=list(dims) or None, keepdims=keepdims)

    def min(self, *dims, keepdims=False):
        return self.sd._op("reduce", "min", [self], dims=list(dims) or None, keepdims=keepdims)

    def std(self, *dims, biasCorrected=True):
        return self.sd._op("reduce", "std", [self], dims=list(dims) or None,
                           biasCorrected=biasCorrected)

    def argmax(self, dim=None):
        return self.sd._op("reduce", "argmax", [self], dims=dim)

    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.sd._op("shape", "reshape", [self], shape=list(shape))

    def transpose(self, *axes):
        return self.sd._op("shape", "transpose", [self], axes=list(axes) or None)

    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        return self

    # -- evaluation
    def eval(self, placeholders: Optional[dict] = None) -> NDArray:
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def getArr(self) -> Optional[NDArray]:
        v = self.sd._values.get(self.name)
        return NDArray(v) if v is not None else None

    def setArray(self, arr):
        self.sd._values[self.name] = jnp.asarray(_unwrap(arr))

    def gradient(self) -> Optional["SDVariable"]:
        gname = f"grad::{self.name}"
        return self.sd._vars.get(gname)


@dataclass
class SameDiffOp:
    """One graph node (ref: org.nd4j.autodiff.samediff.internal.SameDiffOp)."""
    namespace: str
    opname: str
    inputs: List[str]           # var names (positional)
    outputs: List[str]
    kwargs: dict = field(default_factory=dict)


def _compute_dtype(cfg) -> Optional[Any]:
    """TrainingConfig.computeDtype -> jnp dtype (or None = as-imported)."""
    return {"HALF": jnp.bfloat16, "BFLOAT16": jnp.bfloat16,
            "FLOAT": None, None: None}[
                (cfg.computeDtype or "").upper() or None]


def _cast_fp32_leaves(tree: Dict[str, Any], cdt) -> Dict[str, Any]:
    """Cast float32 leaves to the compute dtype (no-op for cdt None and for
    leaves already cast — the idempotence the frozen pre-cast relies on)."""
    if cdt is None:
        return tree
    return {k: (v.astype(cdt)
                if hasattr(v, "dtype") and v.dtype == jnp.float32 else v)
            for k, v in tree.items()}


@dataclass
class TrainingConfig:
    """(ref: org.nd4j.autodiff.samediff.TrainingConfig).

    ``computeDtype``: mixed-precision training for imported graphs — float32
    leaves (params, constants, float placeholders) are cast to this dtype at
    the top of the traced step, the loss is reduced in float32, and gradients
    land back on the float32 master params through the cast's VJP. "HALF" =
    bfloat16, the TPU-native choice (BASELINE.md config #4: fp32-as-imported
    leaves the MXU at half rate AND doubles the HBM traffic). None = run in
    the imported dtype."""
    updater: _upd.Updater = field(default_factory=lambda: _upd.Adam(1e-3))
    dataSetFeatureMapping: List[str] = field(default_factory=list)
    dataSetLabelMapping: List[str] = field(default_factory=list)
    regularization: List[_rega.Regularization] = field(default_factory=list)
    minimize: bool = True
    # "BFLOAT16" is the canonical value. "HALF" is accepted as a dl4j-config
    # compatibility alias but ALSO maps to bfloat16 (the reference's
    # DataType.HALF means IEEE float16, which the MXU does not natively
    # train in) — a warning flags the numerics difference at the boundary.
    computeDtype: Optional[str] = None  # None | "BFLOAT16"/"HALF" | "FLOAT"

    def __post_init__(self):
        if (self.computeDtype or "").upper() == "HALF":
            import warnings
            warnings.warn(
                "TrainingConfig.computeDtype='HALF' maps to bfloat16 on "
                "TPU (the reference's HALF is IEEE float16; bf16 shares "
                "fp32's exponent range, so checkpoints/losses will differ "
                "from a CUDA fp16 run in the tails). Use 'BFLOAT16' to "
                "state the TPU dtype explicitly.", stacklevel=3)


class GraphNamespace:
    """Graph op surface generated from the registry (ref: generated SDMath etc.)."""

    def __init__(self, sd: "SameDiff", namespace: str):
        self._sd = sd
        self._namespace = namespace

    def __getattr__(self, opname: str):
        if f"{self._namespace}.{opname}" not in _registry.REGISTRY:
            raise AttributeError(f"no op {self._namespace}.{opname}")

        def call(*args, **kwargs):
            name = None
            if args and isinstance(args[0], str) and self._namespace != "shape":
                name, args = args[0], args[1:]
            sym = [a for a in args]
            return self._sd._op(self._namespace, opname, sym, name=name, **kwargs)

        return call


class SameDiff:
    """The graph container (ref: org.nd4j.autodiff.samediff.SameDiff)."""

    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._ops: List[SameDiffOp] = []
        self._values: Dict[str, jax.Array] = {}  # VARIABLE/CONSTANT current values
        self._counter = 0
        self._loss_vars: List[str] = []
        self._training_config: Optional[TrainingConfig] = None
        self._opt_state = None
        self._tx = None
        self._jit_cache: Dict = {}
        self._rng_key = jax.random.key(0)
        self.listeners: List[Any] = []
        # graph namespaces
        self.math = GraphNamespace(self, "math")
        self.nn = GraphNamespace(self, "nn")
        self.cnn = GraphNamespace(self, "cnn")
        self.rnn = GraphNamespace(self, "rnn")
        self.loss = GraphNamespace(self, "loss")
        self.image = GraphNamespace(self, "image")
        self.bitwise = GraphNamespace(self, "bitwise")
        self.linalg = GraphNamespace(self, "linalg")
        self.reduce = GraphNamespace(self, "reduce")
        self.shapes = GraphNamespace(self, "shape")
        self.random = GraphNamespace(self, "random")    # ref: SDRandom
        self.updaters = GraphNamespace(self, "updaters")  # ref: updater ops

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ------------------------------------------------------------- variables
    def _fresh(self, base: str) -> str:
        while True:
            self._counter += 1
            name = f"{base}_{self._counter}"
            if name not in self._vars:
                return name

    def var(self, name: str, shape_or_value=None, dtype=jnp.float32,
            weightInit: Optional[str] = None, seed: int = 0) -> SDVariable:
        """Trainable variable (ref: SameDiff.var). Accepts an initial value or
        a shape (+ optional WeightInit scheme)."""
        if isinstance(shape_or_value, (tuple, list)) and all(
                isinstance(s, int) for s in shape_or_value):
            shape = tuple(shape_or_value)
            if weightInit:
                from deeplearning4j_tpu.nn.conf import weights as _w
                fan_in = shape[0] if len(shape) > 1 else 1
                fan_out = shape[-1]
                value = _w.init(weightInit, jax.random.fold_in(jax.random.key(seed),
                                                               len(self._vars)),
                                shape, fan_in, fan_out, dtype)
            else:
                value = jnp.zeros(shape, dtype)
        else:
            value = jnp.asarray(_unwrap(shape_or_value), dtype=dtype)
        v = SDVariable(self, name, VariableType.VARIABLE, tuple(value.shape), value.dtype)
        self._vars[name] = v
        self._values[name] = value
        return v

    def constant(self, name_or_value, value=None) -> SDVariable:
        if value is None:
            name, value = self._fresh("const"), name_or_value
        else:
            name = name_or_value
        arr = jnp.asarray(_unwrap(value))
        v = SDVariable(self, name, VariableType.CONSTANT, tuple(arr.shape), arr.dtype)
        self._vars[name] = v
        self._values[name] = arr
        return v

    def placeHolder(self, name: str, shape=None, dtype=jnp.float32) -> SDVariable:
        v = SDVariable(self, name, VariableType.PLACEHOLDER,
                       tuple(shape) if shape else None, jnp.dtype(dtype))
        self._vars[name] = v
        return v

    def _rename(self, old: str, new: str):
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        if old in self._values:
            self._values[new] = self._values.pop(old)
        for op in self._ops:
            op.inputs = [new if i == old else i for i in op.inputs]
            op.outputs = [new if o == old else o for o in op.outputs]
        self._loss_vars = [new if l == old else l for l in self._loss_vars]
        self._jit_cache.clear()

    def variables(self) -> List[SDVariable]:
        return list(self._vars.values())

    def getVariable(self, name: str) -> SDVariable:
        return self._vars[name]

    def hasVariable(self, name: str) -> bool:
        return name in self._vars

    # ------------------------------------------------------------------ ops
    def _op(self, namespace: str, opname: str, sym_inputs: Sequence, name=None,
            n_outputs: Optional[int] = None, **kwargs) -> Union[SDVariable, Tuple]:
        """Append a node. Inputs may be SDVariables or literals (literals become
        constants). Output arity is discovered by abstract evaluation."""
        spec = _registry.get(opname, namespace)
        in_names = []
        for a in sym_inputs:
            if isinstance(a, SDVariable):
                in_names.append(a.name)
            elif isinstance(a, (int, float, bool)):
                c = self.constant(self._fresh("lit"), a)
                in_names.append(c.name)
            else:
                c = self.constant(self._fresh("const"), a)
                in_names.append(c.name)

        # abstract-eval to learn output structure/shapes (placeholder None dims -> 2)
        def abstract(n):
            v = self._vars[n]
            if n in self._values:
                return jax.ShapeDtypeStruct(self._values[n].shape, self._values[n].dtype)
            shape = tuple(2 if s is None else s for s in (v.shape or ()))
            return jax.ShapeDtypeStruct(shape, v.dtype or jnp.float32)

        try:
            out_struct = jax.eval_shape(lambda *xs: spec.fn(*xs, **kwargs),
                                        *[abstract(n) for n in in_names])
        except Exception:
            out_struct = None

        multi = isinstance(out_struct, (tuple, list))
        count = len(out_struct) if multi else 1
        base = name or self._fresh(opname)
        out_names = [base] if not multi else [f"{base}#{i}" for i in range(count)]
        self._ops.append(SameDiffOp(namespace, opname, in_names, out_names, dict(kwargs)))
        outs = []
        flat_struct = out_struct if multi else [out_struct]
        for i, on in enumerate(out_names):
            st = flat_struct[i] if flat_struct and flat_struct[i] is not None else None

            def mkvar(on, st):
                shape = tuple(st.shape) if st is not None and hasattr(st, "shape") else None
                dt = st.dtype if st is not None and hasattr(st, "dtype") else None
                return SDVariable(self, on, VariableType.ARRAY, shape, dt)

            if st is not None and isinstance(st, (tuple, list)):
                # nested (e.g. lstmLayer second output (h,c)) — flatten naming
                sub = []
                for j, s in enumerate(st):
                    nm = f"{on}.{j}"
                    v = mkvar(nm, s)
                    self._vars[nm] = v
                    sub.append(v)
                # register a passthrough structural var
                self._vars[on] = SDVariable(self, on, VariableType.ARRAY, None, None)
                outs.append(tuple(sub))
            else:
                v = mkvar(on, st)
                self._vars[on] = v
                outs.append(v)
        self._jit_cache.clear()
        return tuple(outs) if multi else outs[0]

    def convertToVariable(self, var) -> SDVariable:
        """Constant -> trainable VARIABLE in place (ref:
        SameDiff.convertToVariable; used to fine-tune imported frozen graphs
        whose weights arrive as constants)."""
        v = var if isinstance(var, SDVariable) else self._vars[var]
        if v.varType == VariableType.CONSTANT:
            v.varType = VariableType.VARIABLE
            self._jit_cache.clear()
        return v

    def convertAllConstantsToVariables(self, min_size: int = 3) -> int:
        """Make every float constant with ≥ min_size elements trainable —
        the standard prelude to fine-tuning an imported frozen graph (small
        constants are attribute carriers: axes, scales, epsilons). Returns
        the number converted."""
        n = 0
        for v in list(self._vars.values()):
            if v.varType == VariableType.CONSTANT and v.shape \
                    and v.dtype is not None and "float" in str(v.dtype) \
                    and int(np.prod(v.shape)) >= min_size:
                self.convertToVariable(v)
                n += 1
        return n

    def fuseAttention(self) -> int:
        """Collapse imported matmul->[scale]->softmax->matmul attention
        chains onto the kernel-backed ``scaledDotProductAttentionFused``
        op (beyond-parity — see autodiff/rewrites.py for the matched
        pattern and its guarantees). Returns the number of sites fused.
        Typical use, mirroring the reference's fine-tune prelude::

            sd = TensorflowFrameworkImporter.runImport(graph_def)
            sd.convertAllConstantsToVariables()
            sd.fuseAttention()        # optional kernel-fusion pass
        """
        from deeplearning4j_tpu.autodiff.rewrites import fuse_attention
        return fuse_attention(self)

    def convertToConstant(self, var) -> SDVariable:
        """VARIABLE -> frozen constant in place (ref: SameDiff.convertToConstant)."""
        v = var if isinstance(var, SDVariable) else self._vars[var]
        if v.varType == VariableType.VARIABLE:
            v.varType = VariableType.CONSTANT
            self._jit_cache.clear()
        return v

    # ----------------------------------------------------------- control flow
    # The reference interprets Enter/Exit/Merge/Switch/NextIteration nodes in
    # InferenceSession (SURVEY §3.2 — o.n.linalg.api.ops.impl.controlflow).
    # TPU-native equivalent: STRUCTURED control flow — each construct is one
    # graph node holding traced sub-graphs, lowered to lax.cond /
    # lax.while_loop / lax.scan inside the single jitted executable (XLA
    # requires structured control flow; dataflow-style Switch/Merge cannot be
    # expressed under jit).

    def _trace_subgraph(self, fn, arg_vars: Sequence[SDVariable], extra_args: int = 0):
        """Run a SameDiffLambda-style ``fn(sub_sd, *args)`` against a fresh
        sub-SameDiff whose placeholders mirror ``arg_vars`` (+ ``extra_args``
        leading scalar int args, e.g. a loop counter)."""
        sub = SameDiff()
        args = []
        for i in range(extra_args):
            args.append(sub.placeHolder(f"__arg{i}", shape=(), dtype=jnp.int32))
        for i, v in enumerate(arg_vars):
            # unknown dims -> 2, the same convention _op's abstract eval uses
            shape = tuple(2 if s is None else s for s in (v.shape or ()))
            args.append(sub.placeHolder(f"__sgin{len(args)}", shape=shape,
                                        dtype=v.dtype or jnp.float32))
        out = fn(sub, *args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        return sub, [a.name for a in args], [o.name for o in outs]

    def _run_subgraph(self, sub: "SameDiff", in_names, in_vals, out_names):
        env = {**sub._values, **dict(zip(in_names, in_vals))}
        env = sub._interpret(env)
        return [env[n] for n in out_names]

    def _control_op(self, opname: str, input_vars: Sequence[SDVariable],
                    kwargs: dict, name: Optional[str]):
        """Append a control-flow node; output shapes via abstract eval."""
        in_names = [v.name for v in input_vars]
        base = name or self._fresh(opname)

        def absval(v):
            # unknown dims -> 2, matching _op's abstract-eval convention
            shape = tuple(2 if s is None else s for s in (v.shape or ()))
            return jax.ShapeDtypeStruct(shape, v.dtype or jnp.float32)

        node = SameDiffOp("control", opname, in_names, [], kwargs)
        try:
            out_struct = jax.eval_shape(
                lambda *xs: tuple(self._exec_control(node, list(xs))),
                *[absval(v) for v in input_vars])
        except Exception:
            out_struct = None
        # fallback arity: while/for return one value per input; "if" returns
        # one per input minus the predicate
        count = len(out_struct) if out_struct is not None else (
            len(in_names) - 1 if opname == "if" else len(in_names))
        node.outputs = [base] if count == 1 else [f"{base}#{i}" for i in range(count)]
        self._ops.append(node)
        outs = []
        for i, on in enumerate(node.outputs):
            st = out_struct[i] if out_struct is not None else None
            v = SDVariable(self, on, VariableType.ARRAY,
                           tuple(st.shape) if st is not None else None,
                           st.dtype if st is not None else None)
            self._vars[on] = v
            outs.append(v)
        self._jit_cache.clear()
        return outs[0] if len(outs) == 1 else tuple(outs)

    def _exec_control(self, node: SameDiffOp, args: list):
        """Lower one control node onto lax primitives (called while tracing)."""
        kw = node.kwargs
        if node.opname == "if":
            (sub_t, tin, tout) = kw["true_graph"]
            (sub_f, fin, fout) = kw["false_graph"]
            pred, rest = args[0], args[1:]
            return jax.lax.cond(
                jnp.asarray(pred).astype(bool).reshape(()),
                lambda xs: tuple(self._run_subgraph(sub_t, tin, xs, tout)),
                lambda xs: tuple(self._run_subgraph(sub_f, fin, xs, fout)),
                tuple(rest))
        if node.opname == "while":
            (sub_c, cin, cout) = kw["cond_graph"]
            (sub_b, bin_, bout) = kw["body_graph"]
            state = tuple(jnp.asarray(a) for a in args)
            # loop vars keep their initial dtypes (TF while-loop semantics;
            # also guards against literal-promotion drift after serde)
            dts = [s.dtype for s in state]

            def body(s):
                new = self._run_subgraph(sub_b, bin_, list(s), bout)
                return tuple(jnp.asarray(n).astype(d) for n, d in zip(new, dts))

            return tuple(jax.lax.while_loop(
                lambda s: jnp.asarray(self._run_subgraph(sub_c, cin, list(s), cout)[0])
                .astype(bool).reshape(()),
                body, state))
        if node.opname == "for":
            (sub_b, bin_, bout) = kw["body_graph"]
            n_iter = kw["n_iter"]
            state0 = tuple(jnp.asarray(a) for a in args)
            dts = [s.dtype for s in state0]

            def body(state, i):
                new = self._run_subgraph(sub_b, bin_, [i, *state], bout)
                return tuple(jnp.asarray(n).astype(d)
                             for n, d in zip(new, dts)), None

            out, _ = jax.lax.scan(body, state0, jnp.arange(n_iter))
            return out
        raise ValueError(f"unknown control op {node.opname}")

    def ifCond(self, cond, trueBody, falseBody, inputs=(), name: Optional[str] = None):
        """Conditional (ref: SameDiff.ifCond — Switch/Merge in the reference;
        lax.cond here, differentiable). ``cond`` is a scalar-bool SDVariable in
        THIS graph; trueBody/falseBody are ``fn(sub_sd, *inputs)`` lambdas
        (ref: SameDiffLambda.define) returning one or more sub-graph vars."""
        inputs = list(inputs)
        tg = self._trace_subgraph(trueBody, inputs)
        fg = self._trace_subgraph(falseBody, inputs)
        assert len(tg[2]) == len(fg[2]), "branches must return the same arity"
        return self._control_op("if", [cond, *inputs],
                                {"true_graph": tg, "false_graph": fg}, name)

    def whileLoop(self, loopVars, condBody, loopBody, name: Optional[str] = None):
        """While loop (ref: SameDiff.whileLoop — Enter/Exit/NextIteration in
        the reference; lax.while_loop here). ``condBody(sub_sd, *state)`` must
        return a scalar bool; ``loopBody(sub_sd, *state)`` returns the next
        state (same arity/shapes). NOTE: like XLA, reverse-mode gradients do
        not flow through a general while loop — use forLoop for trainable
        iteration."""
        loopVars = list(loopVars)
        cg = self._trace_subgraph(condBody, loopVars)
        bg = self._trace_subgraph(loopBody, loopVars)
        assert len(bg[2]) == len(loopVars), "body must return one var per loop var"
        return self._control_op("while", loopVars,
                                {"cond_graph": cg, "body_graph": bg}, name)

    def forLoop(self, n_iter: int, loopVars, loopBody, name: Optional[str] = None):
        """Fixed-trip-count loop lowered to lax.scan — differentiable, the
        TPU-idiomatic replacement for trainable while loops.
        ``loopBody(sub_sd, i, *state)`` returns the next state."""
        loopVars = list(loopVars)
        bg = self._trace_subgraph(loopBody, loopVars, extra_args=1)
        assert len(bg[2]) == len(loopVars), "body must return one var per loop var"
        return self._control_op("for", loopVars,
                                {"body_graph": bg, "n_iter": int(n_iter)}, name)

    # ------------------------------------------------------------- execution
    def _needed_ops(self, output_names) -> List[SameDiffOp]:
        """Ancestor-subgraph pruning (ref: AbstractSession executes only ops
        required for the requested variables)."""
        needed = set()
        for n in output_names:
            needed.add(n.split(".")[0] if "." in n else n)
        keep = []
        for node in reversed(self._ops):
            if any(o in needed for o in node.outputs):
                keep.append(node)
                needed.update(node.inputs)
        return list(reversed(keep))

    def _interpret(self, values: Dict[str, Any], only_ops: Optional[List[SameDiffOp]] = None
                   ) -> Dict[str, Any]:
        """Topologically interpret the DAG over concrete/traced values. Runs
        under jit — each registry fn call traces into the single jaxpr."""
        env = dict(values)
        for node in (only_ops if only_ops is not None else self._ops):
            args = [env[i] for i in node.inputs]
            if node.namespace == "control":
                out = self._exec_control(node, args)
                if len(node.outputs) == 1:
                    out = out[0]
            else:
                spec = _registry.get(node.opname, node.namespace)
                out = spec.fn(*args, **node.kwargs)
            if len(node.outputs) == 1 and not isinstance(out, (tuple, list)):
                env[node.outputs[0]] = out
            else:
                for on, o in zip(node.outputs, out):
                    if isinstance(o, (tuple, list)):
                        for j, oo in enumerate(o):
                            env[f"{on}.{j}"] = oo
                        env[on] = o
                    else:
                        env[on] = o
        return env

    def _exec_fn(self, output_names: Tuple[str, ...]):
        """Build + cache the jitted whole-graph executor for given outputs."""
        key = ("exec", output_names)
        if key not in self._jit_cache:
            ops = self._needed_ops(output_names)

            def fn(var_values, placeholder_values):
                env = {**var_values, **placeholder_values}
                env = self._interpret(env, only_ops=ops)
                return {n: env[n] for n in output_names}

            self._jit_cache[key] = jax.jit(fn)
        return self._jit_cache[key]

    def output(self, placeholders: Dict[str, Any], outputs: Union[str, Sequence[str]]
               ) -> Dict[str, NDArray]:
        """Whole-graph compiled inference (ref: SameDiff.output/batchOutput)."""
        if isinstance(outputs, str):
            outputs = [outputs]
        removed = getattr(self, "_removed_by_rewrite", None)
        if removed:
            for n in outputs:
                base = n.split(".")[0] if "." in n else n
                if base in removed:
                    raise ValueError(
                        f"variable '{n}' was an attention-chain intermediate "
                        f"removed by the {removed[base]} graph rewrite and "
                        f"can no longer be computed; request it before "
                        f"fusing, or skip the rewrite to keep it")
        ph = {k: jnp.asarray(_unwrap(v)) for k, v in placeholders.items()}
        fn = self._exec_fn(tuple(outputs))
        out = fn(self._values, ph)
        return {k: NDArray(v) for k, v in out.items()}

    def batchOutput(self):
        return _BatchOutputBuilder(self)

    def evaluate(self, iterator, outputVariable: str, evaluation=None):
        """Evaluate a dataset against one output variable (ref:
        SameDiff.evaluate(DataSetIterator, String, IEvaluation...)).
        Placeholder names come from the TrainingConfig's feature/label
        mappings; labels feed the evaluation, not the graph."""
        from deeplearning4j_tpu.eval import Evaluation
        cfg = self._training_config
        assert cfg is not None and cfg.dataSetFeatureMapping, \
            "setTrainingConfig with dataSetFeatureMapping first"
        ev = evaluation if evaluation is not None else Evaluation()
        if hasattr(iterator, "reset"):
            iterator.reset()
        for ds in iterator:
            feats = ds.features if isinstance(ds.features, (list, tuple)) \
                else [ds.features]
            ph = {n: f for n, f in zip(cfg.dataSetFeatureMapping, feats)}
            out = self.output(ph, outputVariable)[outputVariable]
            ev.eval(ds.labels, out.toNumpy(),
                    mask=getattr(ds, "labels_mask", None))
        return ev

    # ------------------------------------------------------------- training
    def setLossVariables(self, *names):
        self._loss_vars = [n.name if isinstance(n, SDVariable) else n for n in names]
        self._jit_cache.clear()

    def getLossVariables(self):
        return list(self._loss_vars)

    def setTrainingConfig(self, cfg: TrainingConfig):
        self._training_config = cfg
        self._tx = cfg.updater.to_optax()
        self._opt_state = None
        self._jit_cache.clear()

    def _trainable_names(self) -> List[str]:
        return [n for n, v in self._vars.items() if v.varType == VariableType.VARIABLE]

    def _train_step_fn(self):
        key = "train_step"
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(self._train_step_inner(),
                                           donate_argnums=(0, 2))
        return self._jit_cache[key]

    # steps fused into one executable by fit()'s multi-step path — same
    # de-dispatch rationale as MultiLayerNetwork.fuseSteps (the axon
    # tunnel's per-dispatch latency dominates small whole-graph steps:
    # config #4 measured ~110 ms/step wall for ~30 ms of compute)
    fuseSteps: int = 8
    # how many fused chunks score-only listener callbacks may lag the
    # dispatch head before a forced batched replay (staleness bound; the
    # replay itself is one bulk device->host transfer — see _ReplayQueue).
    # 0 = replay right after each chunk (live streaming, pays one host
    # round trip per chunk — on tunneled/remote devices that round trip is
    # ~100x the per-chunk compute at small step sizes)
    listenerReplayLag: int = 16

    def _train_multi_fn(self):
        key = "train_multi"
        if key not in self._jit_cache:
            step_inner = self._train_step_inner()

            def multi(trainables, opt_state, frozen, ph_stacked):
                def body(carry, ph):
                    tr, opt = carry
                    tr, opt, loss = step_inner(tr, frozen, opt, ph)
                    return (tr, opt), loss

                (trainables, opt_state), losses = jax.lax.scan(
                    body, (trainables, opt_state), ph_stacked)
                return trainables, opt_state, losses

            self._jit_cache[key] = jax.jit(multi, donate_argnums=(0, 1))
        return self._jit_cache[key]

    def _train_step_inner(self):
        """The un-jitted single training step (fwd+bwd+update) shared by the
        per-step executable and the fused lax.scan."""
        key = "train_step_inner"
        if key not in self._jit_cache:
            t_names = tuple(self._trainable_names())
            loss_names = tuple(self._loss_vars)
            cfg = self._training_config
            ops = self._needed_ops(loss_names)
            cdt = _compute_dtype(cfg)

            def cast_tree(tree):
                return _cast_fp32_leaves(tree, cdt)

            def loss_fn(trainables, frozen, placeholders):
                env = {**cast_tree(frozen), **cast_tree(trainables),
                       **cast_tree(placeholders)}
                env = self._interpret(env, only_ops=ops)
                loss = sum(jnp.sum(env[l].astype(jnp.float32))
                           for l in loss_names)
                for reg in cfg.regularization:
                    for n in t_names:
                        loss = loss + reg.penalty(trainables[n])
                return loss if cfg.minimize else -loss

            def step(trainables, frozen, opt_state, placeholders):
                loss, grads = jax.value_and_grad(loss_fn)(
                    trainables, frozen, placeholders)
                updates, opt_state = self._tx.update(grads, opt_state,
                                                     trainables)
                trainables = jax.tree_util.tree_map(
                    lambda p, u: p + u, trainables, updates)
                return trainables, opt_state, loss

            self._jit_cache[key] = step
        return self._jit_cache[key]

    def fit(self, data, epochs: int = 1):
        """Train (ref: SameDiff.fit(MultiDataSetIterator)): one jitted step =
        full fwd + bwd + updater. ``data`` is a DataSetIterator/DataSet or a
        dict of placeholder arrays per batch."""
        from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator, ListDataSetIterator
        cfg = self._training_config
        assert cfg is not None, "call setTrainingConfig first"
        assert self._loss_vars, "call setLossVariables first"
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        elif isinstance(data, dict):
            data = [data]  # one batch of explicit placeholder arrays

        t_names = self._trainable_names()
        trainables = {n: self._values[n] for n in t_names}
        frozen = {n: v for n, v in self._values.items() if n not in trainables}
        # Cast frozen fp32 leaves ONCE per fit call (constants, imported
        # frozen weights): the in-step cast then no-ops on them —
        # frozen-weight HBM reads happen at bf16 width every step instead
        # of fp32-read-plus-cast. Trainables keep fp32 masters (cast
        # inside the step so gradients land on the masters).
        frozen = _cast_fp32_leaves(frozen, _compute_dtype(cfg))
        if self._opt_state is None:
            self._opt_state = self._tx.init(trainables)
        step = self._train_step_fn()
        history = []
        # De-dispatch: steps buffer into fuseSteps-sized lax.scan chunks —
        # one tunnel dispatch each (see fuseSteps). Listeners no longer
        # disable fusing (round-5, mirroring MultiLayerNetwork): chunks are
        # cut at iterations where a listener needs the LIVE model
        # (requiresModelAtIteration), and buffered per-step losses are
        # replayed to listeners after each chunk — identical callback
        # sequence to the per-step path.
        fuse_k = max(self.fuseSteps, 0)
        buf: list = []  # host placeholder dicts of identical shapes

        def ph_host(ds):
            if isinstance(ds, dict):
                return {k: _unwrap(v) for k, v in ds.items()}
            ph = {}
            feats = ds.features if isinstance(ds.features, (list, tuple)) else [ds.features]
            labs = ds.labels if isinstance(ds.labels, (list, tuple)) else [ds.labels]
            for nm, arr in zip(cfg.dataSetFeatureMapping, feats):
                ph[nm] = _unwrap(arr)
            for nm, arr in zip(cfg.dataSetLabelMapping, labs):
                ph[nm] = _unwrap(arr)
            return ph

        def _sig(ph):
            # dtype is part of the signature: same-shaped batches of
            # different dtypes must not np.stack into one chunk (the
            # promotion would silently train on different numerics than
            # the per-step path — round-4 advisor finding). result_type
            # reads the dtype without forcing a device->host transfer.
            return tuple(sorted((k, np.shape(v), str(jnp.result_type(v)))
                                for k, v in ph.items()))

        # Lagged, batched listener replay — the SHARED queue (see
        # nn.multilayer._ReplayQueue): with listeners, drained chunks'
        # losses move device->host in ONE batched transfer (under the axon
        # tunnel any host read costs a full round trip regardless of
        # readiness; per-chunk syncing erased the fusing win, measured
        # 148k -> 101k tok/s on bench config #4). Score-only listeners get
        # their callbacks LATE — batched at fit end / every
        # listenerReplayLag chunks — but in exact order with exact scores;
        # listeners that need the live model flush synchronously at their
        # declared boundaries (rq.push).
        from deeplearning4j_tpu.nn.multilayer import _ReplayQueue, _chunk_limit

        def _replay(losses, k):
            for j in range(k):
                history.append(losses[j])
                self._score = losses[j]
                for lst in self.listeners:
                    lst.iterationDone(self, len(history), 0)

        rq = _ReplayQueue(self, replay=_replay)
        rq.dispatched = 0   # iteration numbers are per-fit (len(history))

        def run_single(ph):
            nonlocal trainables
            rq.drain()   # keep callback order: chunks before this step
            phj = {k: jnp.asarray(v) for k, v in ph.items()}
            trainables, self._opt_state, loss = step(trainables, frozen,
                                                     self._opt_state, phj)
            rq.dispatched += 1
            history.append(loss)   # device scalar; bulk-synced below
            self._score = loss
            # listeners read current values (StatsListener param stats)
            self._values.update(trainables)
            for lst in self.listeners:
                lst.iterationDone(self, len(history), 0)

        def flush(buf):
            nonlocal trainables
            while buf:
                k = _chunk_limit(self.listeners, rq.dispatched, fuse_k)
                if k <= 1:
                    # a listener needs the live model at the very next
                    # iteration: run it as a single (exact semantics)
                    run_single(buf[0])
                    buf = buf[1:]
                    continue
                if len(buf) < k:
                    break
                chunk, buf = buf[:k], buf[k:]
                stacked = {key: jnp.asarray(np.stack([c[key] for c in chunk]))
                           for key in chunk[0]}
                multi = self._train_multi_fn()
                trainables, self._opt_state, losses = multi(
                    trainables, self._opt_state, frozen, stacked)
                # rebind after every chunk: the jit donated the previous
                # buffers, and self._values must never dangle on deleted
                # arrays if a later batch raises mid-fit. rq.push replays
                # synchronously when a boundary listener needs the model
                # as of this chunk end, lagged+batched otherwise.
                self._values.update(trainables)
                rq.push(losses, k)
            return buf

        try:
            for _ in range(epochs):
                for ds in data:
                    ph = ph_host(ds)
                    if fuse_k > 1:
                        if buf and _sig(buf[0]) != _sig(ph):
                            for b in buf:   # shape change: drain as singles
                                run_single(b)
                            buf = []
                        buf.append(ph)
                        buf = flush(buf)
                    else:
                        run_single(ph)
            for b in buf:   # leftover (< fuseSteps) steps run individually
                run_single(b)
            rq.drain()
        except BaseException:
            # an exception mid-fit must not lose the callbacks/scores of
            # chunks that DID complete (pending holds completed chunks
            # only); never mask the original error with a replay failure
            try:
                rq.drain()
            except Exception:
                pass
            raise
        self._values.update(trainables)
        if history:
            # ONE bulk device->host transfer for whatever is still on
            # device. Replayed entries are already host floats (listener
            # path) — re-stacking those onto the device just to read them
            # back would cost a second tunnel round trip.
            dev = [(i, h) for i, h in enumerate(history)
                   if not isinstance(h, float)]
            if dev:
                vals = np.asarray(jnp.stack([h for _, h in dev])).astype(float)
                for (i, _), v in zip(dev, vals):
                    history[i] = float(v)
            history = [float(h) for h in history]
        return history

    def score(self) -> float:
        """Last training loss (ref: the reference's SameDiff training score
        surfaces through History/listeners; models expose score() here)."""
        return float(getattr(self, "_score", float("nan")))

    def numParams(self) -> int:
        import numpy as _np
        return int(sum(_np.size(self._values[n])
                       for n in self._trainable_names()))

    def calculateGradients(self, placeholders: Dict[str, Any], wrt: Sequence[str]
                           ) -> Dict[str, NDArray]:
        """Explicit gradient computation (ref: SameDiff.calculateGradients).
        Also materializes grad::<name> variables (ref: SDVariable.gradient())."""
        assert self._loss_vars, "setLossVariables first"
        loss_names = tuple(self._loss_vars)
        wrt = [w.name if isinstance(w, SDVariable) else w for w in wrt]

        ops = self._needed_ops(loss_names)

        def loss_fn(sel, rest, ph):
            env = {**rest, **sel, **ph}
            env = self._interpret(env, only_ops=ops)
            return sum(jnp.sum(env[l]) for l in loss_names)

        sel = {n: self._values[n] for n in wrt}
        rest = {n: v for n, v in self._values.items() if n not in sel}
        ph = {k: jnp.asarray(_unwrap(v)) for k, v in placeholders.items()}
        grads = jax.jit(jax.grad(loss_fn))(sel, rest, ph)
        out = {}
        for n, g in grads.items():
            gname = f"grad::{n}"
            self._vars[gname] = SDVariable(self, gname, VariableType.ARRAY,
                                           tuple(g.shape), g.dtype)
            self._values[gname] = g
            out[n] = NDArray(g)
        return out

    # ------------------------------------------------------------ persistence
    def save(self, path: str, save_updater_state: bool = False):
        """Zip: graph.json + weights .npy blobs (ref: SameDiff.save — the
        reference uses FlatBuffers; JSON+npz is this framework's container,
        with the same contract: graph + weights + optional updater state)."""
        graph = {
            "vars": [{"name": v.name, "type": v.varType,
                      "shape": list(v.shape) if v.shape else None,
                      "dtype": str(v.dtype) if v.dtype is not None else None}
                     for v in self._vars.values() if "." not in v.name],
            "ops": [_op_to_dict(o) for o in self._ops],
            "loss": self._loss_vars,
        }
        removed = getattr(self, "_removed_by_rewrite", None)
        if removed:
            # keep the targeted removed-by-rewrite error working across a
            # save/load roundtrip (else it degrades back to a deep KeyError)
            graph["removed_by_rewrite"] = removed
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("graph.json", json.dumps(graph, indent=2))
            manifest = []
            for n, val in self._values.items():
                if self._vars[n].varType in (VariableType.VARIABLE, VariableType.CONSTANT):
                    import io
                    buf = io.BytesIO()
                    np.save(buf, np.asarray(val))
                    zf.writestr(f"values/{n}.npy", buf.getvalue())
                    manifest.append({"name": n, "type": self._vars[n].varType})
            zf.writestr("values.json", json.dumps(manifest))
            if save_updater_state and self._training_config is not None:
                from deeplearning4j_tpu.train import updaters as _updz
                cfg = self._training_config
                zf.writestr("training.json", json.dumps({
                    "updater": cfg.updater.to_dict(),
                    "dataSetFeatureMapping": cfg.dataSetFeatureMapping,
                    "dataSetLabelMapping": cfg.dataSetLabelMapping,
                    "minimize": cfg.minimize,
                    "computeDtype": cfg.computeDtype,
                    "hasOptState": self._opt_state is not None,
                }))
                if self._opt_state is not None:
                    import io
                    leaves = jax.tree_util.tree_leaves(self._opt_state)
                    for i, leaf in enumerate(leaves):
                        buf = io.BytesIO()
                        np.save(buf, np.asarray(leaf))
                        zf.writestr(f"updaterState/{i}.npy", buf.getvalue())

    @staticmethod
    def load(path: str) -> "SameDiff":
        sd = SameDiff()
        with zipfile.ZipFile(path) as zf:
            graph = json.loads(zf.read("graph.json"))
            manifest = json.loads(zf.read("values.json"))
            values = {}
            for m in manifest:
                import io
                values[m["name"]] = (m["type"], np.load(io.BytesIO(zf.read(f"values/{m['name']}.npy"))))
        for vd in graph["vars"]:
            name = vd["name"]
            if name in values:
                vtype, arr = values[name]
                if vtype == VariableType.VARIABLE:
                    sd.var(name, arr, dtype=arr.dtype)
                else:
                    sd.constant(name, arr)
            elif vd["type"] == VariableType.PLACEHOLDER:
                sd.placeHolder(name, shape=vd["shape"],
                               dtype=vd["dtype"] or jnp.float32)
            else:
                sd._vars[name] = SDVariable(sd, name, vd["type"],
                                            tuple(vd["shape"]) if vd["shape"] else None,
                                            vd["dtype"])
        for od in graph["ops"]:
            sd._ops.append(_op_from_dict(od))
            for on in od["outputs"]:
                if on not in sd._vars:
                    sd._vars[on] = SDVariable(sd, on, VariableType.ARRAY)
        sd._loss_vars = graph.get("loss", [])
        if graph.get("removed_by_rewrite"):
            sd._removed_by_rewrite = dict(graph["removed_by_rewrite"])

        # updater state: rebuild the optax tree structurally (tx.init on the
        # restored trainables) and refill its leaves in flatten order — the
        # exact-resume contract (ref: SameDiff FlatBuffers updaterState)
        with zipfile.ZipFile(path) as zf:
            names = set(zf.namelist())
            if "training.json" in names:
                import io
                from deeplearning4j_tpu.train import updaters as _updz
                tj = json.loads(zf.read("training.json"))
                sd.setTrainingConfig(TrainingConfig(
                    updater=_updz.from_dict(tj["updater"]),
                    dataSetFeatureMapping=tj.get("dataSetFeatureMapping", []),
                    dataSetLabelMapping=tj.get("dataSetLabelMapping", []),
                    minimize=tj.get("minimize", True),
                    computeDtype=tj.get("computeDtype")))
                if tj.get("hasOptState"):
                    trainables = {n: sd._values[n] for n in sd._trainable_names()}
                    skeleton = sd._tx.init(trainables)
                    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
                    loaded = []
                    for i, ref in enumerate(leaves):
                        arr = np.load(io.BytesIO(zf.read(f"updaterState/{i}.npy")))
                        loaded.append(jnp.asarray(arr, dtype=ref.dtype)
                                      if hasattr(ref, "dtype") else arr)
                    sd._opt_state = jax.tree_util.tree_unflatten(treedef, loaded)
        return sd

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} variables, {len(self._ops)} ops"]
        for o in self._ops:
            lines.append(f"  {','.join(o.outputs)} = {o.namespace}.{o.opname}({', '.join(o.inputs)})")
        return "\n".join(lines)


def _enc_kw_val(v):
    """JSON-encode one kwarg value. Python slice objects (stridedSlice's
    'slices' tuple — what TF's mask[:, newaxis, newaxis, :] imports to)
    get a tagged form so load() restores REAL slices, not their repr."""
    if isinstance(v, slice):
        return {"__slice__": [v.start, v.stop, v.step]}
    if isinstance(v, (list, tuple)):
        return [_enc_kw_val(x) for x in v]
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)


def _dec_kw_val(v):
    if isinstance(v, dict) and "__slice__" in v:
        s = v["__slice__"]
        return slice(s[0], s[1], s[2])
    if isinstance(v, list):
        return [_dec_kw_val(x) for x in v]
    return v


def _json_safe(d):
    return {k: _enc_kw_val(v) for k, v in d.items()}


_SUBGRAPH_KEYS = ("true_graph", "false_graph", "cond_graph", "body_graph")


def _op_to_dict(o: SameDiffOp) -> dict:
    """Serialize one node; control nodes recurse into their sub-graphs."""
    kw = dict(o.kwargs)
    if o.namespace == "control":
        # non-subgraph kwargs go through the SAME tagged encoder as every
        # other op so slice-valued kwargs round-trip serde uniformly
        # (previously they fell through as raw repr strings)
        for k, v in kw.items():
            if k in _SUBGRAPH_KEYS:
                sub, ins, outs = v
                kw[k] = {"__subgraph__": _subgraph_to_dict(sub),
                         "in": ins, "out": outs}
            else:
                kw[k] = _enc_kw_val(v)
    else:
        kw = _json_safe(kw)
    return {"namespace": o.namespace, "op": o.opname, "inputs": o.inputs,
            "outputs": o.outputs, "kwargs": kw}


def _op_from_dict(od: dict) -> SameDiffOp:
    kw = dict(od["kwargs"])
    if od["namespace"] == "control":
        for k, v in kw.items():
            if k in _SUBGRAPH_KEYS:
                kw[k] = (_subgraph_from_dict(v["__subgraph__"]), v["in"], v["out"])
            else:
                kw[k] = _dec_kw_val(v)
    else:
        kw = {k: _dec_kw_val(v) for k, v in kw.items()}
    return SameDiffOp(od["namespace"], od["op"], od["inputs"], od["outputs"], kw)


def _subgraph_to_dict(sd: "SameDiff") -> dict:
    """Control sub-graphs carry their constants inline (they are small —
    literals and shape params; top-level weights stay in npy blobs)."""
    return {
        "vars": [{"name": v.name, "type": v.varType,
                  "shape": list(v.shape) if v.shape else None,
                  "dtype": str(v.dtype) if v.dtype is not None else None}
                 for v in sd._vars.values() if "." not in v.name],
        "ops": [_op_to_dict(o) for o in sd._ops],
        "values": {n: {"data": np.asarray(v).tolist(), "dtype": str(v.dtype)}
                   for n, v in sd._values.items()},
    }


def _subgraph_from_dict(d: dict) -> "SameDiff":
    sub = SameDiff()
    for vd in d["vars"]:
        sub._vars[vd["name"]] = SDVariable(
            sub, vd["name"], vd["type"],
            tuple(vd["shape"]) if vd["shape"] else None, vd["dtype"])
    for n, spec in d["values"].items():
        sub._values[n] = jnp.asarray(np.asarray(spec["data"], dtype=spec["dtype"]))
    for od in d["ops"]:
        sub._ops.append(_op_from_dict(od))
        for on in od["outputs"]:
            if on not in sub._vars:
                sub._vars[on] = SDVariable(sub, on, VariableType.ARRAY)
    return sub


class _BatchOutputBuilder:
    """(ref: SameDiff.batchOutput fluent API)."""

    def __init__(self, sd: SameDiff):
        self._sd = sd
        self._ph = {}
        self._outputs = []

    def input(self, name, arr):
        self._ph[name] = arr
        return self

    def output(self, *names):
        self._outputs.extend(n.name if isinstance(n, SDVariable) else n for n in names)
        return self

    def execSingle(self) -> NDArray:
        return self._sd.output(self._ph, self._outputs)[self._outputs[0]]

    def exec(self) -> Dict[str, NDArray]:
        return self._sd.output(self._ph, self._outputs)
