"""Declarative autodiff graph engine (ref: org.nd4j.autodiff.samediff)."""
from deeplearning4j_tpu.autodiff.samediff import (  # noqa: F401
    SameDiff, SDVariable, SameDiffOp, TrainingConfig, VariableType,
)
