"""Graph-level kernel-fusion rewrites for SameDiff (beyond-parity).

The reference executes imported graphs node by node (SURVEY §3.3:
``TrainingSession`` op-at-a-time); this rebuild already compiles the whole
graph into one XLA program, but XLA still materializes the (B, H, T, T)
attention score tensor between the four matmul/scale/softmax/matmul nodes
an importer emits. ``fuse_attention`` pattern-matches that chain and
collapses it onto the ``scaledDotProductAttentionFused`` registry op, whose
TPU path is the whole-head VMEM Pallas kernel — the same lever that moved
the hand-written flagship (BASELINE.md round 4), applied to IMPORTED
graphs (BASELINE config #4).

Matched shape (what the TF importer emits for BERT-style attention,
verified against tools/tf_bert.py's frozen graph):

    q ----------------------------\
    k -> permute(0,1,3,2) -> matmul -> [mul(scalar)] -> [add(mask)] -> softmax -> matmul -> out
    v ---------------------------------------------------------------------------^

Intermediates must be single-consumer and not loss variables (a
later ``sd.output(...)`` request for a fused-away intermediate will
fail — intermediates are implementation detail, same as under plain
jit fusion); the optional ``mul`` must be by a scalar constant (the
1/sqrt(D) scale — trainable scalar scales are left unfused). The
optional ``add`` is the BERT-import additive padding mask: it becomes
the fused op's ``mask`` input (still a graph variable — masks are
usually placeholder-derived, so they must stay dynamic), which pins
the einsum path (kernels are causal/none only).
"""
from __future__ import annotations

from collections import defaultdict
from typing import Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiffOp, VariableType


def _scalar_const(sd, name) -> Optional[float]:
    """The float value of a size-1 CONSTANT, else None. Trainable scalars
    (varType VARIABLE) are rejected: baking their current value into the
    fused op's static kwargs would silently freeze a learnable scale."""
    try:
        v = sd.getVariable(name)
        if v.varType != VariableType.CONSTANT:
            return None
        arr = v.getArr()
    except Exception:
        return None
    if arr is None:
        return None
    a = np.asarray(arr.toNumpy() if hasattr(arr, "toNumpy") else arr)
    if a.size != 1:
        return None
    return float(a.reshape(()))


def fuse_attention(sd) -> int:
    """Collapse matmul->[scale]->softmax->matmul chains onto
    ``scaledDotProductAttentionFused``. Returns the number of sites fused.
    Output names are preserved, so downstream nodes and graph outputs are
    untouched; numerics are identical on the einsum path and within kernel
    tolerance (~1e-6 fp32 / bf16-rounding) on TPU."""
    ops = sd._ops
    producer = {}
    consumers = defaultdict(list)
    for i, node in enumerate(ops):
        for out in node.outputs:
            producer[out] = i
        for inp in node.inputs:
            consumers[inp].append(i)

    def prod(name):
        i = producer.get(name)
        return (i, ops[i]) if i is not None else (None, None)

    loss_vars = set(getattr(sd, "_loss_vars", []))

    def single_internal(name):
        """name has exactly one op consumer and is not a loss variable
        (fusing away a loss var's producer would break fit())."""
        return len(consumers.get(name, [])) == 1 and name not in loss_vars

    to_remove = set()
    replacements = {}
    fused = 0
    for i, node in enumerate(ops):
        if (node.namespace, node.opname) != ("nn", "softmax"):
            continue
        if node.kwargs.get("dim", -1) not in (-1,):
            continue
        # upward: [add(mask)] <- [mul(scale)] <- matmul(q, permute(k))

        def match_score_chain(name):
            """name -> (mm_i, mm, mul_i, scale) when it is produced by
            matmul or mul(scalar-const)<-matmul, else None."""
            ci, cop = prod(name)
            if cop is None:
                return None
            if (cop.namespace, cop.opname) == ("math", "mul"):
                a, b = cop.inputs
                mm_i, mm = prod(a)
                scale_name = b
                if mm is None or mm.opname != "matmul":
                    mm_i, mm = prod(b)
                    scale_name = a
                if mm is None or mm.opname != "matmul":
                    return None
                sc = _scalar_const(sd, scale_name)
                if sc is None:
                    return None
                return mm_i, mm, ci, sc
            if cop.opname == "matmul":
                return ci, cop, None, 1.0
            return None

        add_i = None
        mask_name = None
        chain = match_score_chain(node.inputs[0])
        if chain is None:
            up_i, up = prod(node.inputs[0])
            if up is None or (up.namespace, up.opname) != ("math", "add"):
                continue
            # additive mask: try BOTH orientations fully — the mask side
            # may itself be mul-produced (e.g. (1-m) * -1e4), so "has a
            # mul producer" does not identify the score side; only a
            # complete chain match does
            a, b = up.inputs
            for cand, other in ((a, b), (b, a)):
                chain = match_score_chain(cand)
                if chain is not None and single_internal(cand):
                    add_i, mask_name = up_i, other
                    break
            if chain is None or add_i is None:
                continue
        mm_i, mm, mul_i, scale = chain
        q_name, kt_name = mm.inputs
        kt_i, kt = prod(kt_name)
        if kt is None or kt.opname != "permute" \
                or tuple(kt.kwargs.get("axes", ())) != (0, 1, 3, 2):
            continue
        k_name = kt.inputs[0]
        # downward: softmax -> matmul(p, v)
        p_name = node.outputs[0]
        cons = consumers.get(p_name, [])
        if len(cons) != 1:
            continue
        pv_i = cons[0]
        pv = ops[pv_i]
        if pv.opname != "matmul" or pv.inputs[0] != p_name:
            continue
        v_name = pv.inputs[1]
        # all pattern intermediates single-consumer (and the kT permute
        # removable only if nothing else reads it)
        mids = [mm.outputs[0], p_name] \
            + ([ops[mul_i].outputs[0]] if mul_i is not None else []) \
            + ([ops[add_i].outputs[0]] if add_i is not None else [])
        if not all(single_internal(m) for m in mids):
            continue
        # shapes: split-head rank-4 with consistent (T, D) trailing dims.
        # Leading dims may differ (or be dynamic-dim sentinels in the
        # recorded metadata): the fused op's einsum path uses broadcasting
        # jnp.matmul with EXACTLY the original chain's semantics, and its
        # kernel gate re-checks true traced shapes at execution time
        q_v, k_v, v_v = (sd.getVariable(n) for n in (q_name, k_name, v_name))
        shapes = [getattr(x, "shape", None) for x in (q_v, k_v, v_v)]
        if any(s is None or len(s) != 4 for s in shapes):
            continue
        if not (shapes[0][2:] == shapes[1][2:] == shapes[2][2:]):
            continue
        inputs = [q_name, k_name, v_name] \
            + ([mask_name] if mask_name is not None else [])
        replacements[pv_i] = SameDiffOp(
            "nn", "scaledDotProductAttentionFused",
            inputs, [pv.outputs[0]], {"scale": scale})
        to_remove.update(x for x in (mm_i, mul_i, add_i, i)
                         if x is not None)
        if single_internal(kt_name):
            to_remove.add(kt_i)
        fused += 1

    if fused:
        # the fused op reproduces only the chain's FINAL output; every other
        # output of a removed node (scores, softmax probs, kT permute) no
        # longer exists. Record them so SameDiff.output() can raise a
        # targeted error naming this rewrite instead of a deep KeyError
        # when one is requested later.
        removed_names = {o for idx in to_remove for o in ops[idx].outputs}
        registry = getattr(sd, "_removed_by_rewrite", None)
        if registry is None:
            registry = sd._removed_by_rewrite = {}
        for name in removed_names:
            registry[name] = "fuseAttention"
        sd._ops = [replacements.get(idx, node) for idx, node in enumerate(ops)
                   if idx not in to_remove]
        sd._jit_cache.clear()
    return fused
