"""Graph-level kernel-fusion rewrites for SameDiff (beyond-parity).

The reference executes imported graphs node by node (SURVEY §3.3:
``TrainingSession`` op-at-a-time); this rebuild already compiles the whole
graph into one XLA program, but XLA still materializes the (B, H, T, T)
attention score tensor between the four matmul/scale/softmax/matmul nodes
an importer emits. ``fuse_attention`` pattern-matches that chain and
collapses it onto the ``scaledDotProductAttentionFused`` registry op, whose
TPU path is the whole-head VMEM Pallas kernel — the same lever that moved
the hand-written flagship (BASELINE.md round 4), applied to IMPORTED
graphs (BASELINE config #4).

Matched shape (what the TF importer emits for BERT-style attention,
verified against tools/tf_bert.py's frozen graph):

    q ----------------------------\
    k -> permute(0,1,3,2) -> matmul -> [mul(scalar)] -> softmax -> matmul -> out
    v -------------------------------------------------------------^

Intermediates must be single-consumer and not loss variables (a
later ``sd.output(...)`` request for a fused-away intermediate will
fail — intermediates are implementation detail, same as under plain
jit fusion); the optional ``mul`` must be by a scalar constant (the
1/sqrt(D) scale — trainable scalar scales are left unfused). Masked
attention (an ``add`` between scale and softmax) is NOT yet matched —
config #4's frozen graph has none; extend here when an imported workload
needs it.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiffOp, VariableType


def _scalar_const(sd, name) -> Optional[float]:
    """The float value of a size-1 CONSTANT, else None. Trainable scalars
    (varType VARIABLE) are rejected: baking their current value into the
    fused op's static kwargs would silently freeze a learnable scale."""
    try:
        v = sd.getVariable(name)
        if v.varType != VariableType.CONSTANT:
            return None
        arr = v.getArr()
    except Exception:
        return None
    if arr is None:
        return None
    a = np.asarray(arr.toNumpy() if hasattr(arr, "toNumpy") else arr)
    if a.size != 1:
        return None
    return float(a.reshape(()))


def fuse_attention(sd) -> int:
    """Collapse matmul->[scale]->softmax->matmul chains onto
    ``scaledDotProductAttentionFused``. Returns the number of sites fused.
    Output names are preserved, so downstream nodes and graph outputs are
    untouched; numerics are identical on the einsum path and within kernel
    tolerance (~1e-6 fp32 / bf16-rounding) on TPU."""
    ops = sd._ops
    producer = {}
    consumers = defaultdict(list)
    for i, node in enumerate(ops):
        for out in node.outputs:
            producer[out] = i
        for inp in node.inputs:
            consumers[inp].append(i)

    def prod(name):
        i = producer.get(name)
        return (i, ops[i]) if i is not None else (None, None)

    loss_vars = set(getattr(sd, "_loss_vars", []))

    def single_internal(name):
        """name has exactly one op consumer and is not a loss variable
        (fusing away a loss var's producer would break fit())."""
        return len(consumers.get(name, [])) == 1 and name not in loss_vars

    to_remove = set()
    replacements = {}
    fused = 0
    for i, node in enumerate(ops):
        if (node.namespace, node.opname) != ("nn", "softmax"):
            continue
        if node.kwargs.get("dim", -1) not in (-1,):
            continue
        # upward: [mul(scale)] <- matmul(q, permute(k))
        scale = None
        mul_i = None
        up_i, up = prod(node.inputs[0])
        if up is not None and (up.namespace, up.opname) == ("math", "mul"):
            a, b = up.inputs
            mm_i, mm = prod(a)
            scale_name = b
            if mm is None or mm.opname != "matmul":
                mm_i, mm = prod(b)
                scale_name = a
            if mm is None or mm.opname != "matmul":
                continue
            scale = _scalar_const(sd, scale_name)
            if scale is None:
                continue
            mul_i = up_i
        elif up is not None and up.opname == "matmul":
            mm_i, mm = up_i, up
            scale = 1.0
        else:
            continue
        q_name, kt_name = mm.inputs
        kt_i, kt = prod(kt_name)
        if kt is None or kt.opname != "permute" \
                or tuple(kt.kwargs.get("axes", ())) != (0, 1, 3, 2):
            continue
        k_name = kt.inputs[0]
        # downward: softmax -> matmul(p, v)
        p_name = node.outputs[0]
        cons = consumers.get(p_name, [])
        if len(cons) != 1:
            continue
        pv_i = cons[0]
        pv = ops[pv_i]
        if pv.opname != "matmul" or pv.inputs[0] != p_name:
            continue
        v_name = pv.inputs[1]
        # all pattern intermediates single-consumer (and the kT permute
        # removable only if nothing else reads it)
        mids = [mm.outputs[0], p_name] \
            + ([ops[mul_i].outputs[0]] if mul_i is not None else [])
        if not all(single_internal(m) for m in mids):
            continue
        # shapes: split-head rank-4, square T, matching k/v
        q_v, k_v, v_v = (sd.getVariable(n) for n in (q_name, k_name, v_name))
        shapes = [getattr(x, "shape", None) for x in (q_v, k_v, v_v)]
        if any(s is None or len(s) != 4 for s in shapes):
            continue
        # FULL shape equality (all four dims): the original matmul chain
        # broadcasts leading dims, the fused einsum does not
        if not (shapes[0] == shapes[1] == shapes[2]):
            continue
        replacements[pv_i] = SameDiffOp(
            "nn", "scaledDotProductAttentionFused",
            [q_name, k_name, v_name], [pv.outputs[0]], {"scale": scale})
        to_remove.update(x for x in (mm_i, mul_i, i) if x is not None)
        if single_internal(kt_name):
            to_remove.add(kt_i)
        fused += 1

    if fused:
        sd._ops = [replacements.get(idx, node) for idx, node in enumerate(ops)
                   if idx not in to_remove]
        sd._jit_cache.clear()
    return fused
