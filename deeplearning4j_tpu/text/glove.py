"""GloVe (ref: deeplearning4j-nlp org.deeplearning4j.models.glove.Glove —
co-occurrence counting + weighted least-squares factorization with AdaGrad)."""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.text.word2vec import WordVectorsModel
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory


def _glove_step(w, wt, b, bt, hw, hwt, hb, hbt, ci, cj, cx, xmax, alpha, lr):
    """Batched AdaGrad GloVe update on co-occurrence triples (i, j, X_ij)."""
    wi, wj = w[ci], wt[cj]
    diff = jnp.sum(wi * wj, axis=-1) + b[ci] + bt[cj] - jnp.log(cx)
    f = jnp.minimum(1.0, (cx / xmax) ** alpha)
    fd = f * diff                                     # (B,)
    gw = fd[:, None] * wj
    gwt = fd[:, None] * wi
    gb = fd
    # AdaGrad accumulators
    hw = hw.at[ci].add(gw * gw)
    hwt = hwt.at[cj].add(gwt * gwt)
    hb = hb.at[ci].add(gb * gb)
    hbt = hbt.at[cj].add(gb * gb)
    w = w.at[ci].add(-lr * gw / jnp.sqrt(hw[ci] + 1e-8))
    wt = wt.at[cj].add(-lr * gwt / jnp.sqrt(hwt[cj] + 1e-8))
    b = b.at[ci].add(-lr * gb / jnp.sqrt(hb[ci] + 1e-8))
    bt = bt.at[cj].add(-lr * gb / jnp.sqrt(hbt[cj] + 1e-8))
    loss = 0.5 * jnp.sum(f * diff * diff)
    return w, wt, b, bt, hw, hwt, hb, hbt, loss


_glove_step_jit = jax.jit(_glove_step)


class Glove(WordVectorsModel):
    """(ref: Glove.Builder)."""

    def __init__(self, minWordFrequency=1, iterations=15, layerSize=50, seed=42,
                 windowSize=5, learningRate=0.05, xMax=100.0, alpha=0.75,
                 batchSize=1024, iterate=None, tokenizerFactory=None):
        super().__init__()
        self.minWordFrequency = minWordFrequency
        self.iterations = iterations
        self.layerSize = layerSize
        self.seed = seed
        self.windowSize = windowSize
        self.learningRate = learningRate
        self.xMax = xMax
        self.alpha = alpha
        self.batchSize = batchSize
        self.iterator = iterate
        self.tokenizer = tokenizerFactory or DefaultTokenizerFactory()

    def fit(self):
        for s in self.iterator:
            for t in self.tokenizer.create(s).getTokens():
                self.vocab.addToken(t)
        self.vocab.finalize_vocab(self.minWordFrequency)
        V, D = self.vocab.numWords(), self.layerSize

        # co-occurrence accumulation with 1/distance weighting (ref:
        # CoOccurrences)
        cooc: dict = {}
        for s in self.iterator:
            ids = [self.vocab.indexOf(t)
                   for t in self.tokenizer.create(s).getTokens()]
            ids = [i for i in ids if i >= 0]
            for i, ci in enumerate(ids):
                for off in range(1, self.windowSize + 1):
                    j = i + off
                    if j >= len(ids):
                        break
                    key = (ci, ids[j])
                    cooc[key] = cooc.get(key, 0.0) + 1.0 / off
                    key2 = (ids[j], ci)
                    cooc[key2] = cooc.get(key2, 0.0) + 1.0 / off
        if not cooc:
            raise ValueError("empty co-occurrence matrix")
        triples = np.asarray([(i, j, x) for (i, j), x in cooc.items()],
                             dtype=np.float64)

        rng = np.random.default_rng(self.seed)
        w = jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D)
        wt = jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D)
        b = jnp.zeros((V,), jnp.float32)
        bt = jnp.zeros((V,), jnp.float32)
        hw = jnp.zeros((V, D), jnp.float32)
        hwt = jnp.zeros((V, D), jnp.float32)
        hb = jnp.zeros((V,), jnp.float32)
        hbt = jnp.zeros((V,), jnp.float32)

        for _ in range(self.iterations):
            rng.shuffle(triples)
            for k in range(0, len(triples), self.batchSize):
                t = triples[k:k + self.batchSize]
                w, wt, b, bt, hw, hwt, hb, hbt, loss = _glove_step_jit(
                    w, wt, b, bt, hw, hwt, hb, hbt,
                    jnp.asarray(t[:, 0], jnp.int32), jnp.asarray(t[:, 1], jnp.int32),
                    jnp.asarray(t[:, 2], jnp.float32), self.xMax, self.alpha,
                    self.learningRate)
        self.syn0 = np.asarray(w) + np.asarray(wt)  # standard GloVe: sum both
        return self
