"""Sentence iterators (ref: org.deeplearning4j.text.sentenceiterator.*)."""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence


class SentenceIterator:
    def nextSentence(self) -> str:
        raise NotImplementedError

    def hasNext(self) -> bool:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.hasNext():
            yield self.nextSentence()


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Sequence[str]):
        self._s = list(sentences)
        self._pos = 0

    def nextSentence(self) -> str:
        s = self._s[self._pos]
        self._pos += 1
        return s

    def hasNext(self) -> bool:
        return self._pos < len(self._s)

    def reset(self):
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line of a file (ref: BasicLineIterator)."""

    def __init__(self, path: str):
        with open(path, "r") as f:
            self._lines = [l.strip() for l in f if l.strip()]
        self._pos = 0

    def nextSentence(self) -> str:
        s = self._lines[self._pos]
        self._pos += 1
        return s

    def hasNext(self) -> bool:
        return self._pos < len(self._lines)

    def reset(self):
        self._pos = 0


LineSentenceIterator = BasicLineIterator
