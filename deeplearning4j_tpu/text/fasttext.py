"""FastText subword embeddings (ref: deeplearning4j-nlp
org.deeplearning4j.models.fasttext.FastText — the reference wraps the C++
fasttext binary via JNI; this is a native reimplementation of the
skipgram-with-subwords model on the same batched negative-sampling trainer
as word2vec.py, so it runs as jitted XLA scatter updates instead of
hogwild threads).

Model (Bojanowski et al. 2017): each word's input representation is the mean
of its word vector and the vectors of its char n-grams (3..6 by default),
hashed into a fixed bucket table. OOV words — the point of fastText — get a
vector from their n-grams alone.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.text.word2vec import (Word2Vec, WordVectorsModel,
                                              _mean_scatter)

BOW, EOW = "<", ">"


def _ngrams(word: str, minn: int, maxn: int) -> List[str]:
    w = BOW + word + EOW
    out = []
    for n in range(minn, maxn + 1):
        for i in range(0, len(w) - n + 1):
            g = w[i:i + n]
            if g != w:  # the full token is the word vector itself
                out.append(g)
    return out


def _hash(gram: str, bucket: int) -> int:
    """FNV-1a 32-bit (with intended wraparound), the hash fastText uses for
    n-gram bucketing."""
    h = 2166136261
    for b in gram.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % bucket


@jax.jit
def _ft_sg_step(syn0, syn1, sub_ids, sub_mask, ctx, neg, lr):
    """One batched subword skip-gram/negative-sampling step. Structurally
    _cbow_step with the window mean replaced by the subword mean: manual
    per-pair gradients scattered sparsely through _mean_scatter's bounded
    accumulation (see word2vec.py for why plain summed/mean updates are
    wrong), NOT dense autodiff over the whole (V+bucket, D) table.

    syn0: (V + bucket, D) input table (words then n-gram buckets).
    sub_ids/sub_mask: (B, M) constituent rows of each center word.
    ctx: (B,) positive context ids into syn1; neg: (B, K) negatives.
    """
    vs = syn0[sub_ids] * sub_mask[:, :, None]
    denom = jnp.maximum(sub_mask.sum(-1, keepdims=True), 1.0)
    h = vs.sum(1) / denom                                        # (B, D)
    u_pos = syn1[ctx]
    u_neg = syn1[neg]
    s_pos = jax.nn.sigmoid(jnp.sum(h * u_pos, axis=-1))
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u_neg))
    # a sampled negative that IS the positive context would cancel the
    # positive update — the reference (and word2vec._sg_step) skips those
    valid = (neg != ctx[:, None]).astype(s_neg.dtype)
    s_neg = s_neg * valid
    g_pos = (s_pos - 1.0)[:, None]
    grad_h = g_pos * u_pos + jnp.einsum("bk,bkd->bd", s_neg, u_neg)
    D = h.shape[-1]
    syn1 = _mean_scatter(
        syn1, jnp.concatenate([ctx, neg.reshape(-1)]),
        jnp.concatenate([g_pos * h,
                         (s_neg[:, :, None] * h[:, None, :]).reshape(-1, D)]),
        lr,
        weights=jnp.concatenate([jnp.ones_like(ctx, valid.dtype),
                                 valid.reshape(-1)]))
    grad_sub = (grad_h / denom)[:, None, :] * sub_mask[:, :, None]
    syn0 = _mean_scatter(syn0, sub_ids.reshape(-1), grad_sub.reshape(-1, D),
                         lr, weights=sub_mask.reshape(-1))
    return syn0, syn1


class FastText(Word2Vec):
    """(ref: org.deeplearning4j.models.fasttext.FastText + .Builder)."""

    def __init__(self, minn=3, maxn=6, bucket=20000, **kw):
        super().__init__(**kw)
        self.minn = minn
        self.maxn = maxn
        self.bucket = bucket
        self._sub_ids: Optional[np.ndarray] = None   # (V, M) padded
        self._sub_mask: Optional[np.ndarray] = None

    class Builder(Word2Vec.Builder):
        def build(self) -> "FastText":
            return FastText(**self._kw)

    # ------------------------------------------------------------------ fit
    def _build_subwords(self):
        V = self.vocab.numWords()
        rows: List[List[int]] = []
        for i in range(V):
            w = self.vocab.wordAtIndex(i)
            ids = [i]  # the word's own vector row
            ids += [V + _hash(g, self.bucket)
                    for g in _ngrams(w, self.minn, self.maxn)]
            rows.append(ids)
        M = max(len(r) for r in rows)
        sub = np.zeros((V, M), np.int32)
        mask = np.zeros((V, M), np.float32)
        for i, r in enumerate(rows):
            sub[i, :len(r)] = r
            mask[i, :len(r)] = 1.0
        self._sub_ids, self._sub_mask = sub, mask

    def fit(self):
        for s in self.iterator:
            for t in self.tokenizer.create(s).getTokens():
                self.vocab.addToken(t)
        self.vocab.finalize_vocab(self.minWordFrequency)
        self._build_subwords()
        V, D = self.vocab.numWords(), self.layerSize
        rng = np.random.default_rng(self.seed)
        syn0 = jnp.asarray(
            (rng.random((V + self.bucket, D), np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), jnp.float32)
        table = self.vocab.unigram_table()
        keep = (self.vocab.subsample_keep_prob(self.sampling)
                if self.sampling > 0 else None)
        sentences = self._sentences_as_ids()
        sub_ids = jnp.asarray(self._sub_ids)
        sub_mask = jnp.asarray(self._sub_mask)
        n_ep = max(self.epochs * self.iterations, 1)
        trained_any = False
        for ep in range(n_ep):
            # fresh pairs per epoch: subsampling + random window shrink are
            # stochastic, exactly as Word2Vec.fit regenerates them
            pairs = []
            for ids in sentences:
                if keep is not None:
                    ids = ids[rng.random(len(ids)) < keep[ids]]
                for i, c in enumerate(ids):
                    win = rng.integers(1, self.windowSize + 1)
                    lo, hi = max(0, i - win), min(len(ids), i + win + 1)
                    for j in range(lo, hi):
                        if j != i:
                            pairs.append((c, ids[j]))
            pairs = np.asarray(pairs, dtype=np.int32)
            if not len(pairs):
                continue  # subsampling can empty a tiny corpus this epoch
            trained_any = True
            rng.shuffle(pairs)
            lr = max(self.learningRate * (1 - ep / n_ep), self.minLearningRate)
            for k in range(0, len(pairs), self.batchSize):
                b = pairs[k:k + self.batchSize]
                neg = rng.choice(len(table), size=(len(b), self.negative),
                                 p=table).astype(np.int32)
                syn0, syn1 = _ft_sg_step(
                    syn0, syn1, sub_ids[b[:, 0]], sub_mask[b[:, 0]],
                    jnp.asarray(b[:, 1]), jnp.asarray(neg), lr)
        if not trained_any:
            raise ValueError("no training pairs in any epoch — corpus too small")
        full = np.asarray(syn0)
        self._bucket_table = full  # (V + bucket, D)
        # materialized per-word vectors (word row + ngram mean), the public
        # API — chunked over vocab rows so the transient (chunk, M, D) stays
        # small (a one-shot (V, M, D) gather can be GBs on realistic vocabs)
        nsub = np.maximum(self._sub_mask.sum(axis=1, keepdims=True), 1.0)
        out = np.empty((V, D), np.float32)
        for lo in range(0, V, 1024):
            hi = min(lo + 1024, V)
            out[lo:hi] = (full[self._sub_ids[lo:hi]] *
                          self._sub_mask[lo:hi, :, None]).sum(axis=1) / nsub[lo:hi]
        self.syn0 = out
        self._syn1 = np.zeros_like(self.syn0)
        return self

    # ---------------------------------------------------------------- query
    def getWordVector(self, word: str) -> Optional[np.ndarray]:
        v = super().getWordVector(word)
        if v is not None:
            return v
        return self.getOOVVector(word)

    def getOOVVector(self, word: str) -> Optional[np.ndarray]:
        """Subword composition for out-of-vocabulary words
        (ref: FastText.getWordVector on OOV — the defining capability)."""
        if self._bucket_table is None:
            return None
        V = self.vocab.numWords()
        ids = [V + _hash(g, self.bucket)
               for g in _ngrams(word, self.minn, self.maxn)]
        if not ids:
            return None
        return self._bucket_table[ids].mean(axis=0)

    _bucket_table: Optional[np.ndarray] = None
