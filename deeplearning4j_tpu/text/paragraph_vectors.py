"""ParagraphVectors / doc2vec (ref: deeplearning4j-nlp
org.deeplearning4j.models.paragraphvectors.ParagraphVectors — PV-DBOW:
the document vector predicts its words, trained alongside Word2Vec tables)."""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.text.word2vec import Word2Vec, _sg_step_jit


class LabelledDocument:
    def __init__(self, content: str, label: str):
        self.content = content
        self.label = label


class ParagraphVectors(Word2Vec):
    """(ref: ParagraphVectors.Builder). Labels (documents) get their own
    vector table; PV-DBOW training: doc vector predicts each word in the doc
    via the shared negative-sampling objective."""

    def __init__(self, labelledDocuments: Optional[Sequence[LabelledDocument]] = None,
                 **kw):
        super().__init__(**kw)
        self.documents = list(labelledDocuments or [])
        self.doc_labels: List[str] = []
        self.doc_vectors: Optional[np.ndarray] = None

    def fit(self):
        from deeplearning4j_tpu.text.sentence_iterator import CollectionSentenceIterator
        if self.iterator is None:
            self.iterator = CollectionSentenceIterator(
                [d.content for d in self.documents])
        super().fit()  # trains word tables + builds vocab
        self.doc_labels = [d.label for d in self.documents]
        D = self.layerSize
        rng = np.random.default_rng(self.seed + 1)
        docvecs = jnp.asarray((rng.random((len(self.documents), D),
                                          np.float32) - 0.5) / D)
        syn1 = jnp.asarray(self._syn1)
        table = self.vocab.unigram_table()
        # PV-DBOW: (doc, word) pairs
        pairs = []
        for di, d in enumerate(self.documents):
            for t in self.tokenizer.create(d.content).getTokens():
                wi = self.vocab.indexOf(t)
                if wi >= 0:
                    pairs.append((di, wi))
        pairs = np.asarray(pairs, dtype=np.int32)
        # small batches give the bounded-accumulation scatter step (see
        # word2vec._mean_scatter) finer-grained, fresher updates per doc row
        b_eff = min(self.batchSize, max(32, 2 * len(self.documents)))
        n_ep = max(self.epochs, 5)
        for ep in range(n_ep):
            rng.shuffle(pairs)
            lr = self.learningRate * (1 - ep / n_ep)
            for k in range(0, len(pairs), b_eff):
                b = pairs[k:k + b_eff]
                neg = rng.choice(len(table), size=(len(b), self.negative),
                                 p=table).astype(np.int32)
                docvecs, syn1 = _sg_step_jit(docvecs, syn1, jnp.asarray(b[:, 0]),
                                             jnp.asarray(b[:, 1]), jnp.asarray(neg),
                                             lr)
        self.doc_vectors = np.asarray(docvecs)
        self._syn1 = np.asarray(syn1)
        return self

    # ---- doc-level API (ref: ParagraphVectors)
    def getVectorForLabel(self, label: str) -> Optional[np.ndarray]:
        if label in self.doc_labels:
            return self.doc_vectors[self.doc_labels.index(label)]
        return None

    def inferVector(self, text: str, steps: int = 20, lr: float = 0.05) -> np.ndarray:
        """Infer a vector for unseen text by gradient steps on a fresh doc
        vector with frozen tables (ref: inferVector)."""
        rng = np.random.default_rng(0)
        ids = [self.vocab.indexOf(t)
               for t in self.tokenizer.create(text).getTokens()]
        ids = np.asarray([i for i in ids if i >= 0], dtype=np.int32)
        v = (rng.random(self.layerSize).astype(np.float32) - 0.5) / self.layerSize
        if len(ids) == 0:
            return v
        syn1 = self._syn1
        table = self.vocab.unigram_table()
        for _ in range(steps):
            u = syn1[ids]
            s = 1.0 / (1.0 + np.exp(-(u @ v)))
            grad = ((s - 1.0)[:, None] * u).sum(0)
            neg = rng.choice(len(table), size=(len(ids), self.negative), p=table)
            un = syn1[neg]
            sn = 1.0 / (1.0 + np.exp(-np.einsum("d,bkd->bk", v, un)))
            grad = grad + np.einsum("bk,bkd->d", sn, un)
            v = v - lr * grad / max(len(ids), 1)
        return v

    def similarityToLabel(self, text: str, label: str) -> float:
        v = self.inferVector(text)
        d = self.getVectorForLabel(label)
        return float(v @ d / (np.linalg.norm(v) * np.linalg.norm(d) + 1e-12))
