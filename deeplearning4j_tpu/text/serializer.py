"""Word-vector persistence (ref: deeplearning4j-nlp WordVectorSerializer —
the ~4k-LoC class handling every w2v file format; here: the standard text
format (word + space-separated floats per line, optional count header) and a
compressed npz container)."""
from __future__ import annotations

import gzip
import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.text.word2vec import Word2Vec, WordVectorsModel


class WordVectorSerializer:

    @staticmethod
    def writeWord2VecModel(model: WordVectorsModel, path: str):
        """Standard text format with "<vocab> <dim>" header
        (ref: writeWord2VecModel)."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            f.write(f"{model.vocab.numWords()} {model.layerSize}\n")
            for i in range(model.vocab.numWords()):
                vec = " ".join(f"{v:.6f}" for v in model.syn0[i])
                f.write(f"{model.vocab.wordAtIndex(i)} {vec}\n")

    @staticmethod
    def readWord2VecModel(path: str) -> Word2Vec:
        """(ref: readWord2VecModel / loadTxtVectors)."""
        opener = gzip.open if path.endswith(".gz") else open
        words, vecs = [], []
        with opener(path, "rt") as f:
            first = f.readline().split()
            header = len(first) == 2  # "<vocab> <dim>"
            if not header:
                words.append(first[0])
                vecs.append([float(v) for v in first[1:]])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                vecs.append([float(v) for v in parts[1:] if v])
        model = Word2Vec(layerSize=len(vecs[0]))
        for w in words:
            model.vocab.addToken(w)
        model.vocab.finalize_vocab(1)
        # preserve file order: re-index by appearance
        syn0 = np.zeros((len(words), len(vecs[0])), np.float32)
        for w, v in zip(words, vecs):
            syn0[model.vocab.indexOf(w)] = v
        model.syn0 = syn0
        return model

    loadTxtVectors = readWord2VecModel

    # ------------------------------------------------- Google binary format
    @staticmethod
    def writeBinaryModel(model: WordVectorsModel, path: str):
        """Google News word2vec .bin format: "<vocab> <dim>\\n" then per word
        "<word> " + dim*float32 little-endian (ref: writeBinary path of
        WordVectorSerializer)."""
        with open(path, "wb") as f:
            f.write(f"{model.vocab.numWords()} {model.layerSize}\n".encode())
            for i in range(model.vocab.numWords()):
                f.write(model.vocab.wordAtIndex(i).encode("utf-8") + b" ")
                f.write(np.asarray(model.syn0[i], np.float32).tobytes())
                f.write(b"\n")

    @staticmethod
    def readBinaryModel(path: str) -> Word2Vec:
        """(ref: readBinaryModel — streams the Google News .bin format)."""
        with open(path, "rb") as f:
            header = f.readline().split()
            vocab_size, dim = int(header[0]), int(header[1])
            model = Word2Vec(layerSize=dim)
            vecs = np.zeros((vocab_size, dim), np.float32)
            words = []
            for _ in range(vocab_size):
                chars = bytearray()
                while True:
                    c = f.read(1)
                    if c in (b" ", b""):
                        break
                    if c != b"\n":  # leading newline from previous record
                        chars.extend(c)
                words.append(chars.decode("utf-8"))
                vecs[len(words) - 1] = np.frombuffer(
                    f.read(4 * dim), dtype="<f4")
        for w in words:
            model.vocab.addToken(w)
        model.vocab.finalize_vocab(1)
        syn0 = np.zeros_like(vecs)
        for w, v in zip(words, vecs):
            syn0[model.vocab.indexOf(w)] = v
        model.syn0 = syn0
        return model

    # ------------------------------------------------- ParagraphVectors serde
    @staticmethod
    def writeParagraphVectors(model, path: str):
        """npz container: word tables + doc labels/vectors
        (ref: writeParagraphVectors zip format)."""
        from deeplearning4j_tpu.text.paragraph_vectors import ParagraphVectors
        assert isinstance(model, ParagraphVectors)
        words = [model.vocab.wordAtIndex(i)
                 for i in range(model.vocab.numWords())]
        np.savez_compressed(
            path,
            words=np.array(words, dtype=object),
            syn0=np.asarray(model.syn0, np.float32),
            syn1=np.asarray(model._syn1, np.float32),  # inferVector needs it
            doc_labels=np.array(model.doc_labels, dtype=object),
            doc_vectors=np.asarray(model.doc_vectors, np.float32),
            layer_size=np.int64(model.layerSize))

    @staticmethod
    def readParagraphVectors(path: str):
        """(ref: readParagraphVectors)."""
        from deeplearning4j_tpu.text.paragraph_vectors import ParagraphVectors
        z = np.load(path if str(path).endswith(".npz") else str(path) + ".npz",
                    allow_pickle=True)
        model = ParagraphVectors(layerSize=int(z["layer_size"]))
        for w in z["words"]:
            model.vocab.addToken(str(w))
        model.vocab.finalize_vocab(1)
        syn0 = np.zeros_like(z["syn0"])
        syn1 = np.zeros_like(z["syn0"])
        for i, w in enumerate(z["words"]):
            j = model.vocab.indexOf(str(w))
            syn0[j] = z["syn0"][i]
            if "syn1" in z:
                syn1[j] = z["syn1"][i]
        model.syn0 = syn0
        model._syn1 = syn1
        model.doc_labels = [str(l) for l in z["doc_labels"]]
        model.doc_vectors = z["doc_vectors"]
        return model

    # ------------------------------------------------------- GloVe text load
    @staticmethod
    def loadGloveVectors(path: str) -> Word2Vec:
        """GloVe's headerless text format — same parser, header optional
        (ref: loadTxtVectors handles both)."""
        return WordVectorSerializer.readWord2VecModel(path)
