"""Word-vector persistence (ref: deeplearning4j-nlp WordVectorSerializer —
the ~4k-LoC class handling every w2v file format; here: the standard text
format (word + space-separated floats per line, optional count header) and a
compressed npz container)."""
from __future__ import annotations

import gzip
import os
from typing import Optional

import numpy as np

from deeplearning4j_tpu.text.word2vec import Word2Vec, WordVectorsModel


class WordVectorSerializer:

    @staticmethod
    def writeWord2VecModel(model: WordVectorsModel, path: str):
        """Standard text format with "<vocab> <dim>" header
        (ref: writeWord2VecModel)."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            f.write(f"{model.vocab.numWords()} {model.layerSize}\n")
            for i in range(model.vocab.numWords()):
                vec = " ".join(f"{v:.6f}" for v in model.syn0[i])
                f.write(f"{model.vocab.wordAtIndex(i)} {vec}\n")

    @staticmethod
    def readWord2VecModel(path: str) -> Word2Vec:
        """(ref: readWord2VecModel / loadTxtVectors)."""
        opener = gzip.open if path.endswith(".gz") else open
        words, vecs = [], []
        with opener(path, "rt") as f:
            first = f.readline().split()
            header = len(first) == 2  # "<vocab> <dim>"
            if not header:
                words.append(first[0])
                vecs.append([float(v) for v in first[1:]])
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                vecs.append([float(v) for v in parts[1:] if v])
        model = Word2Vec(layerSize=len(vecs[0]))
        for w in words:
            model.vocab.addToken(w)
        model.vocab.finalize_vocab(1)
        # preserve file order: re-index by appearance
        syn0 = np.zeros((len(words), len(vecs[0])), np.float32)
        for w, v in zip(words, vecs):
            syn0[model.vocab.indexOf(w)] = v
        model.syn0 = syn0
        return model

    loadTxtVectors = readWord2VecModel
