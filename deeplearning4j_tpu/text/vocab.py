"""Vocabulary cache (ref: deeplearning4j-nlp org.deeplearning4j.models.word2vec.
wordstore.VocabCache / AbstractCache — word counts, frequency filtering, index
assignment, subsampling/negative-sampling tables)."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class VocabWord:
    """(ref: org.deeplearning4j.models.word2vec.VocabWord)."""

    def __init__(self, word: str, count: int = 1, index: int = -1):
        self.word = word
        self.count = count
        self.index = index

    def getWord(self):
        return self.word

    def getElementFrequency(self):
        return self.count

    def getIndex(self):
        return self.index

    def __repr__(self):
        return f"VocabWord({self.word!r}, n={self.count}, i={self.index})"


class VocabCache:
    """(ref: AbstractCache) — built by counting tokens, then trimmed by
    minWordFrequency and indexed by descending frequency."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []

    # ---- building
    def addToken(self, word: str):
        if word in self._words:
            self._words[word].count += 1
        else:
            self._words[word] = VocabWord(word)

    def finalize_vocab(self, minWordFrequency: int = 1):
        kept = [w for w in self._words.values() if w.count >= minWordFrequency]
        kept.sort(key=lambda w: (-w.count, w.word))
        self._by_index = kept
        self._words = {w.word: w for w in kept}
        for i, w in enumerate(kept):
            w.index = i
        return self

    # ---- queries
    def numWords(self) -> int:
        return len(self._by_index)

    def containsWord(self, word: str) -> bool:
        return word in self._words

    def wordFor(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def indexOf(self, word: str) -> int:
        w = self._words.get(word)
        return w.index if w else -1

    def wordAtIndex(self, index: int) -> str:
        return self._by_index[index].word

    def words(self) -> List[str]:
        return [w.word for w in self._by_index]

    def totalWordOccurrences(self) -> int:
        return sum(w.count for w in self._by_index)

    # ---- sampling tables
    def unigram_table(self, power: float = 0.75) -> np.ndarray:
        """Negative-sampling distribution p(w) ~ count^0.75 (word2vec standard;
        ref: the hardcoded 0.75 in libnd4j skipgram + AbstractCache tables)."""
        c = np.array([w.count for w in self._by_index], dtype=np.float64) ** power
        return (c / c.sum()).astype(np.float32)

    def subsample_keep_prob(self, t: float = 1e-3) -> np.ndarray:
        """word2vec frequent-word subsampling keep probability."""
        total = max(self.totalWordOccurrences(), 1)
        f = np.array([w.count / total for w in self._by_index], dtype=np.float64)
        keep = np.minimum(1.0, np.sqrt(t / np.maximum(f, 1e-12)) + t / np.maximum(f, 1e-12))
        return keep.astype(np.float32)
