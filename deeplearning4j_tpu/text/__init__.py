"""NLP / embedding models (ref: deeplearning4j-nlp-parent — Word2Vec,
ParagraphVectors, GloVe, tokenizers, vocab, serializer; SURVEY.md §2.4)."""
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, NGramTokenizerFactory, CommonPreprocessor,
    LowCasePreProcessor)
from deeplearning4j_tpu.text.sentence_iterator import (
    BasicLineIterator, CollectionSentenceIterator, LineSentenceIterator)
from deeplearning4j_tpu.text.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.text.word2vec import Word2Vec
from deeplearning4j_tpu.text.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.text.glove import Glove
from deeplearning4j_tpu.text.fasttext import FastText
from deeplearning4j_tpu.text.serializer import WordVectorSerializer

__all__ = [
    "DefaultTokenizerFactory", "NGramTokenizerFactory", "CommonPreprocessor",
    "LowCasePreProcessor", "BasicLineIterator", "CollectionSentenceIterator",
    "LineSentenceIterator", "VocabCache", "VocabWord", "Word2Vec",
    "ParagraphVectors", "Glove", "FastText", "WordVectorSerializer",
]
