"""Tokenizers (ref: deeplearning4j-nlp org.deeplearning4j.text.tokenization —
TokenizerFactory SPI + TokenPreProcess)."""
from __future__ import annotations

import re
from typing import List, Optional


class TokenPreProcess:
    def preProcess(self, token: str) -> str:
        raise NotImplementedError


class LowCasePreProcessor(TokenPreProcess):
    def preProcess(self, token: str) -> str:
        return token.lower()


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (ref: CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def preProcess(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class Tokenizer:
    def __init__(self, tokens: List[str]):
        self._tokens = tokens
        self._pos = 0

    def hasMoreTokens(self) -> bool:
        return self._pos < len(self._tokens)

    def nextToken(self) -> str:
        t = self._tokens[self._pos]
        self._pos += 1
        return t

    def countTokens(self) -> int:
        return len(self._tokens)

    def getTokens(self) -> List[str]:
        return list(self._tokens)


class DefaultTokenizerFactory:
    """Whitespace tokenizer + optional preprocessor (ref: DefaultTokenizerFactory)."""

    def __init__(self):
        self._pre: Optional[TokenPreProcess] = None

    def setTokenPreProcessor(self, pre: TokenPreProcess):
        self._pre = pre

    def create(self, text: str) -> Tokenizer:
        toks = text.split()
        if self._pre is not None:
            toks = [self._pre.preProcess(t) for t in toks]
        return Tokenizer([t for t in toks if t])


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """Emit n-grams of the base tokens (ref: NGramTokenizerFactory)."""

    def __init__(self, minN: int = 1, maxN: int = 2):
        super().__init__()
        self.minN = minN
        self.maxN = maxN

    def create(self, text: str) -> Tokenizer:
        base = super().create(text).getTokens()
        out: List[str] = []
        for n in range(self.minN, self.maxN + 1):
            for i in range(len(base) - n + 1):
                out.append(" ".join(base[i:i + n]))
        return Tokenizer(out)
