"""Word2Vec (ref: deeplearning4j-nlp org.deeplearning4j.models.word2vec.Word2Vec
+ SequenceVectors training loop + libnd4j skipgram/cbow fused ops).

TPU-native redesign (SURVEY.md §2.9 P12): the reference trains with racing
hogwild threads mutating a shared table through per-pair native ops. Here
training is **batched negative-sampling SGD under one jitted step**: all
(center, context) pairs of a batch update the tables at once via segment-sum
scatter adds — deterministic, MXU-friendly, and convergence-equivalent (the
reference's exact race nondeterminism is not reproducible nor desirable).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.text.sentence_iterator import SentenceIterator
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.text.vocab import VocabCache


def _sg_step(syn0, syn1, center, ctx, neg, lr):
    """One batched skip-gram negative-sampling step.
    center/ctx: (B,) int32; neg: (B, K) int32. Returns updated (syn0, syn1)."""
    v = syn0[center]                      # (B, D)
    u_pos = syn1[ctx]                     # (B, D)
    u_neg = syn1[neg]                     # (B, K, D)

    s_pos = jax.nn.sigmoid(jnp.sum(v * u_pos, axis=-1))          # (B,)
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, u_neg))   # (B, K)

    g_pos = (s_pos - 1.0)[:, None]        # d/du_pos
    g_neg = s_neg[:, :, None]             # d/du_neg

    grad_v = g_pos * u_pos + jnp.einsum("bk,bkd->bd", s_neg, u_neg)
    grad_u_pos = g_pos * v
    grad_u_neg = g_neg * v[:, None, :]

    syn0 = syn0.at[center].add(-lr * grad_v)
    syn1 = syn1.at[ctx].add(-lr * grad_u_pos)
    syn1 = syn1.at[neg.reshape(-1)].add(-lr * grad_u_neg.reshape(-1, grad_v.shape[-1]))
    return syn0, syn1


_sg_step_jit = jax.jit(_sg_step)


def _cbow_step(syn0, syn1, ctx_win, ctx_mask, target, neg, lr):
    """CBOW: mean of window context vectors predicts the target.
    ctx_win: (B, W) int32 (padded), ctx_mask: (B, W) float."""
    vs = syn0[ctx_win] * ctx_mask[:, :, None]
    denom = jnp.maximum(ctx_mask.sum(-1, keepdims=True), 1.0)
    h = vs.sum(1) / denom                                        # (B, D)
    u_pos = syn1[target]
    u_neg = syn1[neg]
    s_pos = jax.nn.sigmoid(jnp.sum(h * u_pos, axis=-1))
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u_neg))
    g_pos = (s_pos - 1.0)[:, None]
    grad_h = g_pos * u_pos + jnp.einsum("bk,bkd->bd", s_neg, u_neg)
    syn1 = syn1.at[target].add(-lr * g_pos * h)
    syn1 = syn1.at[neg.reshape(-1)].add(
        -lr * (s_neg[:, :, None] * h[:, None, :]).reshape(-1, h.shape[-1]))
    grad_ctx = (grad_h / denom)[:, None, :] * ctx_mask[:, :, None]
    syn0 = syn0.at[ctx_win.reshape(-1)].add(
        -lr * grad_ctx.reshape(-1, h.shape[-1]))
    return syn0, syn1


_cbow_step_jit = jax.jit(_cbow_step)


class WordVectorsModel:
    """Shared lookup surface (ref: WordVectors / InMemoryLookupTable)."""

    def __init__(self):
        self.vocab = VocabCache()
        self.syn0: Optional[np.ndarray] = None
        self.layerSize = 0

    # ---- lookups (ref: WordVectors interface)
    def hasWord(self, word: str) -> bool:
        return self.vocab.containsWord(word)

    def getWordVector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.indexOf(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def getWordVectorMatrix(self, word: str):
        return self.getWordVector(word)

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.getWordVector(a), self.getWordVector(b)
        if va is None or vb is None:
            return float("nan")
        return float(np.dot(va, vb) /
                     (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def wordsNearest(self, word_or_vec, topN: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.getWordVector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        if v is None:
            return []
        m = np.asarray(self.syn0)
        sims = m @ v / (np.linalg.norm(m, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.wordAtIndex(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= topN:
                break
        return out


class Word2Vec(WordVectorsModel):
    """(ref: org.deeplearning4j.models.word2vec.Word2Vec + .Builder)."""

    def __init__(self, minWordFrequency=1, iterations=1, epochs=1, layerSize=100,
                 seed=42, windowSize=5, learningRate=0.025, minLearningRate=1e-4,
                 negativeSample=5, sampling=0.0, batchSize=512,
                 elementsLearningAlgorithm="SkipGram",
                 iterate: Optional[SentenceIterator] = None,
                 tokenizerFactory=None):
        super().__init__()
        self.minWordFrequency = minWordFrequency
        self.iterations = iterations
        self.epochs = epochs
        self.layerSize = layerSize
        self.seed = seed
        self.windowSize = windowSize
        self.learningRate = learningRate
        self.minLearningRate = minLearningRate
        self.negative = max(int(negativeSample), 1)
        self.sampling = sampling
        self.batchSize = batchSize
        self.algorithm = elementsLearningAlgorithm
        self.iterator = iterate
        self.tokenizer = tokenizerFactory or DefaultTokenizerFactory()

    class Builder:
        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            def setter(value):
                self._kw[name] = value
                return self
            return setter

        def build(self) -> "Word2Vec":
            return Word2Vec(**self._kw)

    # ------------------------------------------------------------------ fit
    def _sentences_as_ids(self) -> List[np.ndarray]:
        out = []
        for s in self.iterator:
            toks = self.tokenizer.create(s).getTokens()
            ids = [self.vocab.indexOf(t) for t in toks]
            ids = [i for i in ids if i >= 0]
            if len(ids) > 1:
                out.append(np.asarray(ids, dtype=np.int32))
        return out

    def fit(self):
        # 1. vocab pass (ref: VocabConstructor)
        for s in self.iterator:
            for t in self.tokenizer.create(s).getTokens():
                self.vocab.addToken(t)
        self.vocab.finalize_vocab(self.minWordFrequency)
        V, D = self.vocab.numWords(), self.layerSize
        rng = np.random.default_rng(self.seed)
        syn0 = jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), jnp.float32)
        table = self.vocab.unigram_table()
        keep = self.vocab.subsample_keep_prob(self.sampling) if self.sampling > 0 else None

        sentences = self._sentences_as_ids()
        total_steps = max(self.epochs * self.iterations, 1)
        step_no = 0
        for _ in range(self.epochs):
            # 2. generate (center, context) pairs with random window shrink
            pairs = []
            for ids in sentences:
                if keep is not None:
                    ids = ids[rng.random(len(ids)) < keep[ids]]
                for i, c in enumerate(ids):
                    b = rng.integers(1, self.windowSize + 1)
                    lo, hi = max(0, i - b), min(len(ids), i + b + 1)
                    for j in range(lo, hi):
                        if j != i:
                            pairs.append((c, ids[j]))
            if not pairs:
                continue
            pairs = np.asarray(pairs, dtype=np.int32)
            rng.shuffle(pairs)
            lr = max(self.minLearningRate,
                     self.learningRate * (1 - step_no / total_steps))
            for _ in range(self.iterations):
                for k in range(0, len(pairs), self.batchSize):
                    batch = pairs[k:k + self.batchSize]
                    neg = rng.choice(len(table), size=(len(batch), self.negative),
                                     p=table).astype(np.int32)
                    if self.algorithm == "CBOW":
                        ctx = batch[:, 1][:, None]
                        mask = np.ones_like(ctx, dtype=np.float32)
                        syn0, syn1 = _cbow_step_jit(
                            syn0, syn1, jnp.asarray(ctx), jnp.asarray(mask),
                            jnp.asarray(batch[:, 0]), jnp.asarray(neg), lr)
                    else:
                        syn0, syn1 = _sg_step_jit(
                            syn0, syn1, jnp.asarray(batch[:, 0]),
                            jnp.asarray(batch[:, 1]), jnp.asarray(neg), lr)
            step_no += 1
        self.syn0 = np.asarray(syn0)
        self._syn1 = np.asarray(syn1)
        return self
