"""Word2Vec (ref: deeplearning4j-nlp org.deeplearning4j.models.word2vec.Word2Vec
+ SequenceVectors training loop + libnd4j skipgram/cbow fused ops).

TPU-native redesign (SURVEY.md §2.9 P12): the reference trains with racing
hogwild threads mutating a shared table through per-pair native ops. Here
training is **batched negative-sampling SGD under one jitted step**: all
(center, context) pairs of a batch update the tables at once via per-row
MEAN-normalized scatter adds — deterministic, MXU-friendly, and
convergence-equivalent (the reference's exact race nondeterminism is not
reproducible nor desirable; plain gradient SUMS diverge on small vocabs where
one row collects many stale contributions per batch).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.text.sentence_iterator import SentenceIterator
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.text.vocab import VocabCache


_ACCUM_CAP = 8.0


def _mean_scatter(table, idx_flat, grads_flat, lr, weights=None):
    """SGD step with BOUNDED per-row gradient accumulation.

    A plain scatter-add sums B/V stale gradients per row; on small vocabs that
    multiplies the effective lr by the occurrence count and diverges (the
    reference's hogwild loop applies them sequentially at fresh values, which
    self-limits). Full mean-normalization is stable but over-damps — a row
    with 60 pairs in the batch advances like it had one. Capping the
    accumulation factor at _ACCUM_CAP keeps per-batch movement bounded
    (≤ cap·lr·|grad|) while staying within ~cap× of the reference's
    sequential convergence rate.

    Stays sparse: only a (V,1) count buffer is materialized; each
    contribution is pre-scaled by its row's factor and scatter-added
    (sum_i scale_row*grad_i == scale_row * gsum_row). ``weights`` marks
    which contributions are real (masked negative draws must not damp the
    row's scale).
    """
    w = (jnp.ones((idx_flat.shape[0], 1), table.dtype)
         if weights is None else weights[:, None].astype(table.dtype))
    cnt = jnp.zeros((table.shape[0], 1), table.dtype).at[idx_flat].add(w)
    scale = jnp.minimum(1.0, _ACCUM_CAP / jnp.maximum(cnt, 1.0))[idx_flat]
    return table.at[idx_flat].add(-lr * grads_flat * scale)


def _sg_step(syn0, syn1, center, ctx, neg, lr):
    """One batched skip-gram negative-sampling step.
    center/ctx: (B,) int32; neg: (B, K) int32. Returns updated (syn0, syn1)."""
    v = syn0[center]                      # (B, D)
    u_pos = syn1[ctx]                     # (B, D)
    u_neg = syn1[neg]                     # (B, K, D)

    s_pos = jax.nn.sigmoid(jnp.sum(v * u_pos, axis=-1))          # (B,)
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, u_neg))   # (B, K)
    # a sampled negative that IS the positive context would cancel the
    # positive update — the reference skips those draws
    valid = (neg != ctx[:, None]).astype(s_neg.dtype)            # (B, K)
    s_neg = s_neg * valid

    g_pos = (s_pos - 1.0)[:, None]        # d/du_pos
    g_neg = s_neg[:, :, None]             # d/du_neg

    grad_v = g_pos * u_pos + jnp.einsum("bk,bkd->bd", s_neg, u_neg)
    grad_u_pos = g_pos * v
    grad_u_neg = g_neg * v[:, None, :]

    D = grad_v.shape[-1]
    syn0 = _mean_scatter(syn0, center, grad_v, lr)
    syn1 = _mean_scatter(
        syn1, jnp.concatenate([ctx, neg.reshape(-1)]),
        jnp.concatenate([grad_u_pos, grad_u_neg.reshape(-1, D)]), lr,
        weights=jnp.concatenate([jnp.ones_like(ctx, valid.dtype),
                                 valid.reshape(-1)]))
    return syn0, syn1


_sg_step_jit = jax.jit(_sg_step)


def _cbow_step(syn0, syn1, ctx_win, ctx_mask, target, neg, lr):
    """CBOW: mean of window context vectors predicts the target.
    ctx_win: (B, W) int32 (padded), ctx_mask: (B, W) float."""
    vs = syn0[ctx_win] * ctx_mask[:, :, None]
    denom = jnp.maximum(ctx_mask.sum(-1, keepdims=True), 1.0)
    h = vs.sum(1) / denom                                        # (B, D)
    u_pos = syn1[target]
    u_neg = syn1[neg]
    s_pos = jax.nn.sigmoid(jnp.sum(h * u_pos, axis=-1))
    s_neg = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, u_neg))
    valid = (neg != target[:, None]).astype(s_neg.dtype)
    s_neg = s_neg * valid
    g_pos = (s_pos - 1.0)[:, None]
    grad_h = g_pos * u_pos + jnp.einsum("bk,bkd->bd", s_neg, u_neg)
    D = h.shape[-1]
    syn1 = _mean_scatter(
        syn1, jnp.concatenate([target, neg.reshape(-1)]),
        jnp.concatenate([g_pos * h,
                         (s_neg[:, :, None] * h[:, None, :]).reshape(-1, D)]), lr,
        weights=jnp.concatenate([jnp.ones_like(target, valid.dtype),
                                 valid.reshape(-1)]))
    grad_ctx = (grad_h / denom)[:, None, :] * ctx_mask[:, :, None]
    syn0 = _mean_scatter(syn0, ctx_win.reshape(-1), grad_ctx.reshape(-1, D), lr,
                         weights=ctx_mask.reshape(-1))
    return syn0, syn1


_cbow_step_jit = jax.jit(_cbow_step)


class WordVectorsModel:
    """Shared lookup surface (ref: WordVectors / InMemoryLookupTable)."""

    def __init__(self):
        self.vocab = VocabCache()
        self.syn0: Optional[np.ndarray] = None
        self.layerSize = 0

    # ---- lookups (ref: WordVectors interface)
    def hasWord(self, word: str) -> bool:
        return self.vocab.containsWord(word)

    def getWordVector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.indexOf(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def getWordVectorMatrix(self, word: str):
        return self.getWordVector(word)

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.getWordVector(a), self.getWordVector(b)
        if va is None or vb is None:
            return float("nan")
        return float(np.dot(va, vb) /
                     (np.linalg.norm(va) * np.linalg.norm(vb) + 1e-12))

    def wordsNearest(self, word_or_vec, topN: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.getWordVector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        if v is None:
            return []
        m = np.asarray(self.syn0)
        sims = m @ v / (np.linalg.norm(m, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.wordAtIndex(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= topN:
                break
        return out


class Word2Vec(WordVectorsModel):
    """(ref: org.deeplearning4j.models.word2vec.Word2Vec + .Builder)."""

    def __init__(self, minWordFrequency=1, iterations=1, epochs=1, layerSize=100,
                 seed=42, windowSize=5, learningRate=0.025, minLearningRate=1e-4,
                 negativeSample=5, sampling=0.0, batchSize=512,
                 elementsLearningAlgorithm="SkipGram",
                 iterate: Optional[SentenceIterator] = None,
                 tokenizerFactory=None):
        super().__init__()
        self.minWordFrequency = minWordFrequency
        self.iterations = iterations
        self.epochs = epochs
        self.layerSize = layerSize
        self.seed = seed
        self.windowSize = windowSize
        self.learningRate = learningRate
        self.minLearningRate = minLearningRate
        self.negative = max(int(negativeSample), 1)
        self.sampling = sampling
        self.batchSize = batchSize
        self.algorithm = elementsLearningAlgorithm
        self.iterator = iterate
        self.tokenizer = tokenizerFactory or DefaultTokenizerFactory()

    class Builder:
        def __init__(self):
            self._kw = {}

        def __getattr__(self, name):
            def setter(value):
                self._kw[name] = value
                return self
            return setter

        def build(self) -> "Word2Vec":
            return Word2Vec(**self._kw)

    # ------------------------------------------------------------------ fit
    def _sentences_as_ids(self) -> List[np.ndarray]:
        out = []
        for s in self.iterator:
            toks = self.tokenizer.create(s).getTokens()
            ids = [self.vocab.indexOf(t) for t in toks]
            ids = [i for i in ids if i >= 0]
            if len(ids) > 1:
                out.append(np.asarray(ids, dtype=np.int32))
        return out

    def fit(self):
        # 1. vocab pass (ref: VocabConstructor)
        for s in self.iterator:
            for t in self.tokenizer.create(s).getTokens():
                self.vocab.addToken(t)
        self.vocab.finalize_vocab(self.minWordFrequency)
        V, D = self.vocab.numWords(), self.layerSize
        rng = np.random.default_rng(self.seed)
        syn0 = jnp.asarray((rng.random((V, D), np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), jnp.float32)
        table = self.vocab.unigram_table()
        keep = self.vocab.subsample_keep_prob(self.sampling) if self.sampling > 0 else None

        sentences = self._sentences_as_ids()
        # cap the batch so each row averages only a few contributions: with
        # mean-normalized updates a 512-pair batch over a tiny vocab would
        # advance each word by just ~1 effective step — sequential-like
        # freshness needs batches of O(vocab). Real vocabs keep full batches.
        b_eff = min(self.batchSize, max(64, 4 * V))
        for ep in range(self.epochs):
            # 2. generate (center, context) pairs with random window shrink
            pairs = []
            for ids in sentences:
                if keep is not None:
                    ids = ids[rng.random(len(ids)) < keep[ids]]
                for i, c in enumerate(ids):
                    b = rng.integers(1, self.windowSize + 1)
                    lo, hi = max(0, i - b), min(len(ids), i + b + 1)
                    for j in range(lo, hi):
                        if j != i:
                            pairs.append((c, ids[j]))
            if not pairs:
                continue
            pairs = np.asarray(pairs, dtype=np.int32)
            rng.shuffle(pairs)
            nb = max(1, -(-len(pairs) // b_eff) * self.iterations)
            bi = 0
            for _ in range(self.iterations):
                for k in range(0, len(pairs), b_eff):
                    # linear per-batch decay (ref: alpha decays per word seen)
                    frac = (ep + bi / nb) / max(self.epochs, 1)
                    lr = max(self.minLearningRate, self.learningRate * (1 - frac))
                    bi += 1
                    batch = pairs[k:k + b_eff]
                    neg = rng.choice(len(table), size=(len(batch), self.negative),
                                     p=table).astype(np.int32)
                    if self.algorithm == "CBOW":
                        ctx = batch[:, 1][:, None]
                        mask = np.ones_like(ctx, dtype=np.float32)
                        syn0, syn1 = _cbow_step_jit(
                            syn0, syn1, jnp.asarray(ctx), jnp.asarray(mask),
                            jnp.asarray(batch[:, 0]), jnp.asarray(neg), lr)
                    else:
                        syn0, syn1 = _sg_step_jit(
                            syn0, syn1, jnp.asarray(batch[:, 0]),
                            jnp.asarray(batch[:, 1]), jnp.asarray(neg), lr)
        self.syn0 = np.asarray(syn0)
        self._syn1 = np.asarray(syn1)
        return self
