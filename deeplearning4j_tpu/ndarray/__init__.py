"""Tensor core: NDArray facade + factory + dtypes + RNG (nd4j-api equivalent)."""
from deeplearning4j_tpu.ndarray.array import NDArray
from deeplearning4j_tpu.ndarray.indexing import INDArrayIndex, NDArrayIndex
from deeplearning4j_tpu.ndarray.factory import nd
from deeplearning4j_tpu.ndarray import dtypes
from deeplearning4j_tpu.ndarray.random import Random, getRandom

__all__ = ["NDArray", "nd", "dtypes", "Random", "getRandom"]
