"""RNG (ref: org.nd4j.linalg.api.rng + libnd4j RandomLauncher/RandomGenerator).

The reference uses a Philox-style counter RNG with a settable global seed
(``Nd4j.getRandom().setSeed(...)``). The TPU-native equivalent is JAX's
threefry counter PRNG; this module keeps the reference's *stateful seed API* as
a thin shell over explicit key-splitting, so ``setSeed(12345)`` reproduces
deterministic streams just like the reference's test fixtures (SURVEY.md §4.3).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp


class Random:
    """Stateful key holder; each draw splits a fresh subkey.

    Key creation is LAZY (first draw, not construction): the module-level
    singleton below is built at package import, and materializing a jax key
    there would initialize the XLA backend — breaking
    jax.distributed.initialize() for any process that imports the package
    before joining the job."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._key = None
        self._seed = seed

    def setSeed(self, seed: int):
        with self._lock:
            self._key = jax.random.key(seed)
            self._seed = seed

    def getSeed(self) -> int:
        return self._seed

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def nextKey(self) -> jax.Array:
        with self._lock:
            self._ensure()
            self._key, sub = jax.random.split(self._key)
            return sub

    def split(self, n: int):
        with self._lock:
            self._ensure()
            keys = jax.random.split(self._key, n + 1)
            self._key = keys[0]
            return keys[1:]

    # sampling helpers (shapes as tuples)
    def uniform(self, shape, minval=0.0, maxval=1.0, dtype=jnp.float32):
        return jax.random.uniform(self.nextKey(), shape, dtype=dtype, minval=minval, maxval=maxval)

    def normal(self, shape, mean=0.0, std=1.0, dtype=jnp.float32):
        return jax.random.normal(self.nextKey(), shape, dtype=dtype) * std + mean

    def bernoulli(self, shape, p=0.5):
        return jax.random.bernoulli(self.nextKey(), p, shape)

    def randint(self, shape, minval, maxval, dtype=jnp.int32):
        return jax.random.randint(self.nextKey(), shape, minval, maxval, dtype=dtype)

    def permutation(self, n_or_array):
        return jax.random.permutation(self.nextKey(), n_or_array)


_global = Random(0)


def getRandom() -> Random:
    return _global
