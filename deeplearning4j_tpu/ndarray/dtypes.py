"""Data type system (ref: org.nd4j.linalg.api.buffer.DataType).

Maps the reference's DataType enum onto jnp dtypes. On TPU the natural compute
types are bfloat16/float32; float64 is supported (XLA emulates on TPU, native on
CPU) and is used by the gradient-check tier exactly as the reference forces
global fp64 for gradient checks (SURVEY.md §4.3).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# dl4j DataType name -> numpy/jnp dtype
_DTYPES = {
    "DOUBLE": jnp.float64,
    "FLOAT": jnp.float32,
    "HALF": jnp.float16,
    "BFLOAT16": jnp.bfloat16,
    "LONG": jnp.int64,
    "INT": jnp.int32,
    "SHORT": jnp.int16,
    "BYTE": jnp.int8,
    "UBYTE": jnp.uint8,
    "UINT16": jnp.uint16,
    "UINT32": jnp.uint32,
    "UINT64": jnp.uint64,
    "BOOL": jnp.bool_,
}

_CANONICAL = {np.dtype(v).name: k for k, v in _DTYPES.items()}

FLOATING = {"DOUBLE", "FLOAT", "HALF", "BFLOAT16"}
INTEGRAL = {"LONG", "INT", "SHORT", "BYTE", "UBYTE", "UINT16", "UINT32", "UINT64"}


def resolve(dtype) -> jnp.dtype:
    """Accept a dl4j-style name ('FLOAT'), a numpy/jnp dtype, or a python type."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.upper()
        if key in _DTYPES:
            return jnp.dtype(_DTYPES[key])
        return jnp.dtype(dtype)  # allow 'float32' etc.
    return jnp.dtype(dtype)


def name_of(dtype) -> str:
    """The dl4j DataType name for a jnp/numpy dtype ('FLOAT', 'INT', ...)."""
    return _CANONICAL.get(np.dtype(dtype).name, np.dtype(dtype).name.upper())


def is_floating(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.floating) or np.dtype(dtype) == np.dtype(
        jnp.bfloat16
    )


def is_integral(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.integer)


class _Defaults:
    """Global default dtypes (ref: Nd4j.setDefaultDataTypes)."""

    def __init__(self):
        self.floating = jnp.dtype(jnp.float32)
        self.integral = jnp.dtype(jnp.int32)

    def set(self, floating=None, integral=None):
        if floating is not None:
            self.floating = resolve(floating)
        if integral is not None:
            self.integral = resolve(integral)


defaults = _Defaults()
