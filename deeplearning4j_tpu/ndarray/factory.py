"""The static factory — nd4j's ``Nd4j`` class equivalent (ref:
org.nd4j.linalg.factory.Nd4j).

Array creation, global dtype control, linalg entry points. Backend discovery is
moot: there is exactly one backend (XLA via jax), selected by JAX_PLATFORMS.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray import dtypes as _dt
from deeplearning4j_tpu.ndarray import random as _random
from deeplearning4j_tpu.ndarray.array import NDArray, _unwrap


def _dtype(dtype):
    return _dt.resolve(dtype) if dtype is not None else _dt.defaults.floating


def _shape(args):
    if len(args) == 1 and isinstance(args[0], (tuple, list)):
        return tuple(args[0])
    return tuple(args)


class nd:
    """Namespace of static factory/exec methods (Nd4j analog)."""

    DataType = _dt

    # ------------------------------------------------------------- creation
    @staticmethod
    def create(data=None, shape=None, dtype=None) -> NDArray:
        if data is None:
            return nd.zeros(*shape, dtype=dtype)
        arr = jnp.asarray(_unwrap(data), dtype=_dt.resolve(dtype) if dtype else None)
        if shape is not None:
            arr = jnp.reshape(arr, tuple(shape))
        return NDArray(arr)

    @staticmethod
    def zeros(*shape, dtype=None) -> NDArray:
        return NDArray(jnp.zeros(_shape(shape), dtype=_dtype(dtype)))

    @staticmethod
    def ones(*shape, dtype=None) -> NDArray:
        return NDArray(jnp.ones(_shape(shape), dtype=_dtype(dtype)))

    @staticmethod
    def zerosLike(a) -> NDArray:
        return NDArray(jnp.zeros_like(_unwrap(a)))

    @staticmethod
    def onesLike(a) -> NDArray:
        return NDArray(jnp.ones_like(_unwrap(a)))

    @staticmethod
    def valueArrayOf(shape, value, dtype=None) -> NDArray:
        return NDArray(jnp.full(tuple(shape), value, dtype=_dtype(dtype)))

    @staticmethod
    def scalar(value, dtype=None) -> NDArray:
        return NDArray(jnp.asarray(value, dtype=_dt.resolve(dtype) if dtype else None))

    @staticmethod
    def eye(n, dtype=None) -> NDArray:
        return NDArray(jnp.eye(n, dtype=_dtype(dtype)))

    @staticmethod
    def arange(*args, dtype=None) -> NDArray:
        return NDArray(jnp.arange(*args, dtype=_dt.resolve(dtype) if dtype else None))

    @staticmethod
    def linspace(start, stop, num, dtype=None) -> NDArray:
        return NDArray(jnp.linspace(start, stop, num, dtype=_dtype(dtype)))

    # ---------------------------------------------------------------- random
    @staticmethod
    def getRandom() -> _random.Random:
        return _random.getRandom()

    @staticmethod
    def rand(*shape, dtype=None) -> NDArray:
        return NDArray(_random.getRandom().uniform(_shape(shape), dtype=_dtype(dtype)))

    @staticmethod
    def randn(*shape, dtype=None) -> NDArray:
        return NDArray(_random.getRandom().normal(_shape(shape), dtype=_dtype(dtype)))

    @staticmethod
    def randomBernoulli(p, *shape) -> NDArray:
        return NDArray(
            _random.getRandom().bernoulli(_shape(shape), p).astype(_dt.defaults.floating)
        )

    # ------------------------------------------------------------ stack/split
    @staticmethod
    def concat(axis, *arrays) -> NDArray:
        return NDArray(jnp.concatenate([_unwrap(a) for a in arrays], axis=axis))

    @staticmethod
    def stack(axis, *arrays) -> NDArray:
        return NDArray(jnp.stack([_unwrap(a) for a in arrays], axis=axis))

    @staticmethod
    def vstack(*arrays) -> NDArray:
        return NDArray(jnp.vstack([_unwrap(a) for a in arrays]))

    @staticmethod
    def hstack(*arrays) -> NDArray:
        return NDArray(jnp.hstack([_unwrap(a) for a in arrays]))

    @staticmethod
    def split(a, n, axis=0):
        return [NDArray(x) for x in jnp.split(_unwrap(a), n, axis=axis)]

    @staticmethod
    def tile(a, *reps) -> NDArray:
        return NDArray(jnp.tile(_unwrap(a), _shape(reps)))

    @staticmethod
    def where(cond, x=None, y=None):
        if x is None:
            return [NDArray(i) for i in jnp.where(_unwrap(cond))]
        return NDArray(jnp.where(_unwrap(cond), _unwrap(x), _unwrap(y)))

    # ----------------------------------------------------------------- linalg
    @staticmethod
    def gemm(a, b, transposeA=False, transposeB=False, alpha=1.0, beta=0.0, c=None) -> NDArray:
        A = _unwrap(a).T if transposeA else _unwrap(a)
        B = _unwrap(b).T if transposeB else _unwrap(b)
        out = alpha * jnp.matmul(A, B)
        if c is not None and beta != 0.0:
            out = out + beta * _unwrap(c)
        return NDArray(out)

    @staticmethod
    def matmul(a, b) -> NDArray:
        return NDArray(jnp.matmul(_unwrap(a), _unwrap(b)))

    @staticmethod
    def dot(a, b) -> NDArray:
        return NDArray(jnp.dot(_unwrap(a), _unwrap(b)))

    @staticmethod
    def tensorMmul(a, b, axes) -> NDArray:
        return NDArray(jnp.tensordot(_unwrap(a), _unwrap(b), axes=axes))

    @staticmethod
    def kron(a, b) -> NDArray:
        return NDArray(jnp.kron(_unwrap(a), _unwrap(b)))

    @staticmethod
    def diag(a) -> NDArray:
        return NDArray(jnp.diag(_unwrap(a)))

    # -------------------------------------------------------------- gather etc
    @staticmethod
    def gather(a, indices, axis=0) -> NDArray:
        return NDArray(jnp.take(_unwrap(a), _unwrap(indices), axis=axis))

    @staticmethod
    def scatterUpdate(a, indices, updates) -> NDArray:
        return NDArray(_unwrap(a).at[_unwrap(indices)].set(_unwrap(updates)))

    @staticmethod
    def scatterAdd(a, indices, updates) -> NDArray:
        return NDArray(_unwrap(a).at[_unwrap(indices)].add(_unwrap(updates)))

    @staticmethod
    def oneHot(indices, depth, dtype=None) -> NDArray:
        return NDArray(jax.nn.one_hot(_unwrap(indices), depth, dtype=_dtype(dtype)))

    @staticmethod
    def sort(a, axis=-1, descending=False) -> NDArray:
        out = jnp.sort(_unwrap(a), axis=axis)
        return NDArray(jnp.flip(out, axis=axis) if descending else out)

    @staticmethod
    def argsort(a, axis=-1) -> NDArray:
        return NDArray(jnp.argsort(_unwrap(a), axis=axis))

    @staticmethod
    def topK(a, k, axis=-1):
        vals, idx = jax.lax.top_k(jnp.moveaxis(_unwrap(a), axis, -1), k)
        return NDArray(jnp.moveaxis(vals, -1, axis)), NDArray(jnp.moveaxis(idx, -1, axis))

    # --------------------------------------------------------------- defaults
    @staticmethod
    def setDefaultDataTypes(floating=None, integral=None):
        _dt.defaults.set(floating, integral)

    @staticmethod
    def defaultFloatingPointType():
        return _dt.defaults.floating

    # -------------------------------------------------------------- env info
    @staticmethod
    def getBackend() -> str:
        return jax.default_backend()

    @staticmethod
    def getAffinityManager():
        """Device listing (ref: Nd4j.getAffinityManager) — on TPU, placement
        is owned by jax.sharding; this only reports devices."""
        return jax.devices()
