"""NDArray — the tensor facade (ref: org.nd4j.linalg.api.ndarray.INDArray/BaseNDArray).

A thin, zero-copy wrapper over ``jax.Array`` that preserves the reference's op
*names and semantics* at the API boundary while keeping the compute path purely
functional (the TPU-idiomatic form — XLA owns layout/fusion; there is no c/f
order or stride machinery to manage, see SURVEY.md §7.3 item 4).

In-place ``i``-variants (``addi``, ``muli`` …) rebind the wrapper to the new
functional value — observationally in-place for the common "handle held in one
place" pattern the reference's training loops use, without fighting XLA's
immutable buffers. True aliasing of *views* is intentionally not reproduced;
``dup()`` remains a semantic copy.

NDArray is registered as a jax pytree node so it can flow through jit/grad/vmap
transparently.
"""
from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray import dtypes as _dt


def _unwrap(x):
    return x._jax if isinstance(x, NDArray) else x


def _wrap(x):
    return NDArray(x) if isinstance(x, (jax.Array, np.ndarray)) else x


class NDArray:
    """N-dimensional array over a jax.Array value."""

    __slots__ = ("_jax",)

    def __init__(self, value):
        if isinstance(value, NDArray):
            value = value._jax
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        self._jax = value

    # ------------------------------------------------------------------ basics
    @property
    def jax(self) -> jax.Array:
        """The underlying jax.Array (escape hatch)."""
        return self._jax

    @property
    def shape(self):
        return tuple(self._jax.shape)

    @property
    def dtype(self):
        return self._jax.dtype

    def dataType(self) -> str:
        return _dt.name_of(self._jax.dtype)

    def rank(self) -> int:
        return self._jax.ndim

    @property
    def ndim(self) -> int:
        return self._jax.ndim

    def length(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def size(self) -> int:
        return self.length()

    def isScalar(self) -> bool:
        return self._jax.ndim == 0

    def isVector(self) -> bool:
        return self._jax.ndim == 1

    def isMatrix(self) -> bool:
        return self._jax.ndim == 2

    def rows(self) -> int:
        return self.shape[0]

    def columns(self) -> int:
        return self.shape[1]

    def dup(self, order: str = "c") -> "NDArray":
        """Semantic copy (ref: INDArray.dup / dup(char)). The copy's VALUES
        are identical either way — in the reference, order only changes the
        underlying buffer layout, which this facade does not expose (XLA
        owns layout). The observable face of ordering is flattening:
        ravel/reshape take an ``order`` argument."""
        return NDArray(jnp.array(self._jax))

    def ordering(self) -> str:
        """(ref: INDArray.ordering) — the facade is always c-order
        observable; 'f' semantics surface via the order arguments on
        ravel/reshape where flattening order leaks into serialization."""
        return "c"

    def castTo(self, dtype) -> "NDArray":
        return NDArray(self._jax.astype(_dt.resolve(dtype)))

    astype = castTo

    def toNumpy(self) -> np.ndarray:
        return np.asarray(self._jax)

    def __array__(self, dtype=None):
        a = np.asarray(self._jax)
        return a.astype(dtype) if dtype is not None else a

    def item(self):
        return self._jax.item()

    def __float__(self) -> float:
        return float(self._jax)

    def __int__(self) -> int:
        return int(self._jax)

    def __bool__(self) -> bool:
        return bool(self._jax)

    def getDouble(self, *indices) -> float:
        return float(self._jax[tuple(indices)] if indices else self._jax)

    def getInt(self, *indices) -> int:
        return int(self._jax[tuple(indices)] if indices else self._jax)

    # --------------------------------------------------------- binary arithmetic
    def _binary(self, other, fn) -> "NDArray":
        return NDArray(fn(self._jax, _unwrap(other)))

    def _ibinary(self, other, fn) -> "NDArray":
        self._jax = fn(self._jax, _unwrap(other))
        return self

    def add(self, other):
        return self._binary(other, jnp.add)

    def sub(self, other):
        return self._binary(other, jnp.subtract)

    def mul(self, other):
        return self._binary(other, jnp.multiply)

    def div(self, other):
        return self._binary(other, jnp.divide)

    def rsub(self, other):
        return self._binary(other, lambda a, b: b - a)

    def rdiv(self, other):
        return self._binary(other, lambda a, b: b / a)

    def fmod(self, other):
        return self._binary(other, jnp.fmod)

    def pow(self, other):
        return self._binary(other, jnp.power)

    def addi(self, other):
        return self._ibinary(other, jnp.add)

    def subi(self, other):
        return self._ibinary(other, jnp.subtract)

    def muli(self, other):
        return self._ibinary(other, jnp.multiply)

    def divi(self, other):
        return self._ibinary(other, jnp.divide)

    def rsubi(self, other):
        return self._ibinary(other, lambda a, b: b - a)

    def rdivi(self, other):
        return self._ibinary(other, lambda a, b: b / a)

    def neg(self):
        return NDArray(-self._jax)

    def negi(self):
        self._jax = -self._jax
        return self

    def assign(self, other):
        """Overwrite contents (ref: INDArray.assign) — rebinds to a broadcast copy."""
        self._jax = jnp.broadcast_to(_unwrap(other), self.shape).astype(self.dtype)
        return self

    # dunders
    __add__ = add
    __radd__ = add
    __sub__ = sub
    __rsub__ = rsub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __rtruediv__ = rdiv
    __pow__ = pow
    __neg__ = neg
    __mod__ = fmod

    def __matmul__(self, other):
        return self.mmul(other)

    # ----------------------------------------------------------------- linalg
    def mmul(self, other) -> "NDArray":
        return NDArray(jnp.matmul(self._jax, _unwrap(other)))

    def transpose(self, *axes) -> "NDArray":
        if not axes:
            return NDArray(jnp.transpose(self._jax))
        return NDArray(jnp.transpose(self._jax, axes))

    def permute(self, *axes) -> "NDArray":
        return NDArray(jnp.transpose(self._jax, axes))

    def transposei(self):
        self._jax = jnp.transpose(self._jax)
        return self

    # ------------------------------------------------------------------ shape
    def reshape(self, *shape, order: str = "c") -> "NDArray":
        """(ref: INDArray.reshape(char order, ...)): 'f' enumerates/refills
        elements column-major — the reference's f-order reshape semantics,
        reproduced functionally (jnp lacks order=F; transpose-compose)."""
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if order.lower() == "f":
            flat = self.ravel(order="f")._jax
            return NDArray(jnp.transpose(
                jnp.reshape(flat, tuple(reversed(shape)))))
        return NDArray(jnp.reshape(self._jax, shape))

    def ravel(self, order: str = "c") -> "NDArray":
        """(ref: INDArray.ravel(char)): 'f' flattens column-major — the
        order that leaks into the reference's flat-params serialization."""
        if order.lower() == "f":
            axes = tuple(range(self.ndim))[::-1]
            return NDArray(jnp.ravel(jnp.transpose(self._jax, axes)))
        return NDArray(jnp.ravel(self._jax))

    flatten = ravel

    def broadcast(self, *shape) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return NDArray(jnp.broadcast_to(self._jax, shape))

    def repeat(self, repeats, axis=None) -> "NDArray":
        return NDArray(jnp.repeat(self._jax, repeats, axis=axis))

    def squeeze(self, axis=None) -> "NDArray":
        return NDArray(jnp.squeeze(self._jax, axis=axis))

    def expandDims(self, axis) -> "NDArray":
        return NDArray(jnp.expand_dims(self._jax, axis))

    def swapAxes(self, a, b) -> "NDArray":
        return NDArray(jnp.swapaxes(self._jax, a, b))

    # ------------------------------------------------------------- reductions
    def _reduce(self, fn, dims, keepdims=False):
        axis = None if not dims else (dims if len(dims) > 1 else dims[0])
        return NDArray(fn(self._jax, axis=axis, keepdims=keepdims))

    def sum(self, *dims, keepdims=False):
        return self._reduce(jnp.sum, dims, keepdims)

    def mean(self, *dims, keepdims=False):
        return self._reduce(jnp.mean, dims, keepdims)

    def max(self, *dims, keepdims=False):
        return self._reduce(jnp.max, dims, keepdims)

    def min(self, *dims, keepdims=False):
        return self._reduce(jnp.min, dims, keepdims)

    def prod(self, *dims, keepdims=False):
        return self._reduce(jnp.prod, dims, keepdims)

    def std(self, *dims, keepdims=False, biasCorrected=True):
        axis = None if not dims else (dims if len(dims) > 1 else dims[0])
        return NDArray(
            jnp.std(self._jax, axis=axis, keepdims=keepdims, ddof=1 if biasCorrected else 0)
        )

    def var(self, *dims, keepdims=False, biasCorrected=True):
        axis = None if not dims else (dims if len(dims) > 1 else dims[0])
        return NDArray(
            jnp.var(self._jax, axis=axis, keepdims=keepdims, ddof=1 if biasCorrected else 0)
        )

    def norm1(self, *dims, keepdims=False):
        return self._reduce(lambda a, axis, keepdims: jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdims), dims, keepdims)

    def norm2(self, *dims, keepdims=False):
        return self._reduce(
            lambda a, axis, keepdims: jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdims)),
            dims,
            keepdims,
        )

    def normmax(self, *dims, keepdims=False):
        return self._reduce(lambda a, axis, keepdims: jnp.max(jnp.abs(a), axis=axis, keepdims=keepdims), dims, keepdims)

    def argMax(self, *dims):
        axis = dims[0] if dims else None
        return NDArray(jnp.argmax(self._jax, axis=axis))

    def argMin(self, *dims):
        axis = dims[0] if dims else None
        return NDArray(jnp.argmin(self._jax, axis=axis))

    def cumsum(self, axis=None):
        return NDArray(jnp.cumsum(self._jax, axis=axis))

    def cumprod(self, axis=None):
        return NDArray(jnp.cumprod(self._jax, axis=axis))

    def sumNumber(self) -> float:
        return float(jnp.sum(self._jax))

    def meanNumber(self) -> float:
        return float(jnp.mean(self._jax))

    def maxNumber(self) -> float:
        return float(jnp.max(self._jax))

    def minNumber(self) -> float:
        return float(jnp.min(self._jax))

    def norm2Number(self) -> float:
        return float(jnp.sqrt(jnp.sum(self._jax * self._jax)))

    def entropy(self, *dims):
        axis = None if not dims else (dims if len(dims) > 1 else dims[0])
        p = self._jax
        return NDArray(-jnp.sum(p * jnp.log(p), axis=axis))

    # ------------------------------------------------------------ comparisons
    def gt(self, other):
        return self._binary(other, jnp.greater)

    def lt(self, other):
        return self._binary(other, jnp.less)

    def gte(self, other):
        return self._binary(other, jnp.greater_equal)

    def lte(self, other):
        return self._binary(other, jnp.less_equal)

    def eq(self, other):
        return self._binary(other, jnp.equal)

    def neq(self, other):
        return self._binary(other, jnp.not_equal)

    __gt__ = gt
    __lt__ = lt
    __ge__ = gte
    __le__ = lte

    def __eq__(self, other):  # INDArray.eq semantics: elementwise
        if isinstance(other, (NDArray, jax.Array, np.ndarray, int, float, bool)):
            return self.eq(other)
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (NDArray, jax.Array, np.ndarray, int, float, bool)):
            return self.neq(other)
        return NotImplemented

    def __hash__(self):
        return id(self)

    def equalsWithEps(self, other, eps=1e-5) -> bool:
        o = _unwrap(other)
        if tuple(jnp.shape(o)) != self.shape:
            return False
        return bool(jnp.all(jnp.abs(self._jax - o) <= eps))

    def equals(self, other) -> bool:
        return self.equalsWithEps(other, 1e-5)

    # --------------------------------------------------------------- indexing
    def __getitem__(self, idx):
        return NDArray(self._jax[idx])

    def __setitem__(self, idx, value):
        self._jax = self._jax.at[idx].set(_unwrap(value))

    def get(self, *indices):
        """View selection (ref: INDArray.get(INDArrayIndex...)): accepts
        NDArrayIndex.point/all/interval/newAxis/indices objects as well as
        raw ints and slices; fewer indices than rank leaves trailing
        dimensions as all()."""
        from deeplearning4j_tpu.ndarray.indexing import lower_indices
        return NDArray(self._jax[lower_indices(indices)])

    def getRow(self, i):
        return NDArray(self._jax[i])

    def getColumn(self, i):
        return NDArray(self._jax[:, i])

    def getRows(self, *rows):
        return NDArray(self._jax[jnp.asarray(rows)])

    def getColumns(self, *cols):
        return NDArray(self._jax[:, jnp.asarray(cols)])

    def putScalar(self, indices, value):
        if not isinstance(indices, (tuple, list)):
            indices = (indices,)
        self._jax = self._jax.at[tuple(indices)].set(value)
        return self

    def put(self, indices, value):
        """Assign into a view selection (ref: INDArray.put(INDArrayIndex...,
        INDArray)): value broadcasts into the selected region; the update is
        observable through THIS handle (functional .at[].set rebind)."""
        from deeplearning4j_tpu.ndarray.indexing import lower_indices
        if not isinstance(indices, (tuple, list)):
            indices = (indices,)
        self._jax = self._jax.at[lower_indices(indices)].set(_unwrap(value))
        return self

    def putRow(self, i, row):
        self._jax = self._jax.at[i].set(_unwrap(row))
        return self

    def putColumn(self, i, col):
        self._jax = self._jax.at[:, i].set(_unwrap(col))
        return self

    def slice(self, i, axis=0):
        return NDArray(jnp.take(self._jax, i, axis=axis))

    def tensorAlongDimension(self, index, *dims):
        """TAD access (ref: BaseNDArray.tensorAlongDimension) — returns the
        index-th sub-tensor spanning ``dims``."""
        dims = sorted(d % self.ndim for d in dims)
        other = [d for d in range(self.ndim) if d not in dims]
        perm = other + dims
        moved = jnp.transpose(self._jax, perm)
        lead = int(np.prod([self.shape[d] for d in other])) if other else 1
        tad_shape = tuple(self.shape[d] for d in dims)
        return NDArray(jnp.reshape(moved, (lead,) + tad_shape)[index])

    # ------------------------------------------------------------------ misc
    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __iter__(self):
        return (NDArray(self._jax[i]) for i in range(len(self)))

    def __repr__(self):
        return f"NDArray(shape={self.shape}, dtype={_dt.name_of(self.dtype)})\n{self._jax}"

    def shapeInfoToString(self) -> str:
        return f"rank={self.ndim}, shape={list(self.shape)}, dtype={self.dataType()}"


def _flatten_ndarray(x: NDArray):
    return (x._jax,), None


def _unflatten_ndarray(_, children):
    obj = object.__new__(NDArray)
    obj._jax = children[0]
    return obj


jax.tree_util.register_pytree_node(NDArray, _flatten_ndarray, _unflatten_ndarray)
