"""NDArrayIndex — the reference's view-selection DSL at the API boundary
(ref: org.nd4j.linalg.indexing.NDArrayIndex + INDArrayIndex implementations:
PointIndex, IntervalIndex, NDArrayIndexAll, NewAxis, SpecifiedIndex).

Semantics preserved exactly where observable (SURVEY §2.2 / §7.3 item 4):

- ``point(i)``        selects index i and REMOVES the dimension
- ``all()``           keeps the whole dimension
- ``interval(a, b)``  half-open [a, b), keeps the dimension;
  ``interval(a, stride, b)`` strided; ``interval(a, b, inclusive=True)``
  closes the upper bound (the reference's 4-arg boolean form)
- ``newAxis()``       inserts a size-1 dimension
- ``indices(i...)``   fancy selection along the dimension (SpecifiedIndex)
- fewer indices than rank → trailing dimensions behave as ``all()``

Internally everything lowers to one numpy-style index tuple; the compute
path stays functional (``put`` is a functional ``.at[].set`` rebind — the
reference mutates the view in place, observable through the SAME handle,
which the rebind preserves)."""
from __future__ import annotations

from typing import Tuple


class INDArrayIndex:
    """Base marker (ref: org.nd4j.linalg.indexing.INDArrayIndex)."""

    def lower(self):
        raise NotImplementedError


class _Point(INDArrayIndex):
    def __init__(self, i: int):
        self.i = int(i)

    def lower(self):
        return self.i

    def __repr__(self):
        return f"point({self.i})"


class _All(INDArrayIndex):
    def lower(self):
        return slice(None)

    def __repr__(self):
        return "all()"


class _Interval(INDArrayIndex):
    def __init__(self, start: int, stride: int, end: int, inclusive: bool):
        self.start, self.stride, self.end = int(start), int(stride), int(end)
        self.inclusive = inclusive

    def lower(self):
        end = self.end + 1 if self.inclusive else self.end
        return slice(self.start, end, self.stride)

    def __repr__(self):
        return f"interval({self.start},{self.stride},{self.end}" \
            + (",inclusive)" if self.inclusive else ")")


class _NewAxis(INDArrayIndex):
    def lower(self):
        return None  # numpy newaxis

    def __repr__(self):
        return "newAxis()"


class _Specified(INDArrayIndex):
    def __init__(self, idxs):
        self.idxs = [int(i) for i in idxs]

    def lower(self):
        import numpy as np
        return np.asarray(self.idxs)

    def __repr__(self):
        return f"indices({self.idxs})"


class NDArrayIndex:
    """Static factories (ref: NDArrayIndex.point/all/interval/newAxis)."""

    @staticmethod
    def point(i: int) -> INDArrayIndex:
        return _Point(i)

    @staticmethod
    def all() -> INDArrayIndex:
        return _All()

    @staticmethod
    def interval(start: int, *args, inclusive: bool = False) -> INDArrayIndex:
        """interval(a, b) | interval(a, stride, b) | the reference's 4-arg
        form interval(a, stride, b, inclusive) via the keyword."""
        if len(args) == 1:
            stride, end = 1, args[0]
        elif len(args) == 2:
            stride, end = args
        elif len(args) == 3:
            stride, end, inclusive = args
        else:
            raise TypeError("interval(start, [stride,] end[, inclusive])")
        return _Interval(start, stride, end, inclusive)

    @staticmethod
    def newAxis() -> INDArrayIndex:
        return _NewAxis()

    @staticmethod
    def indices(*idxs) -> INDArrayIndex:
        return _Specified(idxs)


def lower_indices(indices) -> Tuple:
    """INDArrayIndex / raw int / slice sequence -> numpy index tuple.
    Trailing unspecified dimensions are implicit all() (numpy already
    behaves this way for a short tuple)."""
    out = []
    for ix in indices:
        out.append(ix.lower() if isinstance(ix, INDArrayIndex) else ix)
    return tuple(out)
