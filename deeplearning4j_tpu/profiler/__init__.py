from deeplearning4j_tpu.profiler.profiler import (
    OpProfiler,
    PanicException,
    ProfilerConfig,
    ProfilingListener,
    device_trace,
    mfu,
)

__all__ = [
    "OpProfiler",
    "PanicException",
    "ProfilerConfig",
    "ProfilingListener",
    "device_trace",
    "mfu",
]
