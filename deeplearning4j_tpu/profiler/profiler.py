"""Profiling + numerical-panic tooling (ref: org.nd4j.linalg.profiler.
OpProfiler with ProfilerConfig's checkForNAN/checkForINF 'panic modes', and
deeplearning4j's PerformanceListener timing hooks — SURVEY.md §5.1).

The reference profiles per-op because each op is a discrete kernel launch.
Under XLA a whole train step is ONE fused executable, so per-Java-op timing is
meaningless here; the profiling unit is the **span** (a step, a data-load, an
eval pass) plus XLA's own kernel-level profiler:

- ``OpProfiler`` — named wall-clock spans, nestable, exported as a Chrome
  trace JSON (chrome://tracing / Perfetto loadable), the TPU analog of the
  reference's printOutDashboard().
- ``device_trace(logdir)`` — delegates to ``jax.profiler.trace``: captures
  XLA/TPU kernel timelines viewable in TensorBoard's profile tab (the real
  per-kernel data the reference's OpProfiler approximates on CPU).
- panic modes — ``ProfilerConfig(checkForNAN=True)`` makes attached
  ``ProfilingListener``s scan score/params/grads each iteration and raise
  ``PanicException`` on the first non-finite value (ref:
  OpExecutionerUtil.checkForAny + ND4JOpProfilerException). Device-side
  reduction: one jitted ``isfinite`` all-reduce per tree, no host transfer of
  the tensors themselves.
- ``mfu()`` — model-flops-utilization calculator used by bench.py.
"""
from __future__ import annotations

import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optimize.listeners import TrainingListener


class PanicException(RuntimeError):
    """Non-finite value detected under panic mode (ref:
    ND4JOpProfilerException)."""


@dataclass
class ProfilerConfig:
    """(ref: org.nd4j.linalg.profiler.ProfilerConfig builder)."""

    checkForNAN: bool = False
    checkForINF: bool = False
    collectSpans: bool = True


@dataclass
class _Span:
    name: str
    start_us: float
    dur_us: float
    tid: int
    args: Optional[dict] = None


@jax.jit
def _finite_report(leaves_stacked):
    """all-finite / any-nan / any-inf flags for a flat f32 vector."""
    return (jnp.all(jnp.isfinite(leaves_stacked)),
            jnp.any(jnp.isnan(leaves_stacked)),
            jnp.any(jnp.isinf(leaves_stacked)))


def check_tree_finite(tree, what: str, check_nan=True, check_inf=True):
    """Raise PanicException if any leaf of ``tree`` holds NaN (or Inf)."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype") and jnp.issubdtype(
                  jnp.asarray(l).dtype, jnp.inexact)]
    if not leaves:
        return
    flat = jnp.concatenate([jnp.ravel(jnp.asarray(l)).astype(jnp.float32)
                            for l in leaves])
    ok, has_nan, has_inf = _finite_report(flat)
    if bool(ok):
        return
    if check_nan and bool(has_nan):
        raise PanicException(f"NaN detected in {what} (panic mode)")
    if check_inf and bool(has_inf):
        raise PanicException(f"Inf detected in {what} (panic mode)")


class OpProfiler:
    """Span collector with Chrome-trace export.

    Use ``with profiler.span("train_step"):`` around anything; nesting is
    expressed via Chrome trace's duration-event stacking per thread.
    """

    _instance: Optional["OpProfiler"] = None

    def __init__(self, config: Optional[ProfilerConfig] = None):
        self.config = config or ProfilerConfig()
        self._spans: List[_Span] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @classmethod
    def getInstance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    def reset(self):
        with self._lock:
            self._spans = []
            self._t0 = time.perf_counter()

    @contextmanager
    def span(self, name: str, **args):
        start = time.perf_counter()
        try:
            yield
        finally:
            if self.config.collectSpans:
                end = time.perf_counter()
                with self._lock:
                    self._spans.append(_Span(
                        name=name,
                        start_us=(start - self._t0) * 1e6,
                        dur_us=(end - start) * 1e6,
                        tid=threading.get_ident() % 100000,
                        args=args or None,
                    ))

    def timeit(self, name: str, fn, *a, **kw):
        with self.span(name):
            return fn(*a, **kw)

    @property
    def spans(self) -> List[_Span]:
        with self._lock:
            return list(self._spans)

    def summary(self) -> dict:
        """name -> {count, total_ms, mean_ms} (ref: printOutDashboard)."""
        agg: dict = {}
        for s in self.spans:
            d = agg.setdefault(s.name, {"count": 0, "total_ms": 0.0})
            d["count"] += 1
            d["total_ms"] += s.dur_us / 1000.0
        for d in agg.values():
            d["mean_ms"] = d["total_ms"] / d["count"]
        return agg

    def export_chrome_trace(self, path: str, tracer=None) -> str:
        """Chrome-trace JSON of the collected spans (pid 1). Pass a
        ``serving.tracing.Tracer`` to merge its retained request traces
        into the same file on the same perf_counter clock — serving lanes
        (one pid per engine, one tid per request) render beside the
        training spans in one Perfetto view."""
        events = [{"name": s.name, "ph": "X", "ts": s.start_us,
                   "dur": s.dur_us, "pid": 1, "tid": s.tid,
                   **({"args": s.args} if s.args else {})}
                  for s in self.spans]
        if tracer is not None:
            # name this process's lane only in the merged view (the
            # plain export stays exactly the span events)
            events.append({"ph": "M", "name": "process_name", "pid": 1,
                           "args": {"name": "training"}})
            events.extend(tracer.chrome_events(t0=self._t0))
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
        return path


@contextmanager
def device_trace(logdir: str):
    """XLA kernel-level profile → TensorBoard profile plugin
    (jax.profiler.trace). Works on TPU and CPU backends."""
    with jax.profiler.trace(logdir):
        yield


class ProfilingListener(TrainingListener):
    """Per-iteration spans + panic checks as a listener (ref: the reference
    enables OpProfiler globally via Nd4j environment; here it attaches to the
    fit loop it should watch)."""

    def __init__(self, profiler: Optional[OpProfiler] = None,
                 config: Optional[ProfilerConfig] = None,
                 checkParams: bool = True, checkGradients: bool = True):
        self.profiler = profiler or OpProfiler.getInstance()
        if config is not None:
            self.profiler.config = config
        self.checkParams = checkParams
        self.checkGradients = checkGradients
        self._last_t: Optional[float] = None

    @property
    def requiresGradients(self) -> bool:
        cfg = self.profiler.config
        return self.checkGradients and (cfg.checkForNAN or cfg.checkForINF)

    def iterationDone(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._last_t is not None and self.profiler.config.collectSpans:
            with self.profiler._lock:
                self.profiler._spans.append(_Span(
                    name="iteration",
                    start_us=(self._last_t - self.profiler._t0) * 1e6,
                    dur_us=(now - self._last_t) * 1e6,
                    tid=0, args={"iteration": iteration, "epoch": epoch}))
        self._last_t = now

        cfg = self.profiler.config
        if not (cfg.checkForNAN or cfg.checkForINF):
            return
        score = model.score()
        if cfg.checkForNAN and np.isnan(score):
            raise PanicException(f"NaN score at iteration {iteration} (panic mode)")
        if cfg.checkForINF and np.isinf(score):
            raise PanicException(f"Inf score at iteration {iteration} (panic mode)")
        if self.checkParams:
            check_tree_finite(model._params, f"parameters@iter{iteration}",
                              cfg.checkForNAN, cfg.checkForINF)
        grads = getattr(model, "_last_grads", None)
        if self.checkGradients and grads is not None:
            check_tree_finite(grads, f"gradients@iter{iteration}",
                              cfg.checkForNAN, cfg.checkForINF)


def mfu(tokens_per_sec: float, flops_per_token: float,
        peak_flops: float = 197e12) -> float:
    """Model FLOPs utilization. ``peak_flops`` defaults to one TPU v5e chip
    (197 TFLOP/s bf16)."""
    return tokens_per_sec * flops_per_token / peak_flops


# ---- THE single flop-counting basis for committed MFU numbers --------
# Round-5 verdict #5: bench.py quoted analytic-flop MFU (~61%) while the
# profile artifact quoted XLA-counted MFU (56.6%) for the same workload,
# with neither stating its basis. Every committed headline MFU now uses
# ``MFU_BASIS`` below; XLA cost-analysis numbers are reported alongside as
# ``mfu_xla`` (XLA counts implementation flops — e.g. attention-softmax
# rebuilds, remat — so it sits a few points off the analytic model number;
# both are valid, they answer different questions).

MFU_BASIS = "analytic_model_flops: 6*N_nonemb + 12*L*H*T per token"

# bf16 peak FLOP/s by TPU generation (fallback: v5e)
PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}


def peak_flops(device) -> float:
    """bf16 peak for a jax device (by device_kind; v5e fallback)."""
    kind = getattr(device, "device_kind", "").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 197e12


def transformer_flops_per_token(n_params_non_embedding: int, layers: int,
                                hidden: int, seq_len: int) -> float:
    """Analytic model flops per trained token for a dense transformer:
    6*N (fwd 2N + bwd 4N matmul flops on non-embedding params) plus the
    attention interior 12*L*H*T (QK^T + PV, fwd+bwd). The standard
    PaLM-appendix accounting; no remat recompute included."""
    return 6 * n_params_non_embedding + 12 * layers * hidden * seq_len


def non_embedding_params(params, cfg) -> int:
    """Non-embedding parameter count for the flagship transformer pytree —
    the N in ``transformer_flops_per_token``. One definition shared by
    bench.py and tools/profile_flagship.py (embedding lookups do ~0 matmul
    flops, so tok/pos embedding tables are excluded; the untied lm_head
    stays in)."""
    import jax

    total = sum(int(x.size) for x in jax.tree.leaves(params))
    return total - cfg.vocab_size * cfg.hidden - cfg.max_seq * cfg.hidden
