"""Input types + shape inference (ref: org.deeplearning4j.nn.conf.inputs.InputType
and the preprocessor auto-insertion logic in MultiLayerConfiguration).

An InputType flows through the layer configs at build time: each layer reports
its output type, nIn fields are filled automatically, and format adapters
(flatten CNN->FF etc. — the reference's InputPreProcessors) are inserted where
the kinds disagree. CNN layout is NCHW (reference default)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class InputType:
    kind: str  # 'ff' | 'cnn' | 'cnn3d' | 'rnn'
    size: int = 0  # ff feature count / rnn feature size
    channels: int = 0
    height: int = 0
    width: int = 0
    depth: int = 0
    timeSeriesLength: int = -1  # -1 = variable

    @staticmethod
    def feedForward(size: int) -> "InputType":
        return InputType("ff", size=size)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", channels=channels, height=height, width=width)

    @staticmethod
    def convolutionalFlat(height: int, width: int, channels: int) -> "InputType":
        """Flattened-image input, e.g. MNIST (B, 784) — the network reshapes to
        NCHW before the first layer (ref: InputType.convolutionalFlat +
        FeedForwardToCnnPreProcessor auto-insertion)."""
        return InputType("cnnflat", channels=channels, height=height, width=width)

    def as_cnn(self) -> "InputType":
        if self.kind == "cnnflat":
            return InputType.convolutional(self.height, self.width, self.channels)
        return self

    @staticmethod
    def convolutional3D(depth: int, height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn3d", channels=channels, height=height, width=width, depth=depth)

    @staticmethod
    def recurrent(size: int, timeSeriesLength: int = -1) -> "InputType":
        return InputType("rnn", size=size, timeSeriesLength=timeSeriesLength)

    @staticmethod
    def convolutionalSequence(height: int, width: int, channels: int,
                              timeSeriesLength: int = -1) -> "InputType":
        """Sequence of images (B, T, C, H, W) — the ConvLSTM2D input
        (ref: KerasConvLSTM2D's 5D input; upstream InputType has no distinct
        kind, the importer there juggles preprocessors instead)."""
        return InputType("cnnseq", channels=channels, height=height,
                         width=width, timeSeriesLength=timeSeriesLength)

    def flat_size(self) -> int:
        if self.kind == "ff":
            return self.size
        if self.kind in ("cnn", "cnnseq"):  # cnnseq: per-frame feature count
            return self.channels * self.height * self.width
        if self.kind == "cnn3d":
            return self.channels * self.depth * self.height * self.width
        return self.size

    def array_shape(self, batch: int = 1):
        if self.kind == "ff":
            return (batch, self.size)
        if self.kind == "cnn":
            return (batch, self.channels, self.height, self.width)
        if self.kind == "cnn3d":
            return (batch, self.channels, self.depth, self.height, self.width)
        if self.kind == "cnnseq":
            t = self.timeSeriesLength if self.timeSeriesLength > 0 else 1
            return (batch, t, self.channels, self.height, self.width)
        t = self.timeSeriesLength if self.timeSeriesLength > 0 else 1
        return (batch, t, self.size)

    def to_dict(self):
        return {"kind": self.kind, **{k: v for k, v in self.__dict__.items() if k != "kind"}}

    @staticmethod
    def from_dict(d):
        return InputType(**d)
