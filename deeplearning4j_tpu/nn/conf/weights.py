"""Weight initialization (ref: org.deeplearning4j.nn.weights.WeightInit enum +
WeightInitUtil; dl4j's XAVIER is gaussian sqrt(2/(fanIn+fanOut))).

All initializers are pure functions of an explicit PRNG key (threefry),
deterministic per seed — matching the reference's seeded-init reproducibility
contract (ref: NeuralNetConfiguration.Builder.seed)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def init(name: str, key, shape, fan_in: int, fan_out: int, dtype=jnp.float32):
    """Initialize a weight tensor per the dl4j WeightInit scheme ``name``."""
    name = str(name).upper()
    if name == "ZERO":
        return jnp.zeros(shape, dtype)
    if name == "ONES":
        return jnp.ones(shape, dtype)
    if name == "XAVIER":  # dl4j: gaussian, std = sqrt(2/(fanIn+fanOut))
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return jax.random.normal(key, shape, dtype) * std
    if name == "XAVIER_UNIFORM":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "XAVIER_FAN_IN":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if name in ("RELU", "HE_NORMAL"):  # He: std = sqrt(2/fanIn)
        return jax.random.normal(key, shape, dtype) * math.sqrt(2.0 / fan_in)
    if name in ("RELU_UNIFORM", "HE_UNIFORM"):
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "LECUN_NORMAL":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if name == "LECUN_UNIFORM":
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "UNIFORM":  # dl4j legacy: U(-a, a), a = 1/sqrt(fanIn)
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "NORMAL":  # dl4j: N(0, 1/sqrt(fanIn))
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if name == "SIGMOID_UNIFORM":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if name == "IDENTITY":
        assert len(shape) == 2 and shape[0] == shape[1]
        return jnp.eye(shape[0], dtype=dtype)
    raise ValueError(f"unknown WeightInit: {name}")
