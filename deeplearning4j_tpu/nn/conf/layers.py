"""Layer configuration classes (ref: org.deeplearning4j.nn.conf.layers.* — the
~80-class config DSL) fused with their runtime implementations (ref:
org.deeplearning4j.nn.layers.* mirror tree).

The reference splits config (conf.layers.DenseLayer) from runtime
(nn.layers.feedforward.dense.DenseLayer); on TPU the runtime half collapses to
a pure ``apply(params, x) -> y`` traced under jit, so each config class here
carries its own init/apply — one class per reference pair:

- ``init_params(key, dtype)``   — parameter pytree (ref: nn.params.*ParamInitializer)
- ``init_state(dtype)``         — non-trainable state (BN running stats; norm
  statistics are kept >= fp32 even when ``dtype`` is bf16)
- ``apply(params, x, ...)``     — forward; gradients come from jax.grad, so the
  reference's per-layer ``backpropGradient`` has no analog (deleted by design)
- ``output_type(input)``        — shape inference (ref: InputType.getOutputType)
- ``set_n_in(input)``           — nIn auto-fill (ref: overrideNinUponBuild)

JSON round-trip via to_dict/from_dict (ref: Jackson serde of layer confs).

Layout conventions: CNN = NCHW + OIHW kernels (reference default); RNN
sequences = (batch, time, features) a.k.a. NWC — TPU-native default, with NCW
(the reference's [b, size, t]) accepted via ``rnnDataFormat``.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import weights as _winit
from deeplearning4j_tpu.ops import nn_defs as _nnops
from deeplearning4j_tpu.train import activations as _act
from deeplearning4j_tpu.train import losses as _losses


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v, v)


def _conv_out(size, k, s, p, mode):
    if mode == "Same":
        return -(-size // s)  # ceil
    return (size + 2 * p - k) // s + 1


@dataclass
class Layer:
    """Base layer config. Fields with None inherit the builder's globals
    (ref: NeuralNetConfiguration.Builder global defaults)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weightInit: Optional[str] = None
    biasInit: Optional[float] = None
    dropOut: Optional[float] = None  # RETAIN probability (dl4j semantics)

    # ---- build-time plumbing
    def inherit(self, globals_: dict):
        for k, v in globals_.items():
            if hasattr(self, k) and getattr(self, k) is None:
                setattr(self, k, v)

    def set_n_in(self, input_type: InputType):
        pass

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    # ---- params
    def init_params(self, key, dtype=jnp.float32) -> dict:
        return {}

    def init_state(self, dtype=jnp.float32) -> dict:
        return {}

    def regularizable(self) -> Tuple[str, ...]:
        return ("W",)

    def n_params(self) -> int:
        import numpy as np
        key = jax.random.key(0)
        p = self.init_params(key)
        return int(sum(np.prod(v.shape) for v in jax.tree_util.tree_leaves(p)))

    # ---- runtime
    def apply(self, params, x, *, training=False, rng=None, state=None):
        raise NotImplementedError

    def _activate(self, z):
        return _act.get(self.activation or "IDENTITY")(z)

    # ---- serde
    def to_dict(self) -> dict:
        from deeplearning4j_tpu.nn.conf.dropout import IDropout
        out = {"@type": type(self).__name__}
        for k, v in self.__dict__.items():
            if isinstance(v, Layer):
                out[k] = v.to_dict()
            elif isinstance(v, IDropout):
                out[k] = v.to_dict()
            elif isinstance(v, tuple):
                out[k] = list(v)
            else:
                out[k] = v
        return out

    @staticmethod
    def from_dict(d: dict) -> "Layer":
        d = dict(d)
        cls = LAYER_TYPES[d.pop("@type")]
        frozen = d.pop("frozen", False)  # set dynamically by TransferLearning
        for k, v in list(d.items()):
            if isinstance(v, dict) and "@dropout" in v:
                from deeplearning4j_tpu.nn.conf.dropout import IDropout
                d[k] = IDropout.from_dict(v)
            elif isinstance(v, dict) and "@type" in v:
                d[k] = Layer.from_dict(v)
            elif isinstance(v, list) and k in ("kernelSize", "stride", "padding", "dilation",
                                               "size", "cropping", "blocks", "poolingDimensions",
                                               "targetShape", "permuteDims"):
                d[k] = tuple(v)
        obj = cls(**d)
        if frozen:
            obj.frozen = True
        return obj


@dataclass
class FeedForwardLayer(Layer):
    nIn: int = 0
    nOut: int = 0

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.flat_size() if input_type.kind != "rnn" else input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.recurrent(self.nOut, input_type.timeSeriesLength)
        return InputType.feedForward(self.nOut)


@dataclass
class DenseLayer(FeedForwardLayer):
    """(ref: conf.layers.DenseLayer / nn.layers.feedforward.dense.DenseLayer)"""
    hasBias: bool = True

    def init_params(self, key, dtype=jnp.float32):
        kW, _ = jax.random.split(key)
        p = {"W": _winit.init(self.weightInit or "XAVIER", kW, (self.nIn, self.nOut),
                              self.nIn, self.nOut, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        z = jnp.matmul(x, params["W"])
        if self.hasBias:
            z = z + params["b"]
        return self._activate(z), state


@dataclass
class EmbeddingLayer(FeedForwardLayer):
    """Index -> dense row (ref: conf.layers.EmbeddingLayer). Input: (B,) or
    (B,1) integer indices."""
    hasBias: bool = False

    def init_params(self, key, dtype=jnp.float32):
        return {"W": _winit.init(self.weightInit or "XAVIER", key, (self.nIn, self.nOut),
                                 self.nIn, self.nOut, dtype)}

    def apply(self, params, x, *, training=False, rng=None, state=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        return self._activate(jnp.take(params["W"], idx, axis=0)), state


@dataclass
class EmbeddingSequenceLayer(FeedForwardLayer):
    """Sequence of indices -> sequence of rows (ref: EmbeddingSequenceLayer).
    Input (B, T) ints -> (B, T, nOut)."""
    inputLength: int = -1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.nOut, input_type.timeSeriesLength)

    def init_params(self, key, dtype=jnp.float32):
        return {"W": _winit.init(self.weightInit or "XAVIER", key, (self.nIn, self.nOut),
                                 self.nIn, self.nOut, dtype)}

    def apply(self, params, x, *, training=False, rng=None, state=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        return self._activate(jnp.take(params["W"], idx, axis=0)), state


# --------------------------------------------------------------------- CNN


def needs_flatten(layer, x_ndim: int) -> bool:
    """CNN->FF flatten-adapter predicate (ref: CnnToFeedForwardPreProcessor
    auto-insertion): spatial (4/5-dim) input into a dense-style layer is
    flattened unless the layer consumes spatial input itself."""
    return x_ndim in (4, 5) and isinstance(layer, FeedForwardLayer) \
        and not getattr(layer, "spatial_input", False) \
        and not isinstance(layer, (BaseRecurrentLayer, BatchNormalization))


@dataclass
class ConvolutionLayer(FeedForwardLayer):
    """2D conv, NCHW/OIHW (ref: conf.layers.ConvolutionLayer ->
    libnd4j conv2d; here lax.conv_general_dilated -> MXU)."""
    spatial_input = True
    kernelSize: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolutionMode: str = "Truncate"  # Truncate | Same (ref: ConvolutionMode)
    hasBias: bool = True

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        k, s, p = _pair(self.kernelSize), _pair(self.stride), _pair(self.padding)
        h = _conv_out(input_type.height, k[0], s[0], p[0], self.convolutionMode)
        w = _conv_out(input_type.width, k[1], s[1], p[1], self.convolutionMode)
        return InputType.convolutional(h, w, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        k = _pair(self.kernelSize)
        fan_in = self.nIn * k[0] * k[1]
        fan_out = self.nOut * k[0] * k[1]
        p = {"W": _winit.init(self.weightInit or "XAVIER", key,
                              (self.nOut, self.nIn, k[0], k[1]), fan_in, fan_out, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def _padding_arg(self):
        if self.convolutionMode == "Same":
            return "SAME"
        p = _pair(self.padding)
        return [(p[0], p[0]), (p[1], p[1])]

    def apply(self, params, x, *, training=False, rng=None, state=None):
        z = _nnops.conv2d(x, params["W"], params.get("b"), strides=_pair(self.stride),
                          padding=self._padding_arg(), dilation=_pair(self.dilation))
        return self._activate(z), state


@dataclass
class Convolution1DLayer(FeedForwardLayer):
    """1D conv over (B, T, C) sequences (ref: Convolution1DLayer)."""
    kernelSize: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolutionMode: str = "Same"
    hasBias: bool = True

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeSeriesLength
        if t > 0:
            t = _conv_out(t, self.kernelSize, self.stride, self.padding, self.convolutionMode)
        return InputType.recurrent(self.nOut, t)

    def init_params(self, key, dtype=jnp.float32):
        fan_in = self.nIn * self.kernelSize
        p = {"W": _winit.init(self.weightInit or "XAVIER", key,
                              (self.nOut, self.nIn, self.kernelSize),
                              fan_in, self.nOut * self.kernelSize, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        xc = jnp.swapaxes(x, 1, 2)  # (B,T,C) -> (B,C,T)
        pad = "SAME" if self.convolutionMode == "Same" else [(self.padding, self.padding)]
        z = _nnops.conv1d(xc, params["W"], params.get("b"), stride=self.stride,
                          padding=pad, dilation=self.dilation)
        return self._activate(jnp.swapaxes(z, 1, 2)), state


@dataclass
class Deconvolution2D(ConvolutionLayer):
    """Transposed conv (ref: conf.layers.Deconvolution2D)."""

    def output_type(self, input_type: InputType) -> InputType:
        k, s, p = _pair(self.kernelSize), _pair(self.stride), _pair(self.padding)
        if self.convolutionMode == "Same":
            h, w = input_type.height * s[0], input_type.width * s[1]
        else:
            h = s[0] * (input_type.height - 1) + k[0] - 2 * p[0]
            w = s[1] * (input_type.width - 1) + k[1] - 2 * p[1]
        return InputType.convolutional(h, w, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        k = _pair(self.kernelSize)
        fan_in = self.nIn * k[0] * k[1]
        p = {"W": _winit.init(self.weightInit or "XAVIER", key,
                              (self.nIn, self.nOut, k[0], k[1]), fan_in,
                              self.nOut * k[0] * k[1], dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        dn = lax.conv_dimension_numbers(x.shape, params["W"].shape, ("NCHW", "IOHW", "NCHW"))
        pad = self._padding_arg()
        if isinstance(pad, list):
            pad = [(p0, p1) for (p0, p1) in pad]
        z = lax.conv_transpose(x, params["W"], strides=_pair(self.stride), padding=pad,
                               dimension_numbers=dn)
        if self.hasBias:
            z = z + params["b"].reshape(1, -1, 1, 1)
        return self._activate(z), state


@dataclass
class DepthwiseConvolution2D(ConvolutionLayer):
    """(ref: conf.layers.DepthwiseConvolution2D); nOut = nIn * depthMultiplier."""
    depthMultiplier: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        self.nOut = self.nIn * self.depthMultiplier
        return super().output_type(input_type)

    def init_params(self, key, dtype=jnp.float32):
        k = _pair(self.kernelSize)
        ch = self.nIn * self.depthMultiplier
        p = {"W": _winit.init(self.weightInit or "XAVIER", key, (ch, 1, k[0], k[1]),
                              k[0] * k[1], k[0] * k[1] * self.depthMultiplier, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((ch,), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        z = _nnops.depthwise_conv2d(x, params["W"], params.get("b"), strides=_pair(self.stride),
                                    padding=self._padding_arg(), dilation=_pair(self.dilation))
        return self._activate(z), state


@dataclass
class SeparableConvolution2D(ConvolutionLayer):
    """(ref: conf.layers.SeparableConvolution2D)."""
    depthMultiplier: int = 1

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        k = _pair(self.kernelSize)
        ch = self.nIn * self.depthMultiplier
        p = {
            "dW": _winit.init(self.weightInit or "XAVIER", k1, (ch, 1, k[0], k[1]),
                              k[0] * k[1], k[0] * k[1], dtype),
            "pW": _winit.init(self.weightInit or "XAVIER", k2, (self.nOut, ch, 1, 1),
                              ch, self.nOut, dtype),
        }
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def regularizable(self):
        return ("dW", "pW")

    def apply(self, params, x, *, training=False, rng=None, state=None):
        z = _nnops.separable_conv2d(x, params["dW"], params["pW"], params.get("b"),
                                    strides=_pair(self.stride), padding=self._padding_arg())
        return self._activate(z), state


@dataclass
class SubsamplingLayer(Layer):
    """Pooling (ref: conf.layers.SubsamplingLayer; PoolingType MAX/AVG/SUM/PNORM)."""
    poolingType: str = "MAX"
    kernelSize: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolutionMode: str = "Truncate"
    pnorm: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        k, s, p = _pair(self.kernelSize), _pair(self.stride), _pair(self.padding)
        h = _conv_out(input_type.height, k[0], s[0], p[0], self.convolutionMode)
        w = _conv_out(input_type.width, k[1], s[1], p[1], self.convolutionMode)
        return InputType.convolutional(h, w, input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        if self.convolutionMode == "Same":
            pad = "SAME"
        else:
            p = _pair(self.padding)
            pad = [(p[0], p[0]), (p[1], p[1])]
        k, s = _pair(self.kernelSize), _pair(self.stride)
        if self.poolingType == "MAX":
            return _nnops._pool(x, "max", k, s, pad), state
        if self.poolingType == "AVG":
            return _nnops._pool(x, "avg", k, s, pad), state
        if self.poolingType == "SUM":
            return _nnops._pool(x, "sum", k, s, pad), state
        if self.poolingType == "PNORM":
            z = _nnops._pool(jnp.abs(x) ** self.pnorm, "sum", k, s, pad)
            return z ** (1.0 / self.pnorm), state
        raise ValueError(self.poolingType)


@dataclass
class Subsampling1DLayer(Layer):
    """1D pooling over (B,T,C) (ref: Subsampling1DLayer)."""
    poolingType: str = "MAX"
    kernelSize: int = 2
    stride: int = 2
    padding: int = 0

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeSeriesLength
        if t > 0:
            t = (t + 2 * self.padding - self.kernelSize) // self.stride + 1
        return InputType.recurrent(input_type.size, t)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        xc = jnp.swapaxes(x, 1, 2)
        pad = [(self.padding, self.padding)]
        kind = "max" if self.poolingType == "MAX" else "avg"
        z = _nnops._pool(xc, kind, (self.kernelSize,), (self.stride,), pad, "NCW")
        return jnp.swapaxes(z, 1, 2), state


@dataclass
class BatchNormalization(FeedForwardLayer):
    """(ref: conf.layers.BatchNormalization; decay 0.9 hmm — dl4j 'decay' is
    the running-average momentum; eps 1e-5). Works on FF (B,F) and CNN NCHW
    (per-channel). Running stats live in layer state, updated in training."""
    decay: float = 0.9
    eps: float = 1e-5
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lockGammaBeta: bool = False
    # channel placement for rank-3 (sequence) activations — BN is otherwise
    # layout-blind and cannot tell (B,T,C) from (B,C,T) at runtime
    rnnDataFormat: str = "NWC"

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = (input_type.channels
                        if input_type.kind in ("cnn", "cnn3d")
                        else input_type.flat_size())
        self.nOut = self.nIn

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init_params(self, key, dtype=jnp.float32):
        if self.lockGammaBeta:
            return {}
        return {"gamma": jnp.full((self.nIn,), self.gamma_init, dtype),
                "beta": jnp.full((self.nIn,), self.beta_init, dtype)}

    def init_state(self, dtype=jnp.float32):
        # norm statistics stay >= fp32 even for HALF networks (standard mixed-
        # precision practice): bf16 EMA would quantize away small corrections
        stat_dtype = jnp.float32 if dtype == jnp.bfloat16 else dtype
        return {"mean": jnp.zeros((self.nIn,), stat_dtype),
                "var": jnp.ones((self.nIn,), stat_dtype)}

    def regularizable(self):
        return ()

    def apply(self, params, x, *, training=False, rng=None, state=None):
        # stats over every non-channel axis. (B,F); rank-3 sequences follow
        # rnnDataFormat (default NWC, the framework's inter-layer layout);
        # NCHW/NCDHW channels-first.
        # != "NCW" so unrecognized values degrade to NWC like the sibling
        # recurrent layers, not silently to channels-first
        if x.ndim == 3 and self.rnnDataFormat != "NCW":
            axes = (0, 1)
            shape = [1, 1, -1]
        else:
            axes = (0,) if x.ndim == 2 else (0,) + tuple(range(2, x.ndim))
            shape = [1, -1] + [1] * (x.ndim - 2)
        if training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            new_state = {"mean": self.decay * state["mean"] + (1 - self.decay) * mean,
                         "var": self.decay * state["var"] + (1 - self.decay) * var}
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        y = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + self.eps)
        if params:
            y = y * params["gamma"].reshape(shape) + params["beta"].reshape(shape)
        return self._activate(y).astype(x.dtype), new_state


@dataclass
class LocalResponseNormalization(Layer):
    """(ref: conf.layers.LocalResponseNormalization; dl4j defaults k=2,n=5,
    alpha=1e-4,beta=0.75)."""
    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return _nnops.local_response_normalization(
            x, depth_radius=self.n // 2, bias=self.k, alpha=self.alpha, beta=self.beta), state


@dataclass
class DropoutLayer(Layer):
    """Standalone dropout (ref: conf.layers.DropoutLayer). ``dropOut`` is the
    RETAIN probability, dl4j semantics; inverted-dropout scaling."""

    def __post_init__(self):
        if self.dropOut is None:
            self.dropOut = 0.5

    def apply(self, params, x, *, training=False, rng=None, state=None):
        if not training or rng is None:
            return x, state
        from deeplearning4j_tpu.nn.conf.dropout import apply_dropout
        return apply_dropout(self.dropOut, rng, x), state


@dataclass
class ActivationLayer(Layer):
    """(ref: conf.layers.ActivationLayer). ``alpha`` parameterizes LEAKYRELU/ELU
    (ref: ActivationLReLU(alpha) etc. carry their own coefficients)."""
    alpha: Optional[float] = None

    def apply(self, params, x, *, training=False, rng=None, state=None):
        if self.alpha is not None and (self.activation or "").upper() == "LEAKYRELU":
            return jax.nn.leaky_relu(x, self.alpha), state
        if self.alpha is not None and (self.activation or "").upper() == "ELU":
            return jax.nn.elu(x, self.alpha), state
        if (self.activation or "").upper() == "THRESHOLDEDRELU":
            theta = self.alpha if self.alpha is not None else 1.0
            return jnp.where(x > theta, x, 0.0), state
        return self._activate(x), state


@dataclass
class Upsampling2D(Layer):
    size: Tuple[int, int] = (2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        s = _pair(self.size)
        return InputType.convolutional(input_type.height * s[0], input_type.width * s[1],
                                       input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return _nnops.upsampling2d(x, _pair(self.size)), state


@dataclass
class ZeroPaddingLayer(Layer):
    padding: Tuple[int, int, int, int] = (0, 0, 0, 0)  # top,bottom,left,right

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self.padding
        return InputType.convolutional(input_type.height + t + b, input_type.width + l + r,
                                       input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        t, b, l, r = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r))), state


@dataclass
class Cropping2D(Layer):
    cropping: Tuple[int, int, int, int] = (0, 0, 0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        t, b, l, r = self.cropping
        return InputType.convolutional(input_type.height - t - b, input_type.width - l - r,
                                       input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        t, b, l, r = self.cropping
        return x[:, :, t:x.shape[2] - b, l:x.shape[3] - r], state


@dataclass
class GlobalPoolingLayer(Layer):
    """(ref: conf.layers.GlobalPoolingLayer) — pools RNN over time or CNN over
    space. Supports masked mean/max for variable-length sequences."""
    poolingType: str = "AVG"
    pnorm: int = 2
    collapseDimensions: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        if input_type.kind == "rnn":
            return InputType.feedForward(input_type.size)
        if input_type.kind == "cnn":
            return InputType.feedForward(input_type.channels)
        return input_type

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        if x.ndim == 3:  # (B,T,F) over time
            axes = (1,)
            if mask is not None:
                m = mask[:, :, None]
                if self.poolingType == "AVG":
                    return jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0), state
                if self.poolingType == "MAX":
                    return jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1), state
        else:  # NCHW over spatial
            axes = tuple(range(2, x.ndim))
        if self.poolingType == "AVG":
            return jnp.mean(x, axis=axes), state
        if self.poolingType == "MAX":
            return jnp.max(x, axis=axes), state
        if self.poolingType == "SUM":
            return jnp.sum(x, axis=axes), state
        if self.poolingType == "PNORM":
            return jnp.sum(jnp.abs(x) ** self.pnorm, axis=axes) ** (1.0 / self.pnorm), state
        raise ValueError(self.poolingType)


# --------------------------------------------------------------------- RNN


@dataclass
class BaseRecurrentLayer(FeedForwardLayer):
    rnnDataFormat: str = "NWC"  # (B,T,F); "NCW" = reference layout [b,size,t]

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.nOut, input_type.timeSeriesLength)

    def _to_nwc(self, x):
        return jnp.swapaxes(x, 1, 2) if self.rnnDataFormat == "NCW" else x

    def _from_nwc(self, x):
        return jnp.swapaxes(x, 1, 2) if self.rnnDataFormat == "NCW" else x

    # -- streaming/tBPTT state surface (ref: BaseRecurrentLayer.stateMap /
    #    tBpttStateMap + rnnTimeStep/rnnActivateUsingStoredState)
    def init_rnn_state(self, batch: int, dtype=jnp.float32) -> dict:
        return {"h": jnp.zeros((batch, self.nOut), dtype)}

    def apply_rnn(self, params, x, rnn_state: dict, *, mask=None):
        """Run the recurrence from ``rnn_state``; returns (ys, final_state)."""
        raise NotImplementedError


@dataclass
class LSTM(BaseRecurrentLayer):
    """(ref: conf.layers.LSTM -> fused libnd4j lstmLayer; here one lax.scan).
    Default activation tanh; gate activation sigmoid (fixed, as reference)."""
    forgetGateBiasInit: float = 1.0

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        H = self.nOut
        p = {
            "W": _winit.init(self.weightInit or "XAVIER", k1, (self.nIn, 4 * H),
                             self.nIn, 4 * H, dtype),
            "RW": _winit.init(self.weightInit or "XAVIER", k2, (H, 4 * H), H, 4 * H, dtype),
        }
        b = jnp.zeros((4 * H,), dtype)
        b = b.at[H:2 * H].set(self.forgetGateBiasInit)  # [i,f,g,o] gate order
        p["b"] = b
        return p

    def regularizable(self):
        return ("W", "RW")

    def init_rnn_state(self, batch: int, dtype=jnp.float32) -> dict:
        H = self.nOut
        return {"h": jnp.zeros((batch, H), dtype), "c": jnp.zeros((batch, H), dtype)}

    def apply_rnn(self, params, x, rnn_state, *, mask=None):
        x = self._to_nwc(x)
        ys, (hT, cT) = _nnops.lstm_layer(x, rnn_state["h"], rnn_state["c"],
                                         params["W"], params["RW"], params["b"], mask=mask)
        return self._from_nwc(ys), {"h": hT, "c": cT}

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None,
              initial_state=None):
        B = x.shape[0]
        rs = self.init_rnn_state(B, x.dtype) if initial_state is None else \
            {"h": initial_state[0], "c": initial_state[1]}
        ys, _ = self.apply_rnn(params, x, rs, mask=mask)
        return ys, state


@dataclass
class GravesLSTM(LSTM):
    """LSTM with peephole connections (ref: conf.layers.GravesLSTM)."""

    def init_params(self, key, dtype=jnp.float32):
        p = super().init_params(key, dtype)
        H = self.nOut
        p["pI"] = jnp.zeros((H,), dtype)
        p["pF"] = jnp.zeros((H,), dtype)
        p["pO"] = jnp.zeros((H,), dtype)
        return p

    def apply_rnn(self, params, x, rnn_state, *, mask=None):
        x = self._to_nwc(x)
        h0, c0 = rnn_state["h"], rnn_state["c"]
        W, RW, b = params["W"], params["RW"], params["b"]
        pI, pF, pO = params["pI"], params["pF"], params["pO"]

        xs = jnp.swapaxes(x, 0, 1)  # (T,B,F)
        ms = jnp.swapaxes(mask, 0, 1) if mask is not None else None

        def step(carry, inp):
            h, c = carry
            xt, mt = inp if ms is not None else (inp, None)
            z = jnp.matmul(xt, W) + jnp.matmul(h, RW) + b
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i = jax.nn.sigmoid(i + pI * c)
            f = jax.nn.sigmoid(f + pF * c)
            g = jnp.tanh(g)
            c2 = f * c + i * g
            o = jax.nn.sigmoid(o + pO * c2)
            h2 = o * jnp.tanh(c2)
            if mt is not None:
                m = mt[:, None]
                h2 = jnp.where(m > 0, h2, h)
                c2 = jnp.where(m > 0, c2, c)
            return (h2, c2), h2

        (hT, cT), ys = lax.scan(step, (h0, c0), (xs, ms) if ms is not None else xs)
        return self._from_nwc(jnp.swapaxes(ys, 0, 1)), {"h": hT, "c": cT}


@dataclass
class SimpleRnn(BaseRecurrentLayer):
    """(ref: conf.layers.SimpleRnn)."""

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        p = {"W": _winit.init(self.weightInit or "XAVIER", k1, (self.nIn, self.nOut),
                              self.nIn, self.nOut, dtype),
             "RW": _winit.init(self.weightInit or "XAVIER", k2, (self.nOut, self.nOut),
                               self.nOut, self.nOut, dtype),
             "b": jnp.full((self.nOut,), self.biasInit or 0.0, dtype)}
        return p

    def regularizable(self):
        return ("W", "RW")

    def apply_rnn(self, params, x, rnn_state, *, mask=None):
        x = self._to_nwc(x)
        act = _act.get(self.activation or "TANH")
        ys, hT = _nnops.simple_rnn(x, rnn_state["h"], params["W"], params["RW"],
                                   params["b"], activation=act)
        return self._from_nwc(ys), {"h": hT}

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None,
              initial_state=None):
        rs = self.init_rnn_state(x.shape[0], x.dtype) if initial_state is None \
            else {"h": initial_state}
        ys, _ = self.apply_rnn(params, x, rs, mask=mask)
        return ys, state


@dataclass
class GRU(BaseRecurrentLayer):
    """(ref: libnd4j gru op; dl4j exposes via samediff) ."""

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        H = self.nOut
        return {"W": _winit.init(self.weightInit or "XAVIER", k1, (self.nIn, 3 * H),
                                 self.nIn, 3 * H, dtype),
                "RW": _winit.init(self.weightInit or "XAVIER", k2, (H, 3 * H), H, 3 * H, dtype),
                "bi": jnp.zeros((3 * H,), dtype), "bh": jnp.zeros((3 * H,), dtype)}

    def regularizable(self):
        return ("W", "RW")

    def apply_rnn(self, params, x, rnn_state, *, mask=None):
        x = self._to_nwc(x)
        ys, hT = _nnops.gru_layer(x, rnn_state["h"], params["W"], params["RW"],
                                  params["bi"], params["bh"])
        return self._from_nwc(ys), {"h": hT}

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None,
              initial_state=None):
        rs = self.init_rnn_state(x.shape[0], x.dtype) if initial_state is None \
            else {"h": initial_state}
        ys, _ = self.apply_rnn(params, x, rs, mask=mask)
        return ys, state


@dataclass
class Bidirectional(Layer):
    """Wrapper running a recurrent layer in both directions (ref:
    conf.layers.recurrent.Bidirectional; Mode CONCAT/ADD/MUL/AVERAGE)."""
    fwd: Optional[Layer] = None
    mode: str = "CONCAT"

    def __post_init__(self):
        if self.fwd is not None and not isinstance(self.fwd, Layer):
            self.fwd = Layer.from_dict(self.fwd)

    def inherit(self, globals_: dict):
        super().inherit(globals_)
        self.fwd.inherit(globals_)

    def set_n_in(self, input_type: InputType):
        self.fwd.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        out = self.fwd.output_type(input_type)
        if self.mode == "CONCAT":
            return InputType.recurrent(out.size * 2, out.timeSeriesLength)
        return out

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        return {"fwd": self.fwd.init_params(k1, dtype), "bwd": self.fwd.init_params(k2, dtype)}

    def regularizable(self):
        return ()

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        yf, _ = self.fwd.apply(params["fwd"], x, training=training, rng=rng, state=None,
                               mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = jnp.flip(mask, axis=1) if mask is not None else None
        yb, _ = self.fwd.apply(params["bwd"], xr, training=training, rng=rng, state=None,
                               mask=mr)
        yb = jnp.flip(yb, axis=1)
        if self.mode == "CONCAT":
            return jnp.concatenate([yf, yb], axis=-1), state
        if self.mode == "ADD":
            return yf + yb, state
        if self.mode == "MUL":
            return yf * yb, state
        if self.mode == "AVERAGE":
            return 0.5 * (yf + yb), state
        raise ValueError(self.mode)


@dataclass
class ConvLSTM2D(Layer):
    """Convolutional LSTM over image sequences (ref: the reference ships
    this via its Keras importer, KerasConvLSTM2D; Shi et al. 2015). Input
    (B, T, C, H, W); gates are SAME-padded convolutions instead of matmuls,
    the time recurrence is one lax.scan. ``returnSequences=False`` emits the
    final hidden map (B, nOut, H, W) — a drop-in head for the CNN stack;
    True emits (B, T, nOut, H, W) for stacked ConvLSTMs. Gate order
    [i, f, g(c), o], matching LSTM/Keras."""
    nIn: int = 0
    nOut: int = 0
    kernelSize: Tuple[int, int] = (3, 3)
    returnSequences: bool = False
    forgetGateBiasInit: float = 1.0

    def set_n_in(self, input_type: InputType):
        if not self.nIn and input_type.kind == "cnnseq":
            self.nIn = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        if self.returnSequences:
            return InputType.convolutionalSequence(
                input_type.height, input_type.width, self.nOut,
                input_type.timeSeriesLength)
        return InputType.convolutional(input_type.height, input_type.width,
                                       self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        kh, kw = self.kernelSize
        C, H4 = self.nIn, 4 * self.nOut
        fan_in = C * kh * kw
        p = {
            "W": _winit.init(self.weightInit or "XAVIER", k1, (H4, C, kh, kw),
                             fan_in, H4, dtype),
            "RW": _winit.init(self.weightInit or "XAVIER", k2,
                              (H4, self.nOut, kh, kw),
                              self.nOut * kh * kw, H4, dtype),
        }
        b = jnp.zeros((H4,), dtype)
        b = b.at[self.nOut:2 * self.nOut].set(self.forgetGateBiasInit)
        p["b"] = b
        return p

    def regularizable(self):
        return ("W", "RW")

    def apply(self, params, x, *, training=False, rng=None, state=None):
        if x.ndim != 5:
            raise ValueError(
                f"ConvLSTM2D expects (B, T, C, H, W), got rank {x.ndim}")
        B, T, C, H, W = x.shape
        nOut = self.nOut
        dn = lax.conv_dimension_numbers((B, C, H, W), params["W"].shape,
                                        ("NCHW", "OIHW", "NCHW"))

        def conv(inp, w):
            return lax.conv_general_dilated(inp, w, (1, 1), "SAME",
                                            dimension_numbers=dn)

        def step(carry, xt):
            h, c = carry
            z = conv(xt, params["W"]) + conv(h, params["RW"]) \
                + params["b"][None, :, None, None]
            i, f, g, o = jnp.split(z, 4, axis=1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new

        h0 = jnp.zeros((B, nOut, H, W), x.dtype)
        (hT, _), ys = lax.scan(step, (h0, h0),
                               jnp.swapaxes(x, 0, 1))  # scan over T
        if self.returnSequences:
            return jnp.swapaxes(ys, 0, 1), state
        return hT, state


@dataclass
class RepeatVector(Layer):
    """Repeats a (B, F) feature vector n times into a (B, n, F) sequence
    (ref: conf.layers.misc.RepeatVector — the reference stores NCW [B, F, n];
    this framework's recurrent stack is NWC, so the time axis is axis 1, the
    same tensor transposed)."""
    repetitionFactor: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(input_type.size, self.repetitionFactor)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        if x.ndim != 2:
            raise ValueError(
                f"RepeatVector expects (B, F) feed-forward input, got rank "
                f"{x.ndim} — the reference requires FF input too")
        return jnp.repeat(x[:, None, :], self.repetitionFactor, axis=1), state


@dataclass
class LastTimeStep(Layer):
    """Wrapper extracting the last (masked) timestep (ref:
    conf.layers.recurrent.LastTimeStep)."""
    underlying: Optional[Layer] = None

    def __post_init__(self):
        if self.underlying is not None and not isinstance(self.underlying, Layer):
            self.underlying = Layer.from_dict(self.underlying)

    def inherit(self, globals_: dict):
        super().inherit(globals_)
        if self.underlying:
            self.underlying.inherit(globals_)

    def set_n_in(self, input_type: InputType):
        if self.underlying:
            self.underlying.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        out = self.underlying.output_type(input_type) if self.underlying else input_type
        return InputType.feedForward(out.size)

    def init_params(self, key, dtype=jnp.float32):
        return self.underlying.init_params(key, dtype) if self.underlying else {}

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        if self.underlying:
            x, state = self.underlying.apply(params, x, training=training, rng=rng,
                                             state=state, mask=mask)
        if mask is not None:
            idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
            return x[jnp.arange(x.shape[0]), idx], state
        return x[:, -1], state


# ------------------------------------------------------------- output layers


@dataclass
class BaseOutputLayer(FeedForwardLayer):
    lossFunction: str = "MCXENT"
    hasBias: bool = True

    def __post_init__(self):
        if self.activation is None and self.lossFunction in ("MCXENT", "NEGATIVELOGLIKELIHOOD"):
            self.activation = "SOFTMAX"

    def init_params(self, key, dtype=jnp.float32):
        kW, _ = jax.random.split(key)
        p = {"W": _winit.init(self.weightInit or "XAVIER", kW, (self.nIn, self.nOut),
                              self.nIn, self.nOut, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        z = jnp.matmul(x, params["W"])
        if self.hasBias:
            z = z + params["b"]
        return self._activate(z), state

    def compute_loss(self, labels, output, mask=None):
        return _losses.get(self.lossFunction)(labels, output, mask)


@dataclass
class OutputLayer(BaseOutputLayer):
    """(ref: conf.layers.OutputLayer)."""


@dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep output (ref: conf.layers.RnnOutputLayer). Input (B,T,F)
    NWC or (B,F,T) NCW per ``rnnDataFormat`` (ref: RnnOutputLayer.dataFormat)."""
    rnnDataFormat: str = "NWC"

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.nOut, input_type.timeSeriesLength)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        ncw = self.rnnDataFormat == "NCW"
        if ncw:
            x = jnp.swapaxes(x, 1, 2)
        z = jnp.matmul(x, params["W"])
        if self.hasBias:
            z = z + params["b"]
        out = self._activate(z)
        return (jnp.swapaxes(out, 1, 2) if ncw else out), state

    def compute_loss(self, labels, output, mask=None):
        if self.rnnDataFormat == "NCW":  # loss math runs in NWC
            labels = jnp.swapaxes(labels, 1, 2)
            output = jnp.swapaxes(output, 1, 2)
        return _losses.get(self.lossFunction)(labels, output, mask)


@dataclass
class LossLayer(Layer):
    """Loss without params (ref: conf.layers.LossLayer)."""
    lossFunction: str = "MCXENT"

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return self._activate(x), state

    def compute_loss(self, labels, output, mask=None):
        return _losses.get(self.lossFunction)(labels, output, mask)


# ------------------------------------------------------------- attention


@dataclass
class SelfAttentionLayer(BaseRecurrentLayer):
    """Dot-product self-attention over sequences (ref:
    conf.layers.SelfAttentionLayer, SameDiff-backed in the reference).
    projectInput=True uses learned Q/K/V/O projections."""
    nHeads: int = 1
    headSize: int = 0
    projectInput: bool = True
    # Pallas-kernel routing for the unmasked case: None = auto (packed VMEM
    # kernel on TPU — first-order autodiff only, see
    # ops.pallas_kernels.higher_order_attention); False pins the fully
    # differentiable XLA einsum path per-layer (e.g. for HVP training);
    # True forces the kernel (interpret mode off-TPU). The kernel route
    # exists only with projectInput=True: forcing True on the unprojected
    # path raises at apply time (False is trivially satisfied there — the
    # unprojected path IS the einsum path)
    attentionKernel: Optional[bool] = None

    def output_type(self, input_type: InputType) -> InputType:
        size = self.nOut if self.projectInput else input_type.size
        return InputType.recurrent(size, input_type.timeSeriesLength)

    def init_params(self, key, dtype=jnp.float32):
        if not self.projectInput:
            return {}
        D = self.nIn
        O = self.nOut
        ks = jax.random.split(key, 4)
        wi = self.weightInit or "XAVIER"
        return {"Wq": _winit.init(wi, ks[0], (D, O), D, O, dtype),
                "Wk": _winit.init(wi, ks[1], (D, O), D, O, dtype),
                "Wv": _winit.init(wi, ks[2], (D, O), D, O, dtype),
                "Wo": _winit.init(wi, ks[3], (O, O), O, O, dtype)}

    def regularizable(self):
        return ("Wq", "Wk", "Wv", "Wo")

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        if self.projectInput:
            out = _nnops.multi_head_attention(x, x, params["Wq"], params["Wk"], params["Wv"],
                                              params["Wo"], self.nHeads, mask=mask,
                                              use_kernel=self.attentionKernel)
        else:
            # False is satisfied trivially (this IS the einsum path); only
            # forcing the kernel is unsatisfiable without projections
            if self.attentionKernel is True:
                raise ValueError(
                    "SelfAttentionLayer.attentionKernel=True requires "
                    "projectInput=True; the unprojected path has no "
                    "Pallas kernel route")
            m = mask[:, None, :] if mask is not None else None
            out = _nnops.dot_product_attention(x, x, x, mask=m)
        return out, state


# ------------------------------------------------- parametric activations etc.


@dataclass
class PReLULayer(Layer):
    """Learned leaky-ReLU slope (ref: conf.layers.PReLULayer). ``inputShape``
    is the per-example shape; ``sharedAxes`` broadcast alpha over those axes
    (1-based, as the reference counts within the example)."""
    inputShape: Tuple[int, ...] = ()
    sharedAxes: Tuple[int, ...] = ()

    def set_n_in(self, input_type: InputType):
        if not self.inputShape:
            self.inputShape = tuple(input_type.array_shape(1)[1:])

    def init_params(self, key, dtype=jnp.float32):
        shape = tuple(1 if (i + 1) in tuple(self.sharedAxes) else s
                      for i, s in enumerate(self.inputShape))
        return {"alpha": jnp.zeros(shape, dtype)}

    def regularizable(self):
        return ()

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return jnp.where(x >= 0, x, params["alpha"] * x), state


@dataclass
class ElementWiseMultiplicationLayer(FeedForwardLayer):
    """out = activation(x * w + b), elementwise learned scale (ref:
    conf.layers.misc.ElementWiseMultiplicationLayer)."""

    def __post_init__(self):
        if not self.nOut:
            self.nOut = self.nIn

    def init_params(self, key, dtype=jnp.float32):
        return {"W": jnp.ones((self.nIn,), dtype),
                "b": jnp.full((self.nIn,), self.biasInit or 0.0, dtype)}

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return self._activate(x * params["W"] + params["b"]), state


@dataclass
class MaskZeroLayer(Layer):
    """Zeroes timesteps equal to maskValue before the underlying layer (ref:
    conf.layers.util.MaskZeroLayer)."""
    underlying: Optional[Layer] = None
    maskValue: float = 0.0

    def __post_init__(self):
        if self.underlying is not None and not isinstance(self.underlying, Layer):
            self.underlying = Layer.from_dict(self.underlying)

    def inherit(self, globals_: dict):
        super().inherit(globals_)
        if self.underlying:
            self.underlying.inherit(globals_)

    def set_n_in(self, input_type: InputType):
        if self.underlying:
            self.underlying.set_n_in(input_type)

    def output_type(self, input_type: InputType) -> InputType:
        return self.underlying.output_type(input_type) if self.underlying else input_type

    def init_params(self, key, dtype=jnp.float32):
        return self.underlying.init_params(key, dtype) if self.underlying else {}

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        step_mask = jnp.any(x != self.maskValue, axis=-1)  # (B,T)
        x = x * step_mask[..., None].astype(x.dtype)
        if self.underlying:
            kwargs = {"mask": step_mask.astype(jnp.float32)} \
                if isinstance(self.underlying, BaseRecurrentLayer) else {}
            return self.underlying.apply(params, x, training=training, rng=rng,
                                         state=state, **kwargs)
        return x, state


def _keras_space_shape(t: InputType):
    """Post-batch dims of ``t`` in Keras channels-LAST coordinates."""
    k = t.kind
    if k == "ff":
        return (t.size,)
    if k == "rnn":
        return (t.timeSeriesLength, t.size)
    if k in ("cnn", "cnnflat"):
        return (t.height, t.width, t.channels)
    if k == "cnn3d":
        return (t.depth, t.height, t.width, t.channels)
    raise ValueError(f"Reshape/Permute do not support input kind {k!r}")


def _type_from_keras_shape(s) -> InputType:
    if len(s) == 1:
        return InputType.feedForward(s[0])
    if len(s) == 2:
        return InputType.recurrent(s[1], s[0])
    if len(s) == 3:
        return InputType.convolutional(s[0], s[1], s[2])
    if len(s) == 4:
        return InputType.convolutional3D(s[0], s[1], s[2], s[3])
    raise ValueError(f"Reshape/Permute target rank {len(s)} not supported")


def _to_keras_layout(x):
    if x.ndim == 4:    # NCHW -> NHWC
        return jnp.transpose(x, (0, 2, 3, 1))
    if x.ndim == 5:    # NCDHW -> NDHWC
        return jnp.transpose(x, (0, 2, 3, 4, 1))
    return x

def _from_keras_layout(y):
    if y.ndim == 4:
        return jnp.transpose(y, (0, 3, 1, 2))
    if y.ndim == 5:
        return jnp.transpose(y, (0, 4, 1, 2, 3))
    return y


@dataclass
class ReshapeLayer(Layer):
    """Keras-semantics reshape (ref: modelimport.keras.layers.core.KerasReshape
    -> ReshapePreprocessor). ``targetShape`` is the post-batch shape in Keras'
    channels-LAST coordinates (one -1 allowed); data is converted from/to this
    framework's channels-first layouts at the boundary, so a following conv
    layer sees NCHW and a following Dense sees Keras' flatten order."""
    targetShape: Tuple[int, ...] = ()

    def _resolve(self, src):
        tgt = tuple(int(v) for v in self.targetShape)
        if any(d <= 0 for d in src):
            raise ValueError(
                "ReshapeLayer needs fully-known input dims (variable-length "
                "sequence inputs are not reshapeable)")
        total = 1
        for d in src:
            total *= d
        if tgt.count(-1) > 1:
            raise ValueError(f"ReshapeLayer: at most one -1 in {tgt}")
        if -1 in tgt:
            known = 1
            for d in tgt:
                if d != -1:
                    known *= d
            if known == 0 or total % known:
                raise ValueError(f"ReshapeLayer: cannot infer -1 in {tgt} "
                                 f"from input of {total} elements")
            tgt = tuple(total // known if d == -1 else d for d in tgt)
        out = 1
        for d in tgt:
            out *= d
        if out != total:
            raise ValueError(f"ReshapeLayer: target {tgt} has {out} elements, "
                             f"input has {total}")
        return tgt

    def output_type(self, input_type: InputType) -> InputType:
        return _type_from_keras_shape(
            self._resolve(_keras_space_shape(input_type)))

    def apply(self, params, x, *, training=False, rng=None, state=None):
        x = _to_keras_layout(x)
        tgt = self._resolve(x.shape[1:])
        return _from_keras_layout(jnp.reshape(x, (x.shape[0],) + tgt)), state


@dataclass
class PermuteLayer(Layer):
    """Keras-semantics axis permutation (ref: KerasPermute ->
    PermutePreprocessor). ``permuteDims`` are 1-based post-batch axis indices
    in Keras channels-last coordinates, exactly as Keras ``Permute(dims)``."""
    permuteDims: Tuple[int, ...] = ()

    def output_type(self, input_type: InputType) -> InputType:
        src = _keras_space_shape(input_type)
        if any(d <= 0 for d in src):
            raise ValueError(
                "PermuteLayer needs fully-known input dims (variable-length "
                "sequence inputs are not permutable)")
        if sorted(self.permuteDims) != list(range(1, len(src) + 1)):
            raise ValueError(f"Permute dims {self.permuteDims} do not match "
                             f"input rank {len(src)}")
        return _type_from_keras_shape(
            tuple(src[d - 1] for d in self.permuteDims))

    def apply(self, params, x, *, training=False, rng=None, state=None):
        x = _to_keras_layout(x)
        y = jnp.transpose(x, (0,) + tuple(self.permuteDims))
        return _from_keras_layout(y), state


@dataclass
class SpaceToDepthLayer(Layer):
    """(ref: conf.layers.SpaceToDepthLayer), NCHW."""
    blockSize: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        b = self.blockSize
        return InputType.convolutional(input_type.height // b, input_type.width // b,
                                       input_type.channels * b * b)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return _nnops.space_to_depth(x, self.blockSize), state


# --------------------------------------------------------------- 1D/3D resize


@dataclass
class Upsampling1D(Layer):
    """Repeat along time (ref: conf.layers.Upsampling1D). Input (B,T,C)."""
    size: int = 2

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeSeriesLength
        return InputType.recurrent(input_type.size, t * self.size if t > 0 else -1)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return jnp.repeat(x, self.size, axis=1), state


@dataclass
class Upsampling3D(Layer):
    """(ref: conf.layers.Upsampling3D), NCDHW."""
    size: Tuple[int, int, int] = (2, 2, 2)

    def output_type(self, input_type: InputType) -> InputType:
        s = self.size
        return InputType.convolutional3D(input_type.depth * s[0], input_type.height * s[1],
                                         input_type.width * s[2], input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        s = self.size
        x = jnp.repeat(x, s[0], axis=2)
        x = jnp.repeat(x, s[1], axis=3)
        return jnp.repeat(x, s[2], axis=4), state


@dataclass
class Cropping1D(Layer):
    """(ref: conf.layers.convolutional.Cropping1D). Input (B,T,C)."""
    cropping: Tuple[int, int] = (0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeSeriesLength
        c = self.cropping
        return InputType.recurrent(input_type.size, t - c[0] - c[1] if t > 0 else -1)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        a, b = self.cropping
        return x[:, a:x.shape[1] - b], state


@dataclass
class Cropping3D(Layer):
    """(ref: conf.layers.convolutional.Cropping3D), NCDHW."""
    cropping: Tuple[int, int, int, int, int, int] = (0, 0, 0, 0, 0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        d0, d1, h0, h1, w0, w1 = self.cropping
        return InputType.convolutional3D(input_type.depth - d0 - d1,
                                         input_type.height - h0 - h1,
                                         input_type.width - w0 - w1, input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        d0, d1, h0, h1, w0, w1 = self.cropping
        return x[:, :, d0:x.shape[2] - d1, h0:x.shape[3] - h1, w0:x.shape[4] - w1], state


@dataclass
class ZeroPadding1DLayer(Layer):
    """(ref: conf.layers.ZeroPadding1DLayer). Input (B,T,C)."""
    padding: Tuple[int, int] = (0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.timeSeriesLength
        p = self.padding
        return InputType.recurrent(input_type.size, t + p[0] + p[1] if t > 0 else -1)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        a, b = self.padding
        return jnp.pad(x, ((0, 0), (a, b), (0, 0))), state


@dataclass
class ZeroPadding3DLayer(Layer):
    """(ref: conf.layers.ZeroPadding3DLayer), NCDHW."""
    padding: Tuple[int, int, int, int, int, int] = (0, 0, 0, 0, 0, 0)

    def output_type(self, input_type: InputType) -> InputType:
        d0, d1, h0, h1, w0, w1 = self.padding
        return InputType.convolutional3D(input_type.depth + d0 + d1,
                                         input_type.height + h0 + h1,
                                         input_type.width + w0 + w1, input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        d0, d1, h0, h1, w0, w1 = self.padding
        return jnp.pad(x, ((0, 0), (0, 0), (d0, d1), (h0, h1), (w0, w1))), state


# ------------------------------------------------------------------- 3D conv


def _triple(v):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v, v, v)


@dataclass
class Convolution3D(FeedForwardLayer):
    """3D conv, NCDHW (ref: conf.layers.Convolution3D -> libnd4j conv3dnew)."""
    spatial_input = True
    kernelSize: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (1, 1, 1)
    padding: Tuple[int, int, int] = (0, 0, 0)
    dilation: Tuple[int, int, int] = (1, 1, 1)
    convolutionMode: str = "Truncate"
    hasBias: bool = True

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.channels

    def output_type(self, input_type: InputType) -> InputType:
        k, s, p = _triple(self.kernelSize), _triple(self.stride), _triple(self.padding)
        d = _conv_out(input_type.depth, k[0], s[0], p[0], self.convolutionMode)
        h = _conv_out(input_type.height, k[1], s[1], p[1], self.convolutionMode)
        w = _conv_out(input_type.width, k[2], s[2], p[2], self.convolutionMode)
        return InputType.convolutional3D(d, h, w, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        k = _triple(self.kernelSize)
        fan_in = self.nIn * k[0] * k[1] * k[2]
        fan_out = self.nOut * k[0] * k[1] * k[2]
        p = {"W": _winit.init(self.weightInit or "XAVIER", key,
                              (self.nOut, self.nIn) + k, fan_in, fan_out, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((self.nOut,), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        if self.convolutionMode == "Same":
            pad = "SAME"
        else:
            p = _triple(self.padding)
            pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
        z = _nnops.conv3d(x, params["W"], params.get("b"), strides=_triple(self.stride),
                          padding=pad, dilation=_triple(self.dilation))
        return self._activate(z), state


@dataclass
class Subsampling3DLayer(Layer):
    """3D pooling, NCDHW (ref: conf.layers.Subsampling3DLayer)."""
    poolingType: str = "MAX"
    kernelSize: Tuple[int, int, int] = (2, 2, 2)
    stride: Tuple[int, int, int] = (2, 2, 2)
    convolutionMode: str = "Truncate"

    def output_type(self, input_type: InputType) -> InputType:
        k, s = _triple(self.kernelSize), _triple(self.stride)
        d = _conv_out(input_type.depth, k[0], s[0], 0, self.convolutionMode)
        h = _conv_out(input_type.height, k[1], s[1], 0, self.convolutionMode)
        w = _conv_out(input_type.width, k[2], s[2], 0, self.convolutionMode)
        return InputType.convolutional3D(d, h, w, input_type.channels)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        pad = "SAME" if self.convolutionMode == "Same" else "VALID"
        if self.poolingType == "MAX":
            fn = _nnops.max_pool3d
        elif self.poolingType == "AVG":
            fn = _nnops.avg_pool3d
        else:
            raise ValueError(f"unsupported 3D poolingType: {self.poolingType}")
        return fn(x, _triple(self.kernelSize), _triple(self.stride), pad), state


# ------------------------------------------------------------ locally connected


@dataclass
class LocallyConnected1D(FeedForwardLayer):
    """Conv1D with UNSHARED weights per position (ref: conf.layers.
    LocallyConnected1D, SameDiff-backed). Input (B,T,C); requires a fixed
    sequence length."""
    kernelSize: int = 2
    stride: int = 1
    inputLength: int = 0
    hasBias: bool = True

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.size
        if not self.inputLength and input_type.timeSeriesLength > 0:
            self.inputLength = input_type.timeSeriesLength

    def _out_len(self):
        return (self.inputLength - self.kernelSize) // self.stride + 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.nOut, self._out_len())

    def init_params(self, key, dtype=jnp.float32):
        T = self._out_len()
        fan = self.kernelSize * self.nIn
        p = {"W": _winit.init(self.weightInit or "XAVIER", key,
                              (T, self.kernelSize * self.nIn, self.nOut),
                              fan, self.nOut, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((T, self.nOut), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        # im2col via XLA's patch primitive — one fused op instead of T_out
        # strided slices (which would grow the jaxpr linearly in T)
        patches = lax.conv_general_dilated_patches(
            x.transpose(0, 2, 1), filter_shape=(self.kernelSize,),
            window_strides=(self.stride,), padding="VALID")  # (B, C*k, T_out)
        patches = patches.transpose(0, 2, 1)  # (B, T_out, C*k)
        z = jnp.einsum("btk,tko->bto", patches, params["W"])
        if self.hasBias:
            z = z + params["b"][None]
        return self._activate(z), state


@dataclass
class LocallyConnected2D(FeedForwardLayer):
    """Conv2D with UNSHARED weights per output position (ref:
    conf.layers.LocallyConnected2D). NCHW; requires fixed inputSize."""
    spatial_input = True
    kernelSize: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (1, 1)
    inputSize: Tuple[int, int] = (0, 0)  # (H, W)
    hasBias: bool = True

    def set_n_in(self, input_type: InputType):
        if not self.nIn:
            self.nIn = input_type.channels
        if not self.inputSize[0]:
            self.inputSize = (input_type.height, input_type.width)

    def _out_hw(self):
        k, s = _pair(self.kernelSize), _pair(self.stride)
        return ((self.inputSize[0] - k[0]) // s[0] + 1,
                (self.inputSize[1] - k[1]) // s[1] + 1)

    def output_type(self, input_type: InputType) -> InputType:
        h, w = self._out_hw()
        return InputType.convolutional(h, w, self.nOut)

    def init_params(self, key, dtype=jnp.float32):
        h, w = self._out_hw()
        k = _pair(self.kernelSize)
        fan = k[0] * k[1] * self.nIn
        p = {"W": _winit.init(self.weightInit or "XAVIER", key,
                              (h * w, k[0] * k[1] * self.nIn, self.nOut),
                              fan, self.nOut, dtype)}
        if self.hasBias:
            p["b"] = jnp.full((h * w, self.nOut), self.biasInit or 0.0, dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        B = x.shape[0]
        h, w = self._out_hw()
        k, s = _pair(self.kernelSize), _pair(self.stride)
        # im2col patches (B, H_out*W_out, k*k*C) via XLA's patch extraction
        patches = lax.conv_general_dilated_patches(
            x, filter_shape=k, window_strides=s, padding="VALID")
        patches = patches.reshape(B, patches.shape[1], h * w).transpose(0, 2, 1)
        z = jnp.einsum("bpk,pko->bpo", patches, params["W"])
        if self.hasBias:
            z = z + params["b"][None]
        z = z.transpose(0, 2, 1).reshape(B, self.nOut, h, w)
        return self._activate(z), state


# --------------------------------------------------------------- autoencoders


@dataclass
class AutoEncoder(FeedForwardLayer):
    """Denoising autoencoder (ref: conf.layers.AutoEncoder — pretrain via
    corrupted-input reconstruction; supervised forward = encoder only)."""
    corruptionLevel: float = 0.3
    sparsity: float = 0.0
    lossFunction: str = "MSE"

    def init_params(self, key, dtype=jnp.float32):
        kW, _ = jax.random.split(key)
        return {"W": _winit.init(self.weightInit or "XAVIER", kW, (self.nIn, self.nOut),
                                 self.nIn, self.nOut, dtype),
                "b": jnp.full((self.nOut,), self.biasInit or 0.0, dtype),
                "vb": jnp.zeros((self.nIn,), dtype)}  # visible bias (decoder)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return self._activate(jnp.matmul(x, params["W"]) + params["b"]), state

    def pretrain_loss(self, params, x, rng):
        """Reconstruction loss on corrupted input (ref: AutoEncoder.computeGradientAndScore)."""
        xc = x
        if self.corruptionLevel > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruptionLevel, x.shape)
            xc = x * keep.astype(x.dtype)
        h = self._activate(jnp.matmul(xc, params["W"]) + params["b"])
        recon = jnp.matmul(h, params["W"].T) + params["vb"]  # tied weights
        loss = jnp.mean((recon - x) ** 2) if self.lossFunction == "MSE" else \
            _losses.get(self.lossFunction)(x, recon, None)
        if self.sparsity > 0:
            loss = loss + self.sparsity * jnp.mean(jnp.abs(h))
        return loss


@dataclass
class VariationalAutoencoder(FeedForwardLayer):
    """VAE (ref: conf.layers.variational.VariationalAutoencoder + runtime
    nn.layers.variational.VariationalAutoencoder). Pretrain = ELBO with a
    Gaussian q(z|x) (reparameterization) and a Gaussian reconstruction
    distribution; supervised forward = mean of q(z|x) (ref: VAE forward uses
    the mean vector)."""
    encoderLayerSizes: Tuple[int, ...] = (100,)
    decoderLayerSizes: Tuple[int, ...] = (100,)
    pzxActivationFunction: str = "IDENTITY"
    numSamples: int = 1
    reconstructionDistribution: str = "GAUSSIAN"  # GAUSSIAN | BERNOULLI

    def init_params(self, key, dtype=jnp.float32):
        wi = self.weightInit or "XAVIER"
        sizes_e = (self.nIn,) + tuple(self.encoderLayerSizes)
        sizes_d = (self.nOut,) + tuple(self.decoderLayerSizes)
        ks = jax.random.split(key, len(sizes_e) + len(sizes_d) + 2)
        ki = iter(range(len(ks)))
        p = {"enc": [], "dec": []}
        for i in range(len(sizes_e) - 1):
            p["enc"].append({
                "W": _winit.init(wi, ks[next(ki)], (sizes_e[i], sizes_e[i + 1]),
                                 sizes_e[i], sizes_e[i + 1], dtype),
                "b": jnp.zeros((sizes_e[i + 1],), dtype)})
        eh = sizes_e[-1]
        p["zMean"] = {"W": _winit.init(wi, ks[next(ki)], (eh, self.nOut), eh, self.nOut, dtype),
                      "b": jnp.zeros((self.nOut,), dtype)}
        p["zLogStd"] = {"W": _winit.init(wi, ks[next(ki)], (eh, self.nOut), eh, self.nOut, dtype),
                        "b": jnp.zeros((self.nOut,), dtype)}
        for i in range(len(sizes_d) - 1):
            p["dec"].append({
                "W": _winit.init(wi, ks[next(ki)], (sizes_d[i], sizes_d[i + 1]),
                                 sizes_d[i], sizes_d[i + 1], dtype),
                "b": jnp.zeros((sizes_d[i + 1],), dtype)})
        dh = sizes_d[-1]
        out_mult = 2 if self.reconstructionDistribution == "GAUSSIAN" else 1
        p["xOut"] = {"W": _winit.init(wi, ks[-1], (dh, self.nIn * out_mult),
                                      dh, self.nIn * out_mult, dtype),
                     "b": jnp.zeros((self.nIn * out_mult,), dtype)}
        return p

    def regularizable(self):
        return ()

    def _encode(self, params, x):
        h = x
        for lay in params["enc"]:
            h = self._activate(jnp.matmul(h, lay["W"]) + lay["b"])
        act = _act.get(self.pzxActivationFunction)
        mean = act(jnp.matmul(h, params["zMean"]["W"]) + params["zMean"]["b"])
        log_std = jnp.matmul(h, params["zLogStd"]["W"]) + params["zLogStd"]["b"]
        return mean, log_std

    def _decode(self, params, z):
        h = z
        for lay in params["dec"]:
            h = self._activate(jnp.matmul(h, lay["W"]) + lay["b"])
        return jnp.matmul(h, params["xOut"]["W"]) + params["xOut"]["b"]

    def apply(self, params, x, *, training=False, rng=None, state=None):
        mean, _ = self._encode(params, x)
        return mean, state

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO (ref: VariationalAutoencoder.computeGradientAndScore)."""
        mean, log_std = self._encode(params, x)
        std = jnp.exp(log_std)
        loss = 0.0
        rng = rng if rng is not None else jax.random.key(0)
        for s in range(max(self.numSamples, 1)):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape, mean.dtype)
            z = mean + std * eps
            out = self._decode(params, z)
            if self.reconstructionDistribution == "GAUSSIAN":
                xm, xls = jnp.split(out, 2, axis=-1)
                xs = jnp.exp(xls)
                recon = 0.5 * jnp.sum(((x - xm) / xs) ** 2 + 2 * xls
                                      + jnp.log(2 * jnp.pi), axis=-1)
            else:
                recon = jnp.sum(jnp.clip(out, 0) - out * x
                                + jnp.log1p(jnp.exp(-jnp.abs(out))), axis=-1)
            loss = loss + jnp.mean(recon)
        loss = loss / max(self.numSamples, 1)
        kl = -0.5 * jnp.sum(1 + 2 * log_std - mean ** 2 - jnp.exp(2 * log_std), axis=-1)
        return loss + jnp.mean(kl)

    def reconstructionProbability(self, params, x, num_samples=5):
        """Monte-Carlo estimate of log p(x) (ref: VAE.reconstructionLogProbability)."""
        mean, log_std = self._encode(params, x)
        std = jnp.exp(log_std)
        total = 0.0
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(jax.random.key(7), s),
                                    mean.shape, mean.dtype)
            out = self._decode(params, mean + std * eps)
            if self.reconstructionDistribution == "GAUSSIAN":
                xm, xls = jnp.split(out, 2, axis=-1)
                xs = jnp.exp(xls)
                lp = -0.5 * jnp.sum(((x - xm) / xs) ** 2 + 2 * xls
                                    + jnp.log(2 * jnp.pi), axis=-1)
            else:
                lp = jnp.sum(x * jax.nn.log_sigmoid(out)
                             + (1 - x) * jax.nn.log_sigmoid(-out), axis=-1)
            total = total + lp
        return total / num_samples


# ------------------------------------------------------- special output layers


@dataclass
class CenterLossOutputLayer(BaseOutputLayer):
    """Softmax + center loss (ref: conf.layers.CenterLossOutputLayer).
    Centers are parameters minimized by the center-loss term itself (the
    reference updates them with an EMA of rate alpha; SGD on the same
    objective is the jit-native equivalent — gradientCheck=true for them)."""
    alpha: float = 0.05
    lambda_: float = 2e-4

    def init_params(self, key, dtype=jnp.float32):
        p = super().init_params(key, dtype)
        p["centers"] = jnp.zeros((self.nOut, self.nIn), dtype)
        return p

    def apply(self, params, x, *, training=False, rng=None, state=None):
        out, _ = super().apply(params, x, training=training, rng=rng, state=state)
        # capture features for the center term (read by compute_loss_ext)
        return out, {"features": x}

    def compute_loss_ext(self, params, labels, output, features, mask=None):
        base = _losses.get(self.lossFunction)(labels, output, mask)
        y = jnp.argmax(labels, axis=-1)
        centers = params["centers"][y]
        center = 0.5 * jnp.mean(jnp.sum((features - centers) ** 2, axis=-1))
        return base + self.lambda_ * center


@dataclass
class OCNNOutputLayer(BaseOutputLayer):
    """One-class neural network output (ref: conf.ocnn.OCNNOutputLayer —
    anomaly scoring with the one-class SVM-style objective of Chalapathy et
    al.; hiddenSize V, output w·g(Vx), loss hinge around r)."""
    hiddenSize: int = 10
    nu: float = 0.04
    initialRValue: float = 0.1

    def __post_init__(self):
        self.nOut = 1
        if self.activation is None:
            self.activation = "IDENTITY"

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        wi = self.weightInit or "XAVIER"
        return {"V": _winit.init(wi, k1, (self.nIn, self.hiddenSize),
                                 self.nIn, self.hiddenSize, dtype),
                "W": _winit.init(wi, k2, (self.hiddenSize, 1), self.hiddenSize, 1, dtype),
                "r": jnp.asarray(self.initialRValue, dtype)}

    def apply(self, params, x, *, training=False, rng=None, state=None):
        h = jax.nn.sigmoid(jnp.matmul(x, params["V"]))
        return jnp.matmul(h, params["W"]) - params["r"], state

    def compute_loss(self, labels, output, mask=None):
        # one-class hinge only (no access to r here); prefer loss_with_params
        return jnp.mean(jnp.maximum(0.0, -output)) / self.nu

    def loss_with_params(self, params, labels, output, mask=None):
        """Full one-class objective (Chalapathy et al.): labels unused;
        (1/nu)·mean(max(0, r − score)) − r, with output = score − r. The −r
        term drives the boundary up; without it r only ever shrinks and
        training stalls at a degenerate zero-loss point."""
        return jnp.mean(jnp.maximum(0.0, -output)) / self.nu - params["r"]


@dataclass
class Yolo2OutputLayer(Layer):
    """YOLOv2 detection output + loss (ref: conf.layers.objdetect.
    Yolo2OutputLayer + nn.layers.objdetect.Yolo2OutputLayer). Input NCHW
    (B, A*(5+C), H, W); labels (B, 4+C, H, W) grid format as the reference's
    ObjectDetectionRecordReader emits. Anchors are in grid units."""
    boundingBoxes: Tuple = ()          # ((w,h), ...) anchor priors
    lambdaCoord: float = 5.0
    lambdaNoObj: float = 0.5

    def regularizable(self):
        return ()

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return x, state

    def _split_predictions(self, x):
        A = len(self.boundingBoxes)
        B, _, H, W = x.shape
        C = x.shape[1] // A - 5
        x = x.reshape(B, A, 5 + C, H, W)
        # sigmoid xy offsets within cell, exp wh scaled by anchors, sigmoid conf
        xy = jax.nn.sigmoid(x[:, :, 0:2])
        anchors = jnp.asarray(self.boundingBoxes, x.dtype)  # (A,2)
        wh = jnp.exp(x[:, :, 2:4]) * anchors[None, :, :, None, None]
        conf = jax.nn.sigmoid(x[:, :, 4])
        cls = jax.nn.softmax(x[:, :, 5:], axis=2)
        return xy, wh, conf, cls

    def compute_loss(self, labels, output, mask=None):
        """Grid-matched YOLOv2 loss. labels (B, 4+C, H, W): tx,ty,tw,th in
        grid units + one-hot class; cells without an object have all-zero
        class vector."""
        xy, wh, conf, cls = self._split_predictions(output)
        lab_xy = labels[:, 0:2]                      # (B,2,H,W) cell offsets
        lab_wh = labels[:, 2:4]
        lab_cls = labels[:, 4:]                      # (B,C,H,W) one-hot
        obj = (jnp.sum(lab_cls, axis=1, keepdims=True) > 0)[:, 0]  # (B,H,W)
        # responsibility: anchor with best IOU against the label box
        inter = jnp.minimum(wh[:, :, 0], lab_wh[:, None, 0]) * \
            jnp.minimum(wh[:, :, 1], lab_wh[:, None, 1])
        union = wh[:, :, 0] * wh[:, :, 1] + \
            lab_wh[:, None, 0] * lab_wh[:, None, 1] - inter
        iou = inter / jnp.maximum(union, 1e-6)       # (B,A,H,W)
        resp = jax.nn.one_hot(jnp.argmax(iou, axis=1), iou.shape[1], axis=1)
        resp = resp * obj[:, None]
        coord = jnp.sum(resp[:, :, None] * (
            (xy - lab_xy[:, None]) ** 2 +
            (jnp.sqrt(wh) - jnp.sqrt(jnp.maximum(lab_wh[:, None], 1e-8))) ** 2))
        conf_obj = jnp.sum(resp * (conf - iou) ** 2)
        conf_noobj = jnp.sum((1 - resp) * conf ** 2)
        cls_loss = jnp.sum(resp[:, :, None] * (cls - lab_cls[:, None]) ** 2)
        B = output.shape[0]
        return (self.lambdaCoord * coord + conf_obj
                + self.lambdaNoObj * conf_noobj + cls_loss) / B

    def getPredictedObjects(self, output, threshold=0.5):
        """Decode detections (ref: YoloUtils.getPredictedObjects): returns a
        list per batch item of (x1, y1, x2, y2, conf, class) in grid units."""
        import numpy as np
        xy, wh, conf, cls = self._split_predictions(jnp.asarray(output))
        xy, wh, conf, cls = map(np.asarray, (xy, wh, conf, cls))
        B, A, H, W = conf.shape
        gy, gx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
        results = []
        for b in range(B):
            dets = []
            for a in range(A):
                score = conf[b, a] * cls[b, a].max(axis=0)
                for (i, j) in zip(*np.nonzero(score > threshold)):
                    cx = gx[i, j] + xy[b, a, 0, i, j]
                    cy = gy[i, j] + xy[b, a, 1, i, j]
                    w_, h_ = wh[b, a, 0, i, j], wh[b, a, 1, i, j]
                    dets.append((cx - w_ / 2, cy - h_ / 2, cx + w_ / 2, cy + h_ / 2,
                                 float(conf[b, a, i, j]),
                                 int(cls[b, a, :, i, j].argmax())))
            results.append(dets)
        return results


# ------------------------------------------------------------ recurrent extras


@dataclass
class GravesBidirectionalLSTM(BaseRecurrentLayer):
    """Bidirectional peephole LSTM as ONE layer (ref: conf.layers.
    GravesBidirectionalLSTM — forward and backward passes each produce nOut
    and are combined additively, so output size stays nOut)."""
    forgetGateBiasInit: float = 1.0

    def _half(self) -> GravesLSTM:
        return GravesLSTM(nIn=self.nIn, nOut=self.nOut, activation=self.activation,
                          weightInit=self.weightInit,
                          forgetGateBiasInit=self.forgetGateBiasInit,
                          rnnDataFormat="NWC")

    def init_params(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        half = self._half()
        return {"fwd": half.init_params(k1, dtype), "bwd": half.init_params(k2, dtype)}

    def regularizable(self):
        return ()

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        half = self._half()
        x = self._to_nwc(x)
        rs = half.init_rnn_state(x.shape[0], x.dtype)
        yf, _ = half.apply_rnn(params["fwd"], x, rs, mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = jnp.flip(mask, axis=1) if mask is not None else None
        yb, _ = half.apply_rnn(params["bwd"], xr, rs, mask=mr)
        return self._from_nwc(yf + jnp.flip(yb, axis=1)), state


@dataclass
class LearnedSelfAttentionLayer(BaseRecurrentLayer):
    """Attention with LEARNED queries (ref: conf.layers.LearnedSelfAttentionLayer):
    nQueries fixed learned query vectors attend over the sequence, output
    (B, nQueries, nOut)."""
    nHeads: int = 1
    nQueries: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.nOut, self.nQueries)

    def init_params(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, 5)
        wi = self.weightInit or "XAVIER"
        D, O = self.nIn, self.nOut
        return {"Q": _winit.init(wi, ks[0], (self.nQueries, O), O, O, dtype),
                "Wk": _winit.init(wi, ks[1], (D, O), D, O, dtype),
                "Wv": _winit.init(wi, ks[2], (D, O), D, O, dtype),
                "Wo": _winit.init(wi, ks[3], (O, O), O, O, dtype)}

    def regularizable(self):
        return ("Wk", "Wv", "Wo")

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        B = x.shape[0]
        q = jnp.broadcast_to(params["Q"][None], (B,) + params["Q"].shape)
        k = jnp.matmul(x, params["Wk"])
        v = jnp.matmul(x, params["Wv"])
        m = mask[:, None, :] if mask is not None else None
        out = _nnops.dot_product_attention(q, k, v, mask=m)
        return jnp.matmul(out, params["Wo"]), state


@dataclass
class RecurrentAttentionLayer(BaseRecurrentLayer):
    """Recurrent cell whose input each step is attention over the full
    sequence conditioned on the previous hidden state (ref:
    conf.layers.RecurrentAttentionLayer, SameDiff-backed)."""
    nHeads: int = 1

    def init_params(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, 5)
        wi = self.weightInit or "XAVIER"
        D, O = self.nIn, self.nOut
        return {"Wq": _winit.init(wi, ks[0], (O, O), O, O, dtype),
                "Wk": _winit.init(wi, ks[1], (D, O), D, O, dtype),
                "Wv": _winit.init(wi, ks[2], (D, O), D, O, dtype),
                "W": _winit.init(wi, ks[3], (D, O), D, O, dtype),
                "RW": _winit.init(wi, ks[4], (O, O), O, O, dtype),
                "b": jnp.zeros((O,), dtype)}

    def regularizable(self):
        return ("Wq", "Wk", "Wv", "W", "RW")

    def apply(self, params, x, *, training=False, rng=None, state=None, mask=None):
        x = self._to_nwc(x)
        B, T, _ = x.shape
        keys = jnp.matmul(x, params["Wk"])          # (B,T,O)
        vals = jnp.matmul(x, params["Wv"])
        act = _act.get(self.activation or "TANH")
        scale = 1.0 / math.sqrt(params["Wq"].shape[1])
        mbias = None
        if mask is not None:
            mbias = jnp.where(mask > 0, 0.0, -1e9)  # (B,T)

        def step(h, xt):
            q = jnp.matmul(h, params["Wq"])         # (B,O)
            s = jnp.einsum("bo,bto->bt", q, keys) * scale
            if mbias is not None:
                s = s + mbias
            a = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bt,bto->bo", a, vals)
            h2 = act(jnp.matmul(xt, params["W"]) + jnp.matmul(h, params["RW"])
                     + ctx + params["b"])
            return h2, h2

        h0 = jnp.zeros((B, self.nOut), x.dtype)
        _, ys = lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
        return self._from_nwc(jnp.swapaxes(ys, 0, 1)), state


# ------------------------------------------------------------------- capsules


@dataclass
class PrimaryCapsules(Layer):
    """Conv caps primary layer (ref: conf.layers.PrimaryCapsules): conv2d ->
    reshape to (B, num_caps, capsuleDimensions) -> squash."""
    capsules: int = 0               # derived if 0
    capsuleDimensions: int = 8
    channels: int = 32
    kernelSize: Tuple[int, int] = (9, 9)
    stride: Tuple[int, int] = (2, 2)
    _nIn: int = 0
    _hw: Tuple[int, int] = (0, 0)

    def set_n_in(self, input_type: InputType):
        self._nIn = input_type.channels
        k, s = _pair(self.kernelSize), _pair(self.stride)
        h = (input_type.height - k[0]) // s[0] + 1
        w = (input_type.width - k[1]) // s[1] + 1
        self._hw = (h, w)
        if not self.capsules:
            self.capsules = self.channels * h * w

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.capsuleDimensions, self.capsules)

    def init_params(self, key, dtype=jnp.float32):
        k = _pair(self.kernelSize)
        cout = self.channels * self.capsuleDimensions
        fan_in = self._nIn * k[0] * k[1]
        return {"W": _winit.init(self.weightInit or "XAVIER", key,
                                 (cout, self._nIn, k[0], k[1]), fan_in, cout, dtype),
                "b": jnp.zeros((cout,), dtype)}

    @staticmethod
    def _squash(s, axis=-1):
        n2 = jnp.sum(s * s, axis=axis, keepdims=True)
        return (n2 / (1.0 + n2)) * s / jnp.sqrt(n2 + 1e-9)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        z = _nnops.conv2d(x, params["W"], params["b"], strides=_pair(self.stride),
                          padding="VALID")
        B = z.shape[0]
        z = z.reshape(B, -1, self.capsuleDimensions)
        return self._squash(z), state


@dataclass
class CapsuleLayer(Layer):
    """Dynamic-routing capsule layer (ref: conf.layers.CapsuleLayer).
    Input (B, inputCaps, inputDims) -> (B, capsules, capsuleDimensions)."""
    capsules: int = 10
    capsuleDimensions: int = 16
    routings: int = 3
    inputCapsules: int = 0
    inputCapsuleDimensions: int = 0

    def set_n_in(self, input_type: InputType):
        if not self.inputCapsules:
            self.inputCapsules = input_type.timeSeriesLength
            self.inputCapsuleDimensions = input_type.size

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.recurrent(self.capsuleDimensions, self.capsules)

    def init_params(self, key, dtype=jnp.float32):
        shape = (self.inputCapsules, self.capsules,
                 self.inputCapsuleDimensions, self.capsuleDimensions)
        return {"W": jax.random.normal(key, shape, dtype) * 0.01}

    def regularizable(self):
        return ()

    def apply(self, params, x, *, training=False, rng=None, state=None):
        # prediction vectors u_hat (B, inCaps, outCaps, outDim)
        u_hat = jnp.einsum("bid,iodk->biok", x, params["W"])
        b = jnp.zeros(u_hat.shape[:3], x.dtype)
        for _ in range(self.routings):
            c = jax.nn.softmax(b, axis=2)
            s = jnp.einsum("bio,biok->bok", c, u_hat)
            v = PrimaryCapsules._squash(s)
            b = b + jnp.einsum("biok,bok->bio", u_hat, v)
        return v, state


@dataclass
class CapsuleStrengthLayer(Layer):
    """Capsule norm per class (ref: conf.layers.CapsuleStrengthLayer)."""

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feedForward(input_type.timeSeriesLength)

    def apply(self, params, x, *, training=False, rng=None, state=None):
        return jnp.sqrt(jnp.sum(x * x, axis=-1) + 1e-9), state


LAYER_TYPES = {c.__name__: c for c in [
    DenseLayer, EmbeddingLayer, EmbeddingSequenceLayer, ConvolutionLayer, Convolution1DLayer,
    Deconvolution2D, DepthwiseConvolution2D, SeparableConvolution2D, SubsamplingLayer,
    Subsampling1DLayer, BatchNormalization, LocalResponseNormalization, DropoutLayer,
    ActivationLayer, Upsampling2D, ZeroPaddingLayer, Cropping2D, GlobalPoolingLayer,
    LSTM, GravesLSTM, SimpleRnn, GRU, Bidirectional, LastTimeStep,
    OutputLayer, RnnOutputLayer, LossLayer, SelfAttentionLayer,
    PReLULayer, ElementWiseMultiplicationLayer, MaskZeroLayer, SpaceToDepthLayer,
    Upsampling1D, Upsampling3D, Cropping1D, Cropping3D, ZeroPadding1DLayer,
    ZeroPadding3DLayer, Convolution3D, Subsampling3DLayer, LocallyConnected1D,
    LocallyConnected2D, AutoEncoder, VariationalAutoencoder, CenterLossOutputLayer,
    OCNNOutputLayer, Yolo2OutputLayer, GravesBidirectionalLSTM,
    LearnedSelfAttentionLayer, RecurrentAttentionLayer,
    PrimaryCapsules, CapsuleLayer, CapsuleStrengthLayer, RepeatVector,
    ConvLSTM2D, ReshapeLayer, PermuteLayer,
]}
