"""ComputationGraph configuration: graph vertices + GraphBuilder DSL
(ref: org.deeplearning4j.nn.conf.ComputationGraphConfiguration.GraphBuilder and
org.deeplearning4j.nn.conf.graph.* vertex classes).

A graph node is either a Layer (via addLayer) or a GraphVertex (via addVertex).
Vertices are parameterless combinators; layers carry params. InputTypes
propagate through the DAG for nIn auto-fill exactly as the sequential builder
does (ref: InputType.getOutputType chain, SURVEY.md §2.4)."""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.train import regularization as _reg
from deeplearning4j_tpu.train import updaters as _upd


class GraphVertex:
    """Parameterless combinator node (ref: o.d.nn.conf.graph.GraphVertex)."""

    def apply(self, inputs: List, *, training=False, rng=None):
        raise NotImplementedError

    def output_type(self, input_types: List[InputType]) -> InputType:
        return input_types[0]

    def regularizable(self):
        """Param keys subject to l1/l2 — vertices default to none (parameterized
        subclasses like AttentionVertex opt in by overriding)."""
        return ()

    def to_dict(self) -> dict:
        out = {"@type": type(self).__name__}
        out.update({k: (list(v) if isinstance(v, tuple) else v)
                    for k, v in self.__dict__.items()})
        return out

    @staticmethod
    def from_dict(d: dict) -> "GraphVertex":
        d = dict(d)
        cls = VERTEX_TYPES[d.pop("@type")]
        return cls(**d)


@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature axis (ref: MergeVertex — dim 1 for
    FF/CNN-channels, last dim for NWC recurrent)."""

    def apply(self, inputs, *, training=False, rng=None):
        x = inputs[0]
        axis = 1 if x.ndim in (2, 4) else -1
        return jnp.concatenate(inputs, axis=axis)

    def output_type(self, input_types):
        t0 = input_types[0]
        if t0 is None:
            return None
        if t0.kind == "cnn":
            return InputType.convolutional(t0.height, t0.width,
                                           sum(t.channels for t in input_types))
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in input_types),
                                       t0.timeSeriesLength)
        return InputType.feedForward(sum(t.size for t in input_types))


@dataclass
class ElementWiseVertex(GraphVertex):
    """(ref: ElementWiseVertex) op in Add|Subtract|Product|Average|Max."""
    op: str = "Add"

    def apply(self, inputs, *, training=False, rng=None):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            return inputs[0] - inputs[1]
        if op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "average":
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"unknown ElementWiseVertex op {self.op}")


@dataclass
class SubsetVertex(GraphVertex):
    """Feature-dim slice [from, to] inclusive (ref: SubsetVertex)."""
    fromIndex: int = 0
    toIndex: int = 0

    def apply(self, inputs, *, training=False, rng=None):
        x = inputs[0]
        sl = slice(self.fromIndex, self.toIndex + 1)
        return x[:, sl] if x.ndim in (2, 4) else x[..., sl]

    def output_type(self, input_types):
        t = input_types[0]
        if t is None:
            return None
        n = self.toIndex - self.fromIndex + 1
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width, n)
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timeSeriesLength)
        return InputType.feedForward(n)


@dataclass
class StackVertex(GraphVertex):
    """Stack along dim 0 (ref: StackVertex — minibatch concat)."""

    def apply(self, inputs, *, training=False, rng=None):
        return jnp.concatenate(inputs, axis=0)


@dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``fromIndex`` of ``stackSize`` along dim 0 (ref: UnstackVertex)."""
    fromIndex: int = 0
    stackSize: int = 1

    def apply(self, inputs, *, training=False, rng=None):
        x = inputs[0]
        step = x.shape[0] // self.stackSize
        return x[self.fromIndex * step:(self.fromIndex + 1) * step]


@dataclass
class ScaleVertex(GraphVertex):
    scaleFactor: float = 1.0

    def apply(self, inputs, *, training=False, rng=None):
        return inputs[0] * self.scaleFactor


@dataclass
class ShiftVertex(GraphVertex):
    shiftFactor: float = 0.0

    def apply(self, inputs, *, training=False, rng=None):
        return inputs[0] + self.shiftFactor


@dataclass
class L2NormalizeVertex(GraphVertex):
    eps: float = 1e-8

    def apply(self, inputs, *, training=False, rng=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True))
        return x / (n + self.eps)


@dataclass
class ReshapeVertex(GraphVertex):
    newShape: Tuple[int, ...] = ()

    def apply(self, inputs, *, training=False, rng=None):
        shape = tuple(self.newShape)
        return inputs[0].reshape((inputs[0].shape[0],) + shape[1:]
                                 if shape and shape[0] == -1 else shape)

    def output_type(self, input_types):
        return None  # shape inference stops; downstream must set nIn explicitly


@dataclass
class DotProductAttentionVertex(GraphVertex):
    """Parameterless scaled dot-product attention over [queries, keys, values
    (, mask)] inputs, NWC sequences (ref: conf.graph.DotProductAttentionVertex)."""
    scale: Optional[float] = None

    def apply(self, inputs, *, training=False, rng=None):
        from deeplearning4j_tpu.ops.nn_defs import dot_product_attention
        q, k, v = inputs[0], inputs[1], inputs[2]
        if self.scale is not None:
            q = q * (self.scale * jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype)))
        mask = None
        if len(inputs) > 3 and inputs[3] is not None:
            mask = inputs[3][:, None, :] > 0  # key mask -> (B, 1, Tk)
        return dot_product_attention(q, k, v, mask=mask)

    def output_type(self, input_types):
        q, v = input_types[0], input_types[2]
        return InputType.recurrent(v.size, q.timeSeriesLength)


@dataclass
class AttentionVertex(GraphVertex):
    """Multi-head attention with learned projections over [queries, keys,
    values] inputs (ref: conf.graph.AttentionVertex, SameDiff-backed)."""
    nInQueries: int = 0
    nInKeys: int = 0
    nInValues: int = 0
    nOut: int = 0
    nHeads: int = 1
    weightInit: Optional[str] = None

    has_params = True

    def init_params(self, key, dtype=jnp.float32):
        from deeplearning4j_tpu.nn.conf import weights as _winit
        ks = jax.random.split(key, 4)
        wi = self.weightInit or "XAVIER"
        O = self.nOut
        return {"Wq": _winit.init(wi, ks[0], (self.nInQueries, O), self.nInQueries, O, dtype),
                "Wk": _winit.init(wi, ks[1], (self.nInKeys, O), self.nInKeys, O, dtype),
                "Wv": _winit.init(wi, ks[2], (self.nInValues, O), self.nInValues, O, dtype),
                "Wo": _winit.init(wi, ks[3], (O, O), O, O, dtype)}

    def apply(self, inputs, *, params=None, training=False, rng=None):
        import math as _math
        q = jnp.matmul(inputs[0], params["Wq"])
        k = jnp.matmul(inputs[1], params["Wk"])
        v = jnp.matmul(inputs[2], params["Wv"])
        B, Tq, O = q.shape
        H = self.nHeads
        d = O // H

        def heads(t):
            return t.reshape(B, t.shape[1], H, d).transpose(0, 2, 1, 3)

        s = jnp.einsum("bhqd,bhkd->bhqk", heads(q), heads(k)) / _math.sqrt(d)
        a = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", a, heads(v))
        o = o.transpose(0, 2, 1, 3).reshape(B, Tq, O)
        return jnp.matmul(o, params["Wo"])

    def output_type(self, input_types):
        return InputType.recurrent(self.nOut, input_types[0].timeSeriesLength)


@dataclass
class PreprocessorVertex(GraphVertex):
    """Standalone input-format adapter (ref: conf.graph.PreprocessorVertex).
    ``preprocessor``: 'cnnToFF' | 'ffToRnn' | 'rnnToFF' | 'rnnToCnn' | 'cnnToRnn'
    (the reference's InputPreProcessor impls)."""
    preprocessor: str = "cnnToFF"
    channels: int = 0
    height: int = 0
    width: int = 0

    def apply(self, inputs, *, training=False, rng=None):
        x = inputs[0]
        p = self.preprocessor
        if p == "cnnToFF":
            return x.reshape(x.shape[0], -1)
        if p == "ffToRnn":
            return x[:, None, :]
        if p == "rnnToFF":
            return x.reshape(-1, x.shape[-1])
        if p == "rnnToCnn":
            B, T = x.shape[0], x.shape[1]
            return x.reshape(B * T, self.channels, self.height, self.width)
        if p == "cnnToRnn":
            return x.reshape(x.shape[0], 1, -1)
        raise ValueError(p)

    def output_type(self, input_types):
        t = input_types[0]
        if self.preprocessor == "cnnToFF":
            return InputType.feedForward(t.flat_size())
        if self.preprocessor == "ffToRnn":
            return InputType.recurrent(t.size, 1)
        if self.preprocessor == "rnnToFF":
            return InputType.feedForward(t.size)
        if self.preprocessor == "rnnToCnn":
            return InputType.convolutional(self.height, self.width, self.channels)
        if self.preprocessor == "cnnToRnn":
            return InputType.recurrent(t.flat_size(), 1)
        return t


VERTEX_TYPES = {c.__name__: c for c in (
    MergeVertex, ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex,
    ScaleVertex, ShiftVertex, L2NormalizeVertex, ReshapeVertex,
    DotProductAttentionVertex, AttentionVertex, PreprocessorVertex)}


@dataclass
class GraphNode:
    name: str
    op: object                      # Layer or GraphVertex
    inputs: List[str]


@dataclass
class ComputationGraphConfiguration:
    """(ref: o.d.nn.conf.ComputationGraphConfiguration)."""
    networkInputs: List[str] = field(default_factory=list)
    networkOutputs: List[str] = field(default_factory=list)
    nodes: List[GraphNode] = field(default_factory=list)
    seed: int = 0
    updater: _upd.Updater = field(default_factory=_upd.Sgd)
    inputTypes: List[Optional[InputType]] = field(default_factory=list)
    regularization: List[_reg.Regularization] = field(default_factory=list)
    gradientNormalization: Optional[str] = None
    gradientNormalizationThreshold: float = 1.0
    backpropType: str = "Standard"
    tbpttFwdLength: int = 20
    tbpttBackLength: int = 20
    dataType: str = "FLOAT"

    def to_json(self) -> str:
        return json.dumps({
            "networkInputs": self.networkInputs,
            "networkOutputs": self.networkOutputs,
            "nodes": [{"name": n.name,
                       "op": n.op.to_dict(),
                       "inputs": n.inputs,
                       "kind": "vertex" if isinstance(n.op, GraphVertex) else "layer"}
                      for n in self.nodes],
            "seed": self.seed,
            "updater": self.updater.to_dict(),
            "inputTypes": [t.to_dict() if t else None for t in self.inputTypes],
            "regularization": [r.to_dict() for r in self.regularization],
            "gradientNormalization": self.gradientNormalization,
            "gradientNormalizationThreshold": self.gradientNormalizationThreshold,
            "backpropType": self.backpropType,
            "tbpttFwdLength": self.tbpttFwdLength,
            "tbpttBackLength": self.tbpttBackLength,
            "dataType": self.dataType,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        d = json.loads(s)
        nodes = []
        for nd in d["nodes"]:
            op = GraphVertex.from_dict(nd["op"]) if nd["kind"] == "vertex" \
                else Layer.from_dict(nd["op"])
            nodes.append(GraphNode(nd["name"], op, list(nd["inputs"])))
        return ComputationGraphConfiguration(
            networkInputs=list(d["networkInputs"]),
            networkOutputs=list(d["networkOutputs"]),
            nodes=nodes,
            seed=d.get("seed", 0),
            updater=_upd.from_dict(d["updater"]),
            inputTypes=[InputType.from_dict(t) if t else None
                        for t in d.get("inputTypes", [])],
            regularization=[_reg.from_dict(r) for r in d.get("regularization", [])],
            gradientNormalization=d.get("gradientNormalization"),
            gradientNormalizationThreshold=d.get("gradientNormalizationThreshold", 1.0),
            backpropType=d.get("backpropType", "Standard"),
            tbpttFwdLength=d.get("tbpttFwdLength", 20),
            tbpttBackLength=d.get("tbpttBackLength", 20),
            dataType=d.get("dataType", "FLOAT"),
        )

    def topo_order(self) -> List[GraphNode]:
        """Kahn topological sort (ref: ComputationGraph.topologicalSortOrder)."""
        produced = set(self.networkInputs)
        remaining = list(self.nodes)
        order: List[GraphNode] = []
        while remaining:
            ready = [n for n in remaining if all(i in produced for i in n.inputs)]
            if not ready:
                missing = {i for n in remaining for i in n.inputs} - produced
                raise ValueError(f"graph has a cycle or unknown inputs: {sorted(missing)}")
            for n in ready:
                order.append(n)
                produced.add(n.name)
                remaining.remove(n)
        return order


class GraphBuilder:
    """(ref: ComputationGraphConfiguration.GraphBuilder, reached via
    NeuralNetConfiguration.Builder().graphBuilder())."""

    def __init__(self, parent=None):
        self._parent = parent
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._nodes: List[GraphNode] = []
        self._input_types: List[Optional[InputType]] = []
        self._backprop_type = "Standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def addInputs(self, *names: str):
        self._inputs.extend(names)
        return self

    def setInputTypes(self, *types: InputType):
        self._input_types = list(types)
        return self

    def addLayer(self, name: str, layer: Layer, *inputs: str):
        layer.name = name
        self._nodes.append(GraphNode(name, layer, list(inputs)))
        return self

    def addVertex(self, name: str, vertex: GraphVertex, *inputs: str):
        self._nodes.append(GraphNode(name, vertex, list(inputs)))
        return self

    def setOutputs(self, *names: str):
        self._outputs = list(names)
        return self

    def backpropType(self, bt: str):
        self._backprop_type = bt
        return self

    def tBPTTForwardLength(self, n: int):
        self._tbptt_fwd = n
        return self

    def tBPTTBackwardLength(self, n: int):
        self._tbptt_back = n
        return self

    def build(self) -> ComputationGraphConfiguration:
        p = self._parent
        conf = ComputationGraphConfiguration(
            networkInputs=list(self._inputs),
            networkOutputs=list(self._outputs),
            nodes=self._nodes,
            inputTypes=list(self._input_types),
            backpropType=self._backprop_type,
            tbpttFwdLength=self._tbptt_fwd,
            tbpttBackLength=self._tbptt_back,
        )
        if p is not None:
            conf.seed = p._seed
            conf.updater = p._updater
            conf.regularization = p._regularization
            conf.gradientNormalization = p._gradNorm
            conf.gradientNormalizationThreshold = p._gradNormThreshold
            conf.dataType = p._dataType
            globals_ = {"activation": p._activation, "weightInit": p._weightInit,
                        "biasInit": p._biasInit, "dropOut": p._dropOut}
            for n in conf.nodes:
                if isinstance(n.op, Layer):
                    n.op.inherit(globals_)
        # InputType propagation for nIn auto-fill across the DAG
        types: Dict[str, Optional[InputType]] = {}
        for i, name in enumerate(conf.networkInputs):
            t = self._input_types[i] if i < len(self._input_types) else None
            types[name] = t.as_cnn() if t else None
        for node in conf.topo_order():
            in_types = [types.get(i) for i in node.inputs]
            if isinstance(node.op, Layer):
                t = in_types[0]
                if t is not None:
                    node.op.set_n_in(t)
                    types[node.name] = node.op.output_type(t)
                else:
                    # fall back to the layer's own nIn so chains stay inferable
                    n_in = getattr(node.op, "nOut", 0)
                    types[node.name] = InputType.feedForward(n_in) if n_in else None
            else:
                types[node.name] = (node.op.output_type(in_types)
                                    if all(t is not None for t in in_types) else None)
        return conf
