"""Configuration DSL (ref: org.deeplearning4j.nn.conf)."""
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration, NeuralNetConfiguration  # noqa: F401
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
