"""Dropout variants (ref: org.deeplearning4j.nn.conf.dropout — IDropout SPI
with Dropout, GaussianDropout, GaussianNoise, AlphaDropout, SpatialDropout).

The reference applies these to a layer's INPUT during training via the
conf-level ``dropOut`` setting; here ``Layer.dropOut`` accepts either a float
(retain probability, plain inverted dropout — dl4j semantics, unchanged) or
one of these objects. All are pure functions of (rng, x) so they live inside
the fused jitted train step.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


class IDropout:
    """(ref: org.deeplearning4j.nn.conf.dropout.IDropout)."""

    def apply(self, rng, x):
        raise NotImplementedError

    def to_dict(self) -> dict:
        out = {"@dropout": type(self).__name__}
        out.update({k: v for k, v in self.__dict__.items()})
        return out

    @staticmethod
    def from_dict(d: dict) -> "IDropout":
        d = dict(d)
        cls = DROPOUT_TYPES[d.pop("@dropout")]
        return cls(**d)

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__


class Dropout(IDropout):
    """Plain inverted dropout; ``p`` is the RETAIN probability (dl4j
    semantics, matching the float form of ``dropOut``)."""

    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def apply(self, rng, x):
        if self.p >= 1.0:
            return x
        mask = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(mask, x / self.p, 0.0)


class GaussianDropout(IDropout):
    """Multiplicative gaussian noise N(1, sqrt(rate/(1-rate)))
    (ref: GaussianDropout; Srivastava et al. §10)."""

    def __init__(self, rate: float = 0.1):
        self.rate = float(rate)

    def apply(self, rng, x):
        if self.rate <= 0.0:
            return x
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(rng, x.shape, jnp.float32)
        return x * noise.astype(x.dtype)


class GaussianNoise(IDropout):
    """Additive gaussian noise N(0, stddev) (ref: GaussianNoise)."""

    def __init__(self, stddev: float = 0.1):
        self.stddev = float(stddev)

    def apply(self, rng, x):
        n = self.stddev * jax.random.normal(rng, x.shape, jnp.float32)
        return x + n.astype(x.dtype)


class AlphaDropout(IDropout):
    """SELU-preserving dropout (ref: AlphaDropout; Klambauer et al.): dropped
    units are set to alpha' and an affine correction keeps self-normalizing
    mean/variance. ``p`` is the RETAIN probability."""

    _ALPHA = 1.6732632423543772
    _LAMBDA = 1.0507009873554805

    def __init__(self, p: float = 0.95):
        self.p = float(p)

    def apply(self, rng, x):
        if self.p >= 1.0:
            return x
        p = self.p
        alpha_p = -self._LAMBDA * self._ALPHA
        a = (p + alpha_p ** 2 * p * (1 - p)) ** -0.5
        b = -a * (1 - p) * alpha_p
        mask = jax.random.bernoulli(rng, p, x.shape)
        return (a * jnp.where(mask, x, alpha_p) + b).astype(x.dtype)


class SpatialDropout(IDropout):
    """Channel-wise dropout (ref: SpatialDropout; Tompson et al.): drops whole
    feature maps. Conv inputs here are NCHW/NCDHW so rank-4/5 masks axis 1.
    For rank-3 sequences the channel axis depends on ``rnnDataFormat``: the
    framework default is NWC (B, T, F) → mask the LAST axis (dropping feature
    channels, matching dl4j-on-NCW and Keras SpatialDropout1D behavior, not
    whole timesteps); set ``rnnDataFormat="NCW"`` for (B, F, T) layouts →
    mask axis 1. Last axis for 2D (B, F). ``p`` is the RETAIN probability."""

    def __init__(self, p: float = 0.5, rnnDataFormat: str = "NWC"):
        self.p = float(p)
        self.rnnDataFormat = str(rnnDataFormat).upper()
        if self.rnnDataFormat not in ("NWC", "NCW"):
            raise ValueError(f"rnnDataFormat must be NWC or NCW, "
                             f"got {rnnDataFormat}")

    def apply(self, rng, x):
        if self.p >= 1.0:
            return x
        if x.ndim == 3:
            if self.rnnDataFormat == "NWC":
                shape = (x.shape[0], 1, x.shape[2])
            else:
                shape = (x.shape[0], x.shape[1], 1)
        elif x.ndim >= 4:
            shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
        else:
            shape = x.shape
        mask = jax.random.bernoulli(rng, self.p, shape)
        return jnp.where(mask, x / self.p, 0.0)


DROPOUT_TYPES = {c.__name__: c for c in
                 (Dropout, GaussianDropout, GaussianNoise, AlphaDropout,
                  SpatialDropout)}


def apply_dropout(drop, rng, x):
    """Dispatch helper: float = retain prob (legacy path), IDropout = SPI."""
    if drop is None or rng is None:
        return x
    if isinstance(drop, IDropout):
        return drop.apply(rng, x)
    keep = float(drop)
    if keep >= 1.0:
        return x
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
