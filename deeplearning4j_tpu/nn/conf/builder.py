"""Network configuration DSL (ref: org.deeplearning4j.nn.conf.
NeuralNetConfiguration.Builder -> ListBuilder -> MultiLayerConfiguration).

Fluent builder with global defaults inherited by layers, InputType-driven
shape inference/nIn auto-fill, and JSON round-trip (the reference's Jackson
serde contract — round-trip equality is itself a tested invariant,
SURVEY.md §5.6).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional, Union

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.train import regularization as _reg
from deeplearning4j_tpu.train import updaters as _upd


@dataclass
class MultiLayerConfiguration:
    layers: List[Layer] = field(default_factory=list)
    seed: int = 0
    updater: _upd.Updater = field(default_factory=_upd.Sgd)
    inputType: Optional[InputType] = None
    regularization: List[_reg.Regularization] = field(default_factory=list)
    gradientNormalization: Optional[str] = None  # ClipL2PerLayer|ClipElementWiseAbsoluteValue|ClipL2PerParamType
    gradientNormalizationThreshold: float = 1.0
    backpropType: str = "Standard"  # or "TruncatedBPTT"
    tbpttFwdLength: int = 20
    tbpttBackLength: int = 20
    dataType: str = "FLOAT"

    # ---- serde (ref: MultiLayerConfiguration.toJson/fromJson)
    def to_json(self) -> str:
        return json.dumps({
            "layers": [l.to_dict() for l in self.layers],
            "seed": self.seed,
            "updater": self.updater.to_dict(),
            "inputType": self.inputType.to_dict() if self.inputType else None,
            "regularization": [r.to_dict() for r in self.regularization],
            "gradientNormalization": self.gradientNormalization,
            "gradientNormalizationThreshold": self.gradientNormalizationThreshold,
            "backpropType": self.backpropType,
            "tbpttFwdLength": self.tbpttFwdLength,
            "tbpttBackLength": self.tbpttBackLength,
            "dataType": self.dataType,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        d = json.loads(s)
        return MultiLayerConfiguration(
            layers=[Layer.from_dict(ld) for ld in d["layers"]],
            seed=d.get("seed", 0),
            updater=_upd.from_dict(d["updater"]),
            inputType=InputType.from_dict(d["inputType"]) if d.get("inputType") else None,
            regularization=[_reg.from_dict(r) for r in d.get("regularization", [])],
            gradientNormalization=d.get("gradientNormalization"),
            gradientNormalizationThreshold=d.get("gradientNormalizationThreshold", 1.0),
            backpropType=d.get("backpropType", "Standard"),
            tbpttFwdLength=d.get("tbpttFwdLength", 20),
            tbpttBackLength=d.get("tbpttBackLength", 20),
            dataType=d.get("dataType", "FLOAT"),
        )

    def input_types(self) -> List[InputType]:
        """Per-layer input InputTypes, starting from self.inputType."""
        out = []
        it = self.inputType.as_cnn() if self.inputType else None
        for layer in self.layers:
            out.append(it)
            if it is not None:
                it = layer.output_type(it)
        return out


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.Builder()`` (ref: same name)."""

    class Builder:
        def __init__(self):
            self._seed = 0
            self._updater = _upd.Sgd()
            self._activation = None
            self._weightInit = "XAVIER"
            self._biasInit = 0.0
            self._dropOut = None
            self._regularization: List[_reg.Regularization] = []
            self._gradNorm = None
            self._gradNormThreshold = 1.0
            self._dataType = "FLOAT"

        def seed(self, s: int):
            self._seed = int(s)
            return self

        def updater(self, u: _upd.Updater):
            self._updater = u
            return self

        def activation(self, a: str):
            self._activation = a
            return self

        def weightInit(self, w: str):
            self._weightInit = str(w)
            return self

        def biasInit(self, b: float):
            self._biasInit = b
            return self

        def dropOut(self, retain: float):
            self._dropOut = retain
            return self

        def l1(self, v: float):
            self._regularization.append(_reg.L1(v))
            return self

        def l2(self, v: float):
            self._regularization.append(_reg.L2(v))
            return self

        def weightDecay(self, v: float):
            self._regularization.append(_reg.WeightDecay(v))
            return self

        def gradientNormalization(self, g: str, threshold: float = 1.0):
            self._gradNorm = g
            self._gradNormThreshold = threshold
            return self

        def dataType(self, dt: str):
            self._dataType = dt
            return self

        def list(self) -> "NeuralNetConfiguration.ListBuilder":
            return NeuralNetConfiguration.ListBuilder(self)

        def graphBuilder(self):
            """DAG networks (ref: NeuralNetConfiguration.Builder.graphBuilder)."""
            from deeplearning4j_tpu.nn.conf.graph import GraphBuilder
            return GraphBuilder(self)

    class ListBuilder:
        def __init__(self, parent: "NeuralNetConfiguration.Builder"):
            self._parent = parent
            self._layers: List[Layer] = []
            self._input_type: Optional[InputType] = None
            self._backprop_type = "Standard"
            self._tbptt_fwd = 20
            self._tbptt_back = 20

        def layer(self, *args) -> "NeuralNetConfiguration.ListBuilder":
            """.layer(l) or .layer(index, l) (reference supports both)."""
            l = args[-1]
            self._layers.append(l)
            return self

        def setInputType(self, it: InputType):
            self._input_type = it
            return self

        def backpropType(self, bt: str):
            self._backprop_type = bt
            return self

        def tBPTTForwardLength(self, n: int):
            self._tbptt_fwd = n
            return self

        def tBPTTBackwardLength(self, n: int):
            self._tbptt_back = n
            return self

        def build(self) -> MultiLayerConfiguration:
            p = self._parent
            globals_ = {
                "activation": p._activation,
                "weightInit": p._weightInit,
                "biasInit": p._biasInit,
                "dropOut": p._dropOut,
            }
            it = self._input_type.as_cnn() if self._input_type else None
            if it is None and self._layers:
                # no explicit InputType: synthesize from the first layer's nIn so
                # downstream nIn auto-fill still works (ref: dl4j requires explicit
                # nIn when no InputType is set; we propagate it instead)
                from deeplearning4j_tpu.nn.conf.layers import (
                    BaseRecurrentLayer, Bidirectional, EmbeddingSequenceLayer,
                )
                first = self._layers[0]
                n_in = getattr(first, "nIn", 0)
                if isinstance(first, Bidirectional):
                    n_in = getattr(first.fwd, "nIn", 0)
                if n_in:
                    if isinstance(first, (BaseRecurrentLayer, EmbeddingSequenceLayer)) or (
                            isinstance(first, Bidirectional)):
                        it = InputType.recurrent(n_in)
                    else:
                        it = InputType.feedForward(n_in)
            for layer in self._layers:
                layer.inherit(globals_)
                if it is not None:
                    layer.set_n_in(it)
                    it = layer.output_type(it)
            return MultiLayerConfiguration(
                layers=self._layers,
                seed=p._seed,
                updater=p._updater,
                inputType=self._input_type,
                regularization=p._regularization,
                gradientNormalization=p._gradNorm,
                gradientNormalizationThreshold=p._gradNormThreshold,
                backpropType=self._backprop_type,
                tbpttFwdLength=self._tbptt_fwd,
                tbpttBackLength=self._tbptt_back,
                dataType=p._dataType,
            )
