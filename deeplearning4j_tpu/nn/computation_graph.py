"""ComputationGraph runtime (ref: org.deeplearning4j.nn.graph.ComputationGraph,
~6k LoC: topo-sorted GraphVertex[] execution with per-op JNI dispatch).

TPU-native redesign: the DAG is traversed in Python at TRACE time only — the
whole forward/backward/update collapses into one jit-compiled XLA program, the
same architecture shift as MultiLayerNetwork (see multilayer.py docstring).
Supports multiple inputs (fit(MultiDataSet)) and multiple outputs (loss =
sum over output layers, as the reference sums ComputationGraph scores)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.eval import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.ndarray.array import NDArray, _unwrap
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration, GraphNode, GraphVertex)
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer, BaseRecurrentLayer, Bidirectional, ConvolutionLayer,
    FeedForwardLayer, GlobalPoolingLayer, LastTimeStep, Layer, LossLayer,
    RnnOutputLayer, BatchNormalization)
from deeplearning4j_tpu.nn.multilayer import _as_jnp, _clip_grads


class ComputationGraph:
    """DAG network over a ComputationGraphConfiguration."""

    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self._order: List[GraphNode] = conf.topo_order()
        self._by_name: Dict[str, GraphNode] = {n.name: n for n in self._order}
        self._params: Optional[Dict[str, dict]] = None
        self._state: Optional[Dict[str, dict]] = None
        self._opt_state = None
        self._tx = None
        self._iteration = 0
        self._epoch = 0
        self._score = float("nan")
        self.listeners: List[Any] = []
        self._jit_cache: dict = {}
        from deeplearning4j_tpu.nn.multilayer import _DeviceCache
        self._dev_cache = _DeviceCache()
        self._rng_key = jax.random.key(conf.seed)
        self._dtype = jnp.float32 if conf.dataType == "FLOAT" else (
            jnp.float64 if conf.dataType == "DOUBLE" else jnp.bfloat16)

    # ------------------------------------------------------------------ init
    def init(self):
        key = jax.random.key(self.conf.seed)
        param_nodes = [n for n in self._order
                       if isinstance(n.op, Layer) or getattr(n.op, "has_params", False)]
        keys = jax.random.split(key, max(len(param_nodes), 1))
        self._params = {}
        self._state = {}
        for i, n in enumerate(param_nodes):
            self._params[n.name] = n.op.init_params(keys[i], self._dtype)
            self._state[n.name] = n.op.init_state(self._dtype) \
                if isinstance(n.op, Layer) else {}
        self._tx = self.conf.updater.to_optax()
        self._opt_state = self._tx.init(self._params)
        return self

    # -------------------------------------------------------------- forward
    def _adapt(self, layer: Layer, x):
        """CNN->FF flatten adapter (same rule as MultiLayerNetwork._forward)."""
        from deeplearning4j_tpu.nn.conf.layers import needs_flatten
        if needs_flatten(layer, x.ndim):
            return x.reshape(x.shape[0], -1)
        return x

    def _forward(self, params, state, inputs: Dict[str, Any], *, training, rng,
                 masks: Optional[Dict[str, Any]] = None):
        acts: Dict[str, Any] = dict(inputs)
        if self._dtype != jnp.float32:  # HALF/DOUBLE nets: cast float inputs
            # once; integer inputs (embedding ids) must not round through bf16
            acts = {k: (v.astype(self._dtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in acts.items()}
        new_state: Dict[str, dict] = {}
        n_layers = max(sum(1 for n in self._order if isinstance(n.op, Layer)), 1)
        rngs = jax.random.split(rng, n_layers) if rng is not None else None
        li = 0
        for node in self._order:
            xs = [acts[i] for i in node.inputs]
            if isinstance(node.op, GraphVertex):
                if getattr(node.op, "has_params", False):
                    acts[node.name] = node.op.apply(
                        xs, params=params.get(node.name, {}), training=training)
                else:
                    acts[node.name] = node.op.apply(xs, training=training)
                continue
            layer = node.op
            x = self._adapt(layer, xs[0])
            r = rngs[li] if rngs is not None else None
            li += 1
            # conf-level input dropout — but NOT for DropoutLayer itself,
            # whose apply() already drops (double-apply over-regularizes;
            # same guard as multilayer.py's _DropoutLike check)
            from deeplearning4j_tpu.nn.conf.layers import DropoutLayer as _DL
            if training and layer.dropOut is not None and r is not None \
                    and not isinstance(layer, _DL):
                from deeplearning4j_tpu.nn.conf.dropout import apply_dropout
                x = apply_dropout(layer.dropOut, jax.random.fold_in(r, 7), x)
            kwargs = {}
            mask = (masks or {}).get(node.inputs[0])
            if isinstance(layer, (BaseRecurrentLayer, Bidirectional, LastTimeStep,
                                  GlobalPoolingLayer)) and mask is not None:
                kwargs["mask"] = mask
            out, st = layer.apply(params.get(node.name, {}), x, training=training,
                                  rng=r, state=state.get(node.name) or None, **kwargs)
            acts[node.name] = out
            new_state[node.name] = st if st is not None else {}
        return acts, new_state

    def _loss_for(self, params, state, inputs, labels, rng, lmasks, fmasks=None):
        acts, new_state = self._forward(params, state, inputs, training=True, rng=rng,
                                        masks=fmasks)
        loss = 0.0
        for i, out_name in enumerate(self.conf.networkOutputs):
            layer = self._by_name[out_name].op
            y = labels[i]
            lm = lmasks[i] if lmasks is not None else None
            from deeplearning4j_tpu.nn.conf.layers import CenterLossOutputLayer
            if isinstance(layer, CenterLossOutputLayer):
                loss = loss + layer.compute_loss_ext(
                    params.get(out_name, {}), y, acts[out_name],
                    new_state[out_name]["features"], lm)
                new_state = dict(new_state)
                new_state[out_name] = {}  # aux features must not persist
            elif hasattr(layer, "loss_with_params"):
                loss = loss + layer.loss_with_params(
                    params.get(out_name, {}), y, acts[out_name], lm)
            elif isinstance(layer, (BaseOutputLayer, LossLayer)):
                loss = loss + layer.compute_loss(y, acts[out_name], lm)
            else:
                loss = loss + jnp.mean((acts[out_name] - y) ** 2)
        for reg in self.conf.regularization:
            for name, p in params.items():
                layer = self._by_name[name].op
                for k in layer.regularizable():
                    if k in p:
                        loss = loss + reg.penalty(p[k])
        return loss, new_state

    # ----------------------------------------------------------- jitted fns
    def _build_step(self, with_stats: bool = False):
        """See MultiLayerNetwork._build_step — same contract; ``with_stats``
        also returns grad + update trees for StatsListener/panic listeners."""
        conf = self.conf

        frozen = {n.name for n in self._order if getattr(n.op, "frozen", False)}

        def zero_frozen(tree_dict):
            if not frozen:
                return tree_dict
            return {k: (jax.tree_util.tree_map(jnp.zeros_like, g) if k in frozen else g)
                    for k, g in tree_dict.items()}

        def step(params, state, opt_state, inputs, labels, rng, lmasks, fmasks):
            (loss, new_state), grads = jax.value_and_grad(
                self._loss_for, has_aux=True)(params, state, inputs, labels, rng,
                                              lmasks, fmasks)
            grads = zero_frozen(grads)  # (ref: FrozenLayer)
            grads = _clip_grads(grads, conf.gradientNormalization,
                                conf.gradientNormalizationThreshold)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            updates = zero_frozen(updates)  # AdamW decay must not touch frozen params
            new_params = optax.apply_updates(params, updates)
            if with_stats:
                return new_params, new_state, opt_state, loss, grads, updates
            return new_params, new_state, opt_state, loss

        return jax.jit(step, donate_argnums=() if with_stats else (0, 2))

    def _stats_requested(self) -> bool:
        return any(getattr(l, "requiresGradients", False)
                   or getattr(l, "requiresUpdates", False)
                   for l in self.listeners)

    # see MultiLayerNetwork.fuseSteps — same de-dispatch rationale
    fuseSteps: int = 8
    # see MultiLayerNetwork.listenerReplayLag — lagged batched replay
    listenerReplayLag: int = 16

    def _build_multi_step(self):
        """``fuseSteps`` steps in one executable (lax.scan over stacked
        minibatches) — see MultiLayerNetwork._build_multi_step."""
        conf = self.conf
        frozen = {n.name for n in self._order if getattr(n.op, "frozen", False)}

        def zero_frozen(tree_dict):
            if not frozen:
                return tree_dict
            return {k: (jax.tree_util.tree_map(jnp.zeros_like, g) if k in frozen else g)
                    for k, g in tree_dict.items()}

        def body(carry, inp):
            params, state, opt_state = carry
            inputs, labels, rng = inp
            (loss, new_state), grads = jax.value_and_grad(
                self._loss_for, has_aux=True)(params, state, inputs, labels,
                                              rng, None, None)
            grads = zero_frozen(grads)
            grads = _clip_grads(grads, conf.gradientNormalization,
                                conf.gradientNormalizationThreshold)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            updates = zero_frozen(updates)
            params = optax.apply_updates(params, updates)
            return (params, new_state, opt_state), loss

        def multi(params, state, opt_state, inputs_stacked, labels_stacked,
                  rngs):
            (params, state, opt_state), losses = jax.lax.scan(
                body, (params, state, opt_state),
                (inputs_stacked, labels_stacked, rngs))
            # full per-step losses: fit() replays them to listeners
            return params, state, opt_state, losses

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def _build_infer(self):
        def infer(params, state, inputs, fmasks):
            acts, _ = self._forward(params, state, inputs, training=False, rng=None,
                                    masks=fmasks)
            return [acts[o] for o in self.conf.networkOutputs]

        return jax.jit(infer)

    def _get_jitted(self, kind):
        if kind not in self._jit_cache:
            builders = {"step": self._build_step, "infer": self._build_infer,
                        "step_stats": lambda: self._build_step(with_stats=True),
                        "multi": self._build_multi_step}
            self._jit_cache[kind] = builders[kind]()
        return self._jit_cache[kind]

    # ------------------------------------------------------------------ fit
    def _input_dict(self, features: Sequence) -> Dict[str, Any]:
        return {name: _as_jnp(f) for name, f in zip(self.conf.networkInputs, features)}

    def fit(self, data, labels=None, epochs: int = 1):
        """fit(DataSet/MultiDataSet), fit(iterator), fit(features, labels).
        A crash during training writes a diagnostic dump (ref:
        CrashReportingUtil), then re-raises."""
        try:
            return self._fit_impl(data, labels, epochs)
        except Exception as e:  # dump-and-reraise; reporting never masks the error
            from deeplearning4j_tpu.util import crash_reporting
            if not getattr(e, "_control_flow", False):  # early-stop signals etc.
                crash_reporting.writeMemoryCrashDump(self, e)
            raise

    def _fit_impl(self, data, labels=None, epochs: int = 1):
        if labels is not None:
            data = [MultiDataSet([data], [labels])]
        elif isinstance(data, DataSet):
            data = [data.toMultiDataSet()]
        elif isinstance(data, MultiDataSet):
            data = [data]
        stats = self._stats_requested()
        step = self._get_jitted("step_stats" if stats else "step")
        # listeners no longer disable fusing — see MultiLayerNetwork._fit_impl
        fuse_k = 0 if stats else self.fuseSteps
        buf: list = []  # (features tuple, labels tuple) host batches
        from deeplearning4j_tpu.nn.multilayer import _ReplayQueue
        rq = _ReplayQueue(self)

        def run_single(mds):
            rq.drain()   # callback order: buffered chunks before this step
            raws = [_unwrap(f) for f in mds.features] + \
                   [_unwrap(y) for y in mds.labels]
            maskless = not any(m is not None
                               for m in (mds.features_masks or [])) \
                and not any(m is not None for m in (mds.labels_masks or []))
            if maskless and all(isinstance(r, np.ndarray) for r in raws):
                inputs, ys = self._dev_cache.get_or_put(
                    raws, lambda: (self._input_dict(mds.features),
                                   [_as_jnp(y) for y in mds.labels]))
            else:
                inputs = self._input_dict(mds.features)
                ys = [_as_jnp(y) for y in mds.labels]
            lmasks = [(_as_jnp(m) if m is not None else None)
                      for m in (mds.labels_masks or [None] * len(ys))]
            if all(m is None for m in lmasks):
                lmasks = None
            fmasks = {name: _as_jnp(m)
                      for name, m in zip(self.conf.networkInputs,
                                         mds.features_masks or [])
                      if m is not None} or None
            self._rng_key, sub = jax.random.split(self._rng_key)
            if stats:
                (self._params, self._state, self._opt_state, loss,
                 self._last_grads, self._last_updates) = step(
                    self._params, self._state, self._opt_state, inputs, ys, sub,
                    lmasks, fmasks)
            else:
                self._params, self._state, self._opt_state, loss = step(
                    self._params, self._state, self._opt_state, inputs, ys, sub,
                    lmasks, fmasks)
            self._score = loss  # device scalar; score() syncs on demand
            self._iteration += 1
            rq.dispatched += 1
            for lst in self.listeners:
                lst.iterationDone(self, self._iteration, self._epoch)

        def drain(buf):
            for item in buf:  # singles reuse the already-compiled step
                run_single(item[2])
            return []

        def flush(buf):
            from deeplearning4j_tpu.nn.multilayer import (
                _chain_split, _chunk_limit, _stack_batches)
            while buf:
                k = _chunk_limit(self.listeners, rq.dispatched, fuse_k)
                if k <= 1:
                    run_single(buf[0][2])
                    buf = buf[1:]
                    continue
                if len(buf) < k:
                    break
                chunk, buf = buf[:k], buf[k:]

                def build():
                    return ({name: _stack_batches([c[0][i] for c in chunk])
                             for i, name in enumerate(self.conf.networkInputs)},
                            [_stack_batches([c[1][i] for c in chunk])
                             for i in range(len(chunk[0][1]))])

                raws = [_unwrap(f) for c in chunk for f in c[0]] + \
                       [_unwrap(y) for c in chunk for y in c[1]]
                if all(isinstance(r, np.ndarray) for r in raws):
                    inputs, ys = self._dev_cache.get_or_put(raws, build)
                else:
                    inputs, ys = build()
                # RNG stream identical to k single steps
                self._rng_key, rngs = _chain_split(self._rng_key, k)
                multi = self._get_jitted("multi")
                (self._params, self._state, self._opt_state,
                 losses) = multi(self._params, self._state,
                                 self._opt_state, inputs, ys, rngs)
                rq.push(losses, k)
            return buf

        def _sig(mds):
            return ([np.shape(f) for f in mds.features],
                    [np.shape(y) for y in mds.labels])

        try:
            for _ in range(epochs):
                for ds in data:
                    mds = ds.toMultiDataSet() if isinstance(ds, DataSet) else ds
                    maskfree = not any(m is not None
                                       for m in (mds.features_masks or [])) \
                        and not any(m is not None
                                    for m in (mds.labels_masks or []))
                    if fuse_k > 1 and maskfree:
                        if buf and _sig(buf[0][2]) != _sig(mds):
                            buf = drain(buf)  # shape change: drain as singles
                        buf.append((mds.features, mds.labels, mds))
                        buf = flush(buf)
                    else:
                        # masked batch: buffered earlier steps apply FIRST
                        # (sequential SGD order, round-3 advisor)
                        buf = drain(buf)
                        run_single(mds)
                # epoch boundary: apply leftovers before onEpochEnd
                buf = drain(buf)
                rq.drain()
                self._epoch += 1
                for lst in self.listeners:
                    if hasattr(lst, "onEpochEnd"):
                        lst.onEpochEnd(self)
        except BaseException:
            try:
                rq.drain()   # deliver completed chunks' callbacks
            except Exception:
                pass
            raise
        return self

    # ------------------------------------------------------------- inference
    def output(self, *features, train: bool = False, features_masks=None) -> List[NDArray]:
        """(ref: ComputationGraph.output) — returns one NDArray per network
        output."""
        infer = self._get_jitted("infer")
        fmasks = {name: _as_jnp(m)
                  for name, m in zip(self.conf.networkInputs, features_masks or [])
                  if m is not None} or None
        outs = infer(self._params, self._state, self._input_dict(features), fmasks)
        return [NDArray(o) for o in outs]

    def outputSingle(self, *features) -> NDArray:
        return self.output(*features)[0]

    def warmup(self, *example_rows, batch_sizes=(1,)) -> "ComputationGraph":
        """Pre-compile inference for the given batch sizes; one example row
        (no batch dim) per network input. See MultiLayerNetwork.warmup —
        the serving registry's warmup-on-deploy hook."""
        exs = [np.asarray(e) for e in example_rows]
        for b in batch_sizes:
            feats = [np.broadcast_to(e, (b,) + e.shape).copy() for e in exs]
            for o in self.output(*feats):
                np.asarray(o.jax)
        return self

    def feedForward(self, *features) -> Dict[str, NDArray]:
        acts, _ = self._forward(self._params, self._state,
                                self._input_dict(features), training=False, rng=None)
        return {k: NDArray(v) for k, v in acts.items()}

    # ---------------------------------------------------------------- score
    def score(self, dataset=None) -> float:
        if dataset is None:
            return float(self._score)
        mds = dataset.toMultiDataSet() if isinstance(dataset, DataSet) else dataset
        loss, _ = self._loss_for(self._params, self._state,
                                 self._input_dict(mds.features),
                                 [_as_jnp(y) for y in mds.labels], None, None, None)
        return float(loss)

    # ----------------------------------------------------------- evaluation
    def evaluate(self, iterator, num_classes: Optional[int] = None) -> Evaluation:
        ev = Evaluation(num_classes)
        for ds in iterator:
            mds = ds.toMultiDataSet() if isinstance(ds, DataSet) else ds
            out = self.output(*mds.features, features_masks=mds.features_masks)[0]
            ev.eval(np.asarray(_unwrap(mds.labels[0])), out.toNumpy(),
                    mask=mds.labels_masks[0] if mds.labels_masks else None)
        return ev

    # ---------------------------------------------------- flat param surface
    def params(self) -> NDArray:
        """Flat params in topological vertex order; tree_flatten within a
        vertex handles nested dicts (e.g. Bidirectional's {'fwd','bwd'})."""
        leaves = []
        for n in self._order:
            if n.name in (self._params or {}):
                leaves.extend(jnp.ravel(l) for l in
                              jax.tree_util.tree_leaves(self._params[n.name]))
        if not leaves:
            return NDArray(jnp.zeros((0,)))
        return NDArray(jnp.concatenate(leaves))

    def setParams(self, flat):
        flat = _as_jnp(flat).ravel()
        pos = 0
        for n in self._order:
            if n.name in (self._params or {}):
                leaves, treedef = jax.tree_util.tree_flatten(self._params[n.name])
                new = []
                for l in leaves:
                    cnt = int(np.prod(l.shape))
                    new.append(flat[pos:pos + cnt].reshape(l.shape).astype(l.dtype))
                    pos += cnt
                self._params[n.name] = jax.tree_util.tree_unflatten(treedef, new)

    def numParams(self) -> int:
        return int(sum(np.prod(l.shape)
                       for l in jax.tree_util.tree_leaves(self._params)))

    # ------------------------------------------------------------- listeners
    def setListeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def addListeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def setHostTransferCache(self, enabled: bool):
        """Toggle the host->device minibatch transfer cache (on by default;
        mutation-safe — see _DeviceCache)."""
        self._dev_cache.enabled = enabled
        return self

    def getIterationCount(self) -> int:
        return self._iteration

    def getEpochCount(self) -> int:
        return self._epoch

    def clone(self) -> "ComputationGraph":
        other = ComputationGraph(self.conf)
        if self._params is not None:
            other._params = jax.tree_util.tree_map(lambda a: a, self._params)
            other._state = jax.tree_util.tree_map(lambda a: a, self._state)
            other._tx = self.conf.updater.to_optax()
            other._opt_state = other._tx.init(other._params)
        return other

    def summary(self) -> str:
        rows = [("name", "type", "inputs", "nParams")]
        total = 0
        for n in self._order:
            p = (self._params or {}).get(n.name, {})
            cnt = int(sum(np.prod(v.shape) for v in p.values()))
            total += cnt
            rows.append((n.name, type(n.op).__name__, ",".join(n.inputs), str(cnt)))
        widths = [max(len(r[c]) for r in rows) for c in range(4)]
        lines = ["  ".join(r[c].ljust(widths[c]) for c in range(4)) for r in rows]
        lines.append(f"Total params: {total}")
        return "\n".join(lines)
