"""MultiLayerNetwork (ref: org.deeplearning4j.nn.multilayer.MultiLayerNetwork,
~5k LoC) — the sequential network runtime.

Architectural shift vs the reference (SURVEY.md §3.1): the reference's fit loop
makes dozens of JNI op calls per step (per-layer forward, per-layer backward,
per-block updater). Here **one training step = one XLA executable**: forward +
loss + regularization + backward (jax.grad) + optimizer update are traced
together and jit-compiled with donated param/opt-state buffers — the
whole-graph execution model SameDiff gestured at but never realized natively.

The reference's workspace machinery (LayerWorkspaceMgr, WS_* scopes) is
deleted: XLA buffer assignment owns activation memory. Flat-parameter-vector
semantics (paramsFlattened) are preserved at the API boundary via
params()/setParams() for serializer/averaging parity.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.eval import Evaluation, RegressionEvaluation
from deeplearning4j_tpu.ndarray.array import NDArray, _unwrap
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer, BaseRecurrentLayer, Bidirectional, ConvolutionLayer, FeedForwardLayer,
    GlobalPoolingLayer, LastTimeStep, Layer, LossLayer, RnnOutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.data.dataset import DataSet, DataSetIterator, ListDataSetIterator


def _as_jnp(x, dtype=None):
    x = _unwrap(x)
    if isinstance(x, np.ndarray) or not isinstance(x, jax.Array):
        x = jnp.asarray(x)
    return x.astype(dtype) if dtype is not None else x


def _clip_grads(grads, mode: Optional[str], threshold: float):
    """Gradient normalization (ref: org.deeplearning4j.nn.conf.GradientNormalization)."""
    if mode is None:
        return grads
    if mode == "ClipElementWiseAbsoluteValue":
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -threshold, threshold), grads)
    if mode in ("ClipL2PerLayer", "ClipL2PerParamType"):
        def clip_layer(layer_grads):
            return {k: _clip_l2(v, threshold) for k, v in layer_grads.items()} \
                if isinstance(layer_grads, dict) else layer_grads
        if mode == "ClipL2PerParamType":
            return [clip_layer(g) for g in grads]
        out = []
        for g in grads:
            leaves = jax.tree_util.tree_leaves(g)
            if not leaves:
                out.append(g)
                continue
            norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves))
            scale = jnp.where(norm > threshold, threshold / (norm + 1e-12), 1.0)
            out.append(jax.tree_util.tree_map(lambda l: l * scale, g))
        return out
    if mode == "RenormalizeL2PerLayer":
        out = []
        for g in grads:
            leaves = jax.tree_util.tree_leaves(g)
            if not leaves:
                out.append(g)
                continue
            norm = jnp.sqrt(sum(jnp.sum(l * l) for l in leaves))
            out.append(jax.tree_util.tree_map(lambda l: l / (norm + 1e-12), g))
        return out
    raise ValueError(f"unknown gradientNormalization: {mode}")


def _clip_l2(g, threshold):
    norm = jnp.sqrt(jnp.sum(g * g))
    return g * jnp.where(norm > threshold, threshold / (norm + 1e-12), 1.0)


def _stack_batches(items):
    """Stack K minibatches into one (K, ...) array with a SINGLE host->device
    transfer when the sources are host arrays (the common iterator case)."""
    raw = [_unwrap(i) for i in items]
    if all(isinstance(r, np.ndarray) for r in raw):
        return jnp.asarray(np.stack(raw))
    return jnp.stack([_as_jnp(i) for i in items])


class _DeviceCache:
    """Identity-keyed host->device transfer cache (bounded FIFO).

    The axon tunnel's host->device bandwidth is orders of magnitude below
    PCIe (measured ~6-60 MB/s, BASELINE.md round-3), so re-transferring the
    same minibatch every epoch dominates small training steps. Training
    loops that revisit the same host arrays (fit(ds, epochs=N), epoch
    iterators over in-memory data) hit this cache and transfer once — the
    TPU answer to the reference's workspace-pinned device buffers
    (ref: MemoryWorkspace / AsyncDataSetIterator prefetch-to-GPU).

    Safety/limits (round-4 advisor findings):
    - **In-place mutation IS observed**: every hit verifies the current
      host bytes against a snapshot taken at insert (np.array_equal — host
      memcmp is 100-1000x cheaper than a tunnel re-transfer) and rebuilds
      on mismatch, so pipelines that refill a preallocated batch buffer
      train on the fresh data.
    - **Byte-bounded**, not entry-bounded: entries evict FIFO once the
      summed host-array bytes (a proxy for the pinned device copies)
      exceed ``max_bytes``.
    - **Streaming detection**: after ``_STREAM_MISSES`` consecutive
      misses the cache stops inserting (it would only pin HBM for batches
      that never repeat); a hit re-arms it.
    Disable entirely with ``enabled = False`` (networks expose
    ``setHostTransferCache``)."""

    _STREAM_MISSES = 16

    def __init__(self, max_bytes: int = 2 << 30):
        self.max_bytes = max_bytes
        self.enabled = True
        self._d: dict = {}
        self._bytes = 0
        self._consec_misses = 0

    def _evict_to_fit(self):
        while self._bytes > self.max_bytes and self._d:
            _, snaps = self._d.pop(next(iter(self._d)))  # FIFO (insert order)
            self._bytes -= sum(s.nbytes for s in snaps)

    def get_or_put(self, raws, build):
        if not self.enabled:
            return build()
        key = tuple(id(r) for r in raws)
        hit = self._d.get(key)
        if hit is not None:
            value, snaps = hit
            if all(np.array_equal(r, s) for r, s in zip(raws, snaps)):
                self._consec_misses = 0
                return value
            # host buffer was mutated in place: rebuild and re-snapshot
            # (still a key hit — re-arm streaming detection)
            self._consec_misses = 0
            value = build()
            self._bytes -= sum(s.nbytes for s in snaps)
            new_snaps = [np.array(r, copy=True) for r in raws]
            self._bytes += sum(s.nbytes for s in new_snaps)
            self._d[key] = (value, new_snaps)
            self._evict_to_fit()
            return value
        value = build()
        self._consec_misses += 1
        if self._consec_misses > self._STREAM_MISSES:
            return value  # streaming workload: don't pin HBM for one-shots
        snaps = [np.array(r, copy=True) for r in raws]
        self._bytes += sum(s.nbytes for s in snaps)
        self._d[key] = (value, snaps)
        self._evict_to_fit()
        return value


import functools as _functools


@_functools.partial(jax.jit, static_argnums=1)
def _chain_split(key, k: int):
    """k sequential ``key, sub = jax.random.split(key)`` draws in ONE
    dispatch (lax.scan). Returns (advanced_key, (k, ...) stacked subs) with
    values IDENTICAL to the per-step loop — so the fused multi-step path
    consumes the RNG stream exactly like the single-step path and the same
    seed yields the same trajectory regardless of fusing (round-3 advisor
    finding)."""

    def body(c, _):
        ks = jax.random.split(c)
        return ks[0], ks[1]

    return jax.lax.scan(body, key, None, length=k)


def _chunk_limit(listeners, iteration: int, fuse_k: int) -> int:
    """Steps the fused fit may scan from ``iteration`` before some listener
    needs the live model (1 = no fusing right now). Shared by
    MultiLayerNetwork and ComputationGraph."""
    k = fuse_k
    for lst in listeners:
        req = getattr(lst, "requiresModelAtIteration", lambda it: True)
        for j in range(1, k + 1):
            if req(iteration + j):
                k = j
                break
    return k


class _ReplayQueue:
    """Lagged, batched listener replay for the fused fit paths (round 5,
    shared by MultiLayerNetwork and ComputationGraph — same design as
    SameDiff.fit's drain_pending). Completed chunks' device losses queue
    here; score-only listener callbacks replay up to ``listenerReplayLag``
    chunks behind the dispatch head, and each drain moves ALL drained
    chunks' losses device->host in ONE transfer — on a tunneled device any
    host read is a full round trip, so per-chunk syncing serializes the
    scan pipeline (measured -32% on the SameDiff bench before this).
    ``dispatched`` tracks the dispatch head for _chunk_limit; the net's
    ``_iteration`` advances only at replay (so listeners see exact
    per-step iteration numbers)."""

    def __init__(self, net, replay=None):
        self.net = net
        # replay(losses, k): fire one chunk's worth of per-step callbacks.
        # Default is the MLN/CG _replay_chunk; SameDiff.fit passes its own
        # (history-indexed iteration numbers) so all three fit paths share
        # THIS queue/transfer logic instead of three hand-rolled copies.
        self.replay = replay or (lambda losses, k: _replay_chunk(net, losses, k))
        self.pending: list = []
        self.dispatched = getattr(net, "_iteration", 0)

    def push(self, losses, k: int):
        self.dispatched += k
        self.pending.append((k, losses))
        need_model = any(
            getattr(l, "requiresModelAtIteration", lambda it: True)(
                self.dispatched) for l in self.net.listeners)
        if need_model or not self.net.listeners:
            # boundary listeners must observe the model exactly as of this
            # chunk end (before anything newer overwrites it); without
            # listeners the replay is free bookkeeping — keep it current
            self.drain()
        else:
            self.drain(keep=max(
                int(getattr(self.net, "listenerReplayLag", 16)), 0))

    def drain(self, keep: int = 0):
        if len(self.pending) <= keep:
            return
        take = self.pending[:len(self.pending) - keep]
        self.pending = self.pending[len(self.pending) - keep:]
        if self.net.listeners:
            flat = np.asarray(jnp.concatenate(
                [jnp.ravel(l) for _, l in take])).astype(float)
            off = 0
            for k, _ in take:
                self.replay(flat[off:off + k], k)
                off += k
        else:
            for k, losses in take:
                self.replay(losses, k)


def _replay_chunk(net, losses, k: int):
    """Replay k buffered per-step losses to listeners after a fused chunk —
    the same callback sequence the per-step path fires, with the model
    synced at chunk end (= every requiresModelAtIteration boundary).
    ``losses`` arrive already host-converted from _ReplayQueue.drain's
    single bulk transfer when listeners are attached; the conversion here
    covers direct callers only."""
    if net.listeners and not isinstance(losses, np.ndarray):
        losses = np.asarray(losses).astype(float)
    for j in range(k):
        net._score = losses[j]
        net._iteration += 1
        for lst in net.listeners:
            lst.iterationDone(net, net._iteration, net._epoch)


def _zero_frozen(tree_list, frozen):
    """Zero per-layer grad/update entries for frozen layers (ref: FrozenLayer)."""
    if not any(frozen):
        return tree_list
    return [jax.tree_util.tree_map(jnp.zeros_like, t) if frozen[i] else t
            for i, t in enumerate(tree_list)]


class MultiLayerNetwork:
    """Sequential network over a MultiLayerConfiguration."""

    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self._params: Optional[list] = None
        self._state: Optional[list] = None
        self._opt_state = None
        self._tx: Optional[optax.GradientTransformation] = None
        self._iteration = 0
        self._epoch = 0
        self._score = float("nan")
        self.listeners: List[Any] = []
        self._jit_cache: dict = {}
        self._dev_cache = _DeviceCache()
        self._rng_key = jax.random.key(conf.seed)
        self._dtype = jnp.float32 if conf.dataType == "FLOAT" else (
            jnp.float64 if conf.dataType == "DOUBLE" else jnp.bfloat16)

    # ------------------------------------------------------------------ init
    def init(self):
        """Initialize params/state deterministically from conf.seed (ref:
        MultiLayerNetwork.init + param initializers)."""
        key = jax.random.key(self.conf.seed)
        keys = jax.random.split(key, max(len(self.layers), 1))
        self._params = [l.init_params(keys[i], self._dtype) for i, l in enumerate(self.layers)]
        self._state = [l.init_state(self._dtype) for l in self.layers]
        self._tx = self.conf.updater.to_optax()
        self._opt_state = self._tx.init(self._params)
        return self

    # -------------------------------------------------------------- forward
    def _adapt_input(self, x):
        it = self.conf.inputType
        if it is not None and it.kind == "cnnflat" and x.ndim == 2:
            x = x.reshape(x.shape[0], it.channels, it.height, it.width)
        # HALF/DOUBLE nets: float inputs join the conf dtype (convs reject
        # mixed operands). Integer inputs (embedding token ids) must NOT
        # round-trip through bf16 — ids > 256 would silently collide.
        if self._dtype != jnp.float32 and jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(self._dtype)
        return x

    def _forward(self, params, state, x, *, training, rng, mask=None, rnn_states=None):
        """Full forward pass; returns (output, new_states, new_rnn_states).
        Auto-inserts the CNN->FF flatten the reference handles via
        InputPreProcessors. When ``rnn_states`` is given, recurrent layers run
        from that state and report their final state (ref:
        rnnActivateUsingStoredState — the tBPTT/streaming path)."""
        x = self._adapt_input(x)
        new_states, new_rnn = [], []
        n = len(self.layers)
        rngs = jax.random.split(rng, n) if rng is not None else [None] * n
        from deeplearning4j_tpu.nn.conf.layers import needs_flatten
        for i, layer in enumerate(self.layers):
            # preprocessor-equivalent: flatten NCHW/NCDHW into (B, -1) for FF layers
            if needs_flatten(layer, x.ndim):
                x = x.reshape(x.shape[0], -1)
            # dl4j conf-level dropout: applied to the layer INPUT during training
            if training and layer.dropOut is not None and not isinstance(layer, _DropoutLike):
                from deeplearning4j_tpu.nn.conf.dropout import apply_dropout
                if rngs[i] is not None:
                    x = apply_dropout(layer.dropOut,
                                      jax.random.fold_in(rngs[i], 7), x)
            if rnn_states is not None and isinstance(layer, BaseRecurrentLayer) \
                    and rnn_states[i]:
                kwargs = {"mask": mask} if mask is not None else {}
                x, rs = layer.apply_rnn(params[i], x, rnn_states[i], **kwargs)
                new_rnn.append(rs)
                new_states.append(state[i] if state[i] else {})
                continue
            kwargs = {}
            if isinstance(layer, (BaseRecurrentLayer, Bidirectional, LastTimeStep,
                                  GlobalPoolingLayer)) and mask is not None:
                kwargs["mask"] = mask
            x, st = layer.apply(params[i], x, training=training, rng=rngs[i],
                                state=state[i] if state[i] else None, **kwargs)
            new_states.append(st if st is not None else {})
            new_rnn.append({})
        return x, new_states, new_rnn

    # ----------------------------------------------------------- jitted fns
    def _loss_for(self, params, state, x, y, rng, fmask, lmask):
        out, new_states, _ = self._forward(params, state, x, training=True, rng=rng, mask=fmask)
        out_layer = self.layers[-1]
        from deeplearning4j_tpu.nn.conf.layers import CenterLossOutputLayer
        if isinstance(out_layer, CenterLossOutputLayer):
            loss = out_layer.compute_loss_ext(params[-1], y, out,
                                              new_states[-1]["features"], lmask)
            # the features were an aux channel for THIS loss only — strip
            # them so a batch of activations is never persisted as model
            # state (it would pin device memory and retrace on batch change)
            new_states = new_states[:-1] + [{}]
        elif hasattr(out_layer, "loss_with_params"):  # OCNN: loss needs own params
            loss = out_layer.loss_with_params(params[-1], y, out, lmask)
        elif hasattr(out_layer, "compute_loss"):  # output/loss/yolo layers
            loss = out_layer.compute_loss(y, out, lmask if lmask is not None else
                                          (fmask if isinstance(out_layer, RnnOutputLayer) else None))
        else:
            loss = jnp.mean((out - y) ** 2)
        # regularization (ref: BaseLayer.calcRegularizationScore summed into score)
        for reg in self.conf.regularization:
            for i, layer in enumerate(self.layers):
                for k in layer.regularizable():
                    if k in params[i]:
                        loss = loss + reg.penalty(params[i][k])
        return loss, new_states

    def _build_step(self, with_stats: bool = False):
        """One XLA executable: grad → clip → update. ``with_stats`` variants
        also return the gradient and applied-update trees for listeners
        advertising requiresGradients/requiresUpdates (StatsListener,
        panic-mode ProfilingListener); params are then NOT donated since the
        returned trees alias them."""
        conf = self.conf

        frozen = [getattr(l, "frozen", False) for l in self.layers]

        def step(params, state, opt_state, x, y, rng, fmask, lmask):
            (loss, new_states), grads = jax.value_and_grad(
                self._loss_for, has_aux=True)(params, state, x, y, rng, fmask, lmask)
            grads = _zero_frozen(grads, frozen)
            grads = _clip_grads(grads, conf.gradientNormalization,
                                conf.gradientNormalizationThreshold)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            # zero the UPDATES too: decoupled weight decay (AdamW) would
            # otherwise mutate frozen params despite zero grads (ref:
            # FrozenLayer applies no update at all)
            updates = _zero_frozen(updates, frozen)
            new_params = optax.apply_updates(params, updates)
            if with_stats:
                return new_params, new_states, opt_state, loss, grads, updates
            return new_params, new_states, opt_state, loss

        return jax.jit(step, donate_argnums=() if with_stats else (0, 2))

    def _stats_requested(self) -> bool:
        return any(getattr(l, "requiresGradients", False)
                   or getattr(l, "requiresUpdates", False)
                   for l in self.listeners)

    # Steps fused into one executable by fit()'s multi-step path. 8 amortizes
    # the axon tunnel's per-dispatch latency (BASELINE.md configs #1-#3 show
    # 2-3x run-to-run spread from it) without inflating compile time.
    fuseSteps: int = 8
    # How many fused chunks score-only listener callbacks may lag the
    # dispatch head before a forced batched replay (see _ReplayQueue;
    # 0 = replay right after every chunk, paying one host round trip each)
    listenerReplayLag: int = 16

    def _build_multi_step(self):
        """``fuseSteps`` training steps in ONE XLA executable: lax.scan over
        stacked minibatches, params/opt-state carried on device. This is the
        de-dispatch move one level up from the per-step fusion — the
        reference's per-op JNI dispatch disease (SURVEY §3.1) reappears as
        per-STEP Python dispatch on small models; the scan deletes it.
        Used by fit() when no listener/mask/tBPTT forces host hops."""
        conf = self.conf
        frozen = [getattr(l, "frozen", False) for l in self.layers]

        def body(carry, inp):
            params, state, opt_state = carry
            x, y, rng = inp
            (loss, new_states), grads = jax.value_and_grad(
                self._loss_for, has_aux=True)(params, state, x, y, rng,
                                              None, None)
            grads = _zero_frozen(grads, frozen)
            grads = _clip_grads(grads, conf.gradientNormalization,
                                conf.gradientNormalizationThreshold)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            updates = _zero_frozen(updates, frozen)
            params = optax.apply_updates(params, updates)
            return (params, new_states, opt_state), loss

        def multi(params, state, opt_state, xs, ys, rngs):
            (params, state, opt_state), losses = jax.lax.scan(
                body, (params, state, opt_state), (xs, ys, rngs))
            # full per-step losses: fit() replays them to listeners after
            # the chunk (one host sync per chunk at most, not per step)
            return params, state, opt_state, losses

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def _build_infer(self):
        def infer(params, state, x, fmask):
            out, _, _ = self._forward(params, state, x, training=False, rng=None, mask=fmask)
            return out

        return jax.jit(infer)

    def _get_jitted(self, kind):
        if kind not in self._jit_cache:
            builders = {"step": self._build_step, "infer": self._build_infer,
                        "step_stats": lambda: self._build_step(with_stats=True),
                        "multi": self._build_multi_step}
            self._jit_cache[kind] = builders[kind]()
        return self._jit_cache[kind]

    # ---------------------------------------------- rnn state (tBPTT/stream)
    def _rnn_format(self) -> str:
        """Time-axis layout of this net's sequence data: 'NWC' (B,T,F) or the
        reference's 'NCW' (B,F,T), taken from the first recurrent layer."""
        for l in self.layers:
            if isinstance(l, BaseRecurrentLayer):
                return l.rnnDataFormat
        return "NWC"

    def _init_rnn_states(self, batch: int) -> list:
        return [l.init_rnn_state(batch, self._dtype)
                if isinstance(l, BaseRecurrentLayer) else {}
                for l in self.layers]

    def _build_tbptt_step(self):
        conf = self.conf
        frozen = [getattr(l, "frozen", False) for l in self.layers]

        def loss_fn(params, state, x, y, rng, fmask, lmask, rnn_states):
            out, new_states, new_rnn = self._forward(
                params, state, x, rnn_states=rnn_states, training=True, rng=rng, mask=fmask)
            out_layer = self.layers[-1]
            if hasattr(out_layer, "compute_loss_ext") or hasattr(out_layer, "loss_with_params"):
                # center-loss/OCNN heads have no tBPTT semantics in the
                # reference either — refuse rather than silently drop terms
                raise NotImplementedError(
                    f"{type(out_layer).__name__} is not supported under TruncatedBPTT")
            if hasattr(out_layer, "compute_loss"):
                loss = out_layer.compute_loss(y, out, lmask if lmask is not None else
                                              (fmask if isinstance(out_layer, RnnOutputLayer) else None))
            else:
                loss = jnp.mean((out - y) ** 2)
            for reg in conf.regularization:
                for i, layer in enumerate(self.layers):
                    for k in layer.regularizable():
                        if k in params[i]:
                            loss = loss + reg.penalty(params[i][k])
            return loss, (new_states, new_rnn)

        def step(params, state, opt_state, x, y, rng, fmask, lmask, rnn_states):
            (loss, (new_states, new_rnn)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, state, x, y, rng, fmask, lmask, rnn_states)
            grads = _zero_frozen(grads, frozen)
            grads = _clip_grads(grads, conf.gradientNormalization,
                                conf.gradientNormalizationThreshold)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            updates = _zero_frozen(updates, frozen)
            params = optax.apply_updates(params, updates)
            # state entering the next segment is a constant (ref: tBPTT detaches)
            new_rnn = jax.lax.stop_gradient(new_rnn)
            return params, new_states, opt_state, loss, new_rnn

        return jax.jit(step, donate_argnums=(0, 2))

    def _fit_tbptt(self, ds):
        """One DataSet fitted by truncated BPTT (ref: MultiLayerNetwork.
        doTruncatedBPTT): time axis sliced into fwdLength segments, recurrent
        state carried (detached) across segments within the batch."""
        if "tbptt" not in self._jit_cache:
            self._jit_cache["tbptt"] = self._build_tbptt_step()
        step = self._jit_cache["tbptt"]
        x_all = _as_jnp(ds.features)
        y_all = _as_jnp(ds.labels)
        fmask_all = _as_jnp(ds.features_mask) if ds.features_mask is not None else None
        lmask_all = _as_jnp(ds.labels_mask) if ds.labels_mask is not None else None
        taxis = 2 if self._rnn_format() == "NCW" else 1  # NCW = (B,F,T)
        T = x_all.shape[taxis]
        k = self.conf.tbpttFwdLength
        rnn_states = self._init_rnn_states(x_all.shape[0])

        def tslice(arr, sl):
            return arr[:, :, sl] if taxis == 2 else arr[:, sl]

        for t0 in range(0, T, k):
            sl = slice(t0, min(t0 + k, T))
            self._rng_key, sub = jax.random.split(self._rng_key)
            self._params, self._state, self._opt_state, loss, rnn_states = step(
                self._params, self._state, self._opt_state,
                tslice(x_all, sl), tslice(y_all, sl), sub,
                None if fmask_all is None else fmask_all[:, sl],  # masks are (B,T)
                None if lmask_all is None else lmask_all[:, sl],
                rnn_states)
            self._score = loss  # device scalar; score() syncs on demand
            self._iteration += 1
            for lst in self.listeners:
                lst.iterationDone(self, self._iteration, self._epoch)

    def rnnTimeStep(self, x) -> NDArray:
        """Streaming inference with stored state (ref: MultiLayerNetwork.
        rnnTimeStep). x: (B,F) one step, or a full sequence in the net's
        rnnDataFormat ((B,T,F) NWC / (B,F,T) NCW)."""
        xv = _as_jnp(x)
        ncw = self._rnn_format() == "NCW"
        single = xv.ndim == 2
        if single:
            xv = xv[:, :, None] if ncw else xv[:, None, :]
        if getattr(self, "_stream_rnn", None) is None or \
                jax.tree_util.tree_leaves(self._stream_rnn) and \
                jax.tree_util.tree_leaves(self._stream_rnn)[0].shape[0] != xv.shape[0]:
            self._stream_rnn = self._init_rnn_states(xv.shape[0])
        if "rnn_step" not in self._jit_cache:
            def fwd(params, state, x, rnn_states):
                out, _, new_rnn = self._forward(params, state, x, rnn_states=rnn_states,
                                                training=False, rng=None)
                return out, new_rnn
            self._jit_cache["rnn_step"] = jax.jit(fwd)
        out, self._stream_rnn = self._jit_cache["rnn_step"](
            self._params, self._state, xv, self._stream_rnn)
        if single and out.ndim == 3:
            out = out[:, :, 0] if ncw else out[:, 0]
        return NDArray(out)

    def rnnClearPreviousState(self):
        """(ref: rnnClearPreviousState)."""
        self._stream_rnn = None

    def rnnGetPreviousState(self, layer_idx: int) -> dict:
        st = getattr(self, "_stream_rnn", None)
        return {} if st is None else {k: NDArray(v) for k, v in st[layer_idx].items()}

    # ------------------------------------------------------------- pretrain
    def pretrainLayer(self, layer_idx: int, data, epochs: int = 1):
        """Layer-wise unsupervised pretraining for AutoEncoder/VAE layers
        (ref: MultiLayerNetwork.pretrainLayer): features forward through the
        preceding layers (inference), then the layer's pretrain_loss is
        minimized — feature extraction + loss + update in ONE jitted step."""
        from deeplearning4j_tpu.data.dataset import DataSet, ListDataSetIterator
        layer = self.layers[layer_idx]
        if not hasattr(layer, "pretrain_loss"):
            return self  # non-pretrainable layers are skipped (ref behavior)
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])

        key = ("pretrain", layer_idx)
        if key not in self._jit_cache:
            tx = self.conf.updater.to_optax()

            def step(lp, all_params, state, opt_state, x, rng):
                from deeplearning4j_tpu.nn.conf.layers import needs_flatten
                feats = self._adapt_input(x)
                for i in range(layer_idx):
                    if needs_flatten(self.layers[i], feats.ndim):
                        feats = feats.reshape(feats.shape[0], -1)
                    feats, _ = self.layers[i].apply(
                        all_params[i], feats, training=False,
                        state=state[i] if state[i] else None)
                loss, g = jax.value_and_grad(layer.pretrain_loss)(lp, feats, rng)
                updates, opt_state = tx.update(g, opt_state, lp)
                return optax.apply_updates(lp, updates), opt_state, loss

            # no donation: lp aliases all_params[layer_idx] in the call
            self._jit_cache[key] = (jax.jit(step), tx)
        step, tx = self._jit_cache[key]
        lp = self._params[layer_idx]
        opt_state = tx.init(lp)
        for _ in range(epochs):
            if hasattr(data, "reset"):
                data.reset()
            for ds in data:
                self._rng_key, sub = jax.random.split(self._rng_key)
                lp, opt_state, loss = step(lp, self._params, self._state,
                                           opt_state, _as_jnp(ds.features), sub)
                self._score = loss  # device scalar; score() syncs on demand
                self._iteration += 1
        self._params = list(self._params)
        self._params[layer_idx] = lp
        return self

    def pretrain(self, data, epochs: int = 1):
        """Pretrain every pretrainable layer in order (ref: MultiLayerNetwork.
        pretrain)."""
        for i in range(len(self.layers)):
            self.pretrainLayer(i, data, epochs)
        return self

    # ------------------------------------------------------------------ fit
    def fit(self, data, labels=None, epochs: int = 1):
        """fit(DataSetIterator), fit(DataSet), or fit(features, labels)
        (ref: MultiLayerNetwork.fit overloads). A crash during training
        writes a diagnostic dump (ref: CrashReportingUtil), then re-raises."""
        try:
            return self._fit_impl(data, labels, epochs)
        except Exception as e:  # dump-and-reraise; reporting never masks the error
            from deeplearning4j_tpu.util import crash_reporting
            if not getattr(e, "_control_flow", False):  # early-stop signals etc.
                crash_reporting.writeMemoryCrashDump(self, e)
            raise

    def _fit_impl(self, data, labels=None, epochs: int = 1):
        if labels is not None:
            data = ListDataSetIterator([DataSet(data, labels)])
        elif isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        tbptt = self.conf.backpropType == "TruncatedBPTT"
        stats = self._stats_requested()
        kind = "step_stats" if stats else "step"
        step = None if tbptt else self._get_jitted(kind)
        # De-dispatch path: steps buffer into fuseSteps-sized lax.scan
        # chunks (one dispatch each). Listeners no longer disable it
        # (round-3 verdict #3): chunks are cut so the scan flushes exactly
        # at iterations where a listener needs the LIVE model
        # (requiresModelAtIteration — e.g. CheckpointListener save points),
        # and the buffered per-step losses are replayed to listeners after
        # each chunk. Only stats-requesting listeners and tBPTT force the
        # true per-step path.
        fuse_k = 0 if (tbptt or stats) else self.fuseSteps
        buf: list = []  # (features, labels) pairs of identical shape
        rq = _ReplayQueue(self)

        def run_single(ds):
            nonlocal step
            rq.drain()   # callback order: buffered chunks before this step
            raw_f, raw_y = _unwrap(ds.features), _unwrap(ds.labels)
            if isinstance(raw_f, np.ndarray) and isinstance(raw_y, np.ndarray):
                x, y = self._dev_cache.get_or_put(
                    [raw_f, raw_y], lambda: (_as_jnp(raw_f), _as_jnp(raw_y)))
            else:
                x, y = _as_jnp(ds.features), _as_jnp(ds.labels)
            fmask = _as_jnp(ds.features_mask) if ds.features_mask is not None else None
            lmask = _as_jnp(ds.labels_mask) if ds.labels_mask is not None else None
            self._rng_key, sub = jax.random.split(self._rng_key)
            if step is None:
                step = self._get_jitted(kind)
            if stats:
                (self._params, self._state, self._opt_state, loss,
                 self._last_grads, self._last_updates) = step(
                    self._params, self._state, self._opt_state, x, y, sub, fmask, lmask)
            else:
                self._params, self._state, self._opt_state, loss = step(
                    self._params, self._state, self._opt_state, x, y, sub, fmask, lmask)
            self._score = loss  # device scalar; score() syncs on demand
            self._iteration += 1
            rq.dispatched += 1
            for lst in self.listeners:
                lst.iterationDone(self, self._iteration, self._epoch)

        def drain(buf):
            for f, y in buf:  # singles reuse the already-compiled step
                run_single(DataSet(f, y))
            return []

        def flush(buf):
            while buf:
                k = _chunk_limit(self.listeners, rq.dispatched, fuse_k)
                if k <= 1:
                    # a listener needs the live model at the very next
                    # iteration: run it as a single (exact semantics)
                    f, y = buf[0]
                    run_single(DataSet(f, y))
                    buf = buf[1:]
                    continue
                if len(buf) < k:
                    break
                chunk, buf = buf[:k], buf[k:]
                raws = [_unwrap(f) for f, _ in chunk] + \
                       [_unwrap(y) for _, y in chunk]
                if all(isinstance(r, np.ndarray) for r in raws):
                    xs, ys = self._dev_cache.get_or_put(
                        raws, lambda: (_stack_batches([f for f, _ in chunk]),
                                       _stack_batches([y for _, y in chunk])))
                else:
                    xs = _stack_batches([f for f, _ in chunk])
                    ys = _stack_batches([y for _, y in chunk])
                # RNG stream identical to k single steps (_chain_split)
                self._rng_key, rngs = _chain_split(self._rng_key, k)
                multi = self._get_jitted("multi")
                (self._params, self._state, self._opt_state,
                 losses) = multi(self._params, self._state,
                                 self._opt_state, xs, ys, rngs)
                rq.push(losses, k)
            return buf

        try:
            for _ in range(epochs):
                for ds in data:
                    if tbptt and np.ndim(ds.features) == 3:
                        # NB fuse_k is 0 whenever tbptt is set, so buf/rq
                        # are necessarily empty here — nothing to drain
                        self._fit_tbptt(ds)
                        continue
                    if fuse_k > 1 and ds.features_mask is None \
                            and ds.labels_mask is None:
                        if buf and (np.shape(buf[0][0]) != np.shape(ds.features)
                                    or np.shape(buf[0][1]) != np.shape(ds.labels)):
                            buf = drain(buf)  # shape change: drain as singles
                        buf.append((ds.features, ds.labels))
                        buf = flush(buf)
                    else:
                        # masked/ineligible batch: buffered earlier steps must
                        # apply FIRST (sequential SGD order, round-3 advisor)
                        buf = drain(buf)
                        run_single(ds)
                # epoch boundary: apply leftovers so epoch listeners see a
                # fully-stepped model, then fire onEpochEnd
                buf = drain(buf)
                rq.drain()
                self._epoch += 1
                for lst in self.listeners:
                    if hasattr(lst, "onEpochEnd"):
                        lst.onEpochEnd(self)
        except BaseException:
            # an exception mid-fit must not lose completed chunks'
            # callbacks; never mask the original error with a replay failure
            try:
                rq.drain()
            except Exception:
                pass
            raise
        return self

    # ------------------------------------------------------------- inference
    def output(self, x, train: bool = False, features_mask=None) -> NDArray:
        """(ref: MultiLayerNetwork.output)."""
        infer = self._get_jitted("infer")
        fmask = _as_jnp(features_mask) if features_mask is not None else None
        return NDArray(infer(self._params, self._state, _as_jnp(x), fmask))

    def warmup(self, example_row, batch_sizes=(1,)) -> "MultiLayerNetwork":
        """Pre-compile the inference executable for the given batch sizes.
        ``example_row`` is ONE row (feature shape, no batch dim); each size
        runs a throwaway forward so jit's shape-specialized cache is hot
        before real traffic — the serving registry's warmup-on-deploy hook
        (serving/registry.py) and a useful standalone latency tool."""
        ex = np.asarray(example_row)
        for b in batch_sizes:
            np.asarray(self.output(np.broadcast_to(ex, (b,) + ex.shape).copy()).jax)
        return self

    def feedForward(self, x) -> List[NDArray]:
        """Per-layer activations list, input first (ref: feedForward)."""
        from deeplearning4j_tpu.nn.conf.layers import needs_flatten
        acts = [NDArray(_as_jnp(x))]
        xv = self._adapt_input(_as_jnp(x))
        cur = xv
        for i, layer in enumerate(self.layers):
            if needs_flatten(layer, cur.ndim):
                cur = cur.reshape(cur.shape[0], -1)
            cur, _ = layer.apply(self._params[i], cur, training=False,
                                 state=self._state[i] if self._state[i] else None)
            acts.append(NDArray(cur))
        return acts

    def predict(self, x) -> np.ndarray:
        """Class indices (ref: MultiLayerNetwork.predict)."""
        return np.asarray(jnp.argmax(self.output(x).jax, axis=-1))

    # ---------------------------------------------------------------- score
    def score(self, dataset: Optional[DataSet] = None) -> float:
        """Last-minibatch loss, or loss on a provided DataSet (ref: score())."""
        if dataset is None:
            return float(self._score)
        x = _as_jnp(dataset.features)
        y = _as_jnp(dataset.labels)
        loss, _ = self._loss_for(self._params, self._state, x, y, None,
                                 _as_jnp(dataset.features_mask) if dataset.features_mask is not None else None,
                                 _as_jnp(dataset.labels_mask) if dataset.labels_mask is not None else None)
        return float(loss)

    # ----------------------------------------------------------- evaluation
    def evaluate(self, iterator: DataSetIterator, num_classes: Optional[int] = None) -> Evaluation:
        """(ref: MultiLayerNetwork.evaluate)."""
        ev = Evaluation(num_classes)
        for ds in iterator:
            out = self.output(ds.features, features_mask=ds.features_mask)
            ev.eval(ds.labels, out.toNumpy(), mask=ds.labels_mask)
        return ev

    def evaluateRegression(self, iterator: DataSetIterator) -> RegressionEvaluation:
        ev = RegressionEvaluation()
        for ds in iterator:
            out = self.output(ds.features)
            ev.eval(ds.labels, out.toNumpy())
        return ev

    # ---------------------------------------------------- flat param surface
    def params(self) -> NDArray:
        """Flat parameter vector, layer order, sorted-key tree order within a
        layer (ref: MultiLayerNetwork.params / paramsFlattened). tree_flatten
        handles nested param dicts (e.g. Bidirectional's {'fwd','bwd'})."""
        leaves = [jnp.ravel(l) for l in jax.tree_util.tree_leaves(self._params)]
        if not leaves:
            return NDArray(jnp.zeros((0,)))
        return NDArray(jnp.concatenate(leaves))

    def setParams(self, flat):
        """(ref: MultiLayerNetwork.setParams) — inverse of params()."""
        flat = _as_jnp(flat).ravel()
        leaves, treedef = jax.tree_util.tree_flatten(self._params)
        pos, new = 0, []
        for l in leaves:
            n = int(np.prod(l.shape))
            new.append(flat[pos:pos + n].reshape(l.shape).astype(l.dtype))
            pos += n
        self._params = jax.tree_util.tree_unflatten(treedef, new)

    def numParams(self) -> int:
        return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(self._params)))

    def getParam(self, layer_idx: int, key: str) -> NDArray:
        return NDArray(self._params[layer_idx][key])

    def setParam(self, layer_idx: int, key: str, value):
        self._params[layer_idx] = dict(self._params[layer_idx])
        self._params[layer_idx][key] = _as_jnp(value)

    # ------------------------------------------------------------- listeners
    def setListeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def addListeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def setHostTransferCache(self, enabled: bool):
        """Toggle the host->device minibatch transfer cache (on by default;
        mutation-safe — see _DeviceCache). Off = every fit() batch is
        re-transferred."""
        self._dev_cache.enabled = enabled
        return self

    def getIterationCount(self) -> int:
        return self._iteration

    def getEpochCount(self) -> int:
        return self._epoch

    # ----------------------------------------------------------------- misc
    def clone(self) -> "MultiLayerNetwork":
        other = MultiLayerNetwork(self.conf)
        if self._params is not None:
            other._params = jax.tree_util.tree_map(lambda a: a, self._params)
            other._state = jax.tree_util.tree_map(lambda a: a, self._state)
            other._tx = self.conf.updater.to_optax()
            other._opt_state = other._tx.init(other._params)
        return other

    def summary(self) -> str:
        """(ref: MultiLayerNetwork.summary)."""
        rows = [("idx", "type", "nParams", "shape")]
        total = 0
        for i, layer in enumerate(self.layers):
            p = self._params[i] if self._params else {}
            n = int(sum(np.prod(v.shape) for v in p.values()))
            total += n
            shapes = ", ".join(f"{k}:{list(v.shape)}" for k, v in sorted(p.items()))
            rows.append((str(i), type(layer).__name__, str(n), shapes))
        widths = [max(len(r[c]) for r in rows) for c in range(4)]
        lines = ["  ".join(r[c].ljust(widths[c]) for c in range(4)) for r in rows]
        lines.append(f"Total params: {total}")
        return "\n".join(lines)


class _DropoutLike:
    pass


from deeplearning4j_tpu.nn.conf.layers import DropoutLayer as _DL  # noqa: E402

_DropoutLike = _DL
