"""Transfer learning (ref: org.deeplearning4j.nn.transferlearning —
TransferLearning.Builder (graph surgery on trained nets), FineTuneConfiguration,
TransferLearningHelper (frozen featurization); FrozenLayer semantics are
implemented as zeroed gradients inside the fused train step)."""
from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import jax
import numpy as np

from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.train import updaters as _upd


@dataclass
class FineTuneConfiguration:
    """Overrides applied to the copied net (ref: FineTuneConfiguration.Builder)."""
    updater: Optional[_upd.Updater] = None
    seed: Optional[int] = None

    class Builder:
        def __init__(self):
            self._updater = None
            self._seed = None

        def updater(self, u):
            self._updater = u
            return self

        def seed(self, s):
            self._seed = s
            return self

        def build(self):
            return FineTuneConfiguration(updater=self._updater, seed=self._seed)


class TransferLearning:
    """(ref: TransferLearning.Builder for MultiLayerNetwork)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            self._net = net
            self._layers: List[Layer] = copy.deepcopy(net.conf.layers)
            # map new-layer-index -> source index for param transfer
            self._src_idx: List[Optional[int]] = list(range(len(self._layers)))
            self._reinit: set = set()
            self._ftc: Optional[FineTuneConfiguration] = None
            self._freeze_upto = -1

        def fineTuneConfiguration(self, ftc: FineTuneConfiguration):
            self._ftc = ftc
            return self

        def setFeatureExtractor(self, layer_idx: int):
            """Freeze layers [0, layer_idx] (ref: setFeatureExtractor)."""
            self._freeze_upto = layer_idx
            return self

        def removeOutputLayer(self):
            return self.removeLayersFromOutput(1)

        def removeLayersFromOutput(self, n: int):
            self._layers = self._layers[:-n]
            self._src_idx = self._src_idx[:-n]
            return self

        def addLayer(self, layer: Layer):
            # auto-fill nIn from the preceding layer's nOut when available
            if getattr(layer, "nIn", 0) in (0, None) and self._layers:
                prev_out = getattr(self._layers[-1], "nOut", 0)
                if prev_out and hasattr(layer, "nIn"):
                    layer.nIn = prev_out
            self._layers.append(layer)
            self._src_idx.append(None)
            return self

        def nOutReplace(self, layer_idx: int, n_out: int,
                        weight_init: Optional[str] = None):
            """Change a layer's nOut and re-init it (+ the next layer's nIn)
            (ref: nOutReplace)."""
            l = self._layers[layer_idx]
            l.nOut = n_out
            if weight_init is not None:
                l.weightInit = weight_init
            self._reinit.add(layer_idx)
            if layer_idx + 1 < len(self._layers):
                nxt = self._layers[layer_idx + 1]
                if hasattr(nxt, "nIn"):
                    nxt.nIn = n_out
                self._reinit.add(layer_idx + 1)
            return self

        def build(self) -> MultiLayerNetwork:
            old = self._net
            conf = MultiLayerConfiguration(
                layers=self._layers,
                seed=(self._ftc.seed if self._ftc and self._ftc.seed is not None
                      else old.conf.seed),
                updater=(self._ftc.updater if self._ftc and self._ftc.updater is not None
                         else old.conf.updater),
                inputType=old.conf.inputType,
                regularization=list(old.conf.regularization),
                gradientNormalization=old.conf.gradientNormalization,
                gradientNormalizationThreshold=old.conf.gradientNormalizationThreshold,
                backpropType=old.conf.backpropType,
                tbpttFwdLength=old.conf.tbpttFwdLength,
                tbpttBackLength=old.conf.tbpttBackLength,
                dataType=old.conf.dataType,
            )
            for i in range(min(self._freeze_upto + 1, len(self._layers))):
                self._layers[i].frozen = True
            net = MultiLayerNetwork(conf).init()
            # transfer trained params for retained, un-reinitialized layers
            for new_i, src_i in enumerate(self._src_idx):
                if src_i is not None and new_i not in self._reinit:
                    net._params[new_i] = jax.tree_util.tree_map(
                        lambda a: a, old._params[src_i])
            net._opt_state = net._tx.init(net._params)
            return net


class TransferLearningHelper:
    """Featurization through the frozen body (ref: TransferLearningHelper)."""

    def __init__(self, net: MultiLayerNetwork, frozen_till: int):
        self.net = net
        self.frozen_till = frozen_till

    def featurize(self, x) -> np.ndarray:
        acts = self.net.feedForward(x)
        return acts[self.frozen_till + 1].toNumpy()
