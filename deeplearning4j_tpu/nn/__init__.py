"""NN framework (ref: deeplearning4j/deeplearning4j-nn)."""
from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration, NeuralNetConfiguration  # noqa: F401
from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf import layers  # noqa: F401
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: F401
from deeplearning4j_tpu.nn.conf.graph import (  # noqa: F401
    ComputationGraphConfiguration, GraphBuilder, GraphVertex, MergeVertex,
    ElementWiseVertex, SubsetVertex, StackVertex, UnstackVertex, ScaleVertex,
    ShiftVertex, L2NormalizeVertex, ReshapeVertex)
from deeplearning4j_tpu.nn.computation_graph import ComputationGraph  # noqa: F401
