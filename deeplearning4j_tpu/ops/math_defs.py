"""Math / reduce / shape / bitwise / linalg / random op definitions.

Covers the reference's legacy transform/pairwise/reduce/broadcast op families
(libnd4j include/loops + org.nd4j.linalg.api.ops.impl.{transforms,reduce,shape,
broadcast,random}) as registry entries over jnp — XLA emits the kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

# ----------------------------------------------------------------- transforms


def _simple(name, fn, ns="math"):
    op(name, ns)(fn)


for _name, _fn in {
    "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor, "round": jnp.round,
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log1p": jnp.log1p,
    "log2": jnp.log2, "log10": jnp.log10, "sqrt": jnp.sqrt, "square": jnp.square,
    "cube": lambda x: x * x * x, "reciprocal": jnp.reciprocal, "neg": jnp.negative,
    "sign": jnp.sign, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    "erf": jax.scipy.special.erf, "erfc": jax.scipy.special.erfc,
    "rsqrt": lax.rsqrt, "isnan": jnp.isnan, "isinf": jnp.isinf,
    "isfinite": jnp.isfinite,
}.items():
    _simple(_name, _fn)

op("identity", "math")(lambda x: x)
op("pow", "math")(jnp.power)
op("atan2", "math")(jnp.arctan2)
op("add", "math")(jnp.add)
op("sub", "math")(jnp.subtract)
op("mul", "math")(jnp.multiply)
op("div", "math")(jnp.divide)
op("floorDiv", "math")(jnp.floor_divide)
op("floorMod", "math")(jnp.mod)
op("fmod", "math")(jnp.fmod)
op("max", "math")(jnp.maximum)
op("min", "math")(jnp.minimum)
op("clipByValue", "math")(lambda x, lo, hi: jnp.clip(x, lo, hi))

op("squaredDifference", "math")(lambda a, b: jnp.square(a - b))
op("zerosLike", "math")(jnp.zeros_like)
op("onesLike", "math")(jnp.ones_like)

# comparisons (ref: SDMath eq/neq/lt/lte/gt/gte + impl.transforms.comparison)
op("eq", "math")(jnp.equal)
op("neq", "math")(jnp.not_equal)
op("lt", "math")(jnp.less)
op("lte", "math")(jnp.less_equal)
op("gt", "math")(jnp.greater)
op("gte", "math")(jnp.greater_equal)


@op("clipByNorm", "math")
def clip_by_norm(x, clip_norm, axis=None):
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=axis is not None))
    return jnp.where(n > clip_norm, x * (clip_norm / jnp.maximum(n, 1e-12)), x)


@op("step", "math")
def step(x, cutoff=0.0):
    return (x > cutoff).astype(x.dtype)


op("logicalAnd", "math")(jnp.logical_and)
op("logicalOr", "math")(jnp.logical_or)
op("logicalNot", "math")(jnp.logical_not)
op("logicalXor", "math")(jnp.logical_xor)

# bitwise namespace (ref: SDBitwise)
op("and_", "bitwise")(jnp.bitwise_and)
op("or_", "bitwise")(jnp.bitwise_or)
op("xor", "bitwise")(jnp.bitwise_xor)
op("leftShift", "bitwise")(jnp.left_shift)
op("rightShift", "bitwise")(jnp.right_shift)
op("bitsHammingDistance", "bitwise")(
    lambda a, b: jnp.sum(jax.lax.population_count(jnp.bitwise_xor(a, b)))
)

# ------------------------------------------------------------------- reduce


def _axis(dims):
    if dims is None or dims == () or dims == []:
        return None
    if isinstance(dims, (tuple, list)):
        return tuple(dims)
    return dims


def _reduce_ns(name, fn):
    @op(name, "reduce")
    def _r(x, dims=None, keepdims=False, _fn=fn):
        return _fn(x, axis=_axis(dims), keepdims=keepdims)


for _name, _fn in {
    "sum": jnp.sum, "mean": jnp.mean, "max": jnp.max, "min": jnp.min,
    "prod": jnp.prod, "any": jnp.any, "all": jnp.all,
    "countNonZero": lambda x, axis=None, keepdims=False: jnp.sum(
        (x != 0).astype(jnp.int32), axis=axis, keepdims=keepdims),
    "countZero": lambda x, axis=None, keepdims=False: jnp.sum(
        (x == 0).astype(jnp.int32), axis=axis, keepdims=keepdims),
    "norm1": lambda x, axis=None, keepdims=False: jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims),
    "norm2": lambda x, axis=None, keepdims=False: jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims)),
    "normMax": lambda x, axis=None, keepdims=False: jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims),
    "squaredNorm": lambda x, axis=None, keepdims=False: jnp.sum(x * x, axis=axis, keepdims=keepdims),
    "logSumExp": lambda x, axis=None, keepdims=False: jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims),
}.items():
    _reduce_ns(_name, _fn)


@op("std", "reduce")
def std(x, dims=None, keepdims=False, biasCorrected=True):
    return jnp.std(x, axis=_axis(dims), keepdims=keepdims, ddof=1 if biasCorrected else 0)


@op("variance", "reduce")
def variance(x, dims=None, keepdims=False, biasCorrected=True):
    return jnp.var(x, axis=_axis(dims), keepdims=keepdims, ddof=1 if biasCorrected else 0)


@op("argmax", "reduce")
def argmax(x, dims=None, keepdims=False):
    if isinstance(dims, (tuple, list)):
        dims = dims[0] if dims else None
    return jnp.argmax(x, axis=dims if dims is not None else None, keepdims=keepdims)


@op("argmin", "reduce")
def argmin(x, dims=None, keepdims=False):
    return jnp.argmin(x, axis=dims if dims is not None else None, keepdims=keepdims)


@op("iamax", "reduce")
def iamax(x, dims=None):
    return jnp.argmax(jnp.abs(x), axis=dims)


@op("cosineSimilarity", "reduce")
def cosine_similarity(a, b, dims=None):
    num = jnp.sum(a * b, axis=_axis(dims))
    den = jnp.sqrt(jnp.sum(a * a, axis=_axis(dims))) * jnp.sqrt(jnp.sum(b * b, axis=_axis(dims)))
    return num / jnp.maximum(den, 1e-12)


@op("euclideanDistance", "reduce")
def euclidean_distance(a, b, dims=None):
    d = a - b
    return jnp.sqrt(jnp.sum(d * d, axis=_axis(dims)))


@op("manhattanDistance", "reduce")
def manhattan_distance(a, b, dims=None):
    return jnp.sum(jnp.abs(a - b), axis=_axis(dims))


@op("hammingDistance", "reduce")
def hamming_distance(a, b, dims=None):
    return jnp.sum((a != b).astype(jnp.float32), axis=_axis(dims))


@op("shannonEntropy", "reduce")
def shannon_entropy(x, dims=None):
    return -jnp.sum(x * jnp.log2(jnp.maximum(x, 1e-30)), axis=_axis(dims))


@op("matchCondition", "reduce")
def match_condition(x, predicate, dims=None):
    """Count of elements matching a python predicate built from jnp comparisons."""
    return jnp.sum(predicate(x).astype(jnp.int64), axis=_axis(dims))


# -------------------------------------------------------------------- shape

op("reshape", "shape")(lambda x, shape: jnp.reshape(x, tuple(shape)))
op("transpose", "shape")(lambda x, axes=None: jnp.transpose(x, axes))
op("permute", "shape")(lambda x, axes: jnp.transpose(x, axes))
op("expandDims", "shape")(jnp.expand_dims)
op("squeeze", "shape")(lambda x, axis=None: jnp.squeeze(x, axis=axis))
op("flatten", "shape")(jnp.ravel)
op("concat", "shape")(lambda arrays, axis=0: jnp.concatenate(arrays, axis=axis))
op("stack", "shape")(lambda arrays, axis=0: jnp.stack(arrays, axis=axis))
# variadic forms for graph-mode construction (one SDVariable per input)
op("concatN", "shape")(lambda *arrays, axis=0: jnp.concatenate(arrays, axis=axis))
op("stackN", "shape")(lambda *arrays, axis=0: jnp.stack(arrays, axis=axis))
op("unstack", "shape")(lambda x, axis=0: [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)])
op("tile", "shape")(lambda x, reps: jnp.tile(x, tuple(reps)))
op("repeat", "shape")(lambda x, repeats, axis=None: jnp.repeat(x, repeats, axis=axis))
op("reverse", "shape")(lambda x, dims: jnp.flip(x, axis=tuple(dims) if isinstance(dims, (list, tuple)) else dims))
op("shapeOf", "shape")(lambda x: jnp.asarray(x.shape, dtype=jnp.int64))
op("sizeAt", "shape")(lambda x, dim: x.shape[dim])
op("rank", "shape")(lambda x: x.ndim)
op("broadcastTo", "shape")(lambda x, shape: jnp.broadcast_to(x, tuple(shape)))
op("gather", "shape")(lambda x, indices, axis=0: jnp.take(x, indices, axis=axis))
op("gatherNd", "shape")(lambda x, indices: x[tuple(jnp.moveaxis(indices, -1, 0))])
op("scatterUpdate", "shape")(lambda x, indices, updates: x.at[indices].set(updates))
op("scatterAdd", "shape")(lambda x, indices, updates: x.at[indices].add(updates))
op("scatterSub", "shape")(lambda x, indices, updates: x.at[indices].add(-updates))
op("scatterMax", "shape")(lambda x, indices, updates: x.at[indices].max(updates))
op("scatterMin", "shape")(lambda x, indices, updates: x.at[indices].min(updates))
op("slice", "shape")(lambda x, begin, size: lax.dynamic_slice(x, tuple(begin), tuple(size)))
op("stridedSlice", "shape")(lambda x, slices: x[tuple(slices)])
op("splitN", "shape")(lambda x, num, axis=0: tuple(jnp.split(x, num, axis=axis)))


@op("reshapeRef", "shape")
def reshape_ref(x, ref, dims):
    """Reshape where some target dims come from ``ref``'s (trace-time static)
    shape: entries are ints, or "dim:i" meaning ref.shape[i]. Lets TF-imported
    graphs whose Reshape shapes are computed from tf.shape() stay static under
    jit (XLA requires static shapes)."""
    shape = tuple(
        ref.shape[int(d[4:])] if isinstance(d, str) and d.startswith("dim:")
        else int(d) for d in dims)
    return jnp.reshape(x, shape)
op("where", "shape")(lambda cond, x, y: jnp.where(cond, x, y))
op("cumsum", "shape")(lambda x, axis=None: jnp.cumsum(x, axis=axis))
op("cumprod", "shape")(lambda x, axis=None: jnp.cumprod(x, axis=axis))
op("pad", "shape")(lambda x, paddings, mode="constant", value=0.0: jnp.pad(
    x, paddings, mode=mode, constant_values=value) if mode == "constant" else jnp.pad(x, paddings, mode=mode))
op("diag", "shape")(jnp.diag)
op("diagPart", "shape")(jnp.diagonal)
op("oneHot", "shape")(lambda indices, depth, axis=-1, on=1.0, off=0.0: jax.nn.one_hot(
    indices, depth, axis=axis) * (on - off) + off)
op("castTo", "shape")(lambda x, dtype: x.astype(dtype))


@op("dynamicPartition", "shape")
def dynamic_partition(x, partitions, num_partitions):
    """Static-shape-friendly variant: returns masked copies (XLA needs static
    shapes; the reference returns ragged lists — callers use segment ops here)."""
    return [jnp.where((partitions == i)[(...,) + (None,) * (x.ndim - partitions.ndim)], x, 0)
            for i in range(num_partitions)]


@op("segmentSum", "shape")
def segment_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids, num_segments)


@op("segmentMean", "shape")
def segment_mean(data, segment_ids, num_segments):
    s = jax.ops.segment_sum(data, segment_ids, num_segments)
    c = jax.ops.segment_sum(jnp.ones_like(data), segment_ids, num_segments)
    return s / jnp.maximum(c, 1)


@op("sequenceMask", "shape")
def sequence_mask(lengths, maxlen, dtype=jnp.float32):
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


# ------------------------------------------------------------------- linalg

op("matmul", "linalg")(jnp.matmul)
op("mmul", "linalg")(jnp.matmul)


@op("gemm", "linalg")
def gemm(a, b, alpha=1.0, beta=0.0, transposeA=False, transposeB=False, c=None):
    A = a.T if transposeA else a
    B = b.T if transposeB else b
    out = alpha * jnp.matmul(A, B)
    if c is not None:
        out = out + beta * c
    return out


op("tensorMmul", "linalg")(lambda a, b, axes: jnp.tensordot(a, b, axes=axes))
op("cholesky", "linalg")(jnp.linalg.cholesky)
op("svd", "linalg")(jnp.linalg.svd)
op("qr", "linalg")(jnp.linalg.qr)
op("inverse", "linalg")(jnp.linalg.inv)
op("det", "linalg")(jnp.linalg.det)
op("solve", "linalg")(jnp.linalg.solve)
op("lstsq", "linalg")(lambda a, b: jnp.linalg.lstsq(a, b)[0])
op("eig", "linalg")(jnp.linalg.eigh)
op("trace", "linalg")(jnp.trace)
op("matrixDiag", "linalg")(jnp.diag)
op("matrixBandPart", "linalg")(
    lambda x, lower, upper: jnp.where(
        (jnp.arange(x.shape[-2])[:, None] - jnp.arange(x.shape[-1])[None, :] <= (lower if lower >= 0 else x.shape[-2]))
        & (jnp.arange(x.shape[-1])[None, :] - jnp.arange(x.shape[-2])[:, None] <= (upper if upper >= 0 else x.shape[-1])),
        x, 0))

# ------------------------------------------------------------------- random
# Key-explicit (functional) random ops; the eager surface threads the global
# Random's key automatically via ops/__init__ wrappers where key=None.

op("uniform", "random")(
    lambda key, shape, minval=0.0, maxval=1.0, dtype=jnp.float32: jax.random.uniform(
        key, tuple(shape), dtype=dtype, minval=minval, maxval=maxval))
op("normal", "random")(
    lambda key, shape, mean=0.0, std=1.0, dtype=jnp.float32: jax.random.normal(
        key, tuple(shape), dtype=dtype) * std + mean)
op("bernoulli", "random")(
    lambda key, shape, p=0.5, dtype=jnp.float32: jax.random.bernoulli(key, p, tuple(shape)).astype(dtype))
op("exponential", "random")(
    lambda key, shape, lam=1.0, dtype=jnp.float32: jax.random.exponential(key, tuple(shape), dtype=dtype) / lam)
op("gamma", "random")(
    lambda key, shape, alpha, dtype=jnp.float32: jax.random.gamma(key, alpha, tuple(shape), dtype=dtype))
op("shuffle", "random")(lambda key, x, axis=0: jax.random.permutation(key, x, axis=axis))
op("dropout", "random")(
    lambda key, x, rate: jnp.where(jax.random.bernoulli(key, 1.0 - rate, x.shape), x / (1.0 - rate), 0.0))
op("truncatedNormal", "random")(
    lambda key, shape, mean=0.0, std=1.0, dtype=jnp.float32: jax.random.truncated_normal(
        key, -2.0, 2.0, tuple(shape), dtype=dtype) * std + mean)
