"""Final op-surface widening toward the reference's full declarable-op
inventory (SURVEY.md §2.1 — libnd4j include/ops/declarable/generic/**).

Families added here and the reference source areas they realize:

- ``updaters`` namespace — libnd4j generic/updaters/*.cpp (sgdUpdater,
  adamUpdater, …): the reference exposes each optimizer update rule as a
  standalone fused op so updaters can run without a training session. Here
  each op is a pure function ``(grad, *state, hyperparams) -> (update,
  *new_state)`` — jit-fusable, donation-friendly, and exactly what
  train/updaters.py applies inside the fused step.
- boolean checks — generic/boolean (is_non_decreasing,
  is_strictly_increasing, is_numeric_tensor).
- parity-op stragglers — generic/parity_ops (stop_gradient, mirror_pad,
  matrix_set_diag, space_to_batch_nd/batch_to_space_nd, bias_add,
  nth_element, check_numerics, broadcast_dynamic_shape, select,
  sparse_to_dense, sufficient_statistics, assign, histogram, split_v,
  weighted_cross_entropy_with_logits, axpy).
- t-SNE helper ops — generic/tsne (gains, symmetrized, edge_force,
  cell_contains); consumed by the UI's embedding page.
- bitmap compression — generic/compression/bitmap.cpp (encode_bitmap /
  decode_bitmap), the fixed-threshold sibling of threshold_encode.
- recurrent variants — generic/recurrent (lstmBlock, lstmBlockCell,
  dynamic_rnn, dynamic_bidirectional_rnn, static_rnn).
- image stragglers — generic/images (non_max_suppression_overlaps,
  draw_bounding_boxes, adjust_gamma).
- cnn stragglers — deconv3d, pnormpool2d.
- loss stragglers — ctc_loss, mean_pairwise_squared_error.
- math/random extras — divide_no_nan, truncatediv, cummax/cummin,
  trigamma, nextafter, lognormal, multinomial alias, intersection
  (generic/transforms + generic/random).

Everything is a jnp/lax composition: XLA fuses these into the surrounding
computation, so no Pallas is needed for any of them (no data reuse XLA
can't already see). Backprop ("*_bp") ops in the reference inventory are
deliberately not mirrored: jax.grad derives them, which is the whole point
of the rebuild (SURVEY §7.0).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

# ------------------------------------------------------------- updaters
# State layout mirrors train/updaters.py; hyperparameter names follow the
# reference's config classes (org.nd4j.linalg.learning.config.*).


@op("sgdUpdater", "updaters")
def sgd_updater(grad, lr=0.1):
    return grad * lr


@op("nesterovsUpdater", "updaters")
def nesterovs_updater(grad, v, lr=0.1, momentum=0.9):
    v_new = momentum * v - lr * grad
    update = -(momentum * v_new - lr * grad)
    return update, v_new


@op("adaGradUpdater", "updaters")
def adagrad_updater(grad, h, lr=0.1, eps=1e-6):
    h_new = h + grad * grad
    return lr * grad / (jnp.sqrt(h_new) + eps), h_new


@op("rmsPropUpdater", "updaters")
def rmsprop_updater(grad, g2, lr=0.1, decay=0.95, eps=1e-8):
    g2_new = decay * g2 + (1.0 - decay) * grad * grad
    return lr * grad / (jnp.sqrt(g2_new) + eps), g2_new


@op("adaDeltaUpdater", "updaters")
def adadelta_updater(grad, msg, msdx, rho=0.95, eps=1e-6):
    msg_new = rho * msg + (1.0 - rho) * grad * grad
    dx = jnp.sqrt(msdx + eps) / jnp.sqrt(msg_new + eps) * grad
    msdx_new = rho * msdx + (1.0 - rho) * dx * dx
    return dx, msg_new, msdx_new


@op("adamUpdater", "updaters")
def adam_updater(grad, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    t = t + 1
    m_new = beta1 * m + (1.0 - beta1) * grad
    v_new = beta2 * v + (1.0 - beta2) * grad * grad
    mhat = m_new / (1.0 - beta1 ** t)
    vhat = v_new / (1.0 - beta2 ** t)
    return lr * mhat / (jnp.sqrt(vhat) + eps), m_new, v_new, t


@op("adaMaxUpdater", "updaters")
def adamax_updater(grad, m, u, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    t = t + 1
    m_new = beta1 * m + (1.0 - beta1) * grad
    u_new = jnp.maximum(beta2 * u, jnp.abs(grad))
    return lr / (1.0 - beta1 ** t) * m_new / (u_new + eps), m_new, u_new, t


@op("nadamUpdater", "updaters")
def nadam_updater(grad, m, v, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    t = t + 1
    m_new = beta1 * m + (1.0 - beta1) * grad
    v_new = beta2 * v + (1.0 - beta2) * grad * grad
    mhat = m_new / (1.0 - beta1 ** t)
    vhat = v_new / (1.0 - beta2 ** t)
    m_bar = beta1 * mhat + (1.0 - beta1) / (1.0 - beta1 ** t) * grad
    return lr * m_bar / (jnp.sqrt(vhat) + eps), m_new, v_new, t


@op("amsGradUpdater", "updaters")
def amsgrad_updater(grad, m, v, vhat_max, t, lr=1e-3, beta1=0.9, beta2=0.999,
                    eps=1e-8):
    t = t + 1
    m_new = beta1 * m + (1.0 - beta1) * grad
    v_new = beta2 * v + (1.0 - beta2) * grad * grad
    vhat_new = jnp.maximum(vhat_max, v_new)
    # Reddi et al. / DL4J form: alpha_t = lr*sqrt(1-b2^t)/(1-b1^t) folds the
    # bias corrections of BOTH moments into the step size
    alpha_t = lr * jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)
    return alpha_t * m_new / (jnp.sqrt(vhat_new) + eps), m_new, v_new, vhat_new, t


@op("adaBeliefUpdater", "updaters")
def adabelief_updater(grad, m, s, t, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8):
    t = t + 1
    m_new = beta1 * m + (1.0 - beta1) * grad
    diff = grad - m_new
    s_new = beta2 * s + (1.0 - beta2) * diff * diff + eps
    mhat = m_new / (1.0 - beta1 ** t)
    shat = s_new / (1.0 - beta2 ** t)
    return lr * mhat / (jnp.sqrt(shat) + eps), m_new, s_new, t


# -------------------------------------------------------- boolean checks

op("isNonDecreasing", "math")(
    lambda x: jnp.all(jnp.ravel(x)[1:] >= jnp.ravel(x)[:-1]))
op("isStrictlyIncreasing", "math")(
    lambda x: jnp.all(jnp.ravel(x)[1:] > jnp.ravel(x)[:-1]))
op("isNumericTensor", "math")(
    lambda x: jnp.issubdtype(jnp.asarray(x).dtype, jnp.number))


# -------------------------------------------------- parity-op stragglers

op("stopGradient", "math")(lax.stop_gradient)
op("assign", "math")(lambda x, y: jnp.broadcast_to(jnp.asarray(y, dtype=jnp.asarray(x).dtype), jnp.shape(x)))
op("axpy", "math")(lambda x, y, alpha=1.0: alpha * x + y)
op("divideNoNan", "math")(
    lambda x, y: jnp.where(y == 0, jnp.zeros_like(jnp.asarray(x) * jnp.asarray(y)), x / jnp.where(y == 0, 1, y)))
op("realDiv", "math")(lambda x, y: jnp.true_divide(x, y))
op("truncateDiv", "math")(
    lambda x, y: jnp.trunc(jnp.true_divide(x, y)).astype(jnp.result_type(x, y)))
op("cummax", "math")(
    lambda x, axis=-1: lax.cummax(jnp.asarray(x), axis=axis % jnp.asarray(x).ndim))
op("cummin", "math")(
    lambda x, axis=-1: lax.cummin(jnp.asarray(x), axis=axis % jnp.asarray(x).ndim))
op("trigamma", "math")(lambda x: jax.scipy.special.polygamma(1, x))
op("nextafter", "math")(jnp.nextafter)


@op("checkNumerics", "math")
def check_numerics(x, message="checkNumerics"):
    """Eager-only guard (the reference's op aborts on NaN/Inf; under jit use
    profiler.nan_panic / jax_debug_nans instead)."""
    import numpy as np
    arr = np.asarray(x)
    if not np.all(np.isfinite(arr)):
        raise FloatingPointError(f"{message}: tensor contains NaN or Inf")
    return x


@op("biasAdd", "nn")
def bias_add(x, bias, data_format="NWC"):
    x = jnp.asarray(x)
    if data_format in ("NWC", "NHWC", "channels_last"):
        return x + bias
    shape = [1] * x.ndim
    shape[1] = -1
    return x + jnp.reshape(bias, shape)


@op("mirrorPad", "shape")
def mirror_pad(x, paddings, mode="REFLECT"):
    mode = {"REFLECT": "reflect", "SYMMETRIC": "symmetric"}.get(str(mode).upper(), mode)
    return jnp.pad(jnp.asarray(x), [tuple(p) for p in paddings], mode=mode)


@op("matrixSetDiag", "linalg")
def matrix_set_diag(x, diagonal):
    x = jnp.asarray(x)
    n = min(x.shape[-2], x.shape[-1])
    eye = jnp.eye(x.shape[-2], x.shape[-1], dtype=bool)
    diag_full = jnp.zeros_like(x).at[..., jnp.arange(n), jnp.arange(n)].set(diagonal)
    return jnp.where(eye, diag_full, x)


@op("spaceToBatchNd", "cnn")
def space_to_batch_nd(x, block_shape, paddings):
    x = jnp.asarray(x)
    pads = [(0, 0)] + [tuple(p) for p in paddings]
    pads += [(0, 0)] * (x.ndim - len(pads))
    x = jnp.pad(x, pads)
    n = x.shape[0]
    spatial = x.shape[1:1 + len(block_shape)]
    rest = x.shape[1 + len(block_shape):]
    new_shape = [n]
    for dim, blk in zip(spatial, block_shape):
        new_shape += [dim // blk, blk]
    x = jnp.reshape(x, new_shape + list(rest))
    # (n, s1/b1, b1, s2/b2, b2, ..., rest) -> (b1, b2, ..., n, s1/b1, ..., rest)
    nb = len(block_shape)
    perm = [2 * i + 2 for i in range(nb)] + [0] + [2 * i + 1 for i in range(nb)]
    perm += list(range(1 + 2 * nb, x.ndim))
    x = jnp.transpose(x, perm)
    blk_prod = 1
    for b in block_shape:
        blk_prod *= int(b)
    out_shape = [n * blk_prod] + \
        [dim // blk for dim, blk in zip(spatial, block_shape)] + list(rest)
    return jnp.reshape(x, out_shape)


@op("batchToSpaceNd", "cnn")
def batch_to_space_nd(x, block_shape, crops):
    x = jnp.asarray(x)
    nb = len(block_shape)
    blk_prod = 1
    for b in block_shape:
        blk_prod *= int(b)
    n = x.shape[0] // blk_prod
    spatial = x.shape[1:1 + nb]
    rest = x.shape[1 + nb:]
    x = jnp.reshape(x, list(block_shape) + [n] + list(spatial) + list(rest))
    perm = [nb]
    for i in range(nb):
        perm += [nb + 1 + i, i]
    perm += list(range(2 * nb + 1, x.ndim))
    x = jnp.transpose(x, perm)
    x = jnp.reshape(x, [n] + [s * b for s, b in zip(spatial, block_shape)] + list(rest))
    slices = [slice(None)]
    for (lo, hi), dim in zip([tuple(c) for c in crops], x.shape[1:1 + nb]):
        slices.append(slice(lo, dim - hi))
    return x[tuple(slices)]


@op("nthElement", "math")
def nth_element(x, n, reverse=False):
    x = jnp.asarray(x)
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]


op("broadcastShape", "shape")(
    lambda a, b: jnp.broadcast_shapes(tuple(a), tuple(b)))
op("select", "shape")(lambda cond, x, y: jnp.where(cond, x, y))


@op("sparseToDense", "shape")
def sparse_to_dense(indices, output_shape, values, default_value=0):
    indices = jnp.asarray(indices)
    if indices.ndim == 1:
        indices = indices[:, None]
    out = jnp.full(tuple(int(s) for s in output_shape), default_value,
                   dtype=jnp.asarray(values).dtype)
    return out.at[tuple(indices[:, i] for i in range(indices.shape[1]))].set(values)


@op("sufficientStatistics", "math")
def sufficient_statistics(x, axes, shift=None):
    x = jnp.asarray(x)
    axes = tuple(axes)
    count = 1.0
    for a in axes:
        count *= x.shape[a]
    if shift is not None:
        x = x - shift
    return (jnp.asarray(count, x.dtype), jnp.sum(x, axis=axes),
            jnp.sum(x * x, axis=axes))


@op("histogram", "math")
def histogram(x, bins=10):
    x = jnp.ravel(jnp.asarray(x))
    lo, hi = jnp.min(x), jnp.max(x)
    width = jnp.where(hi > lo, hi - lo, 1.0)
    idx = jnp.clip(((x - lo) / width * bins).astype(jnp.int32), 0, bins - 1)
    return jnp.zeros((bins,), jnp.int32).at[idx].add(1)


@op("splitV", "shape")
def split_v(x, size_splits, axis=0):
    sizes = [int(s) for s in size_splits]
    offsets, acc = [], 0
    for s in sizes[:-1]:
        acc += s
        offsets.append(acc)
    return jnp.split(jnp.asarray(x), offsets, axis=axis)


op("intersection", "shape")(
    lambda a, b: jnp.intersect1d(jnp.asarray(a), jnp.asarray(b)))


# ----------------------------------------------------------------- t-SNE
# libnd4j generic/tsne: gradient-adaptation gains, symmetrized affinities,
# and per-edge forces for Barnes-Hut t-SNE (the UI embedding page computes
# embeddings with the dense equivalents of these).


@op("tsneGains", "math")
def tsne_gains(gains, gradient, step, min_gain=0.01):
    same_sign = jnp.sign(gradient) == jnp.sign(step)
    new = jnp.where(same_sign, gains * 0.8, gains + 0.2)
    return jnp.maximum(new, min_gain)


@op("tsneSymmetrized", "math")
def tsne_symmetrized(p):
    p = jnp.asarray(p)
    s = p + p.T
    return s / jnp.maximum(jnp.sum(s), 1e-12)


@op("tsneEdgeForces", "math")
def tsne_edge_forces(y, p):
    """Dense attractive-force field: sum_j p_ij q'_ij (y_i - y_j)."""
    y = jnp.asarray(y)
    d2 = jnp.sum((y[:, None, :] - y[None, :, :]) ** 2, axis=-1)
    qn = 1.0 / (1.0 + d2)
    w = p * qn
    return jnp.sum(w[..., None] * (y[:, None, :] - y[None, :, :]), axis=1)


@op("tsneCellContains", "math")
def tsne_cell_contains(corner, width, point):
    corner, width, point = map(jnp.asarray, (corner, width, point))
    return jnp.all((point >= corner) & (point <= corner + width), axis=-1)


# --------------------------------------------------- bitmap compression
# libnd4j generic/compression/bitmap.cpp: fixed-threshold 2-bit encoding —
# each element becomes {0, +threshold, -threshold}. Dense tensors in/out
# (the wire format's int packing is the transport's concern; on TPU the
# collective rides ICI so the codec is semantic, not bandwidth-critical).


@op("encodeBitmap", "math")
def encode_bitmap(x, threshold):
    x = jnp.asarray(x)
    code = jnp.where(x >= threshold, 1, jnp.where(x <= -threshold, -1, 0)).astype(jnp.int8)
    residual = x - code.astype(x.dtype) * threshold
    return code, residual


@op("decodeBitmap", "math")
def decode_bitmap(code, threshold, dtype=jnp.float32):
    return jnp.asarray(code, dtype) * threshold


# ---------------------------------------------------- recurrent variants


@op("lstmBlockCell", "rnn")
def lstm_block_cell(x, c_prev, h_prev, w, b, forget_bias=1.0):
    """TF-style fused cell: w:(I+H, 4H), gate order [i, c, f, o]."""
    z = jnp.matmul(jnp.concatenate([x, h_prev], axis=-1), w) + b
    i, j, f, o = jnp.split(z, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev + jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return h, c


@op("lstmBlock", "rnn")
def lstm_block(x, c0, h0, w, b, forget_bias=1.0, time_major=True):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(carry, xt):
        c, h = carry
        h_new, c_new = lstm_block_cell(xt, c, h, w, b, forget_bias)
        return (c_new, h_new), h_new

    (c_fin, h_fin), hs = lax.scan(step, (c0, h0), x)
    if not time_major:
        hs = jnp.swapaxes(hs, 0, 1)
    return hs, c_fin, h_fin


@op("dynamicRnn", "rnn")
def dynamic_rnn(x, h0, w_ih, w_hh, b, seq_lengths=None, time_major=False):
    """Simple-RNN (tanh) over a sequence with optional per-example lengths."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)
    T = x.shape[0]

    def step(h, inp):
        t, xt = inp
        h_new = jnp.tanh(jnp.matmul(xt, w_ih) + jnp.matmul(h, w_hh) + b)
        if seq_lengths is not None:
            mask = (t < jnp.asarray(seq_lengths))[:, None]
            h_new = jnp.where(mask, h_new, h)
            # TF dynamic_rnn semantics: carry holds the last valid state,
            # but OUTPUTS past each example's length are zero, so time
            # reductions and the bidirectional concat never see stale values.
            return h_new, jnp.where(mask, h_new, jnp.zeros_like(h_new))
        return h_new, h_new

    h_fin, hs = lax.scan(step, h0, (jnp.arange(T), x))
    if not time_major:
        hs = jnp.swapaxes(hs, 0, 1)
    return hs, h_fin


@op("staticRnn", "rnn")
def static_rnn(x, h0, w_ih, w_hh, b, time_major=False):
    return dynamic_rnn(x, h0, w_ih, w_hh, b, seq_lengths=None,
                       time_major=time_major)


@op("dynamicBidirectionalRnn", "rnn")
def dynamic_bidirectional_rnn(x, h0_fwd, h0_bwd, w_ih_f, w_hh_f, b_f,
                              w_ih_b, w_hh_b, b_b, seq_lengths=None,
                              time_major=False):
    hs_f, hf = dynamic_rnn(x, h0_fwd, w_ih_f, w_hh_f, b_f, seq_lengths,
                           time_major)
    axis = 0 if time_major else 1
    if seq_lengths is None:
        rev = lambda a: jnp.flip(a, axis=axis)
    else:
        # ragged batches: reverse each example within its own length so the
        # backward pass starts at the last REAL frame, not at padding
        lens = jnp.asarray(seq_lengths)
        T = x.shape[axis]
        idx = jnp.arange(T)
        rev_bt = jnp.where(idx[None, :] < lens[:, None],
                           lens[:, None] - 1 - idx[None, :], idx[None, :])
        gather_idx = rev_bt.T[:, :, None] if time_major else rev_bt[:, :, None]
        rev = lambda a: jnp.take_along_axis(a, gather_idx, axis=axis)
    hs_b, hb = dynamic_rnn(rev(x), h0_bwd, w_ih_b, w_hh_b, b_b, seq_lengths,
                           time_major)
    return jnp.concatenate([hs_f, rev(hs_b)], axis=-1), hf, hb


# ------------------------------------------------------ image stragglers


@op("nonMaxSuppressionOverlaps", "image")
def non_max_suppression_overlaps(overlaps, scores, max_out, overlap_threshold=0.5,
                                 score_threshold=float("-inf")):
    """NMS given a precomputed pairwise overlap matrix (N,N)."""
    overlaps = jnp.asarray(overlaps)
    n = overlaps.shape[0]
    order = jnp.argsort(-jnp.asarray(scores))

    def body(state, _):
        selected, suppressed, count = state
        avail = jnp.where(suppressed[order], jnp.inf, jnp.arange(n))
        pick_pos = jnp.argmin(avail).astype(jnp.int32)
        pick = order[pick_pos].astype(jnp.int32)
        valid = (~suppressed[pick]) & (count < max_out) & \
                (jnp.asarray(scores)[pick] > score_threshold)
        selected = selected.at[count].set(jnp.where(valid, pick, -1))
        newly = overlaps[pick] > overlap_threshold
        suppressed = jnp.where(valid, suppressed | newly | (jnp.arange(n) == pick),
                               suppressed)
        count = count + valid.astype(jnp.int32)
        return (selected, suppressed, count), None

    init = (jnp.full((max_out,), -1, jnp.int32), jnp.zeros((n,), bool),
            jnp.asarray(0, jnp.int32))
    (selected, _, _), _ = lax.scan(body, init, None, length=min(int(n), int(max_out)))
    return selected


@op("drawBoundingBoxes", "image")
def draw_bounding_boxes(images, boxes, colors=None):
    """images (B,H,W,C) float, boxes (B,K,4) normalized [ymin,xmin,ymax,xmax]."""
    images = jnp.asarray(images)
    b, h, w, c = images.shape
    boxes = jnp.asarray(boxes)
    if colors is None:
        colors = jnp.ones((1, c), images.dtype)
    colors = jnp.asarray(colors)
    ys = jnp.arange(h)[:, None]
    xs = jnp.arange(w)[None, :]

    def draw_one(img, bxs):
        def body(im, inp):
            box, color = inp
            y0 = jnp.round(box[0] * (h - 1)).astype(jnp.int32)
            x0 = jnp.round(box[1] * (w - 1)).astype(jnp.int32)
            y1 = jnp.round(box[2] * (h - 1)).astype(jnp.int32)
            x1 = jnp.round(box[3] * (w - 1)).astype(jnp.int32)
            inside = (ys >= y0) & (ys <= y1) & (xs >= x0) & (xs <= x1)
            border = inside & ((ys == y0) | (ys == y1) | (xs == x0) | (xs == x1))
            return jnp.where(border[..., None], color, im), None

        cols = jnp.broadcast_to(colors, (bxs.shape[0], c))
        im, _ = lax.scan(body, img, (bxs, cols))
        return im

    return jax.vmap(draw_one)(images, boxes)


op("adjustGamma", "image")(
    lambda img, gamma=1.0, gain=1.0: gain * jnp.power(jnp.asarray(img), gamma))


# -------------------------------------------------------- cnn stragglers


@op("deconv3d", "cnn")
def deconv3d(x, w, strides=(1, 1, 1), padding="VALID"):
    """x (N,C,D,H,W); w (kD,kH,kW,Cout,Cin) — mirrors deconv2d's layout."""
    return lax.conv_transpose(
        jnp.asarray(x), jnp.asarray(w), strides=tuple(strides), padding=padding,
        dimension_numbers=("NCDHW", "DHWOI", "NCDHW"))


@op("pnormPool2d", "cnn")
def pnorm_pool2d(x, window=(2, 2), strides=None, padding="VALID", p=2.0):
    """p-norm pooling (N,C,H,W) — the reference's pnormpool2d."""
    x = jnp.asarray(x)
    strides = tuple(strides) if strides is not None else tuple(window)
    xp = jnp.power(jnp.abs(x), p)
    summed = lax.reduce_window(
        xp, jnp.asarray(0.0, x.dtype), lax.add,
        (1, 1) + tuple(window), (1, 1) + strides, padding)
    return jnp.power(summed, 1.0 / p)


# ------------------------------------------------------- loss stragglers


@op("weightedCrossEntropyWithLogits", "loss")
def weighted_cross_entropy_with_logits(targets, logits, pos_weight=1.0):
    log_w = 1.0 + (pos_weight - 1.0) * targets
    return (1.0 - targets) * logits + log_w * (
        jnp.log1p(jnp.exp(-jnp.abs(logits))) + jnp.maximum(-logits, 0.0))


@op("meanPairwiseSquaredError", "loss")
def mean_pairwise_squared_error(labels, predictions, weights=1.0):
    d = jnp.asarray(predictions) - jnp.asarray(labels)
    d = d.reshape(d.shape[0], -1)
    n = d.shape[1]
    sum_d = jnp.sum(d, axis=1)
    sum_d2 = jnp.sum(d * d, axis=1)
    per_ex = 2.0 * (n * sum_d2 - sum_d * sum_d) / jnp.maximum(n * (n - 1), 1)
    return jnp.mean(per_ex * weights)


@op("ctcLoss", "loss")
def ctc_loss(log_probs, targets, input_lengths, target_lengths, blank=0):
    """CTC negative log-likelihood. log_probs (B,T,V) log-softmaxed,
    targets (B,S) padded with any value beyond target_lengths."""
    log_probs = jnp.asarray(log_probs)
    targets = jnp.asarray(targets)
    B, T, V = log_probs.shape
    S = targets.shape[1]
    L = 2 * S + 1
    NEG = jnp.asarray(-1e30, log_probs.dtype)

    ext = jnp.full((B, L), blank, targets.dtype)
    ext = ext.at[:, 1::2].set(targets)  # blank, t0, blank, t1, ...

    # alpha recursion over time (lax.scan over T)
    labels_logp = jnp.take_along_axis(
        log_probs[:, :, :], ext[:, None, :], axis=2)  # (B,T,L)

    can_skip = jnp.concatenate(
        [jnp.zeros((B, 2), bool),
         (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)

    alpha0 = jnp.full((B, L), NEG)
    alpha0 = alpha0.at[:, 0].set(labels_logp[:, 0, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(S > 0, labels_logp[:, 0, 1], NEG))

    def lse(a, b):
        m = jnp.maximum(a, b)
        m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
        return jnp.where(
            jnp.isfinite(m),
            m + jnp.log(jnp.exp(a - m_safe) + jnp.exp(b - m_safe)), m)

    def step(alpha, t):
        stay = alpha
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        merged = lse(lse(stay, prev1), prev2) + labels_logp[:, t, :]
        alpha_new = jnp.where((t < jnp.asarray(input_lengths))[:, None],
                              merged, alpha)
        return alpha_new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    last = 2 * jnp.asarray(target_lengths)  # index of final blank
    ll_blank = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    ll_label = jnp.take_along_axis(
        alpha, jnp.maximum(last - 1, 0)[:, None], axis=1)[:, 0]
    ll_label = jnp.where(jnp.asarray(target_lengths) > 0, ll_label, NEG)
    return -lse(ll_blank, ll_label)


# --------------------------------------------------------- random extras

op("lognormal", "random")(
    lambda key, shape, mean=0.0, std=1.0, dtype=jnp.float32:
        jnp.exp(jax.random.normal(key, tuple(shape), dtype=dtype) * std + mean))
@op("multinomial", "random")
def multinomial(key, logits, num_samples):
    """Per-row categorical draws: (B,V) logits -> (B, num_samples) indices."""
    logits = jnp.asarray(logits)
    keys = jax.random.split(key, logits.shape[0])
    return jax.vmap(
        lambda k, row: jax.random.categorical(k, row, shape=(num_samples,))
    )(keys, logits)


# ----------------------------------------------- import-path conveniences
# (ref: TF ops hit by frozen-graph corpora that had no direct registry slot)

op("einsum", "linalg")(lambda *xs, equation: jnp.einsum(equation, *xs))
op("l2Loss", "loss")(lambda x: 0.5 * jnp.sum(jnp.square(x)))
# (math.erfc already registered in math_defs — no re-registration here)


# ------------------------------------------------ ONNX-layout recurrent ops
# (ref: samediff-import-onnx maps ONNX LSTM/GRU/RNN onto lstmLayer-class ops;
# here the ONNX layouts/gate orders are first-class op variants, like
# libnd4j's lstmLayer handles multiple data formats and directions.)


def _onnx_dir_list(direction, num_dir):
    if direction == "bidirectional":
        return [(0, False), (1, True)]
    return [(0, direction == "reverse")]


@op("lstmOnnx", "rnn")
def lstm_onnx(x, w, r, b=None, sequence_lens=None, initial_h=None,
              initial_c=None, direction="forward"):
    """ONNX LSTM: x (T,B,I); w (D,4H,I) gates IOFC; r (D,4H,H); b (D,8H)
    = Wb|Rb; outputs (Y (T,D,B,H), Y_h (D,B,H), Y_c (D,B,H))."""
    from deeplearning4j_tpu.ops.nn_defs import lstm_layer
    x = jnp.asarray(x)
    T, B, _ = x.shape
    D, four_h, _ = w.shape
    H = four_h // 4
    mask = None
    if sequence_lens is not None:
        mask = (jnp.arange(T)[:, None] < jnp.asarray(sequence_lens)[None, :]
                ).astype(x.dtype)  # (T,B)
    perm = jnp.concatenate([jnp.arange(H),                # i
                            2 * H + jnp.arange(H),        # f
                            3 * H + jnp.arange(H),        # g (ONNX c)
                            H + jnp.arange(H)])           # o
    ys_all, h_all, c_all = [], [], []
    for d, reverse in _onnx_dir_list(direction, D):
        wi = jnp.transpose(w[d])[:, perm]                 # (I,4H) IFGO
        ri = jnp.transpose(r[d])[:, perm]                 # (H,4H)
        if b is not None:
            bias = (b[d, :four_h] + b[d, four_h:])[perm]
        else:
            bias = jnp.zeros((four_h,), x.dtype)
        h0 = initial_h[d] if initial_h is not None else jnp.zeros((B, H), x.dtype)
        c0 = initial_c[d] if initial_c is not None else jnp.zeros((B, H), x.dtype)
        ys, (hT, cT) = lstm_layer(x, h0, c0, wi, ri, bias, time_major=True,
                                  reverse=reverse, mask=mask)
        ys_all.append(ys); h_all.append(hT); c_all.append(cT)
    return (jnp.stack(ys_all, axis=1),      # (T,D,B,H)
            jnp.stack(h_all, axis=0),       # (D,B,H)
            jnp.stack(c_all, axis=0))


@op("gruOnnx", "rnn")
def gru_onnx(x, w, r, b=None, sequence_lens=None, initial_h=None,
             direction="forward", linear_before_reset=0):
    """ONNX GRU: x (T,B,I); w (D,3H,I) gates ZRH; r (D,3H,H); b (D,6H)
    = Wb|Rb. Both linear_before_reset semantics."""
    x = jnp.asarray(x)
    T, B, _ = x.shape
    D, three_h, _ = w.shape
    H = three_h // 3
    mask = None
    if sequence_lens is not None:
        mask = (jnp.arange(T)[:, None] < jnp.asarray(sequence_lens)[None, :]
                ).astype(x.dtype)

    def run_dir(d, reverse):
        wi = jnp.transpose(w[d])        # (I,3H) ZRH
        ri = jnp.transpose(r[d])        # (H,3H)
        wb = b[d, :three_h] if b is not None else jnp.zeros((three_h,), x.dtype)
        rb = b[d, three_h:] if b is not None else jnp.zeros((three_h,), x.dtype)
        h0 = initial_h[d] if initial_h is not None else jnp.zeros((B, H), x.dtype)
        xs = jnp.flip(x, 0) if reverse else x
        ms = None if mask is None else (jnp.flip(mask, 0) if reverse else mask)

        def step(h, inp):
            xt, mt = inp if ms is not None else (inp, None)
            gx = jnp.matmul(xt, wi) + wb          # (B,3H)
            gh = jnp.matmul(h, ri) + rb
            z = jax.nn.sigmoid(gx[:, :H] + gh[:, :H])
            rr = jax.nn.sigmoid(gx[:, H:2*H] + gh[:, H:2*H])
            if linear_before_reset:
                n = jnp.tanh(gx[:, 2*H:] + rr * gh[:, 2*H:])
            else:
                n = jnp.tanh(gx[:, 2*H:] +
                             jnp.matmul(rr * h, ri[:, 2*H:]) + rb[2*H:])
            h_new = (1.0 - z) * n + z * h
            if mt is not None:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        inp = (xs, ms) if ms is not None else xs
        hT, ys = lax.scan(step, h0, inp)
        if reverse:
            ys = jnp.flip(ys, 0)
        return ys, hT

    outs = [run_dir(d, rev) for d, rev in _onnx_dir_list(direction, D)]
    return (jnp.stack([y for y, _ in outs], axis=1),
            jnp.stack([h for _, h in outs], axis=0))


@op("rnnOnnx", "rnn")
def rnn_onnx(x, w, r, b=None, sequence_lens=None, initial_h=None,
             direction="forward", activation="Tanh"):
    """ONNX vanilla RNN: x (T,B,I); w (D,H,I); r (D,H,H); b (D,2H)."""
    x = jnp.asarray(x)
    T, B, _ = x.shape
    D, H, _ = w.shape
    act = {"Tanh": jnp.tanh, "Relu": jax.nn.relu,
           "Sigmoid": jax.nn.sigmoid}[activation]
    mask = None
    if sequence_lens is not None:
        mask = (jnp.arange(T)[:, None] < jnp.asarray(sequence_lens)[None, :]
                ).astype(x.dtype)

    def run_dir(d, reverse):
        wi, ri = jnp.transpose(w[d]), jnp.transpose(r[d])
        bias = (b[d, :H] + b[d, H:]) if b is not None else jnp.zeros((H,), x.dtype)
        h0 = initial_h[d] if initial_h is not None else jnp.zeros((B, H), x.dtype)
        xs = jnp.flip(x, 0) if reverse else x
        ms = None if mask is None else (jnp.flip(mask, 0) if reverse else mask)

        def step(h, inp):
            xt, mt = inp if ms is not None else (inp, None)
            h_new = act(jnp.matmul(xt, wi) + jnp.matmul(h, ri) + bias)
            if mt is not None:
                h_new = jnp.where(mt[:, None] > 0, h_new, h)
            return h_new, h_new

        inp = (xs, ms) if ms is not None else xs
        hT, ys = lax.scan(step, h0, inp)
        if reverse:
            ys = jnp.flip(ys, 0)
        return ys, hT

    outs = [run_dir(d, rev) for d, rev in _onnx_dir_list(direction, D)]
    return (jnp.stack([y for y, _ in outs], axis=1),
            jnp.stack([h for _, h in outs], axis=0))


# ---------------------------------------------- element-indexing stragglers

op("gatherElements", "shape")(
    lambda x, indices, axis=0: jnp.take_along_axis(
        jnp.asarray(x), jnp.asarray(indices), axis=axis))


@op("scatterElements", "shape")
def scatter_elements(x, indices, updates, axis=0, reduction="none"):
    x = jnp.asarray(x)
    indices = jnp.asarray(indices)
    idx = [jnp.broadcast_to(jnp.arange(s).reshape(
        [-1 if i == d else 1 for i in range(indices.ndim)]), indices.shape)
        for d, s in enumerate(indices.shape)]
    idx[axis] = indices
    ref = x.at[tuple(idx)]
    return {"none": ref.set, "add": ref.add, "mul": ref.multiply,
            "max": ref.max, "min": ref.min}[reduction](jnp.asarray(updates))


op("eyeLike", "shape")(
    lambda x, k=0, dtype=None: jnp.eye(jnp.asarray(x).shape[0],
                                       jnp.asarray(x).shape[1], k=k,
                                       dtype=dtype or jnp.asarray(x).dtype))


@op("shrink", "nn")
def shrink(x, bias=0.0, lambd=0.5):
    x = jnp.asarray(x)
    return jnp.where(x > lambd, x - bias, jnp.where(x < -lambd, x + bias, 0.0))


@op("meanVarianceNormalization", "nn")
def mean_variance_normalization(x, axes=(0, 2, 3), eps=1e-9):
    x = jnp.asarray(x)
    mu = jnp.mean(x, axis=tuple(axes), keepdims=True)
    var = jnp.var(x, axis=tuple(axes), keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps)


# ----------------------------------------------- last libnd4j stragglers
# (generic/parity_ops + generic/images + helpers/knn + loss rounding out
# the ~450-op declarable inventory)

op("bitcast", "math")(
    lambda x, dtype: lax.bitcast_convert_type(jnp.asarray(x), dtype))


@op("assertOp", "math")
def assert_op(condition, message="assertOp failed"):
    """Eager-only (the reference's Assert aborts execution; under jit use
    checkify/debug callbacks)."""
    import numpy as np
    if not np.all(np.asarray(condition)):
        raise AssertionError(message)
    return jnp.asarray(True)


@op("whereNonzero", "shape")
def where_nonzero(x):
    """Indices of nonzero elements, (N, ndim) int — TF's 1-input Where.
    Eager-only: the output shape is data-dependent (the reference computes
    it host-side too)."""
    import numpy as np
    return jnp.asarray(np.argwhere(np.asarray(x)))


@op("fakeQuantWithMinMaxVars", "math")
def fake_quant_with_min_max_vars(x, min_val, max_val, num_bits=8,
                                 narrow_range=False):
    """TF-style fake quantization (ref: fake_quant_with_min_max_vars.cpp)."""
    qmin = 1.0 if narrow_range else 0.0
    qmax = 2.0 ** num_bits - 1.0
    min_val = jnp.asarray(min_val, jnp.float32)
    max_val = jnp.asarray(max_val, jnp.float32)
    try:
        if bool(jnp.any(max_val <= min_val)):
            # TF's kernel requires min < max; fail loudly, not with NaNs
            raise ValueError(
                "fakeQuantWithMinMaxVars requires min_val < max_val")
    except jax.errors.TracerBoolConversionError:
        pass  # under trace (e.g. the per-channel vmap) the check is skipped
    scale = (max_val - min_val) / (qmax - qmin)
    zero_point = qmin - min_val / scale
    nudged_zp = jnp.clip(jnp.round(zero_point), qmin, qmax)
    nudged_min = (qmin - nudged_zp) * scale
    nudged_max = (qmax - nudged_zp) * scale
    clamped = jnp.clip(x, nudged_min, nudged_max)
    return jnp.round((clamped - nudged_min) / scale) * scale + nudged_min


op("fakeQuantWithMinMaxVarsPerChannel", "math")(
    lambda x, min_vals, max_vals, num_bits=8, narrow_range=False:
        jax.vmap(lambda xc, lo, hi: fake_quant_with_min_max_vars(
            xc, lo, hi, num_bits, narrow_range),
            in_axes=(-1, 0, 0), out_axes=-1)(
                jnp.asarray(x), jnp.asarray(min_vals), jnp.asarray(max_vals)))


@op("knnMindistance", "math")
def knn_mindistance(point, lowest, highest):
    """Min distance from a point to an axis-aligned box (ref: helpers/knn —
    used by the barnes-hut tree walk)."""
    point, lowest, highest = map(jnp.asarray, (point, lowest, highest))
    clamped = jnp.clip(point, lowest, highest)
    return jnp.sqrt(jnp.sum((point - clamped) ** 2, axis=-1))


@op("hashCode", "math")
def hash_code(x):
    """Order-sensitive 32-bit hash over the tensor's RAW bytes with the
    Java-style ``h = 31*h + e`` recurrence (ref: hashcode.cpp hashes the
    native buffer in the array's own dtype; a float32 and float64 view of
    the same values hash DIFFERENTLY, there as here — canonicalize dtype
    before hashing if config-independent keys are needed). Vectorized in
    fixed-size chunks: per chunk sum(e_i * 31^(m-1-i)), chained with
    h = h*31^m + chunk — uint64 wraparound preserves residues mod 2^32
    since 2^32 | 2^64, and peak memory stays bounded for GB-scale tensors."""
    import numpy as np
    arr = np.ravel(np.asarray(x))  # contiguous; copies only if it must
    bytes_view = arr.view(np.uint8)
    n = bytes_view.size
    if n == 0:
        return jnp.asarray(np.int64(0))
    CHUNK = 1 << 20
    h = np.uint64(0)
    with np.errstate(over="ignore"):  # uint64 wraparound is the algorithm
        for start in range(0, n, CHUNK):
            block = bytes_view[start:start + CHUNK].astype(np.uint64)
            m = block.size
            pows = np.ones(m, np.uint64)
            if m > 1:
                np.multiply.accumulate(np.full(m - 1, 31, np.uint64),
                                       out=pows[1:])
            h = (h * np.uint64(pow(31, m, 1 << 64))
                 + np.uint64((block * pows[::-1]).sum()))
    return jnp.asarray(np.int64(h & np.uint64(0xFFFFFFFF)))


_YIQ = jnp.array([[0.299, 0.587, 0.114],
                  [0.5959, -0.2746, -0.3213],
                  [0.2115, -0.5227, 0.3112]], jnp.float32)

_YIQ_INV = jnp.linalg.inv(_YIQ)

op("rgbToYiq", "image")(
    lambda x: jnp.einsum("...c,dc->...d", jnp.asarray(x, jnp.float32), _YIQ))
op("yiqToRgb", "image")(
    lambda x: jnp.einsum("...c,dc->...d", jnp.asarray(x, jnp.float32),
                         _YIQ_INV))


@op("compareAndBitpack", "math")
def compare_and_bitpack(x, threshold):
    """Pack (x > threshold) into uint8 bytes, 8 along the last axis, MSB
    first (ref: compare_and_bitpack.cpp)."""
    x = jnp.asarray(x)
    bits = (x > threshold).astype(jnp.uint8)
    bits = bits.reshape(x.shape[:-1] + (x.shape[-1] // 8, 8))
    weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
    return jnp.sum(bits * weights, axis=-1).astype(jnp.uint8)


@op("matchConditionTransform", "math")
def match_condition_transform(x, value, condition="eq", eps=1e-5):
    """Boolean mask of elements matching the condition (ref:
    match_condition_transform.cpp; the reduce variant is reduce.matchCondition)."""
    x = jnp.asarray(x)
    ops_map = {
        "eq": lambda: jnp.abs(x - value) <= eps,
        "neq": lambda: jnp.abs(x - value) > eps,
        "lt": lambda: x < value, "lte": lambda: x <= value,
        "gt": lambda: x > value, "gte": lambda: x >= value,
    }
    return ops_map[condition]()


@op("ctcGreedyDecoder", "loss")
def ctc_greedy_decoder(log_probs, sequence_lengths, blank=0, merge_repeated=True):
    """Greedy (best-path) CTC decode: argmax per frame, collapse repeats,
    drop blanks (ref: ctc_beam.cpp's greedy path). Returns (B, T) decoded
    ids padded with -1 plus (B,) decoded lengths. Eager-friendly."""
    import numpy as np
    lp = np.asarray(log_probs)
    B, T, V = lp.shape
    seq = np.full((B, T), -1, np.int64)
    lens = np.zeros((B,), np.int64)
    raw = lp.argmax(-1)
    for b in range(B):
        prev = -1
        k = 0
        for t in range(int(np.asarray(sequence_lengths)[b])):
            s = int(raw[b, t])
            if s != blank and not (merge_repeated and s == prev):
                seq[b, k] = s
                k += 1
            prev = s
        lens[b] = k
    return jnp.asarray(seq), jnp.asarray(lens)


@op("logPoissonLoss", "loss")
def log_poisson_loss(targets, log_input, compute_full_loss=False):
    """(ref: log_poisson_loss.cpp): exp(log_input) - targets*log_input
    (+ Stirling when full)."""
    targets = jnp.asarray(targets)
    log_input = jnp.asarray(log_input)
    loss = jnp.exp(log_input) - targets * log_input
    if compute_full_loss:
        stirling = (targets * jnp.log(jnp.maximum(targets, 1e-12))
                    - targets + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(targets, 1.0)))
        loss = loss + jnp.where(targets > 1.0, stirling, 0.0)
    return loss
