"""Op-spec registry — the single source of truth for the op surface.

The reference maintains ~2,000 Java op wrapper classes plus a codegen tool
(contrib/codegen-tools) that emits typed namespaces (SDMath, SDNN, ...). The
TPU rebuild collapses that to ONE table: each op is registered once with its
jnp-level implementation, and both surfaces are generated from it:

- the **eager** namespaces (``ops.math.tanh(x)`` on NDArray) — analog of
  org.nd4j.linalg.factory.ops.NDMath etc.;
- the **graph** namespaces (``sd.math.tanh(var)`` building graph nodes) — analog
  of org.nd4j.autodiff.samediff.ops.SDMath etc. (see autodiff/).

Gradients come from jax.grad over the impl (every impl is differentiable jnp
code), so there is no per-op ``doDiff`` to write — the reference's largest
maintenance surface (SURVEY.md §2.2 "op classes") disappears by construction.

The registry doubles as the **coverage ledger** (ref:
org.nd4j.autodiff.validation.OpValidation): tests mark ops validated and
``coverage_report()`` lists unvalidated ops.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from deeplearning4j_tpu.ndarray.array import NDArray, _unwrap


@dataclass
class OpSpec:
    name: str
    namespace: str
    fn: Callable  # jnp-level implementation (jax arrays in/out)
    doc: str = ""
    validated: bool = False  # flipped by the op-validation test harness


REGISTRY: Dict[str, OpSpec] = {}


def op(name: str, namespace: str, doc: str = ""):
    """Register a jnp-level function as a framework op."""

    def deco(fn):
        key = f"{namespace}.{name}"
        REGISTRY[key] = OpSpec(name=name, namespace=namespace, fn=fn, doc=doc or fn.__doc__ or "")
        return fn

    return deco


def get(name: str, namespace: Optional[str] = None) -> OpSpec:
    if namespace is not None:
        return REGISTRY[f"{namespace}.{name}"]
    matches = [s for k, s in REGISTRY.items() if s.name == name]
    if not matches:
        raise KeyError(f"unknown op: {name}")
    return matches[0]


def mark_validated(name: str, namespace: Optional[str] = None):
    get(name, namespace).validated = True


def coverage_report():
    """(validated, unvalidated) op key lists — the op-parity ledger."""
    done = sorted(k for k, s in REGISTRY.items() if s.validated)
    todo = sorted(k for k, s in REGISTRY.items() if not s.validated)
    return done, todo


class EagerNamespace:
    """Eager op surface over NDArray, generated from the registry
    (ref: org.nd4j.linalg.factory.ops.ND* generated classes)."""

    def __init__(self, namespace: str):
        self._namespace = namespace

    def __getattr__(self, name: str):
        spec = REGISTRY.get(f"{self._namespace}.{name}")
        if spec is None:
            raise AttributeError(f"no op {self._namespace}.{name}")

        def wrap_out(out):
            if isinstance(out, tuple) and hasattr(out, "_fields"):  # namedtuple
                return type(out)(*(wrap_out(o) for o in out))
            if isinstance(out, (tuple, list)):
                return type(out)(wrap_out(o) for o in out)
            if isinstance(out, (int, float, bool)):
                return out
            return NDArray(out)

        @functools.wraps(spec.fn)
        def call(*args, **kwargs):
            args = [_unwrap(a) for a in args]
            kwargs = {k: _unwrap(v) for k, v in kwargs.items()}
            return wrap_out(spec.fn(*args, **kwargs))

        # cache on the instance so repeated lookups are cheap
        setattr(self, name, call)
        return call

    def __dir__(self):
        prefix = self._namespace + "."
        return [k[len(prefix):] for k in REGISTRY if k.startswith(prefix)]
