"""NN / CNN / RNN / loss / image op definitions.

Covers the reference's declarable custom-op inventory for neural nets
(libnd4j include/ops/declarable/generic: conv2d, lstmLayer, batchnorm, softmax,
attention, image_resize, ... and org.nd4j.linalg.api.ops.impl.layers.*) as
registry entries over jnp/lax. Convs and matmuls lower to the MXU via XLA;
recurrences are expressed with lax.scan so XLA compiles one fused loop instead
of the reference's per-timestep op dispatch.

Layout convention: CNN ops default to NCHW with OIHW kernels (the reference's
default); NHWC is available via ``data_format`` for TPU-preferred layouts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

# -------------------------------------------------------------- activations
# (ref: org.nd4j.linalg.activations.impl.* — ~25 classes)

op("relu", "nn")(jax.nn.relu)
op("relu6", "nn")(jax.nn.relu6)
op("leakyRelu", "nn")(lambda x, alpha=0.01: jax.nn.leaky_relu(x, alpha))
op("elu", "nn")(jax.nn.elu)
op("selu", "nn")(jax.nn.selu)
op("celu", "nn")(jax.nn.celu)
op("gelu", "nn")(lambda x, approximate=True: jax.nn.gelu(x, approximate=approximate))
op("sigmoid", "nn")(jax.nn.sigmoid)
op("hardSigmoid", "nn")(jax.nn.hard_sigmoid)
op("hardTanh", "nn")(lambda x: jnp.clip(x, -1.0, 1.0))
op("softmax", "nn")(lambda x, axis=-1: jax.nn.softmax(x, axis=axis))
op("logSoftmax", "nn")(lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis))
op("softplus", "nn")(jax.nn.softplus)
op("softsign", "nn")(jax.nn.soft_sign)
op("swish", "nn")(jax.nn.silu)
op("mish", "nn")(jax.nn.mish)
op("prelu", "nn")(lambda x, alpha: jnp.where(x >= 0, x, alpha * x))
op("thresholdRelu", "nn")(lambda x, theta=1.0: jnp.where(x > theta, x, 0.0))
op("rationalTanh", "nn")(
    lambda x: 1.7159 * jnp.tanh(2.0 * x / 3.0))
op("rectifiedTanh", "nn")(lambda x: jnp.maximum(0.0, jnp.tanh(x)))
op("gumbelSoftmax", "nn")(
    lambda key, logits, temperature=1.0, axis=-1: jax.nn.softmax(
        (logits + jax.random.gumbel(key, logits.shape)) / temperature, axis=axis))


@op("linear", "nn")
def linear(x, w, b=None):
    """Dense affine: x @ w (+ b). w: (in, out)."""
    y = jnp.matmul(x, w)
    return y + b if b is not None else y


@op("layerNorm", "nn")
def layer_norm(x, gain=None, bias=None, axis=-1, eps=1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    if gain is not None:
        y = y * gain
    if bias is not None:
        y = y + bias
    return y


@op("batchNorm", "nn")
def batch_norm(x, mean, var, gamma=None, beta=None, eps=1e-5, axis=1):
    """Inference-mode batch norm over channel ``axis`` (ref: batchnorm op)."""
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    y = (x - mean.reshape(shape)) * lax.rsqrt(var.reshape(shape) + eps)
    if gamma is not None:
        y = y * gamma.reshape(shape)
    if beta is not None:
        y = y + beta.reshape(shape)
    return y


@op("lrn", "nn")
def local_response_normalization(x, depth_radius=5, bias=1.0, alpha=1.0, beta=0.5):
    """LRN over channel dim of NCHW input (ref: LocalResponseNormalization)."""
    sq = x * x
    pad = depth_radius
    padded = jnp.pad(sq, ((0, 0), (pad, pad), (0, 0), (0, 0)))
    windows = sum(padded[:, i:i + x.shape[1]] for i in range(2 * depth_radius + 1))
    return x / jnp.power(bias + alpha * windows, beta)


@op("dotProductAttention", "nn")
def dot_product_attention(q, k, v, mask=None, scaled=True):
    """(ref: dot_product_attention / multi_head_dot_product_attention custom op)
    q,k,v: (..., seq, head_dim); mask: broadcastable to (..., q_seq, k_seq)."""
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(d, dtype=scores.dtype))
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, jnp.finfo(scores.dtype).min)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


@op("scaledDotProductAttentionFused", "nn")
def scaled_dot_product_attention_fused(q, k, v, mask=None, scale=None,
                                       causal=False, use_kernel=None):
    """Kernel-backed scaled-dot-product attention on split-head
    (B, H, T, D) layouts — the target op of the SameDiff attention-fusion
    rewrite (``SameDiff.fuseAttention``): an imported graph's
    matmul->scale->softmax->matmul chain collapses onto this, so the
    (B, H, T, T) score tensor stays in VMEM instead of round-tripping HBM
    between four graph nodes. ``use_kernel``: None = auto, True forces a
    kernel (interpret mode off-TPU), False pins the einsum. First-order
    autodiff when a kernel is taken; the einsum path differentiates to any
    order.

    The auto gate is MEASURED, not assumed (BASELINE.md round-5 "imported
    attention fusion"): on this split-head layout the per-(b, h) kernel
    grid only beats XLA's batched einsum once the per-row (T, T) block is
    large — (32, 12, T, 64) fwd+bwd: einsum 3.1/3.3/6.9/20.6 ms vs kernel
    3.2/4.0/7.1/9.4 at T=128/256/512/1024. Auto therefore takes the
    whole-head kernel at T >= 768, the STREAMED flash kernel past the
    whole-(T, T) VMEM envelope (T > 1024), and the einsum below — which is
    why fusing config #4's T=128 graph is perf-neutral by design there.

    ``mask`` is ADDITIVE, broadcast onto the (B, H, T, T) scores after
    scaling (the BERT-import convention: 0 for visible, a large negative
    number for padding). A masked call always takes the einsum path — the
    kernels support only causal/none masking — so for masked graphs the
    fusion is a node-collapse, not a kernel win."""
    B, H, T, D = q.shape
    from deeplearning4j_tpu.ops.pallas_kernels import (
        active_global_mesh, flash_attention, flash_envelope_ok,
        mha_attention, packed_kernel_shape_ok)
    on_tpu = jax.default_backend() == "tpu"
    same = mask is None and k.shape == q.shape and v.shape == q.shape
    whole_ok = same and packed_kernel_shape_ok(T)
    stream_ok = same and T > 1024 and flash_envelope_ok(T)
    if use_kernel and not (whole_ok or stream_ok):
        raise ValueError(
            f"scaledDotProductAttentionFused: use_kernel=True but shape "
            f"{q.shape} (mask={'set' if mask is not None else 'None'}) "
            f"fits neither the whole-head (unmasked, T % 8 == 0, T <= "
            f"1024, matching q/k/v) nor the streamed kernel envelope; "
            f"use_kernel=None/False for the einsum path")
    auto = use_kernel is None and on_tpu and active_global_mesh() is None
    take_whole = whole_ok and (use_kernel or (auto and T >= 768))
    take_stream = stream_ok and (use_kernel or auto)
    if take_whole:
        return mha_attention(q, k, v, causal, scale, not on_tpu)
    if take_stream:
        return flash_attention(q, k, v, causal, None, None, scale,
                               not on_tpu)
    sc = scale if scale is not None else 1.0 / (D ** 0.5)
    # matmul (not einsum) so leading dims BROADCAST exactly like the
    # original imported matmul chain — shared-across-batch/head k/v
    # remain valid after the fuseAttention rewrite, and static-shape
    # sentinels in SameDiff metadata can't manufacture a runtime mismatch
    s = jnp.matmul(q, jnp.swapaxes(k, -1, -2)) * sc
    if mask is not None:
        s = s + mask.astype(s.dtype)
    if causal:
        cm = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(cm[None, None], s, jnp.finfo(s.dtype).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.matmul(p, v)


@op("multiHeadDotProductAttention", "nn")
def multi_head_attention(x_q, x_kv, wq, wk, wv, wo, num_heads, mask=None,
                         use_kernel=None):
    """Fused MHA: x_q (B,Tq,D), x_kv (B,Tk,D); wq/wk/wv: (D,O); wo: (O,O).
    Head dims derive from the PROJECTION width O, not the input width D —
    rectangular projections (nIn != nOut, e.g. SelfAttentionLayer with
    distinct sizes) are valid.

    ``use_kernel``: route the unmasked square (Tq == Tk) case through the
    packed whole-head VMEM Pallas kernel — the flagship-bench attention
    path (round 5): the (B, T, O) projections feed the kernel directly, so
    the (B, H, T, hd) head transposes never materialize and the per-head
    (T, T) scores stay on-chip. None (default) = auto: kernel on TPU,
    XLA einsum elsewhere (interpret-mode Pallas would slow CPU runs);
    True forces it (tests use interpret mode); False forces the einsum
    path. Masked / cross-length attention always uses the einsum path
    (the kernel supports only causal/none masking). Auto never routes to
    the kernel while a global mesh context is active (ParallelWrapper's
    sharded-jit fit): a monolithic pallas_call over sharded operands
    would force GSPMD all-gathers — the einsum path partitions cleanly
    instead. ``use_kernel=True`` overrides even that (single-device
    meshes, tests)."""
    B, Tq, _ = x_q.shape
    Tk = x_kv.shape[1]
    O = wq.shape[-1]
    hd = O // num_heads

    from deeplearning4j_tpu.ops.pallas_kernels import (
        active_global_mesh, mha_attention_packed, packed_kernel_shape_ok)
    eligible = (mask is None and Tq == Tk and packed_kernel_shape_ok(Tq)
                and O % num_heads == 0)
    on_tpu = jax.default_backend() == "tpu"
    auto = use_kernel is None and on_tpu and active_global_mesh() is None
    if eligible and (use_kernel or auto):
        qp = jnp.matmul(x_q, wq)
        kp = jnp.matmul(x_kv, wk)
        vp = jnp.matmul(x_kv, wv)
        out = mha_attention_packed(qp, kp, vp, num_heads, False, None,
                                   not on_tpu, jnp.float32)
        return jnp.matmul(out, wo)

    def split(x, w, T):
        return jnp.matmul(x, w).reshape(B, T, num_heads, hd).transpose(0, 2, 1, 3)

    q, k, v = split(x_q, wq, Tq), split(x_kv, wk, Tk), split(x_kv, wv, Tk)
    m = mask[:, None, None, :] if (mask is not None and mask.ndim == 2) else mask
    out = dot_product_attention(q, k, v, mask=m)
    out = out.transpose(0, 2, 1, 3).reshape(B, Tq, O)
    return jnp.matmul(out, wo)


@op("embeddingLookup", "nn")
def embedding_lookup(table, ids):
    return jnp.take(table, ids, axis=0)


# --------------------------------------------------------------------- CNN


def _dims(data_format, spatial):
    if spatial == 1:
        return ("NCH", "OIH", "NCH") if data_format == "NCW" else ("NHC", "HIO", "NHC")
    if spatial == 2:
        return ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")
    return ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" else ("NDHWC", "DHWIO", "NDHWC")


def _pad(padding, kernel, strides, dilation):
    if isinstance(padding, str):
        return padding  # 'SAME' | 'VALID'
    if isinstance(padding, int):
        padding = [padding] * len(kernel)
    return [(p, p) if isinstance(p, int) else tuple(p) for p in padding]


@op("conv2d", "cnn")
def conv2d(x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1),
           data_format="NCHW", groups=1):
    """2D convolution (ref: libnd4j generic/nn/convo/conv2d.cpp).
    x: NCHW, w: OIHW (out_ch, in_ch/groups, kh, kw) by default."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _dims(data_format, 2))
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(strides),
        padding=_pad(padding, w.shape[-2:], strides, dilation),
        rhs_dilation=tuple(dilation), dimension_numbers=dn,
        feature_group_count=groups)
    if b is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + b.reshape(shape)
    return out


@op("conv1d", "cnn")
def conv1d(x, w, b=None, stride=1, padding="SAME", dilation=1, data_format="NCW"):
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _dims(data_format, 1))
    out = lax.conv_general_dilated(
        x, w, window_strides=(stride,), padding=_pad(padding, w.shape[-1:], (stride,), (dilation,)),
        rhs_dilation=(dilation,), dimension_numbers=dn)
    if b is not None:
        shape = [1, -1, 1] if data_format == "NCW" else [1, 1, -1]
        out = out + b.reshape(shape)
    return out


@op("conv3d", "cnn")
def conv3d(x, w, b=None, strides=(1, 1, 1), padding="SAME", dilation=(1, 1, 1),
           data_format="NCDHW"):
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _dims(data_format, 3))
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(strides),
        padding=_pad(padding, w.shape[-3:], strides, dilation),
        rhs_dilation=tuple(dilation), dimension_numbers=dn)
    if b is not None:
        shape = [1, -1, 1, 1, 1] if data_format == "NCDHW" else [1, 1, 1, 1, -1]
        out = out + b.reshape(shape)
    return out


@op("deconv2d", "cnn")
def deconv2d(x, w, b=None, strides=(1, 1), padding="SAME", data_format="NCHW"):
    """Transposed conv (ref: deconv2d.cpp). w: (in_ch, out_ch, kh, kw) -> we
    accept OIHW-like (out=in_ch of fwd) by using conv_transpose semantics."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _dims(data_format, 2))
    out = lax.conv_transpose(
        x, w, strides=tuple(strides),
        padding=_pad(padding, w.shape[-2:], strides, (1, 1)),
        dimension_numbers=dn, transpose_kernel=True)
    if b is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + b.reshape(shape)
    return out


@op("depthwiseConv2d", "cnn")
def depthwise_conv2d(x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1),
                     data_format="NCHW"):
    """w: (ch_mult*in_ch, 1, kh, kw) grouped conv with groups=in_ch."""
    in_ch = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    dn = lax.conv_dimension_numbers(x.shape, w.shape, _dims(data_format, 2))
    out = lax.conv_general_dilated(
        x, w, window_strides=tuple(strides),
        padding=_pad(padding, w.shape[-2:], strides, dilation),
        rhs_dilation=tuple(dilation), dimension_numbers=dn,
        feature_group_count=in_ch)
    if b is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + b.reshape(shape)
    return out


@op("separableConv2d", "cnn")
def separable_conv2d(x, depth_w, point_w, b=None, strides=(1, 1), padding="SAME",
                     data_format="NCHW"):
    y = depthwise_conv2d(x, depth_w, None, strides, padding, (1, 1), data_format)
    return conv2d(y, point_w, b, (1, 1), "VALID", (1, 1), data_format)


def _pool(x, kind, kernel, strides, padding, data_format="NCHW"):
    spatial = len(kernel)
    if data_format.startswith("NC"):
        window = (1, 1) + tuple(kernel)
        strides_full = (1, 1) + tuple(strides)
    else:
        window = (1,) + tuple(kernel) + (1,)
        strides_full = (1,) + tuple(strides) + (1,)
    if isinstance(padding, str):
        pads = lax.padtype_to_pads(x.shape, window, strides_full, padding)
    else:
        p = _pad(padding, kernel, strides, (1,) * spatial)
        pads = ([(0, 0), (0, 0)] + list(p)) if data_format.startswith("NC") else ([(0, 0)] + list(p) + [(0, 0)])
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
                                 lax.max, window, strides_full, pads)
    if kind == "sum":
        return lax.reduce_window(x, 0.0, lax.add, window, strides_full, pads)
    # avg: divide by actual window size (count_include_pad=False, dl4j default)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_full, pads)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, window, strides_full, pads)
    return s / counts


@op("maxPool2d", "cnn")
def max_pool2d(x, kernel=(2, 2), strides=None, padding="VALID", data_format="NCHW"):
    return _pool(x, "max", kernel, strides or kernel, padding, data_format)


@op("avgPool2d", "cnn")
def avg_pool2d(x, kernel=(2, 2), strides=None, padding="VALID", data_format="NCHW"):
    return _pool(x, "avg", kernel, strides or kernel, padding, data_format)


@op("maxPool1d", "cnn")
def max_pool1d(x, kernel=2, strides=None, padding="VALID", data_format="NCW"):
    return _pool(x, "max", (kernel,), (strides or kernel,), padding, data_format)


@op("avgPool1d", "cnn")
def avg_pool1d(x, kernel=2, strides=None, padding="VALID", data_format="NCW"):
    return _pool(x, "avg", (kernel,), (strides or kernel,), padding, data_format)


@op("maxPool3d", "cnn")
def max_pool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID", data_format="NCDHW"):
    return _pool(x, "max", kernel, strides or kernel, padding, data_format)


@op("avgPool3d", "cnn")
def avg_pool3d(x, kernel=(2, 2, 2), strides=None, padding="VALID", data_format="NCDHW"):
    return _pool(x, "avg", kernel, strides or kernel, padding, data_format)


@op("globalAvgPool", "cnn")
def global_avg_pool(x, data_format="NCHW", keepdims=False):
    axes = tuple(range(2, x.ndim)) if data_format.startswith("NC") else tuple(range(1, x.ndim - 1))
    return jnp.mean(x, axis=axes, keepdims=keepdims)


@op("globalMaxPool", "cnn")
def global_max_pool(x, data_format="NCHW", keepdims=False):
    axes = tuple(range(2, x.ndim)) if data_format.startswith("NC") else tuple(range(1, x.ndim - 1))
    return jnp.max(x, axis=axes, keepdims=keepdims)


@op("upsampling2d", "cnn")
def upsampling2d(x, scale=(2, 2), data_format="NCHW"):
    if data_format == "NCHW":
        return jnp.repeat(jnp.repeat(x, scale[0], axis=2), scale[1], axis=3)
    return jnp.repeat(jnp.repeat(x, scale[0], axis=1), scale[1], axis=2)


@op("spaceToDepth", "cnn")
def space_to_depth(x, block_size, data_format="NCHW"):
    b = block_size
    if data_format == "NCHW":
        N, C, H, W = x.shape
        x = x.reshape(N, C, H // b, b, W // b, b)
        return x.transpose(0, 3, 5, 1, 2, 4).reshape(N, C * b * b, H // b, W // b)
    N, H, W, C = x.shape
    x = x.reshape(N, H // b, b, W // b, b, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(N, H // b, W // b, C * b * b)


@op("depthToSpace", "cnn")
def depth_to_space(x, block_size, data_format="NCHW"):
    b = block_size
    if data_format == "NCHW":
        N, C, H, W = x.shape
        x = x.reshape(N, b, b, C // (b * b), H, W)
        return x.transpose(0, 3, 4, 1, 5, 2).reshape(N, C // (b * b), H * b, W * b)
    N, H, W, C = x.shape
    x = x.reshape(N, H, W, b, b, C // (b * b))
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(N, H * b, W * b, C // (b * b))


@op("zeroPadding2d", "cnn")
def zero_padding2d(x, padding, data_format="NCHW"):
    (pt, pb), (pl, pr) = padding
    if data_format == "NCHW":
        return jnp.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
    return jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))


@op("cropping2d", "cnn")
def cropping2d(x, cropping, data_format="NCHW"):
    (ct, cb), (cl, cr) = cropping
    H = x.shape[2] if data_format == "NCHW" else x.shape[1]
    W = x.shape[3] if data_format == "NCHW" else x.shape[2]
    if data_format == "NCHW":
        return x[:, :, ct:H - cb, cl:W - cr]
    return x[:, ct:H - cb, cl:W - cr, :]


@op("im2col", "cnn")
def im2col(x, kernel, strides=(1, 1), padding="VALID"):
    """Patch extraction (ref: libnd4j im2col helper) — provided for parity;
    XLA convs don't need it."""
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=tuple(kernel), window_strides=tuple(strides),
        padding=padding, dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return patches


# --------------------------------------------------------------------- RNN


@op("lstmCell", "rnn")
def lstm_cell(x, h_prev, c_prev, w_ih, w_hh, b):
    """One LSTM step. x:(B,I), h/c:(B,H), w_ih:(I,4H), w_hh:(H,4H), b:(4H,).
    Gate order: [input, forget, cell(g), output] (ref: lstmLayer gate layout)."""
    z = jnp.matmul(x, w_ih) + jnp.matmul(h_prev, w_hh) + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


@op("lstmLayer", "rnn")
def lstm_layer(x, h0, c0, w_ih, w_hh, b, time_major=False, reverse=False, mask=None):
    """Full-sequence LSTM via lax.scan — the whole recurrence compiles to one
    fused XLA loop (ref: libnd4j lstmLayer.cpp runs per-step kernels).
    x: (B,T,I) [or (T,B,I) if time_major]. Returns (outputs (B,T,H), (hT, cT))."""
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)  # -> (T,B,I)
    if mask is not None and not time_major:
        mask = jnp.swapaxes(mask, 0, 1)  # (T,B)
    if reverse:
        x = jnp.flip(x, axis=0)
        if mask is not None:
            mask = jnp.flip(mask, axis=0)

    def step(carry, inp):
        h_prev, c_prev = carry
        if mask is not None:
            xt, mt = inp
        else:
            xt, mt = inp, None
        h, c = lstm_cell(xt, h_prev, c_prev, w_ih, w_hh, b)
        if mt is not None:
            mt = mt[:, None]
            h = jnp.where(mt > 0, h, h_prev)
            c = jnp.where(mt > 0, c, c_prev)
        return (h, c), h

    xs = (x, mask) if mask is not None else x
    (hT, cT), ys = lax.scan(step, (h0, c0), xs)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, (hT, cT)


@op("gruCell", "rnn")
def gru_cell(x, h_prev, w_ih, w_hh, b_ih, b_hh):
    """One GRU step. w_ih:(I,3H), w_hh:(H,3H). Gate order: [reset, update, new]."""
    gi = jnp.matmul(x, w_ih) + b_ih
    gh = jnp.matmul(h_prev, w_hh) + b_hh
    ir, iz, inew = jnp.split(gi, 3, axis=-1)
    hr, hz, hnew = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(inew + r * hnew)
    return (1.0 - z) * n + z * h_prev


@op("gru", "rnn")
def gru_layer(x, h0, w_ih, w_hh, b_ih, b_hh, time_major=False):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(h, xt):
        h2 = gru_cell(xt, h, w_ih, w_hh, b_ih, b_hh)
        return h2, h2

    hT, ys = lax.scan(step, h0, x)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hT


@op("simpleRnn", "rnn")
def simple_rnn(x, h0, w_ih, w_hh, b, activation=jnp.tanh, time_major=False):
    if not time_major:
        x = jnp.swapaxes(x, 0, 1)

    def step(h, xt):
        h2 = activation(jnp.matmul(xt, w_ih) + jnp.matmul(h, w_hh) + b)
        return h2, h2

    hT, ys = lax.scan(step, h0, x)
    if not time_major:
        ys = jnp.swapaxes(ys, 0, 1)
    return ys, hT


# -------------------------------------------------------------------- loss
# (ref: org.nd4j.linalg.lossfunctions.impl.* — ~20 classes). All take
# (labels, predictions) and reduce to scalar mean unless average=False.


def _weighted_mean(per_example, weights, average=True):
    if weights is not None:
        per_example = per_example * weights
    return jnp.mean(per_example) if average else jnp.sum(per_example)


@op("mse", "loss")
def loss_mse(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.mean((preds - labels) ** 2, axis=-1), weights, average)


@op("mae", "loss")
def loss_mae(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.mean(jnp.abs(preds - labels), axis=-1), weights, average)


@op("mape", "loss")
def loss_mape(labels, preds, weights=None, average=True):
    return _weighted_mean(
        jnp.mean(jnp.abs((labels - preds) / jnp.maximum(jnp.abs(labels), 1e-8)), axis=-1) * 100.0,
        weights, average)


@op("msle", "loss")
def loss_msle(labels, preds, weights=None, average=True):
    return _weighted_mean(
        jnp.mean((jnp.log1p(jnp.maximum(preds, 0)) - jnp.log1p(jnp.maximum(labels, 0))) ** 2, axis=-1),
        weights, average)


@op("mcxent", "loss")
def loss_mcxent(labels, preds_logprob_or_prob, weights=None, average=True, from_logits=False,
                label_smoothing=0.0):
    """Multi-class cross-entropy against one-hot labels (ref: LossMCXENT)."""
    if label_smoothing > 0:
        k = labels.shape[-1]
        labels = labels * (1.0 - label_smoothing) + label_smoothing / k
    if from_logits:
        logp = jax.nn.log_softmax(preds_logprob_or_prob, axis=-1)
    else:
        logp = jnp.log(jnp.clip(preds_logprob_or_prob, 1e-10, 1.0))
    return _weighted_mean(-jnp.sum(labels * logp, axis=-1), weights, average)


@op("sparseMcxent", "loss")
def loss_sparse_mcxent(labels, logits, weights=None, average=True):
    """Integer-label cross-entropy from logits (ref: sparse_softmax_cross_entropy)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return _weighted_mean(nll, weights, average)


@op("binaryXent", "loss")
def loss_binary_xent(labels, preds, weights=None, average=True, from_logits=False):
    if from_logits:
        per = jnp.maximum(preds, 0) - preds * labels + jnp.log1p(jnp.exp(-jnp.abs(preds)))
    else:
        p = jnp.clip(preds, 1e-7, 1.0 - 1e-7)
        per = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    return _weighted_mean(jnp.mean(per, axis=-1), weights, average)


@op("hinge", "loss")
def loss_hinge(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.mean(jnp.maximum(0.0, 1.0 - labels * preds), axis=-1), weights, average)


@op("squaredHinge", "loss")
def loss_squared_hinge(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.mean(jnp.maximum(0.0, 1.0 - labels * preds) ** 2, axis=-1), weights, average)


@op("huber", "loss")
def loss_huber(labels, preds, delta=1.0, weights=None, average=True):
    d = jnp.abs(preds - labels)
    per = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _weighted_mean(jnp.mean(per, axis=-1), weights, average)


@op("logCosh", "loss")
def loss_logcosh(labels, preds, weights=None, average=True):
    d = preds - labels
    per = d + jax.nn.softplus(-2.0 * d) - jnp.log(2.0)
    return _weighted_mean(jnp.mean(per, axis=-1), weights, average)


@op("poisson", "loss")
def loss_poisson(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.mean(preds - labels * jnp.log(jnp.maximum(preds, 1e-8)), axis=-1),
                          weights, average)


@op("kld", "loss")
def loss_kld(labels, preds, weights=None, average=True):
    p = jnp.clip(labels, 1e-10, 1.0)
    q = jnp.clip(preds, 1e-10, 1.0)
    return _weighted_mean(jnp.sum(p * jnp.log(p / q), axis=-1), weights, average)


@op("cosineProximity", "loss")
def loss_cosine_proximity(labels, preds, weights=None, average=True):
    num = jnp.sum(labels * preds, axis=-1)
    den = jnp.linalg.norm(labels, axis=-1) * jnp.linalg.norm(preds, axis=-1)
    return _weighted_mean(-num / jnp.maximum(den, 1e-12), weights, average)


@op("l1", "loss")
def loss_l1(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.sum(jnp.abs(preds - labels), axis=-1), weights, average)


@op("l2", "loss")
def loss_l2(labels, preds, weights=None, average=True):
    return _weighted_mean(jnp.sum((preds - labels) ** 2, axis=-1), weights, average)


@op("sparseMcxentWithMask", "loss")
def loss_sparse_mcxent_masked(labels, logits, mask, average=True):
    """Masked integer-label xent — the BERT MLM loss shape."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    nll = nll * mask
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(nll) / denom if average else jnp.sum(nll)


# -------------------------------------------------------------------- image
# (ref: libnd4j generic/parity_ops image ops + helpers/image_resize)


def _tf_resize_matrix(n_in, n_out, method, align_corners, half_pixel,
                      nearest_mode="floor", cubic_a=-0.5,
                      exclude_outside=False, roi=None,
                      pytorch_half_pixel=False):
    """1-D interpolation matrix (n_out, n_in) with TF/ONNX coordinate rules.

    half_pixel (TF2 default): src = (i+0.5)*in/out - 0.5 — what
    jax.image.resize implements. align_corners (TF1): src = i*(in-1)/(out-1).
    Neither (TF1 legacy default): src = i*in/out. ``nearest_mode``
    (non-align-corners nearest only): 'floor' (TF legacy) or
    'round_prefer_floor' (ONNX default — round, ties toward floor).
    ``method='cubic'`` uses the ONNX/Keys convolution kernel with coefficient
    ``cubic_a`` (-0.75 per ONNX spec, -0.5 = Keys/TF); ``exclude_outside``
    zeroes taps outside the image and renormalizes (ONNX attribute).
    ``roi=(start, end)`` (normalized) switches to ONNX tf_crop_and_resize
    coordinates; returns (matrix, valid) then, where ~valid rows must take
    the extrapolation value.
    """
    import numpy as _np
    i = _np.arange(n_out, dtype=_np.float64)
    if roi is not None:
        start, end = roi
        if n_out > 1:
            src = start * (n_in - 1) + i * (end - start) * (n_in - 1) / (n_out - 1)
        else:
            src = _np.full(1, 0.5 * (start + end) * (n_in - 1))
        valid = (src >= 0.0) & (src <= n_in - 1)
    elif align_corners:
        scale = (n_in - 1) / (n_out - 1) if n_out > 1 else 0.0
        src = i * scale
        valid = None
    elif half_pixel:
        # ONNX pytorch_half_pixel: a length-1 output samples coordinate 0,
        # not the center (the only place the two half-pixel variants differ)
        if pytorch_half_pixel and n_out == 1:
            src = _np.zeros(1)
        else:
            src = (i + 0.5) * (n_in / n_out) - 0.5
        valid = None
    else:
        src = i * (n_in / n_out)
        valid = None
    m = _np.zeros((n_out, n_in), _np.float32)
    if method == "nearest":
        if align_corners:
            # TF uses roundf (half away from zero), NOT banker's rounding
            idx = _np.floor(src + 0.5).astype(int)
        elif nearest_mode == "round_prefer_floor":
            idx = _np.ceil(src - 0.5).astype(int)
        else:  # floor
            idx = _np.floor(src).astype(int)
        idx = _np.clip(idx, 0, n_in - 1)
        m[_np.arange(n_out), idx] = 1.0
    elif method == "cubic":
        lo = _np.floor(src).astype(int)
        a = float(cubic_a)

        def kern(t):
            at = _np.abs(t)
            return _np.where(
                at <= 1.0, (a + 2) * at ** 3 - (a + 3) * at ** 2 + 1.0,
                _np.where(at < 2.0,
                          a * at ** 3 - 5 * a * at ** 2 + 8 * a * at - 4 * a,
                          0.0))

        rows = _np.arange(n_out)
        for k in (-1, 0, 1, 2):
            j = lo + k
            w = kern(src - j)
            inside = (j >= 0) & (j < n_in)
            if exclude_outside:
                w = _np.where(inside, w, 0.0)
            _np.add.at(m, (rows, _np.clip(j, 0, n_in - 1)), w)
        if exclude_outside:
            s = m.sum(axis=1, keepdims=True)
            m = m / _np.where(s == 0.0, 1.0, s)
    else:  # bilinear
        src = _np.clip(src, 0.0, n_in - 1)
        lo = _np.floor(src).astype(int)
        hi = _np.minimum(lo + 1, n_in - 1)
        frac = (src - lo).astype(_np.float32)
        m[_np.arange(n_out), lo] += 1.0 - frac
        # hi may equal lo at the border: += accumulates to exactly 1.0
        m[_np.arange(n_out), hi] += frac
    m = jnp.asarray(m.astype(_np.float32))
    if roi is not None:
        return m, _np.asarray(valid)
    return m


def _tf_resize(x, size, method, data_format, align_corners, half_pixel,
               nearest_mode="floor", cubic_a=-0.5, exclude_outside=False,
               roi=None, extrapolation_value=0.0, pytorch_half_pixel=False):
    if data_format == "NCHW":
        H, W = x.shape[2], x.shape[3]
    else:
        H, W = x.shape[1], x.shape[2]
    fast_ok = (roi is None and half_pixel and not align_corners
               and not (method == "cubic"
                        and (cubic_a != -0.5 or exclude_outside))
               and not (pytorch_half_pixel and min(size) == 1))
    if fast_ok:
        # identical to jax.image.resize's sampling — use the fused path
        if data_format == "NCHW":
            out_shape = (x.shape[0], x.shape[1], size[0], size[1])
        else:
            out_shape = (x.shape[0], size[0], size[1], x.shape[3])
        return jax.image.resize(x, out_shape, method=method)
    roi_h = roi_w = None
    if roi is not None:
        (roi_h, roi_w) = roi
    wh = _tf_resize_matrix(H, size[0], method, align_corners, half_pixel,
                           nearest_mode, cubic_a, exclude_outside, roi_h,
                           pytorch_half_pixel)
    ww = _tf_resize_matrix(W, size[1], method, align_corners, half_pixel,
                           nearest_mode, cubic_a, exclude_outside, roi_w,
                           pytorch_half_pixel)
    valid_h = valid_w = None
    if roi is not None:
        wh, valid_h = wh
        ww, valid_w = ww
    # precision="highest": interpolation weights must not round through the
    # accelerator's fast-matmul dtype (bf16/TF32-analog) — parity vs the TF
    # kernels is the contract here and the matrices are tiny
    if data_format == "NCHW":
        out = jnp.einsum("oh,nchw,pw->ncop", wh.astype(x.dtype), x,
                         ww.astype(x.dtype), precision="highest")
    else:
        out = jnp.einsum("oh,nhwc,pw->nopc", wh.astype(x.dtype), x,
                         ww.astype(x.dtype), precision="highest")
    if roi is not None:
        # ONNX tf_crop_and_resize: coordinates outside the image take the
        # extrapolation value
        vh = jnp.asarray(valid_h)
        vw = jnp.asarray(valid_w)
        mask = vh[:, None] & vw[None, :]
        if data_format == "NCHW":
            mask = mask[None, None, :, :]
        else:
            mask = mask[None, :, :, None]
        out = jnp.where(mask, out, jnp.asarray(extrapolation_value, x.dtype))
    return out


@op("resizeBilinear", "image")
def resize_bilinear(x, size, data_format="NCHW", align_corners=False,
                    half_pixel_centers=True, roi=None,
                    extrapolation_value=0.0, pytorch_half_pixel=False):
    """TF-semantics bilinear resize incl. the TF1 align_corners /
    legacy-coordinate modes (ref: helpers/image_resize computeInterpolation
    weights; TF kernels are the behavioral oracle in tests). ``roi`` =
    ((start_h, end_h), (start_w, end_w)) normalized switches to ONNX
    tf_crop_and_resize coordinates with ``extrapolation_value`` outside."""
    return _tf_resize(x, size, "bilinear", data_format, align_corners,
                      half_pixel_centers, roi=roi,
                      extrapolation_value=extrapolation_value,
                      pytorch_half_pixel=pytorch_half_pixel)


@op("resizeNearest", "image")
def resize_nearest(x, size, data_format="NCHW", align_corners=False,
                   half_pixel_centers=True, nearest_mode="floor", roi=None,
                   extrapolation_value=0.0, pytorch_half_pixel=False):
    return _tf_resize(x, size, "nearest", data_format, align_corners,
                      half_pixel_centers, nearest_mode, roi=roi,
                      extrapolation_value=extrapolation_value,
                      pytorch_half_pixel=pytorch_half_pixel)


@op("cropAndResize", "image")
def crop_and_resize(x, boxes, box_indices, crop_size):
    """x: NHWC; boxes: (n,4) normalized [y1,x1,y2,x2]."""
    x = jnp.asarray(x)  # numpy input would break x[idx] under the vmap trace

    def one(box, idx):
        y1, x1, y2, x2 = box
        img = x[idx]
        H, W = img.shape[0], img.shape[1]
        ys = y1 * (H - 1) + jnp.linspace(0.0, 1.0, crop_size[0]) * (y2 - y1) * (H - 1)
        xs = x1 * (W - 1) + jnp.linspace(0.0, 1.0, crop_size[1]) * (x2 - x1) * (W - 1)
        grid_y, grid_x = jnp.meshgrid(ys, xs, indexing="ij")
        coords = jnp.stack([grid_y, grid_x], axis=0)
        return jnp.stack([
            jax.scipy.ndimage.map_coordinates(img[..., c], coords, order=1, mode="nearest")
            for c in range(img.shape[-1])], axis=-1)

    return jax.vmap(one)(boxes, box_indices)


@op("adjustContrast", "image")
def adjust_contrast(x, factor):
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


@op("rgbToGrayscale", "image")
def rgb_to_grayscale(x):
    """NHWC RGB -> NHW1."""
    w = jnp.asarray([0.2989, 0.587, 0.114], dtype=x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


@op("nonMaxSuppression", "image")
def non_max_suppression(boxes, scores, max_output, iou_threshold=0.5, score_threshold=-jnp.inf):
    """Greedy NMS with static output size (padded with -1) — XLA-friendly
    (ref: non_max_suppression.cpp returns dynamic count)."""
    n = boxes.shape[0]

    def iou(b1, b2):
        y1 = jnp.maximum(b1[0], b2[0]); x1 = jnp.maximum(b1[1], b2[1])
        y2 = jnp.minimum(b1[2], b2[2]); x2 = jnp.minimum(b1[3], b2[3])
        inter = jnp.maximum(0.0, y2 - y1) * jnp.maximum(0.0, x2 - x1)
        a1 = (b1[2] - b1[0]) * (b1[3] - b1[1])
        a2 = (b2[2] - b2[0]) * (b2[3] - b2[1])
        return inter / jnp.maximum(a1 + a2 - inter, 1e-9)

    def body(i, state):
        sel, active_scores = state
        best = jnp.argmax(active_scores)
        valid = active_scores[best] > score_threshold
        sel = sel.at[i].set(jnp.where(valid, best, -1))
        ious = jax.vmap(lambda b: iou(boxes[best], b))(boxes)
        suppress = (ious > iou_threshold) | (jnp.arange(n) == best)
        active_scores = jnp.where(suppress | ~valid, -jnp.inf, active_scores)
        return sel, active_scores

    sel0 = jnp.full((max_output,), -1, dtype=jnp.int32)
    sel, _ = lax.fori_loop(0, max_output, body, (sel0, scores))
    return sel
